#!/usr/bin/env python
"""Benchmark sweep over the BASELINE.md config table.

Runs each named config through the library tiers and appends one JSON line
per run to a stats file — the experiment-harvesting workflow the reference
drives with its `stats_pfsp_*_cuda.dat` appends (`pfsp_gpu_cuda.c:140-148`),
generalized to every tier.

    python scripts/sweep.py                     # default set, ./sweep_stats.jsonl
    python scripts/sweep.py --quick             # small instances only (CPU-friendly)
    python scripts/sweep.py --configs nq15,ta014_lb1 --stats-file out.jsonl

Configs (BASELINE.md "Targets" table):
    nq14_seq     N-Queens N=14, sequential           (parity anchor)
    nq14         N-Queens N=14, device-resident
    nq15         N-Queens N=15, device-resident
    nq17         N-Queens N=17, device-resident      (large; TPU recommended)
    ta014_lb1    PFSP ta014 lb1  ub=1, device-resident
    ta014_lb1d   PFSP ta014 lb1_d ub=1, device-resident
    ta014_lb2    PFSP ta014 lb2  ub=1, device-resident
    ta021_lb2    PFSP ta021 lb2  ub=1, device-resident (large; TPU recommended)
    ta014_mesh   PFSP ta014 lb2  ub=1, mesh tier (all local devices)
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _nq(N):
    from tpu_tree_search.problems import NQueensProblem

    return NQueensProblem(N=N)


def _pfsp(inst, lb):
    from tpu_tree_search.problems import PFSPProblem

    return PFSPProblem(inst=inst, lb=lb, ub=1)


def run_config(name: str, M: int):
    from tpu_tree_search.engine.resident import resident_search
    from tpu_tree_search.engine.sequential import sequential_search
    from tpu_tree_search.parallel.resident_mesh import mesh_resident_search

    if name == "nq14_seq":
        return sequential_search(_nq(14)), {"tier": "seq"}
    if name.startswith("nq"):
        N = int(name[2:4])
        return resident_search(_nq(N), m=25, M=M), {"tier": "device"}
    if name == "ta014_mesh":
        return mesh_resident_search(_pfsp(14, "lb2"), m=25, M=min(M, 16384)), {
            "tier": "mesh"
        }
    inst = int(name[2:5])
    lb = {"lb1": "lb1", "lb1d": "lb1_d", "lb2": "lb2"}[name.split("_")[1]]
    return resident_search(_pfsp(inst, lb), m=25, M=M), {"tier": "device"}


DEFAULT = [
    "nq14_seq", "nq14", "nq15", "ta014_lb1", "ta014_lb1d", "ta014_lb2",
    "ta014_mesh",
]
QUICK = ["nq14_seq", "nq14", "ta014_lb1", "ta014_lb1d"]
LARGE = ["nq17", "ta021_lb2"]


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--configs", type=str, default=None,
                    help="comma-separated subset (default: standard set; "
                    "'all' adds the large TPU-scale configs)")
    ap.add_argument("--quick", action="store_true",
                    help="small CPU-friendly subset")
    ap.add_argument("--stats-file", type=str, default="sweep_stats.jsonl")
    ap.add_argument("--M", type=int, default=65536)
    args = ap.parse_args()

    from tpu_tree_search.cli import enable_compile_cache

    enable_compile_cache()

    if args.configs == "all":
        names = DEFAULT + LARGE
    elif args.configs:
        names = [c.strip() for c in args.configs.split(",")]
    elif args.quick:
        names = QUICK
    else:
        names = DEFAULT

    failures = 0
    for name in names:
        t0 = time.time()
        try:
            res, extra = run_config(name, args.M)
            phase = (
                res.phases[1].seconds
                if len(res.phases) > 1
                else res.elapsed
            )
            rec = {
                "config": name,
                "explored_tree": res.explored_tree,
                "explored_sol": res.explored_sol,
                "best": res.best,
                "elapsed_s": round(res.elapsed, 3),
                "device_phase_s": round(phase, 3),
                "nodes_per_sec": round(res.explored_tree / max(phase, 1e-9), 1),
                **extra,
            }
        except Exception as e:  # noqa: BLE001 — sweep must finish
            failures += 1
            rec = {"config": name, "error": f"{type(e).__name__}: {e}",
                   "elapsed_s": round(time.time() - t0, 3)}
        print(json.dumps(rec), flush=True)
        with open(args.stats_file, "a") as f:
            f.write(json.dumps(rec) + "\n")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
