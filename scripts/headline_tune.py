#!/usr/bin/env python
"""On-chip chunk-size tuning grids for the bench configs.

The round-5 session measured 34 ms/cycle at M=65536 on the lb1 headline
while the kernel microbench implies ~4 ms of bound math per cycle — most
of the cycle is orchestration (pop/compact/push) whose cost is ~linear in
M (dense padded compute), so chunk size must match how full the frontier
keeps the chunks. This grid sweeps M (and K to expose fixed per-dispatch
overhead) and prints per-cycle decompositions so bench defaults are set
from measurement instead of habit. Measured outcomes so far are recorded
in docs/HW_VALIDATION.md ("chunk-size tuning").

Run on the TPU host:
    python scripts/headline_tune.py [--quick]              # ta014 lb1
    python scripts/headline_tune.py --problem nqueens      # N-Queens N=15
    python scripts/headline_tune.py --problem nqueens --N 16   # bounded
(N-Queens has no pruning, so its frontier FILLS large chunks — the sweep
spans upward to find whether bigger-than-65536 chunks pay.  This is the
first-ever N-Queens chunk-size sweep, VERDICT r5 #2: N=15 rows are full
runs with solution-count parity; N=16/17 trees cost minutes-to-hours, so
their rows are BOUNDED-dispatch rate rows — ``max_steps`` cuts after a few
K-cycle dispatches and parity is not computable on a cutoff.  Rows are
tagged with the resolved survivor path (``compact``), so the armed-session
log doubles as the fused-vs-scatter A/B when driven with TTS_COMPACT.)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import GOLDEN_LB1, NQ_SOL, REF_C_SEQ  # noqa: E402 — canonical anchors


def run_one(problem_name: str, M: int, K: int, N: int = 15,
            max_steps: int | None = None) -> dict:
    from tpu_tree_search.engine.resident import resident_search

    if problem_name == "nqueens":
        from tpu_tree_search.problems import NQueensProblem

        mk = lambda: NQueensProblem(N=N)
        anchor = REF_C_SEQ.get(f"nqueens_n{N}")
        check = (
            (lambda r: r.explored_sol == NQ_SOL[N]) if N in NQ_SOL
            and max_steps is None else (lambda r: r.explored_tree > 0)
        )
    else:
        from tpu_tree_search.problems import PFSPProblem

        mk = lambda: PFSPProblem(inst=14, lb="lb1", ub=1)
        anchor = REF_C_SEQ["pfsp_ta014_lb1"]
        check = lambda r: (
            r.explored_tree == GOLDEN_LB1["tree"]
            and r.explored_sol == GOLDEN_LB1["sol"]
            and r.best == GOLDEN_LB1["makespan"]
        )
    # ONE instance for warm + timed: compiled programs are cached on the
    # problem object, so a fresh instance would re-trace inside the timed
    # run and inflate every measurement.
    kw = {} if max_steps is None else {"max_steps": max_steps}
    prob = mk()
    resident_search(prob, m=25, M=M, K=K,
                    **({} if max_steps is None else {"max_steps": 1}))
    t0 = time.time()
    res = resident_search(prob, m=25, M=M, K=K, **kw)
    elapsed = time.time() - t0
    device_phase = (
        res.phases[1].seconds if len(res.phases) > 1 else res.elapsed
    )
    cycles = max(1, res.diagnostics.kernel_launches)
    nps = res.explored_tree / max(device_phase, 1e-9)
    return {
        "problem": problem_name, "M": M, "K": K,
        **({"N": N} if problem_name == "nqueens" else {}),
        **({"bounded_steps": max_steps} if max_steps is not None else {}),
        # Trace-time knobs that change what this row measured — without
        # them an A/B session log's rows are indistinguishable.  The
        # resolved survivor path comes from the run itself (under the
        # default auto knob the env alone no longer names it).
        "compact": res.compact or os.environ.get("TTS_COMPACT", "auto"),
        "pallas": os.environ.get("TTS_PALLAS", "1") != "0",
        "nodes_per_sec": round(nps, 1),
        **({"vs_ref_c_seq": round(nps / anchor, 3)} if anchor else {}),
        "device_phase_s": round(device_phase, 3),
        "total_s": round(elapsed, 3),
        "cycles": cycles,
        "ms_per_cycle": round(1e3 * device_phase / cycles, 2),
        "parents_per_cycle": round(res.explored_tree / cycles, 1),
        "parity": check(res),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--problem", choices=["pfsp", "nqueens"], default="pfsp")
    ap.add_argument("--N", type=int, default=15, choices=[15, 16, 17],
                    help="N-Queens size: 15 = full runs with parity; "
                    "16/17 = bounded-dispatch rate rows (the tree is too "
                    "big to finish in a sweep slot)")
    args = ap.parse_args()

    max_steps = None
    if args.problem == "nqueens":
        # No pruning -> the frontier fills any chunk; sweep UP from the
        # current 65536 to find where padded-compute cost overtakes fill.
        grid = (
            [(32768, 4096), (65536, 4096), (131072, 4096)]
            if args.quick else
            [(8192, 4096), (32768, 4096), (65536, 4096), (131072, 4096),
             (262144, 4096)]
        )
        if args.N > 15:
            # Bounded rate rows: a handful of K-cycle dispatches measures
            # steady-state nodes/s without paying the full tree.
            max_steps = 4
            grid = [(M, 64) for M, _ in grid]
    else:
        grid = (
            [(1024, 4096), (2048, 4096), (4096, 4096)]
            if args.quick else
            # 512-131072 spans underutilization -> the measured 1024-8192
            # plateau -> padded-compute collapse; K=1 exposes per-dispatch
            # overhead (measured ~360ms through the axon tunnel).
            [(512, 4096), (1024, 4096), (2048, 4096), (4096, 4096),
             (8192, 4096), (32768, 4096), (65536, 4096), (131072, 4096),
             (65536, 1)]
        )
    best = None
    for M, K in grid:
        try:
            row = run_one(args.problem, M, K, N=args.N,
                          max_steps=max_steps)
        except Exception as e:  # noqa: BLE001 — keep sweeping
            row = {"problem": args.problem, "M": M, "K": K,
                   "error": f"{type(e).__name__}: {e}"}
        print(json.dumps(row), flush=True)
        if row.get("parity") and (
            best is None or row["nodes_per_sec"] > best["nodes_per_sec"]
        ):
            best = row
    if best:
        print(json.dumps({"best": best}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
