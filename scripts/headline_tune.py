#!/usr/bin/env python
"""On-chip tuning grid for the HEADLINE bench config (ta014 lb1 ub=1).

The round-5 session measured 34 ms/cycle at M=65536 while the kernel
microbench implies ~4 ms of bound math per cycle — most of the cycle is
orchestration (pop/compact/push) whose cost scales differently with chunk
size than the kernel does. This grid sweeps M (and K to expose fixed
per-dispatch overhead) and prints per-cycle decompositions so the bench
default can be set from measurement instead of habit.

Run on the TPU host:  python scripts/headline_tune.py [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import GOLDEN_LB1 as GOLDEN, REF_C_SEQ  # noqa: E402 — canonical anchors

REF_C_LB1 = REF_C_SEQ["pfsp_ta014_lb1"]


def run_one(M: int, K: int) -> dict:
    from tpu_tree_search.engine.resident import resident_search
    from tpu_tree_search.problems import PFSPProblem

    prob = PFSPProblem(inst=14, lb="lb1", ub=1)
    resident_search(prob, m=25, M=M, K=K)  # compile + warm
    t0 = time.time()
    res = resident_search(prob, m=25, M=M, K=K)
    elapsed = time.time() - t0
    device_phase = (
        res.phases[1].seconds if len(res.phases) > 1 else res.elapsed
    )
    cycles = max(1, res.diagnostics.kernel_launches)
    nps = res.explored_tree / max(device_phase, 1e-9)
    return {
        "M": M, "K": K,
        "nodes_per_sec": round(nps, 1),
        "vs_ref_c_seq": round(nps / REF_C_LB1, 3),
        "device_phase_s": round(device_phase, 3),
        "cycles": cycles,
        "ms_per_cycle": round(1e3 * device_phase / cycles, 2),
        "parents_per_cycle": round(res.explored_tree / cycles, 1),
        "parity": (
            res.explored_tree == GOLDEN["tree"]
            and res.explored_sol == GOLDEN["sol"]
            and res.best == GOLDEN["makespan"]
        ),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()

    grid = (
        [(1024, 4096), (2048, 4096), (4096, 4096)]
        if args.quick else
        # 512-131072 spans underutilization -> the measured 1024-8192
        # plateau -> padded-compute collapse; K=1 exposes per-dispatch
        # overhead (measured ~360ms through the axon tunnel).
        [(512, 4096), (1024, 4096), (2048, 4096), (4096, 4096),
         (8192, 4096), (32768, 4096), (65536, 4096), (131072, 4096),
         (65536, 1)]
    )
    best = None
    for M, K in grid:
        try:
            row = run_one(M, K)
        except Exception as e:  # noqa: BLE001 — keep sweeping
            row = {"M": M, "K": K, "error": f"{type(e).__name__}: {e}"}
        print(json.dumps(row), flush=True)
        if row.get("parity") and (
            best is None or row["nodes_per_sec"] > best["nodes_per_sec"]
        ):
            best = row
    if best:
        print(json.dumps({"best": best}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
