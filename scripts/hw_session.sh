#!/usr/bin/env bash
# One-shot hardware session: run this whenever the TPU tunnel is up.
# Produces: smoke-test results, a tile sweep table, and a bench line
# (which also refreshes BENCH_LAST_GOOD.json). Each stage is
# independently timeboxed so a hang cannot eat the window.
set -u
cd "$(dirname "$0")/.."

echo "== 1/4 backend liveness =="
if ! timeout 120 python -c "import jax; print(jax.devices())"; then
  echo "TPU unreachable — aborting hardware session"; exit 1
fi

echo "== 2/4 Pallas smoke gate (hardware compiles + oracle parity) =="
TTS_TPU_TESTS=1 timeout 3000 python -m pytest tests/test_tpu_smoke.py -v

echo "== 3/4 tile sweep (per-kernel compile/throughput; informational) =="
timeout 3000 python scripts/tile_sweep.py || true

echo "== 4/4 bench (writes BENCH_LAST_GOOD.json on success) =="
timeout 3000 python bench.py

echo "Done. Update docs/HW_VALIDATION.md with the results."
