#!/usr/bin/env bash
# One-shot hardware session: run this whenever the TPU tunnel is up.
# Stage order is artifact-first (round-4 lesson: a mid-session tunnel drop
# ate the smoke/sweep budget and left BENCH_LAST_GOOD.json stale): the
# round's one mandatory artifact — a bench line with Pallas probes — is
# captured immediately after liveness; validation breadth comes after.
# Each stage is independently timeboxed so a hang cannot eat the window.
set -u
cd "$(dirname "$0")/.."

echo "== 1/5 backend liveness =="
if ! timeout 120 python -c "import jax; print(jax.devices())"; then
  echo "TPU unreachable — aborting hardware session"; exit 1
fi

echo "== 2/5 bench (writes BENCH_LAST_GOOD.json on success) =="
set -o pipefail
if timeout 3000 python bench.py | tee /tmp/tts_bench_line.json; then
  echo "BENCH OK"
else
  # Loud marker: the round's one mandatory artifact did NOT land; the
  # remaining stages still run (they have independent value) but the
  # watcher log must not read as a banked bench.
  echo "BENCH FAILED — BENCH_LAST_GOOD.json NOT refreshed"
fi
set +o pipefail

echo "== 3/5 Pallas smoke gate (hardware compiles + oracle parity) =="
TTS_TPU_TESTS=1 timeout 3000 python -m pytest tests/test_tpu_smoke.py -v

echo "== 4/5 warm AOT compile cache for the validation matrix =="
timeout 1200 python scripts/warm_cache.py || true

echo "== 5/5 tile sweep (per-kernel compile/throughput; informational) =="
timeout 3000 python scripts/tile_sweep.py || true
# Large-instance classes (VERDICT r4 #7): measured tile tables for ta056
# (50x20) and ta111 (500x20); small batches + few tiles keep it bounded.
timeout 1500 python scripts/tile_sweep.py --inst 56 --kernels lb1,lb2 \
  --tiles 8,16,32 --batch 2048 || true
timeout 1000 python scripts/tile_sweep.py --inst 111 --kernels lb1 \
  --tiles 8,16 --batch 512 || true

echo "Done. Update docs/HW_VALIDATION.md with the results."
