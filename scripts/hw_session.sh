#!/usr/bin/env bash
# One-shot hardware session: run this whenever the TPU tunnel is up.
# Stage order is artifact-first (round-4 lesson: a mid-session tunnel drop
# ate the smoke/sweep budget and left BENCH_LAST_GOOD.json stale): the
# round's one mandatory artifact — a bench line with Pallas probes — is
# captured immediately after liveness; validation breadth comes after.
# Each stage is independently timeboxed so a hang cannot eat the window.
set -u
cd "$(dirname "$0")/.."

# Arm the flight recorder for EVERY stage (obs/flightrec.py): any stage
# that dies on a dead tunnel or hung dispatch dumps a post-mortem trace
# naming the last completed dispatch instead of leaving nothing — stage 7
# banks whatever got dumped. (TTS_OBS stays per-stage: bench pins =host
# itself; the CLI runs below pass --trace/--costmodel.)
export TTS_FLIGHTREC="${TTS_FLIGHTREC:-/tmp/tts_flight}"
# Tighter stall threshold than the 300s default: a session stage whose
# dispatch goes quiet for 2 minutes is the dead-tunnel signature.
export TTS_WATCHDOG_S="${TTS_WATCHDOG_S:-120}"

echo "== 1/9 backend liveness =="
if ! timeout 120 python -c "import jax; print(jax.devices())"; then
  echo "TPU unreachable — aborting hardware session"; exit 1
fi

echo "== 2/9 express bench (first on-chip number in the smallest window) =="
set -o pipefail
if TTS_BENCH_EXPRESS=1 timeout 600 python bench.py \
    | tee /tmp/tts_bench_express.json; then
  echo "EXPRESS BENCH OK"
else
  echo "EXPRESS BENCH FAILED"
fi

echo "== 3/9 bench (full; overwrites BENCH_LAST_GOOD.json on success) =="
if timeout 3000 python bench.py | tee /tmp/tts_bench_line.json; then
  echo "BENCH OK"
else
  # Loud marker: the FULL bench did not land (the watcher may still count
  # the round as banked from the earlier express artifact; this line keeps
  # the log honest about which of the two succeeded).
  echo "BENCH FAILED — full bench did not refresh BENCH_LAST_GOOD.json"
fi
set +o pipefail

echo "== 4/9 Pallas smoke gate (hardware compiles + oracle parity) =="
TTS_TPU_TESTS=1 timeout 3000 python -m pytest tests/test_tpu_smoke.py -v

echo "== 5/9 warm AOT compile cache for the validation matrix =="
timeout 1200 python scripts/warm_cache.py || true

echo "== 6/9 guard-safe telemetry smoke (traced headline run + tts report) =="
# The obs acceptance run (docs/OBSERVABILITY.md): full counters + trace
# under the steady-state guard — zero guard violations required — then the
# report summarizer over the written trace. --costmodel banks the measured
# dispatch latency+bandwidth fit into COSTMODEL.json (the controllers
# resolve their K bands from it when TTS_COSTMODEL=COSTMODEL.json is set).
if timeout 900 python -m tpu_tree_search.cli pfsp --inst 14 --tier device \
    --trace /tmp/tts_headline_trace.json --costmodel COSTMODEL.json --guard; then
  timeout 120 python -m tpu_tree_search.cli report /tmp/tts_headline_trace.json \
    || echo "TTS REPORT FAILED"
else
  echo "TRACED GUARDED RUN FAILED"
fi

echo "== 6b/9 batched-headline row (instance batching on the headline config) =="
# The --batch-slots evidence on real hardware (docs/SERVING.md): the
# headline PFSP class run as 8 concurrent tenants through the batched
# engine at B in {1,4,8}, bounded by max_steps so each cell costs a few
# dispatches. Bit-identity per job vs the serial run is asserted inline;
# the aggregate-nodes/s row lands in BATCH_AB.json. Guard armed: a splice
# that recompiled would fail loudly here, not in production.
TTS_GUARD=1 timeout 900 python - <<'EOF' | tee BATCH_AB.json \
  || echo "BATCHED HEADLINE FAILED"
import json, time
from tpu_tree_search.engine.batched import batched_search
from tpu_tree_search.engine.resident import resident_search
from tpu_tree_search.problems import PFSPProblem

prob = PFSPProblem(inst=14, lb="lb1", ub=1)
m, M, K, jobs = 25, 1024, 4096, 8
resident_search(prob, m=m, M=M, K=K, max_steps=1)  # warm
t0 = time.perf_counter()
serial = [resident_search(prob, m=m, M=M, K=K) for _ in range(jobs)]
serial_s = time.perf_counter() - t0
golden = [(r.explored_tree, r.explored_sol, r.best) for r in serial]
row = {"metric": "batch_ab_headline", "jobs": jobs,
       "serial_s": round(serial_s, 3),
       "serial_nodes_per_sec":
           round(sum(r.explored_tree for r in serial) / serial_s, 1)}
for B in (1, 4, 8):
    batched_search(prob, n_jobs=B, B=B, m=m, M=M, K=K)  # warm
    t0 = time.perf_counter()
    res = batched_search(prob, n_jobs=jobs, B=B, m=m, M=M, K=K)
    wall = time.perf_counter() - t0
    assert [(r.explored_tree, r.explored_sol, r.best) for r in res] == golden
    row[f"b{B}_s"] = round(wall, 3)
    row[f"b{B}_nodes_per_sec"] = round(
        sum(r.explored_tree for r in res) / wall, 1)
    row[f"b{B}_speedup"] = round(serial_s / wall, 3)
print(json.dumps(row))
EOF

echo "== 6c/9 hierarchical-stealing A/B (flat vs hier, banked row) =="
# The TTS_STEAL evidence row (docs/PARALLELISM.md): flat vs hier on the
# virtual-host simulated-latency harness — 6 hosts in 2 pods, injected
# asymmetric ICI/DCN latencies, adversarial initial imbalance, parity
# gated on bit-identical N-Queens counts. Runs on the CPU backend BY
# DESIGN (the latencies are injected, not measured; a TPU run would
# measure nothing extra) — banked from the session so the row rides the
# same provenance as the hardware artifacts.
JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  timeout 900 python - <<'EOF' | tee STEAL_AB.json \
  || echo "STEAL AB FAILED"
import json
from bench import steal_ab

row = steal_ab()
assert row["parity"], "steal A/B parity broke: counts depend on schedule"
print(json.dumps(row))
EOF

echo "== 7/9 post-mortem + cost-model banking =="
# Bank whatever the flight recorder dumped (a stage above that died on a
# dead tunnel or hung dispatch left a post-mortem naming its last
# completed dispatch) and this session's measured-profile/provenance
# artifacts, so even a half-dead session ends with a diagnosable record.
for f in "$TTS_FLIGHTREC".trace.json "$TTS_FLIGHTREC".metrics.jsonl; do
  if [ -f "$f" ]; then
    cp "$f" . && echo "banked post-mortem: $(basename "$f")"
    timeout 120 python -m tpu_tree_search.cli report "$f" \
      || echo "POST-MORTEM REPORT FAILED"
  fi
done
[ -f COSTMODEL.json ] && echo "COSTMODEL.json present (arm future runs with TTS_COSTMODEL=COSTMODEL.json)"
[ -f BENCH_PARTIAL.json ] && echo "BENCH_PARTIAL.json present (per-stage bench provenance)"

echo "== 7b/9 phase decomposition + XLA trace (tts profile) =="
# The measured cycle decomposition (ROADMAP item 1's fallback deliverable
# and item 3's gate): armed phase clocks on the two headline configs, plus
# ONE steady-state XLA op-level capture. The armed program is a separate
# cache-keyed variant — these runs are decomposition artifacts, never
# headline numbers (docs/OBSERVABILITY.md leg 7; these artifacts are
# committed only from real TPU sessions — CPU smoke routes to tempdir).
if timeout 900 python -m tpu_tree_search.cli profile pfsp --inst 14 \
    --tier device --xla-trace /tmp/tts_xla_trace \
    --trace /tmp/tts_phase_ta014.json --json \
    | tee PHASES_ta014_lb1.json; then
  timeout 120 python -m tpu_tree_search.cli report /tmp/tts_phase_ta014.json \
    || echo "PHASE REPORT FAILED"
  # Bank the XProf capture directory listing (the .pb/.json.gz payloads
  # stay in /tmp; the listing proves the capture landed).
  find /tmp/tts_xla_trace -type f | tee XLA_TRACE_MANIFEST.txt
else
  echo "TTS PROFILE (ta014 lb1) FAILED"
fi
timeout 900 python -m tpu_tree_search.cli profile nqueens --N 15 \
    --tier device --json | tee PHASES_nqueens_n15.json \
  || echo "TTS PROFILE (N-Queens N=15) FAILED"
# Armed bench decomposition: pick_compact records the per-mode phase
# split and eval_cycle_ms comes from the profiler (one mechanism).
TTS_PHASEPROF=1 TTS_BENCH_EXPRESS=1 timeout 900 python bench.py \
    > /tmp/tts_bench_phase.json \
  || echo "ARMED EXPRESS BENCH FAILED (decomposition rows missing)"

echo "== 8/9 chunk-size sweeps (un-measured configs first) =="
# N-Queens chunk sweep (first ever, VERDICT r5 #2): the default knob is
# TTS_COMPACT=auto now (dense shift path for N-Queens); the scatter pin is
# the round-5 baseline — together these rows ARE the fused-vs-scatter A/B
# (docs/HW_VALIDATION.md armed-session rows; done bar: N=15 >= 10M
# nodes/s). N=16/17 rows are bounded-dispatch rate rows (BASELINE
# config 2).
timeout 1800 python scripts/headline_tune.py --problem nqueens || true
TTS_COMPACT=scatter timeout 1800 python scripts/headline_tune.py --problem nqueens --quick || true
TTS_COMPACT=sort timeout 1200 python scripts/headline_tune.py --problem nqueens --quick || true
TTS_COMPACT=search timeout 1200 python scripts/headline_tune.py --problem nqueens --quick || true
timeout 1200 python scripts/headline_tune.py --problem nqueens --N 16 || true
timeout 1200 python scripts/headline_tune.py --problem nqueens --N 17 --quick || true
# Quick PFSP passes re-validate the banked defaults against drift; the
# headline done bar is ta014 lb1 >= 4.3M nodes/s (beat the host C++ seq).
timeout 1200 python scripts/headline_tune.py --quick || true
timeout 1200 python scripts/lb2_tune.py --quick || true
# Compaction A/B/C/D on the PFSP grid: auto (dense at M=1024 shapes) vs
# the three explicit rank inversions (rows are tagged with the resolved
# mode; bench also picks empirically per run and records the per-mode
# evaluator-vs-maintenance cycle decomposition).
TTS_COMPACT=scatter timeout 1200 python scripts/headline_tune.py --quick || true
TTS_COMPACT=sort timeout 1200 python scripts/headline_tune.py --quick || true
TTS_COMPACT=search timeout 1200 python scripts/headline_tune.py --quick || true
# Cycle decomposition: where the non-evaluator ~85% of the cycle goes
# (evaluator-in-loop vs alone, pop, compact+push) at the tuned and the
# old chunk sizes.
timeout 900 python scripts/cycle_profile.py --M 1024 || true
timeout 900 python scripts/cycle_profile.py --M 65536 --cycles 16 || true

echo "== 8b/9 one-kernel cycle A/B (megakernel keep/retire evidence) =="
# The ISSUE 13 decision row (docs/HW_VALIDATION.md keep/retire procedure):
# ta014 lb1 at the small-M pool-resident config, off vs force vs the
# streamed tiled arm (ISSUE 19, TTS_MEGAKERNEL_MT), guard armed — golden
# parity asserted inline, timed + roofline rows banked in
# MEGAKERNEL_AB.json. A Mosaic lowering failure or a slowdown here is
# the retire signal (the lb1-Pallas precedent); parity breakage is a bug.
TTS_GUARD=1 timeout 900 python - <<'EOF' | tee MEGAKERNEL_AB.json \
  || echo "MEGAKERNEL AB FAILED"
import json, os, time
from tpu_tree_search.engine.resident import resident_search
from tpu_tree_search.problems import PFSPProblem

GOLDEN = None
row = {"metric": "megakernel_ab_hw", "m": 25, "M": 1024}
# Third arm: the STREAMED grid form (ISSUE 19) — forced Mt=256 tiles the
# M=1024 pool 4-wide through the double-buffered HBM->VMEM pipeline; its
# timed row next to the single-tile one is the streaming keep/retire
# evidence, and a phase-profiled pass banks the roofline audit per arm.
for label, knob, mt in (("off", "0", None), ("force", "force", None),
                        ("tiled", "force", "256")):
    os.environ["TTS_MEGAKERNEL"] = knob
    os.environ.pop("TTS_MEGAKERNEL_MT", None)
    if mt is not None:
        os.environ["TTS_MEGAKERNEL_MT"] = mt
    resident_search(PFSPProblem(inst=14, lb="lb1", ub=1), m=25, M=1024)
    t0 = time.perf_counter()
    res = resident_search(PFSPProblem(inst=14, lb="lb1", ub=1), m=25, M=1024)
    wall = time.perf_counter() - t0
    counts = (res.explored_tree, res.explored_sol, res.best)
    if GOLDEN is None:
        GOLDEN = counts
    assert counts == GOLDEN, f"{label}: {counts} != {GOLDEN}"
    row[f"{label}_s"] = round(wall, 3)
    row[f"{label}_nodes_per_sec"] = round(res.explored_tree / wall, 1)
    row[f"{label}_megakernel"] = res.megakernel
    if res.megakernel_mt:
        row[f"{label}_mt"] = res.megakernel_mt
    if res.megakernel_reason:
        row[f"{label}_reason"] = res.megakernel_reason
    os.environ["TTS_PHASEPROF"] = "1"
    prof = resident_search(PFSPProblem(inst=14, lb="lb1", ub=1),
                           m=25, M=1024)
    os.environ.pop("TTS_PHASEPROF", None)
    if prof.roofline is not None:
        row[f"{label}_roofline_mem"] = prof.roofline
os.environ.pop("TTS_MEGAKERNEL_MT", None)
row["speedup"] = round(row["off_s"] / max(row["force_s"], 1e-9), 3)
row["speedup_tiled"] = round(row["off_s"] / max(row["tiled_s"], 1e-9), 3)
print(json.dumps(row))
EOF
# Phase split of the ARMED run: the fused cycle collapses the in-cycle
# decomposition to one eval-dominant slot — the profile row reports that
# honestly (compact/push ~0 is the expected armed shape, not a bug).
TTS_MEGAKERNEL=force timeout 900 python -m tpu_tree_search.cli profile pfsp \
    --inst 14 --tier device --M 1024 --json \
    | tee PHASES_ta014_lb1_megakernel.json \
  || echo "TTS PROFILE (megakernel armed) FAILED"

echo "== 8c/9 narrow node storage A/B (TTS_NARROW bandwidth evidence) =="
# The ISSUE 15 decision row (docs/HW_VALIDATION.md keep/retire): ta014
# lb1 at the headline config, wide vs narrow host pools, guard armed —
# golden parity asserted inline, bytes + timed rows banked in
# NARROW_AB.json. The byte columns are facts from the layout; the walls
# are the HBM/PCIe bandwidth effect this session exists to measure.
TTS_GUARD=1 timeout 900 python - <<'EOF' | tee NARROW_AB.json \
  || echo "NARROW AB FAILED"
import json, os, time
import numpy as np
from tpu_tree_search.engine import checkpoint as ckpt
from tpu_tree_search.engine.resident import resident_search
from tpu_tree_search.problems import PFSPProblem

GOLDEN = None
row = {"metric": "narrow_ab_hw", "inst": "ta014", "m": 25, "M": 1024}
for label, knob in (("wide", "0"), ("narrow", "auto")):
    os.environ["TTS_NARROW"] = knob
    prob = PFSPProblem(inst=14, lb="lb1", ub=1)
    fields = prob.node_fields()
    row[f"{label}_bytes_per_node"] = sum(
        int(np.prod(shape, dtype=np.int64)) * np.dtype(dt).itemsize
        for shape, dt in fields.values())
    resident_search(prob, m=25, M=1024)
    t0 = time.perf_counter()
    res = resident_search(prob, m=25, M=1024)
    wall = time.perf_counter() - t0
    counts = (res.explored_tree, res.explored_sol, res.best)
    if GOLDEN is None:
        GOLDEN = counts
    assert counts == GOLDEN, f"{label}: {counts} != {GOLDEN}"
    path = f"/tmp/narrow_ab_{label}.ckpt"
    resident_search(prob, m=25, M=1024, max_steps=2, checkpoint_path=path)
    row[f"{label}_ckpt_bytes"] = os.path.getsize(path)
    snap = ckpt.load(path, prob)
    row[f"{label}_snapshot_host_bytes"] = sum(
        np.asarray(v).nbytes for v in snap.batch.values())
    row[f"{label}_s"] = round(wall, 3)
    row[f"{label}_nodes_per_sec"] = round(res.explored_tree / wall, 1)
row["speedup"] = round(row["wide_s"] / max(row["narrow_s"], 1e-9), 3)
row["node_shrink"] = round(
    row["wide_bytes_per_node"] / row["narrow_bytes_per_node"], 2)
print(json.dumps(row))
EOF

echo "== 9/9 tile sweep (per-kernel compile/throughput; informational) =="
# Full ta014 tables were measured in the round-5 session
# (docs/HW_VALIDATION.md); re-run is cheap with a warm cache and catches
# compile-time regressions.
timeout 3000 python scripts/tile_sweep.py || true
# Large-instance classes (VERDICT r4 #7): measured tile tables for ta056
# (50x20) and ta111 (500x20); small batches + few tiles keep it bounded.
timeout 1500 python scripts/tile_sweep.py --inst 56 --kernels lb1,lb2 \
  --tiles 8,16,32 --batch 2048 || true
timeout 1000 python scripts/tile_sweep.py --inst 111 --kernels lb1 \
  --tiles 8,16 --batch 512 || true

echo "== 9b/9 fleet saturation curve (router over 2 daemons; FLEET_SAT.json) =="
# The real-hardware run of the `bench.py fleet_sat` ladder: in-process
# router + daemons on THIS host's accelerator, heavier offered rates and
# bigger heavy-tailed budgets than the CI CPU smoke. Banked
# flush-as-you-go to FLEET_SAT.json (one atomic rewrite per rate point),
# so even a dead tunnel leaves a curve prefix. docs/SERVING.md "Fleet".
timeout 2400 env TTS_FLEET_SAT_RATES=0.5,1,2,4,8 TTS_FLEET_SAT_JOBS=10 \
  python bench.py fleet_sat || true

echo "Done. Update docs/HW_VALIDATION.md with the results."
