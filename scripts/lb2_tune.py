#!/usr/bin/env python
"""On-chip tuning grid for the ta014 lb2 bench config (the one extra that
still trails the reference C sequential: BENCH round-5 measured 0.775x).

The lb2 ub=1 tree is small (144,639 nodes) and heavily pruned, so the
frontier stays narrow and per-cycle fixed costs — not kernel FLOPs — set
the wall clock. This grid varies the knobs that trade cycle count against
cycle width (M, m) and the staging toggle, printing one JSON line per
config so a hardware session can paste the table into docs/HW_VALIDATION.md.

Run on the TPU host:  python scripts/lb2_tune.py [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import GOLDEN_LB2 as GOLDEN, REF_C_SEQ  # noqa: E402 — canonical anchors

REF_C_LB2 = REF_C_SEQ["pfsp_ta014_lb2"]


def run_one(m: int, M: int, staged: str) -> dict:
    from tpu_tree_search.engine.resident import resident_search
    from tpu_tree_search.problems import PFSPProblem

    os.environ["TTS_LB2_STAGED"] = staged
    # Fresh problem per config: resident programs cache per (instance, env
    # knobs) and a stale cache entry would measure the wrong path.
    prob = PFSPProblem(inst=14, lb="lb2", ub=1)
    resident_search(prob, m=m, M=M)  # compile + warm
    t0 = time.time()
    res = resident_search(prob, m=m, M=M)
    elapsed = time.time() - t0
    device_phase = (
        res.phases[1].seconds if len(res.phases) > 1 else res.elapsed
    )
    nps = res.explored_tree / max(device_phase, 1e-9)
    return {
        "m": m, "M": M, "staged": staged,
        "nodes_per_sec": round(nps, 1),
        "vs_ref_c_seq": round(nps / REF_C_LB2, 3),
        "device_phase_s": round(device_phase, 3),
        "total_s": round(elapsed, 3),
        "kernel_launches": res.diagnostics.kernel_launches,
        "parity": (
            res.explored_tree == GOLDEN["tree"]
            and res.explored_sol == GOLDEN["sol"]
            and res.best == GOLDEN["makespan"]
        ),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="staged-only, 3 chunk sizes")
    args = ap.parse_args()

    Ms = ([1024, 2048, 4096] if args.quick
          else [1024, 2048, 4096, 16384, 65536])
    stageds = ["1"] if args.quick else ["1", "0"]
    best = None
    for staged in stageds:
        for M in Ms:
            try:
                row = run_one(25, M, staged)
            except Exception as e:  # noqa: BLE001 — keep sweeping
                row = {"m": 25, "M": M, "staged": staged,
                       "error": f"{type(e).__name__}: {e}"}
            print(json.dumps(row), flush=True)
            if row.get("parity") and (
                best is None or row["nodes_per_sec"] > best["nodes_per_sec"]
            ):
                best = row
    if best:
        print(json.dumps({"best": best}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
