#!/usr/bin/env python
"""Decompose the resident cycle's on-chip time: evaluator vs orchestration.

Round-5 measurement: the ta014 lb1 cycle costs ~0.55 us/parent end to end
while the evaluator microbench implies ~0.065 us/parent — an ~8x gap that
is flat in M, i.e. proportional work somewhere in pop/compact/push or in
how the evaluator fuses INSIDE the while_loop. This script times, at the
same (M, n) shapes on the real chip:

  a. the full program step (K cycles of the real while_loop), per cycle;
  b. the jitted evaluator alone on one chunk;
  c. a stripped while_loop whose body runs ONLY the evaluator + counter
     bookkeeping (no dynamic_slice pop, no compaction, no push);
  d. a stripped while_loop with pop + evaluator (no compact/push).

(b vs c) isolates while-loop/fusion-context cost of the evaluator itself;
(c vs d) prices the pop; (d vs a) prices compaction + push. Run on the TPU
host:  python scripts/cycle_profile.py [--M 1024] [--cycles 64]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def timed(fn, *args, iters=5):
    out = fn(*args)
    jax_block(out)
    t0 = time.time()
    for _ in range(iters):
        out = fn(*args)
    jax_block(out)
    return (time.time() - t0) / iters


def jax_block(out):
    import jax

    jax.tree_util.tree_map(
        lambda x: x.block_until_ready() if hasattr(x, "block_until_ready")
        else x, out)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--M", type=int, default=1024)
    ap.add_argument("--cycles", type=int, default=64)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    from tpu_tree_search.engine.device import warmup
    from tpu_tree_search.engine.resident import _make_program, resolve_capacity
    from tpu_tree_search.pool import SoAPool
    from tpu_tree_search.problems import PFSPProblem
    from tpu_tree_search.problems.base import index_batch

    M, K = args.M, args.cycles
    prob = PFSPProblem(inst=14, lb="lb1", ub=1)
    n = prob.child_slots
    capacity, M = resolve_capacity(prob, M, None)
    device = jax.devices()[0]
    prog = _make_program(prob, 25, M, K, capacity, device)

    # A realistic mid-search frontier: warm up on host until > 4*M nodes so
    # every profiled cycle pops a FULL chunk.
    pool = SoAPool(prob.node_fields())
    pool.push_back(index_batch(prob.root(), 0))
    warmup(prob, pool, prob.initial_ub, 4 * M + 64)
    ub = int(prob.initial_ub)

    rows = {}

    # a. real step (fresh state each call would change the tree; reuse the
    # same initial state — donation rules out reuse, so rebuild per call).
    def real_step():
        s = prog.init_state(pool.as_batch(), prob.initial_ub)
        return prog.step(s)

    t_prep = timed(lambda: prog.init_state(pool.as_batch(), prob.initial_ub))
    # The real loop may exit before K cycles (frontier drain / capacity
    # guard): divide by the ACTUAL executed cycle count it reports.
    real_cycles = int(real_step()[-1])
    if real_cycles == 0:
        print(json.dumps({"error": "real step ran 0 cycles; lower --M"}))
        return 1
    t_real = timed(real_step)
    rows["a_real_cycles"] = real_cycles
    rows["a_full_step_ms_per_cycle"] = round(
        1e3 * (t_real - t_prep) / real_cycles, 3)

    # b. evaluator alone on one full chunk (the microbench, at this M).
    evaluate = prog._make_eval()
    vals = jnp.asarray(
        np.tile(np.arange(n, dtype=np.int32), (M, 1))
    )
    aux = jnp.zeros((M,), jnp.int32)
    valid = jnp.ones((M,), bool)
    ev = jax.jit(lambda v, a, vd: evaluate(v, a, vd, ub))
    rows["b_eval_alone_ms"] = round(1e3 * timed(ev, vals, aux, valid), 3)

    # c. while_loop with evaluator-only body (same carry/trip count).
    def mk_loop(with_pop: bool):
        C = capacity

        def body(carry):
            pool_vals, pool_aux, size, best, tree, sol, cycles = carry
            if with_pop:
                cnt = jnp.minimum(size, M)
                start2 = jnp.clip(size - cnt, 0, C - M)
                v_c = lax.dynamic_slice(
                    pool_vals, (start2, 0), (M, n)).astype(jnp.int32)
                a_c = lax.dynamic_slice(
                    pool_aux, (start2,), (M,)).astype(jnp.int32)
                vd = jnp.arange(M, dtype=jnp.int32) < cnt
            else:
                v_c, a_c, vd = vals.astype(jnp.int32), aux, valid
            keep, sol_inc, best = evaluate(v_c, a_c, vd, best)
            # Fold keep into the counters so nothing is dead-code-eliminated.
            tree = tree + jnp.sum(keep, dtype=jnp.int32)
            return (pool_vals, pool_aux, size, best, tree,
                    sol + sol_inc * 0 + 1, cycles + 1)

        def cond(carry):
            return carry[-1] < K

        def run(pool_vals, pool_aux):
            zero = jnp.int32(0)
            return lax.while_loop(cond, body, (
                pool_vals, pool_aux, jnp.int32(4 * M), jnp.int32(ub),
                zero, zero, zero))

        return jax.jit(run)

    pv = jnp.zeros((capacity, n), prog.pool_fields[0][1])
    pa = jnp.zeros((capacity,), prog.pool_fields[1][1])
    rows["c_eval_only_loop_ms_per_cycle"] = round(
        1e3 * timed(mk_loop(False), pv, pa) / K, 3)
    rows["d_pop_plus_eval_loop_ms_per_cycle"] = round(
        1e3 * timed(mk_loop(True), pv, pa) / K, 3)

    rows["M"] = M
    rows["implied_compact_push_ms"] = round(
        rows["a_full_step_ms_per_cycle"]
        - rows["d_pop_plus_eval_loop_ms_per_cycle"], 3)
    print(json.dumps(rows))
    return 0


if __name__ == "__main__":
    sys.exit(main())
