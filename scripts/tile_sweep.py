"""Batch-tile sweep for the Pallas kernels on a real TPU.

The per-kernel tile defaults in `ops/pallas_kernels.py` were chosen from
measured v5e compile times; this script re-measures compile + steady-state
throughput per (kernel, tile) so the defaults can be re-tuned when the
kernels or the toolchain change. Tiles are injected through the TTS_TILE_*
env knobs (read per call; the pallas_call cache is keyed by tile, so one
process sweeps all sizes).

Usage (on a TPU machine)::

    python scripts/tile_sweep.py [--kernels lb1,lb1d,lb2,lb2self]
        [--tiles 32,64,128,256] [--batch 8192] [--inst 14]

Each cell prints compile seconds and children/us; OOM/compile failures are
recorded per cell, never fatal (the sweep is itself a feasibility probe).
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


ENV_BY_KERNEL = {
    "lb1": "TTS_TILE_LB1",
    "lb1d": "TTS_TILE_LB1D",
    "lb2": "TTS_TILE_LB2",
    "lb2self": "TTS_TILE_LB2SELF",
}


def run_cell(kernel: str, tile: int, batch: int, inst: int, reps: int = 20):
    import jax.numpy as jnp
    import numpy as np

    from tpu_tree_search.ops import pallas_kernels as PK, pfsp_device as P
    from tpu_tree_search.problems import PFSPProblem

    prob = PFSPProblem(inst=inst, lb="lb1", ub=1)
    t = P.PFSPDeviceTables(prob.lb1_data, prob.lb2_data)
    rng = np.random.default_rng(0)
    prmu = np.tile(np.arange(prob.jobs, dtype=np.int32), (batch, 1))
    for i in range(batch):
        rng.shuffle(prmu[i])
    limit1 = rng.integers(0, prob.jobs - 1, size=batch).astype(np.int32)
    pd, ld = jnp.asarray(prmu), jnp.asarray(limit1)

    os.environ[ENV_BY_KERNEL[kernel]] = str(tile)
    # The model may shrink an infeasible request (and batch clamps it) —
    # report the tile that actually compiles via the kernels' own
    # effective_tile, or re-tuning would read mislabeled rows.
    eff = PK.effective_tile(
        kernel, prob.jobs, prob.machines, t.pairs.shape[0], batch=batch
    )

    def call():
        if kernel == "lb1":
            return PK.pfsp_lb1_bounds(pd, ld, t.ptm_t, t.min_heads, t.min_tails)
        if kernel == "lb1d":
            return PK.pfsp_lb1_d_bounds(pd, ld, t.ptm_t, t.min_heads,
                                        t.min_tails)
        if kernel == "lb2":
            return PK.pfsp_lb2_bounds(pd, ld, t)
        return PK.pfsp_lb2_self_bounds(pd, ld, batch, t)

    t0 = time.perf_counter()
    call().block_until_ready()
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(reps):
        out = call()
    out.block_until_ready()
    per_call = (time.perf_counter() - t0) / reps
    children = batch * prob.jobs if kernel != "lb2self" else batch
    return eff, compile_s, per_call, children / per_call / 1e6


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--kernels", default="lb1,lb1d,lb2,lb2self")
    ap.add_argument("--tiles", default="32,64,128,256")
    ap.add_argument("--batch", type=int, default=8192)
    ap.add_argument("--inst", type=int, default=14)
    ap.add_argument("--timeout", type=float, default=240.0,
                    help="per-cell subprocess timeout (a pathological "
                    "Mosaic compile must not eat the sweep)")
    ap.add_argument("--cell", default=None, help=argparse.SUPPRESS)
    args = ap.parse_args()

    if args.cell:  # subprocess worker: one (kernel, tile) cell
        kernel, tile = args.cell.split(":")
        try:
            eff, c, p, thr = run_cell(kernel, int(tile), args.batch, args.inst)
            print(f"CELL_OK {eff} {c:.1f} {p * 1e6:.0f} {thr:.2f}")
        except Exception as e:  # noqa: BLE001 — report, don't die
            print(f"CELL_FAIL {type(e).__name__}: {e}")
        return 0

    import subprocess

    print(f"{'kernel':<8} {'tile':>5} {'eff':>5} {'compile_s':>10} "
          f"{'us/call':>9} {'Mchild/s':>9}")
    for kernel in args.kernels.split(","):
        for tile in args.tiles.split(","):
            cmd = [sys.executable, os.path.abspath(__file__),
                   "--cell", f"{kernel}:{tile}",
                   "--batch", str(args.batch), "--inst", str(args.inst)]
            try:
                res = subprocess.run(cmd, capture_output=True, text=True,
                                     timeout=args.timeout)
                line = next((ln for ln in res.stdout.splitlines()
                             if ln.startswith("CELL_")), "CELL_FAIL no output")
            except subprocess.TimeoutExpired:
                line = f"CELL_FAIL timeout>{args.timeout:.0f}s"
            if line.startswith("CELL_OK"):
                _, eff, c, p, thr = line.split()
                print(f"{kernel:<8} {tile:>5} {eff:>5} {c:>10} {p:>9} "
                      f"{thr:>9}")
            else:
                print(f"{kernel:<8} {tile:>5}       {line[10:]}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
