#!/usr/bin/env bash
# Tunnel watcher: poll the TPU backend every ~2 min; the moment it is up,
# run the full hardware session (bench-first) so a short green window still
# banks the round's artifact. If the session ends WITHOUT a banked bench
# (tunnel dropped mid-run), resume watching for the next window; exit only
# once a parity-true bench line landed. Log to .tunnel_watch.log.
set -u
cd "$(dirname "$0")/.."
LOG=.tunnel_watch.log
echo "[watch] start $(date -u +%FT%TZ)" >> "$LOG"
while true; do
  if timeout 45 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    echo "[watch] TPU UP $(date -u +%FT%TZ) — running hw_session" >> "$LOG"
    # Stale parity-true lines from a previous session must not count as a
    # banked bench for THIS run.
    rm -f /tmp/tts_bench_line.json /tmp/tts_bench_express.json
    bash scripts/hw_session.sh >> .hw_session.log 2>&1
    rc=$?
    echo "[watch] hw_session done rc=$rc $(date -u +%FT%TZ)" >> "$LOG"
    if python - <<'EOF' >/dev/null 2>&1
import json, sys
for path in ("/tmp/tts_bench_line.json", "/tmp/tts_bench_express.json"):
    try:
        rec = json.load(open(path))
        # backend must be "tpu": an exported JAX_PLATFORMS=cpu (the outage
        # workaround) passes the liveness probe and yields parity-true CPU
        # records, which must NOT stop the watch (mirrors bench.py's
        # on_tpu banking guard).
        if (rec.get("backend") == "tpu" and rec.get("parity")
                and rec.get("value", 0) > 0):
            sys.exit(0)
    except Exception:
        pass
sys.exit(1)
EOF
    then
      echo "[watch] bench BANKED — exiting $(date -u +%FT%TZ)" >> "$LOG"
      exit 0
    fi
    echo "[watch] bench NOT banked — resuming watch" >> "$LOG"
  else
    echo "[watch] down $(date -u +%FT%TZ)" >> "$LOG"
  fi
  sleep 120
done
