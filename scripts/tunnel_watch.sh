#!/usr/bin/env bash
# Tunnel watcher: poll the TPU backend every ~2 min; the moment it is up,
# run the full hardware session (bench-first) so a short green window still
# banks the round's artifact, then exit. Log everything to .tunnel_watch.log.
set -u
cd "$(dirname "$0")/.."
LOG=.tunnel_watch.log
echo "[watch] start $(date -u +%FT%TZ)" >> "$LOG"
while true; do
  if timeout 45 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    echo "[watch] TPU UP $(date -u +%FT%TZ) — running hw_session" >> "$LOG"
    bash scripts/hw_session.sh >> .hw_session.log 2>&1
    echo "[watch] hw_session done rc=$? $(date -u +%FT%TZ)" >> "$LOG"
    exit 0
  fi
  echo "[watch] down $(date -u +%FT%TZ)" >> "$LOG"
  sleep 120
done
