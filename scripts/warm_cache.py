"""Warm the persistent XLA/Mosaic compile cache for the validation matrix.

A Mosaic compile on the real chip can cost minutes (lb1 tile-128 measured
>270s) and tunnel windows are short — so during any green window this script
compiles every program the bench and the smoke gate need, storing the
executables in the version-keyed compile cache (`cli.enable_compile_cache`).
A second session then starts from a hot cache: bench's numbers stop being
hostage to compile time, and its 300s kernel-probe timeout can't silently
flip the run to the jnp path.

Cache keys include the full program shape, so warming MUST run the exact
entry points with the exact shapes the consumers use: each config below is
one ``resident_search(..., max_steps=1)`` — the full while-loop program plus
its kernels, compiled and executed for a single step. Staged and unstaged
lb2 are distinct programs; both warm. Each config runs in a subprocess with
its own timeout (a compile hang must only cost its slot, bench.py's probe
lesson) and prints wall seconds — re-run to see hits (near-zero seconds).
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

_ITEM = r"""
import os, time, sys
t0 = time.time()
import jax
from tpu_tree_search.cli import enable_compile_cache
from tpu_tree_search.engine.resident import resident_search
from tpu_tree_search.problems import NQueensProblem, PFSPProblem

enable_compile_cache()
kind = sys.argv[1]
if kind == "kernel":
    # Kernel-level warm at the smoke-gate shapes: large-instance resident
    # programs explore tens of millions of nodes in ONE K=4096 dispatch
    # (max_steps can't cut inside a dispatch), blowing the slot timeout on
    # execution the cache doesn't need — the session's reusable artifacts
    # for these classes are the Mosaic KERNEL compiles.
    import jax.numpy as jnp
    from tpu_tree_search.ops import pallas_kernels as PK
    inst, lb, B = int(sys.argv[2]), sys.argv[3], int(sys.argv[4])
    prob = PFSPProblem(inst=inst, lb=lb, ub=1)
    t = prob.device_tables()
    n = prob.jobs
    prmu = jnp.tile(jnp.arange(n, dtype=jnp.int32), (B, 1))
    limit1 = jnp.zeros((B,), dtype=jnp.int32)
    if lb == "lb1":
        out = PK.pfsp_lb1_bounds(prmu, limit1, t.ptm_t, t.min_heads,
                                 t.min_tails, bf16=t.exact_bf16)
    else:
        out = PK.pfsp_lb2_bounds(prmu, limit1, t)
    out.block_until_ready()
    print(f"WARM_OK shape={tuple(out.shape)} wall={time.time() - t0:.1f}s")
    sys.exit(0)
if kind == "nqueens":
    prob = NQueensProblem(N=int(sys.argv[2]))
else:
    prob = PFSPProblem(inst=int(sys.argv[2]), lb=sys.argv[3], ub=1)
M = int(sys.argv[3] if kind == "nqueens" else sys.argv[5])
res = resident_search(prob, m=25, M=M, max_steps=1)
print(f"WARM_OK tree={res.explored_tree} wall={time.time() - t0:.1f}s")
"""

# (label, argv tail, env overrides) — the bench + smoke-gate matrix, most
# valuable first so a closing window still banks the flagship programs.
CONFIGS: list[tuple[str, list[str], dict[str, str]]] = [
    # M values match the bench's measured defaults (HEADLINE_M / lb2_M —
    # scripts/headline_tune.py, scripts/lb2_tune.py): warming MUST compile
    # the exact programs the bench dispatches.
    ("ta014 lb2 staged M=1024", ["pfsp", "14", "lb2", "-", "1024"],
     {"TTS_LB2_STAGED": "1"}),
    ("ta014 lb2 unstaged M=1024", ["pfsp", "14", "lb2", "-", "1024"],
     {"TTS_LB2_STAGED": "0"}),
    # Pair-block A/B for the armed lb2 session (docs/HW_VALIDATION.md):
    # the serial-loop build (TTS_LB2_PAIRBLOCK=1) is a distinct program
    # from the default blocked one warmed above — bank both so the A/B
    # costs measurement time only.
    ("ta014 lb2 staged M=1024 pairblock=1", ["pfsp", "14", "lb2", "-", "1024"],
     {"TTS_LB2_STAGED": "1", "TTS_LB2_PAIRBLOCK": "1"}),
    # Published BASELINE config 4 (ta021-ta030 class, 20x20, P=190 —
    # `pfsp_multigpu_chpl.chpl:312`): never benched on chip; warm both
    # staged variants at the lb2-tuned chunk size so the first measured
    # ta021 number pays zero compile seconds.
    ("ta021 lb2 staged M=1024", ["pfsp", "21", "lb2", "-", "1024"],
     {"TTS_LB2_STAGED": "1"}),
    ("ta021 lb2 unstaged M=1024", ["pfsp", "21", "lb2", "-", "1024"],
     {"TTS_LB2_STAGED": "0"}),
    ("ta014 lb1 M=1024 jnp", ["pfsp", "14", "lb1", "-", "1024"],
     {"TTS_PALLAS": "0"}),
    # TTS_K=auto ladder programs for the headline config (geometric rungs
    # 1..1024; the default row below covers 4096): the adaptive controller
    # climbs through every rung from the bottom, and each rung is a
    # distinct while-loop compile — bank them all so an auto-K session
    # resizes through cache hits instead of paying ~30s per rung
    # (engine/pipeline.py AdaptiveK; zero steady-state recompiles).
    ("ta014 lb1 M=1024 K=1", ["pfsp", "14", "lb1", "-", "1024"],
     {"TTS_K": "1"}),
    ("ta014 lb1 M=1024 K=4", ["pfsp", "14", "lb1", "-", "1024"],
     {"TTS_K": "4"}),
    ("ta014 lb1 M=1024 K=16", ["pfsp", "14", "lb1", "-", "1024"],
     {"TTS_K": "16"}),
    ("ta014 lb1 M=1024 K=64", ["pfsp", "14", "lb1", "-", "1024"],
     {"TTS_K": "64"}),
    ("ta014 lb1 M=1024 K=256", ["pfsp", "14", "lb1", "-", "1024"],
     {"TTS_K": "256"}),
    ("ta014 lb1 M=1024 K=1024", ["pfsp", "14", "lb1", "-", "1024"],
     {"TTS_K": "1024"}),
    # Default knob is TTS_COMPACT=auto now (survivor-path overhaul): the
    # unpinned rows below warm the AUTO programs (dense at these shapes);
    # the explicit compact=... variants warm the A/B counterparts.
    ("ta014 lb1 M=1024", ["pfsp", "14", "lb1", "-", "1024"], {}),
    ("ta014 lb1_d M=1024", ["pfsp", "14", "lb1_d", "-", "1024"], {}),
    ("nqueens N=15 M=65536", ["nqueens", "15", "65536"], {}),
    # Published BASELINE config 2 (N-Queens N=16/17): the bench's bounded
    # rate rows dispatch these exact programs (max_steps cuts the run, the
    # compile is shape-identical).
    ("nqueens N=16 M=65536", ["nqueens", "16", "65536"], {}),
    ("nqueens N=17 M=65536", ["nqueens", "17", "65536"], {}),
    # First-ever N-Queens chunk-size sweep (VERDICT r5 #2,
    # scripts/headline_tune.py --problem nqueens --N ...): bank the sweep
    # grid's end points so the armed session spends its window measuring,
    # not compiling (the 65536 rows above cover the middle).
    ("nqueens N=15 M=8192", ["nqueens", "15", "8192"], {}),
    ("nqueens N=15 M=262144", ["nqueens", "15", "262144"], {}),
    ("nqueens N=16 M=262144", ["nqueens", "16", "262144"], {}),
    ("nqueens N=17 M=131072", ["nqueens", "17", "131072"], {}),
    # Compaction-mode variants (ADVICE r5 + the survivor-path A/B):
    # bench's on-TPU pick dispatches every TTS_COMPACT mode (the mode is
    # part of the routing token, so each is a distinct compile) — warm
    # them too, or a fresh cache makes the pick burn its 600s/300s budget
    # on compiles and skip modes. `scatter` must be pinned explicitly now
    # that the default resolves to dense at these shapes.
    ("ta014 lb1 M=1024 compact=scatter", ["pfsp", "14", "lb1", "-", "1024"],
     {"TTS_COMPACT": "scatter"}),
    ("ta014 lb1 M=1024 compact=sort", ["pfsp", "14", "lb1", "-", "1024"],
     {"TTS_COMPACT": "sort"}),
    ("ta014 lb1 M=1024 compact=search", ["pfsp", "14", "lb1", "-", "1024"],
     {"TTS_COMPACT": "search"}),
    ("ta014 lb2 M=1024 compact=scatter", ["pfsp", "14", "lb2", "-", "1024"],
     {"TTS_COMPACT": "scatter"}),
    ("ta014 lb2 M=1024 compact=sort", ["pfsp", "14", "lb2", "-", "1024"],
     {"TTS_COMPACT": "sort"}),
    ("ta014 lb2 M=1024 compact=search", ["pfsp", "14", "lb2", "-", "1024"],
     {"TTS_COMPACT": "search"}),
    # The N-Queens fused-vs-scatter A/B programs (docs/HW_VALIDATION.md
    # armed-session rows): default auto resolves dense; scatter is the
    # round-5 baseline path.
    ("nqueens N=15 M=65536 compact=scatter", ["nqueens", "15", "65536"],
     {"TTS_COMPACT": "scatter"}),
    # Large-instance classes (VERDICT r4 #7): ta031 = 50x10, ta056 = 50x20,
    # ta111 = 500x20. Kernel-level at the smoke-gate shapes (see _ITEM's
    # "kernel" note); the set mirrors test_large_instance_kernels_compile_on_tpu.
    ("ta031 lb1 kernel B=64", ["kernel", "31", "lb1", "64"], {}),
    ("ta056 lb1 kernel B=32", ["kernel", "56", "lb1", "32"], {}),
    ("ta056 lb2 kernel B=16", ["kernel", "56", "lb2", "16"], {}),
    ("ta111 lb1 kernel B=16", ["kernel", "111", "lb1", "16"], {}),
]


def main() -> int:
    timeout_s = float(os.environ.get("TTS_WARM_TIMEOUT", "420"))
    failures = 0
    for label, argv, env in CONFIGS:
        t0 = time.time()
        try:
            res = subprocess.run(
                [sys.executable, "-c", _ITEM, *argv],
                timeout=timeout_s, capture_output=True, text=True,
                env={**os.environ, **env},
            )
            ok = res.returncode == 0 and "WARM_OK" in res.stdout
            detail = (res.stdout.strip().splitlines() or [""])[-1] if ok else \
                (res.stderr or res.stdout).strip().splitlines()[-1:]
        except subprocess.TimeoutExpired:
            ok, detail = False, f"timeout {timeout_s:.0f}s"
        failures += not ok
        # flush: the session log must stream per-config progress (a redirect
        # block-buffers prints, hiding everything until exit — observed when
        # the tunnel died mid-run and the log stayed empty).
        print(f"{'ok ' if ok else 'FAIL'} {time.time() - t0:7.1f}s  "
              f"{label}  {detail}", flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
