"""Warm the persistent XLA/Mosaic compile cache for the validation matrix.

A Mosaic compile on the real chip can cost minutes (lb1 tile-128 measured
>270s) and tunnel windows are short — so during any green window this script
compiles every program the bench and the smoke gate need, storing the
executables in the version-keyed compile cache (`cli.enable_compile_cache`).
A second session then starts from a hot cache: bench's numbers stop being
hostage to compile time, and its 300s kernel-probe timeout can't silently
flip the run to the jnp path.

Cache keys include the full program shape, so warming MUST run the exact
entry points with the exact shapes the consumers use: each config below is
one ``resident_search(..., max_steps=1)`` — the full while-loop program plus
its kernels, compiled and executed for a single step. Staged and unstaged
lb2 are distinct programs; both warm. Each config runs in a subprocess with
its own timeout (a compile hang must only cost its slot, bench.py's probe
lesson) and prints wall seconds — re-run to see hits (near-zero seconds).
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

_ITEM = r"""
import os, time, sys
t0 = time.time()
import jax
from tpu_tree_search.cli import enable_compile_cache
from tpu_tree_search.engine.resident import resident_search
from tpu_tree_search.problems import NQueensProblem, PFSPProblem

enable_compile_cache()
kind = sys.argv[1]
if kind == "nqueens":
    prob = NQueensProblem(N=int(sys.argv[2]))
else:
    prob = PFSPProblem(inst=int(sys.argv[2]), lb=sys.argv[3], ub=1)
M = int(sys.argv[3] if kind == "nqueens" else sys.argv[5])
res = resident_search(prob, m=25, M=M, max_steps=1)
print(f"WARM_OK tree={res.explored_tree} wall={time.time() - t0:.1f}s")
"""

# (label, argv tail, env overrides) — the bench + smoke-gate matrix, most
# valuable first so a closing window still banks the flagship programs.
CONFIGS: list[tuple[str, list[str], dict[str, str]]] = [
    ("ta014 lb2 staged M=65536", ["pfsp", "14", "lb2", "-", "65536"],
     {"TTS_LB2_STAGED": "1"}),
    ("ta014 lb2 unstaged M=65536", ["pfsp", "14", "lb2", "-", "65536"],
     {"TTS_LB2_STAGED": "0"}),
    ("ta014 lb1 M=65536", ["pfsp", "14", "lb1", "-", "65536"], {}),
    ("ta014 lb1_d M=65536", ["pfsp", "14", "lb1_d", "-", "65536"], {}),
    ("nqueens N=15 M=65536", ["nqueens", "15", "65536"], {}),
    # Large-instance classes (VERDICT r4 #7): ta056 = 50x20, ta111 = 500x20.
    ("ta056 lb1 M=4096", ["pfsp", "56", "lb1", "-", "4096"], {}),
    ("ta056 lb2 M=4096", ["pfsp", "56", "lb2", "-", "4096"], {}),
    ("ta111 lb1 M=1024", ["pfsp", "111", "lb1", "-", "1024"], {}),
]


def main() -> int:
    timeout_s = float(os.environ.get("TTS_WARM_TIMEOUT", "420"))
    failures = 0
    for label, argv, env in CONFIGS:
        t0 = time.time()
        try:
            res = subprocess.run(
                [sys.executable, "-c", _ITEM, *argv],
                timeout=timeout_s, capture_output=True, text=True,
                env={**os.environ, **env},
            )
            ok = res.returncode == 0 and "WARM_OK" in res.stdout
            detail = (res.stdout.strip().splitlines() or [""])[-1] if ok else \
                (res.stderr or res.stdout).strip().splitlines()[-1:]
        except subprocess.TimeoutExpired:
            ok, detail = False, f"timeout {timeout_s:.0f}s"
        failures += not ok
        print(f"{'ok ' if ok else 'FAIL'} {time.time() - t0:7.1f}s  "
              f"{label}  {detail}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
