"""Warm the persistent XLA/Mosaic compile cache for the validation matrix.

Thin shim: the warm matrix and the subprocess loop moved to
``tpu_tree_search/serve/warmup.py`` (the serve daemon reuses them for its
AOT pool warm at startup); ``tts warmup`` is the first-class entry point
and adds per-config compile-cache hit/miss reporting. This script remains
so existing recipes (`python scripts/warm_cache.py` during a green tunnel
window) keep working unchanged.

Optionally pass a config selection: ``python scripts/warm_cache.py
ta014-lb1,nqueens-15`` (names from ``tts warmup --configs``; default: the
whole matrix).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from tpu_tree_search.serve.warmup import warmup_main  # noqa: E402


def main() -> int:
    names = sys.argv[1] if len(sys.argv) > 1 else None
    return warmup_main(names)


if __name__ == "__main__":
    sys.exit(main())
