#!/usr/bin/env bash
# One-shot GPU session: run this on a host with a CUDA jaxlib and a card.
# The GPU mirror of scripts/hw_session.sh, artifact-first for the same
# round-4 reason: the session's one mandatory artifact — GPU_BASELINE.json
# with parity-gated ta014 lb1/lb2 + N-Queens rows and roofline capture —
# is banked immediately after liveness; validation breadth comes after.
# Every stage is independently timeboxed so a hang cannot eat the window.
#
# What "GPU" means here (docs/PARALLELISM.md backend matrix): the factored
# Pallas tile bodies lowered through pallas.triton (TTS_KERNEL_BACKEND=gpu,
# ops/backend.py), the GPU rows of the routing policy tables, and the
# single-tile megakernel arm. Correctness was already proven on CPU by
# interpret-mode bit-parity (tests/test_gpu_lowering.py, CI); this session
# exists to (a) prove the Triton compiles land on a real card and (b) bank
# measured rates + the measured HBM peak that replaces the nominal 900 GB/s
# placeholder in obs/roofline.py.
set -u
cd "$(dirname "$0")/.."

export TTS_FLIGHTREC="${TTS_FLIGHTREC:-/tmp/tts_flight_gpu}"
export TTS_WATCHDOG_S="${TTS_WATCHDOG_S:-120}"

echo "== 1/7 backend liveness =="
if ! timeout 120 python -c "
import jax
devs = jax.devices()
print(devs)
assert devs[0].platform == 'gpu', f'not a GPU backend: {devs[0].platform}'
"; then
  echo "GPU unreachable — aborting GPU session"; exit 1
fi

echo "== 2/7 compiled-kernel parity gate (Triton lowering, not interpret) =="
# The interpret-mode gate already ran in CI; this is the part CI cannot
# prove — the pallas.triton COMPILE of each lowered body on this card,
# checked bit-for-bit against the jnp oracle. Red here means stop: every
# later rate would be a number for a different tree.
set -o pipefail
timeout 900 python - <<'EOF' || { echo "GPU COMPILED PARITY FAILED — aborting"; exit 1; }
import numpy as np
import jax.numpy as jnp
from tpu_tree_search.ops import pallas_kernels as PK
from tpu_tree_search.ops import pfsp_device
from tpu_tree_search.problems import PFSPProblem

prob = PFSPProblem(inst=14, lb="lb2", ub=1)
t = pfsp_device.PFSPDeviceTables(prob.lb1_data, prob.lb2_data)
n = prob.jobs
rng = np.random.default_rng(20)
B = 4096
prmu = jnp.asarray(np.stack([rng.permutation(n).astype(np.int32)
                             for _ in range(B)]))
limit1 = jnp.asarray(rng.integers(-1, n - 1, B).astype(np.int32))
o1 = pfsp_device._lb1_chunk(prmu, limit1, t.ptm_t, t.min_heads, t.min_tails)
g1 = PK.pfsp_lb1_bounds(prmu, limit1, t.ptm_t, t.min_heads, t.min_tails,
                        interpret=False, backend="gpu")
assert np.array_equal(np.asarray(o1), np.asarray(g1)), "lb1 compiled parity"
o2 = pfsp_device._lb2_chunk(prmu, limit1, t.ptm_t, t.min_heads, t.min_tails,
                            t.pairs, t.lags, t.johnson_schedules)
g2 = PK.pfsp_lb2_bounds(prmu, limit1, t, interpret=False, backend="gpu")
open_ = np.arange(n)[None, :] >= np.asarray(limit1)[:, None] + 1
assert np.array_equal(np.asarray(o2)[open_], np.asarray(g2)[open_]), \
    "lb2 compiled parity"
print("GPU_COMPILED_PARITY_OK", B)
EOF

echo "== 3/7 GPU headline bench (banks GPU_BASELINE.json on success) =="
# ta014 lb1 + lb2 and N-Queens N=15 under TTS_KERNEL_BACKEND=gpu, parity
# gated against the sequential goldens, roofline captured per row. On a
# gpu platform bench.py writes the COMMITTED GPU_BASELINE.json path.
if timeout 3000 python bench.py gpu_headline | tee /tmp/tts_gpu_headline.json; then
  echo "GPU HEADLINE OK"
else
  echo "GPU HEADLINE FAILED — GPU_BASELINE.json not refreshed"
fi
set +o pipefail

echo "== 4/7 measured HBM peak (replaces the nominal roofline row) =="
# The roofline denominator (obs/roofline.py NOMINAL_GBPS['gpu'] = 900 is
# an A100-PCIe-class placeholder): bank this card's measured dispatch
# latency+bandwidth fit into COSTMODEL.json, whose hbm link the audit
# prefers over the nominal table. TTS_HBM_GBPS stays available as the
# explicit per-run override when the fit is unavailable.
TTS_KERNEL_BACKEND=gpu timeout 900 python -m tpu_tree_search.cli pfsp \
    --inst 14 --tier device --costmodel COSTMODEL.json --guard \
  || echo "COSTMODEL BANKING FAILED (roofline rows stay nominal:gpu)"

echo "== 5/7 megakernel single-tile arm (GPU keep/retire evidence) =="
# The GPU megakernel ships single-tile only (no sequential-grid carry in
# Triton's parallel CUDA-block model — the tiled arm refuses with a
# reason, docs/PARALLELISM.md). Off vs force, golden parity inline.
TTS_GUARD=1 TTS_KERNEL_BACKEND=gpu timeout 900 python - <<'EOF' \
  | tee MEGAKERNEL_AB_GPU.json || echo "GPU MEGAKERNEL AB FAILED"
import json, os, time
from tpu_tree_search.engine.resident import resident_search
from tpu_tree_search.problems import PFSPProblem

GOLDEN = None
row = {"metric": "megakernel_ab_gpu", "m": 25, "M": 1024}
for label, knob in (("off", "0"), ("force", "force")):
    os.environ["TTS_MEGAKERNEL"] = knob
    resident_search(PFSPProblem(inst=14, lb="lb1", ub=1), m=25, M=1024)
    t0 = time.perf_counter()
    res = resident_search(PFSPProblem(inst=14, lb="lb1", ub=1), m=25, M=1024)
    wall = time.perf_counter() - t0
    counts = (res.explored_tree, res.explored_sol, res.best)
    if GOLDEN is None:
        GOLDEN = counts
    assert counts == GOLDEN, f"{label}: {counts} != {GOLDEN}"
    row[f"{label}_s"] = round(wall, 3)
    row[f"{label}_nodes_per_sec"] = round(res.explored_tree / wall, 1)
    row[f"{label}_megakernel"] = res.megakernel
    row[f"{label}_kernel_backend"] = res.kernel_backend
    if res.megakernel_reason:
        row[f"{label}_reason"] = res.megakernel_reason
row["speedup"] = round(row["off_s"] / max(row["force_s"], 1e-9), 3)
print(json.dumps(row))
EOF

echo "== 6/7 GPU lowering suite (native run of the CI interpret matrix) =="
timeout 1800 python -m pytest tests/test_gpu_lowering.py -v \
  || echo "GPU LOWERING SUITE FAILED"

echo "== 7/7 post-mortem banking =="
for f in "$TTS_FLIGHTREC".trace.json "$TTS_FLIGHTREC".metrics.jsonl; do
  if [ -f "$f" ]; then
    cp "$f" . && echo "banked post-mortem: $(basename "$f")"
  fi
done
[ -f GPU_BASELINE.json ] && echo "GPU_BASELINE.json present"
[ -f COSTMODEL.json ] && echo "COSTMODEL.json present (arm runs with TTS_COSTMODEL=COSTMODEL.json)"

echo "Done. Update docs/HW_VALIDATION.md (GPU session) with the results."
