"""Pallas TPU kernels for the hot chunk evaluators.

The XLA (jnp) evaluators in `nqueens_device.py` / `pfsp_device.py` are the
semantic oracles and the portable path; these kernels are the hand-scheduled
TPU variants: one VMEM-resident pass per batch tile — the instance tables
(processing times, min heads/tails) are pinned in VMEM for the whole grid,
every intermediate (the one-hot gather, the O(n) schedule_front scan, the
per-child bound chain) lives in registers/VMEM, and nothing round-trips
through HBM between fusion boundaries.

Reference counterparts: `evaluate_gpu` (`nqueens_gpu_chpl.chpl:97-123`) and
`evaluate_gpu_lb1` (`evaluate.cu:25-49`, device math `c_bounds_gpu.cu:15-195`)
— one SIMT thread per (parent, child); here one grid step per TILE_B parents
with all children vectorized on the VPU/MXU.

Selection: ``use_pallas()`` returns True on TPU backends unless disabled via
``TTS_PALLAS=0``; tests force ``interpret=True`` on CPU to check the kernels
bit-for-bit against the jnp oracles.
"""

from __future__ import annotations

import os
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def use_pallas() -> bool:
    if os.environ.get("TTS_PALLAS", "1") == "0":
        return False
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _round_up(x: int, k: int) -> int:
    return (x + k - 1) // k * k


# ---------------------------------------------------------------------------
# N-Queens safety labels
# ---------------------------------------------------------------------------


def _nqueens_kernel(board_ref, depth_ref, out_ref, *, N: int, g: int):
    """labels[b, k] = 1 iff board[b, k] placed at column depth_b clashes with
    no placed queen on either diagonal (`nqueens_gpu_chpl.chpl:99-123`)."""
    board = board_ref[:].astype(jnp.int32)  # (T, N)
    depth = depth_ref[:, 0].astype(jnp.int32)  # (T,)
    qk = board[:, None, :]  # candidate rows (T, 1, N)
    bi = board[:, :, None]  # placed queens  (T, N, 1)
    i = jax.lax.broadcasted_iota(jnp.int32, (1, N, 1), 1)
    d = depth[:, None, None] - i  # (T, N, 1)
    placed = i < depth[:, None, None]

    def one_round(_, safe):
        clash = (bi == qk - d) | (bi == qk + d)
        return safe & ~jnp.any(clash & placed, axis=1)

    safe = one_round(0, jnp.ones(board.shape, dtype=bool))
    if g > 1:
        safe = jax.lax.fori_loop(0, g - 1, one_round, safe)
    k = jax.lax.broadcasted_iota(jnp.int32, board.shape, 1)
    out_ref[:] = (safe & (k >= depth[:, None])).astype(jnp.uint8)


@lru_cache(maxsize=None)
def _nqueens_call(N: int, g: int, B: int, tile: int, interpret: bool):
    kernel = partial(_nqueens_kernel, N=N, g=g)
    grid = (B // tile,)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B, N), jnp.uint8),
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile, N), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tile, N), lambda i: (i, 0), memory_space=pltpu.VMEM),
        interpret=interpret,
    )


def nqueens_labels(board, depth, N: int, g: int = 1, interpret: bool = False):
    """(B, N) uint8 labels; same contract as `nqueens_device.make_core`."""
    B = board.shape[0]
    tile = min(512, B)
    Bp = _round_up(B, tile)
    if Bp != B:
        board = jnp.pad(board, ((0, Bp - B), (0, 0)))
        depth = jnp.pad(depth, ((0, Bp - B),))
    out = _nqueens_call(N, g, Bp, tile, interpret)(
        board.astype(jnp.int32), depth.astype(jnp.int32)[:, None]
    )
    return out[:B]


# ---------------------------------------------------------------------------
# PFSP lb1 child bounds
# ---------------------------------------------------------------------------


def _lb1_kernel(
    prmu_ref, limit1_ref, ptm_ref, heads_ref, tails_ref, out_ref, *, n: int, m: int
):
    """Full lb1 bound of every child of every parent in the tile.

    Math identical to `ops/pfsp_device._lb1_chunk` (itself the batched form
    of `c_bound_simple.c:51-141` + one incremental `add_forward` per child);
    here the whole chain runs on one VMEM tile: one-hot MXU gather of the
    per-position processing times, the O(n) schedule_front scan, the O(m)
    child update, and the machine-bound max chain.
    """
    prmu = prmu_ref[:].astype(jnp.int32)  # (T, n)
    limit1 = limit1_ref[:, 0].astype(jnp.int32)  # (T,)
    ptm = ptm_ref[:].astype(jnp.float32)  # (n, m) job-major
    T = prmu.shape[0]

    # ptg[b, i, :] = ptm[prmu[b, i]] via one-hot matmul (exact: ints < 2^24).
    jobs_iota = jax.lax.broadcasted_iota(jnp.int32, (T, n, n), 2)
    onehot = (jobs_iota == prmu[:, :, None]).astype(jnp.float32)
    ptg = jax.lax.dot_general(
        onehot.reshape(T * n, n),
        ptm,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,  # MXU default bf16 pass rounds ints > 256
    ).reshape(T, n, m).astype(jnp.int32)

    # schedule_front(prmu, limit1): n-step scan, masked per row.
    front = jnp.zeros((T, m), jnp.int32)

    def scan_step(i, front):
        pt = ptg[:, i, :]
        cols = [front[:, 0] + pt[:, 0]]
        for j in range(1, m):
            cols.append(jnp.maximum(cols[-1], front[:, j]) + pt[:, j])
        newf = jnp.stack(cols, axis=-1)
        return jnp.where((i <= limit1)[:, None], newf, front)

    front = jax.lax.fori_loop(0, n, scan_step, front)
    front = jnp.where((limit1 == -1)[:, None], heads_ref[:], front)

    # remaining work per machine after removing the child job.
    unsched = (
        jax.lax.broadcasted_iota(jnp.int32, (T, n), 1) >= (limit1 + 1)[:, None]
    ).astype(jnp.int32)
    remain = jnp.sum(ptg * unsched[:, :, None], axis=1)  # (T, m)

    # Child k: one add_forward step + machine bound chain, unrolled over m.
    tails = tails_ref[:][0]  # (m,)
    f = front[:, None, :]  # (T, 1, m)
    cf0 = f[..., 0] + ptg[..., 0]  # child front, machine 0: (T, n)
    child_front = [cf0]
    for j in range(1, m):
        child_front.append(jnp.maximum(child_front[-1], f[..., j]) + ptg[..., j])
    cremain = remain[:, None, :] - ptg  # (T, n, m)
    tmp0 = child_front[0] + cremain[..., 0]
    lb = tmp0 + tails[0]
    for i in range(1, m):
        tmp1 = jnp.maximum(tmp0, child_front[i] + cremain[..., i])
        lb = jnp.maximum(lb, tmp1 + tails[i])
        tmp0 = tmp1
    out_ref[:] = lb


@lru_cache(maxsize=None)
def _lb1_call(n: int, m: int, B: int, tile: int, interpret: bool):
    kernel = partial(_lb1_kernel, n=n, m=m)
    grid = (B // tile,)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B, n), jnp.int32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile, n), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((n, m), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, m), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, m), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tile, n), lambda i: (i, 0), memory_space=pltpu.VMEM),
        interpret=interpret,
    )


def pfsp_lb1_bounds(
    prmu, limit1, ptm_t, min_heads, min_tails, interpret: bool = False
):
    """(B, n) int32 lb1 child bounds; same contract as `_lb1_chunk`."""
    B, n = prmu.shape
    m = ptm_t.shape[1]
    tile = min(256, B)
    Bp = _round_up(B, tile)
    if Bp != B:
        prmu = jnp.pad(prmu, ((0, Bp - B), (0, 0)))
        limit1 = jnp.pad(limit1, ((0, Bp - B),))
    out = _lb1_call(n, m, Bp, tile, interpret)(
        prmu.astype(jnp.int32),
        limit1.astype(jnp.int32)[:, None],
        ptm_t.astype(jnp.int32),
        min_heads.astype(jnp.int32)[None, :],
        min_tails.astype(jnp.int32)[None, :],
    )
    return out[:B]
