"""Pallas TPU kernels for the hot chunk evaluators.

The XLA (jnp) evaluators in `nqueens_device.py` / `pfsp_device.py` are the
semantic oracles and the portable path; these kernels are the hand-scheduled
TPU variants: one VMEM-resident pass per batch tile — the instance tables
(processing times, min heads/tails) are pinned in VMEM for the whole grid,
every intermediate (the one-hot gather, the O(n) schedule_front scan, the
per-child bound chain) lives in registers/VMEM, and nothing round-trips
through HBM between fusion boundaries.

Reference counterparts: `evaluate_gpu` (`nqueens_gpu_chpl.chpl:97-123`) and
`evaluate_gpu_lb1` (`evaluate.cu:25-49`, device math `c_bounds_gpu.cu:15-195`)
— one SIMT thread per (parent, child); here one grid step per TILE_B parents
with all children vectorized on the VPU/MXU.

Selection: ``use_pallas()`` consults the kernel-backend seam
(`ops/backend.py`, ``TTS_KERNEL_BACKEND``) — True on native TPU/GPU
backends unless disabled via ``TTS_PALLAS=0``; tests force
``interpret=True`` on CPU to check the kernels bit-for-bit against the jnp
oracles.  Every factory takes a ``backend`` flavor ("tpu"/"gpu"): the GPU
flavor lowers the SAME tile bodies through `jax.experimental.pallas.triton`
— plain BlockSpecs (Triton has no memory spaces), no scratch refs (the
position-major scan staging statically unrolls instead — `_front_scan`),
Triton compiler params — and runs under interpret mode on non-GPU
processes (the CI parity path).
"""

from __future__ import annotations

import os
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..utils import jax_compat


def use_pallas(device=None) -> bool:
    """Route to the Pallas kernels only when the *target device* natively
    compiles the resolved kernel flavor (`ops/backend.py`).

    The reference's dispatcher selects per device context
    (`evaluate.cu:93-119`); keying on the process default backend instead
    breaks any CPU-device execution inside a TPU-default process (e.g. the
    driver's virtual-CPU multichip dryrun). Callers that own a device thread
    it through; ``None`` falls back to the default backend.  A FORCED gpu
    flavor on a non-GPU process still routes to the kernels — they run
    under interpret mode (`_default_interpret`), which is how CI proves the
    Triton-structured lowering bit-exact without a GPU.
    """
    if os.environ.get("TTS_PALLAS", "1") == "0":
        return False
    if pallas_interpret():
        return True
    try:
        from . import backend as BK

        b = BK.resolve_backend(device)
    except Exception:
        return False
    if b.kind == "jnp":
        return False
    if b.kind == "tpu":
        return b.native
    return True  # gpu: native compiles Triton; forced runs interpret


def pallas_forced() -> bool:
    """``TTS_PALLAS=force``: re-arm the demoted lb1-family kernel routing
    (see ``lb1_pallas_enabled``) — the armed-session A/B spelling."""
    return os.environ.get("TTS_PALLAS", "") == "force"


def lb1_pallas_enabled() -> bool:
    """lb1-family demotion (decision record: docs/HW_VALIDATION.md).

    The round-5 on-chip microbench measured the fused jnp/XLA lb1 path at
    ~7x the hand-written Pallas kernel on the production chunk shapes
    (315M vs 41M bound-evals/s — XLA's own fusion wins on this op), and
    the bench had been empirically demoting the headline to jnp every
    round. This makes that measurement the default: the lb1/lb1_d
    evaluators route to the fused jnp path everywhere, and the kernels
    stay reachable for the A/B via ``TTS_PALLAS=force`` (interpret mode
    also still routes through them — it exists to exercise kernel/
    composition code paths, not to be fast). The lb2 family is NOT
    demoted: its kernel keeps the whole Johnson pair loop in VMEM and
    measures faster than jnp on chip."""
    return pallas_forced() or pallas_interpret()


def pallas_interpret() -> bool:
    """``TTS_PALLAS_INTERPRET=1`` routes the evaluators to the Pallas
    kernels in interpret mode on ANY backend. This is the off-chip way to
    drive compositions the CPU suite otherwise cannot reach — above all
    pallas_call inside the mesh tiers' ``shard_map`` (the round-5 hardware
    session caught a vma trace failure there that every CPU test missed
    because ``use_pallas`` is False off-TPU). Kernel *math* runs
    interpreted; routing, tracing, and the shard_map composition are the
    real path. ``TTS_PALLAS=0`` still wins."""
    return os.environ.get("TTS_PALLAS_INTERPRET", "0") == "1"


def _default_interpret(backend: str = "tpu") -> bool:
    """The interpret default a kernel entry resolves when the caller does
    not force one: the TTS_PALLAS_INTERPRET knob as always, plus — for the
    gpu flavor — any process that cannot compile Triton natively (the CI
    parity path: Triton-structured kernels, interpreted on CPU)."""
    if pallas_interpret():
        return True
    if backend == "gpu":
        from . import backend as BK

        return not BK.resolve_backend(None).native
    return False


def _round_up(x: int, k: int) -> int:
    return (x + k - 1) // k * k


def _vmem_limit_bytes(backend: str = "tpu") -> int | None:
    """Scoped fast-memory ceiling for the PFSP kernels, per backend.

    TPU: the Mosaic scoped-VMEM charge. The Mosaic default (16 MB) rejects
    the lb-family kernels above tile 64 (the (T, n, n) one-hot and the
    (n, T, m) scan scratch pad n/m up to the 128-lane tile); v5e has
    128 MB of VMEM, so raising the scope to 96 MB is safe for a standalone
    pallas_call and lets the batch tile grow to MXU-efficient sizes.

    GPU: Triton has no compiler-enforced scope — this is the PROVISIONAL
    per-block working-set ceiling the tile chooser sizes against
    (``TTS_PALLAS_GPU_MB``, default 64: register file + L1/shared per SM
    on A100/H100-class parts comfortably covers a 32 MB half-budget
    working set via L2 residency; re-measure with
    `scripts/gpu_session.sh`)."""
    if backend == "gpu":
        mb = int(os.environ.get("TTS_PALLAS_GPU_MB", "64"))
        if mb < 0:
            raise ValueError(
                f"TTS_PALLAS_GPU_MB must be >= 0 (0 disables), got {mb}")
        return mb * 2**20 if mb else None
    mb = int(os.environ.get("TTS_PALLAS_VMEM_MB", "96"))
    if mb < 0:
        raise ValueError(f"TTS_PALLAS_VMEM_MB must be >= 0 (0 disables), got {mb}")
    return mb * 2**20 if mb else None


def _compiler_params(ndims: int = 1, parallel: bool = False,
                     backend: str = "tpu"):
    # Backend-keyed compiler params via the jax_compat shim (the version
    # probe — CompilerParams vs TPUCompilerParams vs TritonCompilerParams —
    # lives there, never inline here). ``ndims`` sizes dimension_semantics
    # to the grid rank; ``parallel`` marks every grid axis
    # Megacore-splittable (only safe for carry-free kernels — see
    # megakernel.streamed_eval_bounds). Both are TPU-only concepts: the
    # Triton grid is parallel CUDA blocks unconditionally.
    return jax_compat.pallas_compiler_params(
        backend=backend, ndims=ndims, parallel=parallel,
        vmem_limit_bytes=_vmem_limit_bytes(backend),
    )


def _bs(shape, index_map, space: str = "vmem", backend: str = "tpu"):
    """Backend-keyed BlockSpec (jax_compat shim): memory-space-pinned on
    TPU, plain on Triton."""
    return jax_compat.pallas_block_spec(shape, index_map, space=space,
                                        backend=backend)


def _scratch(backend: str, *tpu_shapes):
    """Backend-keyed scratch_shapes (jax_compat shim): empty on Triton."""
    return jax_compat.pallas_scratch_shapes(backend, *tpu_shapes)


def _env_tile(name: str, default: int) -> int:
    tile = int(os.environ.get(name, str(default)))
    if tile < 1:
        raise ValueError(f"{name} must be a positive batch-tile size, got {tile}")
    return tile


def _r8(x: int) -> int:
    return _round_up(x, 8)


def _r128(x: int) -> int:
    return _round_up(x, 128)


def _model_bytes(t: int, n: int, m: int, extra_bytes: int,
                 tn2_copies: int, pair_copies: int = 0,
                 pair_group: int = 1) -> int:
    """The kernels' modeled VMEM footprint at batch tile ``t`` — the single
    source of truth shared by the tile chooser and the routing gate.
    ``tn2_copies`` counts the shared (T, n, n)-class f32 live values
    (one-hot + reshape copies); ``pair_copies`` the per-pair ones (the pair
    body's u_o/mp0/mp1/cum0/suf1), charged once per member of the unrolled
    pair group — the extra pair axis of the blocked lb2 kernels
    (conservative: Mosaic may overlap the unrolled bodies' temporaries, so
    the model assumes they are all live). ``extra_bytes`` adds
    tile-independent residents (lb2's per-pair tables)."""
    tn2 = (tn2_copies + pair_copies * pair_group) * t * _r8(n) * _r128(n) * 4
    oh_nt = n * _r8(t) * _r128(n) * 4
    scan = n * _r8(t) * _r128(m) * 4
    ptg = t * _r8(n) * _r128(m) * 4
    chains = 2 * m * t * _r128(n) * 4
    return tn2 + oh_nt + scan + ptg + chains + extra_bytes


def _vmem_budget(backend: str = "tpu") -> int:
    return (_vmem_limit_bytes(backend) or 16 * 2**20) // 2


def _auto_tile(n: int, m: int, default: int, extra_bytes: int = 0,
               tn2_copies: int = 3, pair_copies: int = 0,
               pair_group: int = 1, backend: str = "tpu") -> int:
    """Shrink the batch tile until the kernel's modeled memory footprint
    fits the backend's budget (`_vmem_limit_bytes`).

    The reference rebuilds with bigger compile-time params for large
    instances (`Taillard.chpl:29-52`); here the same kernel covers 20-500
    jobs by trading batch-tile size for job count — the big matmuls keep
    T*n rows, so MXU utilization survives small T at large n. The model
    (``_model_bytes``) is checked against half the scoped budget, halving
    the tile until it fits (floor 8)."""
    budget = _vmem_budget(backend)
    tile = default
    while tile > 8 and _model_bytes(tile, n, m, extra_bytes, tn2_copies,
                                    pair_copies, pair_group) > budget:
        # Halve, then align down to the sublane quantum (a non-power-of-two
        # env override must not walk below the floor or mis-align the
        # (tile, n) BlockSpec).
        tile = max(8, (tile // 2) // 8 * 8)
    return tile


def _auto_tile_fits(n: int, m: int, default: int, extra_bytes: int = 0,
                    tn2_copies: int = 3, pair_copies: int = 0,
                    pair_group: int = 1, backend: str = "tpu") -> bool:
    """True iff the kernel fits the memory model even at the smallest tile
    — the routing gate: shapes that do not fit must stay on the jnp path
    instead of dying inside a Mosaic VMEM OOM."""
    tile = _auto_tile(n, m, default, extra_bytes, tn2_copies, pair_copies,
                      pair_group, backend)
    return _model_bytes(tile, n, m, extra_bytes, tn2_copies, pair_copies,
                        pair_group) <= _vmem_budget(backend)


def _lb2_static_extra(n: int, m: int, P: int) -> int:
    return (P * _r8(n) * _r128(n) + 3 * P * _r128(n) + 2 * P * _r128(m)) * 4


# The single source of truth for each kernel's VMEM-model parameters:
# (tile env knob, tile default, shared tn2_copies, needs per-pair extra,
# per-pair tn2 copies — charged once per unrolled pair-group member).
# Tile defaults: lb1 64 and lb1d 256 are MEASURED on the real v5e
# (docs/HW_VALIDATION.md; lb1 at 128 compiled >270s — Mosaic compile time
# grows superlinearly with tile). The lb2 family is not hardware-measured
# yet, and it is a strictly bigger kernel (190-pair fori_loop, per-pair
# tables), so its defaults start in the compile-time-safe class lb1 proved
# (64): a first-window probe that compiles beats a faster tile that times
# out. scripts/tile_sweep.py re-measures per (kernel, tile) so the
# defaults can be raised with data.
_KERNEL_MODEL = {
    "lb1": ("TTS_TILE_LB1", 64, 3, False, 0),
    "lb1d": ("TTS_TILE_LB1D", 256, 3, False, 0),
    "lb2": ("TTS_TILE_LB2", 64, 3, True, 5),
    "lb2self": ("TTS_TILE_LB2SELF", 64, 1, True, 5),
}


def _resolve_pair_group(kernel: str, n: int, P: int | None,
                        pair_group: int | None) -> int:
    """The pair-group unroll a kernel will compile with: an explicit value
    wins; otherwise the lb2-family kernels resolve the shared knob
    (`pfsp_device.lb2_kernel_pair_group` — lazy import, both modules load
    each other lazily so there is no cycle)."""
    if pair_group is not None:
        return pair_group
    if kernel in ("lb2", "lb2self") and P is not None:
        from . import pfsp_device

        return pfsp_device.lb2_kernel_pair_group(P, n)
    return 1


def _kernel_tile_args(kernel: str, n: int, m: int, P: int | None):
    env, default, copies, pairwise, pair_copies = _KERNEL_MODEL[kernel]
    extra = _lb2_static_extra(n, m, P) if pairwise else 0
    return _env_tile(env, default), extra, copies, pair_copies


def effective_tile(kernel: str, n: int, m: int, P: int | None = None,
                   batch: int | None = None,
                   pair_group: int | None = None,
                   backend: str = "tpu") -> int:
    """The batch tile a kernel will actually use for shape (n, m[, P]) —
    shared by the feasibility gates, the kernel callers, and
    scripts/tile_sweep.py so the model constants live in exactly one
    place."""
    default, extra, copies, pair_copies = _kernel_tile_args(kernel, n, m, P)
    pg = _resolve_pair_group(kernel, n, P, pair_group)
    tile = _auto_tile(n, m, default, extra_bytes=extra, tn2_copies=copies,
                      pair_copies=pair_copies, pair_group=pg,
                      backend=backend)
    return tile if batch is None else min(tile, batch)


def _kernel_feasible(kernel: str, n: int, m: int, P: int | None,
                     pair_group: int | None = None,
                     backend: str = "tpu") -> bool:
    default, extra, copies, pair_copies = _kernel_tile_args(kernel, n, m, P)
    pg = _resolve_pair_group(kernel, n, P, pair_group)
    return _auto_tile_fits(n, m, default, extra_bytes=extra,
                           tn2_copies=copies, pair_copies=pair_copies,
                           pair_group=pg, backend=backend)


def lb1_kernel_feasible(n: int, m: int, backend: str = "tpu") -> bool:
    return _kernel_feasible("lb1", n, m, None, backend=backend)


def lb2_kernel_feasible(n: int, m: int, P: int,
                        backend: str = "tpu") -> bool:
    return _kernel_feasible("lb2", n, m, P, backend=backend)


def lb2_self_kernel_feasible(n: int, m: int, P: int,
                             backend: str = "tpu") -> bool:
    return _kernel_feasible("lb2self", n, m, P, backend=backend)


# ---------------------------------------------------------------------------
# N-Queens safety labels
# ---------------------------------------------------------------------------


def _nqueens_tile_labels(board, depth, *, N: int, g: int):
    """Bool safety labels of one VMEM tile — the body of `_nqueens_kernel`,
    shared with the one-kernel cycle (`ops/megakernel.py`)."""
    qk = board[:, None, :]  # candidate rows (T, 1, N)
    bi = board[:, :, None]  # placed queens  (T, N, 1)
    i = jax.lax.broadcasted_iota(jnp.int32, (1, N, 1), 1)
    d = depth[:, None, None] - i  # (T, N, 1)
    placed = i < depth[:, None, None]

    def one_round(_, safe):
        clash = (bi == qk - d) | (bi == qk + d)
        return safe & ~jnp.any(clash & placed, axis=1)

    safe = one_round(0, jnp.ones(board.shape, dtype=bool))
    if g > 1:
        safe = jax.lax.fori_loop(0, g - 1, one_round, safe)
    k = jax.lax.broadcasted_iota(jnp.int32, board.shape, 1)
    return safe & (k >= depth[:, None])


def _nqueens_kernel(board_ref, depth_ref, out_ref, *, N: int, g: int):
    """labels[b, k] = 1 iff board[b, k] placed at column depth_b clashes with
    no placed queen on either diagonal (`nqueens_gpu_chpl.chpl:99-123`)."""
    board = board_ref[:].astype(jnp.int32)  # (T, N)
    depth = depth_ref[:, 0].astype(jnp.int32)  # (T,)
    out_ref[:] = _nqueens_tile_labels(board, depth, N=N, g=g).astype(jnp.uint8)


@lru_cache(maxsize=None)
def _nqueens_call(N: int, g: int, B: int, tile: int, interpret: bool,
                  backend: str = "tpu"):
    kernel = partial(_nqueens_kernel, N=N, g=g)
    grid = (B // tile,)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B, N), jnp.uint8),
        grid=grid,
        in_specs=[
            _bs((tile, N), lambda i: (i, 0), backend=backend),
            _bs((tile, 1), lambda i: (i, 0), backend=backend),
        ],
        out_specs=_bs((tile, N), lambda i: (i, 0), backend=backend),
        compiler_params=_compiler_params(backend=backend),
        interpret=interpret,
    )


def nqueens_labels(board, depth, N: int, g: int = 1,
                   interpret: bool | None = None, backend: str = "tpu"):
    """(B, N) uint8 labels; same contract as `nqueens_device.make_core`."""
    interpret = _default_interpret(backend) if interpret is None else interpret
    B = board.shape[0]
    tile = min(512, B)
    Bp = _round_up(B, tile)
    if Bp != B:
        board = jnp.pad(board, ((0, Bp - B), (0, 0)))
        depth = jnp.pad(depth, ((0, Bp - B),))
    out = _nqueens_call(N, g, Bp, tile, interpret, backend)(
        board.astype(jnp.int32), depth.astype(jnp.int32)[:, None]
    )
    return out[:B]


# ---------------------------------------------------------------------------
# PFSP lb1 child bounds
# ---------------------------------------------------------------------------


def _hp_dot(a, b, bf16: bool = False):
    """Exact MXU matmul. ``bf16=False``: f32 at HIGHEST precision (the
    default single bf16 pass rounds ints > 256). ``bf16=True`` (set when
    every operand value < 2^8 — one-hot/0-1 masks and Taillard times): a
    single bf16 x bf16 -> f32 pass, bit-exact and ~3x cheaper."""
    if bf16:
        a = a.astype(jnp.bfloat16)
        b = b.astype(jnp.bfloat16)
        precision = None
    else:
        precision = jax.lax.Precision.HIGHEST
    return jax.lax.dot_general(
        a, b,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=precision,
    )


def _front_scan(prmu, limit1, ptm, scan_ref, n: int, m: int,
                bf16: bool = False):
    """The masked schedule_front scan (`c_bound_simple.c:51-69`) over a
    (T, n) permutation tile — shared by `_tile_parent_state` and the
    staged self-bound kernel.  Returns the (T, m) int32 front.

    ``scan_ref`` is an (n, T, m) VMEM scratch: Mosaic cannot dynamic_slice
    a *value* with the traced loop index, but it can dynamically index a
    Ref on its leading axis — so the scan's per-position processing times
    are staged there (position-major: the same one-hot trick as the child
    gather, rows swapped so the reshape lands (n, T, m) without a 3-D
    transpose) and the fori_loop reads ``scan_ref[i]``.

    ``scan_ref=None`` is the GPU (Triton) lowering: Triton pallas has no
    scratch memory and cannot lower dynamic indexing of register values
    either, so the scan unrolls STATICALLY over the n positions — static
    slices of the position-major value, same math, n-way larger program
    (n <= 100 by the lb2 routing gate, so the unroll stays bounded)."""
    T = prmu.shape[0]
    iota_nT = jax.lax.broadcasted_iota(jnp.int32, (n, T, n), 2)
    oh_nT = (iota_nT == prmu.T[:, :, None]).astype(jnp.float32)
    pts = (
        _hp_dot(oh_nT.reshape(n * T, n), ptm, bf16)
        .reshape(n, T, m).astype(jnp.int32)
    )

    def step(i, pt, front):
        cols = [front[:, 0] + pt[:, 0]]
        for j in range(1, m):
            cols.append(jnp.maximum(cols[-1], front[:, j]) + pt[:, j])
        newf = jnp.stack(cols, axis=-1)
        return jnp.where((i <= limit1)[:, None], newf, front)

    front0 = jnp.zeros((T, m), jnp.int32)
    if scan_ref is None:
        front = front0
        for i in range(n):  # static unroll — no scratch ref on Triton
            front = step(i, pts[i], front)
        return front
    scan_ref[...] = pts
    return jax.lax.fori_loop(
        0, n, lambda i, f: step(i, scan_ref[i], f), front0
    )


def _tile_parent_state(prmu, limit1, ptm, heads, scan_ref, n: int, m: int,
                       bf16: bool = False):
    """Shared tile prologue of the PFSP bound kernels: the one-hot MXU gather
    of per-position processing times, the masked schedule_front scan
    (`_front_scan` — staged through ``scan_ref`` on TPU, statically
    unrolled when ``scan_ref`` is None on the Triton lowering), and the
    per-child add_forward fronts.

    Returns (onehot, ptg, front, child_front_cols) with child_front_cols a
    list of m (T, n) columns.
    """
    T = prmu.shape[0]
    jobs_iota = jax.lax.broadcasted_iota(jnp.int32, (T, n, n), 2)
    onehot = (jobs_iota == prmu[:, :, None]).astype(jnp.float32)
    ptg = (
        _hp_dot(onehot.reshape(T * n, n), ptm, bf16)
        .reshape(T, n, m).astype(jnp.int32)
    )

    front = _front_scan(prmu, limit1, ptm, scan_ref, n, m, bf16)
    front = jnp.where((limit1 == -1)[:, None], heads, front)

    # Remaining work per machine over the open positions (sum_unscheduled,
    # `c_bound_simple.c:108-124`).
    unsched = (
        jax.lax.broadcasted_iota(jnp.int32, (T, n), 1) >= (limit1 + 1)[:, None]
    ).astype(jnp.int32)
    remain = jnp.sum(ptg * unsched[:, :, None], axis=1)  # (T, m)

    # 2-D static lane slices only: the (T, 1, m) reshape-then-extract form
    # (front[:, None, :][..., j]) sends Mosaic down a pathological relayout
    # path — ~17x slower compiles per chain and an XLA `array.h` check crash
    # in larger compositions (measured on v5e, jax 0.9).
    child_front = [front[:, 0:1] + ptg[..., 0]]
    for j in range(1, m):
        child_front.append(
            jnp.maximum(child_front[-1], front[:, j:j + 1]) + ptg[..., j]
        )
    return onehot, ptg, front, remain, child_front


def _lb1_tile_lb(prmu, limit1, ptm, heads, tails, scan_ref,
                 *, n: int, m: int, bf16: bool = False):
    """(T, n) int32 lb1 bound of every child in the tile — the body of
    `_lb1_kernel`, shared with the one-kernel cycle (`ops/megakernel.py`)."""
    _, ptg, _, remain, child_front = _tile_parent_state(
        prmu, limit1, ptm, heads, scan_ref, n, m, bf16
    )

    # Child k: machine bound chain, unrolled over m. Per-machine remain as a
    # 2-D slice (see the relayout note in _tile_parent_state).
    tmp0 = child_front[0] + (remain[:, 0:1] - ptg[..., 0])
    lb = tmp0 + tails[0, 0]
    for i in range(1, m):
        tmp1 = jnp.maximum(
            tmp0, child_front[i] + (remain[:, i:i + 1] - ptg[..., i])
        )
        lb = jnp.maximum(lb, tmp1 + tails[0, i])
        tmp0 = tmp1
    return lb


def _lb1_kernel(
    prmu_ref, limit1_ref, ptm_ref, heads_ref, tails_ref, out_ref, scan_ref,
    *, n: int, m: int, bf16: bool = False
):
    """Full lb1 bound of every child of every parent in the tile.

    Math identical to `ops/pfsp_device._lb1_chunk` (itself the batched form
    of `c_bound_simple.c:51-141` + one incremental `add_forward` per child);
    here the whole chain runs on one VMEM tile.
    """
    prmu = prmu_ref[:].astype(jnp.int32)  # (T, n)
    limit1 = limit1_ref[:, 0].astype(jnp.int32)  # (T,)
    ptm = ptm_ref[:].astype(jnp.float32)  # (n, m) job-major
    out_ref[:] = _lb1_tile_lb(
        prmu, limit1, ptm, heads_ref[:], tails_ref[:], scan_ref,
        n=n, m=m, bf16=bf16,
    )


@lru_cache(maxsize=None)
def _lb1_family_call(kernel_fn, n: int, m: int, B: int, tile: int,
                     interpret: bool, bf16: bool = False,
                     backend: str = "tpu"):
    """Shared pallas_call factory for the lb1-shaped kernels (lb1 / lb1_d):
    same operand layout — (prmu, limit1, ptm, heads, tails) -> (B, n) —
    same tiling, same scan scratch (TPU; the gpu flavor passes a
    scratch-free kernel_fn and declares none — `_front_scan` unrolls)."""
    kernel = partial(kernel_fn, n=n, m=m, bf16=bf16)
    grid = (B // tile,)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B, n), jnp.int32),
        grid=grid,
        in_specs=[
            _bs((tile, n), lambda i: (i, 0), backend=backend),
            _bs((tile, 1), lambda i: (i, 0), backend=backend),
            _bs((n, m), lambda i: (0, 0), backend=backend),
            _bs((1, m), lambda i: (0, 0), backend=backend),
            _bs((1, m), lambda i: (0, 0), backend=backend),
        ],
        out_specs=_bs((tile, n), lambda i: (i, 0), backend=backend),
        scratch_shapes=_scratch(backend, pltpu.VMEM((n, tile, m), jnp.int32)),
        compiler_params=_compiler_params(backend=backend),
        interpret=interpret,
    )


def _lb1_family_bounds(
    kernel_fn, prmu, limit1, ptm_t, min_heads, min_tails, interpret: bool,
    bf16: bool = False, kernel_name: str = "lb1", backend: str = "tpu",
):
    B, n = prmu.shape
    m = ptm_t.shape[1]
    if backend == "gpu":
        kernel_fn = _GPU_KERNELS[kernel_fn]
    # Per-kernel tile defaults are measured, not uniform (_KERNEL_MODEL):
    # Mosaic compile time for the lb1 kernel grows superlinearly with the
    # batch tile (64 -> ~16s, 128 -> >270s on v5e), while lb1_d compiles at
    # 256 in ~50s. Large instances then shrink the tile further until the
    # VMEM model fits.
    tile = effective_tile(kernel_name, n, m, batch=B, backend=backend)
    Bp = _round_up(B, tile)
    if Bp != B:
        prmu = jnp.pad(prmu, ((0, Bp - B), (0, 0)))
        limit1 = jnp.pad(limit1, ((0, Bp - B),))
    out = _lb1_family_call(kernel_fn, n, m, Bp, tile, interpret, bf16,
                           backend)(
        prmu.astype(jnp.int32),
        limit1.astype(jnp.int32)[:, None],
        ptm_t.astype(jnp.int32),
        min_heads.astype(jnp.int32)[None, :],
        min_tails.astype(jnp.int32)[None, :],
    )
    return out[:B]


def _lb1_d_kernel(
    prmu_ref, limit1_ref, ptm_ref, heads_ref, tails_ref, out_ref, scan_ref,
    *, n: int, m: int, bf16: bool = False
):
    """lb1_d bounds of every child in the tile: the O(m)-per-child weak bound
    from the parent's front/remain (`add_front_and_bound`,
    `c_bound_simple.c:213-244`; device: `evaluate.cu:51-71`). Math identical
    to `ops/pfsp_device._lb1_d_chunk`; shares the VMEM tile prologue."""
    prmu = prmu_ref[:].astype(jnp.int32)  # (T, n)
    limit1 = limit1_ref[:, 0].astype(jnp.int32)  # (T,)
    ptm = ptm_ref[:].astype(jnp.float32)  # (n, m)
    T = prmu.shape[0]
    _, ptg, front, remain, _ = _tile_parent_state(
        prmu, limit1, ptm, heads_ref[:], scan_ref, n, m, bf16
    )
    back = tails_ref[:]  # (1, m)
    # 2-D slices throughout (see the relayout note in _tile_parent_state).
    lb = front[:, 0:1] + remain[:, 0:1] + back[0, 0]  # (T, 1) -> (T, n)
    tmp0 = front[:, 0:1] + ptg[..., 0]  # (T, n)
    for i in range(1, m):
        tmp1 = jnp.maximum(tmp0, front[:, i:i + 1])
        lb = jnp.maximum(lb, tmp1 + remain[:, i:i + 1] + back[0, i])
        tmp0 = tmp1 + ptg[..., i]
    out_ref[:] = jnp.broadcast_to(lb, (T, n))


def _lb1_kernel_gpu(prmu_ref, limit1_ref, ptm_ref, heads_ref, tails_ref,
                    out_ref, *, n: int, m: int, bf16: bool = False):
    """The lb1 kernel without its scan scratch — the Triton flavor
    (`_front_scan` unrolls statically where the TPU kernel staged)."""
    _lb1_kernel(prmu_ref, limit1_ref, ptm_ref, heads_ref, tails_ref,
                out_ref, None, n=n, m=m, bf16=bf16)


def _lb1_d_kernel_gpu(prmu_ref, limit1_ref, ptm_ref, heads_ref, tails_ref,
                      out_ref, *, n: int, m: int, bf16: bool = False):
    _lb1_d_kernel(prmu_ref, limit1_ref, ptm_ref, heads_ref, tails_ref,
                  out_ref, None, n=n, m=m, bf16=bf16)


#: TPU kernel body -> its scratch-free Triton twin (`_lb1_family_bounds`).
_GPU_KERNELS = {
    _lb1_kernel: _lb1_kernel_gpu,
    _lb1_d_kernel: _lb1_d_kernel_gpu,
}


def pfsp_lb1_d_bounds(
    prmu, limit1, ptm_t, min_heads, min_tails, interpret: bool | None = None,
    bf16: bool = False, backend: str = "tpu",
):
    """(B, n) int32 lb1_d child bounds; same contract as `_lb1_d_chunk`."""
    interpret = _default_interpret(backend) if interpret is None else interpret
    return _lb1_family_bounds(
        _lb1_d_kernel, prmu, limit1, ptm_t, min_heads, min_tails, interpret,
        bf16, kernel_name="lb1d", backend=backend,
    )


def _lb2_tile_lb(
    prmu, limit1, ptm, heads,
    p0_ref, p1_ref, lag_ref, t0_ref, t1_ref, msel0_ref, msel1_ref, jorder_ref,
    scan_ref, *, n: int, m: int, P: int, pg: int = 1, bf16: bool = False,
):
    """(T, n) f32 lb2 bound of every child in the tile — the body of
    `_lb2_kernel`, shared with the one-kernel cycle (`ops/megakernel.py`).
    Mixed value/Ref signature: the per-pair tables stay Refs because the
    pair loop indexes them dynamically on a non-tiled leading axis."""
    T = prmu.shape[0]
    hp = _hp_dot
    onehot, _, _, _, cf = _tile_parent_state(
        prmu, limit1, ptm, heads, scan_ref, n, m, bf16
    )
    child_front = jnp.stack(cf, axis=-1).astype(jnp.float32)  # (T, n, m)

    # Free-job flags by job id: parent's open positions minus the child job.
    slot_iota = jax.lax.broadcasted_iota(jnp.int32, (T, n), 1)
    unsched = (slot_iota >= (limit1 + 1)[:, None]).astype(jnp.float32)  # (T, n)
    u_parent = jnp.sum(onehot * unsched[:, :, None], axis=1)  # (T, n) by job
    u_child = u_parent[:, None, :] - onehot  # (T, k, job)

    neg = jnp.float32(-(2.0**30))
    # Prefix/suffix sums along the ordered-slot axis as triangular matmuls
    # (MXU work; Mosaic has no native lane-axis cumsum).
    ri = jax.lax.broadcasted_iota(jnp.int32, (n, n), 0)
    ci = jax.lax.broadcasted_iota(jnp.int32, (n, n), 1)
    tri_incl = (ri <= ci).astype(jnp.float32)  # prefix: sum_{s<=t}
    tri_suf = (ri >= ci).astype(jnp.float32)  # suffix: sum_{s>=t}

    def pair_body(q, lb):
        jord = jorder_ref[q]  # (n, n) slot-order one-hot
        # u_o[b, k, t] = u_child[b, k, sched_q[t]]
        u_o = hp(u_child.reshape(T * n, n), jord.T, bf16).reshape(T, n, n)
        # Per-pair tables are (P, 1, n): the dynamic q index must hit a
        # non-tiled leading axis (a (P, n) ref would put it on the sublane
        # dim, which Mosaic cannot index dynamically).
        p0 = p0_ref[q][0].astype(jnp.float32)  # (n,)
        p1 = p1_ref[q][0].astype(jnp.float32)
        lag = lag_ref[q][0].astype(jnp.float32)
        mp0 = u_o * p0[None, None, :]
        mp1 = u_o * p1[None, None, :]
        # Machine selection as a one-hot contraction on the lane axis —
        # Mosaic cannot dynamic_slice a VMEM *value* along a lane dim, but a
        # masked reduction against the precomputed (P, m) selector rows is
        # exact (0/1 mask) and pure VPU work.
        s0 = msel0_ref[q][0].astype(jnp.float32)  # (m,)
        s1 = msel1_ref[q][0].astype(jnp.float32)
        tmp0_0 = jnp.sum(child_front * s0[None, None, :], axis=-1)  # (T, n)
        tmp1_0 = jnp.sum(child_front * s1[None, None, :], axis=-1)
        cum0 = hp(mp0.reshape(T * n, n), tri_incl, bf16).reshape(T, n, n)
        suf1 = hp(mp1.reshape(T * n, n), tri_suf, bf16).reshape(T, n, n)
        t0 = tmp0_0[:, :, None] + cum0
        a = jnp.where(u_o > 0, t0 + lag[None, None, :] + suf1, neg)
        tmp1 = jnp.maximum(tmp1_0 + jnp.sum(mp1, axis=-1), jnp.max(a, axis=-1))
        tmp0 = tmp0_0 + jnp.sum(mp0, axis=-1)
        pair_lb = jnp.maximum(
            tmp1 + t1_ref[q].astype(jnp.float32),
            tmp0 + t0_ref[q].astype(jnp.float32),
        )
        return jnp.maximum(lb, pair_lb)

    lb0 = jnp.zeros((T, n), jnp.float32)
    if pg > 1:
        def group_body(g, lb):
            q0 = g * pg
            for j in range(pg):  # static unroll within the group
                lb = pair_body(q0 + j, lb)
            return lb

        lb = jax.lax.fori_loop(0, P // pg, group_body, lb0)
    else:
        lb = jax.lax.fori_loop(0, P, pair_body, lb0)
    return lb


def _lb2_kernel(
    prmu_ref, limit1_ref, ptm_ref, heads_ref,
    p0_ref, p1_ref, lag_ref, t0_ref, t1_ref, msel0_ref, msel1_ref, jorder_ref,
    out_ref, scan_ref, *, n: int, m: int, P: int, pg: int = 1,
    bf16: bool = False,
):
    """Full lb2 (two-machine Johnson) bound of every child in the tile.

    Math identical to `ops/pfsp_device._lb2_chunk` (the closed-form max-plus
    scan of `c_bound_johnson.c:190-234`, early exit dropped — see that
    module's docstring). The decisive difference from the jnp path: the
    whole pair loop runs against VMEM-resident tile state (child fronts,
    free-job flags, the Johnson-ordered tables), so the ~P x (B, n, n)
    intermediates never touch HBM.

    ``pg``: pair-group unroll — the fori_loop runs over P/pg pair GROUPS
    (caller pads P to a multiple) with pg statically-unrolled pair bodies
    per iteration, giving the VPU/MXU pg independent chains to overlap
    instead of one serialized pair per loop step (the pair-axis batching
    of the blocked jnp path, expressed as unrolling here — the VMEM model
    charges the per-pair live values once per group member).
    """
    prmu = prmu_ref[:].astype(jnp.int32)  # (T, n)
    limit1 = limit1_ref[:, 0].astype(jnp.int32)  # (T,)
    ptm = ptm_ref[:].astype(jnp.float32)  # (n, m)
    lb = _lb2_tile_lb(
        prmu, limit1, ptm, heads_ref[:],
        p0_ref, p1_ref, lag_ref, t0_ref, t1_ref, msel0_ref, msel1_ref,
        jorder_ref, scan_ref, n=n, m=m, P=P, pg=pg, bf16=bf16,
    )
    out_ref[:] = lb.astype(jnp.int32)


def _lb2_kernel_gpu(
    prmu_ref, limit1_ref, ptm_ref, heads_ref,
    p0_ref, p1_ref, lag_ref, t0_ref, t1_ref, msel0_ref, msel1_ref, jorder_ref,
    out_ref, *, n: int, m: int, P: int, pg: int = 1, bf16: bool = False,
):
    """The lb2 kernel without its scan scratch — the Triton flavor.  The
    pair loop's dynamic leading-axis ref reads stay: a Triton ref is a
    pointer, and dynamic pointer offsets are the one dynamic indexing form
    the lowering is built on."""
    _lb2_kernel(
        prmu_ref, limit1_ref, ptm_ref, heads_ref,
        p0_ref, p1_ref, lag_ref, t0_ref, t1_ref, msel0_ref, msel1_ref,
        jorder_ref, out_ref, None, n=n, m=m, P=P, pg=pg, bf16=bf16,
    )


@lru_cache(maxsize=None)
def _lb2_call(n: int, m: int, P: int, B: int, tile: int, interpret: bool,
              bf16: bool = False, pg: int = 1, backend: str = "tpu"):
    kernel_fn = _lb2_kernel_gpu if backend == "gpu" else _lb2_kernel
    kernel = partial(kernel_fn, n=n, m=m, P=P, pg=pg, bf16=bf16)
    grid = (B // tile,)
    full = lambda i: (0, 0)
    full3 = lambda i: (0, 0, 0)
    bs = partial(_bs, backend=backend)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B, n), jnp.int32),
        grid=grid,
        in_specs=[
            bs((tile, n), lambda i: (i, 0)),
            bs((tile, 1), lambda i: (i, 0)),
            bs((n, m), full),
            bs((1, m), full),
            # Per-pair tables as (P, 1, n)/(P, 1, m): leading-axis dynamic
            # ref reads (see pair_body).
            bs((P, 1, n), full3),
            bs((P, 1, n), full3),
            bs((P, 1, n), full3),
            # Per-pair scalars read with a dynamic index: SMEM (Mosaic cannot
            # dynamically index 1-D VMEM along the lane dim; Triton has no
            # memory spaces — the shim drops the pin there).
            bs((P,), lambda i: (0,), space="smem"),
            bs((P,), lambda i: (0,), space="smem"),
            # (P, 1, m) one-hot machine selectors (rows read per pair).
            bs((P, 1, m), full3),
            bs((P, 1, m), full3),
            bs((P, n, n), full3),
        ],
        out_specs=bs((tile, n), lambda i: (i, 0)),
        scratch_shapes=_scratch(backend, pltpu.VMEM((n, tile, m), jnp.int32)),
        compiler_params=_compiler_params(backend=backend),
        interpret=interpret,
    )


def _eager_context() -> bool:
    """True outside any jax trace — the only context where device-cached
    table uploads are safe to build (a trace would capture tracers)."""
    try:
        from jax._src import core as _core

        return bool(_core.trace_state_clean())
    except Exception:  # API moved: degrade to numpy constants (correct,
        return False   # just re-transfers on eager calls)


def pfsp_lb2_bounds(prmu, limit1, tables, interpret: bool | None = None,
                    bf16: bool | None = None,
                    pair_group: int | None = None, backend: str = "tpu"):
    """(B, n) int32 lb2 child bounds; same contract as `_lb2_chunk`.
    ``pair_group``: pair-group unroll per grid step (None resolves the
    shared TTS_LB2_PAIRBLOCK knob); the pair tables are padded to a
    multiple of it with copies of pair 0 (max is idempotent)."""
    interpret = _default_interpret(backend) if interpret is None else interpret
    if bf16 is None:
        bf16 = getattr(tables, "exact_bf16", False)
    B, n = prmu.shape
    m = tables.ptm_t.shape[1]
    P = tables.pairs.shape[0]
    pg = _resolve_pair_group("lb2", n, P, pair_group)
    # Tile-independent residents (per-pair tables) + the shared + per-pair
    # (T, n, n)-class live f32 pair-loop values — see _KERNEL_MODEL["lb2"].
    tile = effective_tile("lb2", n, m, P, batch=B, pair_group=pg,
                          backend=backend)
    Bp = _round_up(B, tile)
    if Bp != B:
        prmu = jnp.pad(prmu, ((0, Bp - B), (0, 0)))
        limit1 = jnp.pad(limit1, ((0, Bp - B),))
    # Eager calls reuse once-uploaded device tables; traced calls bake the
    # numpy tables as executable constants (and must NOT touch the device
    # cache — building it under a trace would capture tracers). Both are
    # padded to a pair-group multiple (johnson_ordered_mp's policy).
    ordered = (tables.johnson_ordered_device(pg) if _eager_context()
               else tables.johnson_ordered_mp(pg))
    Pp = ordered.lag_o.shape[0]
    out = _lb2_call(n, m, Pp, Bp, tile, interpret, bf16, pg, backend)(
        prmu.astype(jnp.int32),
        limit1.astype(jnp.int32)[:, None],
        tables.ptm_t,
        tables.min_heads[None, :],
        ordered.p0_o[:, None, :],
        ordered.p1_o[:, None, :],
        ordered.lag_o[:, None, :],
        ordered.tails0,
        ordered.tails1,
        ordered.msel0[:, None, :],
        ordered.msel1[:, None, :],
        ordered.jorder,
    )
    return out[:B]


def pfsp_lb1_bounds(
    prmu, limit1, ptm_t, min_heads, min_tails, interpret: bool | None = None,
    bf16: bool = False, backend: str = "tpu",
):
    """(B, n) int32 lb1 child bounds; same contract as `_lb1_chunk`."""
    interpret = _default_interpret(backend) if interpret is None else interpret
    return _lb1_family_bounds(
        _lb1_kernel, prmu, limit1, ptm_t, min_heads, min_tails, interpret,
        bf16, backend=backend,
    )


# ---------------------------------------------------------------------------
# PFSP lb2 self bound (staged evaluation)
# ---------------------------------------------------------------------------


def _lb2_self_kernel(
    prmu_ref, limit1_ref, nact_ref, ptm_ref,
    p0_ref, p1_ref, lag_ref, t0_ref, t1_ref, msel0_ref, msel1_ref, jorder_ref,
    out_ref, scan_ref, *, n: int, m: int, P: int, tile: int, pg: int = 1,
    bf16: bool = False,
):
    """Johnson bound of each ROW's own partial schedule (the staged
    evaluator's compacted child nodes) — `_lb2_kernel` with the
    child-expansion axis dropped, including its ``pg`` pair-group
    unrolling (fori_loop over P/pg groups, pg unrolled pair bodies each).
    Tiles whose rows are all beyond ``n_active`` skip the entire body:
    this is where the incumbent-driven work reduction lands (the
    reference's per-thread early exit, `evaluate.cu:73-91`, becomes
    whole-tile skipping on the sequential TPU grid)."""

    @pl.when(pl.program_id(0) * tile < nact_ref[0])
    def _active():
        prmu = prmu_ref[:].astype(jnp.int32)  # (T, n)
        limit1 = limit1_ref[:, 0].astype(jnp.int32)  # (T,) — always >= 0
        ptm = ptm_ref[:].astype(jnp.float32)  # (n, m)
        T = prmu.shape[0]
        hp = _hp_dot

        # schedule_front via the position-major scan staging (`_front_scan`
        # — scratch-staged on TPU, statically unrolled when scan_ref is
        # None on the Triton lowering).
        front = _front_scan(prmu, limit1, ptm, scan_ref, n, m,
                            bf16).astype(jnp.float32)

        # Free flags by job id.
        jobs_iota = jax.lax.broadcasted_iota(jnp.int32, (T, n, n), 2)
        onehot = (jobs_iota == prmu[:, :, None]).astype(jnp.float32)
        slot_iota = jax.lax.broadcasted_iota(jnp.int32, (T, n), 1)
        unsched = (slot_iota >= (limit1 + 1)[:, None]).astype(jnp.float32)
        u = jnp.sum(onehot * unsched[:, :, None], axis=1)  # (T, job)

        neg = jnp.float32(-(2.0**30))
        ri = jax.lax.broadcasted_iota(jnp.int32, (n, n), 0)
        ci = jax.lax.broadcasted_iota(jnp.int32, (n, n), 1)
        tri_incl = (ri <= ci).astype(jnp.float32)
        tri_suf = (ri >= ci).astype(jnp.float32)

        def pair_body(q, lb):
            jord = jorder_ref[q]  # (n, n)
            u_o = hp(u, jord.T, bf16)  # (T, n) ordered free flags
            p0 = p0_ref[q][0].astype(jnp.float32)  # (n,)
            p1 = p1_ref[q][0].astype(jnp.float32)
            lag = lag_ref[q][0].astype(jnp.float32)
            s0 = msel0_ref[q][0].astype(jnp.float32)  # (m,)
            s1 = msel1_ref[q][0].astype(jnp.float32)
            tmp0_0 = jnp.sum(front * s0[None, :], axis=-1, keepdims=True)
            tmp1_0 = jnp.sum(front * s1[None, :], axis=-1, keepdims=True)
            mp0 = u_o * p0[None, :]
            mp1 = u_o * p1[None, :]
            cum0 = hp(mp0, tri_incl, bf16)
            suf1 = hp(mp1, tri_suf, bf16)
            a = jnp.where(u_o > 0, tmp0_0 + cum0 + lag[None, :] + suf1, neg)
            tmp1 = jnp.maximum(
                tmp1_0 + jnp.sum(mp1, axis=-1, keepdims=True),
                jnp.max(a, axis=-1, keepdims=True),
            )
            tmp0 = tmp0_0 + jnp.sum(mp0, axis=-1, keepdims=True)
            pair_lb = jnp.maximum(
                tmp1 + t1_ref[q].astype(jnp.float32),
                tmp0 + t0_ref[q].astype(jnp.float32),
            )
            return jnp.maximum(lb, pair_lb)

        lb0 = jnp.zeros((T, 1), jnp.float32)
        if pg > 1:
            def group_body(g, lb):
                q0 = g * pg
                for j in range(pg):  # static unroll within the group
                    lb = pair_body(q0 + j, lb)
                return lb

            lb = jax.lax.fori_loop(0, P // pg, group_body, lb0)
        else:
            lb = jax.lax.fori_loop(0, P, pair_body, lb0)
        out_ref[:] = lb.astype(jnp.int32)


def _lb2_self_kernel_gpu(
    prmu_ref, limit1_ref, nact_ref, ptm_ref,
    p0_ref, p1_ref, lag_ref, t0_ref, t1_ref, msel0_ref, msel1_ref, jorder_ref,
    out_ref, *, n: int, m: int, P: int, tile: int, pg: int = 1,
    bf16: bool = False,
):
    """The staged self-bound kernel without its scan scratch — the Triton
    flavor (tile skipping via `pl.when` is backend-neutral)."""
    _lb2_self_kernel(
        prmu_ref, limit1_ref, nact_ref, ptm_ref,
        p0_ref, p1_ref, lag_ref, t0_ref, t1_ref, msel0_ref, msel1_ref,
        jorder_ref, out_ref, None, n=n, m=m, P=P, tile=tile, pg=pg,
        bf16=bf16,
    )


@lru_cache(maxsize=None)
def _lb2_self_call(n: int, m: int, P: int, R: int, tile: int, interpret: bool,
                   bf16: bool = False, pg: int = 1, backend: str = "tpu"):
    kernel_fn = _lb2_self_kernel_gpu if backend == "gpu" else _lb2_self_kernel
    kernel = partial(kernel_fn, n=n, m=m, P=P, tile=tile, pg=pg, bf16=bf16)
    grid = (R // tile,)
    full = lambda i: (0, 0)
    full3 = lambda i: (0, 0, 0)
    bs = partial(_bs, backend=backend)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((R, 1), jnp.int32),
        grid=grid,
        in_specs=[
            bs((tile, n), lambda i: (i, 0)),
            bs((tile, 1), lambda i: (i, 0)),
            bs((1,), lambda i: (0,), space="smem"),
            bs((n, m), full),
            bs((P, 1, n), full3),
            bs((P, 1, n), full3),
            bs((P, 1, n), full3),
            bs((P,), lambda i: (0,), space="smem"),
            bs((P,), lambda i: (0,), space="smem"),
            bs((P, 1, m), full3),
            bs((P, 1, m), full3),
            bs((P, n, n), full3),
        ],
        out_specs=bs((tile, 1), lambda i: (i, 0)),
        scratch_shapes=_scratch(backend, pltpu.VMEM((n, tile, m), jnp.int32)),
        compiler_params=_compiler_params(backend=backend),
        interpret=interpret,
    )


_ORDERED_FIELDS = ("p0_o", "p1_o", "lag_o", "tails0", "tails1",
                   "msel0", "msel1", "jorder")


class _PaddedOrdered:
    """Ordered tables padded to a pair-group multiple with copies of pair 0
    (max over pairs is idempotent). Works on traced fields — the mp-sharded
    path passes dynamic slices — and the pads are (reps, ...) slivers."""

    def __init__(self, ordered, reps: int):
        for f in _ORDERED_FIELDS:
            arr = jnp.asarray(getattr(ordered, f))
            setattr(self, f, jnp.concatenate(
                [arr, jnp.repeat(arr[:1], reps, axis=0)], axis=0
            ))


def pfsp_lb2_self_bounds_tables(prmu, limit1, n_active, ptm_t, ordered,
                                interpret: bool | None = None,
                                bf16: bool = False,
                                pair_group: int | None = None,
                                backend: str = "tpu"):
    """`pfsp_lb2_self_bounds` over EXPLICIT ordered tables (possibly traced
    slices of the full pair set — the mp-sharded staged path slices each
    shard's contiguous pair block before the call; pallas_call takes traced
    operands like any other op). ``ordered`` needs p0_o/p1_o/lag_o (P, n),
    tails0/tails1 (P,), msel0/msel1 (P, m), jorder (P, n, n)."""
    interpret = _default_interpret(backend) if interpret is None else interpret
    R, n = prmu.shape
    m = ptm_t.shape[1]
    P = ordered.lag_o.shape[0]
    pg = _resolve_pair_group("lb2self", n, P, pair_group)
    reps = _round_up(P, pg) - P
    if reps:
        ordered = _PaddedOrdered(ordered, reps)
    tile = effective_tile("lb2self", n, m, P, batch=R, pair_group=pg,
                          backend=backend)
    Rp = _round_up(R, tile)
    if Rp != R:
        prmu = jnp.pad(prmu, ((0, Rp - R), (0, 0)))
        limit1 = jnp.pad(limit1, ((0, Rp - R),))
    out = _lb2_self_call(n, m, P + reps, Rp, tile, interpret, bf16, pg,
                         backend)(
        prmu.astype(jnp.int32),
        limit1.astype(jnp.int32)[:, None],
        jnp.asarray(n_active, dtype=jnp.int32).reshape(1),
        ptm_t,
        ordered.p0_o[:, None, :],
        ordered.p1_o[:, None, :],
        ordered.lag_o[:, None, :],
        ordered.tails0,
        ordered.tails1,
        ordered.msel0[:, None, :],
        ordered.msel1[:, None, :],
        ordered.jorder,
    )
    return out[:R, 0]


def pfsp_lb2_self_bounds(prmu, limit1, n_active, tables,
                         interpret: bool | None = None,
                         bf16: bool | None = None,
                         pair_group: int | None = None,
                         backend: str = "tpu"):
    """(R,) int32 self lb2 bounds; rows >= n_active are garbage (their
    tiles are skipped entirely). Same contract as `_lb2_self_chunk` on the
    first n_active rows."""
    if bf16 is None:
        bf16 = getattr(tables, "exact_bf16", False)
    n = prmu.shape[-1]
    pg = _resolve_pair_group("lb2self", n, tables.pairs.shape[0], pair_group)
    # Tables pre-padded to the pair-group multiple: the cached device copy
    # (eager) / host numpy (traced) avoid a per-call concat.
    ordered = (tables.johnson_ordered_device(pg) if _eager_context()
               else tables.johnson_ordered_mp(pg))
    return pfsp_lb2_self_bounds_tables(
        prmu, limit1, n_active, tables.ptm_t, ordered, interpret, bf16,
        pair_group=pg, backend=backend,
    )
