"""One-kernel resident cycle: a grid-tiled, streamed Pallas megakernel.

The resident engine's inner loop (pop -> bound -> prune -> compact -> push,
the offload cycle of `pfsp_gpu_chpl.chpl:276-298`) normally compiles as a
chain of XLA ops inside the `lax.while_loop`: each op boundary is a
dispatch, and every intermediate (the child cube, the keep plane, the
compacted rows) round-trips through HBM.  At the headline shapes (M around
1024) `tts profile` shows the cycle is dominated by exactly those
boundaries.  This module fuses the whole cycle into a SINGLE `pallas_call`:
the popped chunk streams through VMEM in pool tiles of width ``Mt``
(``grid=(M//Mt,)`` — the pipelined grid double-buffers each tile's
HBM->VMEM copy under the previous tile's bound evaluation, the in-kernel
form of the PR 5 `DeviceOffloader.stage/dispatch_staged` host overlap),
bounds are evaluated with the same tile math as the standalone kernels
(`_nqueens_tile_labels` / `_lb1_tile_lb` / `_lb2_tile_lb` in
`ops/pallas_kernels.py` — shared helpers, so the bound values are the
already-pinned-exact kernel values), pruning, the LSB-first binary-shift
survivor compaction of `ops/compaction.shift_compact`, and the push all
happen against the tile in VMEM, and only the compacted child rows leave.

Tiling (``TTS_MEGAKERNEL_MT``, auto-resolved like `_auto_compact`):

* ``Mt == M`` (one tile) keeps the original pool-resident form verbatim:
  ``grid=(1,)``, no streaming, the whole chunk lives in VMEM.
* ``Mt < M`` streams ``G = M//Mt`` tiles.  Survivor compaction becomes
  two-phase across tiles: each tile dense-ranks its own survivors in VMEM
  (`_compact_push` at width Mt), and an SMEM carry accumulates the
  cross-tile survivor offset so push destinations stay collision-free and
  the concatenation of tiles is exactly the dense-mode global
  (parent, slot) order of `ops/compaction.py`.  The engine stitches the
  per-tile blocks with G overlapping `dynamic_update_slice` writes at the
  carried offsets — bit-identical to the single-tile emit.
* The PFSP families need the incumbent fold over ALL leaves before any
  tile's keep test (`best = min(best, leaf bounds)` is global), so their
  grid is ``(2, G)``: phase 0 streams every tile, evaluates bounds into a
  per-tile VMEM stash and folds the global leaf-min; phase 1 re-streams
  the tiles (bounds are NOT recomputed — they are read back from the
  stash) and runs prune/compact/emit against the final incumbent.
  N-Queens has no bound pruning and keeps the single sweep ``grid=(G,)``.
* The cross-tile carry forces sequential grid order, so the full cycle
  kernels declare ``dimension_semantics=("arbitrary", ...)``; the
  evaluation-only pass has no carry and ships as a separate
  Megacore-parallel variant (:func:`streamed_eval_bounds`,
  ``dimension_semantics=("parallel",)`` — one chip's two TensorCores
  split the tiles).

Exactness:

* survivor ranks are triangular MXU matmuls over the 0/1 keep plane at
  HIGHEST precision — counts are < 2^24, so f32 accumulation is exact;
* lb1 is the int32 chain of `_lb1_tile_lb` (bit-exact vs `_lb1_chunk` on
  open slots);
* lb2 rides the max-plus closed form as bf16 MXU matmuls and is only
  allowed to arm when the instance passes the bf16-exactness gate
  (`PFSPDeviceTables.exact_bf16`: every processing time < 2^8, so every
  matmul operand is exactly representable in bf16) — otherwise
  :func:`resolve` refuses and records why (banner + SearchResult).

Routing (`TTS_MEGAKERNEL=auto|0|force`, resolved like the compact auto
policy): ``auto`` arms only on a real TPU backend and when the per-tile
VMEM model fits — inside the small-M window the single-tile resident form
is kept verbatim; past it `Mt` shrinks `_auto_tile`-style (halving, sublane
aligned, dividing M) until each tile fits the window and the per-tile +
double-buffer + stash charge of `_mega_pool_bytes`, so ``auto`` arms far
past the old ``M*n <= 2^16`` ceiling and refuses only when even the
smallest tile (or the PFSP bound stash, which scales with M) cannot fit.
``force`` arms everywhere (interpret mode off-TPU — the CI/CPU parity
spelling).  The raw TTS_MEGAKERNEL and TTS_MEGAKERNEL_MT knobs are keyed
into `routing_cache_token`, so a flip rebuilds the resident program and
``0`` is a byte-identical jaxpr (contracts `megakernel-off-identity`,
`megakernel-tiled-identity`).

Keep/retire: the lb1 Pallas kernel lost 7x to fused jnp and was demoted
(docs/HW_VALIDATION.md) — this kernel ships with the same decision
procedure (docs/HW_VALIDATION.md "Megakernel keep/retire",
`hw_session.sh` stage 8): it either beats the measured phase split on chip
— now quantified per phase by the roofline audit (`obs/roofline.py`,
`tts report --roofline`) — or dies quickly.
"""

from __future__ import annotations

import dataclasses
import os
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..analysis.contracts import contract
from . import backend as BK
from . import pallas_kernels as PK

#: single-tile window on the Mt*n product — within it the original
#: pool-resident form (grid=(1,), no streaming) is kept verbatim; past it
#: the pool axis tiles down so each STREAMED TILE stays inside the regime
#: the dense shift-compact was validated in (same window as the
#: dense-compact policy).
SMALL_M_LIMIT = 1 << 16

#: the same window expressed in POOL BYTES (2^16 int32 elements): with
#: narrow node storage armed (TTS_NARROW, problems/base.py) the write-back
#: that bounds the small-M regime moves pool-dtype bytes, so the window
#: widens by the narrowing factor — an int8 pool admits 4x the
#: Mt*n product at the same byte traffic. TTS_NARROW=0 keeps the
#: element-count window verbatim (`narrow-knob-inert`).
SMALL_M_BYTES = SMALL_M_LIMIT * 4


def _pool_itemsize(fam: str, n: int) -> int:
    """Bytes per pool value element for the resident pool this cycle runs
    against — the `engine/resident._pool_int_dtype` ladder (int8/int16/
    int32 by n) for PFSP, the uint8 board for N-Queens. Mirrored here so
    the kernel module keeps its lazy-import relationship with the engine
    package."""
    if fam == "nqueens":
        return 1
    return 1 if n <= 127 else (2 if n <= 32767 else 4)

#: mirrors problems.base.INF_BOUND without importing the problems package
#: into a kernel module (the packages import each other lazily).
_INF_BOUND = 2**31 - 1


def megakernel_mode() -> str:
    """The TTS_MEGAKERNEL knob: ``auto`` (default — TPU + per-tile VMEM
    fit), ``0`` (off, byte-identical jaxpr), ``force`` (arm everywhere;
    interpret mode off-TPU)."""
    mode = os.environ.get("TTS_MEGAKERNEL", "auto")
    if mode not in ("auto", "0", "force"):
        raise ValueError(
            f"TTS_MEGAKERNEL must be auto|0|force, got {mode!r}"
        )
    return mode


def megakernel_mt() -> int | None:
    """The TTS_MEGAKERNEL_MT knob: force the streamed pool-tile width
    ``Mt`` (None — unset — resolves it from the VMEM budget).  Alignment
    (multiple of 8, divides M) is a per-shape property and is checked in
    :func:`resolve`, which refuses with a recorded reason instead of
    raising."""
    raw = os.environ.get("TTS_MEGAKERNEL_MT")
    if raw is None or raw == "":
        return None
    mt = int(raw)
    if mt <= 0:
        raise ValueError(
            f"TTS_MEGAKERNEL_MT must be a positive tile width, got {raw!r}"
        )
    return mt


@dataclasses.dataclass(frozen=True)
class Decision:
    """The resolved megakernel routing for one resident program build.

    ``reason`` records why the kernel did NOT arm (auto declined, or a
    correctness refusal that even ``force`` honors) — surfaced in the
    `tts` banner and carried in SearchResult.megakernel_reason.
    ``mt``/``grid`` record the resolved pool-tile width and tile count
    (``grid == 1`` is the original single-tile resident form; ``grid > 1``
    streams the pool through VMEM tile by tile).  ``backend`` is the
    kernel flavor the cycle builds with (`ops/backend.py` — ``gpu`` is
    the Triton lowering, single-tile only: the cross-tile SMEM carry
    needs the TPU's sequential grid)."""

    enabled: bool
    auto: bool
    interpret: bool
    reason: str | None
    mt: int = 0
    grid: int = 1
    backend: str = "tpu"

    @property
    def state(self) -> str:
        return "on" if self.enabled else "off"

    @property
    def tiled(self) -> bool:
        return self.enabled and self.grid > 1


def _family(problem) -> str | None:
    name = getattr(problem, "name", None)
    if name == "nqueens":
        return "nqueens"
    if name == "pfsp":
        return getattr(problem, "lb", None)
    return None


def _native_kind(device) -> str | None:
    """The kernel flavor the resolved backend compiles NATIVELY on the
    target device — 'tpu' or 'gpu' — else None (interpret territory:
    forced/interpret builds, CPU processes).  Replaces the old hard
    ``platform == "tpu"`` gate with the `ops/backend.py` seam."""
    try:
        b = BK.resolve_backend(device)
    except Exception:
        return None
    return b.kind if (b.native and b.kind in ("tpu", "gpu")) else None


def _mega_pool_bytes(M: int, n: int, pool_itemsize: int = 4,
                     mt: int | None = None, lb_stash: bool = False) -> int:
    """The cycle's VMEM charge on top of the bound kernels' own
    `_model_bytes` model (the ``extra_bytes`` the feasibility gate adds).

    Single tile (``mt`` None or == M): the original pool-resident charge —
    the batch tile IS M (grid=(1,)), so the child cube, the flattened
    (M*n, n) child rows plus the shift pass's live copies, the rank/dist
    columns, and the two triangular rank operands are all live inside one
    grid step.

    Tiled (``mt < M``): the same intermediates at tile width Mt, PLUS a 2x
    double-buffer charge on every streamed block (the pipelined grid
    prefetches tile i+1's HBM->VMEM copies under tile i's compute — in
    and out blocks both carry two live buffers), PLUS, with ``lb_stash``
    (the PFSP two-phase grid), the (G, Mt, n) int32 bound stash that holds
    phase 0's evaluations for phase 1 — the one charge that scales with M,
    not Mt, and therefore the one that can still refuse a shape.

    ``pool_itemsize`` charges the pool-dtype tiles (the popped values
    entering) at their storage width; the in-kernel intermediates stay
    int32/f32 regardless."""
    r8, r128 = PK._r8, PK._r128
    if mt is not None and mt >= M:
        mt = None
    if mt is None:
        Mn = M * n
        cube = M * r8(n) * r128(n) * 4          # (M, n, n) child cube
        flat = 3 * r8(Mn) * r128(n) * 4         # (Mn, n) rows + shift copies
        cols = 4 * r8(Mn) * 128 * 4             # aux/rank/dist/take columns
        tri = r8(M) * r128(M) * 4 + r8(n) * r128(n) * 4  # rank triangles
        # popped pool tile + its narrow copy, keep plane, scalar lanes
        io = (2 * r8(M) * r128(n) * pool_itemsize
              + r8(M) * r128(n) * 4 + 128 * 4)
        return cube + flat + cols + tri + io
    G = M // mt
    Mtn = mt * n
    cube = mt * r8(n) * r128(n) * 4
    flat = 3 * r8(Mtn) * r128(n) * 4
    cols = 4 * r8(Mtn) * 128 * 4
    tri = r8(mt) * r128(mt) * 4 + r8(n) * r128(n) * 4
    # Streamed blocks are double-buffered by the pipelined grid: two live
    # copies of each in block (pool tile + narrow copy + keep plane +
    # lanes) and each out block (compacted rows + aux column + scalar row).
    stream_in = 2 * (2 * r8(mt) * r128(n) * pool_itemsize
                     + r8(mt) * r128(n) * 4 + 128 * 4)
    stream_out = 2 * (r8(Mtn) * r128(n) * 4 + r8(Mtn) * 128 * 4 + 128 * 4)
    total = cube + flat + cols + tri + stream_in + stream_out
    if lb_stash:
        total += G * r8(mt) * r128(n) * 4
    return total


def _tile_window_ok(fam: str, mt: int, n: int) -> bool:
    """Per-tile small-M window: the dense shift-compact regime each
    streamed tile must stay inside (byte-based with narrow storage)."""
    from ..problems.base import narrow_enabled

    if narrow_enabled():
        return mt * n * _pool_itemsize(fam, n) <= SMALL_M_BYTES
    return mt * n <= SMALL_M_LIMIT


def _fits(problem, fam: str, M: int, n: int, mt: int | None = None,
          backend: str = "tpu") -> tuple[bool, str | None]:
    """Fast-memory feasibility at pool-tile width ``mt`` (None or M — the
    single-tile resident form; smaller — the streamed per-tile +
    double-buffer + stash charge of `_mega_pool_bytes`), against the
    backend's budget (`pallas_kernels._vmem_limit_bytes`)."""
    from ..problems.base import narrow_enabled

    if mt is not None and mt >= M:
        mt = None
    t = mt or M
    itemsize = _pool_itemsize(fam, n) if narrow_enabled() else 4
    extra = _mega_pool_bytes(M, n, itemsize, mt=mt,
                             lb_stash=(fam != "nqueens"))
    if fam == "nqueens":
        need = PK._model_bytes(t, n, 1, extra, 3)
    elif fam == "lb1":
        need = PK._model_bytes(t, n, problem.machines, extra, 3)
    else:  # lb2
        from . import pfsp_device as PD

        m = problem.machines
        P = problem.lb2_data.pairs.shape[0]
        pg = PD.lb2_kernel_pair_group(P, n)
        need = PK._model_bytes(
            t, n, m, extra + PK._lb2_static_extra(n, m, P + (-P) % pg), 3,
            pair_copies=5, pair_group=pg,
        )
    budget = PK._vmem_budget(backend)
    if need > budget:
        if mt is None:
            return False, (
                f"auto: VMEM model {need // 2**20} MiB exceeds the "
                f"{budget // 2**20} MiB budget at M={M} "
                "(single-tile resident cycle)"
            )
        return False, (
            f"auto: VMEM model {need // 2**20} MiB exceeds the "
            f"{budget // 2**20} MiB budget even at pool tile Mt={t} "
            "(per-tile charge + double-buffered streams + the (G, Mt, n) "
            "bound stash, which scales with M)"
        )
    return True, None


def _resolve_mt(problem, fam: str, M: int, n: int) -> int | None:
    """Resolve the streamed pool-tile width `_auto_compact`-style: the
    largest halving-ladder Mt (multiple of 8, divides M) whose tile stays
    inside the small-M window AND whose per-tile VMEM model fits.  None
    when even the smallest tile cannot fit (the caller records the
    refusal)."""
    mt = M
    while True:
        if _tile_window_ok(fam, mt, n) and _fits(problem, fam, M, n, mt)[0]:
            return mt
        if mt <= 8:
            return None
        nxt = max(8, (mt // 2) // 8 * 8)
        while M % nxt:
            nxt -= 8
        mt = nxt


def resolve(problem, M: int, device=None, mp_axis: str | None = None,
            mp_size: int = 1) -> Decision:
    """Resolve the megakernel routing for one resident program build —
    the `_auto_compact`-style policy.  Correctness refusals (unsupported
    bound family, mp pair sharding, the lb2 bf16-exactness gate, tile
    misalignment — including a TTS_MEGAKERNEL_MT that does not divide M,
    and tiled streaming on the gpu flavor, whose cross-tile SMEM carry
    only the TPU's sequential grid can run) hold even under ``force``;
    the remaining gates (native TPU/GPU backend, per-tile memory fit)
    apply to ``auto`` only."""
    mode = megakernel_mode()
    if mode == "0":
        return Decision(False, False, False, None)
    auto = mode == "auto"
    fam = _family(problem)
    n = int(problem.child_slots)
    kb = BK.kernel_kind(device)  # 'gpu' only when the seam resolves gpu
    if fam not in ("nqueens", "lb1", "lb2"):
        return Decision(False, auto, False,
                        f"unsupported bound family {fam!r} (the megakernel "
                        "ports nqueens/lb1/lb2 only)", backend=kb)
    if mp_axis is not None or mp_size > 1:
        return Decision(False, auto, False,
                        "mp pair-axis sharding (the fused cycle is "
                        "single-shard)", backend=kb)
    if M % 8 != 0:
        return Decision(False, auto, False,
                        f"M={M} not a multiple of the sublane quantum (8)",
                        backend=kb)
    if fam == "lb2":
        t = problem.device_tables()
        if not getattr(t, "exact_bf16", False):
            return Decision(False, auto, False,
                            "lb2 bf16-exactness gate: max processing time "
                            ">= 256, the max-plus MXU formulation is not "
                            "bit-exact (f32 pair-blocked oracle keeps the "
                            "cycle)", backend=kb)
    mt_env = megakernel_mt()
    if mt_env is not None and (mt_env % 8 != 0 or M % mt_env != 0):
        return Decision(False, auto, False,
                        f"TTS_MEGAKERNEL_MT={mt_env} must be a multiple of "
                        f"the sublane quantum (8) and divide M={M}",
                        backend=kb)
    if kb == "gpu" and mt_env is not None and mt_env < M:
        return Decision(False, auto, False,
                        f"gpu backend: TTS_MEGAKERNEL_MT={mt_env} < M={M} "
                        "requests tiled streaming, whose cross-tile SMEM "
                        "carry needs the TPU's sequential grid (Triton "
                        "blocks are parallel)", backend=kb)
    native = _native_kind(device)
    if not auto:
        interpret = PK.pallas_interpret() or native is None
        if kb == "gpu":
            return Decision(True, False, interpret, None, mt=M, grid=1,
                            backend="gpu")
        mt = mt_env or _resolve_mt(problem, fam, M, n) or M
        return Decision(True, False, interpret, None, mt=mt, grid=M // mt)
    if native is None or PK.pallas_interpret():
        reason = ("auto: not on a TPU backend" if kb != "gpu" else
                  "auto: kernel backend gpu is not native here (no GPU in "
                  "this process — TTS_MEGAKERNEL=force runs it interpreted)")
        return Decision(False, True, False, reason, backend=kb)
    if kb == "gpu":
        # Single tile or nothing: tiled streaming is a TPU-only construct.
        ok, why = _fits(problem, fam, M, n, backend="gpu")
        if _tile_window_ok(fam, M, n) and ok:
            return Decision(True, True, False, None, mt=M, grid=1,
                            backend="gpu")
        why = why or (
            f"gpu backend: M*n={M * n} exceeds the single-tile window and "
            "tiled streaming needs the TPU's sequential-grid SMEM carry")
        return Decision(False, True, False, why, backend="gpu")
    if mt_env is not None:
        ok, why = _fits(problem, fam, M, n, mt_env)
        if not ok:
            return Decision(False, True, False, why)
        return Decision(True, True, False, None, mt=mt_env,
                        grid=M // mt_env)
    # Single-tile fast path: inside the small-M window the original
    # pool-resident form (grid=(1,), no 2x PFSP re-stream) is kept
    # verbatim when it fits.
    if _tile_window_ok(fam, M, n) and _fits(problem, fam, M, n)[0]:
        return Decision(True, True, False, None, mt=M, grid=1)
    mt = _resolve_mt(problem, fam, M, n)
    if mt is None:
        _, why = _fits(problem, fam, M, n, 8)
        return Decision(False, True, False, why)
    return Decision(True, True, False, None, mt=mt, grid=M // mt)


# ---------------------------------------------------------------------------
# in-kernel cycle epilogue: prune -> rank -> shift-compact -> emit
# ---------------------------------------------------------------------------


def _scalar_lanes(tree_inc, sol_inc, best):
    """(1, 128) int32 scalar output row: lanes 0/1/2 = tree_inc / sol_inc /
    best (Mosaic wants a full lane register, not three scalars)."""
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, 128), 1)
    return jnp.where(
        lane == 0, tree_inc,
        jnp.where(lane == 1, sol_inc, jnp.where(lane == 2, best, 0)),
    )


def _tile_scalar_lanes(offs, cnt, sol_cum, best):
    """(1, 128) int32 PER-TILE scalar row of the streamed grid: lanes
    0/1/2/3 = cross-tile survivor offset before this tile / this tile's
    survivor count / cumulative sol_inc through this tile / incumbent.
    The engine reads the last tile's row for the cycle scalars and the
    offset column for the stitch destinations."""
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, 128), 1)
    return jnp.where(
        lane == 0, offs,
        jnp.where(lane == 1, cnt,
                  jnp.where(lane == 2, sol_cum,
                            jnp.where(lane == 3, best, 0))),
    )


def _compact_push(vals, aux, d, keep, *, n: int, M: int):
    """Survivor compaction entirely in VMEM: ranks as triangular MXU
    matmuls, children as the three-select swap cube (`_swap_children`'s
    structure — no gather), then the LSB-first binary-shift scheme of
    `ops/compaction.shift_compact`, statically unrolled over the flattened
    (M*n, *) payloads.  Returns (rows (Mn, n) i32, caux (Mn, 1) i32,
    tree_inc) with rows beyond ``tree_inc`` garbage (dead by the pool
    contract — the engine advances ``size`` by tree_inc only).  On the
    streamed path this runs per tile at ``M = Mt``; the cross-tile offset
    carry makes the concatenation of tiles the dense-mode global order."""
    i32, f32 = jnp.int32, jnp.float32
    Mn = M * n
    keep_f = keep.astype(f32)  # (M, n)

    # Exclusive prefix counts: within-row along lanes (keep @ strict-upper
    # triangle) and across rows (strict-lower triangle @ per-row counts).
    # 0/1 x 0/1 matmuls at HIGHEST precision; every count < 2^24 -> exact.
    rl = jax.lax.broadcasted_iota(i32, (n, n), 0)
    cl = jax.lax.broadcasted_iota(i32, (n, n), 1)
    lane = PK._hp_dot(keep_f, (rl < cl).astype(f32))  # (M, n)
    cnt = jnp.sum(keep_f, axis=1, keepdims=True)  # (M, 1)
    rm = jax.lax.broadcasted_iota(i32, (M, M), 0)
    cm = jax.lax.broadcasted_iota(i32, (M, M), 1)
    offs = PK._hp_dot((cm < rm).astype(f32), cnt)  # (M, 1)
    ranks = (offs + lane).astype(i32)  # (M, n) row-major survivor ranks
    tree_inc = jnp.sum(keep, dtype=i32)

    # Child cube by pure selects (a child differs from its parent at
    # exactly the two swapped positions); the value at the swap position
    # comes out of a one-hot lane reduction — no gather in the kernel.
    iota_l = jax.lax.broadcasted_iota(i32, (M, n, n), 2)
    kcol = jax.lax.broadcasted_iota(i32, (M, n, n), 1)
    ohd = jax.lax.broadcasted_iota(i32, (M, n), 1) == d[:, None]
    v_d = jnp.sum(jnp.where(ohd, vals, 0), axis=1)  # (M,) value at pos d
    cube = jnp.where(
        iota_l == d[:, None, None], vals[:, :, None],
        jnp.where(iota_l == kcol, v_d[:, None, None], vals[:, None, :]),
    )
    rows = cube.reshape(Mn, n)
    caux = jnp.broadcast_to((aux + 1)[:, None, None], (M, n, 1)).reshape(Mn, 1)
    keep_col = keep[:, :, None].reshape(Mn, 1)
    ranks_col = ranks[:, :, None].reshape(Mn, 1)
    idx_col = jax.lax.broadcasted_iota(i32, (Mn, 1), 0)
    dist = jnp.where(keep_col, idx_col - ranks_col, 0)

    # LSB-first binary shift (`ops/compaction.shift_compact`), statically
    # unrolled: distances only lose set bits, so log2(Mn) masked
    # shift-by-2^b rounds land every survivor at its rank.
    for b in range(max(1, int(Mn - 1).bit_length())):
        s = 1 << b
        if s >= Mn:
            break
        zc = jnp.zeros((s, 1), i32)
        sh_d = jnp.concatenate([dist[s:], zc], axis=0)
        take = (sh_d & s) != 0
        moving = (dist & s) != 0
        rows = jnp.where(take, jnp.concatenate(
            [rows[s:], jnp.zeros((s, n), i32)], axis=0), rows)
        caux = jnp.where(take, jnp.concatenate([caux[s:], zc], axis=0), caux)
        dist = jnp.where(take, sh_d - s, jnp.where(moving, 0, dist))
    return rows, caux, tree_inc


def _pfsp_epilogue(prmu, limit1, valid, best, lb, *, n: int, M: int):
    """The `_PFSPResident` evaluate fold (open/leaf/incumbent/keep — the
    unstaged branch; see the staged-equivalence note in `make_cycle`) +
    compaction.  ``lb`` int32 per child slot; swap position and child
    limit1 are both ``limit1 + 1``.  On the streamed grid ``best`` arrives
    already folded over ALL tiles' leaves (phase 0), so the local re-fold
    here is idempotent and the keep test prunes against the same global
    incumbent every tile — the reason the PFSP grid is two-phase."""
    i32 = jnp.int32
    pdepth = limit1 + 1
    kk = jax.lax.broadcasted_iota(i32, (M, n), 1)
    open_ = (kk >= pdepth[:, None]) & valid[:, None]
    leaf = open_ & ((pdepth[:, None] + 1) == n)
    sol_inc = jnp.sum(leaf, dtype=i32)
    best = jnp.minimum(best, jnp.min(jnp.where(leaf, lb, i32(_INF_BOUND))))
    keep = open_ & (~leaf) & (lb < best)
    rows, caux, tree_inc = _compact_push(prmu, limit1, pdepth, keep, n=n, M=M)
    return rows, caux, tree_inc, sol_inc, best


def _pfsp_leaf_min(limit1, valid, lb, *, n: int, M: int):
    """Phase 0's contribution to the global incumbent fold: the min bound
    over this tile's leaves (INF when none)."""
    i32 = jnp.int32
    pdepth = limit1 + 1
    kk = jax.lax.broadcasted_iota(i32, (M, n), 1)
    open_ = (kk >= pdepth[:, None]) & valid[:, None]
    leaf = open_ & ((pdepth[:, None] + 1) == n)
    return jnp.min(jnp.where(leaf, lb, i32(_INF_BOUND)))


# ---------------------------------------------------------------------------
# family cycle kernels — single tile (grid=(1,), pool resident)
# ---------------------------------------------------------------------------


def _mega_nqueens_kernel(board_ref, depth_ref, valid_ref, best_ref,
                         out_vals_ref, out_aux_ref, scal_ref,
                         *, N: int, g: int, M: int):
    board = board_ref[:].astype(jnp.int32)  # (M, N)
    depth = depth_ref[:, 0].astype(jnp.int32)  # (M,)
    valid = valid_ref[:, 0] != 0
    best = best_ref[0]
    labels = PK._nqueens_tile_labels(board, depth, N=N, g=g)
    # The `_NQueensResident` evaluate fold: swap position is the depth.
    keep = labels & valid[:, None] & (depth < N)[:, None]
    sol_inc = jnp.sum(valid & (depth == N), dtype=jnp.int32)
    rows, caux, tree_inc = _compact_push(board, depth, depth, keep, n=N, M=M)
    out_vals_ref[:] = rows
    out_aux_ref[:] = caux
    scal_ref[:] = _scalar_lanes(tree_inc, sol_inc, best)


def _mega_lb1_kernel(prmu_ref, limit1_ref, valid_ref, best_ref,
                     ptm_ref, heads_ref, tails_ref,
                     out_vals_ref, out_aux_ref, scal_ref, scan_ref,
                     *, n: int, m: int, M: int, bf16: bool):
    prmu = prmu_ref[:].astype(jnp.int32)
    limit1 = limit1_ref[:, 0].astype(jnp.int32)
    valid = valid_ref[:, 0] != 0
    best = best_ref[0]
    ptm = ptm_ref[:].astype(jnp.float32)
    lb = PK._lb1_tile_lb(prmu, limit1, ptm, heads_ref[:], tails_ref[:],
                         scan_ref, n=n, m=m, bf16=bf16)
    rows, caux, tree_inc, sol_inc, best = _pfsp_epilogue(
        prmu, limit1, valid, best, lb, n=n, M=M)
    out_vals_ref[:] = rows
    out_aux_ref[:] = caux
    scal_ref[:] = _scalar_lanes(tree_inc, sol_inc, best)


def _mega_lb2_kernel(prmu_ref, limit1_ref, valid_ref, best_ref,
                     ptm_ref, heads_ref,
                     p0_ref, p1_ref, lag_ref, t0_ref, t1_ref,
                     msel0_ref, msel1_ref, jorder_ref,
                     out_vals_ref, out_aux_ref, scal_ref, scan_ref,
                     *, n: int, m: int, P: int, M: int, pg: int, bf16: bool):
    prmu = prmu_ref[:].astype(jnp.int32)
    limit1 = limit1_ref[:, 0].astype(jnp.int32)
    valid = valid_ref[:, 0] != 0
    best = best_ref[0]
    ptm = ptm_ref[:].astype(jnp.float32)
    lb = PK._lb2_tile_lb(
        prmu, limit1, ptm, heads_ref[:],
        p0_ref, p1_ref, lag_ref, t0_ref, t1_ref, msel0_ref, msel1_ref,
        jorder_ref, scan_ref, n=n, m=m, P=P, pg=pg, bf16=bf16,
    ).astype(jnp.int32)
    rows, caux, tree_inc, sol_inc, best = _pfsp_epilogue(
        prmu, limit1, valid, best, lb, n=n, M=M)
    out_vals_ref[:] = rows
    out_aux_ref[:] = caux
    scal_ref[:] = _scalar_lanes(tree_inc, sol_inc, best)


def _mega_lb1_kernel_gpu(prmu_ref, limit1_ref, valid_ref, best_ref,
                         ptm_ref, heads_ref, tails_ref,
                         out_vals_ref, out_aux_ref, scal_ref,
                         *, n: int, m: int, M: int, bf16: bool):
    """The lb1 cycle without its scan scratch — the Triton flavor
    (`pallas_kernels._front_scan` unrolls statically where the TPU kernel
    staged; the epilogue is shift/select math, backend-neutral)."""
    _mega_lb1_kernel(prmu_ref, limit1_ref, valid_ref, best_ref,
                     ptm_ref, heads_ref, tails_ref,
                     out_vals_ref, out_aux_ref, scal_ref, None,
                     n=n, m=m, M=M, bf16=bf16)


def _mega_lb2_kernel_gpu(prmu_ref, limit1_ref, valid_ref, best_ref,
                         ptm_ref, heads_ref,
                         p0_ref, p1_ref, lag_ref, t0_ref, t1_ref,
                         msel0_ref, msel1_ref, jorder_ref,
                         out_vals_ref, out_aux_ref, scal_ref,
                         *, n: int, m: int, P: int, M: int, pg: int,
                         bf16: bool):
    _mega_lb2_kernel(prmu_ref, limit1_ref, valid_ref, best_ref,
                     ptm_ref, heads_ref,
                     p0_ref, p1_ref, lag_ref, t0_ref, t1_ref,
                     msel0_ref, msel1_ref, jorder_ref,
                     out_vals_ref, out_aux_ref, scal_ref, None,
                     n=n, m=m, P=P, M=M, pg=pg, bf16=bf16)


# ---------------------------------------------------------------------------
# family cycle kernels — streamed (grid over pool tiles, SMEM offset carry)
# TPU-only: the cross-tile carry needs the sequential grid; `resolve`
# refuses tiled streaming on the gpu flavor.
# ---------------------------------------------------------------------------
#
# SMEM carry layout (persists across sequential grid steps):
#   [0] cross-tile survivor offset   [1] cumulative sol_inc
#   [2] globally folded incumbent (PFSP phase 0)


def _mega_nqueens_tiled_kernel(board_ref, depth_ref, valid_ref, best_ref,
                               out_vals_ref, out_aux_ref, scal_ref,
                               carry_ref, *, N: int, g: int, Mt: int):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _seed():
        carry_ref[0] = 0
        carry_ref[1] = 0

    board = board_ref[:].astype(jnp.int32)  # (Mt, N)
    depth = depth_ref[:, 0].astype(jnp.int32)
    valid = valid_ref[:, 0] != 0
    best = best_ref[0]
    labels = PK._nqueens_tile_labels(board, depth, N=N, g=g)
    keep = labels & valid[:, None] & (depth < N)[:, None]
    sol_inc = jnp.sum(valid & (depth == N), dtype=jnp.int32)
    rows, caux, cnt = _compact_push(board, depth, depth, keep, n=N, M=Mt)
    offs = carry_ref[0]
    sol_cum = carry_ref[1] + sol_inc
    out_vals_ref[:] = rows
    out_aux_ref[:] = caux
    scal_ref[:] = _tile_scalar_lanes(offs, cnt, sol_cum, best)
    carry_ref[0] = offs + cnt
    carry_ref[1] = sol_cum


def _mega_lb1_tiled_kernel(prmu_ref, limit1_ref, valid_ref, best_ref,
                           ptm_ref, heads_ref, tails_ref,
                           out_vals_ref, out_aux_ref, scal_ref,
                           scan_ref, lb_ref, carry_ref,
                           *, n: int, m: int, Mt: int, bf16: bool):
    p = pl.program_id(0)
    i = pl.program_id(1)

    @pl.when((p == 0) & (i == 0))
    def _seed():
        carry_ref[0] = 0
        carry_ref[1] = 0
        carry_ref[2] = best_ref[0]

    prmu = prmu_ref[:].astype(jnp.int32)
    limit1 = limit1_ref[:, 0].astype(jnp.int32)
    valid = valid_ref[:, 0] != 0

    @pl.when(p == 0)
    def _sweep():
        # Phase 0: evaluate this tile's bounds into the stash and fold its
        # leaf-min into the global incumbent — no tile may prune before
        # every tile's leaves have been folded (PFSP exactness rule).
        lb = PK._lb1_tile_lb(prmu, limit1, ptm_ref[:].astype(jnp.float32),
                             heads_ref[:], tails_ref[:], scan_ref,
                             n=n, m=m, bf16=bf16)
        lb_ref[i] = lb
        carry_ref[2] = jnp.minimum(
            carry_ref[2], _pfsp_leaf_min(limit1, valid, lb, n=n, M=Mt))

    @pl.when(p == 1)
    def _emit():
        # Phase 1: re-stream the tile (bounds come back from the stash,
        # not recomputed) and prune/compact against the final incumbent.
        lb = lb_ref[i]
        rows, caux, cnt, sol_inc, best = _pfsp_epilogue(
            prmu, limit1, valid, carry_ref[2], lb, n=n, M=Mt)
        offs = carry_ref[0]
        sol_cum = carry_ref[1] + sol_inc
        out_vals_ref[:] = rows
        out_aux_ref[:] = caux
        scal_ref[:] = _tile_scalar_lanes(offs, cnt, sol_cum, best)
        carry_ref[0] = offs + cnt
        carry_ref[1] = sol_cum


def _mega_lb2_tiled_kernel(prmu_ref, limit1_ref, valid_ref, best_ref,
                           ptm_ref, heads_ref,
                           p0_ref, p1_ref, lag_ref, t0_ref, t1_ref,
                           msel0_ref, msel1_ref, jorder_ref,
                           out_vals_ref, out_aux_ref, scal_ref,
                           scan_ref, lb_ref, carry_ref,
                           *, n: int, m: int, P: int, Mt: int, pg: int,
                           bf16: bool):
    p = pl.program_id(0)
    i = pl.program_id(1)

    @pl.when((p == 0) & (i == 0))
    def _seed():
        carry_ref[0] = 0
        carry_ref[1] = 0
        carry_ref[2] = best_ref[0]

    prmu = prmu_ref[:].astype(jnp.int32)
    limit1 = limit1_ref[:, 0].astype(jnp.int32)
    valid = valid_ref[:, 0] != 0

    @pl.when(p == 0)
    def _sweep():
        lb = PK._lb2_tile_lb(
            prmu, limit1, ptm_ref[:].astype(jnp.float32), heads_ref[:],
            p0_ref, p1_ref, lag_ref, t0_ref, t1_ref, msel0_ref, msel1_ref,
            jorder_ref, scan_ref, n=n, m=m, P=P, pg=pg, bf16=bf16,
        ).astype(jnp.int32)
        lb_ref[i] = lb
        carry_ref[2] = jnp.minimum(
            carry_ref[2], _pfsp_leaf_min(limit1, valid, lb, n=n, M=Mt))

    @pl.when(p == 1)
    def _emit():
        lb = lb_ref[i]
        rows, caux, cnt, sol_inc, best = _pfsp_epilogue(
            prmu, limit1, valid, carry_ref[2], lb, n=n, M=Mt)
        offs = carry_ref[0]
        sol_cum = carry_ref[1] + sol_inc
        out_vals_ref[:] = rows
        out_aux_ref[:] = caux
        scal_ref[:] = _tile_scalar_lanes(offs, cnt, sol_cum, best)
        carry_ref[0] = offs + cnt
        carry_ref[1] = sol_cum


# ---------------------------------------------------------------------------
# pallas_call factories
# ---------------------------------------------------------------------------


def _cycle_out(M: int, n: int, backend: str = "tpu"):
    """Single-tile out plumbing (grid=(1,) — the pool tile IS the grid)."""
    Mn = M * n
    full = lambda i: (0, 0)
    shapes = (
        jax.ShapeDtypeStruct((Mn, n), jnp.int32),
        jax.ShapeDtypeStruct((Mn, 1), jnp.int32),
        jax.ShapeDtypeStruct((1, 128), jnp.int32),
    )
    specs = (
        PK._bs((Mn, n), full, backend=backend),
        PK._bs((Mn, 1), full, backend=backend),
        PK._bs((1, 128), full, backend=backend),
    )
    return shapes, specs


def _chunk_specs(M: int, n: int, backend: str = "tpu"):
    full = lambda i: (0, 0)
    return [
        PK._bs((M, n), full, backend=backend),   # vals
        PK._bs((M, 1), full, backend=backend),   # aux
        PK._bs((M, 1), full, backend=backend),   # valid
        PK._bs((1,), lambda i: (0,), space="smem", backend=backend),  # best
    ]


def _tiled_out(M: int, n: int, mt: int, two_phase: bool):
    """Streamed out plumbing: each tile owns its (Mt*n)-row block of the
    (M*n, n) reservation plus one row of the (G, 128) per-tile scalar
    output.  On the two-phase PFSP grid the out index map pins every
    phase-0 step to block 0 (``p * i``): no block boundary is crossed
    before the first real write at step (1, 0), so the phase-0 sweep never
    flushes an unwritten buffer over the output."""
    G = M // mt
    Mtn = mt * n
    if two_phase:
        tm = lambda p, i: (p * i, 0)
    else:
        tm = lambda i: (i, 0)
    shapes = (
        jax.ShapeDtypeStruct((M * n, n), jnp.int32),
        jax.ShapeDtypeStruct((M * n, 1), jnp.int32),
        jax.ShapeDtypeStruct((G, 128), jnp.int32),
    )
    specs = (
        pl.BlockSpec((Mtn, n), tm, memory_space=pltpu.VMEM),
        pl.BlockSpec((Mtn, 1), tm, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 128), tm, memory_space=pltpu.VMEM),
    )
    return shapes, specs


def _tiled_chunk_specs(mt: int, n: int, two_phase: bool):
    """Streamed in plumbing: the (i)-th grid step's BlockSpec index maps
    fetch pool tile i's rows HBM->VMEM — the pipelined grid prefetches
    tile i+1 under tile i's compute (the double buffer).  The two-phase
    PFSP grid re-fetches each tile in phase 1 (bounds are stashed; node
    fields are cheaper to re-stream than to hold for the whole sweep)."""
    if two_phase:
        tile = lambda p, i: (i, 0)
        smem = lambda p, i: (0,)
    else:
        tile = lambda i: (i, 0)
        smem = lambda i: (0,)
    return [
        pl.BlockSpec((mt, n), tile, memory_space=pltpu.VMEM),   # vals
        pl.BlockSpec((mt, 1), tile, memory_space=pltpu.VMEM),   # aux
        pl.BlockSpec((mt, 1), tile, memory_space=pltpu.VMEM),   # valid
        pl.BlockSpec((1,), smem, memory_space=pltpu.SMEM),      # best
    ]


@lru_cache(maxsize=None)
def _nqueens_cycle_call(N: int, g: int, M: int, interpret: bool,
                        backend: str = "tpu"):
    # The N-Queens cycle body holds no scratch, so the gpu flavor reuses it
    # verbatim — only the specs/params change spelling.
    shapes, out_specs = _cycle_out(M, N, backend)
    return pl.pallas_call(
        partial(_mega_nqueens_kernel, N=N, g=g, M=M),
        out_shape=shapes,
        grid=(1,),
        in_specs=_chunk_specs(M, N, backend),
        out_specs=out_specs,
        compiler_params=PK._compiler_params(backend=backend),
        interpret=interpret,
    )


@lru_cache(maxsize=None)
def _nqueens_tiled_call(N: int, g: int, M: int, mt: int, interpret: bool):
    shapes, out_specs = _tiled_out(M, N, mt, two_phase=False)
    return pl.pallas_call(
        partial(_mega_nqueens_tiled_kernel, N=N, g=g, Mt=mt),
        out_shape=shapes,
        grid=(M // mt,),
        in_specs=_tiled_chunk_specs(mt, N, two_phase=False),
        out_specs=out_specs,
        scratch_shapes=[pltpu.SMEM((4,), jnp.int32)],
        compiler_params=PK._compiler_params(),
        interpret=interpret,
    )


@lru_cache(maxsize=None)
def _lb1_cycle_call(n: int, m: int, M: int, bf16: bool, interpret: bool,
                    backend: str = "tpu"):
    full = lambda i: (0, 0)
    shapes, out_specs = _cycle_out(M, n, backend)
    kernel = _mega_lb1_kernel_gpu if backend == "gpu" else _mega_lb1_kernel
    return pl.pallas_call(
        partial(kernel, n=n, m=m, M=M, bf16=bf16),
        out_shape=shapes,
        grid=(1,),
        in_specs=_chunk_specs(M, n, backend) + [
            PK._bs((n, m), full, backend=backend),
            PK._bs((1, m), full, backend=backend),
            PK._bs((1, m), full, backend=backend),
        ],
        out_specs=out_specs,
        scratch_shapes=PK._scratch(backend, pltpu.VMEM((n, M, m), jnp.int32)),
        compiler_params=PK._compiler_params(backend=backend),
        interpret=interpret,
    )


@lru_cache(maxsize=None)
def _lb1_tiled_call(n: int, m: int, M: int, mt: int, bf16: bool,
                    interpret: bool):
    G = M // mt
    full = lambda p, i: (0, 0)
    shapes, out_specs = _tiled_out(M, n, mt, two_phase=True)
    return pl.pallas_call(
        partial(_mega_lb1_tiled_kernel, n=n, m=m, Mt=mt, bf16=bf16),
        out_shape=shapes,
        grid=(2, G),
        in_specs=_tiled_chunk_specs(mt, n, two_phase=True) + [
            pl.BlockSpec((n, m), full, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, m), full, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, m), full, memory_space=pltpu.VMEM),
        ],
        out_specs=out_specs,
        scratch_shapes=[pltpu.VMEM((n, mt, m), jnp.int32),
                        pltpu.VMEM((G, mt, n), jnp.int32),
                        pltpu.SMEM((4,), jnp.int32)],
        compiler_params=PK._compiler_params(ndims=2),
        interpret=interpret,
    )


@lru_cache(maxsize=None)
def _lb2_cycle_call(n: int, m: int, P: int, M: int, pg: int, bf16: bool,
                    interpret: bool, backend: str = "tpu"):
    full = lambda i: (0, 0)
    full3 = lambda i: (0, 0, 0)
    bs = partial(PK._bs, backend=backend)
    shapes, out_specs = _cycle_out(M, n, backend)
    kernel = _mega_lb2_kernel_gpu if backend == "gpu" else _mega_lb2_kernel
    return pl.pallas_call(
        partial(kernel, n=n, m=m, P=P, M=M, pg=pg, bf16=bf16),
        out_shape=shapes,
        grid=(1,),
        in_specs=_chunk_specs(M, n, backend) + [
            bs((n, m), full),
            bs((1, m), full),
            # Per-pair table layout matches `_lb2_call` exactly — see the
            # leading-axis / SMEM notes there.
            bs((P, 1, n), full3),
            bs((P, 1, n), full3),
            bs((P, 1, n), full3),
            bs((P,), lambda i: (0,), space="smem"),
            bs((P,), lambda i: (0,), space="smem"),
            bs((P, 1, m), full3),
            bs((P, 1, m), full3),
            bs((P, n, n), full3),
        ],
        out_specs=out_specs,
        scratch_shapes=PK._scratch(backend, pltpu.VMEM((n, M, m), jnp.int32)),
        compiler_params=PK._compiler_params(backend=backend),
        interpret=interpret,
    )


@lru_cache(maxsize=None)
def _lb2_tiled_call(n: int, m: int, P: int, M: int, mt: int, pg: int,
                    bf16: bool, interpret: bool):
    G = M // mt
    full = lambda p, i: (0, 0)
    full3 = lambda p, i: (0, 0, 0)
    smem1 = lambda p, i: (0,)
    shapes, out_specs = _tiled_out(M, n, mt, two_phase=True)
    return pl.pallas_call(
        partial(_mega_lb2_tiled_kernel, n=n, m=m, P=P, Mt=mt, pg=pg,
                bf16=bf16),
        out_shape=shapes,
        grid=(2, G),
        in_specs=_tiled_chunk_specs(mt, n, two_phase=True) + [
            pl.BlockSpec((n, m), full, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, m), full, memory_space=pltpu.VMEM),
            pl.BlockSpec((P, 1, n), full3, memory_space=pltpu.VMEM),
            pl.BlockSpec((P, 1, n), full3, memory_space=pltpu.VMEM),
            pl.BlockSpec((P, 1, n), full3, memory_space=pltpu.VMEM),
            pl.BlockSpec((P,), smem1, memory_space=pltpu.SMEM),
            pl.BlockSpec((P,), smem1, memory_space=pltpu.SMEM),
            pl.BlockSpec((P, 1, m), full3, memory_space=pltpu.VMEM),
            pl.BlockSpec((P, 1, m), full3, memory_space=pltpu.VMEM),
            pl.BlockSpec((P, n, n), full3, memory_space=pltpu.VMEM),
        ],
        out_specs=out_specs,
        scratch_shapes=[pltpu.VMEM((n, mt, m), jnp.int32),
                        pltpu.VMEM((G, mt, n), jnp.int32),
                        pltpu.SMEM((4,), jnp.int32)],
        compiler_params=PK._compiler_params(ndims=2),
        interpret=interpret,
    )


# ---------------------------------------------------------------------------
# engine entry
# ---------------------------------------------------------------------------


def make_cycle(problem, M: int, device, decision: Decision):
    """Build ``cycle(vals_c, aux_c, valid, best) -> (rows (Mn, n) i32,
    caux (Mn,) i32, offs (G,) i32, tree_inc, sol_inc, best)`` — the armed
    alternate body `engine/resident.py loop_fns` splices in after the pop.
    ``offs`` carries each tile's cross-tile survivor offset (all-zero on
    the single-tile path, G == 1): the engine writes tile t's (Mt*n)-row
    block at ``size + offs[t]``, in tile order, so each write's garbage
    tail is overwritten by the next tile's rows and the surviving layout
    is exactly the dense-mode global (parent, slot) order.

    lb2 note: the kernel always evaluates the UNSTAGED fold, even when the
    two-pass staged evaluator is enabled for the jnp path.  They are
    value-identical: at a leaf the lb1 and lb2 makespans coincide (nothing
    is unscheduled), and for interior nodes ``lb2 >= lb1`` pointwise, so
    the staged keep ``open & ~leaf & (lb1 < best) & (lb2 < best)``
    equals the unstaged ``open & ~leaf & (lb2 < best)``.
    """
    fam = _family(problem)
    interpret = decision.interpret
    tiled = decision.grid > 1
    mt = decision.mt or M
    G = decision.grid
    # Tiled streaming is TPU-only (resolve refuses it on gpu), so only the
    # single-tile factories take the flavor.
    kb = decision.backend

    def _legacy(rows, caux, scal):
        zero_offs = jnp.zeros((1,), jnp.int32)
        return (rows, caux[:, 0], zero_offs,
                scal[0, 0], scal[0, 1], scal[0, 2])

    def _streamed(rows, caux, scal):
        last = scal[G - 1]
        return (rows, caux[:, 0], scal[:, 0],
                last[0] + last[1], last[2], last[3])

    if fam == "nqueens":
        if tiled:
            call = _nqueens_tiled_call(problem.N, problem.g, M, mt,
                                       interpret)
        else:
            call = _nqueens_cycle_call(problem.N, problem.g, M, interpret,
                                       kb)

        def cycle(vals_c, aux_c, valid, best):
            rows, caux, scal = call(
                vals_c, aux_c[:, None], valid.astype(jnp.int32)[:, None],
                jnp.reshape(best, (1,)),
            )
            return (_streamed if tiled else _legacy)(rows, caux, scal)

        return cycle

    t = problem.device_tables()
    n = problem.jobs
    m = problem.machines
    bf16 = bool(getattr(t, "exact_bf16", False))
    if fam == "lb1":
        if tiled:
            call = _lb1_tiled_call(n, m, M, mt, bf16, interpret)
        else:
            call = _lb1_cycle_call(n, m, M, bf16, interpret, kb)

        def cycle(vals_c, aux_c, valid, best):
            rows, caux, scal = call(
                vals_c, aux_c[:, None], valid.astype(jnp.int32)[:, None],
                jnp.reshape(best, (1,)),
                t.ptm_t, t.min_heads[None, :], t.min_tails[None, :],
            )
            return (_streamed if tiled else _legacy)(rows, caux, scal)

        return cycle

    # lb2 — Johnson-ordered tables resolved exactly like `pfsp_lb2_bounds`
    # (device cache when eager, numpy constants under a trace).
    from . import pfsp_device as PD

    P = t.pairs.shape[0]
    pg = PD.lb2_kernel_pair_group(P, n)
    ordered = (t.johnson_ordered_device(pg) if PK._eager_context()
               else t.johnson_ordered_mp(pg))
    Pp = ordered.lag_o.shape[0]
    if tiled:
        call = _lb2_tiled_call(n, m, Pp, M, mt, pg, bf16, interpret)
    else:
        call = _lb2_cycle_call(n, m, Pp, M, pg, bf16, interpret, kb)

    def cycle(vals_c, aux_c, valid, best):
        rows, caux, scal = call(
            vals_c, aux_c[:, None], valid.astype(jnp.int32)[:, None],
            jnp.reshape(best, (1,)),
            t.ptm_t, t.min_heads[None, :],
            ordered.p0_o[:, None, :],
            ordered.p1_o[:, None, :],
            ordered.lag_o[:, None, :],
            ordered.tails0,
            ordered.tails1,
            ordered.msel0[:, None, :],
            ordered.msel1[:, None, :],
            ordered.jorder,
        )
        return (_streamed if tiled else _legacy)(rows, caux, scal)

    return cycle


# ---------------------------------------------------------------------------
# Megacore-parallel evaluation-only pass
# ---------------------------------------------------------------------------


def _eval_nqueens_kernel(board_ref, depth_ref, out_ref, *, N: int, g: int):
    labels = PK._nqueens_tile_labels(
        board_ref[:].astype(jnp.int32), depth_ref[:, 0].astype(jnp.int32),
        N=N, g=g)
    out_ref[:] = labels.astype(jnp.int32)


def _eval_lb1_kernel(prmu_ref, limit1_ref, ptm_ref, heads_ref, tails_ref,
                     out_ref, scan_ref, *, n: int, m: int, bf16: bool):
    out_ref[:] = PK._lb1_tile_lb(
        prmu_ref[:].astype(jnp.int32), limit1_ref[:, 0].astype(jnp.int32),
        ptm_ref[:].astype(jnp.float32), heads_ref[:], tails_ref[:],
        scan_ref, n=n, m=m, bf16=bf16)


def _eval_lb2_kernel(prmu_ref, limit1_ref, ptm_ref, heads_ref,
                     p0_ref, p1_ref, lag_ref, t0_ref, t1_ref,
                     msel0_ref, msel1_ref, jorder_ref,
                     out_ref, scan_ref,
                     *, n: int, m: int, P: int, pg: int, bf16: bool):
    out_ref[:] = PK._lb2_tile_lb(
        prmu_ref[:].astype(jnp.int32), limit1_ref[:, 0].astype(jnp.int32),
        ptm_ref[:].astype(jnp.float32), heads_ref[:],
        p0_ref, p1_ref, lag_ref, t0_ref, t1_ref, msel0_ref, msel1_ref,
        jorder_ref, scan_ref, n=n, m=m, P=P, pg=pg, bf16=bf16,
    ).astype(jnp.int32)


def _eval_lb1_kernel_gpu(prmu_ref, limit1_ref, ptm_ref, heads_ref, tails_ref,
                         out_ref, *, n: int, m: int, bf16: bool):
    _eval_lb1_kernel(prmu_ref, limit1_ref, ptm_ref, heads_ref, tails_ref,
                     out_ref, None, n=n, m=m, bf16=bf16)


def _eval_lb2_kernel_gpu(prmu_ref, limit1_ref, ptm_ref, heads_ref,
                         p0_ref, p1_ref, lag_ref, t0_ref, t1_ref,
                         msel0_ref, msel1_ref, jorder_ref,
                         out_ref, *, n: int, m: int, P: int, pg: int,
                         bf16: bool):
    _eval_lb2_kernel(prmu_ref, limit1_ref, ptm_ref, heads_ref,
                     p0_ref, p1_ref, lag_ref, t0_ref, t1_ref,
                     msel0_ref, msel1_ref, jorder_ref,
                     out_ref, None, n=n, m=m, P=P, pg=pg, bf16=bf16)


@lru_cache(maxsize=None)
def _eval_nqueens_call(N: int, g: int, B: int, mt: int, interpret: bool,
                       backend: str = "tpu"):
    tm = lambda i: (i, 0)
    bs = partial(PK._bs, backend=backend)
    return pl.pallas_call(
        partial(_eval_nqueens_kernel, N=N, g=g),
        out_shape=jax.ShapeDtypeStruct((B, N), jnp.int32),
        grid=(B // mt,),
        in_specs=[bs((mt, N), tm), bs((mt, 1), tm)],
        out_specs=bs((mt, N), tm),
        compiler_params=PK._compiler_params(parallel=True, backend=backend),
        interpret=interpret,
    )


@lru_cache(maxsize=None)
def _eval_lb1_call(n: int, m: int, B: int, mt: int, bf16: bool,
                   interpret: bool, backend: str = "tpu"):
    tm = lambda i: (i, 0)
    full = lambda i: (0, 0)
    bs = partial(PK._bs, backend=backend)
    kernel = _eval_lb1_kernel_gpu if backend == "gpu" else _eval_lb1_kernel
    return pl.pallas_call(
        partial(kernel, n=n, m=m, bf16=bf16),
        out_shape=jax.ShapeDtypeStruct((B, n), jnp.int32),
        grid=(B // mt,),
        in_specs=[
            bs((mt, n), tm),
            bs((mt, 1), tm),
            bs((n, m), full),
            bs((1, m), full),
            bs((1, m), full),
        ],
        out_specs=bs((mt, n), tm),
        scratch_shapes=PK._scratch(backend,
                                   pltpu.VMEM((n, mt, m), jnp.int32)),
        compiler_params=PK._compiler_params(parallel=True, backend=backend),
        interpret=interpret,
    )


@lru_cache(maxsize=None)
def _eval_lb2_call(n: int, m: int, P: int, B: int, mt: int, pg: int,
                   bf16: bool, interpret: bool, backend: str = "tpu"):
    tm = lambda i: (i, 0)
    full = lambda i: (0, 0)
    full3 = lambda i: (0, 0, 0)
    smem1 = lambda i: (0,)
    bs = partial(PK._bs, backend=backend)
    kernel = _eval_lb2_kernel_gpu if backend == "gpu" else _eval_lb2_kernel
    return pl.pallas_call(
        partial(kernel, n=n, m=m, P=P, pg=pg, bf16=bf16),
        out_shape=jax.ShapeDtypeStruct((B, n), jnp.int32),
        grid=(B // mt,),
        in_specs=[
            bs((mt, n), tm),
            bs((mt, 1), tm),
            bs((n, m), full),
            bs((1, m), full),
            bs((P, 1, n), full3),
            bs((P, 1, n), full3),
            bs((P, 1, n), full3),
            bs((P,), smem1, space="smem"),
            bs((P,), smem1, space="smem"),
            bs((P, 1, m), full3),
            bs((P, 1, m), full3),
            bs((P, n, n), full3),
        ],
        out_specs=bs((mt, n), tm),
        scratch_shapes=PK._scratch(backend,
                                   pltpu.VMEM((n, mt, m), jnp.int32)),
        compiler_params=PK._compiler_params(parallel=True, backend=backend),
        interpret=interpret,
    )


def streamed_eval_bounds(problem, vals, aux, mt: int | None = None,
                         interpret: bool | None = None):
    """Evaluation-only streamed pass over a (B, n) chunk — the Megacore
    split of the tiled megakernel.  Unlike the full cycle there is no
    cross-tile carry, so every grid axis is declared
    ``dimension_semantics=("parallel",)`` and Mosaic is free to split the
    pool tiles across a chip's two TensorCores.  Returns the (B, n) int32
    bound plane (lb1/lb2) or keep-label plane (N-Queens) — bit-identical
    to the carried kernels' phase-0 values (shared tile bodies).  ``mt``
    defaults to one tile (B); tests force small multi-tile widths."""
    fam = _family(problem)
    if fam not in ("nqueens", "lb1", "lb2"):
        raise ValueError(f"streamed_eval_bounds: unsupported family {fam!r}")
    B = int(vals.shape[0])
    mt = mt or B
    if B % mt or mt % 8:
        raise ValueError(
            f"streamed_eval_bounds: tile {mt} must divide B={B} and be a "
            "multiple of the sublane quantum (8)")
    kb = BK.kernel_kind(None)
    if interpret is None:
        interpret = PK.pallas_interpret() or _native_kind(None) is None
    vals_c = jnp.asarray(vals).astype(jnp.int32)
    aux_c = jnp.asarray(aux).astype(jnp.int32)[:, None]
    if fam == "nqueens":
        call = _eval_nqueens_call(problem.N, problem.g, B, mt, interpret, kb)
        return call(vals_c, aux_c)
    t = problem.device_tables()
    n, m = problem.jobs, problem.machines
    bf16 = bool(getattr(t, "exact_bf16", False))
    if fam == "lb1":
        call = _eval_lb1_call(n, m, B, mt, bf16, interpret, kb)
        return call(vals_c, aux_c, t.ptm_t, t.min_heads[None, :],
                    t.min_tails[None, :])
    from . import pfsp_device as PD

    P = t.pairs.shape[0]
    pg = PD.lb2_kernel_pair_group(P, n)
    ordered = (t.johnson_ordered_device(pg) if PK._eager_context()
               else t.johnson_ordered_mp(pg))
    Pp = ordered.lag_o.shape[0]
    call = _eval_lb2_call(n, m, Pp, B, mt, pg, bf16, interpret, kb)
    return call(vals_c, aux_c, t.ptm_t, t.min_heads[None, :],
                ordered.p0_o[:, None, :], ordered.p1_o[:, None, :],
                ordered.lag_o[:, None, :], ordered.tails0, ordered.tails1,
                ordered.msel0[:, None, :], ordered.msel1[:, None, :],
                ordered.jorder)


def megakernel_lb2_bounds(prmu, limit1, tables, interpret: bool | None = None):
    """The lb2 bound values the megakernel arms with, as a standalone (B, n)
    call — the bf16 max-plus MXU formulation over the shared
    `_lb2_tile_lb` body.  The bf16-exactness gate test bit-compares this
    against the f32 pair-blocked oracle (`pfsp_device._lb2_chunk`) on real
    Taillard instances; a mismatch means :func:`resolve`'s gate is wrong
    and the kernel must refuse to arm."""
    return PK.pfsp_lb2_bounds(prmu, limit1, tables, interpret=interpret,
                              bf16=True)


# ---------------------------------------------------------------------------
# contracts (tts check)
# ---------------------------------------------------------------------------


@contract(
    "megakernel-off-identity",
    claim="TTS_MEGAKERNEL unset (auto, unarmed on the audit's CPU traces) "
          "and =0 build byte-identical resident step jaxprs — the armed "
          "body is compiled out when off, never branched",
    artifact="variants",
)
def _contract_megakernel_off_identity(art, cell):
    if not art.has("off", "mk0"):
        return []
    out = []
    if art.text("off") != art.text("mk0"):
        out.append("TTS_MEGAKERNEL=0 build differs from the unset build "
                   "(the armed cycle body leaked into the off path)")
    if art.outvars("mk0") != art.outvars("off"):
        out.append("TTS_MEGAKERNEL=0 build changed the carry width")
    return out


@contract(
    "megakernel-tiled-identity",
    claim="the Mt knob is inert when the kernel is off (TTS_MEGAKERNEL=0 "
          "with TTS_MEGAKERNEL_MT set is byte-identical to the off build) "
          "and the tiled armed build keeps the off build's carry width — "
          "the tile count never leaks into the step signature",
    artifact="variants",
)
def _contract_megakernel_tiled_identity(art, cell):
    out = []
    if art.has("off", "mk0-mt"):
        if art.text("mk0-mt") != art.text("off"):
            out.append("TTS_MEGAKERNEL_MT leaked into the TTS_MEGAKERNEL=0 "
                       "build (off must stay a byte-identical jaxpr)")
        if art.outvars("mk0-mt") != art.outvars("off"):
            out.append("TTS_MEGAKERNEL_MT changed the off build's carry "
                       "width")
    if art.has("off", "mk-tiled"):
        if art.outvars("mk-tiled") != art.outvars("off"):
            out.append("tiled armed build changed the carry width vs off "
                       "(per-tile offsets must stay inside the kernel)")
    return out


@contract(
    "megakernel-single-call",
    claim="the armed cycle body is ONE pallas_call — single- and "
          "multi-tile alike — no sort, no searchsorted, and no scatter "
          "beyond the phase profiler's clock-block updates; a build that "
          "refused to arm recorded why",
    artifact="resident-step",
    applies=lambda cell: cell is not None
    and getattr(cell, "megakernel", None) == "force",
)
def _contract_megakernel_single_call(art, cell):
    dec = getattr(art.prog, "megakernel", None)
    if dec is None:
        return ["resident program carries no megakernel decision"]
    ncalls = sum(1 for name, _ in art.prims if name == "pallas_call")
    if not dec.enabled:
        out = []
        if not dec.reason:
            return ["megakernel refused to arm without recording a reason"]
        if ncalls:
            out.append(
                f"refused build ({dec.reason}) still contains "
                f"{ncalls} pallas_call(s)"
            )
        return out
    out = []
    if ncalls != 1:
        out.append(f"armed cycle body contains {ncalls} pallas_call eqns "
                   "(expected exactly 1)")
    banned = {"sort", "searchsorted"} & art.prim_names
    if banned:
        out.append(f"armed cycle body contains banned primitives: "
                   f"{sorted(banned)}")
    # The phase profiler's clock block updates (.at[].add on the
    # (NSLOTS+1,) uint32 block) lower to tiny scatters — exempt; any
    # node-data-sized scatter breaks the claim.
    from ..obs import phases as obs_phases

    for name, eqn in art.prims:
        if not name.startswith("scatter"):
            continue
        if any(v.aval.size > obs_phases.NSLOTS + 1 for v in eqn.outvars):
            out.append(
                f"armed cycle body contains a node-sized {name} "
                f"({[tuple(v.aval.shape) for v in eqn.outvars]})"
            )
    return out
