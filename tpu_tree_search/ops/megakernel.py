"""One-kernel resident cycle: a Pallas megakernel for the small-M regime.

The resident engine's inner loop (pop -> bound -> prune -> compact -> push,
the offload cycle of `pfsp_gpu_chpl.chpl:276-298`) normally compiles as a
chain of XLA ops inside the `lax.while_loop`: each op boundary is a
dispatch, and every intermediate (the child cube, the keep plane, the
compacted rows) round-trips through HBM.  At the headline shapes (M around
1024) `tts profile` shows the cycle is dominated by exactly those
boundaries.  This module fuses the whole cycle into a SINGLE `pallas_call`:
the popped tile enters VMEM once, bounds are evaluated with the same tile
math as the standalone kernels (`_nqueens_tile_labels` / `_lb1_tile_lb` /
`_lb2_tile_lb` in `ops/pallas_kernels.py` — shared helpers, so the bound
values are the already-pinned-exact kernel values), pruning, the LSB-first
binary-shift survivor compaction of `ops/compaction.shift_compact`, and the
push all happen against that same resident tile, and only the compacted
child rows leave.

Exactness:

* survivor ranks are triangular MXU matmuls over the 0/1 keep plane at
  HIGHEST precision — counts are < 2^24, so f32 accumulation is exact;
* lb1 is the int32 chain of `_lb1_tile_lb` (bit-exact vs `_lb1_chunk` on
  open slots);
* lb2 rides the max-plus closed form as bf16 MXU matmuls and is only
  allowed to arm when the instance passes the bf16-exactness gate
  (`PFSPDeviceTables.exact_bf16`: every processing time < 2^8, so every
  matmul operand is exactly representable in bf16) — otherwise
  :func:`resolve` refuses and records why (banner + SearchResult).

Routing (`TTS_MEGAKERNEL=auto|0|force`, resolved like the compact auto
policy): ``auto`` arms only on a real TPU backend, in the small-M window,
and when the VMEM model fits — the megakernel's batch tile IS the chunk
width M (grid=(1,), the pool tile stays resident across the whole cycle),
so unlike the standalone kernels there is no `_auto_tile` shrinking: the
pool-resident buffers are charged into `_model_bytes` as ``extra_bytes``
and a shape that does not fit is REFUSED, never tiled down.  ``force``
arms everywhere (interpret mode off-TPU — the CI/CPU parity spelling).
The raw knob is keyed into `routing_cache_token`, so a flip rebuilds the
resident program and ``0`` is a byte-identical jaxpr (contract
`megakernel-off-identity`).

Keep/retire: the lb1 Pallas kernel lost 7x to fused jnp and was demoted
(docs/HW_VALIDATION.md) — this kernel ships with the same decision
procedure (docs/HW_VALIDATION.md "Megakernel keep/retire",
`hw_session.sh` stage 8): it either beats the measured phase split on chip
or dies quickly.
"""

from __future__ import annotations

import dataclasses
import os
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..analysis.contracts import contract
from . import pallas_kernels as PK

#: auto refuses above this M*n product — beyond the small-M regime the
#: compacted write-back dominates and the fused cycle has no dispatch
#: overhead left to amortize (same window as the dense-compact policy).
SMALL_M_LIMIT = 1 << 16

#: the same window expressed in POOL BYTES (2^16 int32 elements): with
#: narrow node storage armed (TTS_NARROW, problems/base.py) the write-back
#: that bounds the small-M regime moves pool-dtype bytes, so the auto
#: window widens by the narrowing factor — an int8 pool admits 4x the
#: M*n product at the same byte traffic. TTS_NARROW=0 keeps the
#: element-count window verbatim (`narrow-knob-inert`).
SMALL_M_BYTES = SMALL_M_LIMIT * 4


def _pool_itemsize(fam: str, n: int) -> int:
    """Bytes per pool value element for the resident pool this cycle runs
    against — the `engine/resident._pool_int_dtype` ladder (int8/int16/
    int32 by n) for PFSP, the uint8 board for N-Queens. Mirrored here so
    the kernel module keeps its lazy-import relationship with the engine
    package."""
    if fam == "nqueens":
        return 1
    return 1 if n <= 127 else (2 if n <= 32767 else 4)

#: mirrors problems.base.INF_BOUND without importing the problems package
#: into a kernel module (the packages import each other lazily).
_INF_BOUND = 2**31 - 1


def megakernel_mode() -> str:
    """The TTS_MEGAKERNEL knob: ``auto`` (default — TPU + small-M + VMEM
    fit), ``0`` (off, byte-identical jaxpr), ``force`` (arm everywhere;
    interpret mode off-TPU)."""
    mode = os.environ.get("TTS_MEGAKERNEL", "auto")
    if mode not in ("auto", "0", "force"):
        raise ValueError(
            f"TTS_MEGAKERNEL must be auto|0|force, got {mode!r}"
        )
    return mode


@dataclasses.dataclass(frozen=True)
class Decision:
    """The resolved megakernel routing for one resident program build.

    ``reason`` records why the kernel did NOT arm (auto declined, or a
    correctness refusal that even ``force`` honors) — surfaced in the
    `tts` banner and carried in SearchResult.megakernel_reason."""

    enabled: bool
    auto: bool
    interpret: bool
    reason: str | None

    @property
    def state(self) -> str:
        return "on" if self.enabled else "off"


def _family(problem) -> str | None:
    name = getattr(problem, "name", None)
    if name == "nqueens":
        return "nqueens"
    if name == "pfsp":
        return getattr(problem, "lb", None)
    return None


def _on_tpu(device) -> bool:
    try:
        if device is not None:
            return getattr(device, "platform", None) == "tpu"
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _mega_pool_bytes(M: int, n: int, pool_itemsize: int = 4) -> int:
    """The pool-resident VMEM charge of the fused cycle at chunk width M —
    the ``extra_bytes`` the feasibility gate adds on top of the bound
    kernels' own `_model_bytes` model.  Unlike the standalone kernels the
    batch tile here IS M (grid=(1,)), so these buffers cannot be tiled
    away: the child cube, the flattened (M*n, n) child rows plus the shift
    pass's live copies, the rank/dist columns, and the two triangular rank
    operands are all live inside one grid step.  ``pool_itemsize`` charges
    the pool-dtype tiles (the popped values entering and the compacted
    rows leaving) at their storage width; the in-kernel intermediates stay
    int32/f32 regardless."""
    r8, r128 = PK._r8, PK._r128
    Mn = M * n
    cube = M * r8(n) * r128(n) * 4          # (M, n, n) child cube
    flat = 3 * r8(Mn) * r128(n) * 4         # (Mn, n) rows + shift copies
    cols = 4 * r8(Mn) * 128 * 4             # aux/rank/dist/take columns
    tri = r8(M) * r128(M) * 4 + r8(n) * r128(n) * 4  # rank triangles
    # popped pool tile + its narrow copy, keep plane, scalar lanes
    io = (2 * r8(M) * r128(n) * pool_itemsize
          + r8(M) * r128(n) * 4 + 128 * 4)
    return cube + flat + cols + tri + io


def _fits(problem, fam: str, M: int, n: int) -> tuple[bool, str | None]:
    """VMEM feasibility at the fixed tile M (no `_auto_tile` shrinking —
    see `_mega_pool_bytes`)."""
    from ..problems.base import narrow_enabled

    itemsize = _pool_itemsize(fam, n) if narrow_enabled() else 4
    extra = _mega_pool_bytes(M, n, itemsize)
    if fam == "nqueens":
        need = PK._model_bytes(M, n, 1, extra, 3)
    elif fam == "lb1":
        need = PK._model_bytes(M, n, problem.machines, extra, 3)
    else:  # lb2
        from . import pfsp_device as PD

        m = problem.machines
        P = problem.lb2_data.pairs.shape[0]
        pg = PD.lb2_kernel_pair_group(P, n)
        need = PK._model_bytes(
            M, n, m, extra + PK._lb2_static_extra(n, m, P + (-P) % pg), 3,
            pair_copies=5, pair_group=pg,
        )
    budget = PK._vmem_budget()
    if need > budget:
        return False, (
            f"auto: VMEM model {need // 2**20} MiB exceeds the "
            f"{budget // 2**20} MiB budget at M={M} (the cycle tile is the "
            "chunk width — the pool-resident charge cannot be tiled down)"
        )
    return True, None


def resolve(problem, M: int, device=None, mp_axis: str | None = None,
            mp_size: int = 1) -> Decision:
    """Resolve the megakernel routing for one resident program build —
    the `_auto_compact`-style policy.  Correctness refusals (unsupported
    bound family, mp pair sharding, the lb2 bf16-exactness gate, tile
    misalignment) hold even under ``force``; the remaining gates (real
    TPU, small-M window, VMEM fit) apply to ``auto`` only."""
    mode = megakernel_mode()
    if mode == "0":
        return Decision(False, False, False, None)
    auto = mode == "auto"
    fam = _family(problem)
    n = int(problem.child_slots)
    if fam not in ("nqueens", "lb1", "lb2"):
        return Decision(False, auto, False,
                        f"unsupported bound family {fam!r} (the megakernel "
                        "ports nqueens/lb1/lb2 only)")
    if mp_axis is not None or mp_size > 1:
        return Decision(False, auto, False,
                        "mp pair-axis sharding (the fused cycle is "
                        "single-shard)")
    if M % 8 != 0:
        return Decision(False, auto, False,
                        f"M={M} not a multiple of the sublane quantum (8)")
    if fam == "lb2":
        t = problem.device_tables()
        if not getattr(t, "exact_bf16", False):
            return Decision(False, auto, False,
                            "lb2 bf16-exactness gate: max processing time "
                            ">= 256, the max-plus MXU formulation is not "
                            "bit-exact (f32 pair-blocked oracle keeps the "
                            "cycle)")
    if not auto:
        interpret = PK.pallas_interpret() or not _on_tpu(device)
        return Decision(True, False, interpret, None)
    if not _on_tpu(device) or PK.pallas_interpret():
        return Decision(False, True, False, "auto: not on a TPU backend")
    from ..problems.base import narrow_enabled

    if narrow_enabled():
        # Byte-based window: narrow pool storage moves fewer bytes per
        # node, so the write-back-bound regime extends by the narrowing
        # factor (4x at int8) at the same byte traffic.
        win = M * n * _pool_itemsize(fam, n)
        if win > SMALL_M_BYTES:
            return Decision(False, True, False,
                            f"auto: M*n pool bytes {win} above the small-M "
                            f"window ({SMALL_M_BYTES} B)")
    elif M * n > SMALL_M_LIMIT:
        return Decision(False, True, False,
                        f"auto: M*n={M * n} above the small-M window "
                        f"({SMALL_M_LIMIT})")
    ok, why = _fits(problem, fam, M, n)
    if not ok:
        return Decision(False, True, False, why)
    return Decision(True, True, False, None)


# ---------------------------------------------------------------------------
# in-kernel cycle epilogue: prune -> rank -> shift-compact -> emit
# ---------------------------------------------------------------------------


def _scalar_lanes(tree_inc, sol_inc, best):
    """(1, 128) int32 scalar output row: lanes 0/1/2 = tree_inc / sol_inc /
    best (Mosaic wants a full lane register, not three scalars)."""
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, 128), 1)
    return jnp.where(
        lane == 0, tree_inc,
        jnp.where(lane == 1, sol_inc, jnp.where(lane == 2, best, 0)),
    )


def _compact_push(vals, aux, d, keep, *, n: int, M: int):
    """Survivor compaction entirely in VMEM: ranks as triangular MXU
    matmuls, children as the three-select swap cube (`_swap_children`'s
    structure — no gather), then the LSB-first binary-shift scheme of
    `ops/compaction.shift_compact`, statically unrolled over the flattened
    (M*n, *) payloads.  Returns (rows (Mn, n) i32, caux (Mn, 1) i32,
    tree_inc) with rows beyond ``tree_inc`` garbage (dead by the pool
    contract — the engine advances ``size`` by tree_inc only)."""
    i32, f32 = jnp.int32, jnp.float32
    Mn = M * n
    keep_f = keep.astype(f32)  # (M, n)

    # Exclusive prefix counts: within-row along lanes (keep @ strict-upper
    # triangle) and across rows (strict-lower triangle @ per-row counts).
    # 0/1 x 0/1 matmuls at HIGHEST precision; every count < 2^24 -> exact.
    rl = jax.lax.broadcasted_iota(i32, (n, n), 0)
    cl = jax.lax.broadcasted_iota(i32, (n, n), 1)
    lane = PK._hp_dot(keep_f, (rl < cl).astype(f32))  # (M, n)
    cnt = jnp.sum(keep_f, axis=1, keepdims=True)  # (M, 1)
    rm = jax.lax.broadcasted_iota(i32, (M, M), 0)
    cm = jax.lax.broadcasted_iota(i32, (M, M), 1)
    offs = PK._hp_dot((cm < rm).astype(f32), cnt)  # (M, 1)
    ranks = (offs + lane).astype(i32)  # (M, n) row-major survivor ranks
    tree_inc = jnp.sum(keep, dtype=i32)

    # Child cube by pure selects (a child differs from its parent at
    # exactly the two swapped positions); the value at the swap position
    # comes out of a one-hot lane reduction — no gather in the kernel.
    iota_l = jax.lax.broadcasted_iota(i32, (M, n, n), 2)
    kcol = jax.lax.broadcasted_iota(i32, (M, n, n), 1)
    ohd = jax.lax.broadcasted_iota(i32, (M, n), 1) == d[:, None]
    v_d = jnp.sum(jnp.where(ohd, vals, 0), axis=1)  # (M,) value at pos d
    cube = jnp.where(
        iota_l == d[:, None, None], vals[:, :, None],
        jnp.where(iota_l == kcol, v_d[:, None, None], vals[:, None, :]),
    )
    rows = cube.reshape(Mn, n)
    caux = jnp.broadcast_to((aux + 1)[:, None, None], (M, n, 1)).reshape(Mn, 1)
    keep_col = keep[:, :, None].reshape(Mn, 1)
    ranks_col = ranks[:, :, None].reshape(Mn, 1)
    idx_col = jax.lax.broadcasted_iota(i32, (Mn, 1), 0)
    dist = jnp.where(keep_col, idx_col - ranks_col, 0)

    # LSB-first binary shift (`ops/compaction.shift_compact`), statically
    # unrolled: distances only lose set bits, so log2(Mn) masked
    # shift-by-2^b rounds land every survivor at its rank.
    for b in range(max(1, int(Mn - 1).bit_length())):
        s = 1 << b
        if s >= Mn:
            break
        zc = jnp.zeros((s, 1), i32)
        sh_d = jnp.concatenate([dist[s:], zc], axis=0)
        take = (sh_d & s) != 0
        moving = (dist & s) != 0
        rows = jnp.where(take, jnp.concatenate(
            [rows[s:], jnp.zeros((s, n), i32)], axis=0), rows)
        caux = jnp.where(take, jnp.concatenate([caux[s:], zc], axis=0), caux)
        dist = jnp.where(take, sh_d - s, jnp.where(moving, 0, dist))
    return rows, caux, tree_inc


def _pfsp_epilogue(prmu, limit1, valid, best, lb, *, n: int, M: int):
    """The `_PFSPResident` evaluate fold (open/leaf/incumbent/keep — the
    unstaged branch; see the staged-equivalence note in `make_cycle`) +
    compaction.  ``lb`` int32 per child slot; swap position and child
    limit1 are both ``limit1 + 1``."""
    i32 = jnp.int32
    pdepth = limit1 + 1
    kk = jax.lax.broadcasted_iota(i32, (M, n), 1)
    open_ = (kk >= pdepth[:, None]) & valid[:, None]
    leaf = open_ & ((pdepth[:, None] + 1) == n)
    sol_inc = jnp.sum(leaf, dtype=i32)
    best = jnp.minimum(best, jnp.min(jnp.where(leaf, lb, i32(_INF_BOUND))))
    keep = open_ & (~leaf) & (lb < best)
    rows, caux, tree_inc = _compact_push(prmu, limit1, pdepth, keep, n=n, M=M)
    return rows, caux, tree_inc, sol_inc, best


# ---------------------------------------------------------------------------
# family cycle kernels
# ---------------------------------------------------------------------------


def _mega_nqueens_kernel(board_ref, depth_ref, valid_ref, best_ref,
                         out_vals_ref, out_aux_ref, scal_ref,
                         *, N: int, g: int, M: int):
    board = board_ref[:].astype(jnp.int32)  # (M, N)
    depth = depth_ref[:, 0].astype(jnp.int32)  # (M,)
    valid = valid_ref[:, 0] != 0
    best = best_ref[0]
    labels = PK._nqueens_tile_labels(board, depth, N=N, g=g)
    # The `_NQueensResident` evaluate fold: swap position is the depth.
    keep = labels & valid[:, None] & (depth < N)[:, None]
    sol_inc = jnp.sum(valid & (depth == N), dtype=jnp.int32)
    rows, caux, tree_inc = _compact_push(board, depth, depth, keep, n=N, M=M)
    out_vals_ref[:] = rows
    out_aux_ref[:] = caux
    scal_ref[:] = _scalar_lanes(tree_inc, sol_inc, best)


def _mega_lb1_kernel(prmu_ref, limit1_ref, valid_ref, best_ref,
                     ptm_ref, heads_ref, tails_ref,
                     out_vals_ref, out_aux_ref, scal_ref, scan_ref,
                     *, n: int, m: int, M: int, bf16: bool):
    prmu = prmu_ref[:].astype(jnp.int32)
    limit1 = limit1_ref[:, 0].astype(jnp.int32)
    valid = valid_ref[:, 0] != 0
    best = best_ref[0]
    ptm = ptm_ref[:].astype(jnp.float32)
    lb = PK._lb1_tile_lb(prmu, limit1, ptm, heads_ref[:], tails_ref[:],
                         scan_ref, n=n, m=m, bf16=bf16)
    rows, caux, tree_inc, sol_inc, best = _pfsp_epilogue(
        prmu, limit1, valid, best, lb, n=n, M=M)
    out_vals_ref[:] = rows
    out_aux_ref[:] = caux
    scal_ref[:] = _scalar_lanes(tree_inc, sol_inc, best)


def _mega_lb2_kernel(prmu_ref, limit1_ref, valid_ref, best_ref,
                     ptm_ref, heads_ref,
                     p0_ref, p1_ref, lag_ref, t0_ref, t1_ref,
                     msel0_ref, msel1_ref, jorder_ref,
                     out_vals_ref, out_aux_ref, scal_ref, scan_ref,
                     *, n: int, m: int, P: int, M: int, pg: int, bf16: bool):
    prmu = prmu_ref[:].astype(jnp.int32)
    limit1 = limit1_ref[:, 0].astype(jnp.int32)
    valid = valid_ref[:, 0] != 0
    best = best_ref[0]
    ptm = ptm_ref[:].astype(jnp.float32)
    lb = PK._lb2_tile_lb(
        prmu, limit1, ptm, heads_ref[:],
        p0_ref, p1_ref, lag_ref, t0_ref, t1_ref, msel0_ref, msel1_ref,
        jorder_ref, scan_ref, n=n, m=m, P=P, pg=pg, bf16=bf16,
    ).astype(jnp.int32)
    rows, caux, tree_inc, sol_inc, best = _pfsp_epilogue(
        prmu, limit1, valid, best, lb, n=n, M=M)
    out_vals_ref[:] = rows
    out_aux_ref[:] = caux
    scal_ref[:] = _scalar_lanes(tree_inc, sol_inc, best)


# ---------------------------------------------------------------------------
# pallas_call factories (grid=(1,) — the pool tile IS the grid)
# ---------------------------------------------------------------------------


def _cycle_out(M: int, n: int):
    Mn = M * n
    shapes = (
        jax.ShapeDtypeStruct((Mn, n), jnp.int32),
        jax.ShapeDtypeStruct((Mn, 1), jnp.int32),
        jax.ShapeDtypeStruct((1, 128), jnp.int32),
    )
    specs = (
        pl.BlockSpec((Mn, n), lambda i: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((Mn, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 128), lambda i: (0, 0), memory_space=pltpu.VMEM),
    )
    return shapes, specs


def _chunk_specs(M: int, n: int):
    full = lambda i: (0, 0)
    return [
        pl.BlockSpec((M, n), full, memory_space=pltpu.VMEM),   # vals
        pl.BlockSpec((M, 1), full, memory_space=pltpu.VMEM),   # aux
        pl.BlockSpec((M, 1), full, memory_space=pltpu.VMEM),   # valid
        pl.BlockSpec((1,), lambda i: (0,), memory_space=pltpu.SMEM),  # best
    ]


@lru_cache(maxsize=None)
def _nqueens_cycle_call(N: int, g: int, M: int, interpret: bool):
    shapes, out_specs = _cycle_out(M, N)
    return pl.pallas_call(
        partial(_mega_nqueens_kernel, N=N, g=g, M=M),
        out_shape=shapes,
        grid=(1,),
        in_specs=_chunk_specs(M, N),
        out_specs=out_specs,
        compiler_params=PK._compiler_params(),
        interpret=interpret,
    )


@lru_cache(maxsize=None)
def _lb1_cycle_call(n: int, m: int, M: int, bf16: bool, interpret: bool):
    full = lambda i: (0, 0)
    shapes, out_specs = _cycle_out(M, n)
    return pl.pallas_call(
        partial(_mega_lb1_kernel, n=n, m=m, M=M, bf16=bf16),
        out_shape=shapes,
        grid=(1,),
        in_specs=_chunk_specs(M, n) + [
            pl.BlockSpec((n, m), full, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, m), full, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, m), full, memory_space=pltpu.VMEM),
        ],
        out_specs=out_specs,
        scratch_shapes=[pltpu.VMEM((n, M, m), jnp.int32)],
        compiler_params=PK._compiler_params(),
        interpret=interpret,
    )


@lru_cache(maxsize=None)
def _lb2_cycle_call(n: int, m: int, P: int, M: int, pg: int, bf16: bool,
                    interpret: bool):
    full = lambda i: (0, 0)
    full3 = lambda i: (0, 0, 0)
    shapes, out_specs = _cycle_out(M, n)
    return pl.pallas_call(
        partial(_mega_lb2_kernel, n=n, m=m, P=P, M=M, pg=pg, bf16=bf16),
        out_shape=shapes,
        grid=(1,),
        in_specs=_chunk_specs(M, n) + [
            pl.BlockSpec((n, m), full, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, m), full, memory_space=pltpu.VMEM),
            # Per-pair table layout matches `_lb2_call` exactly — see the
            # leading-axis / SMEM notes there.
            pl.BlockSpec((P, 1, n), full3, memory_space=pltpu.VMEM),
            pl.BlockSpec((P, 1, n), full3, memory_space=pltpu.VMEM),
            pl.BlockSpec((P, 1, n), full3, memory_space=pltpu.VMEM),
            pl.BlockSpec((P,), lambda i: (0,), memory_space=pltpu.SMEM),
            pl.BlockSpec((P,), lambda i: (0,), memory_space=pltpu.SMEM),
            pl.BlockSpec((P, 1, m), full3, memory_space=pltpu.VMEM),
            pl.BlockSpec((P, 1, m), full3, memory_space=pltpu.VMEM),
            pl.BlockSpec((P, n, n), full3, memory_space=pltpu.VMEM),
        ],
        out_specs=out_specs,
        scratch_shapes=[pltpu.VMEM((n, M, m), jnp.int32)],
        compiler_params=PK._compiler_params(),
        interpret=interpret,
    )


# ---------------------------------------------------------------------------
# engine entry
# ---------------------------------------------------------------------------


def make_cycle(problem, M: int, device, decision: Decision):
    """Build ``cycle(vals_c, aux_c, valid, best) -> (rows (Mn, n) i32,
    caux (Mn,) i32, tree_inc, sol_inc, best)`` — the armed alternate body
    `engine/resident.py loop_fns` splices in after the pop.

    lb2 note: the kernel always evaluates the UNSTAGED fold, even when the
    two-pass staged evaluator is enabled for the jnp path.  They are
    value-identical: at a leaf the lb1 and lb2 makespans coincide (nothing
    is unscheduled), and for interior nodes ``lb2 >= lb1`` pointwise, so
    the staged keep ``open & ~leaf & (lb1 < best) & (lb2 < best)``
    equals the unstaged ``open & ~leaf & (lb2 < best)``.
    """
    fam = _family(problem)
    interpret = decision.interpret
    if fam == "nqueens":
        call = _nqueens_cycle_call(problem.N, problem.g, M, interpret)

        def cycle(vals_c, aux_c, valid, best):
            rows, caux, scal = call(
                vals_c, aux_c[:, None], valid.astype(jnp.int32)[:, None],
                jnp.reshape(best, (1,)),
            )
            return rows, caux[:, 0], scal[0, 0], scal[0, 1], scal[0, 2]

        return cycle

    t = problem.device_tables()
    n = problem.jobs
    m = problem.machines
    bf16 = bool(getattr(t, "exact_bf16", False))
    if fam == "lb1":
        call = _lb1_cycle_call(n, m, M, bf16, interpret)

        def cycle(vals_c, aux_c, valid, best):
            rows, caux, scal = call(
                vals_c, aux_c[:, None], valid.astype(jnp.int32)[:, None],
                jnp.reshape(best, (1,)),
                t.ptm_t, t.min_heads[None, :], t.min_tails[None, :],
            )
            return rows, caux[:, 0], scal[0, 0], scal[0, 1], scal[0, 2]

        return cycle

    # lb2 — Johnson-ordered tables resolved exactly like `pfsp_lb2_bounds`
    # (device cache when eager, numpy constants under a trace).
    from . import pfsp_device as PD

    P = t.pairs.shape[0]
    pg = PD.lb2_kernel_pair_group(P, n)
    ordered = (t.johnson_ordered_device(pg) if PK._eager_context()
               else t.johnson_ordered_mp(pg))
    Pp = ordered.lag_o.shape[0]
    call = _lb2_cycle_call(n, m, Pp, M, pg, bf16, interpret)

    def cycle(vals_c, aux_c, valid, best):
        rows, caux, scal = call(
            vals_c, aux_c[:, None], valid.astype(jnp.int32)[:, None],
            jnp.reshape(best, (1,)),
            t.ptm_t, t.min_heads[None, :],
            ordered.p0_o[:, None, :],
            ordered.p1_o[:, None, :],
            ordered.lag_o[:, None, :],
            ordered.tails0,
            ordered.tails1,
            ordered.msel0[:, None, :],
            ordered.msel1[:, None, :],
            ordered.jorder,
        )
        return rows, caux[:, 0], scal[0, 0], scal[0, 1], scal[0, 2]

    return cycle


def megakernel_lb2_bounds(prmu, limit1, tables, interpret: bool | None = None):
    """The lb2 bound values the megakernel arms with, as a standalone (B, n)
    call — the bf16 max-plus MXU formulation over the shared
    `_lb2_tile_lb` body.  The bf16-exactness gate test bit-compares this
    against the f32 pair-blocked oracle (`pfsp_device._lb2_chunk`) on real
    Taillard instances; a mismatch means :func:`resolve`'s gate is wrong
    and the kernel must refuse to arm."""
    return PK.pfsp_lb2_bounds(prmu, limit1, tables, interpret=interpret,
                              bf16=True)


# ---------------------------------------------------------------------------
# contracts (tts check)
# ---------------------------------------------------------------------------


@contract(
    "megakernel-off-identity",
    claim="TTS_MEGAKERNEL unset (auto, unarmed on the audit's CPU traces) "
          "and =0 build byte-identical resident step jaxprs — the armed "
          "body is compiled out when off, never branched",
    artifact="variants",
)
def _contract_megakernel_off_identity(art, cell):
    if not art.has("off", "mk0"):
        return []
    out = []
    if art.text("off") != art.text("mk0"):
        out.append("TTS_MEGAKERNEL=0 build differs from the unset build "
                   "(the armed cycle body leaked into the off path)")
    if art.outvars("mk0") != art.outvars("off"):
        out.append("TTS_MEGAKERNEL=0 build changed the carry width")
    return out


@contract(
    "megakernel-single-call",
    claim="the armed cycle body is ONE pallas_call — no sort, no "
          "searchsorted, and no scatter beyond the phase profiler's "
          "clock-block updates; a build that refused to arm recorded why",
    artifact="resident-step",
    applies=lambda cell: cell is not None
    and getattr(cell, "megakernel", None) == "force",
)
def _contract_megakernel_single_call(art, cell):
    dec = getattr(art.prog, "megakernel", None)
    if dec is None:
        return ["resident program carries no megakernel decision"]
    ncalls = sum(1 for name, _ in art.prims if name == "pallas_call")
    if not dec.enabled:
        out = []
        if not dec.reason:
            return ["megakernel refused to arm without recording a reason"]
        if ncalls:
            out.append(
                f"refused build ({dec.reason}) still contains "
                f"{ncalls} pallas_call(s)"
            )
        return out
    out = []
    if ncalls != 1:
        out.append(f"armed cycle body contains {ncalls} pallas_call eqns "
                   "(expected exactly 1)")
    banned = {"sort", "searchsorted"} & art.prim_names
    if banned:
        out.append(f"armed cycle body contains banned primitives: "
                   f"{sorted(banned)}")
    # The phase profiler's clock block updates (.at[].add on the
    # (NSLOTS+1,) uint32 block) lower to tiny scatters — exempt; any
    # node-data-sized scatter breaks the claim.
    from ..obs import phases as obs_phases

    for name, eqn in art.prims:
        if not name.startswith("scatter"):
            continue
        if any(v.aval.size > obs_phases.NSLOTS + 1 for v in eqn.outvars):
            out.append(
                f"armed cycle body contains a node-sized {name} "
                f"({[tuple(v.aval.shape) for v in eqn.outvars]})"
            )
    return out
