"""Kernel-backend seam: which flavor of the Pallas kernel layer a build
targets, resolved once per program build and keyed into every cache.

The evaluators exist in three flavors:

  * ``tpu`` — the Mosaic-TPU kernels of `ops/pallas_kernels.py` /
    `ops/megakernel.py` (VMEM/SMEM BlockSpecs, scratch refs,
    ``dimension_semantics``, the scoped-VMEM charge).
  * ``gpu`` — the same tile bodies lowered through
    ``jax.experimental.pallas.triton``: plain BlockSpecs (Triton has no
    memory spaces and **no scratch memory**, so the position-major scan
    staging unrolls statically instead — see
    `pallas_kernels._front_scan`), Triton compiler params, and a
    parallel-CUDA-block grid.  Tiled megakernel streaming is refused (its
    cross-tile SMEM carry needs the TPU's sequential grid).
  * ``jnp`` — the fused XLA oracles (`ops/pfsp_device.py`,
    `ops/nqueens_device.py`); the portable path and the semantic oracle
    every kernel is bit-compared against.

``TTS_KERNEL_BACKEND=auto|tpu|gpu|jnp`` picks one, resolved
`_auto_compact`-style: ``auto`` (the default) maps the target device's
platform — TPU -> ``tpu``, GPU/CUDA/ROCm -> ``gpu``, anything else ->
``jnp`` — so an unset knob on a non-GPU process builds byte-identical
jaxprs to a build that predates this module (contract
`kernel-backend-inert`).  A forced flavor that does not match the physical
platform still builds (``gpu`` runs the Triton-structured kernels under
Pallas interpret mode — the CI parity path; ``tpu`` off-TPU keeps the jnp
routing exactly as ``TTS_PALLAS`` always has).  The raw knob and the
resolved kind both ride ``routing_cache_token``, so a flip rebuilds the
resident program instead of reusing a stale flavor.
"""

from __future__ import annotations

import dataclasses
import os

KINDS = ("tpu", "gpu", "jnp")

#: platform strings that count as a GPU target (jax reports "gpu" for the
#: plugin backends; raw PJRT device platforms spell the vendor).
_GPU_PLATFORMS = ("gpu", "cuda", "rocm")


def kernel_backend_mode() -> str:
    """The raw ``TTS_KERNEL_BACKEND`` knob: ``auto`` (default) or one of
    ``KINDS``.  Baked into compiled programs at trace time, so the engines
    carry it in ``routing_cache_token``."""
    mode = os.environ.get("TTS_KERNEL_BACKEND", "auto")
    if mode != "auto" and mode not in KINDS:
        raise ValueError(
            "TTS_KERNEL_BACKEND must be 'auto', 'tpu', 'gpu', or 'jnp', "
            f"got {mode!r}"
        )
    return mode


@dataclasses.dataclass(frozen=True)
class Backend:
    """The resolved kernel backend for one program build.

    ``kind``: which kernel flavor to build (one of ``KINDS``).
    ``native``: the physical platform can compile that flavor for real —
    False means the kernels run under Pallas interpret mode (the
    correctness/CI path; ``jnp`` is native everywhere)."""

    kind: str
    native: bool


def _platform(device=None) -> str:
    """The physical platform of the target device (the same fallback
    ladder `use_pallas`/`resolve_compact_mode` always used: an explicit
    device wins, else the process default backend)."""
    if device is not None:
        return getattr(device, "platform", "cpu") or "cpu"
    try:
        import jax

        return jax.default_backend()
    except Exception:
        return "cpu"


def resolve_backend(device=None) -> Backend:
    """Resolve the ``TTS_KERNEL_BACKEND`` knob against the target device —
    the `_auto_compact`-style policy this module exists for."""
    mode = kernel_backend_mode()
    plat = _platform(device)
    if mode == "auto":
        if plat == "tpu":
            return Backend("tpu", True)
        if plat in _GPU_PLATFORMS:
            return Backend("gpu", True)
        return Backend("jnp", True)
    if mode == "jnp":
        return Backend("jnp", True)
    if mode == "gpu":
        return Backend("gpu", plat in _GPU_PLATFORMS)
    return Backend("tpu", plat == "tpu")


def kernel_kind(device=None) -> str:
    """The kernel FLAVOR a pallas entry builds: ``gpu`` only when the
    resolved backend is gpu.  Everything else — including a ``jnp`` kind
    reached by forced interpret mode (``TTS_PALLAS_INTERPRET=1`` routes to
    the kernels on any backend) — keeps the TPU-flavored kernels, the
    interpret-mode flavor of record, so pre-existing builds stay
    byte-identical."""
    return "gpu" if resolve_backend(device).kind == "gpu" else "tpu"


def policy_backend(device=None) -> str:
    """The backend string the ``_auto_*`` policy tables key on.

    ``gpu`` whenever the resolved kind is gpu — forced gpu on a CPU
    process exercises the gpu policy rows too, so CI parity runs route
    exactly like a GPU host.  ``tpu`` only when NATIVE: a forced ``tpu``
    off-TPU falls back to jnp routing (`use_pallas` is False there), so
    its policy rows must stay the physical platform's — that keeps the
    kb-tpu build byte-identical off-GPU (contract `kernel-backend-inert`).
    The ``jnp`` kind runs XLA on whatever hardware is actually there, so
    its rows are the platform's as well."""
    b = resolve_backend(device)
    if b.kind == "gpu":
        return "gpu"
    if b.kind == "tpu" and b.native:
        return "tpu"
    return _platform(device)


def profile_backend(device=None) -> str:
    """The backend component of COSTMODEL profile keys and roofline peaks
    (`obs/costmodel.profile_key` — ``backend|topology|shape``).  Under
    ``auto`` (and any forced flavor that matches the platform) this is the
    raw platform string — byte-stable with every profile banked before
    this module existed.  A forced NON-native flavor gets a compound
    ``platform+kind`` key so its dispatch fits and band tables never
    contaminate the native profiles."""
    b = resolve_backend(device)
    plat = _platform(device)
    native_name = (
        b.kind == plat
        or (b.kind == "gpu" and plat in _GPU_PLATFORMS)
        or (b.kind == "jnp" and plat not in ("tpu",) + _GPU_PLATFORMS)
    )
    if kernel_backend_mode() == "auto" or native_name:
        return plat
    return f"{plat}+{b.kind}"


# -- compiled-program contracts (`tts check`, analysis/contracts.py) --------

from ..analysis.contracts import contract  # noqa: E402


@contract(
    "kernel-backend-inert",
    claim="TTS_KERNEL_BACKEND unset, =auto, =jnp, and =tpu all build "
          "byte-identical resident step jaxprs on a non-GPU process — the "
          "backend seam resolves to the same flavor today's builds already "
          "had, adds zero behavior of its own off-GPU, and only =gpu "
          "changes the program (the Triton-structured interpret lowering)",
    artifact="variants",
)
def _contract_kernel_backend_inert(art, cell):
    inert = [lb for lb in ("kb-auto", "kb-jnp", "kb-tpu") if art.has(lb)]
    if not art.has("off") or not inert:
        return []  # variant set traced without the kernel-backend labels
    out = []
    for lb in inert:
        if art.text(lb) != art.text("off"):
            out.append(
                f"TTS_KERNEL_BACKEND={lb[3:]} build differs from the unset "
                "build on a non-GPU process (the seam must be inert off-GPU)"
            )
    if art.has("kb-gpu") and art.outvars("kb-gpu") != art.outvars("off"):
        out.append(
            "TTS_KERNEL_BACKEND=gpu changed the resident step carry width "
            "(the flavor may change the program body, never its signature)"
        )
    return out
