"""Survivor-path stream compaction for the resident engines.

One chunk cycle ends by pushing the surviving children contiguously onto
the pool in (parent, slot) order — the reference's child push order
(`pfsp_gpu_chpl.chpl:276-298`).  Computing each survivor's *rank* is a pair
of prefix sums (cheap); inverting the rank map (which (parent, slot) is
the rank-s survivor?) is the expensive part, and round-5 hardware numbers
put it — not bound evaluation — at ~85% of resident-cycle time (VERDICT r5
"What's weak" #1-3).  This module owns every rank-inversion implementation
and the policy that picks between them:

  * ``scatter`` — one int32-id scatter to unique destinations.  Fast on
    CPU (gather-like); XLA:TPU lowers large general scatters to a
    mostly-serial loop.
  * ``sort``    — stable argsort of ranked keys (TPU's vectorized sort).
  * ``search``  — binary-search inverse: log2(M) gather rounds plus one
    (S, n) lane pass.  No sort, no scatter.
  * ``dense``   — the dense-child fast path: stream compaction by
    **LSB-first binary shifts**.  Every survivor must move left by
    ``dist = flat_index - rank``; between consecutive survivors the
    distance grows by exactly the gap between them, so shifting by
    2^b (bit b of the remaining distance, b ascending) keeps positions
    strictly increasing and never collides — the zero-conflict
    compaction of the N-Queens DFS line of work (arXiv 2511.12009)
    expressed as log2(M*n) rounds of static slice + select.  The
    compiled program contains **no sort, no scatter, no searchsorted,
    and no gather** (jaxpr-pinned by tests/test_compaction.py); its cost
    is ~(M*n*log2(M*n)) fully-vectorized selects, independent of the
    survivor count — which is exactly the regime where survivors are
    *dense* (N-Queens keeps most slots; the PFSP ub=inf warm-up regime
    prunes nothing) and the S-proportional gather modes pay the most.

All four produce identical ids in identical order (pinned).  ``auto`` (the
default) resolves per (problem, M, n, prune-rate regime, backend) from the
measured table in ``_auto_compact`` — the same self-tuning contract as
``--lb2-pairblock auto``; the raw knob rides ``routing_cache_token`` and
the resolved mode is baked into compiled programs at trace time.

The streamed megakernel (ops/megakernel.py) runs the **tiled** form of
``dense``: each pool tile of width Mt compacts its own (Mt*n) plane with
the same LSB-first shifts (rank base 0 per tile), and a cross-tile
survivor offset carried in SMEM across sequential grid steps restores the
global dense order when the engine stitches the tiles back at
``size + offset[t]``.  Per-tile rank + carried base is exactly the global
dense rank, so the tiled kernel is bit-identical to this module's
single-shot dense mode (pinned by tests/test_megakernel.py).
"""

from __future__ import annotations

MODES = ("scatter", "sort", "search", "dense")


def compact_mode() -> str:
    """The raw ``TTS_COMPACT`` knob: one of ``MODES`` or ``auto`` (the
    default — resolved per shape by ``resolve_compact_mode``).  Baked into
    compiled programs at trace time, so the engines carry it in
    ``routing_cache_token``."""
    import os

    mode = os.environ.get("TTS_COMPACT", "auto")
    if mode != "auto" and mode not in MODES:
        raise ValueError(
            "TTS_COMPACT must be 'auto', 'scatter', 'sort', 'search', or "
            f"'dense', got {mode!r}"
        )
    return mode


def _auto_compact(problem, M: int | None, n: int | None, platform: str) -> str:
    """The measured ``auto`` table.  Provisional entries come from the
    round-5 cycle arithmetic (docs/HW_VALIDATION.md) and are updated from
    BENCH artifacts — ``bench.py pick_compact`` measures all four modes on
    chip and records what ``auto`` would have picked:

      * N-Queens never prunes: survivors are dense, the scatter serializes
        on the full M*n grid, and the shift compaction's cost is flat in
        the survivor count -> ``dense`` (every backend: the CPU tiers only
        see test-sized chunks).
      * gpu kernel backend: small grids take the same log-shift ``dense``
        as TPU (the shift passes are coalesced row copies, the regime the
        reference's prefix-sum compaction runs in — arXiv 2012.09511);
        larger grids fall back to ``scatter``, which on CUDA is a real
        parallel scatter rather than the TPU's serialized one.
        PROVISIONAL until `bench.py pick_compact` rows land from a GPU
        session (scripts/gpu_session.sh stage 4).
      * other non-TPU backends: ``scatter`` is a fast gather-like op on
        CPU and sort LOSES ~2x (the original measured default)
        -> unchanged.
      * TPU, small grids (M*n <= 64k — the tuned PFSP M=1024 class): the
        log-shift passes are near-free and dodge the serialized scatter
        -> ``dense``.
      * TPU, no-prune PFSP regime (ub=inf warm-up): dense survivors
        -> ``dense``.
      * TPU, large pruned grids: survivors are sparse, so the
        S-proportional binary-search inverse does the least work
        -> ``search``.

    ``platform`` is the policy backend (`ops/backend.policy_backend`): the
    resolved kernel backend when it names real hardware, else the physical
    platform — so TTS_KERNEL_BACKEND=gpu exercises the gpu rows anywhere.
    """
    if getattr(problem, "name", None) == "nqueens":
        return "dense"
    if platform == "gpu":
        if M is not None and n is not None and M * n <= (1 << 16):
            return "dense"
        return "scatter"
    if platform != "tpu":
        return "scatter"
    if M is not None and n is not None and M * n <= (1 << 16):
        return "dense"
    from ..problems.base import INF_BOUND

    if getattr(problem, "initial_ub", 0) >= INF_BOUND:
        return "dense"
    return "search"


def resolve_compact_mode(problem=None, M: int | None = None,
                         n: int | None = None, device=None) -> str:
    """The resolved compaction mode a resident program bakes in: the
    explicit knob when set, else the ``auto`` policy.  Every input that
    shapes the decision is already part of the engines' program cache keys
    (problem instance, M, device), so a knob flip or shape change rebuilds
    instead of reusing a stale path."""
    mode = compact_mode()
    if mode != "auto":
        return mode
    from . import backend as BK

    return _auto_compact(problem, M, n, BK.policy_backend(device))


def _shift_left(x, s: int):
    """x shifted s positions toward index 0 along axis 0, zero-filled at
    the tail (a static concat+slice — never a gather)."""
    import jax.numpy as jnp

    pad = jnp.zeros((s,) + x.shape[1:], x.dtype)
    return jnp.concatenate([x[s:], pad], axis=0)


def shift_compact(dist, payloads: tuple):
    """Stable left-packing by LSB-first binary shifts.

    ``dist``: (L,) int32 — how far each element must move toward index 0
    (0 for non-survivors, ``index - rank`` for survivors; between
    consecutive survivors dist grows by exactly their index gap, which is
    what makes the per-bit shifts collision-free — see module docstring).
    ``payloads``: arrays with leading axis L, moved in lockstep.

    Invariant per round b (ascending): every survivor sits at
    ``index - (dist mod 2^(b+1))`` and survivor positions stay strictly
    increasing; a vacated position that nothing lands on is marked dead
    (dist 0) so its stale copy can never move again and shadow a live
    element.  After the last round, ranks 0..count-1 hold the survivors in
    order; everything past them is garbage (dead by the pool contract).
    """
    import jax.numpy as jnp

    L = dist.shape[0]
    for b in range(max(1, int(L - 1).bit_length())):
        s = 1 << b
        if s >= L:
            break
        sh_d = _shift_left(dist, s)
        take = (sh_d & s) != 0
        moving = (dist & s) != 0
        payloads = tuple(
            jnp.where(take.reshape((-1,) + (1,) * (p.ndim - 1)),
                      _shift_left(p, s), p)
            for p in payloads
        )
        dist = jnp.where(take, sh_d - s, jnp.where(moving, 0, dist))
    return payloads


def survivor_ranks(keep):
    """Hierarchical survivor ranks of a (M, n) keep mask — lane scan +
    per-parent prefix, much cheaper than a flat M*n cumsum.  Returns
    ``(ranks, tree_inc)``: ranks (M, n) int32 in (parent, slot) order and
    the survivor count."""
    import jax.numpy as jnp

    cnt = jnp.sum(keep, axis=1, dtype=jnp.int32)  # (M,)
    offs = jnp.cumsum(cnt) - cnt  # exclusive prefix
    lane = jnp.cumsum(keep.astype(jnp.int32), axis=1) - keep
    return offs[:, None] + lane, offs[-1] + cnt[-1]


def compact_ids(keep, S: int, mode: str):
    """Stream-compaction indices of the surviving (parent, slot) pairs.

    keep: (M, n) bool.  Returns (ids, tree_inc): ids (S,) int32 such that
    ids[s] = flat index i*n+k of the s-th survivor in (parent, slot) order
    for s < tree_inc (the reference's child push order,
    `pfsp_gpu_chpl.chpl:276-298`); rows past tree_inc resolve arbitrarily
    but stay in-bounds.  ``mode`` selects the rank inversion (module
    docstring); all modes return identical ids in identical order
    (pinned by tests/test_compaction.py and CI's per-mode tier-1 runs).
    """
    import jax.numpy as jnp

    M, n = keep.shape
    Mn = M * n
    ranks, tree_inc = survivor_ranks(keep)
    flat = keep.reshape(Mn)
    if mode == "dense":
        flat_idx = jnp.arange(Mn, dtype=jnp.int32)
        dist = jnp.where(flat, flat_idx - ranks.reshape(Mn), 0)
        (ids,) = shift_compact(dist, (flat_idx,))
        return ids[:S], tree_inc
    if mode == "sort":
        key = jnp.where(flat, ranks.reshape(Mn), jnp.int32(Mn))
        ids = jnp.argsort(key, stable=True)[:S].astype(jnp.int32)
        return ids, tree_inc
    if mode == "search":
        # Binary-search inverse: for output rank s, its parent is the last
        # p with offs[p] <= s (zero-count parents share the next parent's
        # offs, so side='right' skips them), and its slot is the lane
        # whose exclusive cumsum equals the within-parent rank. log2(M)
        # vectorized gather rounds + one (S, n) lane pass — no scatter, no
        # sort; the clips keep dead rows in-bounds.
        cnt = jnp.sum(keep, axis=1, dtype=jnp.int32)
        offs = jnp.cumsum(cnt) - cnt
        lane = ranks - offs[:, None]
        pos = jnp.arange(S, dtype=jnp.int32)
        parent = jnp.clip(
            jnp.searchsorted(offs, pos, side="right").astype(jnp.int32) - 1,
            0, M - 1,
        )
        r = pos - offs[parent]  # within-parent rank
        krows = keep[parent]  # (S, n)
        lane_s = lane[parent]  # (S, n) exclusive lane cumsum
        slot = jnp.argmax((lane_s == r[:, None]) & krows, axis=1)
        ids = (parent * n + slot).astype(jnp.int32)
        return ids, tree_inc
    if mode != "scatter":
        raise ValueError(f"unknown compaction mode {mode!r}")
    flat_idx = jnp.arange(Mn, dtype=jnp.int32)
    # Non-survivors get distinct out-of-bounds destinations so the scatter
    # is genuinely unique-indexed (mode="drop" discards them).
    dst = jnp.where(flat, ranks.reshape(Mn), S + flat_idx)
    ids = (
        jnp.zeros((S,), jnp.int32)
        .at[dst]
        .set(flat_idx, mode="drop", unique_indices=True)
    )
    return ids, tree_inc


# -- compiled-program contracts (`tts check`, analysis/contracts.py) --------
# The survivor-path performance claims, declared next to the code that
# makes them and verified over the whole knob matrix by
# analysis/program_audit.py (these used to live as one-off jaxpr pins in
# tests/test_compaction.py, each guarding a single knob combination).

from ..analysis.contracts import contract, prim_eqns  # noqa: E402


@contract(
    "dense-step-no-sort-scatter",
    claim="a resident step whose resolved survivor path is `dense` adds "
          "ZERO sort and ZERO scatter ops beyond the bound evaluator's "
          "own — compaction, fused push, and the overflow fallback branch "
          "are all shift/select-structured (searchsorted has no primitive "
          "of its own; this plus the gather ban in dense-ids-shift-only "
          "covers every lowering it could take).  The budget is the BARE "
          "evaluator's histogram (lb2's one-hot free-flag scatter is the "
          "evaluator's business), plus the armed phase profiler's "
          "accumulation into its own (NSLOTS+1,) clock block — anything "
          "else is survivor-path structure and is banned",
    artifact="resident-step",
)
def _contract_dense_step(art, cell):
    if art.prog.compact != "dense":
        return []
    from ..obs.phases import NSLOTS as _PH_NSLOTS

    out = []
    allowed = {
        n: c for n, c in art.eval_counts.items()
        if n == "sort" or n.startswith("scatter")
    }
    seen: dict[str, int] = {}
    armed = cell is not None and getattr(cell, "phaseprof", "0") == "1"
    for name, eqn in art.prims:
        if name != "sort" and not name.startswith("scatter"):
            continue
        sizes = [int(v.aval.size) for v in eqn.outvars]
        if armed and all(s <= _PH_NSLOTS + 1 for s in sizes):
            continue  # the sanctioned phase-clock block accumulation
        seen[name] = seen.get(name, 0) + 1
    for name, cnt in sorted(seen.items()):
        if cnt > allowed.get(name, 0):
            out.append(
                f"dense step contains {cnt}x {name} but the bare evaluator "
                f"accounts for {allowed.get(name, 0)} — the survivor path "
                "re-introduced a banned op"
            )
    return out


@contract(
    "dense-ids-shift-only",
    claim="the dense rank inversion (`compact_ids` mode='dense') is pure "
          "shifts + selects: no sort, no scatter, and not even a gather "
          "(the fused write performs the cycle's single gather)",
    artifact="compact-ids",
)
def _contract_dense_ids(art, cell):
    if art["mode"] != "dense":
        return []
    names = {n for n, _ in prim_eqns(art["jaxpr"])}
    bad = sorted(
        n for n in names
        if n in ("sort", "gather") or n.startswith("scatter")
    )
    return [f"dense compact_ids contains banned ops {bad}"] if bad else []


@contract(
    "scatter-ids-unique",
    claim="the scatter rank inversion's destination scatter is genuinely "
          "unique-indexed (XLA owes it no conflict resolution — the mode's "
          "whole cost model rests on that)",
    artifact="compact-ids",
)
def _contract_scatter_ids(art, cell):
    if art["mode"] != "scatter":
        return []
    scatters = [
        (n, e) for n, e in prim_eqns(art["jaxpr"]) if n.startswith("scatter")
    ]
    if not scatters:
        return ["scatter mode lowered without any scatter op"]
    bad = [
        n for n, e in scatters if not e.params.get("unique_indices", False)
    ]
    return (
        [f"non-unique-indexed scatter(s) in scatter compact_ids: {bad}"]
        if bad else []
    )


@contract(
    "compact-auto-identity",
    claim="TTS_COMPACT=auto bakes in a byte-identical program to the "
          "explicitly spelled mode it resolves to — the policy layer adds "
          "zero behavior of its own",
    artifact="variants",
)
def _contract_auto_identity(art, cell):
    explicit = [
        lb for lb in art.variants
        if lb.startswith("compact-") and lb != "compact-auto"
    ]
    if "compact-auto" not in art.variants or not explicit:
        return []  # variant set traced without the compact labels
    out = []
    for lb in explicit:
        if art.text("compact-auto") != art.text(lb):
            out.append(
                f"auto-resolved program differs from explicit {lb[8:]!r}"
            )
    return out
