"""Device kernels (vectorized XLA + Pallas) for batched node evaluation."""
