"""Batched PFSP lower-bound kernels for TPU (vectorized XLA).

TPU-first reformulation of the reference's per-thread CUDA bound kernels
(`baselines/pfsp/lib/c_bounds_gpu.cu`, `baselines/pfsp/lib/evaluate.cu:25-91`;
Chapel: `pfsp_gpu_chpl.chpl:192-254`). Instead of one SIMT thread per
(parent, child) running scalar loops, each chunk is evaluated as dense
integer tensor algebra over a ``(B, J)`` lane grid (B parents x J child
slots), which XLA tiles onto the VPU:

  * Forward branching fixes ``limit2 == jobs`` (`pfsp_chpl.chpl:23-26`), so
    ``schedule_back`` is always the constant ``min_tails`` table — no tail
    scans at all.
  * A child's head schedule is one ``add_forward`` step from its parent's
    (`c_bound_simple.c:31-38` applied incrementally), so the kernel scans the
    parent prefix once (O(n) steps of (B, m) vector work) and then does a
    single unrolled O(m) update per child slot.
  * The Johnson two-machine recurrence
        tmp0_t = tmp0_{t-1} + p0_t
        tmp1_t = max(tmp1_{t-1}, tmp0_t + lag_t) + p1_t          (c_bound_johnson.c:190-209)
    is a max-plus scan whose closed form is
        tmp1_n = max( tmp1_0 + sum(p1),  max_t [ tmp0_t + lag_t + suffix_sum(p1)_t ] )
    i.e. prefix sums + suffix sums + a max reduction — log-depth parallel
    work instead of a sequential per-thread loop. The data-dependent early
    exit (`c_bound_johnson.c:231-234`) is dropped: on TPU a masked full
    reduction is cheaper than divergent control flow, and the host-side
    pruning decision `bound < best` is provably identical either way (an
    early-exited value exceeds best iff the full value does).

All arithmetic is int32 (bounds fit comfortably; max makespan < 2^31).
"""

from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = jnp.int32(-(2**30))


def _add_forward_batched(front, pt_job):
    """One add_forward step over arbitrary leading axes.

    front: (..., m), pt_job: (..., m) processing times of the appended job.
    Returns the child front. Unrolled over machines (m is small & static).
    """
    m = front.shape[-1]
    cols = [front[..., 0] + pt_job[..., 0]]
    for j in range(1, m):
        cols.append(jnp.maximum(cols[-1], front[..., j]) + pt_job[..., j])
    return jnp.stack(cols, axis=-1)


def _machine_bound_from_parts(front, back, remain):
    """Vectorized `machine_bound_from_parts` (`c_bound_simple.c:126-141`).

    front/remain: (..., m); back: broadcastable (m,). Returns (...,).
    """
    m = front.shape[-1]
    tmp0 = front[..., 0] + remain[..., 0]
    lb = tmp0 + back[..., 0]
    for i in range(1, m):
        tmp1 = jnp.maximum(tmp0, front[..., i] + remain[..., i])
        lb = jnp.maximum(lb, tmp1 + back[..., i])
        tmp0 = tmp1
    return lb


def gather_ptimes(prmu, ptm_t, exact_bf16: bool = False):
    """Per-position processing times ``ptg[b, i, :] = ptm_t[prmu[b, i]]``.

    For small job counts this is a one-hot matmul instead of a gather: the
    MXU evaluates it far faster than TPU dynamic gathers, and it is exact
    (one-hot rows select a single int value). ``exact_bf16=True`` (set when
    every processing time < 256, i.e. all Taillard instances — times are
    1..99, `c_taillard.c:84`) runs it as a single-pass bf16 x bf16 -> f32
    matmul: 0/1 one-hot rows and ints < 2^8 are exactly representable in
    bf16 and the accumulation is f32, so the result is bit-identical to the
    f32 HIGHEST path at a third or less of the MXU cost. Larger instances
    fall back to the gather (the (B, n, n) one-hot would dominate memory:
    at n=50 and a 64k chunk it is already ~650 MB).
    """
    n = prmu.shape[-1]
    if n <= 32:
        dt = jnp.bfloat16 if exact_bf16 else jnp.float32
        # f32 needs HIGHEST (the TPU default single bf16 pass would round
        # ints > 256); the gated bf16 single pass is already exact.
        prec = None if exact_bf16 else jax.lax.Precision.HIGHEST
        oh = jax.nn.one_hot(prmu, n, dtype=dt)  # (B, n, n)
        return jnp.einsum(
            "bkj,jm->bkm", oh, ptm_t.astype(dt),
            preferred_element_type=jnp.float32,
            precision=prec,
        ).astype(jnp.int32)
    return ptm_t[prmu]


def _parent_state(prmu, limit1, ptm_t, min_heads, bf16: bool = False):
    """Shared per-parent precomputation for a chunk.

    prmu: (B, n) int32; limit1: (B,) int32; ptm_t: (n, m) int32 (transposed
    processing times); min_heads: (m,).

    Returns (front, remain, ptg, unsched) where
      front:   (B, m) = schedule_front(prmu, limit1)   (c_bound_simple.c:51-69)
      remain:  (B, m) = sum_unscheduled(prmu, limit1, n) (c_bound_simple.c:108-124)
      ptg:     (B, n, m) processing times gathered per position
      unsched: (B, n) 1.0 where position is free (pos >= limit1 + 1)
    """
    B, n = prmu.shape
    ptg = gather_ptimes(prmu, ptm_t, bf16)  # (B, n, m)
    pos = jnp.arange(n, dtype=jnp.int32)[None, :]
    unsched = (pos >= limit1[:, None] + 1).astype(jnp.int32)  # (B, n)

    def body(i, front):
        newf = _add_forward_batched(front, ptg[:, i, :])
        take = (i <= limit1)[:, None]
        return jnp.where(take, newf, front)

    # Derive the zero init from ptg (not jnp.zeros) so the carry inherits
    # ptg's varying-manual-axes type under shard_map (scan-vma rule).
    front0 = ptg[:, 0, :] * 0
    front = jax.lax.fori_loop(0, n, body, front0)
    # schedule_front(-1) returns min_heads (c_bound_simple.c:58-61); only the
    # root ever hits this, but keep parity.
    front = jnp.where((limit1 == -1)[:, None], min_heads[None, :], front)
    remain = jnp.sum(ptg * unsched[:, :, None], axis=1)  # (B, m)
    return front, remain, ptg, unsched


@partial(jax.jit, static_argnames=("bf16",))
def _lb1_chunk(prmu, limit1, ptm_t, min_heads, min_tails, bf16: bool = False):
    """Bounds of every child of every parent under lb1.

    Child slot (i, k), k >= limit1+1: full `lb1_bound` of the child whose
    prefix is the parent's plus the job at position k
    (`pfsp_gpu_chpl.chpl:192-208` / `evaluate.cu:25-49`). Returns (B, n)
    int32; slots k <= limit1 hold garbage (never read by the host, matching
    the reference's untouched-slot convention, SURVEY.md Appendix A).
    """
    front, remain, ptg, _ = _parent_state(prmu, limit1, ptm_t, min_heads, bf16)
    # Child k appends job prmu[:, k]: one add_forward step per slot.
    child_front = _add_forward_batched(front[:, None, :], ptg)  # (B, n, m)
    child_remain = remain[:, None, :] - ptg  # (B, n, m)
    return _machine_bound_from_parts(child_front, min_tails[None, None, :], child_remain)


@partial(jax.jit, static_argnames=("bf16",))
def _lb1_d_chunk(prmu, limit1, ptm_t, min_heads, min_tails, bf16: bool = False):
    """Bounds of every child under lb1_d (`add_front_and_bound`,
    `c_bound_simple.c:213-244`; device: `pfsp_gpu_chpl.chpl:216-235` /
    `evaluate.cu:51-71`): O(m) per child from the parent's front/remain,
    weaker than lb1's full chain but one pass for all children.
    """
    front, remain, ptg, _ = _parent_state(prmu, limit1, ptm_t, min_heads, bf16)
    m = front.shape[-1]
    back = min_tails
    f = front[:, None, :]  # (B, 1, m)
    r = remain[:, None, :]
    lb = f[..., 0] + r[..., 0] + back[0]  # (B, 1) -> broadcasts to (B, n)
    tmp0 = f[..., 0] + ptg[..., 0]  # (B, n)
    for i in range(1, m):
        tmp1 = jnp.maximum(tmp0, f[..., i])
        lb = jnp.maximum(lb, tmp1 + r[..., i] + back[i])
        tmp0 = tmp1 + ptg[..., i]
    return lb


def _pad_pair_tables(pairs, lags, scheds, Pb: int):
    """Pad the (P, ...) pair tables to a multiple of ``Pb`` with copies of
    pair 0 (max over pairs is idempotent, so duplicates only re-max the same
    value). Static shapes in, static shapes out — safe on traced arrays
    (the mp-sharded paths pass dynamic slices)."""
    P = pairs.shape[0]
    reps = -(-P // Pb) * Pb - P
    # tts-lint: waive tracer-branch -- reps is a Python int (static shape P and the static_argnames-bound Pb); the branch picks a padded vs unpadded program shape
    if reps:
        pairs = jnp.concatenate([pairs, jnp.repeat(pairs[:1], reps, 0)])
        lags = jnp.concatenate([lags, jnp.repeat(lags[:1], reps, 0)])
        scheds = jnp.concatenate([scheds, jnp.repeat(scheds[:1], reps, 0)])
    return pairs, lags, scheds


def _johnson_block_tables(pairs_b, lags_b, sched_b, ptm, min_tails):
    """Slot-ordered Johnson tables of one pair block, derived in-trace.

    pairs_b (Pb, 2), lags_b/sched_b (Pb, n), ptm (m, n) machine-major.
    Returns p0_o/p1_o/lag_o (Pb, n) — the value of the t-th job of each
    pair's Johnson schedule — plus tails0/tails1 (Pb,). The per-block gather
    is tiny (Pb x n) next to the (B, ..., Pb, n) batch tensors it feeds.
    """
    p0_o = jnp.take_along_axis(ptm[pairs_b[:, 0]], sched_b, axis=1)
    p1_o = jnp.take_along_axis(ptm[pairs_b[:, 1]], sched_b, axis=1)
    lag_o = jnp.take_along_axis(lags_b, sched_b, axis=1)
    return p0_o, p1_o, lag_o, min_tails[pairs_b[:, 0]], min_tails[pairs_b[:, 1]]


@partial(jax.jit, static_argnames=("bf16", "pairblock"))
def _lb2_chunk(
    prmu,
    limit1,
    ptm_t,
    min_heads,
    min_tails,
    pairs,
    lags,
    johnson_schedules,
    bf16: bool = False,
    pairblock: int = 1,
):
    """Bounds of every child under lb2 (`c_bound_johnson.c:239-254`; device:
    `pfsp_gpu_chpl.chpl:238-254` / `evaluate.cu:73-91`).

    Per child (i, k) and machine pair (ma0, ma1): the Johnson cmax of the
    free jobs with lags, via the closed-form max-plus scan (module
    docstring).

    ``pairblock`` (static) batches the machine-pair axis: ``Pb`` pairs are
    evaluated at once as an extra leading tensor axis over the slot-ordered
    tables and max-reduced within the block; the running max carries across
    the statically-unrolled blocks, so the compiled program contains NO
    serial per-pair loop (the reference serializes exactly this loop,
    `Bound_johnson.chpl:188-239`). ``pairblock=1`` keeps the original
    serial ``fori_loop`` (the degenerate old behavior, still used by the
    jaxpr-pin regression tests).

    Shapes: pairs (P, 2), lags/johnson_schedules (P, n).
    """
    B, n = prmu.shape
    front, remain_unused, ptg, unsched = _parent_state(
        prmu, limit1, ptm_t, min_heads, bf16
    )
    del remain_unused
    child_front = _add_forward_batched(front[:, None, :], ptg)  # (B, n, m)

    # Free-job indicator per child, by job id: parent's free jobs minus the
    # one the child schedules (set_flags, c_bound_johnson.c:180-188, inverted).
    u_parent = jnp.zeros((B, n), dtype=jnp.int32)
    bidx = jnp.arange(B, dtype=jnp.int32)[:, None]
    u_parent = u_parent.at[bidx, prmu].set(unsched)  # by job id
    onehot_child = jax.nn.one_hot(prmu, n, dtype=jnp.int32)  # (B, n_slots, n_jobs)
    u_child = u_parent[:, None, :] * (1 - onehot_child)  # (B, k, job)

    P = pairs.shape[0]
    ptm = ptm_t.T  # (m, n)
    # Zero init derived from varying operands (not jnp.zeros) so the carry
    # type matches under shard_map along both dp (prmu) and mp (lags) axes.
    lb0 = prmu * 0 + 0 * jnp.min(lags).astype(jnp.int32)

    if pairblock > 1:
        Pb = min(pairblock, P)
        pairs, lags, johnson_schedules = _pad_pair_tables(
            pairs, lags, johnson_schedules, Pb
        )

        def block(lb, pairs_b, lags_b, sched_b):
            p0_o, p1_o, lag_o, tl0, tl1 = _johnson_block_tables(
                pairs_b, lags_b, sched_b, ptm, min_tails
            )
            u_o = u_child[:, :, sched_b]  # (B, k, Pb, n) ordered free flags
            mp0 = u_o * p0_o[None, None]
            mp1 = u_o * p1_o[None, None]
            f0 = jnp.take(child_front, pairs_b[:, 0], axis=2)  # (B, k, Pb)
            f1 = jnp.take(child_front, pairs_b[:, 1], axis=2)
            t0 = f0[..., None] + jnp.cumsum(mp0, axis=-1)
            suf1 = jnp.cumsum(mp1[..., ::-1], axis=-1)[..., ::-1]
            a = jnp.where(u_o > 0, t0 + lag_o[None, None] + suf1, NEG_INF)
            tmp1 = jnp.maximum(f1 + jnp.sum(mp1, axis=-1), jnp.max(a, axis=-1))
            tmp0 = f0 + jnp.sum(mp0, axis=-1)
            pair_lb = jnp.maximum(tmp1 + tl1[None, None], tmp0 + tl0[None, None])
            return jnp.maximum(lb, jnp.max(pair_lb, axis=-1))

        lb = lb0
        for b in range(pairs.shape[0] // Pb):
            sl = slice(b * Pb, (b + 1) * Pb)
            lb = block(lb, pairs[sl], lags[sl], johnson_schedules[sl])
        return lb

    def pair_body(q, lb):
        ma0 = pairs[q, 0]
        ma1 = pairs[q, 1]
        sched = johnson_schedules[q]  # (n,) job ids in Johnson order
        lag_o = lags[q][sched]  # (n,) lag per ordered slot
        p0_o = jnp.take(ptm, ma0, axis=0)[sched]  # (n,)
        p1_o = jnp.take(ptm, ma1, axis=0)[sched]
        u_o = jnp.take(u_child, sched, axis=2)  # (B, k, n) ordered free flags
        mp0 = u_o * p0_o[None, None, :]
        mp1 = u_o * p1_o[None, None, :]
        tmp0_0 = jnp.take_along_axis(child_front, jnp.broadcast_to(ma0, (B, n, 1)), axis=2)[..., 0]
        tmp1_0 = jnp.take_along_axis(child_front, jnp.broadcast_to(ma1, (B, n, 1)), axis=2)[..., 0]
        t0 = tmp0_0[:, :, None] + jnp.cumsum(mp0, axis=-1)  # running tmp0 at slot t
        suf1 = (
            jnp.cumsum(mp1[..., ::-1], axis=-1)[..., ::-1]
        )  # suffix sum of p1 from t inclusive
        a = jnp.where(u_o > 0, t0 + lag_o[None, None, :] + suf1, NEG_INF)
        tmp1 = jnp.maximum(
            tmp1_0 + jnp.sum(mp1, axis=-1), jnp.max(a, axis=-1)
        )
        tmp0 = tmp0_0 + jnp.sum(mp0, axis=-1)
        pair_lb = jnp.maximum(tmp1 + min_tails[ma1], tmp0 + min_tails[ma0])
        return jnp.maximum(lb, pair_lb)

    return jax.lax.fori_loop(0, P, pair_body, lb0)


class PFSPDeviceTables:
    """Instance tables placed on device once per search
    (`pfsp_gpu_chpl.chpl:362-371`: device-resident lbound1/lbound2 copies).

    ``johnson_ordered()`` additionally derives the Johnson data in *schedule
    order* per machine pair (`p0_o/p1_o/lag_o[q, t]` = value of the t-th job
    of pair q's Johnson schedule) plus a per-pair permutation one-hot, so the
    Pallas lb2 kernel reorders the per-child free-job flags with one small
    matmul instead of a runtime gather chain. Built lazily: the dense
    (P, n, n) one-hot is only worth its memory when that kernel runs.
    """

    def __init__(self, lb1_data, lb2_data):
        self.ptm_t = jnp.asarray(np.ascontiguousarray(lb1_data.p_times.T), dtype=jnp.int32)
        # Single-pass bf16 MXU gathers are exact iff every time < 2^8
        # (true for all Taillard instances: times are 1..99).
        self.exact_bf16 = bool(int(np.max(lb1_data.p_times)) < 256)
        self.min_heads = jnp.asarray(lb1_data.min_heads, dtype=jnp.int32)
        self.min_tails = jnp.asarray(lb1_data.min_tails, dtype=jnp.int32)
        self.pairs = jnp.asarray(lb2_data.pairs, dtype=jnp.int32)
        self.lags = jnp.asarray(lb2_data.lags, dtype=jnp.int32)
        self.johnson_schedules = jnp.asarray(lb2_data.johnson_schedules, dtype=jnp.int32)

    def mp_padded(self, mp_size: int):
        """(pairs, lags, johnson_schedules) padded to a multiple of
        ``mp_size`` with copies of pair 0 (max over pairs is idempotent, so
        duplicates only re-max the same value). Cached per mp_size.

        The cache holds NUMPY arrays, never jnp: this method is called
        inside shard_map traces (lb2_bounds_mp / lb2_self_bounds_mp), and
        a jnp constant created during trace A would be cached as a tracer
        that leaks into trace B — observed as an UnexpectedTracerError when
        two virtual hosts build their mesh programs from one shared tables
        object. Numpy re-lifts to a fresh constant in every trace."""
        cache = getattr(self, "_mp_padded", None)
        if cache is None:
            cache = self._mp_padded = {}
        if mp_size not in cache:
            pairs = np.asarray(self.pairs)
            lags = np.asarray(self.lags)
            scheds = np.asarray(self.johnson_schedules)
            P = pairs.shape[0]
            Pp = -(-P // mp_size) * mp_size
            if Pp != P:
                reps = Pp - P
                pairs = np.concatenate([pairs, np.repeat(pairs[:1], reps, 0)])
                lags = np.concatenate([lags, np.repeat(lags[:1], reps, 0)])
                scheds = np.concatenate(
                    [scheds, np.repeat(scheds[:1], reps, 0)]
                )
            cache[mp_size] = (pairs, lags, scheds)
        return cache[mp_size]

    def _build_ordered(self, pairs, lags, sched):
        # NUMPY fields only (same tracer-leak hazard as mp_padded: these
        # builders run inside shard_map traces, and caching a trace-created
        # jnp constant poisons every later trace).
        ptm = np.asarray(self.ptm_t).T  # (m, n)
        P, n = sched.shape
        rows = np.arange(P)[:, None]
        tails = np.asarray(self.min_tails)
        jorder = np.zeros((P, n, n), dtype=np.float32)
        jorder[rows, np.arange(n)[None, :], sched] = 1.0

        class _Ordered:
            pass

        o = _Ordered()
        o.p0_o = ptm[pairs[:, 0][:, None], sched].astype(np.int32)
        o.p1_o = ptm[pairs[:, 1][:, None], sched].astype(np.int32)
        o.lag_o = lags[rows, sched].astype(np.int32)
        o.tails0 = tails[pairs[:, 0]].astype(np.int32)
        o.tails1 = tails[pairs[:, 1]].astype(np.int32)
        o.jorder = jorder
        # (P, m) one-hot machine selectors: the Pallas kernel reads row q
        # and contracts it against the child fronts instead of dynamically
        # slicing a VMEM value along the machine (lane) axis.
        m = ptm.shape[0]
        eye = np.eye(m, dtype=np.float32)
        o.msel0 = eye[pairs[:, 0]]
        o.msel1 = eye[pairs[:, 1]]
        return o

    def johnson_ordered(self):
        if not hasattr(self, "_johnson_ordered"):
            self._johnson_ordered = self._build_ordered(
                np.asarray(self.pairs), np.asarray(self.lags),
                np.asarray(self.johnson_schedules),
            )
        return self._johnson_ordered

    def johnson_ordered_device(self, pad_to: int = 1):
        """Device-resident copy of the ordered tables for EAGER (un-jitted)
        kernel calls — without it every eager lb2 evaluation would pay a
        fresh host->device transfer of all eight arrays (the (P, n, n)
        jorder alone is MBs). ``pad_to``: pair axis padded to this multiple
        (the Pallas pair-group unroll), cached per multiple. Callers must
        only invoke this OUTSIDE a trace (`_eager_context()`), so the
        cache can never capture a tracer; traced callers keep the numpy
        tables, which bake into the executable as constants."""
        cache = getattr(self, "_johnson_ordered_dev", None)
        if cache is None:
            cache = self._johnson_ordered_dev = {}
        if pad_to not in cache:
            o = self.johnson_ordered_mp(pad_to)

            class _Dev:
                pass

            d = _Dev()
            for f in ("p0_o", "p1_o", "lag_o", "tails0", "tails1",
                      "msel0", "msel1", "jorder"):
                setattr(d, f, jnp.asarray(getattr(o, f)))
            cache[pad_to] = d
        return cache[pad_to]

    def johnson_ordered_mp(self, mp_size: int):
        """Ordered tables over the mp-padded pair set (P rounded up to a
        multiple of ``mp_size`` with copies of pair 0 — max over pairs is
        idempotent), so each mp shard can slice its contiguous P/mp block.
        Cached per mp_size."""
        if self.pairs.shape[0] % mp_size == 0:
            return self.johnson_ordered()  # no padding needed: share
        cache = getattr(self, "_johnson_ordered_mp", None)
        if cache is None:
            cache = self._johnson_ordered_mp = {}
        if mp_size not in cache:
            pairs, lags, scheds = self.mp_padded(mp_size)
            cache[mp_size] = self._build_ordered(
                np.asarray(pairs), np.asarray(lags), np.asarray(scheds)
            )
        return cache[mp_size]


def lb1_bounds(prmu, limit1, tables: "PFSPDeviceTables", device=None):
    """lb1 chunk bounds, routed per target device: Pallas kernel on TPU
    (VMEM-resident tile pass, `ops/pallas_kernels.py`), the jnp/XLA oracle
    elsewhere (cf. the reference's per-device dispatcher,
    `evaluate.cu:93-119`)."""
    from . import pallas_kernels as PK

    # The kernel covers every Taillard size (20-500 jobs): _auto_tile shrinks
    # the batch tile as n grows; shapes that cannot fit VMEM even at the
    # smallest tile stay on the jnp oracle. Demoted by default — the fused
    # jnp path measured ~7x faster in-kernel on chip (docs/HW_VALIDATION.md
    # decision record); TTS_PALLAS=force re-arms it for the A/B.
    n, m = prmu.shape[-1], tables.ptm_t.shape[1]
    kb = _kernel_kind(device)
    if (PK.use_pallas(device) and PK.lb1_pallas_enabled() and n <= 512
            and PK.lb1_kernel_feasible(n, m, backend=kb)):
        return PK.pfsp_lb1_bounds(
            prmu, limit1, tables.ptm_t, tables.min_heads, tables.min_tails,
            bf16=tables.exact_bf16, backend=kb,
        )
    return _lb1_chunk(prmu, limit1, tables.ptm_t, tables.min_heads,
                      tables.min_tails, bf16=tables.exact_bf16)


def lb1_d_bounds(prmu, limit1, tables: "PFSPDeviceTables", device=None):
    """lb1_d chunk bounds, routed like ``lb1_bounds``
    (`evaluate.cu:51-71` is the per-parent CUDA counterpart)."""
    from . import pallas_kernels as PK

    n, m = prmu.shape[-1], tables.ptm_t.shape[1]
    kb = _kernel_kind(device)
    if (PK.use_pallas(device) and PK.lb1_pallas_enabled() and n <= 512
            and PK.lb1_kernel_feasible(n, m, backend=kb)):
        return PK.pfsp_lb1_d_bounds(
            prmu, limit1, tables.ptm_t, tables.min_heads, tables.min_tails,
            bf16=tables.exact_bf16, backend=kb,
        )
    return _lb1_d_chunk(
        prmu, limit1, tables.ptm_t, tables.min_heads, tables.min_tails,
        bf16=tables.exact_bf16,
    )


def _kernel_kind(device=None) -> str:
    """The kernel flavor the seam resolves for this device
    (`ops/backend.kernel_kind`) — 'gpu' only when the resolved backend is
    gpu, else the TPU flavor of record (so off-gpu routing stays
    byte-identical)."""
    from . import backend as BK

    return BK.kernel_kind(device)


def _lb2_pallas_enabled() -> bool:
    """Per-family kill switch: TTS_PALLAS_LB2=0 routes ONLY the lb2-family
    kernels (child + self) to the jnp path while the hardware-proven
    lb1-family kernels stay on Pallas — so an lb2 compile failure costs the
    lb2 extras, never the headline lb1 number (bench.py probes the
    families in separate subprocesses and sets this on an lb2-only
    failure)."""
    import os

    return os.environ.get("TTS_PALLAS_LB2", "1") != "0"


def _auto_pairblock(P: int, n: int, backend: str | None = None) -> int:
    """Auto pair-block policy: the largest power-of-two block whose
    per-(row, child) working set stays near ~2048 ordered-slot lanes
    (``Pb * n``), clamped to the pair count. At the published shapes this
    gives Pb = P at ta014 (n=20, P=45 — a single block, loop-free) and
    Pb = 64 at ta021 (P=190 — three unrolled blocks); 500-job instances
    fall to Pb = 4 so the (B, n, Pb, n) intermediates keep fitting.

    The gpu row halves the lane target (~1024): the Triton kernels hold
    the per-pair group's live values in registers/shared memory per CUDA
    block rather than a chip-wide VMEM, and the reference tunes its pair
    batching to that budget (arXiv 2012.09511). PROVISIONAL until a GPU
    session banks measured rows. ``backend=None`` resolves the seam
    (`ops/backend.policy_backend`) — off-gpu this is the 2048 row
    verbatim."""
    if backend is None:
        from . import backend as BK

        backend = BK.policy_backend(None)
    lanes = 1024 if backend == "gpu" else 2048
    per = max(4, lanes // max(1, n))
    pb = 4
    # tts-lint: waive tracer-branch -- pure host policy on Python ints; P and n are static shapes at every call site (traced callers resolve the knob before tracing)
    while pb * 2 <= per:
        pb *= 2
    return max(1, min(P, pb))


def lb2_pairblock(P: int, n: int, backend: str | None = None) -> int:
    """Resolved lb2 pair-block size for a (P pairs, n jobs) shape.

    ``TTS_LB2_PAIRBLOCK`` / ``--lb2-pairblock``: ``auto`` (default) applies
    `_auto_pairblock` (backend-keyed — see its gpu row); an explicit
    positive integer forces the block size (``1`` = the serial per-pair
    fori_loop, the pre-blocking behavior; values above P clamp to P).
    Baked into compiled programs at trace time, so `routing_cache_token`
    carries the resolved value."""
    import os

    knob = os.environ.get("TTS_LB2_PAIRBLOCK", "auto")
    if knob == "auto":
        return _auto_pairblock(P, n, backend)
    try:
        v = int(knob)
    except ValueError:
        raise ValueError(
            "TTS_LB2_PAIRBLOCK must be 'auto' or a positive integer, got "
            f"{knob!r}"
        ) from None
    if v < 1:
        raise ValueError(
            f"TTS_LB2_PAIRBLOCK must be >= 1 (got {v}); 1 is the serial "
            "per-pair loop"
        )
    return min(v, P)


def lb2_kernel_pair_group(P: int, n: int, backend: str | None = None) -> int:
    """Pair-group unroll of the Pallas lb2 kernels: the same knob, capped
    at 8 — the kernel VMEM model charges the per-pair live values once per
    unrolled group member (`pallas_kernels._model_bytes`), and 8 is the
    largest group whose modeled footprint keeps MXU-efficient batch tiles
    at the published shapes."""
    return min(lb2_pairblock(P, n, backend), 8)


def lb2_bounds(prmu, limit1, tables: "PFSPDeviceTables", device=None):
    """lb2 chunk bounds, routed like ``lb1_bounds``. The Pallas kernel keeps
    the whole Johnson pair loop in VMEM — the jnp path's per-pair (B, n, n)
    intermediates round-trip HBM, which dominates its cost."""
    from . import pallas_kernels as PK

    # lb2's (P, n, n) slot-order tables cap the kernel at ~100 jobs
    # (ta031-ta090); beyond that the jnp path has the same asymptotic cost.
    n, m = prmu.shape[-1], tables.ptm_t.shape[1]
    P = tables.pairs.shape[0]
    kb = _kernel_kind(device)
    if (PK.use_pallas(device) and _lb2_pallas_enabled() and n <= 100
            and PK.lb2_kernel_feasible(n, m, P, backend=kb)):
        return PK.pfsp_lb2_bounds(
            prmu, limit1, tables,
            pair_group=lb2_kernel_pair_group(P, n, kb), backend=kb,
        )
    return _lb2_chunk(
        prmu, limit1, tables.ptm_t, tables.min_heads, tables.min_tails,
        tables.pairs, tables.lags, tables.johnson_schedules,
        bf16=tables.exact_bf16, pairblock=lb2_pairblock(P, n),
    )


@partial(jax.jit, static_argnames=("bf16", "pairblock"))
def _lb2_self_chunk(
    prmu,
    limit1,
    ptm_t,
    min_heads,
    min_tails,
    pairs,
    lags,
    johnson_schedules,
    bf16: bool = False,
    pairblock: int = 1,
):
    """lb2 of each ROW as a node (not of its children): the Johnson bound of
    the row's own partial schedule (`lb2_bound`, `c_bound_johnson.c:239-254`
    applied to the node itself). The staged evaluator feeds compacted child
    rows here — same closed-form max-plus scan as `_lb2_chunk` with the
    child-expansion axis dropped, and the same ``pairblock`` batching of
    the machine-pair axis. Returns (R,) int32."""
    R, n = prmu.shape
    front, _, ptg, unsched = _parent_state(prmu, limit1, ptm_t, min_heads, bf16)
    # Free flags by job id for the row itself.
    u = jnp.zeros((R, n), dtype=jnp.int32)
    ridx = jnp.arange(R, dtype=jnp.int32)[:, None]
    u = u.at[ridx, prmu].set(unsched)  # (R, job)

    P = pairs.shape[0]
    ptm = ptm_t.T  # (m, n)
    lb0 = prmu[:, 0] * 0 + 0 * jnp.min(lags).astype(jnp.int32)

    if pairblock > 1:
        Pb = min(pairblock, P)
        pairs, lags, johnson_schedules = _pad_pair_tables(
            pairs, lags, johnson_schedules, Pb
        )

        def block(lb, pairs_b, lags_b, sched_b):
            p0_o, p1_o, lag_o, tl0, tl1 = _johnson_block_tables(
                pairs_b, lags_b, sched_b, ptm, min_tails
            )
            u_o = u[:, sched_b]  # (R, Pb, n) ordered free flags
            mp0 = u_o * p0_o[None]
            mp1 = u_o * p1_o[None]
            f0 = jnp.take(front, pairs_b[:, 0], axis=1)  # (R, Pb)
            f1 = jnp.take(front, pairs_b[:, 1], axis=1)
            t0 = f0[..., None] + jnp.cumsum(mp0, axis=-1)
            suf1 = jnp.cumsum(mp1[..., ::-1], axis=-1)[..., ::-1]
            a = jnp.where(u_o > 0, t0 + lag_o[None] + suf1, NEG_INF)
            tmp1 = jnp.maximum(f1 + jnp.sum(mp1, axis=-1), jnp.max(a, axis=-1))
            tmp0 = f0 + jnp.sum(mp0, axis=-1)
            pair_lb = jnp.maximum(tmp1 + tl1[None], tmp0 + tl0[None])
            return jnp.maximum(lb, jnp.max(pair_lb, axis=-1))

        lb = lb0
        for b in range(pairs.shape[0] // Pb):
            sl = slice(b * Pb, (b + 1) * Pb)
            lb = block(lb, pairs[sl], lags[sl], johnson_schedules[sl])
        return lb

    def pair_body(q, lb):
        ma0 = pairs[q, 0]
        ma1 = pairs[q, 1]
        sched = johnson_schedules[q]
        lag_o = lags[q][sched]
        p0_o = jnp.take(ptm, ma0, axis=0)[sched]
        p1_o = jnp.take(ptm, ma1, axis=0)[sched]
        u_o = jnp.take(u, sched, axis=1)  # (R, n) ordered free flags
        mp0 = u_o * p0_o[None, :]
        mp1 = u_o * p1_o[None, :]
        tmp0_0 = jnp.take_along_axis(
            front, jnp.broadcast_to(ma0, (R, 1)), axis=1
        )  # (R, 1)
        tmp1_0 = jnp.take_along_axis(front, jnp.broadcast_to(ma1, (R, 1)), axis=1)
        t0 = tmp0_0 + jnp.cumsum(mp0, axis=-1)
        suf1 = jnp.cumsum(mp1[:, ::-1], axis=-1)[:, ::-1]
        a = jnp.where(u_o > 0, t0 + lag_o[None, :] + suf1, NEG_INF)
        tmp1 = jnp.maximum(
            tmp1_0[:, 0] + jnp.sum(mp1, axis=-1), jnp.max(a, axis=-1)
        )
        tmp0 = tmp0_0[:, 0] + jnp.sum(mp0, axis=-1)
        pair_lb = jnp.maximum(tmp1 + min_tails[ma1], tmp0 + min_tails[ma0])
        return jnp.maximum(lb, pair_lb)

    return jax.lax.fori_loop(0, P, pair_body, lb0)


def lb2_self_bounds(prmu, limit1, n_active, tables: "PFSPDeviceTables",
                    device=None):
    """Self lb2 of (R, n) node rows; rows >= ``n_active`` return garbage.
    On TPU the Pallas kernel skips whole inactive tiles (the
    incumbent-driven work reduction the reference gets from its per-thread
    early exit, `evaluate.cu:73-91`); the jnp oracle evaluates everything."""
    from . import pallas_kernels as PK

    n, m = prmu.shape[-1], tables.ptm_t.shape[1]
    P = tables.pairs.shape[0]
    kb = _kernel_kind(device)
    if (PK.use_pallas(device) and _lb2_pallas_enabled() and n <= 100
            and PK.lb2_self_kernel_feasible(n, m, P, backend=kb)):
        return PK.pfsp_lb2_self_bounds(
            prmu, limit1, n_active, tables,
            pair_group=lb2_kernel_pair_group(P, n, kb), backend=kb,
        )
    return _lb2_self_chunk(
        prmu, limit1, tables.ptm_t, tables.min_heads, tables.min_tails,
        tables.pairs, tables.lags, tables.johnson_schedules,
        bf16=tables.exact_bf16, pairblock=lb2_pairblock(P, n),
    )


class _OrderedSlice:
    """Per-shard view of the Johnson-ordered tables: each field is a traced
    ``dynamic_slice`` of the mp-padded full table along the pair axis."""

    _FIELDS = ("p0_o", "p1_o", "lag_o", "tails0", "tails1", "msel0", "msel1",
               "jorder")

    def __init__(self, full, start, P_local: int):
        for f in self._FIELDS:
            arr = getattr(full, f)
            setattr(self, f, jax.lax.dynamic_slice_in_dim(
                arr, start, P_local, axis=0
            ))


def lb2_self_bounds_mp(prmu, limit1, n_active, tables: "PFSPDeviceTables",
                       mp_axis: str, mp_size: int, device=None):
    """Self lb2 with the Johnson pair loop sharded over ``mp_axis`` (the
    staged path's analogue of ``lb2_bounds_mp``): each shard bounds its own
    contiguous pair block — Pallas kernel on TPU (sliced ordered tables,
    inactive-tile skipping intact), jnp chunk elsewhere — and the shards
    combine with ``lax.pmax``. Must be called inside shard_map with
    ``mp_axis`` in scope. Exact: max over pairs is associative/idempotent
    and the padding pairs are copies of pair 0."""
    from . import pallas_kernels as PK

    n, m = prmu.shape[-1], tables.ptm_t.shape[1]
    idx = jax.lax.axis_index(mp_axis)
    # One source of truth for the slice geometry: the padded tables' own
    # pair axis (re-deriving the padding here could silently misalign with
    # mp_padded's policy).
    pairs, lags, scheds = tables.mp_padded(mp_size)
    P_local = pairs.shape[0] // mp_size
    start = idx * P_local
    kb = _kernel_kind(device)
    if (PK.use_pallas(device) and _lb2_pallas_enabled() and n <= 100
            and PK.lb2_self_kernel_feasible(n, m, P_local, backend=kb)):
        ordered = tables.johnson_ordered_mp(mp_size)
        assert ordered.lag_o.shape[0] == pairs.shape[0]
        sliced = _OrderedSlice(ordered, start, P_local)
        local = PK.pfsp_lb2_self_bounds_tables(
            prmu, limit1, n_active, tables.ptm_t, sliced,
            bf16=tables.exact_bf16,
            pair_group=lb2_kernel_pair_group(P_local, n, kb), backend=kb,
        )
    else:
        prs = jax.lax.dynamic_slice_in_dim(pairs, start, P_local, axis=0)
        lgs = jax.lax.dynamic_slice_in_dim(lags, start, P_local, axis=0)
        sch = jax.lax.dynamic_slice_in_dim(scheds, start, P_local, axis=0)
        # Pair-blocking composes with the mp slicing: each shard blocks its
        # own P/mp pair subset (a smaller P just means fewer blocks).
        local = _lb2_self_chunk(
            prmu, limit1, tables.ptm_t, tables.min_heads, tables.min_tails,
            prs, lgs, sch, bf16=tables.exact_bf16,
            pairblock=lb2_pairblock(P_local, n),
        )
    return jax.lax.pmax(local, mp_axis)


def lb2_staged_enabled(device=None, n: int | None = None) -> bool:
    """Staged lb2 (lb1 prefilter -> compacted self-lb2) pays off only where
    inactive tiles are actually skipped — the Pallas path. TTS_LB2_STAGED=1
    forces it everywhere (tests exercise the compaction machinery on CPU);
    =0 disables."""
    import os

    from . import pallas_kernels as PK

    knob = os.environ.get("TTS_LB2_STAGED", "auto")
    if knob == "0":
        return False
    if knob == "1":
        return True
    return (PK.use_pallas(device) and _lb2_pallas_enabled()
            and (n is None or n <= 100))


def compact_mode() -> str:
    """The raw ``TTS_COMPACT`` knob (``auto`` default — see
    `ops/compaction.py` for the mode catalogue, the shift-based ``dense``
    fast path, and the measured ``auto`` table).  Re-exported here so the
    routing token below and its existing import sites keep one spelling;
    the survivor-path implementations live in `ops/compaction.py`."""
    from .compaction import compact_mode as _raw

    return _raw()


def routing_cache_token(problem, device=None) -> tuple:
    """Every env-dependent kernel-routing decision that gets baked into a
    compiled program at trace time (Pallas vs jnp, the lb2-family kill
    switch, the staged-lb2 choice). Program caches keyed per problem
    instance must carry this token so flipping TTS_PALLAS /
    TTS_PALLAS_LB2 / TTS_LB2_STAGED between searches rebuilds instead of
    silently reusing a stale program. One definition — used by both the
    resident and mesh-resident cache keys."""
    from ..problems.base import narrow_mode
    from . import backend as BK
    from . import pallas_kernels as PK
    from .megakernel import megakernel_mode

    tok: tuple = (PK.use_pallas(device), PK.pallas_interpret(),
                  # Kernel-backend seam (ops/backend.py): the raw knob and
                  # the flavor it resolves to — a TTS_KERNEL_BACKEND flip
                  # rebuilds instead of reusing the other flavor's program.
                  BK.kernel_backend_mode(), BK.kernel_kind(device),
                  # lb1-family demotion override (TTS_PALLAS=force) is a
                  # trace-time routing decision like the rest.
                  PK.pallas_forced(),
                  compact_mode(),
                  # One-kernel cycle knobs (ops/megakernel.py): the raw
                  # mode and the raw forced pool-tile width — the rest of
                  # the decision (M, device, family, mp) is already in
                  # every program cache key carrying this token.
                  megakernel_mode(),
                  os.environ.get("TTS_MEGAKERNEL_MT"),
                  # Narrow node storage (TTS_NARROW, problems/base.py):
                  # host staging dtypes and the megakernel auto window are
                  # trace-time decisions keyed on it.
                  narrow_mode())
    if getattr(problem, "name", None) == "pfsp" and problem.lb == "lb2":
        tok += (
            _lb2_pallas_enabled(),
            lb2_staged_enabled(device, problem.jobs),
            # The resolved pair-block size (TTS_LB2_PAIRBLOCK) is baked
            # into the evaluator at trace time; the kernel pair group is a
            # pure function of it, so one entry covers both paths.
            lb2_pairblock(problem.lb2_data.pairs.shape[0], problem.jobs),
        )
    return tok


def lb2_bounds_staged(prmu, limit1, cand, tables: "PFSPDeviceTables",
                      device=None, mp_axis: str | None = None,
                      mp_size: int = 1):
    """lb2 child bounds evaluated ONLY for candidate children.

    ``cand`` (B, n) marks open, non-leaf children whose lb1 is below the
    incumbent; since lb2 >= lb1 pointwise (every machine's lb1 term appears
    as the one-machine term of some Johnson pair), children outside ``cand``
    are pruned under lb2 too — skipping them is exact. Candidates are
    compacted to the front of an (R = B*n)-row buffer of materialized child
    nodes (parent permutation with slots (limit1+1, k) swapped), the self
    bound runs on ceil(count/tile) active tiles, and results scatter back.
    Non-candidate slots hold garbage (never read: the caller masks with
    ``cand``).

    ``mp_axis`` set (mesh dp x mp tier): the compaction is pure shard-local
    ops — every mp replica of a dp block computes the identical candidate
    set — and the self bound shards the pair loop over mp with a pmax
    combine (``lb2_self_bounds_mp``), so all replicas see full-pair bounds
    and stay in lockstep."""
    B, n = prmu.shape
    R = B * n
    flat = cand.reshape(R)
    pos = jnp.cumsum(flat.astype(jnp.int32)) - 1  # compacted row per cand
    count = jnp.sum(flat.astype(jnp.int32))
    # Scatter each candidate's flat source index into its compacted row
    # (R+1 buffer: non-candidates target the spill slot, then dropped).
    tgt = jnp.where(flat, pos, R)
    src = (
        jnp.zeros((R + 1,), jnp.int32)
        .at[tgt]
        .set(jnp.arange(R, dtype=jnp.int32))[:R]
    )
    b_idx = src // n
    k_idx = src % n
    parent = prmu[b_idx]  # (R, n)
    d = limit1[b_idx] + 1  # the child's limit1
    # Child permutation: swap slots d and k (k == d is a no-op swap).
    iota = jnp.arange(n, dtype=prmu.dtype)[None, :]
    vd = jnp.take_along_axis(parent, d[:, None], axis=1)[:, 0]
    vk = jnp.take_along_axis(parent, k_idx[:, None], axis=1)[:, 0]
    ohd = (iota == d[:, None]).astype(parent.dtype)
    ohk = (iota == k_idx[:, None]).astype(parent.dtype)
    child = parent + ohd * (vk - vd)[:, None] + ohk * (vd - vk)[:, None]
    if mp_axis is not None:
        out = lb2_self_bounds_mp(child, d, count, tables, mp_axis, mp_size,
                                 device)  # (R,)
    else:
        out = lb2_self_bounds(child, d, count, tables, device)  # (R,)
    vals = out[jnp.where(flat, pos, 0)]
    return vals.reshape(B, n)


def lb2_bounds_mp(prmu, limit1, tables: "PFSPDeviceTables", mp_axis: str,
                  mp_size: int, device=None):
    """lb2 chunk bounds with the Johnson machine-pair loop SHARDED over a
    mesh axis: each ``mp`` shard reduces its pair subset and the shards
    combine with ``lax.pmax`` (max over machine pairs is the bound; max is
    associative and idempotent, so padding with copies of pair 0 is safe).
    Must be called inside shard_map with ``mp_axis`` in scope. The SIMT
    design has no equivalent of this axis — it is the model-parallel
    analogue for bound evaluation (SURVEY.md §2.4 note).

    jnp path only: the Pallas kernel's per-pair ordered tables are built
    host-side for the full pair set; slicing them per shard inside the
    kernel would need a second staging pass (future work).
    """
    del device
    pairs, lags, scheds = tables.mp_padded(mp_size)
    P_local = pairs.shape[0] // mp_size
    idx = jax.lax.axis_index(mp_axis)
    start = idx * P_local
    prs = jax.lax.dynamic_slice_in_dim(pairs, start, P_local, axis=0)
    lgs = jax.lax.dynamic_slice_in_dim(lags, start, P_local, axis=0)
    sch = jax.lax.dynamic_slice_in_dim(scheds, start, P_local, axis=0)
    # Pair-blocking applies within each shard's P/mp subset (fewer blocks,
    # same math) — the pair axis composes with the mp slicing.
    local = _lb2_chunk(
        prmu, limit1, tables.ptm_t, tables.min_heads, tables.min_tails,
        prs, lgs, sch, bf16=tables.exact_bf16,
        pairblock=lb2_pairblock(P_local, prmu.shape[-1]),
    )
    return jax.lax.pmax(local, mp_axis)


def make_evaluator(tables: PFSPDeviceTables, lb: str, device=None):
    """Dispatcher over the three bounds (`pfsp_gpu_chpl.chpl:256-270`).

    Returns ``fn(parents: dict, count, best) -> (B, jobs) int32 bounds``;
    ``device`` selects the Pallas-vs-XLA path per target platform.

    The offload tiers may stage ``prmu``/``limit1`` at the narrow storage
    dtypes (TTS_NARROW, problems/base.py); bound arithmetic is exact at
    int32, so every entry point widens first — a no-op cast when storage
    is already wide (the resident tier pre-widens its popped chunks).
    """
    def _wide(parents):
        return (jnp.asarray(parents["prmu"]).astype(jnp.int32),
                jnp.asarray(parents["limit1"]).astype(jnp.int32))

    if lb == "lb1":
        def evaluate(parents, count, best):
            del count, best
            prmu, limit1 = _wide(parents)
            return lb1_bounds(prmu, limit1, tables, device)
    elif lb == "lb1_d":
        def evaluate(parents, count, best):
            del count, best
            prmu, limit1 = _wide(parents)
            return lb1_d_bounds(prmu, limit1, tables, device)
    elif lb == "lb2":
        if lb2_staged_enabled(device, tables.ptm_t.shape[0]):
            @jax.jit
            def _staged(prmu, limit1, count, best):
                prmu = prmu.astype(jnp.int32)
                limit1 = limit1.astype(jnp.int32)
                # Offload-path staging: children killed by the cheap lb1
                # pass report their lb1 value (>= the dispatch-time best,
                # so the host prunes them identically — lb2 >= lb1 and the
                # host's running best only tightens); candidates report
                # the compacted self lb2. Leaf slots report lb1 = exact
                # makespan, so the host's incumbent fold is unchanged.
                # ``count`` masks the bucket-padding clone rows out of the
                # candidate set (their result slots are never read, but
                # they would inflate the compaction and waste kernel
                # tiles).
                n = prmu.shape[-1]
                bounds1 = lb1_bounds(prmu, limit1, tables, device)
                kk = jnp.arange(n, dtype=jnp.int32)[None, :]
                valid = (
                    jnp.arange(prmu.shape[0], dtype=jnp.int32) < count
                )[:, None]
                open_ = (kk >= (limit1 + 1)[:, None]) & valid
                leaf = open_ & ((limit1[:, None] + 2) == n)
                # Fold this chunk's leaf makespans before selecting
                # candidates (as the resident staged path does): the host
                # folds leaves before its keep test anyway, so children a
                # leaf already dominates would be pruned regardless —
                # don't spend kernel tiles on them.
                from ..problems.base import INF_BOUND

                best = jnp.minimum(
                    best, jnp.min(jnp.where(leaf, bounds1, INF_BOUND))
                )
                cand = open_ & (~leaf) & (bounds1 < best)
                b2 = lb2_bounds_staged(prmu, limit1, cand, tables, device)
                return jnp.where(cand, b2, bounds1)

            def evaluate(parents, count, best):
                return _staged(parents["prmu"], parents["limit1"], count, best)
        else:
            def evaluate(parents, count, best):
                del count, best
                prmu, limit1 = _wide(parents)
                return lb2_bounds(prmu, limit1, tables, device)
    else:
        raise ValueError(f"Unsupported lower bound: {lb!r}")
    return evaluate


# -- compiled-program contracts (`tts check`, analysis/contracts.py) --------

from ..analysis.contracts import contract, loop_op_count  # noqa: E402


@contract(
    "lb2-pairblock-loop-free",
    claim="with pair-blocking on (Pb > 1) the compiled lb2 child/self "
          "evaluators contain NO loop whose trip count scales with P — "
          "only `_parent_state`'s O(n) prefix scan survives (1 loop op); "
          "the serial build (Pb=1) keeps its per-pair fori_loop (2 loop "
          "ops), so the pin is never trivially zero-by-construction",
    artifact="lb2-eval",
)
def _contract_pairblock_loop_free(art, cell):
    expect = 2 if art["pairblock"] == 1 else 1
    out = []
    for kind in ("child", "self"):
        got = loop_op_count(art[kind])
        if got != expect:
            out.append(
                f"lb2 {kind} evaluator at Pb={art['pairblock']}: {got} "
                f"serial loop ops (expected {expect})"
            )
    if art.get("auto") and art["pairblock"] <= 1:
        out.append(
            "auto pair-block policy resolved to the serial loop at a "
            "published blocked shape"
        )
    return out
