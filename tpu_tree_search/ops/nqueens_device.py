"""Batched N-Queens safety kernel (vectorized XLA).

TPU-first reformulation of the reference's per-thread SIMT kernel
(`nqueens_gpu_chpl.chpl:97-123`, `baselines/nqueens/nqueens_gpu_cuda.cu:137-164`):
one (B, N, N) clash tensor — (parent, placed queen i, candidate slot k) —
reduced over i, instead of one scalar thread per (parent, k). All int32 lane
work; XLA tiles it onto the VPU.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp


def make_core(N: int, g: int = 1):
    """Returns ``fn(board: (B, N) uint8/int32, depth: (B,) int32) -> (B, N)
    uint8`` labels; labels[i, k] == 1 iff swapping slot k into position
    depth_i keeps all diagonals safe. Slots k < depth are 0 (the reference
    leaves them as garbage and never reads them; emitting 0 is strictly
    safer, SURVEY.md Appendix A).
    """

    def core(board, depth):
        board = board.astype(jnp.int32)  # (B, N)
        depth = depth.astype(jnp.int32)  # (B,)
        qk = board[:, None, :]  # candidate row for slot k: (B, 1, N)
        bi = board[:, :, None]  # placed queen rows:        (B, N, 1)
        i = jnp.arange(N, dtype=jnp.int32)
        d = depth[:, None] - i[None, :]  # (B, N): depth - i
        placed = i[None, :] < depth[:, None]  # (B, N) mask over i
        clash = (bi == qk - d[:, :, None]) | (bi == qk + d[:, :, None])
        safe = ~jnp.any(clash & placed[:, :, None], axis=1)  # (B, N)
        if g > 1:
            # Honor the g workload knob with a real loop op so XLA cannot
            # CSE the redundant rechecks away (the reference repeats the
            # comparisons g times, `nqueens_gpu_chpl.chpl:115-118`).
            def recheck(_, s):
                c = (bi == qk - d[:, :, None]) | (bi == qk + d[:, :, None])
                return s & ~jnp.any(c & placed[:, :, None], axis=1)

            safe = jax.lax.fori_loop(0, g - 1, recheck, safe)
        k = jnp.arange(N, dtype=jnp.int32)[None, :]
        valid = k >= depth[:, None]
        return (safe & valid).astype(jnp.uint8)

    return core


def make_labels(N: int, g: int = 1, device=None):
    """Routed safety evaluator: Pallas kernel when the target device
    natively compiles the resolved kernel flavor (`pallas_kernels.py` /
    `ops/backend.py` — the gpu flavor also routes forced-interpret), the
    jnp/XLA core elsewhere. Same contract as ``make_core``."""
    from . import backend as BK
    from . import pallas_kernels as PK

    if PK.use_pallas(device):
        kb = BK.kernel_kind(device)
        return lambda board, depth: PK.nqueens_labels(board, depth, N, g,
                                                      backend=kb)
    return make_core(N, g)


@lru_cache(maxsize=None)
def _make_jitted_core(N: int, g: int, device, routing_key: tuple):
    del routing_key  # cache key only — the knobs it captures are baked in
    return jax.jit(make_labels(N, g, device))


def make_jitted_core(N: int, g: int = 1, device=None):
    """Module-level jit cache: every DeviceOffloader / worker thread shares
    one compiled kernel per bucket shape instead of re-tracing per closure
    (cf. the module-level jitted PFSP chunk kernels). The env-dependent
    routing decisions make_labels bakes in at trace time are part of the
    key — flipping TTS_PALLAS / TTS_PALLAS_INTERPRET between searches must
    rebuild, not reuse a stale core (same invariant as
    ``pfsp_device.routing_cache_token``)."""
    from . import backend as BK
    from . import pallas_kernels as PK

    return _make_jitted_core(
        N, g, device,
        (PK.use_pallas(device), PK.pallas_interpret(),
         BK.kernel_backend_mode(), BK.kernel_kind(device)),
    )
