"""Device-resident search engine — the fully TPU-native tier.

The reference's offload loop round-trips host<->device once per chunk
(`pfsp_gpu_chpl.chpl:373-396`: H2D parents, kernel, D2H bounds, host
prune/branch).  On TPU the dominant cost of that design is not the kernel but
the dispatch + transfer latency of every cycle (hundreds of ms over a remote
runtime, vs sub-ms of device compute for a 64k-node chunk).  This engine
inverts the ownership: the **pool itself lives in HBM** as fixed-capacity SoA
arrays, and one jitted step advances the search by up to K chunk cycles
inside a `lax.while_loop` — pop, evaluate, prune, compact, push are all
device ops; the host only re-dispatches the step and reads back four scalars
every K cycles.

Semantics per cycle are exactly the reference's chunk cycle (SURVEY.md
Appendix A):

  * pop the back `cnt = min(size, M)` nodes, only while `size >= m`;
  * evaluate all `cnt * child_slots` children in one batch;
  * PFSP: a child with depth == jobs is a leaf -> exploredSol++, folds the
    incumbent with a min; a non-leaf child is pushed iff `bound < best`
    strictly, counting exploredTree (`pfsp_chpl.chpl:100-111`);
  * N-Queens: a parent popped at depth == N counts one solution; safe
    children are always pushed (no pruning), depth-N leaves included
    (`nqueens_chpl.chpl:70-89`).

The push is a fused prune+push (the device-side equivalent of the
prune+compact improvement suggested in SURVEY.md §7.3): survivors are
ranked with hierarchical prefix sums, the rank map is inverted by the
compaction mode baked in at build time (`ops/compaction.py` — scatter /
sort / binary-search / shift-based dense, `TTS_COMPACT=auto` picks per
problem shape), and each surviving child row is rebuilt *at its
destination slot* from one gather of its parent's (row, aux) — the
(M, n, n) child cube is materialized only on the rare overflow fallback.

Capacity safety: the loop only runs a cycle while `size + M*child_slots <=
capacity`, so a cycle can never lose children.  If the pool outgrows that
headroom the step returns early and the host falls back to classic offload
cycles (pop via the host pool) until the frontier shrinks — correctness never
depends on the capacity heuristic.
"""

from __future__ import annotations

import time

import numpy as np

from ..obs import counters as obs_counters
from ..obs import events as ev
from ..obs import flightrec as fr
from ..obs import phases as obs_phases
from ..obs import quality as obs_quality
from ..obs import roofline as obs_roofline
from ..pool import SoAPool
from ..problems.base import INF_BOUND, Problem
from ..problems.nqueens import NQueensProblem
from ..problems.pfsp.problem import PFSPProblem
from .device import DeviceOffloader, bucket_size, warmup
from .results import Diagnostics, PhaseStats, SearchResult


def _pool_int_dtype(n: int):
    import jax.numpy as jnp

    if n <= 127:
        return jnp.int8
    if n <= 32767:
        return jnp.int16
    return jnp.int32


def _swap_children(chunk_vals, depth):
    """All single-swap children of each parent row.

    chunk_vals: (M, n) permutation rows; depth: (M,) swap position.
    Returns (M, n, n): row (i, k) = parent i with positions depth_i and k
    swapped (identity when k == depth_i) — the branching rule shared by both
    problems (`pfsp_chpl.chpl:91-96`, `nqueens_chpl.chpl:78-87`).
    """
    import jax.numpy as jnp

    # A child differs from its parent at exactly two positions, so the cube
    # is three elementwise selects over (M, n, n) — no gather (a full
    # take_along_axis over the cube costs ~40x more on TPU).
    iota = jnp.arange(chunk_vals.shape[1], dtype=jnp.int32)[None, None, :]
    kcol = iota.transpose(0, 2, 1)  # (1, n, 1)
    d = depth[:, None, None]
    val_at_k = chunk_vals[:, :, None]  # parent[i, k] per (i, k, *)
    val_at_d = jnp.take_along_axis(chunk_vals, depth[:, None], axis=1)[:, :, None]
    base = chunk_vals[:, None, :]  # parent[i, *, j]
    return jnp.where(iota == d, val_at_k, jnp.where(iota == kcol, val_at_d, base))


def _compact_ids(keep, S: int, mode: str | None = None):
    """Stream-compaction ids of the surviving (parent, slot) pairs — the
    engine-side entry point for `ops/compaction.compact_ids` (which owns
    the four rank inversions and their contract).  ``mode=None`` resolves
    the ``TTS_COMPACT`` knob without problem context (bare/oracle calls in
    tests); the resident programs pass their baked-in resolved mode."""
    from ..ops.compaction import compact_ids, resolve_compact_mode

    if mode is None:
        mode = resolve_compact_mode()
    return compact_ids(keep, S, mode)


class _ResidentProgram:
    """Compiled device-resident step for one (problem, m, M, K, capacity).

    Pool layout (both problems): ``vals`` (C, n) — the permutation rows —
    plus one scalar ``aux`` column (C,) (PFSP: limit1; N-Queens: depth).
    Subclasses provide the chunk evaluator and the swap position.
    """

    def __init__(self, problem, m: int, M: int, K: int, capacity: int, device,
                 mp_axis: str | None = None, mp_size: int = 1,
                 allow_staged: bool = True):
        import jax

        self.problem = problem
        self.m = m
        self.M = M
        self.capacity = capacity
        # Mesh-resident mp sharding of the lb2 pair loop (read by
        # _make_eval); harmless None/1 everywhere else.
        self.mp_axis = mp_axis
        self.mp_size = mp_size
        # Staged lb2 (lb1 prefilter + compacted self bound) — disabled by
        # the mesh tier (the compaction runs inside shard_map; unvalidated
        # there) and anywhere the evaluator must stay single-pass.
        self.allow_staged = allow_staged
        n = problem.child_slots
        # Counter headroom: every step call accumulates at most K*M*n into
        # int32 counters.
        self.K = max(1, min(K, (2**31 - 1) // max(1, M * n)))
        self.device = device if device is not None else jax.devices()[0]
        # Survivor-path selection (ops/compaction.py): resolved once at
        # build time from the TTS_COMPACT knob / auto policy and baked into
        # the compiled step.  Surfaced through SearchResult.compact so a
        # stats line can prove which path ran.
        from ..ops.compaction import compact_mode, resolve_compact_mode

        self.compact = resolve_compact_mode(problem, M, n, self.device)
        self.compact_auto = compact_mode() == "auto"
        # The while condition reserves exactly M*n rows of headroom, so the
        # survivor budget must never exceed it (a small M would otherwise
        # make the fused-path write overrun the reservation and corrupt
        # live rows).
        self.S = min(max(64 * n, M * n // self.survivor_budget_div), M * n)
        # On-device cycle counters (TTS_OBS=1, obs/counters.py): baked in at
        # build time — when off, the carry/body/jaxpr are byte-identical to
        # a counter-free build (compiled out, not branched). _make_program
        # keys its cache on this flag.
        self.obs = obs_counters.device_counters_enabled()
        # Per-phase cycle clocks (TTS_PHASEPROF=1, obs/phases.py): a
        # separate cache-keyed program variant for `tts profile` — when
        # off, nothing below is traced and the jaxpr is byte-identical.
        self.phaseprof = obs_phases.phase_profiling_enabled()
        # One-kernel cycle (TTS_MEGAKERNEL, ops/megakernel.py): the whole
        # pop->bound->prune->compact->push cycle as a single pallas_call,
        # resolved once at build time like the compact auto policy (TPU +
        # small-M + VMEM fit; correctness refusals recorded in
        # .megakernel.reason for the banner/SearchResult). The raw knob
        # rides routing_cache_token, so a flip rebuilds; when off, nothing
        # in loop_fns traces differently (contract megakernel-off-identity).
        from ..ops import megakernel as MK

        self.megakernel = MK.resolve(problem, M, self.device,
                                     mp_axis=mp_axis, mp_size=mp_size)
        # Kernel-backend seam (TTS_KERNEL_BACKEND, ops/backend.py): which
        # kernel flavor this build routed ('gpu' only when the seam
        # resolves gpu — everything else keeps the TPU flavor of record).
        # Surfaced through SearchResult.kernel_backend; the raw knob and
        # the resolved kind both ride routing_cache_token.
        from ..ops import backend as _BK

        self.kernel_backend = _BK.kernel_kind(self.device)
        self._step = self._build()

    def loop_fns(self, K: int | None = None):
        """(cond, body) of the K-cycle device loop over the carry
        ``(pool_vals, pool_aux, size, best, tree, sol, cycles)`` — reused by
        the single-device step and, per shard, by the mesh-resident tier.
        With ``self.obs`` the carry gains one trailing ``(NSLOTS,)`` int32
        counter block (obs/counters.py), accumulated per cycle and harvested
        at the dispatch boundary; with ``self.phaseprof`` a final
        ``(phases.NSLOTS + 1,)`` uint32 phase-clock block rides behind it
        (obs/phases.py — clock reads fenced by ``lax.optimization_barrier``
        at the pop/eval/compact/push boundaries); when both are off the
        carry is exactly the 7-tuple above."""
        import jax.numpy as jnp
        from jax import lax

        from ..ops.compaction import shift_compact, survivor_ranks

        n = self.problem.child_slots
        m, M, C = self.m, self.M, self.capacity
        K = self.K if K is None else K
        Mn = M * n
        obs = self.obs
        phaseprof = self.phaseprof
        S = self.S
        mode = self.compact
        vals_dt = self.pool_fields[0][1]
        aux_dt = self.pool_fields[1][1]
        evaluate = self._make_eval()
        swap_of = self._swap_pos
        mk_cycle = None
        if self.megakernel.enabled:
            from ..ops import megakernel as MK

            mk_cycle = MK.make_cycle(self.problem, M, self.device,
                                     self.megakernel)

        # tts-lint: traced (returned to lax.while_loop via loop_fns)
        def body(carry):
            pool_vals, pool_aux, size, best, tree, sol, cycles = carry[:7]
            ctr = carry[7] if obs else None
            ph = carry[-1] if phaseprof else None
            if phaseprof:
                # Cycle start: the gap since the previous boundary (cond +
                # carry plumbing, or the pre-loop seed) is `loop` time;
                # the reading stored here is the cycle's t0 for `total`.
                ph, (pool_vals, pool_aux, size) = obs_phases.boundary(
                    ph, "loop", pool_vals, pool_aux, size
                )
                t_cycle0 = ph[obs_phases.TPREV]
            cnt = jnp.minimum(size, M)
            start = size - cnt
            start2 = jnp.clip(start, 0, C - M)
            idx = start2 + jnp.arange(M, dtype=jnp.int32)
            valid = (idx >= start) & (idx < size)
            vals8_c = lax.dynamic_slice(pool_vals, (start2, 0), (M, n))
            vals_c = vals8_c.astype(jnp.int32)
            aux_c = lax.dynamic_slice(pool_aux, (start2,), (M,)).astype(jnp.int32)
            size = size - cnt
            if phaseprof:
                ph, (vals8_c, vals_c, aux_c, size, valid) = obs_phases.boundary(
                    ph, "pop", vals8_c, vals_c, aux_c, size, valid
                )

            if mk_cycle is not None:
                # Armed one-kernel cycle (ops/megakernel.py): bound + prune
                # + shift-compact + emit run inside ONE pallas_call; the
                # engine only writes the compacted rows back into the
                # reserved Mn headroom (rows past tree_inc are dead by the
                # pool contract). On the streamed path (grid > 1) each of
                # the G tiles owns an (Mt*n)-row block compacted to its own
                # front; the blocks are stitched with G overlapping
                # dynamic_update_slice writes at the kernel's carried
                # offsets — written in tile order, so each write's garbage
                # tail is overwritten by the next tile's rows and the live
                # prefix is exactly the dense-mode global order (single-
                # tile: G == 1, offs == [0], one full-width write as
                # before). The phase profiler reports the collapse
                # honestly: everything lands in `eval`, and the
                # pop+eval+...+overflow == total telescope still holds.
                rows_mk, aux_mk, offs_mk, tree_inc, sol_inc, best = mk_cycle(
                    vals_c, aux_c, valid, best
                )
                fits = tree_inc <= S  # survivor-budget overflow counter
                rows_cast = rows_mk.astype(vals_dt)
                aux_cast = aux_mk.astype(aux_dt)
                G_mk = offs_mk.shape[0]
                Mtn = Mn // G_mk
                for ti in range(G_mk):
                    dst = size + offs_mk[ti]
                    pool_vals = lax.dynamic_update_slice(
                        pool_vals, rows_cast[ti * Mtn:(ti + 1) * Mtn],
                        (dst, jnp.int32(0))
                    )
                    pool_aux = lax.dynamic_update_slice(
                        pool_aux, aux_cast[ti * Mtn:(ti + 1) * Mtn], (dst,)
                    )
                size = size + tree_inc
                if phaseprof:
                    ph, (pool_vals, pool_aux, size) = obs_phases.boundary(
                        ph, "eval", pool_vals, pool_aux, size
                    )
                    ph = obs_phases.close_total(ph, t_cycle0)
                out = (
                    pool_vals, pool_aux, size, best,
                    tree + tree_inc, sol + sol_inc, cycles + 1,
                )
                if obs:
                    # push_rows: the megakernel always shift-compacts the
                    # whole Mn reservation.
                    ctr = obs_counters.update(
                        ctr, cnt, n, tree_inc, sol_inc, fits, size,
                        jnp.int32(Mn),
                    )
                    out = out + (ctr,)
                if phaseprof:
                    out = out + (ph,)
                return out

            keep, sol_inc, best = evaluate(vals_c, aux_c, valid, best)
            d = swap_of(aux_c)  # (M,) swap position per parent
            if phaseprof:
                ph, (keep, sol_inc, best, d) = obs_phases.boundary(
                    ph, "eval", keep, sol_inc, best, d
                )

            ids, tree_inc = _compact_ids(keep, S, mode)
            fits = tree_inc <= S
            if phaseprof:
                ph, (ids, tree_inc, fits) = obs_phases.boundary(
                    ph, "compact", ids, tree_inc, fits
                )

            def small(pool_vals, pool_aux):
                # Fused prune+push: ONE gather of the survivor budget —
                # parent row and parent aux ride the same augmented
                # (M, n+1) gather (aux fits the pool value dtype: limit1
                # in [-1, n) and depth in [0, N] are in range) — and the
                # child row is rebuilt at its destination slot by pure
                # selects over the gathered row (the `_swap_children`
                # structure: a child differs from its parent at exactly
                # the two swapped positions), so the (M, n, n) child cube
                # is never materialized and never gathered twice.  Rows
                # beyond tree_inc are garbage past the new size (dead by
                # the pool contract).
                pi = ids // n
                kj = ids % n
                aug = jnp.concatenate(
                    [vals8_c, aux_c.astype(vals_dt)[:, None]], axis=1
                )
                g = aug[pi]  # (S, n+1): the cycle's one child-value gather
                rows = g[:, :n]
                pa = g[:, n].astype(jnp.int32)  # parent aux
                dp = swap_of(pa)
                iota = jnp.arange(n, dtype=jnp.int32)[None, :]
                ohd = iota == dp[:, None]
                ohk = iota == kj[:, None]
                # One-hot extraction instead of take_along_axis: exactly
                # one lane is selected per row, so the sum is exact.
                v_k = jnp.sum(jnp.where(ohk, rows, 0), axis=1,
                              dtype=jnp.int32)
                v_d = jnp.sum(jnp.where(ohd, rows, 0), axis=1,
                              dtype=jnp.int32)
                crows = jnp.where(
                    ohd,
                    v_k[:, None].astype(vals_dt),
                    jnp.where(ohk, v_d[:, None].astype(vals_dt), rows),
                )
                pool_vals = lax.dynamic_update_slice(
                    pool_vals, crows, (size, jnp.int32(0))
                )
                pool_aux = lax.dynamic_update_slice(
                    pool_aux, (pa + 1).astype(aux_dt), (size,)
                )
                return pool_vals, pool_aux

            def big(pool_vals, pool_aux):
                # Overflow fallback (rare — only when a chunk keeps more
                # than S children): materialize the child cube and place
                # all survivors at once.
                child = _swap_children(vals_c, d).astype(vals_dt)
                ranks, _ = survivor_ranks(keep)
                caux = jnp.repeat(aux_c + 1, n).astype(aux_dt)
                if mode == "dense":
                    # Scatter-free overflow: shift-compact the child rows
                    # themselves (ops/compaction.py), then one contiguous
                    # write of the reserved Mn headroom — rows past
                    # tree_inc are dead by the pool contract.
                    flat_idx = jnp.arange(Mn, dtype=jnp.int32)
                    dist = jnp.where(
                        keep.reshape(Mn), flat_idx - ranks.reshape(Mn), 0
                    )
                    rowsc, auxc = shift_compact(
                        dist, (child.reshape(Mn, n), caux)
                    )
                    pool_vals = lax.dynamic_update_slice(
                        pool_vals, rowsc, (size, jnp.int32(0))
                    )
                    pool_aux = lax.dynamic_update_slice(
                        pool_aux, auxc, (size,)
                    )
                    return pool_vals, pool_aux
                dest = jnp.where(keep.reshape(Mn), size + ranks.reshape(Mn), C)
                pool_vals = pool_vals.at[dest].set(
                    child.reshape(Mn, n), mode="drop"
                )
                pool_aux = pool_aux.at[dest].set(caux, mode="drop")
                return pool_vals, pool_aux

            pool_vals, pool_aux = lax.cond(fits, small, big, pool_vals, pool_aux)
            size = size + tree_inc
            if phaseprof:
                # The cond ran exactly one branch: charge its time to the
                # slot the predicate names, then close the cycle's total
                # (`pop+eval+compact+push+overflow == total` telescopes).
                slot = jnp.where(
                    fits,
                    jnp.int32(obs_phases.IDX["push"]),
                    jnp.int32(obs_phases.IDX["overflow"]),
                )
                ph, (pool_vals, pool_aux, size) = obs_phases.boundary(
                    ph, slot, pool_vals, pool_aux, size, tag="push"
                )
                ph = obs_phases.close_total(ph, t_cycle0)
            out = (
                pool_vals, pool_aux, size, best,
                tree + tree_inc, sol + sol_inc, cycles + 1,
            )
            if obs:
                # push_rows: rows the push stage processed this cycle —
                # the maintenance-work series (the fused path always
                # touches its full S budget; the overflow path the whole
                # Mn reservation), vs the evaluator's cnt*n child evals.
                push_rows = jnp.where(fits, jnp.int32(S), jnp.int32(Mn))
                ctr = obs_counters.update(
                    ctr, cnt, n, tree_inc, sol_inc, fits, size, push_rows
                )
                out = out + (ctr,)
            if phaseprof:
                out = out + (ph,)
            return out

        # tts-lint: traced (returned to lax.while_loop via loop_fns)
        def cond(carry):
            size, cycles = carry[2], carry[6]
            return (size >= m) & (size + Mn <= C) & (cycles < K)

        return cond, body

    def _build(self):
        import jax
        import jax.numpy as jnp
        from jax import lax

        cond, body = self.loop_fns()
        obs = self.obs
        phaseprof = self.phaseprof

        def step(pool_vals, pool_aux, size, best):
            zero = jnp.int32(0)
            init = (pool_vals, pool_aux, size, best, zero, zero, zero)
            if obs:
                init = init + (obs_counters.init_block(),)
            if phaseprof:
                # Pre-loop clock seed: base of the first cycle's `loop`
                # delta (dep on `size` orders it after the inputs).
                init = init + (obs_phases.seed_block(size.astype(jnp.uint32)),)
            return lax.while_loop(cond, body, init)

        return jax.jit(step, donate_argnums=(0, 1))

    # -- state layout: (pool..., size, best, tree_inc, sol_inc, cycles) ----

    def init_state(self, frontier: dict, best: int):
        import jax
        import jax.numpy as jnp

        C = self.capacity
        k = frontier[self.size_field].shape[0] if frontier else 0
        with jax.default_device(self.device):
            pools = []
            for name, dtype, shape in self.pool_fields:
                buf = jnp.zeros((C,) + shape, dtype=dtype)
                if k:
                    rows = jnp.asarray(frontier[name]).astype(dtype)
                    buf = buf.at[:k].set(rows)
                pools.append(buf)
            return (
                *pools,
                jnp.int32(k),
                jnp.int32(best),
            )

    def step(self, state):
        """One dispatch: up to K device-side chunk cycles."""
        return self._step(*state)

    def carry(self, out):
        """The dispatch's carried state ``(pool_vals, pool_aux, size,
        best)`` — the next dispatch's input.  Pure tuple slicing: nothing
        is forced, so a speculative dispatch can be enqueued on it while
        the producing computation is still in flight."""
        return tuple(out[:4])

    def read_scalars(self, out):
        """Blocks on the dispatch's SCALAR outputs only: returns
        ``(tree, sol, cycles, size, best, ctr)``.  Never touches the pool
        leaves — under pipelined dispatch those buffers were already
        donated into the next speculative dispatch and are dead; the
        scalar outputs are not donated and stay readable.  This is the
        sanctioned per-dispatch readback (a few ints + the optional obs
        counter block), same bytes as the synchronous path always read."""
        ctr = np.asarray(out[7]) if self.obs else None
        return (int(out[4]), int(out[5]), int(out[6]),
                int(out[2]), int(out[3]), ctr)

    def read_phase_block(self, out):
        """The dispatch's harvested phase-clock block (np array) when the
        profiler variant is armed, else None — same dispatch-boundary
        readback contract as ``read_scalars`` (the block is the final,
        non-donated output leaf)."""
        return np.asarray(out[-1]) if self.phaseprof else None

    def read(self, out):
        """Blocks on the step result; returns ``(state, tree, sol, cycles,
        ctr)`` where ``ctr`` is the harvested counter block (np array) when
        device counters are on, else None. The reads happen at the dispatch
        boundary, outside the steady-state guard — the same sanctioned
        scalar readback the engine always performed."""
        state = tuple(out[:4])
        tree, sol, cycles = int(out[4]), int(out[5]), int(out[6])
        ctr = np.asarray(out[7]) if self.obs else None
        return state, tree, sol, cycles, ctr

    def residual(self, state) -> tuple[dict, int, int]:
        """Downloads the remaining pool -> (host NodeBatch, size, best)."""
        *pools, size, best = state
        size = int(size)
        best = int(best)
        # Static-shape slice: residual after a completed run is < m nodes, so
        # one padded transfer; the overflow fallback passes larger sizes.
        batch = {}
        fields = self.problem.node_fields()
        for (name, _, _), buf in zip(self.pool_fields, pools):
            host = np.asarray(buf[: max(size, 1)])[:size]
            batch[name] = host.astype(fields[name][1])
        return self.derive_fields(batch), size, best

    def snapshot(self, state) -> tuple[dict, int, int]:
        """Full live-frontier download (checkpointing): one whole-pool
        transfer, sliced to the live rows."""
        *pools, size, best = state
        size = int(size)
        best = int(best)
        fields = self.problem.node_fields()
        batch = {}
        for (name, _, _), buf in zip(self.pool_fields, pools):
            batch[name] = np.asarray(buf)[:size].astype(fields[name][1])
        return self.derive_fields(batch), size, best


class _PFSPResident(_ResidentProgram):
    size_field = "prmu"
    # Deep PFSP chunks prune heavily (closed slots + bound cuts); comfortably
    # under a quarter of the slot grid in practice.
    survivor_budget_div = 4

    def __init__(self, problem: PFSPProblem, *a, **kw):
        import jax.numpy as jnp

        n = problem.jobs
        self._dt = _pool_int_dtype(n)
        self.pool_fields = (
            ("prmu", self._dt, (n,)),
            ("limit1", jnp.int8 if n <= 127 else jnp.int32, ()),
        )
        super().__init__(problem, *a, **kw)

    def derive_fields(self, batch: dict) -> dict:
        # depth == limit1 + 1 for every node the engine ever pushes (forward
        # branching; the root depth=0/limit1=-1 satisfies it too).
        batch["depth"] = (batch["limit1"] + 1).astype(
            self.problem.node_fields()["depth"][1]
        )
        return batch

    def _swap_pos(self, aux_c):
        return aux_c + 1  # parent depth = limit1 + 1

    def _make_eval(self):
        import jax.numpy as jnp

        from ..ops import pfsp_device as P

        prob = self.problem
        t = prob.device_tables()
        lb = prob.lb
        n = prob.jobs
        device = self.device
        # Set by the mesh-resident program when the Johnson pair axis is
        # sharded over a second mesh axis (lb2 only).
        mp_axis = self.mp_axis
        mp_size = self.mp_size

        # Staging composes with the mp pair-axis sharding: the lb1
        # prefilter + compaction are pure shard-local ops (identical on
        # every mp replica), and the compacted self bound shards its pair
        # loop with a pmax combine (`lb2_self_bounds_mp`).
        staged = (
            lb == "lb2" and self.allow_staged
            and P.lb2_staged_enabled(device, n)
        )

        # tts-lint: traced (called from the while-loop body's evaluate hook)
        def evaluate(prmu_c, limit1_c, valid, best):
            pdepth = limit1_c + 1
            kk = jnp.arange(n, dtype=jnp.int32)[None, :]
            open_ = (kk >= pdepth[:, None]) & valid[:, None]
            leaf = open_ & ((pdepth[:, None] + 1) == n)
            sol_inc = jnp.sum(leaf, dtype=jnp.int32)
            if staged:
                # Incumbent-aware staging: the cheap lb1 pass decides leaves
                # and the candidate set; lb2 runs only on compacted
                # candidates (exact: lb2 >= lb1 pointwise, so lb1-dead
                # children are lb2-dead too). Leaf bounds under lb1 ARE the
                # makespan (complete schedule), so the incumbent fold is
                # identical to the single-pass path.
                bounds1 = P.lb1_bounds(prmu_c, limit1_c, t, device)
                best = jnp.minimum(
                    best, jnp.min(jnp.where(leaf, bounds1, INF_BOUND))
                )
                cand = open_ & (~leaf) & (bounds1 < best)
                bounds2 = P.lb2_bounds_staged(prmu_c, limit1_c, cand, t,
                                              device, mp_axis=mp_axis,
                                              mp_size=mp_size)
                keep = cand & (bounds2 < best)
                return keep, sol_inc, best
            if lb == "lb1":
                bounds = P.lb1_bounds(prmu_c, limit1_c, t, device)
            elif lb == "lb1_d":
                bounds = P.lb1_d_bounds(prmu_c, limit1_c, t, device)
            elif mp_axis is not None:
                bounds = P.lb2_bounds_mp(
                    prmu_c, limit1_c, t, mp_axis, mp_size, device
                )
            else:
                bounds = P.lb2_bounds(prmu_c, limit1_c, t, device)
            # Leaf makespans fold into the incumbent before the prune test,
            # exactly like the host generate_children (`pfsp_chpl.chpl:100-111`).
            best = jnp.minimum(best, jnp.min(jnp.where(leaf, bounds, INF_BOUND)))
            keep = open_ & (~leaf) & (bounds < best)
            return keep, sol_inc, best

        return evaluate


class _NQueensResident(_ResidentProgram):
    size_field = "board"
    # No pruning: every safe slot survives, so give the compactor half the
    # slot grid before it falls back to the full scatter.
    survivor_budget_div = 2

    def __init__(self, problem: NQueensProblem, *a, **kw):
        import jax.numpy as jnp

        self.pool_fields = (
            ("board", jnp.uint8, (problem.N,)),
            ("depth", jnp.int8 if problem.N <= 127 else jnp.int32, ()),
        )
        super().__init__(problem, *a, **kw)

    def derive_fields(self, batch: dict) -> dict:
        return batch

    def _swap_pos(self, aux_c):
        return aux_c  # swap position is the parent depth itself

    def _make_eval(self):
        import jax.numpy as jnp

        from ..ops import nqueens_device

        N = self.problem.N
        core = nqueens_device.make_labels(N, self.problem.g, self.device)

        # tts-lint: traced (called from the while-loop body's evaluate hook)
        def evaluate(board_c, depth_c, valid, best):
            # A popped node at depth == N is a solution (`nqueens_chpl.chpl:74`).
            sol_inc = jnp.sum(valid & (depth_c == N), dtype=jnp.int32)
            labels = core(board_c, depth_c).astype(bool)  # k >= depth folded in
            keep = labels & valid[:, None] & (depth_c < N)[:, None]
            return keep, sol_inc, best

        return evaluate


def _make_program(
    problem: Problem, m, M, K, capacity, device,
    mp_axis: str | None = None, mp_size: int = 1,
    allow_staged: bool = True,
) -> _ResidentProgram:
    # One compiled program per (problem, config): rebuilding the jit closure
    # would recompile the whole while-loop program on every search (~30 s on
    # TPU), so programs are cached on the problem instance.
    cache = getattr(problem, "_resident_programs", None)
    if cache is None:
        cache = problem._resident_programs = {}
    # Kernel-routing decisions (Pallas vs jnp, lb2 kill switch, staging)
    # are baked in at trace time but depend on env knobs — key them, or
    # flipping a knob between searches on the same problem instance would
    # silently reuse the stale program.
    from ..ops.pfsp_device import routing_cache_token

    key = (m, M, K, capacity, id(device), mp_axis, mp_size, allow_staged,
           routing_cache_token(problem, device),
           # Counter-block / phase-clock programs are distinct
           # compilations: flipping TTS_OBS or TTS_PHASEPROF between
           # searches must rebuild, not reuse.
           obs_counters.device_counters_enabled(),
           obs_phases.phase_profiling_enabled())
    if key in cache:
        return cache[key]
    if isinstance(problem, PFSPProblem):
        prog = _PFSPResident(problem, m, M, K, capacity, device,
                             mp_axis=mp_axis, mp_size=mp_size,
                             allow_staged=allow_staged)
    elif isinstance(problem, NQueensProblem):
        prog = _NQueensResident(problem, m, M, K, capacity, device,
                                mp_axis=mp_axis, mp_size=mp_size,
                                allow_staged=allow_staged)
    else:
        raise TypeError(f"no resident program for {type(problem).__name__}")
    cache[key] = prog
    return prog


def default_capacity(M: int, child_slots: int, node_bytes: int) -> int:
    """Pool capacity heuristic: at least two full chunk fan-outs of headroom,
    capped by a ~1 GiB HBM budget. Correctness never depends on it (overflow
    falls back to host offload cycles)."""
    want = max(2 * M * child_slots, 1 << 21)
    budget = (1 << 30) // max(1, node_bytes)
    return max(4 * M, min(want, budget))


def resolve_capacity(problem: Problem, M: int, capacity: int | None) -> tuple[int, int]:
    """Shared (capacity, M) resolution for the resident tiers: apply the
    default_capacity heuristic when unset, then clamp M so one chunk fan-out
    always fits in half the pool."""
    n = problem.child_slots
    if capacity is None:
        fields = problem.node_fields()
        node_bytes = sum(
            int(np.prod(shape, dtype=np.int64)) * dt.itemsize + 4
            for shape, dt in fields.values()
        )
        capacity = default_capacity(M, n, node_bytes)
    M = min(M, max(64, (capacity // 2) // n))
    # If the 64-chunk floor binds, grow the pool instead of leaving
    # M*n > capacity/2 — that would make the device loop's headroom check
    # (`size + M*n <= capacity`) unsatisfiable and silently run the whole
    # search through the host-offload fallback.
    if 2 * M * n > capacity:
        capacity = 2 * M * n
    return capacity, M


def _emit_device_explored(ctr_total: dict | None, tree2: int, sol2: int,
                          fb_tree: int, fb_sol: int, host: int = 0) -> None:
    """Phase-2 ``explored`` counter samples. When device counters ran, the
    device part comes from the harvested block (so the obs totals exercise
    the counter path, not the engine's own sums — tests pin exact parity)
    and the overflow-fallback host part is emitted separately; otherwise
    one sample carries the engine counts."""
    if not ev.enabled():
        return
    if ctr_total is not None:
        ev.counter("explored", host=host, tree=ctr_total["pushed"],
                   sol=ctr_total["leaves"], phase=2)
        if fb_tree or fb_sol:
            ev.counter("explored", host=host, tree=fb_tree, sol=fb_sol,
                       phase=2)
    else:
        ev.counter("explored", host=host, tree=tree2, sol=sol2, phase=2)


def resident_search(
    problem: Problem,
    m: int = 25,
    M: int = 65536,
    K: int | str = 4096,
    capacity: int | None = None,
    device=None,
    initial_best: int | None = None,
    warmup_target: int | None = None,
    max_steps: int | None = None,
    checkpoint_path: str | None = None,
    checkpoint_interval_s: float = 60.0,
    resume_from: str | None = None,
    guard: bool | None = None,
    yield_fn=None,
) -> SearchResult:
    """3-phase search with a device-resident hot loop.

    Phase 1 (host warm-up) and phase 3 (host drain) are identical to
    `device_search`; phase 2 runs on-device in blocks of up to K chunk
    cycles per dispatch.

    Dispatch is **pipelined** (``TTS_PIPELINE``, engine/pipeline.py):
    up to depth speculative K-cycle dispatches ride the device queue
    while the host reads lagged scalars — exact, because a dispatch on a
    terminated/stalled pool is a zero-cycle no-op. ``K="auto"`` (or
    ``TTS_K=auto``) enables the adaptive geometric-ladder K controller;
    an explicit K pins it. Under pipelining a ``max_steps`` cutoff drains
    the (up to depth-1) in-flight speculative dispatches, so the counted
    work can exceed ``max_steps`` blocks by that margin — the checkpoint
    stays coherent either way.

    Checkpointing (absent from the reference, SURVEY.md §5): with
    ``checkpoint_path`` the live frontier + counters are saved every
    ``checkpoint_interval_s`` and at a ``max_steps`` cutoff (which returns
    ``complete=False``); ``resume_from`` seeds the search from a saved file
    and keeps counting. ``yield_fn`` is the cooperative-preemption seam
    (checkpoint.RunController): checked at every dispatch boundary, True
    cuts the run exactly like a ``max_steps`` cutoff — the serve daemon
    uses it to make a long job yield to its queue and resume
    bit-identically (``tpu_tree_search/serve/``).

    Guard mode (``guard=True`` or TTS_GUARD=1, docs/ANALYSIS.md): every
    steady-state dispatch is asserted to reuse the compiled step (zero
    recompilations) and to run under ``jax.transfer_guard("disallow")`` —
    a regression that re-introduces a per-cycle host round trip raises
    ``GuardViolation`` instead of silently costing ~360 ms per cycle.
    """
    best = (
        initial_best
        if initial_best is not None
        else getattr(problem, "initial_ub", INF_BOUND)
    )
    n = problem.child_slots
    capacity, M = resolve_capacity(problem, M, capacity)

    from ..problems.base import index_batch
    from . import checkpoint as ckpt

    pool = SoAPool(problem.node_fields())
    diagnostics = Diagnostics()
    phases: list[PhaseStats] = []
    t0 = time.perf_counter()

    # -- phase 1: host warm-up (or checkpoint restore) ---------------------
    if resume_from is not None:
        saved = ckpt.load(resume_from, problem)
        pool.push_back_bulk(saved.batch)
        tree1, sol1 = saved.tree, saved.sol
        # Keep the tighter incumbent: the resumed run may supply a better one
        # (e.g. ub=1 after a ub=0 checkpoint).
        best = min(best, saved.best)
        # A resumed frontier can exceed the warm-up-sized pool: grow the
        # capacity so the whole frontier plus one fan-out fits.
        capacity = max(capacity, pool.size + 2 * M * n)
    else:
        pool.push_back(index_batch(problem.root(), 0))
        target = m if warmup_target is None else warmup_target
        tree1, sol1, best = warmup(problem, pool, best, target)
    t1 = time.perf_counter()
    phases.append(PhaseStats(t1 - t0, tree1, sol1))
    ev.counter("explored", tree=tree1, sol=sol1, phase=1)

    # -- phase 2: device-resident loop ------------------------------------
    from .pipeline import (
        AdaptiveK,
        DispatchQueue,
        RESIDENT_TARGET,
        resolve_k,
        resolve_pipeline_depth,
        resolve_target_band,
    )

    k_auto, k_value = resolve_k(K, default_max=4096)
    # TTS_COSTMODEL: a measured-profile band replaces the fixed target
    # (engine/pipeline.py resolve_target_band; fixed band is the fallback).
    band, band_src = resolve_target_band(
        "resident", RESIDENT_TARGET, problem, topology="device-D1"
    )
    ctl = AdaptiveK(k_value, target=band) if k_auto else None
    depth = resolve_pipeline_depth()
    program = _make_program(problem, m, M, ctl.K if ctl else k_value,
                            capacity, device)
    state = program.init_state(pool.as_batch(), best)
    pool.clear()
    diagnostics.host_to_device += 1
    tree2 = 0
    sol2 = 0
    size = pool.size
    offloader = None

    from ..analysis.guard import SteadyStateGuard, guard_enabled

    genabled = guard_enabled(guard)
    guards: dict[int, SteadyStateGuard] = {}

    def guard_of(prog) -> SteadyStateGuard:
        # One guard per compiled program: each ladder rung's first dispatch
        # is its sanctioned warm one; re-selecting a rung reuses its guard
        # (and its cached executable — zero steady-state recompiles).
        g = guards.get(id(prog))
        if g is None:
            g = guards[id(prog)] = SteadyStateGuard(
                prog._step, "resident step", enabled=genabled
            )
        return g

    ctr_total: dict | None = None
    ph_total: dict | None = None  # per-phase ns totals (TTS_PHASEPROF=1)
    cycles_total = 0  # device chunk cycles consumed (roofline denominator)
    fb_tree = fb_sol = 0  # overflow-fallback host increments (obs parity)
    prev_best = best
    # Anytime quality: None on the off path; otherwise records the
    # incumbent trajectory from scalars consume() already reads.
    qt = obs_quality.tracker(problem)
    n_disp = 0  # completed-dispatch sequence (flight-recorder registry)
    queue = DispatchQueue(depth)
    # Steady-state XLA capture (`tts profile` / --xla-trace): opens after
    # the first consumed dispatch (compile excluded), closes with phase 2.
    xwin = obs_phases.XlaTraceWindow("resident")

    def obs_result() -> dict | None:
        parts = {}
        if ctr_total is not None:
            parts["device_counters"] = ctr_total
        if ph_total is not None:
            parts["device_phases"] = ph_total
        return parts or None

    def enqueue() -> None:
        # Speculative pipelined dispatch: the carry chains device-side from
        # one dispatch's output into the next's input (donated), so up to
        # `depth` K-cycle blocks ride the device queue while the host is
        # still reading lagged scalars.  Exact: a dispatch on a terminated
        # or stalled pool is a zero-cycle no-op (see pipeline.py).
        nonlocal state
        t_enq = ev.now_us()
        with guard_of(program).step():
            out = program.step(state)
        state = program.carry(out)
        queue.push(out, t_enq)

    def consume(out, t_enq) -> tuple[int, int, int]:
        nonlocal tree2, sol2, size, best, ctr_total, ph_total, prev_best
        nonlocal n_disp, cycles_total
        t_wait = ev.now_us()
        tree_inc, sol_inc, cycles, size, best, ctr = \
            program.read_scalars(out)
        phb = program.read_phase_block(out)
        tree2 += tree_inc
        sol2 += sol_inc
        n_disp += 1
        cycles_total += cycles
        diagnostics.kernel_launches += cycles
        if ctr is not None:
            ctr_total = obs_counters.merge_host(ctr_total, ctr)
        if phb is not None:
            ph_total = obs_phases.merge_host(ph_total, phb)
        xwin.on_dispatch(n_disp)
        fr.heartbeat("resident", seq=n_disp, cycles=cycles, size=size,
                     best=best, tree=tree2, sol=sol2, depth=depth,
                     K=program.K, inflight=len(queue),
                     phases=ph_total)
        if qt is not None:
            qt.observe(best, n_disp, tree1 + tree2)
        if ev.enabled():
            now = ev.now_us()
            # Span semantics under pipelining (docs/OBSERVABILITY.md): the
            # span covers enqueue -> scalars-ready (spans overlap at
            # depth > 1; `tts report` merges overlaps for the busy
            # fraction); read_wait_us is the blocked portion alone.
            ev.emit("dispatch", ph="X", ts=t_enq,
                    dur=max(0.0, now - t_enq), args={
                        "cycles": cycles, "tree": tree_inc, "sol": sol_inc,
                        "size": size, "best": best,
                        "enqueue_us": t_enq, "read_wait_us": now - t_wait,
                        "pipeline_depth": depth,
                    })
            if ctr is not None:
                ev.counter("device_counters", **obs_counters.as_args(ctr))
            if phb is not None:
                # One Perfetto counter track per phase (ns this dispatch).
                ev.counter("device_phases", **obs_phases.as_args(phb))
            if best < prev_best:
                ev.emit("incumbent", args={"best": best})
        prev_best = best
        return tree_inc, sol_inc, cycles

    def drain_queue() -> tuple[int, int]:
        # Read every in-flight speculative dispatch before any action that
        # needs coherent totals or the final carried state (termination,
        # checkpoint cuts, K resizes, the capacity-stall fallback).
        dt = ds = 0
        for out, t_enq in queue.drain():
            ti, si, _ = consume(out, t_enq)
            dt += ti
            ds += si
        return dt, ds

    def snapshot_fn():
        batch, _, bst = program.snapshot(state)
        diagnostics.device_to_host += 1
        return batch, bst

    controller = ckpt.RunController(
        problem, checkpoint_path, checkpoint_interval_s, max_steps,
        snapshot_fn, drain_fn=drain_queue, yield_fn=yield_fn,
    )

    fr.arm("resident")
    ev.emit("pipeline", args={
        "depth": depth, "K": program.K, "k_auto": k_auto, "tier": "resident",
    })
    if ev.enabled():
        # Static shape/routing facts for the trace-side roofline audit
        # (`tts report --roofline`, obs/roofline.py): paired with the
        # dispatch spans' cycle counts and the device_phases counters, a
        # trace alone can rebuild the per-phase byte floors.
        ev.emit("roofline_meta", args=obs_roofline.meta_args(program))
    if band_src is not None:
        ev.emit("costmodel", args={
            "source": band_src, "lo_ms": round(1e3 * band[0], 1),
            "hi_ms": round(1e3 * band[1], 1), "tier": "resident",
        })
    last_ready = time.monotonic()

    while True:
        while not queue.full:
            enqueue()
        out, t_enq = queue.pop()
        tree_inc, sol_inc, cycles = consume(out, t_enq)
        now = time.monotonic()
        period, last_ready = now - last_ready, now
        if size < m:
            drain_queue()  # speculative no-ops: zero counts, state intact
            break
        if controller.after_step(tree1 + tree2, sol1 + sol2):
            drain_queue()  # no-op if the cutoff save already drained
            xwin.close()
            t2 = time.perf_counter()
            phases.append(PhaseStats(t2 - t1, tree2, sol2))
            ev.emit("checkpoint", args={"cutoff": True})
            _emit_device_explored(ctr_total, tree2, sol2, fb_tree, fb_sol)
            return SearchResult(
                explored_tree=tree1 + tree2,
                explored_sol=sol1 + sol2,
                best=best,
                elapsed=t2 - t0,
                phases=phases,
                diagnostics=diagnostics,
                complete=False,
                steps=controller.steps,
                compact=program.compact,
                compact_auto=program.compact_auto,
                megakernel=program.megakernel.state,
                megakernel_auto=program.megakernel.auto,
                megakernel_reason=program.megakernel.reason,
                megakernel_mt=program.megakernel.mt or None,
                megakernel_tiled=program.megakernel.tiled,
                kernel_backend=program.kernel_backend,
                pipeline_depth=depth,
                k_resolved=program.K,
                k_auto=k_auto,
                obs=obs_result(),
                phase_profile=ph_total,
                roofline=obs_roofline.result_audit(
                    program, ph_total, cycles_total),
                quality=qt.result() if qt is not None else None,
            )
        if ctl is not None and cycles > 0 and ctl.observe(period, cycles):
            # Geometric-ladder K resize: drain, then swap in the rung's
            # cached program (same pool state arrays — capacity does not
            # depend on K; at most len(ladder) compiles ever happen).
            drain_queue()
            program = _make_program(problem, m, M, ctl.K, capacity, device)
            ev.emit("k_resize", args={"K": program.K})
            last_ready = time.monotonic()
            if size < m:
                # The drained speculative dispatches finished the search.
                break
            continue
        if cycles == 0:
            # Capacity stall: pool too full for another device fan-out. Run
            # classic offload cycles through a host pool until there is
            # headroom again (rare; guarantees progress at any capacity).
            drain_queue()  # stalled speculative dispatches are no-ops too
            t_fb = ev.now_us()
            fb_tree0, fb_sol0 = tree2, sol2
            batch, size, best = program.residual(state)
            diagnostics.device_to_host += 1
            pool.reset_from(batch)
            if offloader is None:
                offloader = DeviceOffloader(problem, program.device)
            chunk_buf = problem.empty_batch(M)
            while pool.size >= m and pool.size + M * n > capacity:
                count = pool.pop_back_bulk(m, M, chunk_buf)
                if count == 0:
                    break
                bucket = bucket_size(count, m, M)
                snapshot = {k: v[:count].copy() for k, v in chunk_buf.items()}
                res_dev = offloader.dispatch(snapshot, count, bucket, best)
                res = problem.generate_children(
                    snapshot, count, offloader.collect(res_dev), best
                )
                tree2 += res.tree_inc
                sol2 += res.sol_inc
                best = res.best
                pool.push_back_bulk(res.children)
            diagnostics.kernel_launches += offloader.diagnostics.kernel_launches
            diagnostics.host_to_device += offloader.diagnostics.host_to_device
            diagnostics.device_to_host += offloader.diagnostics.device_to_host
            offloader.diagnostics = Diagnostics()
            state = program.init_state(pool.as_batch(), best)
            pool.clear()
            diagnostics.host_to_device += 1
            # The re-upload is a sanctioned host round trip; the next
            # dispatch is a fresh warm one for the guard.
            guard_of(program).rearm()
            last_ready = time.monotonic()
            fb_tree += tree2 - fb_tree0
            fb_sol += sol2 - fb_sol0
            ev.complete("overflow_fallback", t_fb, args={
                "tree": tree2 - fb_tree0, "sol": sol2 - fb_sol0,
            })
    xwin.close()
    batch, size, best = program.residual(state)
    diagnostics.device_to_host += 1
    pool.reset_from(batch)
    t2 = time.perf_counter()
    phases.append(PhaseStats(t2 - t1, tree2, sol2))
    _emit_device_explored(ctr_total, tree2, sol2, fb_tree, fb_sol)

    # -- phase 3: host drain ----------------------------------------------
    from .device import drain

    tree3, sol3, best = drain(problem, pool, best)
    t3 = time.perf_counter()
    phases.append(PhaseStats(t3 - t2, tree3, sol3))
    ev.counter("explored", tree=tree3, sol=sol3, phase=3)
    if qt is not None:
        # The host drain can improve the incumbent one last time.
        qt.observe(best, n_disp, tree1 + tree2 + tree3)

    return SearchResult(
        explored_tree=tree1 + tree2 + tree3,
        explored_sol=sol1 + sol2 + sol3,
        best=best,
        elapsed=t3 - t0,
        phases=phases,
        diagnostics=diagnostics,
        steps=controller.steps,
        compact=program.compact,
        compact_auto=program.compact_auto,
        megakernel=program.megakernel.state,
        megakernel_auto=program.megakernel.auto,
        megakernel_reason=program.megakernel.reason,
        megakernel_mt=program.megakernel.mt or None,
        megakernel_tiled=program.megakernel.tiled,
        kernel_backend=program.kernel_backend,
        pipeline_depth=depth,
        k_resolved=program.K,
        k_auto=k_auto,
        obs=obs_result(),
        phase_profile=ph_total,
        roofline=obs_roofline.result_audit(program, ph_total, cycles_total),
        quality=qt.result() if qt is not None else None,
    )


# -- compiled-program contracts (`tts check`, analysis/contracts.py) --------
# The fused-push / donation / steady-state-purity claims of this engine,
# declared here and verified for EVERY knob-matrix cell by
# analysis/program_audit.py (previously scattered one-cell jaxpr pins in
# tests/test_compaction.py and runtime-only guard assertions).

from ..analysis.contracts import child_value_gathers, contract  # noqa: E402


@contract(
    "fused-push-single-gather",
    claim="in EVERY survivor-path mode the compiled step contains at most "
          "ONE gather big enough to be moving child values (>= S rows of "
          "n lanes in the pool value dtype) — the single augmented "
          "(row, aux) gather of the fused prune+push; mask gathers move "
          "no node data and are exempt",
    artifact="resident-step",
)
def _contract_single_gather(art, cell):
    prog = art.prog
    n = prog.problem.child_slots
    vals_dt = np.dtype(prog.pool_fields[0][1])
    big = child_value_gathers(art.prims, prog.S, n, vals_dt)
    if len(big) <= 1:
        return []
    return [
        f"{len(big)} child-value-sized gathers in the step (budget is 1): "
        + "; ".join(str(e).splitlines()[0][:120] for e in big)
    ]


@contract(
    "pool-donation",
    claim="the resident step donates its pool buffers (input/output "
          "aliasing present in the lowered program) — pipelined dispatch "
          "chains the carry device-side and correctness of the memory "
          "budget depends on the donation never silently disappearing",
    artifact="resident-step",
)
def _contract_pool_donation(art, cell):
    txt = art.lowered_text
    if "tf.aliasing_output" in txt or "jax.buffer_donor" in txt:
        return []
    return ["no input-output aliasing in the lowered step (donation lost)"]


@contract(
    "step-callback-armed-only",
    claim="the steady-state step program contains no host callbacks and no "
          "infeed/outfeed — EXCEPT the phase-profiler variant, whose "
          "pure_callback clock reads are the armed instrument and must be "
          "present there (and only there)",
    artifact="resident-step",
)
def _contract_callbacks(art, cell):
    cbs = sorted(
        n for n in art.prim_names
        if "callback" in n or n in ("infeed", "outfeed")
    )
    armed = cell is not None and getattr(cell, "phaseprof", "0") == "1"
    if armed:
        if any("callback" in n for n in cbs):
            return []
        return ["armed phase-profiler variant lowered without its clock "
                "callback (the instrument is silently gone)"]
    if cbs:
        return [f"host-callback ops in an unarmed steady-state step: {cbs}"]
    return []


@contract(
    "program-cache-key-sound",
    claim="knobs baked into the compiled program (TTS_COMPACT, TTS_OBS, "
          "TTS_PHASEPROF, TTS_LB2_PAIRBLOCK) key the resident program "
          "cache — a flip rebuilds, never reuses stale structure; "
          "host-only knobs (TTS_PIPELINE, TTS_GUARD) hit the same cached "
          "program — they must not fork compilations",
    artifact="cache-key",
)
def _contract_cache_key(art, cell):
    out = []
    for knob, (a, b) in art.distinct.items():
        if a is b:
            out.append(f"{knob} flip reused the same cached program "
                       "(stale structure would run)")
    for knob, (a, b) in art.shared.items():
        if a is not b:
            out.append(f"{knob} flip rebuilt the program (a host-only knob "
                       "leaks into the cache key and forks compilations)")
    return out


@contract(
    "narrow-knob-inert",
    claim="TTS_NARROW never changes the compiled resident step: the device "
          "pools were always narrow (`_pool_int_dtype`) — the knob governs "
          "HOST storage/transfer/checkpoint dtypes only, so the unset "
          "(auto) build and the =0 build produce byte-identical step "
          "jaxprs with identical carry widths",
    artifact="variants",
)
def _contract_narrow_inert(art, cell):
    if not art.has("off", "narrow0"):
        return []
    out = []
    if art.text("off") != art.text("narrow0"):
        out.append("TTS_NARROW=0 build differs from the unset (auto) build "
                   "(narrow host storage leaked into the device program)")
    if art.outvars("narrow0") != art.outvars("off"):
        out.append("TTS_NARROW=0 build changed the carry width")
    return out
