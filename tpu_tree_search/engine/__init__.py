"""Search drivers: sequential, chunked single-device, fused on-device."""

from .results import SearchResult
from .sequential import sequential_search

__all__ = ["SearchResult", "sequential_search"]
