"""Sequential search driver — the correctness anchor.

Exact semantics of the reference's sequential tiers
(`nqueens_chpl.chpl:92-113`, `pfsp_chpl.chpl:191-215`): a single deque,
pop-back DFS, host decompose. Every other tier must reproduce this tier's
exploredTree/exploredSol (and optimum, for PFSP with ub=1) — SURVEY.md §4.2.
"""

from __future__ import annotations

import time

from ..obs import events as ev
from ..pool import SoAPool
from ..problems.base import INF_BOUND, Problem, batch_length, index_batch
from .results import PhaseStats, SearchResult


def sequential_search(problem: Problem, initial_best: int | None = None) -> SearchResult:
    best = (
        initial_best
        if initial_best is not None
        else getattr(problem, "initial_ub", INF_BOUND)
    )
    t0 = time.perf_counter()
    native = problem.native_sequential(best)
    if native is not None:
        tree, sol, best = native
        elapsed = time.perf_counter() - t0
        ev.counter("explored", tree=tree, sol=sol, phase=1)
        return SearchResult(
            explored_tree=tree,
            explored_sol=sol,
            best=best,
            elapsed=elapsed,
            phases=[PhaseStats(elapsed, tree, sol)],
        )

    pool = SoAPool(problem.node_fields())
    root = problem.root()
    pool.push_back(index_batch(root, 0))

    tree = 0
    sol = 0
    while True:
        node = pool.pop_back()
        if node is None:
            break
        res = problem.decompose(node, best)
        tree += res.tree_inc
        sol += res.sol_inc
        best = res.best
        n = batch_length(res.children)
        for i in range(n):
            pool.push_back(index_batch(res.children, i))
    elapsed = time.perf_counter() - t0
    ev.counter("explored", tree=tree, sol=sol, phase=1)

    return SearchResult(
        explored_tree=tree,
        explored_sol=sol,
        best=best,
        elapsed=elapsed,
        phases=[PhaseStats(elapsed, tree, sol)],
    )
