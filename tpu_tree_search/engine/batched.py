"""Instance-axis batched resident engine.

The resident engine (engine/resident.py) runs ONE search instance per
compiled program: pool-in-HBM SoA arrays plus a `lax.while_loop` that
advances up to K chunk cycles per dispatch.  For a fleet of small
same-shape jobs that leaves the MXU idle between dispatches — each job
pays the full dispatch latency alone.  Following the batch-scheduling
architecture of arXiv:2002.07062, this module makes *instance* one more
axis of the compiled program: the while-loop carry becomes a tuple of B
per-slot sub-carries (each slot = its own pool, size, incumbent and
cycle/explored counters), and one dispatch advances every live slot.

Two design rules keep the batch bit-identical to solo execution:

  * **Unrolled slots, not vmap.**  The body applies the resident
    engine's own per-instance body (``loop_fns``) to each slot and masks
    the result with that slot's own cond (``jnp.where(live, new, old)``).
    A frozen slot (terminated, stalled, or empty) discards every update
    — its cycle counter stays put — so each slot executes *exactly* the
    cycle sequence its solo program would, in the same order, with the
    same reductions.  vmap would rebuild the math with a batch axis and
    forfeit the B=1 jaxpr identity that pins this claim.
  * **Admission is a transfer, not a trace.**  ``make_slot`` builds a
    slot's carry leaves on the host (zero-padded to pool capacity) and
    `jax.device_put`s them; the jit cache key is (avals, statics), and
    every slot's leaves have the same avals by construction, so splicing
    a job into a free slot between dispatches can never trigger a
    recompile.  Both rules are pinned by `tts check` contracts at the
    bottom of this file.

The loop's global cond is the OR of the per-slot conds: the program runs
while ANY slot is live, and empty slots (size=0) are just frozen slots.
Admission/retirement happens only at dispatch boundaries on the host —
a finished or preempted slot is cut out via ``residual_slot`` /
``snapshot_slot`` (same downloads the solo engine uses for phase 3 /
checkpoints), and a new same-shape job restores into the freed slot.

Phase profiling (TTS_PHASEPROF) is a solo-only diagnostic: the phase
clock block is per-program, not per-slot, so batched builds refuse it.
Per-slot device counter blocks (TTS_OBS) are supported — each slot
carries its own block, harvested per dispatch and attributable to the
job occupying the slot.
"""

from __future__ import annotations

import os
import time

import numpy as np

from ..obs import counters as obs_counters
from ..obs import phases as obs_phases
from ..pool import SoAPool
from ..problems.base import INF_BOUND, Problem, index_batch
from .device import drain, warmup
from .pipeline import resolve_k
from .resident import _make_program, resident_search, resolve_capacity
from .results import SearchResult

# Leaves per slot in the *dispatch argument* list: pool_vals, pool_aux,
# size, best.  (The in-loop carry additionally holds the tree/sol/cycle
# scalars and the optional counter block, all seeded to zero per dispatch
# exactly as the solo step does.)
SLOT_ARGS = 4


class _BatchedProgram:
    """B-slot batched wrapper around one resident program.

    Holds the inner `_ResidentProgram` for its loop body, field layout
    and snapshot/residual transforms; compiles a single jitted step whose
    carry is a B-tuple of per-slot sub-carries.  B is baked into the
    program (fixed at trace time) — the *occupancy* varies at runtime via
    masking, never the shape.
    """

    def __init__(self, problem: Problem, B: int, m: int, M: int, K: int,
                 capacity: int, device):
        if B < 1:
            raise ValueError(f"batch slots must be >= 1, got {B}")
        if obs_phases.phase_profiling_enabled():
            # The phase clock block is a per-program diagnostic with no
            # slot attribution; refusing beats silently misattributing.
            raise RuntimeError(
                "TTS_PHASEPROF is not supported in batched builds; "
                "profile with a solo run instead")
        self.problem = problem
        self.B = int(B)
        self.inner = _make_program(problem, m, M, K, capacity, device)
        self.m = m
        self.M = self.inner.M
        self.K = self.inner.K
        self.capacity = capacity
        self.device = device
        self.obs = self.inner.obs
        self._step = self._build()

    # -- compiled step -------------------------------------------------

    def _build(self):
        import jax
        import jax.numpy as jnp
        from functools import partial
        from jax import lax

        cond1, body1 = self.inner.loop_fns()
        B, obs = self.B, self.obs

        if B == 1:
            # Pure pytree nesting: one extra tuple level is invisible in
            # the flattened jaxpr, so B=1 compiles to byte-for-byte the
            # solo step (contract `batch-b1-identity`).
            def cond(carry):
                return cond1(carry[0])

            def body(carry):
                return (body1(carry[0]),)
        else:
            def cond(carry):
                live = cond1(carry[0])
                for i in range(1, B):
                    live = live | cond1(carry[i])
                return live

            def body(carry):
                out = []
                for slot in carry:
                    live = cond1(slot)
                    new = body1(slot)
                    out.append(jax.tree_util.tree_map(
                        partial(jnp.where, live), new, slot))
                return tuple(out)

        def step(*flat):
            zero = jnp.int32(0)
            slots = []
            for i in range(B):
                pv, pa, size, best = flat[SLOT_ARGS * i:SLOT_ARGS * (i + 1)]
                init = (pv, pa, size, best, zero, zero, zero)
                if obs:
                    init = init + (obs_counters.init_block(),)
                slots.append(init)
            return lax.while_loop(cond, body, tuple(slots))

        donate = tuple(x for i in range(B)
                       for x in (SLOT_ARGS * i, SLOT_ARGS * i + 1))
        return jax.jit(step, donate_argnums=donate)

    # -- slot construction (host -> device transfers only) -------------

    def make_slot(self, frontier: dict | None, best: int) -> tuple:
        """Build one slot's dispatch args from a host frontier: zero-pad
        each pool field to capacity and `device_put` the leaves.  Pure
        transfers — no traced ops — so admission can never compile
        (contract `batch-splice-no-recompile`)."""
        import jax

        C = self.capacity
        k = 0
        if frontier is not None:
            k = int(np.asarray(frontier[self.inner.size_field]).shape[0])
        leaves = []
        for name, dtype, shape in self.inner.pool_fields:
            dt = np.dtype(dtype)
            buf = np.zeros((C,) + tuple(shape), dtype=dt)
            if k:
                buf[:k] = np.asarray(frontier[name]).astype(dt, copy=False)
            leaves.append(jax.device_put(buf, self.device))
        leaves.append(jax.device_put(np.int32(k), self.device))
        leaves.append(jax.device_put(np.int32(best), self.device))
        return tuple(leaves)

    def empty_slot(self) -> tuple:
        """A frozen slot: size=0 fails the loop cond, so it is pure
        ballast.  Each empty slot needs its OWN buffers — donation
        rejects aliased arguments."""
        return self.make_slot(None, 0)

    def slot_avals(self) -> list:
        """The aval signature one slot's dispatch args must match — aval
        equality against the compiled step's inputs IS the zero-recompile
        guarantee (jit cache key = avals + statics)."""
        import jax

        C = self.capacity
        out = [jax.ShapeDtypeStruct((C,) + tuple(shape), np.dtype(dtype))
               for _name, dtype, shape in self.inner.pool_fields]
        out.append(jax.ShapeDtypeStruct((), np.int32))
        out.append(jax.ShapeDtypeStruct((), np.int32))
        return out

    # -- dispatch + harvest --------------------------------------------

    def step(self, states: list) -> tuple:
        """One K-cycle dispatch over all B slots. `states` is a list of B
        slot arg tuples (SLOT_ARGS leaves each); returns the raw out
        carry (B sub-tuples)."""
        flat = [leaf for slot in states for leaf in slot]
        return self._step(*flat)

    def carry(self, out: tuple) -> list:
        """Next dispatch's per-slot args from a step's output."""
        return [tuple(slot[:SLOT_ARGS]) for slot in out]

    def read_slot_scalars(self, out: tuple, i: int):
        """(tree_inc, sol_inc, cycles, size, best, ctr) for slot i —
        mirrors the solo program's read_scalars."""
        slot = out[i]
        ctr = np.asarray(slot[7]) if self.obs else None
        return (int(slot[4]), int(slot[5]), int(slot[6]),
                int(slot[2]), int(slot[3]), ctr)

    def residual_slot(self, states: list, i: int):
        """Download slot i's remaining frontier for the host drain."""
        return self.inner.residual(states[i])

    def snapshot_slot(self, states: list, i: int):
        """Download slot i's full live frontier for a checkpoint cut."""
        return self.inner.snapshot(states[i])


def make_batched_program(problem: Problem, B: int, m: int, M: int, K: int,
                         capacity: int, device=None) -> _BatchedProgram:
    """Cached `_BatchedProgram` factory — one compiled program per
    (B, config); rebuilding would recompile the whole while-loop."""
    import jax

    if device is None:
        device = jax.devices()[0]
    cache = getattr(problem, "_batched_programs", None)
    if cache is None:
        cache = problem._batched_programs = {}
    from ..ops.pfsp_device import routing_cache_token

    key = (B, m, M, K, capacity, id(device),
           routing_cache_token(problem, device),
           obs_counters.device_counters_enabled())
    if key in cache:
        return cache[key]
    prog = _BatchedProgram(problem, B, m, M, K, capacity, device)
    cache[key] = prog
    return prog


def batched_search(
    problem: Problem,
    n_jobs: int,
    B: int,
    m: int = 25,
    M: int = 65536,
    K: int | str = 4096,
    capacity: int | None = None,
    device=None,
    initial_best: int | None = None,
) -> list[SearchResult]:
    """Run `n_jobs` identical searches through a B-slot batched program.

    The engine-level driver (the serve daemon's BatchExecutor is the
    multi-tenant variant): fill the slots, dispatch until a slot's pool
    drops below m, retire it (residual download + host drain, exactly the
    solo phase 3) and refill from the pending list.  Every job's result
    is bit-identical to a solo ``resident_search`` of the same spec —
    each slot's masked sub-carry executes the same cycle sequence.

    A capacity-stalled slot (frontier too big for a K-cycle fan-out) is
    cut to a checkpoint and finished by a solo ``resident_search`` resume
    — capacity can grow there, it cannot in a fixed batch slot.  Counters
    stay cumulative across the handoff, but the host-offload portion may
    order work differently than a solo run that stalled in place.
    """
    if n_jobs <= 0:
        return []
    import jax

    if device is None:
        device = jax.devices()[0]
    capacity, M = resolve_capacity(problem, M, capacity)
    _auto, k_value = resolve_k(K, default_max=4096)
    prog = make_batched_program(problem, B, m, M, k_value, capacity, device)
    best0 = (int(initial_best) if initial_best is not None
             else getattr(problem, "initial_ub", INF_BOUND))

    results: list[SearchResult | None] = [None] * n_jobs
    pending = list(range(n_jobs))
    slots: list[dict | None] = [None] * B
    states = [prog.empty_slot() for _ in range(B)]

    def admit(i: int, j: int) -> None:
        pool = SoAPool(problem.node_fields())
        pool.push_back(index_batch(problem.root(), 0))
        tree1, sol1, best = warmup(problem, pool, best0, m)
        states[i] = prog.make_slot(pool.as_batch(), best)
        slots[i] = {"job": j, "tree": tree1, "sol": sol1,
                    "t0": time.perf_counter()}

    def finish_solo(i: int, sl: dict, best: int) -> None:
        # Stall: checkpoint the slot and let the solo engine (which may
        # grow capacity on resume) finish the job.
        import tempfile

        from . import checkpoint as ckpt

        batch, _size, best = prog.snapshot_slot(states, i)
        fd, path = tempfile.mkstemp(suffix=".ckpt.npz")
        os.close(fd)
        try:
            ckpt.save(path, problem, batch, best, sl["tree"], sl["sol"])
            results[sl["job"]] = resident_search(
                problem, m=m, M=M, K=k_value, capacity=None, device=device,
                resume_from=path)
        finally:
            if os.path.exists(path):
                os.remove(path)

    for i in range(B):
        if pending:
            admit(i, pending.pop(0))

    while any(sl is not None for sl in slots):
        out = prog.step(states)
        carry = prog.carry(out)
        for i in range(B):
            states[i] = carry[i]
        for i in range(B):
            sl = slots[i]
            if sl is None:
                continue
            tree_inc, sol_inc, cycles, size, best, _ctr = \
                prog.read_slot_scalars(out, i)
            sl["tree"] += tree_inc
            sl["sol"] += sol_inc
            if _ctr is not None:
                sl["ctr"] = obs_counters.merge_host(sl.get("ctr"), _ctr)
            if size < m:
                batch, _size, best = prog.residual_slot(states, i)
                pool = SoAPool(problem.node_fields())
                if _size:
                    pool.reset_from(batch)
                tree3, sol3, best = drain(problem, pool, best)
                results[sl["job"]] = SearchResult(
                    explored_tree=sl["tree"] + tree3,
                    explored_sol=sl["sol"] + sol3,
                    best=best,
                    elapsed=time.perf_counter() - sl["t0"],
                    complete=True,
                    compact=prog.inner.compact,
                    compact_auto=prog.inner.compact_auto,
                    megakernel=prog.inner.megakernel.state,
                    megakernel_auto=prog.inner.megakernel.auto,
                    megakernel_reason=prog.inner.megakernel.reason,
                    k_resolved=prog.K,
                    obs=({"device_counters": sl["ctr"]}
                         if sl.get("ctr") is not None else None),
                )
                slots[i] = None
                if pending:
                    admit(i, pending.pop(0))
                # else: the retired carry stays as frozen ballast
                # (size < m fails its cond) — no fresh buffers needed.
            elif cycles == 0:
                finish_solo(i, sl, best)
                slots[i] = None
                if pending:
                    admit(i, pending.pop(0))
                else:
                    states[i] = prog.empty_slot()
    return [r for r in results if r is not None]


# -- contracts ---------------------------------------------------------

from ..analysis.contracts import contract  # noqa: E402


@contract(
    "batch-b1-identity",
    claim="the B=1 batched step's jaxpr is byte-identical to the solo "
          "resident step's: the instance axis is pure pytree nesting, "
          "invisible to the flattened program, so --batch-slots 1 IS "
          "today's path with zero structural drift",
    artifact="batched-step",
)
def _contract_b1_identity(art, cell):
    if art.get("b1_text") is None:
        return []
    if art["b1_text"] == art["resident_text"]:
        return []
    return ["B=1 batched jaxpr differs from the solo resident step jaxpr"]


@contract(
    "batch-splice-no-recompile",
    claim="slot admission is a device_put into the donated carry, never "
          "a new program: make_slot's leaf avals equal the compiled "
          "step's per-slot input avals exactly, and the jit cache key is "
          "(avals, statics) — aval equality IS the zero-recompile "
          "guarantee for mid-flight splices",
    artifact="batched-step",
)
def _contract_splice_no_recompile(art, cell):
    slot = art["slot_avals"]
    carry = art["carry_avals"]
    B = art["B"]
    msgs = []
    if len(carry) != len(slot) * B:
        msgs.append(
            f"step takes {len(carry)} leaves, expected "
            f"{len(slot)} x {B} slots")
        return msgs
    for b in range(B):
        for j, want in enumerate(slot):
            got = carry[b * len(slot) + j]
            if got != want:
                msgs.append(
                    f"slot {b} leaf {j}: splice aval {want} != "
                    f"carry aval {got}")
    return msgs
