"""Checkpoint / resume.

The reference has no checkpointing (SURVEY.md §5: a crashed run loses the
search). But the pool *is* the complete search state — the frontier plus the
incumbent and the counters determine the rest of the run exactly — so a
checkpoint is one serialized NodeBatch + four scalars. The resident tiers
snapshot on a wall-clock cadence (downloading the device pool costs one
host transfer, so snapshots are amortized over many K-cycle blocks); a
resumed search seeds phase 2 from the saved frontier and keeps counting
where the saved run stopped.

Format: one ``.npz`` written atomically (tmp + rename), holding the node
fields plus a JSON header identifying the problem. Resuming validates the
header against the live problem to refuse mixing incompatible searches.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass

import numpy as np

from ..problems.base import NodeBatch, Problem

# v2: PFSP meta carries a p_times digest (ptimes_sha).
# v3 (multi-host) / v2 (single-host): multi-host per-host files write the
# higher version so a pre-v3 reader — which has no hosts/cut coherence
# checks — refuses them instead of silently resuming one host's share as
# the whole frontier.
# v4 (multi-host) / v3 (single-host): narrow node storage (TTS_NARROW,
# problems/base.py) — field arrays are saved at the problem's storage
# dtypes (int8/int16), shrinking payloads ~4x. The npz is self-describing,
# so the loader casts every field to the LIVE problem's node_fields dtypes
# on the way in: old wide files resume under narrow runtimes, narrow files
# resume under TTS_NARROW=0, bit-identically either way (node values are
# range-proven for the narrow dtypes by construction).
FORMAT_VERSION = 4
_SINGLE_HOST_VERSION = 3


class RunController:
    """Shared max-steps / periodic-checkpoint bookkeeping for the resident
    tiers. ``snapshot_fn() -> (batch, best)`` downloads the live frontier;
    ``after_step(tree, sol)`` returns True when the run must stop now (the
    cutoff checkpoint, if requested, has already been written).

    ``drain_fn() -> (tree_inc, sol_inc)``: under pipelined dispatch
    (engine/pipeline.py) the frontier snapshot includes the work of every
    in-flight speculative dispatch, so a cut must first drain their scalar
    counts or the saved counters would lag the saved frontier (a resumed
    run would under-count).  Called exactly once, right before a snapshot
    is taken; the engine's drain also folds the increments into its own
    running totals.

    ``yield_fn() -> bool``: cooperative preemption (the serve daemon's
    seam, ``tpu_tree_search/serve/``). Checked at every dispatch boundary
    like the ``max_steps`` cutoff; returning True cuts the run NOW — the
    queue drains, the frontier snapshots, the checkpoint (if a path is
    set) is written — and the engine returns ``complete=False``. A
    resumed search from that cut reproduces the uninterrupted result
    bit-for-bit (the frontier + incumbent + counters are the complete
    search state), which is what makes preemption safe to impose on a
    tenant's job."""

    def __init__(
        self,
        problem: Problem,
        checkpoint_path: str | None,
        interval_s: float,
        max_steps: int | None,
        snapshot_fn,
        drain_fn=None,
        yield_fn=None,
    ):
        import time

        self.problem = problem
        self.path = checkpoint_path
        self.interval_s = interval_s
        self.max_steps = max_steps
        self.snapshot_fn = snapshot_fn
        self.drain_fn = drain_fn
        self.yield_fn = yield_fn
        self.steps = 0
        self._clock = time.monotonic
        self._last = self._clock()

    def _save(self, tree: int, sol: int) -> None:
        if self.drain_fn is not None:
            dt, ds = self.drain_fn()
            tree += dt
            sol += ds
        batch, best = self.snapshot_fn()
        save(self.path, self.problem, batch, best, tree, sol)

    def after_step(self, tree: int, sol: int) -> bool:
        self.steps += 1
        cut = self.max_steps is not None and self.steps >= self.max_steps
        if not cut and self.yield_fn is not None:
            cut = bool(self.yield_fn())
        if cut:
            if self.path is not None:
                self._save(tree, sol)
            return True
        if self.path is not None and self._clock() - self._last >= self.interval_s:
            self._save(tree, sol)
            self._last = self._clock()
        return False


def lockstep_commit(ok: bool, staging: str, final: str, vote=None) -> bool:
    """Two-phase commit of a staged per-host checkpoint file — the ONE
    copy of the protocol shared by the dist and dist_mesh tiers: optionally
    vote across hosts (``vote(bool) -> list[bool]``, an allgather), commit
    the rename only if EVERY host staged successfully, otherwise discard
    the staging file so the set stays on the previous coherent cut. A
    vetoed/failed cut warns on stderr — silently keeping a stale file
    while the CLI tells the user to resume would lose budgeted work."""
    import sys

    if vote is not None:
        ok = all(vote(bool(ok)))
    if ok:
        os.replace(staging, final)
    else:
        if os.path.exists(staging):
            os.remove(staging)
        print(
            f"[checkpoint] lockstep cut NOT committed ({final}); the "
            "previous coherent cut (if any) is retained",
            file=sys.stderr,
        )
    return ok


@dataclass
class Checkpoint:
    meta: dict  # problem identity, see problem_meta()
    batch: NodeBatch  # the frontier
    best: int
    tree: int
    sol: int
    hosts: int = 1  # multi-host sets: total per-host files in this cut
    # Dist tier: identity of the lockstep cut this file belongs to
    # ("<run-uuid>:<round>", stamped identically on every host of the cut);
    # older files carry the bare communicator round (int). None = timer cut.
    cut_tag: int | str | None = None


def problem_meta(problem: Problem) -> dict:
    meta = {"problem": problem.name}
    if problem.name == "nqueens":
        meta.update(N=problem.N, g=problem.g)
    elif problem.name == "pfsp":
        import hashlib

        # Digest of the processing-times matrix: two ad-hoc instances with
        # the same (jobs, machines) but different p_times must not resume
        # each other's frontiers (inst=None alone cannot tell them apart).
        pt = np.ascontiguousarray(problem.lb1_data.p_times, dtype=np.int64)
        digest = hashlib.sha256(pt.tobytes()).hexdigest()[:16]
        meta.update(inst=getattr(problem, "inst", None), lb=problem.lb,
                    ub=problem.ub, jobs=problem.jobs, machines=problem.machines,
                    ptimes_sha=digest)
        # Johnson pair subset (bounds.LB2_VARIANTS): a non-full variant
        # prunes a different tree, so its frontier must not resume a full
        # run's (and vice versa). Stamped only when non-default, so every
        # pre-variant checkpoint keeps loading against full-variant runs.
        if getattr(problem, "lb2_variant", "full") != "full":
            meta.update(lb2_variant=problem.lb2_variant)
    return meta


def save(path: str, problem: Problem, batch: NodeBatch, best: int, tree: int,
         sol: int, hosts: int = 1, cut_tag: int | str | None = None) -> None:
    header = {
        "version": FORMAT_VERSION if hosts > 1 else _SINGLE_HOST_VERSION,
        "meta": problem_meta(problem),
        "best": int(best),
        "tree": int(tree),
        "sol": int(sol),
        "fields": sorted(batch.keys()),
        "hosts": int(hosts),
        "cut_tag": cut_tag,
    }
    arrays = {f"field_{k}": v for k, v in batch.items()}
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        np.savez_compressed(
            f, header=np.frombuffer(json.dumps(header).encode(), dtype=np.uint8),
            **arrays,
        )
    os.replace(tmp, path)


def load(path: str, problem: Problem, expect_hosts: int = 1) -> Checkpoint:
    """``expect_hosts``: the host count of the resuming run. A per-host file
    from an H-host cut resumed into a different-H run would silently drop
    (or double-explore) the other hosts' shares — refuse loudly instead."""
    with np.load(path) as data:
        header = json.loads(bytes(data["header"]).decode())
        if header["version"] not in (1, 2, _SINGLE_HOST_VERSION, FORMAT_VERSION):
            raise ValueError(f"unsupported checkpoint version {header['version']}")
        want = problem_meta(problem)
        got = dict(header["meta"])
        if header["version"] == 1:
            # v1 predates the p_times digest, and v1-era writers stamped the
            # constructor-default inst even for ad-hoc matrices — so a v1
            # PFSP meta claiming inst=14 may belong to a different matrix
            # entirely and its frontier would silently resume with wrong
            # bounds. NQueens meta (N, g) fully determines the search, so v1
            # NQueens checkpoints remain resumable; every v1 PFSP file is
            # refused.
            if got.get("problem") != "nqueens":
                raise ValueError(
                    "v1 PFSP checkpoints cannot be trusted: the format "
                    "predates the p_times digest and may impersonate a named "
                    "Taillard instance; re-run from scratch"
                )
            got.pop("ptimes_sha", None)
        if got != want:
            raise ValueError(
                f"checkpoint is for {header['meta']}, not {problem_meta(problem)}"
            )
        hosts = int(header.get("hosts", 1))
        if hosts != expect_hosts:
            raise ValueError(
                f"checkpoint is 1 of {hosts} per-host files; resuming with "
                f"{expect_hosts} host(s) would lose or double-explore the "
                "other shares (resume with the original host count)"
            )
        # Cast every field to the LIVE problem's storage dtypes: the file
        # may predate narrow storage (wide int32 payloads) or have been
        # written under the opposite TTS_NARROW setting — the npz carries
        # the dtypes, so the cast is exact in both directions.
        fields = problem.node_fields()
        batch = {
            k: (np.asarray(data[f"field_{k}"]).astype(fields[k][1])
                if k in fields else data[f"field_{k}"])
            for k in header["fields"]
        }
    return Checkpoint(
        meta=header["meta"], batch=batch,
        best=header["best"], tree=header["tree"], sol=header["sol"],
        hosts=hosts, cut_tag=header.get("cut_tag"),
    )
