"""Single-device chunked-offload search driver.

TPU-first re-design of the reference's 3-step single-GPU drivers
(`nqueens_gpu_chpl.chpl:152-245`, `pfsp_gpu_chpl.chpl:306-452`):

  step 1  CPU BFS warm-up: pop-front + host decompose until the pool holds at
          least ``warmup_target`` nodes (`nqueens_gpu_chpl.chpl:169-175`);
  step 2  hot loop: pop a back chunk of ``m..M`` parents, evaluate all
          children on device, prune/branch on host, push survivors
          (`nqueens_gpu_chpl.chpl:197-215`);
  step 3  CPU DFS drain of the remainder (`nqueens_gpu_chpl.chpl:230-236`).

Differences from the reference, driven by the XLA compilation model
(SURVEY.md §7.3):

  * **Shape bucketing.** `popBackBulk` yields a variable chunk size; XLA
    wants static shapes. Chunks are padded to power-of-two buckets so at most
    ~log2(M/m) compilations ever happen; padded slots carry a cloned valid
    node and their results are sliced away before the host prune. (The
    reference always allocates full-M device buffers and launches
    size-dependent grids, `pfsp_gpu_chpl.chpl:356-360` — on TPU the bucket
    pad is the analogue.)
  * **Async dispatch overlap.** JAX dispatch is asynchronous: the driver
    pops and dispatches chunk i+1 *before* consuming chunk i's device
    results, overlapping device compute with the host-side prune/branch of
    the previous chunk — the reference's loop is fully synchronous
    (`pfsp_gpu_chpl.chpl:373-396`). With a fixed incumbent (ub=1, or
    N-Queens which never prunes) the explored tree is provably identical;
    with an improving incumbent it is a valid B&B relaxation (same optimum,
    possibly different node count — same property the reference's multi-GPU
    tier already has, SURVEY.md §2.4.4).
"""

from __future__ import annotations

import time

import numpy as np

from ..pool import SoAPool
from ..problems.base import INF_BOUND, Problem, batch_length, index_batch
from .results import Diagnostics, PhaseStats, SearchResult


def bucket_size(count: int, m: int, M: int) -> int:
    """Smallest power-of-two bucket >= count, clamped to [next_pow2(m), M].

    The lower clamp matters for the tail of the search: step 2 never pops
    fewer than m nodes, but warm-up targets and tests can push small counts —
    folding them all into the m-bucket keeps the number of compiled shapes at
    ~log2(M/m) + 1.
    """
    lo = 1
    while lo < m:
        lo *= 2
    b = lo
    while b < count:
        b *= 2
    return min(b, M)


def pad_chunk(parents: dict, count: int, bucket: int) -> dict:
    """Pad a popped chunk up to its bucket by cloning node 0 into the tail.

    A cloned valid node (not zeros) keeps device arithmetic in-range for any
    problem; its result slots are ignored (`generate_children` reads only
    ``[:count]``, matching the reference's untouched-slot convention,
    SURVEY.md Appendix A).
    """
    if count >= bucket:
        return {name: arr[:bucket] for name, arr in parents.items()}
    out = {}
    for name, arr in parents.items():
        buf = np.empty((bucket,) + arr.shape[1:], dtype=arr.dtype)
        buf[:count] = arr[:count]
        buf[count:] = arr[0]
        out[name] = buf
    return out


class DeviceOffloader:
    """Owns the device-side evaluator + transfer bookkeeping for one device.

    Counts launches/copies like Chapel's GpuDiagnostics
    (`pfsp_gpu_chpl.chpl:454-466`).

    Double-buffered staging: ``stage()`` copies a popped chunk into one of
    TWO reusable bucket-sized host buffers per bucket shape (pre-padded, no
    per-chunk allocation), alternating buffers so chunk k+1 can stage and
    ``device_put`` while chunk k's staged buffer still backs an in-flight
    dispatch — the H2D of the next chunk overlaps the device evaluation of
    the current one. Two buffers are exactly enough for the drivers'
    one-pending overlap discipline (dispatch k+1 before consuming k);
    ``Diagnostics.double_buffered`` counts the dispatches that actually
    overlapped an in-flight one.
    """

    def __init__(self, problem: Problem, device=None):
        import jax

        self.problem = problem
        self.device = device if device is not None else jax.devices()[0]
        self._evaluate = problem.make_device_evaluator(self.device)
        self.diagnostics = Diagnostics()
        # bucket -> [buf, buf] of {name: np.ndarray((bucket,)+shape)};
        # allocated lazily on first use of each bucket shape.
        self._staging: dict[int, list] = {}
        self._flip: dict[int, int] = {}

    def stage(self, chunk: dict, count: int, bucket: int) -> dict:
        """Copy+pad ``chunk[:count]`` into the bucket's next staging buffer
        (the `pad_chunk` convention: tail slots clone row 0) and return it.
        The returned dict stays valid until the SECOND-next ``stage`` of
        the same bucket — long enough for the one-pending overlap."""
        bufs = self._staging.setdefault(bucket, [None, None])
        i = self._flip.get(bucket, 0)
        self._flip[bucket] = 1 - i
        buf = bufs[i]
        if buf is None:
            buf = bufs[i] = {
                name: np.empty((bucket,) + arr.shape[1:], dtype=arr.dtype)
                for name, arr in chunk.items()
            }
        for name, arr in chunk.items():
            dst = buf[name]
            dst[:count] = arr[:count]
            if count < bucket:
                dst[count:] = arr[0]
        return buf

    def dispatch_staged(self, staged: dict, count: int, best: int,
                        overlapped: bool = False):
        """H2D + async kernel dispatch of an already-padded staging buffer;
        returns an unmaterialized device result. ``overlapped=True`` records
        that another dispatch was still in flight (the double-buffer
        counter the bench/report read)."""
        import jax

        parents_dev = {
            k: jax.device_put(v, self.device) for k, v in staged.items()
        }
        self.diagnostics.host_to_device += 1
        if overlapped:
            self.diagnostics.double_buffered += 1
        result = self._evaluate(parents_dev, count, best)
        self.diagnostics.kernel_launches += 1
        return result

    def dispatch(self, parents_np: dict, count: int, bucket: int, best: int):
        """Classic one-shot path (pads a fresh snapshot): kept for the rare
        overflow-fallback call sites that dispatch synchronously."""
        padded = pad_chunk(parents_np, count, bucket)
        return self.dispatch_staged(padded, count, best)

    def collect(self, result) -> np.ndarray:
        """D2H (blocks until the device result is ready)."""
        out = np.asarray(result)
        self.diagnostics.device_to_host += 1
        return out


def warmup(problem: Problem, pool: SoAPool, best: int, target: int):
    """Step 1: breadth-first host expansion until ``pool.size >= target``
    (`nqueens_gpu_chpl.chpl:169-175`). Pops from the *front* so the leftover
    pool is shallow-first (SURVEY.md Appendix A warm-up note).
    Returns (tree_inc, sol_inc, best).
    """
    if pool.size > 0 and pool.size < target:
        native = problem.native_warmup(pool.as_batch(), best, target)
        if native is not None:
            frontier, tree, sol, best = native
            pool.reset_from(frontier)
            return tree, sol, best
    tree = 0
    sol = 0
    while pool.size > 0 and pool.size < target:
        node = pool.pop_front()
        res = problem.decompose(node, best)
        tree += res.tree_inc
        sol += res.sol_inc
        best = res.best
        pool.push_back_bulk(res.children)
    return tree, sol, best


def drain(problem: Problem, pool: SoAPool, best: int):
    """Step 3: host DFS of whatever is left (`nqueens_gpu_chpl.chpl:230-236`)."""
    if pool.size > 0:
        native = problem.native_drain(pool.as_batch(), best)
        if native is not None:
            pool.reset_from(problem.empty_batch(0))
            return native
    tree = 0
    sol = 0
    while True:
        node = pool.pop_back()
        if node is None:
            break
        res = problem.decompose(node, best)
        tree += res.tree_inc
        sol += res.sol_inc
        best = res.best
        n = batch_length(res.children)
        for i in range(n):
            pool.push_back(index_batch(res.children, i))
    return tree, sol, best


def device_search(
    problem: Problem,
    m: int = 25,
    M: int = 50000,
    device=None,
    initial_best: int | None = None,
    overlap: bool = True,
    warmup_target: int | None = None,
) -> SearchResult:
    best = (
        initial_best
        if initial_best is not None
        else getattr(problem, "initial_ub", INF_BOUND)
    )
    pool = SoAPool(problem.node_fields())
    pool.push_back(index_batch(problem.root(), 0))
    off = DeviceOffloader(problem, device)

    from ..obs import flightrec as fr

    fr.arm("offload")
    phases: list[PhaseStats] = []
    t0 = time.perf_counter()

    # -- step 1: warm-up ---------------------------------------------------
    target = m if warmup_target is None else warmup_target
    tree1, sol1, best = warmup(problem, pool, best, target)
    t1 = time.perf_counter()
    phases.append(PhaseStats(t1 - t0, tree1, sol1))

    # -- step 2: chunked offload loop --------------------------------------
    tree2 = 0
    sol2 = 0
    chunk_buf = problem.empty_batch(M)
    pending = None  # (staged_buffer, count, device_result)

    n_chunk = 0  # completed-chunk sequence (flight-recorder registry)

    def consume(p):
        nonlocal tree2, sol2, best, n_chunk
        parents_np, count, dev_result = p
        results = off.collect(dev_result)
        res = problem.generate_children(parents_np, count, results, best)
        tree2 += res.tree_inc
        sol2 += res.sol_inc
        best = res.best
        pool.push_back_bulk(res.children)
        n_chunk += 1
        fr.heartbeat("offload", seq=n_chunk, size=pool.size, best=best,
                     tree=tree2, sol=sol2)

    while True:
        count = pool.pop_back_bulk(m, M, chunk_buf)
        if count == 0:
            if pending is not None:
                consume(pending)
                pending = None
                continue  # children may refill the pool past m
            break
        bucket = bucket_size(count, m, M)
        # Double-buffered staging: the copy+pad reuses one of two
        # bucket-sized host buffers, so staging+H2D of this chunk overlaps
        # the in-flight evaluation of the pending one (no per-chunk
        # allocation; the pending chunk's buffer is the other one).
        staged = off.stage(chunk_buf, count, bucket)
        dev_result = off.dispatch_staged(
            staged, count, best, overlapped=pending is not None
        )
        if overlap and pending is not None:
            consume(pending)
            pending = (staged, count, dev_result)
        elif overlap:
            pending = (staged, count, dev_result)
        else:
            consume((staged, count, dev_result))
    t2 = time.perf_counter()
    phases.append(PhaseStats(t2 - t1, tree2, sol2))

    # -- step 3: drain ------------------------------------------------------
    tree3, sol3, best = drain(problem, pool, best)
    t3 = time.perf_counter()
    phases.append(PhaseStats(t3 - t2, tree3, sol3))

    return SearchResult(
        explored_tree=tree1 + tree2 + tree3,
        explored_sol=sol1 + sol2 + sol3,
        best=best,
        elapsed=t3 - t0,
        phases=phases,
        diagnostics=off.diagnostics,
    )
