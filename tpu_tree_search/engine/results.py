"""Search result/report structures.

Reproduces the reference's self-reported metrics (SURVEY.md §6): exploredTree,
exploredSol, optimum, elapsed time, the 3-phase breakdown of the offload
tiers (`nqueens_gpu_chpl.chpl:178-245`), and offload diagnostics counters
(GpuDiagnostics equivalent, `pfsp_gpu_chpl.chpl:454-466`).
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class PhaseStats:
    """One phase's deltas (`res1/res2/res3`, `nqueens_gpu_chpl.chpl:178-245`)."""

    seconds: float = 0.0
    tree: int = 0
    sol: int = 0


@dataclass
class Diagnostics:
    """Offload counters (Chapel GpuDiagnostics: kernel_launch /
    host_to_device / device_to_host, `nqueens_gpu_chpl.chpl:278-283`).
    """

    kernel_launches: int = 0
    host_to_device: int = 0
    device_to_host: int = 0
    # Offload engine: dispatches whose H2D staging overlapped an
    # in-flight device evaluation (the double-buffer fast path,
    # `engine/device.py DeviceOffloader.stage`).
    double_buffered: int = 0


@dataclass
class SearchResult:
    explored_tree: int = 0
    explored_sol: int = 0
    best: int | None = None  # final incumbent (PFSP optimum)
    elapsed: float = 0.0
    phases: list[PhaseStats] = field(default_factory=list)
    diagnostics: Diagnostics = field(default_factory=Diagnostics)
    # multi-device extras (`pfsp_multigpu_chpl.chpl:518-522`)
    per_worker_tree: list[int] = field(default_factory=list)
    # False when the run stopped early (max_steps cutoff) and saved a
    # checkpoint instead of finishing; counters cover work done so far.
    complete: bool = True
    # Resident tiers: dispatch-boundary steps the RunController counted
    # this run (one per consumed K-cycle dispatch). The serve scheduler
    # accumulates this across preemption slices so a max_steps budget
    # spans resumes; 0 for tiers without a controller.
    steps: int = 0
    # multi/dist tiers: successful intra-host work steals (the reference
    # declares nSteal counters but never reports them,
    # `pfsp_multigpu_chpl.chpl:380`).
    steals: int = 0
    # dist tier: inter-host communicator totals (exchange rounds, stolen
    # blocks/nodes), summed across hosts.
    comm: dict | None = None
    # dist/dist_mesh tiers: the resolved steal policy (TTS_STEAL,
    # parallel/topology.py) — {"mode", "pods", "levels": {link: {level,
    # every, period_s, quantum, source}}} where source names the
    # COSTMODEL.json profile key the quantum/period resolved from (or
    # "fixed"). Identical on every host; None for tiers without an
    # inter-host communicator.
    steal_policy: dict | None = None
    # Resident tiers: the survivor-path compaction mode the compiled step
    # baked in (ops/compaction.py — "dense"/"scatter"/"sort"/"search"),
    # with compact_auto True when the TTS_COMPACT=auto policy chose it.
    # None for tiers that prune on host and never compact.
    compact: str | None = None
    compact_auto: bool = False
    # Resident tiers: the one-kernel cycle state the compiled step baked
    # in (TTS_MEGAKERNEL, ops/megakernel.py) — "on"/"off", with
    # megakernel_auto True when the auto policy decided and, when the
    # kernel refused to arm (or auto declined), the recorded reason.
    # None for tiers without a resident program.
    megakernel: str | None = None
    megakernel_auto: bool = False
    megakernel_reason: str | None = None
    # Resident tiers, armed builds: the resolved streamed pool-tile width
    # Mt and whether the pool axis actually tiled (grid > 1 — the
    # double-buffered HBM->VMEM streaming form; False is the single-tile
    # pool-resident form). None/False when the kernel is off.
    megakernel_mt: int | None = None
    megakernel_tiled: bool = False
    # Resident tiers: the kernel flavor the backend seam resolved for this
    # build (TTS_KERNEL_BACKEND, ops/backend.py) — "tpu" (the flavor of
    # record, including jnp-routed and interpret-forced builds) or "gpu"
    # (the Triton-structured lowering). None for tiers without a resident
    # program.
    kernel_backend: str | None = None
    # Roofline audit (obs/roofline.py): per-phase %-of-memory-bound-peak
    # computed from the phase_profile ns splits, the analytic per-cycle
    # byte floors, and the resolved peak HBM bandwidth (COSTMODEL "hbm"
    # link / TTS_HBM_GBPS / nominal backend table) — {"peak_gbps",
    # "peak_source", "cycles", "phases": [{phase, ns, bytes, gbps,
    # pct_of_peak}, ...]}. None when the phase profiler is off.
    roofline: dict | None = None
    # Resident tiers: dispatch-pipeline depth the host loop ran with
    # (TTS_PIPELINE — 1 = synchronous, >= 2 = speculative), the K the
    # loop ended on, and whether TTS_K=auto resolved it (engine/pipeline.py).
    pipeline_depth: int = 1
    k_resolved: int | None = None
    k_auto: bool = False
    # Telemetry snapshot (TTS_OBS=1, docs/OBSERVABILITY.md): per-run totals
    # of the on-device counter block harvested at dispatch boundaries
    # ({"device_counters": {popped, pushed, leaves, pruned, overflow,
    # pool_hwm, surv_hwm}}). None when obs is off — the default-off path
    # carries no cost and no payload.
    obs: dict | None = None
    # Per-phase device-time totals in nanoseconds (TTS_PHASEPROF=1 /
    # `tts profile`, obs/phases.py): {pop, eval, compact, push, overflow,
    # balance, loop, total} harvested from the armed program variant's
    # phase-clock block. The in-cycle slots sum to `total` exactly; for
    # the mesh tiers the values aggregate across shards (shares stay
    # D-invariant). None when the profiler is off. (`phases` above is the
    # host-side 3-phase wall-clock breakdown — a different axis.)
    phase_profile: dict | None = None
    # Anytime quality telemetry (TTS_QUALITY=1 or a serve-bound recorder,
    # obs/quality.py): {"optimum": best-known reference or None, "points":
    # [{t_s, step, best, nodes}, ...]} — one point per incumbent
    # improvement, harvested host-side at dispatch boundaries. None when
    # the recorder is off (the default path records nothing and the
    # compiled step is byte-identical either way).
    quality: dict | None = None

    def workload_shares(self) -> list[float]:
        """Per-worker share of explored nodes (load-balance report,
        `nqueens_multigpu_chpl.chpl:337`)."""
        total = sum(self.per_worker_tree)
        if not total:
            return []
        return [100.0 * t / total for t in self.per_worker_tree]
