"""Async pipelined dispatch + adaptive-K control for the host loops.

Every host loop in the repo used to be fully synchronous: dispatch the
compiled step, block reading its lagged scalars, decide, dispatch again —
so the device sat idle for a full host round trip (~360 ms through the
tunnel, docs/HW_VALIDATION.md) between every K-cycle block, exactly the
serialization the reference pays per chunk (`pfsp_gpu_chpl.chpl:373-396`).
JAX dispatch is asynchronous: enqueueing step k+1 *before* reading step
k's scalars keeps the device queue non-empty across the host round trip
(the transfer/compute-overlap playbook of arXiv 1904.06825 and the batch
host-loop pipelining of arXiv 2002.07062).

Speculation here is **exact**, not approximate: the compiled step's
while-cond (``size >= m``) makes a dispatch on a terminated or stalled
pool a zero-cycle no-op — the carry passes through untouched and every
counter increment is zero — so a speculatively enqueued step after
termination changes nothing (pinned by tests/test_pipeline.py's
no-op-dispatch invariant test).  The host reads only the small scalar
outputs of each dispatch (tree/sol/cycles/size/best and the obs counter
block); the donated pool carry is never forced — it flows device-side
from one dispatch's output into the next dispatch's input.

Knobs
-----

``TTS_PIPELINE``: dispatch queue depth. ``0``/``1`` = synchronous (one
dispatch in flight — the pre-pipeline behavior), ``2``/``3`` = that many
speculative dispatches in flight, ``auto`` (default) = 2.  Exactness does
not depend on the depth; bit-parity across depths is a test axis
(tests/test_cross_tier_fuzz.py).

``TTS_K``: K-cycles-per-dispatch schedule. An integer pins K; ``auto``
enables the :class:`AdaptiveK` controller — measure the host period per
dispatch from the obs-span clock and resize K along a **geometric
ladder** toward a target period, so the program cache (which keys on K)
sees at most ``len(ladder)`` distinct compilations and steady state stays
recompile-free (each rung's program compiles once, on a sanctioned warm
dispatch; re-selecting a rung is a cache hit).  The ladder cap is the
caller's K (the tier default when the CLI passes ``--K auto``); the
mesh/dist tiers hand the controller a tighter target band so K never
grows past their steal/exchange responsiveness.
"""

from __future__ import annotations

import os
from collections import deque

#: Hard cap on the in-flight dispatch queue: beyond 3 the lagged scalars
#: stop informing anything (termination detection lags `depth` dispatches,
#: each a no-op after the fact but still enqueue latency at shutdown).
MAX_DEPTH = 3

#: Default host-period target band (seconds) for ``TTS_K=auto`` on the
#: single-device resident tier: dispatches shorter than the band waste a
#: growing fraction of wall time on host round trips; longer ones delay
#: termination detection and checkpoint cadence.
RESIDENT_TARGET = (0.100, 0.250)

#: Tighter band for the mesh/dist tiers: incumbent folds, diffusion
#: balancing, and the inter-host exchange all happen at dispatch
#: boundaries, so K is bounded by steal/exchange responsiveness, not just
#: dispatch overhead.
MESH_TARGET = (0.050, 0.150)


def resolve_target_band(
    tier: str,
    default: tuple[float, float],
    problem=None,
    topology: str = "",
) -> tuple[tuple[float, float], str | None]:
    """The AdaptiveK target band for one run: ``(band, source)``.

    With ``TTS_COSTMODEL=<profile>`` set and a usable entry in it, the
    band derives from the profile's MEASURED per-dispatch latency fit
    (obs/costmodel.py — the arXiv:1904.06825 latency+bandwidth model);
    otherwise ``default`` (the documented fixed band) with source None.
    Because the mesh/dist tiers fold incumbents, run diffusion rounds,
    and exchange at dispatch boundaries, this band IS their steal and
    exchange period — resolving it from the profile paces those too.

    A band only moves K along the existing geometric ladder: search
    results stay bit-identical to the fixed-band fallback by construction
    (tests/test_costmodel.py pins it).
    """
    path_env = os.environ.get("TTS_COSTMODEL", "") or ""
    if path_env in ("", "0"):
        return default, None
    from ..obs import costmodel as cm

    profile = cm.load(path_env)
    if not profile:
        return default, None
    try:
        # The kernel-backend seam's profile key: the raw platform under
        # auto (byte-stable with every banked profile), a compound
        # "platform+kind" for a forced non-native flavor so its bands
        # never contaminate the native rows (ops/backend.profile_backend).
        from ..ops import backend as BK

        backend = BK.profile_backend()
    except Exception:  # noqa: BLE001 — band resolution must never fail a run
        backend = "cpu"
    hit = cm.lookup(profile, backend, topology, cm.shape_class(problem))
    if hit is None:
        return default, None
    key, entry = hit
    band = cm.resolve_band(entry, tier)
    if band is None:
        return default, None
    return band, key


def pipeline_mode() -> str:
    """The raw ``TTS_PIPELINE`` knob (``auto`` default)."""
    return os.environ.get("TTS_PIPELINE", "auto") or "auto"


def resolve_pipeline_depth(knob: str | int | None = None) -> int:
    """Dispatch queue depth: 1 = synchronous, >= 2 = pipelined.

    ``0`` and ``1`` both mean synchronous (``0`` is the natural "off"
    spelling; a queue always holds at least the dispatch being read).
    ``auto`` resolves to 2 — speculation is exact at any depth, and one
    speculative dispatch already hides a full host round trip.
    """
    if knob is None:
        knob = pipeline_mode()
    if knob == "auto":
        return 2
    try:
        depth = int(knob)
    except (TypeError, ValueError):
        raise ValueError(
            f"TTS_PIPELINE must be 'auto' or an integer 0..{MAX_DEPTH}, "
            f"got {knob!r}"
        ) from None
    if depth < 0 or depth > MAX_DEPTH:
        raise ValueError(
            f"TTS_PIPELINE must be in 0..{MAX_DEPTH} (got {depth}); "
            "0/1 = synchronous, 2/3 = speculative depth"
        )
    return max(1, depth)


def resolve_k(K: int | str, default_max: int) -> tuple[bool, int]:
    """Resolve the K schedule for one search: ``(auto, k)``.

    ``auto=True``: adaptive ladder capped at ``k``; ``auto=False``: fixed
    ``k``.  Precedence: the ``TTS_K`` env knob (``auto`` or an integer)
    overrides the engine parameter — so a test matrix can pin the whole
    suite without threading a kwarg through every tier — and a parameter
    of ``"auto"`` (the CLI's ``--K auto``) requests adaptation capped at
    the tier default.
    """
    knob = (os.environ.get("TTS_K") or "").strip()
    if knob:
        if knob == "auto":
            kmax = default_max if isinstance(K, str) else int(K)
            return True, max(1, kmax)
        try:
            return False, max(1, int(knob))
        except ValueError:
            raise ValueError(
                f"TTS_K must be 'auto' or a positive integer, got {knob!r}"
            ) from None
    if isinstance(K, str):
        if K != "auto":
            raise ValueError(f"K must be an integer or 'auto', got {K!r}")
        return True, max(1, default_max)
    return False, max(1, int(K))


class AdaptiveK:
    """Geometric-ladder K controller (``TTS_K=auto``).

    Rungs are ``k_max, k_max/4, k_max/16, ...`` down to 1 (ascending
    internally); the controller starts on the lowest rung (fast first
    feedback) and, fed one ``observe(period_s, cycles)`` per dispatch,
    climbs one rung when a full-K dispatch at the *next* rung is still
    predicted inside the target band, and drops rungs when the measured
    period overshoots the band.  Ladder-only K values mean the engines'
    program caches see a bounded set of compilations — the zero
    steady-state recompiles guarantee rides the caches' existing K key.
    """

    def __init__(self, k_max: int, target: tuple[float, float] | None = None,
                 factor: int = 4):
        k_max = max(1, int(k_max))
        rungs = [k_max]
        while rungs[-1] > 1 and len(rungs) < 8:
            rungs.append(max(1, rungs[-1] // factor))
        self.ladder: tuple[int, ...] = tuple(rungs[::-1])
        self.idx = 0
        self.lo, self.hi = target if target is not None else RESIDENT_TARGET
        self.factor = factor
        self.resizes = 0

    @property
    def K(self) -> int:
        return self.ladder[self.idx]

    def observe(self, period_s: float, cycles: int) -> bool:
        """Feed one dispatch's host period (scalars-ready to scalars-ready)
        and its device cycle count; returns True when K should change (the
        caller rebuilds its program from the cache at the new ``.K``).

        Dispatches can end early (pool drained below m mid-block), so the
        decision uses the *per-cycle* rate scaled to a full-K block, not
        the raw period.
        """
        if cycles <= 0 or period_s <= 0.0:
            return False
        per_cycle = period_s / cycles
        est = per_cycle * self.K
        if (self.idx + 1 < len(self.ladder)
                and est * self.factor <= self.hi):
            # The next rung's predicted full block still fits the band —
            # climbing can never overshoot, so no up/down oscillation.
            self.idx += 1
            self.resizes += 1
            return True
        if est > self.hi and self.idx > 0:
            while self.idx > 0 and per_cycle * self.ladder[self.idx] > self.hi:
                self.idx -= 1
            self.resizes += 1
            return True
        return False


class DispatchQueue:
    """Bounded FIFO of in-flight speculative dispatches.

    The engines own the dispatch call (it runs under their steady-state
    guard) and the scalar read; this class owns only the queue mechanics
    so the three resident host loops cannot drift on them.  Entries are
    ``(out, enqueue_us)`` — the dispatch's raw output tuple (whose pool
    leaves may already be donated into a later dispatch; only the scalar
    leaves may be read) and its enqueue timestamp for the obs span.
    """

    def __init__(self, depth: int):
        self.depth = max(1, int(depth))
        self._q: deque = deque()

    def __len__(self) -> int:
        return len(self._q)

    @property
    def full(self) -> bool:
        return len(self._q) >= self.depth

    def push(self, out, enqueue_us: float) -> None:
        if self.full:
            raise RuntimeError(
                f"dispatch queue overfull (depth {self.depth})"
            )
        self._q.append((out, enqueue_us))

    def pop(self):
        """Oldest in-flight dispatch ``(out, enqueue_us)``."""
        return self._q.popleft()

    def drain(self):
        """Yield every remaining entry, oldest first, emptying the queue.
        Engines drain (accumulating the scalar counts — zeros for no-op
        speculative dispatches, real work otherwise) before any action
        that must see coherent totals: termination, checkpoint cuts,
        K resizes, donation downloads, and the capacity-stall fallback."""
        while self._q:
            yield self._q.popleft()


# -- compiled-program contracts (`tts check`, analysis/contracts.py) --------

from ..analysis.contracts import contract


@contract(
    "pipeline-knob-inert",
    claim="TTS_PIPELINE never reaches the compiled program: depth-0 and "
          "depth-2 builds are byte-identical — speculation is host-side "
          "queueing only, and its exactness rests on the no-op-dispatch "
          "invariant of the while-cond, not on a program variant",
    artifact="variants",
)
def _contract_pipeline_inert(art, cell):
    if not art.has("off", "pipe0", "pipe2"):
        return []
    if art.text("off") == art.text("pipe0") == art.text("pipe2"):
        return []
    return ["TTS_PIPELINE leaked into the compiled step (depth-dependent "
            "program structure breaks the exact-speculation argument)"]
