"""SoA work-pool deques.

The reference keeps one growable deque of nodes per task: back ops drive DFS,
front ops drive the BFS warm-up and work stealing
(`lib/commons/Pool.chpl:1-75`, `lib/commons/Pool_par.chpl:1-193`). Here the
pool is a struct-of-arrays over the problem's node fields so a popped chunk
is already in the layout device kernels want — handing a chunk to JAX is a
contiguous slice per field, no per-node marshalling (the reference pays a
per-node copy into `parents` instead, `Pool.chpl:50-59`).

An optional C++ backend (tpu_tree_search.pool.native) provides the same
interface for the hot host path; this numpy implementation is the portable
fallback and the semantic model.
"""

from __future__ import annotations

import math
import threading

import numpy as np

INITIAL_CAPACITY = 1024  # `Pool.chpl:10`


class SoAPool:
    """Serial growable SoA deque (`lib/commons/Pool.chpl`).

    fields: dict name -> (per-node shape, dtype).
    """

    def __init__(self, fields, capacity: int = INITIAL_CAPACITY):
        self.fields = dict(fields)
        self.capacity = int(capacity)
        self.front = 0
        self.size = 0
        self.data = {
            name: np.empty((self.capacity,) + tuple(shape), dtype=dtype)
            for name, (shape, dtype) in self.fields.items()
        }

    # -- growth ------------------------------------------------------------

    def _ensure(self, extra: int) -> None:
        needed = self.front + self.size + extra
        if needed <= self.capacity:
            return
        if self.size + extra <= self.capacity // 2 and self.front > 0:
            # Plenty of room once the consumed [0:front) prefix is dropped:
            # compact in place instead of growing (improvement over the
            # reference pool, which carries the dead prefix forever,
            # `Pool.chpl:27-35`).
            for arr in self.data.values():
                arr[: self.size] = arr[self.front : self.front + self.size]
            self.front = 0
            return
        # Grow by powers of two like `Pool_par.chpl:79` / `Pool_ext.c:40`,
        # compacting away the dead prefix while copying.
        live = self.size + extra
        new_cap = self.capacity * 2 ** max(1, math.ceil(math.log2(live / self.capacity)))
        for name, arr in self.data.items():
            grown = np.empty((new_cap,) + arr.shape[1:], dtype=arr.dtype)
            grown[: self.size] = arr[self.front : self.front + self.size]
            self.data[name] = grown
        self.front = 0
        self.capacity = new_cap

    # -- single-node ops ---------------------------------------------------

    def push_back(self, node: dict) -> None:
        """`Pool.chpl:27-35`."""
        self._ensure(1)
        end = self.front + self.size
        for name, arr in self.data.items():
            arr[end] = node[name]
        self.size += 1

    def pop_back(self) -> dict | None:
        """`Pool.chpl:38-47`."""
        if self.size <= 0:
            return None
        self.size -= 1
        end = self.front + self.size
        return {name: arr[end].copy() for name, arr in self.data.items()}

    def pop_front(self) -> dict | None:
        """`Pool.chpl:62-73`."""
        if self.size <= 0:
            return None
        node = {name: arr[self.front].copy() for name, arr in self.data.items()}
        self.front += 1
        self.size -= 1
        return node

    # -- bulk ops ----------------------------------------------------------

    def push_back_bulk(self, batch: dict) -> None:
        """`Pool_par.chpl:73-92` (without the lock)."""
        k = 0
        for v in batch.values():
            k = v.shape[0]
            break
        if k == 0:
            return
        self._ensure(k)
        end = self.front + self.size
        for name, arr in self.data.items():
            arr[end : end + k] = batch[name]
        self.size += k

    def pop_back_bulk(self, m: int, M: int, out: dict) -> int:
        """Pop min(size, M) from the back into ``out`` iff size >= m; else 0
        (`Pool.chpl:50-59`). ``out`` arrays must have capacity >= M.
        """
        if self.size < m:
            return 0
        k = min(self.size, M)
        self.size -= k
        start = self.front + self.size
        for name, arr in self.data.items():
            out[name][:k] = arr[start : start + k]
        return k

    def pop_back_bulk_all(self, M: int, out: dict) -> int:
        """Drain up to M from the back unconditionally (used by the CPU
        drain phase when fewer than m nodes remain).
        """
        if self.size == 0:
            return 0
        k = min(self.size, M)
        self.size -= k
        start = self.front + self.size
        for name, arr in self.data.items():
            out[name][:k] = arr[start : start + k]
        return k

    def pop_front_bulk_half(
        self, m: int, perc: float = 0.5, cap: int | None = None
    ) -> dict | None:
        """Steal a ``perc`` fraction of the pool from the *front* (oldest,
        shallowest subtrees) iff size >= 2m. perc=0.5 is the steal-half
        policy of `Pool_par.chpl:180-191`; other fractions mirror the CUDA
        baseline's `--perc` knob (`Pool_ext.c:138-151`). ``cap`` bounds the
        stolen block (inter-host donations cap at M so a huge pool never
        ships an unbounded block over DCN). Returns a batch or None.
        """
        if self.size < 2 * m:
            return None
        k = max(1, int(self.size * perc))
        k = min(k, self.size)
        if cap is not None:
            k = min(k, cap)
        batch = {
            name: arr[self.front : self.front + k].copy()
            for name, arr in self.data.items()
        }
        self.front += k
        self.size -= k
        return batch

    def as_batch(self) -> dict:
        """Copy out the whole pool contents (front..front+size)."""
        return {
            name: arr[self.front : self.front + self.size].copy()
            for name, arr in self.data.items()
        }

    def reset_from(self, batch: dict) -> None:
        """Replace the whole contents with ``batch`` (native-runtime handoff)."""
        self.clear()
        self.push_back_bulk(batch)

    def clear(self) -> None:
        self.front = 0
        self.size = 0


class ParallelSoAPool(SoAPool):
    """Lock-protected pool for the multi-device runtime
    (`lib/commons/Pool_par.chpl`). The reference spins on an atomic bool with
    task yields (`Pool_par.chpl:28-40`); host threads here use a mutex with
    ``try_lock`` exposed for the bounded-retry steal loop
    (`nqueens_multigpu_chpl.chpl:268-293`).

    Concurrency contract (checked by `tts lint`, rule ``guarded-by`` —
    docs/ANALYSIS.md): once an instance is shared with worker threads, its
    SoA state may only be touched with ``lock`` held — via the ``locked_*``
    wrappers, ``with pool.lock:``, or the taken branch of
    ``if pool.try_lock():``. The inherited unsynchronized methods carry the
    caller-must-hold-the-lock contract below.
    """

    # guarded-by: lock -- front, size, capacity, data
    # requires-lock: lock -- push_back, pop_back, pop_front, push_back_bulk
    # requires-lock: lock -- pop_back_bulk, pop_back_bulk_all
    # requires-lock: lock -- pop_front_bulk_half, as_batch, reset_from, clear
    # requires-lock: lock -- _ensure

    def __init__(self, fields, capacity: int = INITIAL_CAPACITY):
        super().__init__(fields, capacity)
        self.lock = threading.Lock()

    def try_lock(self) -> bool:
        return self.lock.acquire(blocking=False)

    def unlock(self) -> None:
        self.lock.release()

    def locked_push_back_bulk(self, batch: dict) -> None:
        with self.lock:
            self.push_back_bulk(batch)

    def locked_pop_back_bulk(self, m: int, M: int, out: dict) -> int:
        with self.lock:
            return self.pop_back_bulk(m, M, out)

    def locked_pop_back_bulk_all(self, M: int, out: dict) -> int:
        with self.lock:
            return self.pop_back_bulk_all(M, out)
