"""Host-side work pools (SoA deques)."""

from .pool import SoAPool, ParallelSoAPool

__all__ = ["SoAPool", "ParallelSoAPool"]
