"""Version shims for the narrow band of jax APIs this repo tracks.

The codebase is written against current jax (``jax.shard_map`` with
varying-manual-axes checking, ``lax.pcast``, ``pltpu.CompilerParams``);
older installs (0.4.x) expose the same functionality under earlier names
(``jax.experimental.shard_map.shard_map`` with ``check_rep``,
``pltpu.TPUCompilerParams``) and predate the vma type system entirely.
Every call site routes through these wrappers so the version probe lives
in exactly one place; each wrapper degrades to the semantically closest
older behavior rather than stubbing anything out.
"""

from __future__ import annotations

import jax


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` when available, else the ``jax.experimental``
    original. ``check_vma`` maps onto the older ``check_rep``: both gate the
    per-shard type/replication checker that pallas_call does not yet
    satisfy (see the resident_mesh call site)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    # The pre-vma checker (check_rep) has no replication rule for
    # while_loop at all — every resident mesh program would die at trace
    # time — so it is forced off here; the real vma checking only exists
    # (and stays on) under current jax.
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False,
    )


def pcast_varying(x, axis_name: str):
    """Re-mark an axis-invariant value as varying over ``axis_name`` so a
    while-loop carry keeps a consistent vma type (`lax.pcast`). Pre-vma jax
    has no such typing — the identity is exact there."""
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is None:
        return x
    return pcast(x, (axis_name,), to="varying")
