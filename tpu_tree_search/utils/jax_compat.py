"""Version shims for the narrow band of jax APIs this repo tracks.

The codebase is written against current jax (``jax.shard_map`` with
varying-manual-axes checking, ``lax.pcast``, ``pltpu.CompilerParams``);
older installs (0.4.x) expose the same functionality under earlier names
(``jax.experimental.shard_map.shard_map`` with ``check_rep``,
``pltpu.TPUCompilerParams``) and predate the vma type system entirely.
Every call site routes through these wrappers so the version probe lives
in exactly one place; each wrapper degrades to the semantically closest
older behavior rather than stubbing anything out.
"""

from __future__ import annotations

import jax


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` when available, else the ``jax.experimental``
    original. ``check_vma`` maps onto the older ``check_rep``: both gate the
    per-shard type/replication checker that pallas_call does not yet
    satisfy (see the resident_mesh call site)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    # The pre-vma checker (check_rep) has no replication rule for
    # while_loop at all — every resident mesh program would die at trace
    # time — so it is forced off here; the real vma checking only exists
    # (and stays on) under current jax.
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False,
    )


def pcast_varying(x, axis_name: str):
    """Re-mark an axis-invariant value as varying over ``axis_name`` so a
    while-loop carry keeps a consistent vma type (`lax.pcast`). Pre-vma jax
    has no such typing — the identity is exact there."""
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is None:
        return x
    return pcast(x, (axis_name,), to="varying")


# -- pallas backend shims (ops/backend.py seam) -----------------------------
# The kernel modules never probe versions or platforms inline: every
# TPU-only pallas construct (CompilerParams + dimension_semantics + the
# scoped-VMEM charge, memory-space BlockSpecs, scratch refs) routes
# through these three wrappers, which also know the Triton spellings.


def pallas_compiler_params(backend: str = "tpu", ndims: int = 1,
                           parallel: bool = False,
                           vmem_limit_bytes: int | None = None):
    """Backend-keyed ``pallas_call`` compiler params.

    TPU: ``pltpu.CompilerParams`` (``TPUCompilerParams`` before jax 0.5)
    with ``dimension_semantics`` sized to the grid rank (``parallel``
    marks every axis Megacore-splittable — carry-free kernels only) and
    the scoped-VMEM ceiling.  GPU: ``TritonCompilerParams`` at its
    defaults — Triton has no dimension semantics (every grid program is a
    parallel CUDA block) and no VMEM scope; the shared-memory budget is a
    policy-table concern (`pallas_kernels._vmem_limit_bytes`), not a
    compiler param.  Returns None when the flavor's module is absent
    (``pallas_call`` treats that as defaults)."""
    if backend == "gpu":
        try:
            from jax.experimental.pallas import triton as plgpu
        except Exception:
            return None
        cls = (getattr(plgpu, "CompilerParams", None)
               or getattr(plgpu, "TritonCompilerParams", None))
        return cls() if cls is not None else None
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    sem = ("parallel" if parallel else "arbitrary",) * ndims
    return cls(dimension_semantics=sem, vmem_limit_bytes=vmem_limit_bytes)


def pallas_block_spec(shape, index_map, space: str = "vmem",
                      backend: str = "tpu"):
    """Backend-keyed BlockSpec: the TPU flavor pins the block to VMEM or
    SMEM (``space``); the Triton flavor has no memory spaces at all —
    every operand is a plain pointer-backed ref, including the per-pair
    scalar tables the TPU kernels must stage in SMEM."""
    from jax.experimental import pallas as pl

    if backend == "gpu":
        return pl.BlockSpec(shape, index_map)
    from jax.experimental.pallas import tpu as pltpu

    ms = pltpu.SMEM if space == "smem" else pltpu.VMEM
    return pl.BlockSpec(shape, index_map, memory_space=ms)


def pallas_scratch_shapes(backend: str, *tpu_shapes):
    """The ``scratch_shapes`` a kernel may declare: the given TPU scratch
    allocations on the TPU flavor, NONE on Triton (scratch memory is not
    implemented in the Triton lowering — the kernels restructure instead:
    `pallas_kernels._front_scan` unrolls what the scratch ref staged)."""
    return [] if backend == "gpu" else list(tpu_shapes)
