"""Shared runtime utilities: idle-state tracking and termination detection."""

from .termination import BUSY, IDLE, TaskStates

__all__ = ["BUSY", "IDLE", "TaskStates"]
