"""Termination detection for the work-stealing runtime.

Reproduces the reference's two-phase idle-scan with sticky fast-exit flag
(`lib/commons/util.chpl:7-30`, C: `baselines/commons/util.c:18-30`): a task
that finds no work and no victim sets its state IDLE and asks "is everyone
idle?"; the first scan that observes all-idle sets a sticky global flag so
every other task exits on its next check without rescanning. A task that
finds or steals work flips itself back to BUSY first (the
become-BUSY-again transition the scan's correctness depends on,
`pfsp_multigpu_chpl.chpl:416-419`, SURVEY.md §2.4.5).

CPython note: the per-element reads/writes are plain list slots guarded by
the GIL (each is a single bytecode-level store, same atomicity class as the
reference's relaxed atomics); the sticky flag uses an Event for cross-thread
visibility.
"""

from __future__ import annotations

import threading

BUSY = False  # `util.chpl:3`
IDLE = True  # `util.chpl:4`


class TaskStates:
    """One BUSY/IDLE slot per task plus the sticky all-idle flag."""

    def __init__(self, n: int):
        self.states = [BUSY] * n
        self.flag = threading.Event()

    def set_busy(self, tid: int) -> None:
        self.states[tid] = BUSY

    def set_idle(self, tid: int) -> None:
        self.states[tid] = IDLE

    def _all_idle(self) -> bool:
        """`util.chpl:7-14`."""
        return all(s == IDLE for s in self.states)

    def all_idle(self, tid_unused: int | None = None) -> bool:
        """`util.chpl:16-30`: sticky fast path, else scan and latch."""
        if self.flag.is_set():
            return True
        if self._all_idle():
            self.flag.set()
            return True
        return False
