"""The router's keeper thread: scrape, detect, pull, rebalance.

One background thread owns every periodic concern the router has, so
the HTTP threads never block on a daemon socket:

  * **scrape loop** — every ``interval_s`` each registered daemon is
    scraped (``placement.scrape``: healthz/classes/metrics/jobs). A
    failed probe counts a miss and backs off exponentially; after
    ``max_misses`` consecutive misses the daemon is declared dead and
    the router recovers its jobs (``FleetRouter.recover_daemon`` with
    ``live=False`` — the daemon cannot answer, so recovery runs from the
    checkpoints this thread pulled while it was alive). A daemon whose
    ``/healthz`` reports ``draining`` triggers the live recovery path
    instead (cancel-with-cut -> fetch -> resubmit, the ``tts migrate``
    flow) while its HTTP surface still answers.
  * **checkpoint pulls** — every ``pull_interval_s`` the router copies
    each in-flight job's latest checkpoint cut (and the job record's
    exact ``steps`` at that cut) into its own ``--state-dir``. This is
    what makes SIGKILL recovery possible at all: a dead daemon serves
    nothing.
  * **rebalance** — when one daemon queues while another sits idle
    (``placement.pick_rebalance``), the hot daemon's longest-running
    checkpointed job is migrated to the idle one, at most once per
    ``rebalance_cooldown_s``.

The keeper holds no locks of its own: all shared state lives behind
``FleetView`` and the router's job map, and every callback it makes
(``recover_daemon``, ``pull_checkpoints``, ``maybe_rebalance``) is
written to be safe against concurrent HTTP-thread reads.
"""

from __future__ import annotations

import sys
import threading
import time

from . import placement


class HealthChecker(threading.Thread):
    """The keeper. ``scrape_once()`` is also callable synchronously —
    the router runs one sweep at startup so static ``--daemon`` entries
    are placeable before the first request arrives (and tests can drive
    ticks deterministically without waiting out the interval)."""

    def __init__(self, router, interval_s: float = 1.0,
                 max_misses: int = 3, backoff0_s: float = 0.5,
                 max_backoff_s: float = 10.0,
                 pull_interval_s: float = 2.0,
                 rebalance: bool = True,
                 rebalance_min_depth: int = 2,
                 rebalance_cooldown_s: float = 10.0,
                 scrape_timeout_s: float = 3.0):
        super().__init__(name="tts-fleet-keeper", daemon=True)
        self.router = router
        self.interval_s = float(interval_s)
        self.max_misses = max(1, int(max_misses))
        self.backoff0_s = float(backoff0_s)
        self.max_backoff_s = float(max_backoff_s)
        self.pull_interval_s = float(pull_interval_s)
        self.rebalance = bool(rebalance)
        self.rebalance_min_depth = int(rebalance_min_depth)
        self.rebalance_cooldown_s = float(rebalance_cooldown_s)
        self.scrape_timeout_s = float(scrape_timeout_s)
        self.stop_event = threading.Event()
        # Keeper-private bookkeeping (single-thread + startup sweep;
        # never touched by HTTP threads).
        self._dead_handled: set = set()
        self._drain_handled: set = set()
        self._next_pull = 0.0
        self._next_rebalance = 0.0
        self._last_err = 0.0

    def stop(self) -> None:
        self.stop_event.set()

    def run(self) -> None:
        while not self.stop_event.wait(self.interval_s):
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 — the keeper must
                # outlive any single bad scrape/recovery; a dead keeper
                # is a router that never notices a dead daemon.
                self._report(e)

    def _report(self, e: Exception) -> None:
        now = time.monotonic()
        if now - self._last_err >= 5.0:  # rate-limited operator signal
            self._last_err = now
            print(f"fleet keeper: {type(e).__name__}: {e}",
                  file=sys.stderr, flush=True)

    def tick(self) -> None:
        self.scrape_once()
        now = time.monotonic()
        if now >= self._next_pull:
            self._next_pull = now + self.pull_interval_s
            self.router.pull_checkpoints()
        if self.rebalance and now >= self._next_rebalance:
            if self.router.maybe_rebalance(self.rebalance_min_depth):
                self._next_rebalance = (time.monotonic()
                                        + self.rebalance_cooldown_s)

    def scrape_once(self) -> None:
        """One sweep over every registered daemon: refresh snapshots,
        count misses, fire death/drain recovery exactly once per
        episode."""
        view = self.router.view
        now = time.monotonic()
        for st in view.states():
            if st.next_probe > now:
                continue  # backing off a missing daemon
            try:
                data = placement.scrape(st.url,
                                        timeout=self.scrape_timeout_s)
            except Exception as e:  # noqa: BLE001 — any failure is a miss
                misses = view.mark_miss(st, self.backoff0_s,
                                        self.max_backoff_s)
                if misses >= self.max_misses \
                        and st.url not in self._dead_handled:
                    self._dead_handled.add(st.url)
                    view.mark_dead(st)
                    self._report(e)
                    self.router.recover_daemon(st.url, live=False)
                continue
            view.mark_ok(st, data)
            self._dead_handled.discard(st.url)
            if st.draining:
                if st.url not in self._drain_handled:
                    self._drain_handled.add(st.url)
                    self.router.recover_daemon(st.url, live=True)
            else:
                self._drain_handled.discard(st.url)
