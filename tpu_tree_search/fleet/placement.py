"""Per-daemon scraped state + the pure placement policy.

The router never asks a daemon anything on the submit path: placement
runs against the keeper thread's last scrape of every registered
daemon's ``/healthz`` + ``/classes`` + ``/metrics`` + ``/jobs``. The
policy itself is pure functions over those snapshots, so every decision
is unit-testable without a single socket:

  * **warm first** — a daemon whose ``/classes`` already shows the job's
    shape class warm gets the job (zero-compile admission: the class key
    here is the same ``serve/pool.class_key`` computation the daemon
    will make). Among warm daemons, one with a *free same-class batch
    slot* (or an empty queue when batching is off) wins;
  * **least-loaded otherwise** — a cold class warms on the daemon with
    the lowest load score: queue depth, the measured mean queue wait
    (from the ``tts_serve_queue_wait_seconds`` histogram), resident
    pool bytes, and class occupancy, with the weights below.

Lock discipline (analysis/lockorder.py): ``FleetView._lock`` is a leaf
guarding only the url -> DaemonState dict; scrapes replace whole
snapshot fields, readers copy the list out — no method calls out while
holding it.
"""

from __future__ import annotations

import json
import threading
import time
from urllib.request import urlopen

#: Load-score weights. Units: a queued job ~ 10 points, a second of
#: measured mean queue wait ~ 5, a GiB of resident pool ~ 1, a resident
#: class ~ 0.5 — queue state dominates, memory pressure breaks ties.
W_QUEUE_DEPTH = 10.0
W_QUEUE_WAIT_S = 5.0
W_POOL_GIB = 1.0
W_CLASSES = 0.5


def fleet_class_key(spec: dict) -> str:
    """The job's shape class, computed router-side with the exact
    ``serve/pool.py`` functions the daemon will use at admission — the
    whole warm-placement story rests on both ends agreeing. Host-only:
    ``resolved_knobs`` resolves auto knobs without building a problem
    (and falls back to the cpu platform when jax is absent)."""
    from ..serve.jobs import validate_spec
    from ..serve.pool import class_key

    return class_key(validate_spec(spec))


class DaemonState:
    """One daemon's last-scraped snapshot + liveness bookkeeping.

    Mutated only by the keeper thread (health.py) through
    ``FleetView.update``; placement reads copies. ``misses`` counts
    consecutive failed probes; ``healthy`` flips false after
    ``max_misses`` of them (with exponential probe backoff in between,
    so a dead daemon costs one socket timeout per backoff step, not per
    tick)."""

    def __init__(self, url: str):
        self.url = url.rstrip("/")
        self.healthy = False
        self.draining = False
        self.misses = 0
        self.next_probe = 0.0  # monotonic; backoff gate for dead daemons
        self.health: dict = {}
        self.classes: list = []
        self.metrics: dict = {}
        self.jobs: list = []
        self.last_ok = 0.0

    def snapshot(self) -> dict:
        """JSON view for ``/daemons`` and ``tts top --router``."""
        return {
            "url": self.url,
            "healthy": self.healthy,
            "draining": self.draining,
            "misses": self.misses,
            "health": self.health,
            "classes": self.classes,
            "jobs_by_state": _jobs_by_state(self.jobs),
        }


def _jobs_by_state(jobs: list) -> dict:
    out: dict = {}
    for j in jobs:
        s = j.get("state", "?")
        out[s] = out.get(s, 0) + 1
    return out


def scrape(url: str, timeout: float = 3.0) -> dict:
    """One full scrape of a daemon: health, classes, metrics, jobs.
    Raises on any failure (the keeper counts it as a miss)."""
    from ..serve.metrics import parse_text

    base = url.rstrip("/")

    def get_json(path):
        with urlopen(base + path, timeout=timeout) as r:  # noqa: S310
            return json.loads(r.read().decode())

    health = get_json("/healthz")
    classes = get_json("/classes")
    with urlopen(base + "/metrics", timeout=timeout) as r:  # noqa: S310
        metrics = parse_text(r.read().decode())
    jobs = get_json("/jobs")
    return {"health": health, "classes": classes, "metrics": metrics,
            "jobs": jobs}


class FleetView:
    """url -> DaemonState behind one leaf lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self._daemons: dict = {}  # guarded-by: _lock

    def add(self, url: str) -> DaemonState:
        url = url.rstrip("/")
        with self._lock:
            st = self._daemons.get(url)
            if st is None:
                st = self._daemons[url] = DaemonState(url)
            return st

    def get(self, url: str):
        with self._lock:
            return self._daemons.get(url.rstrip("/"))

    def states(self) -> list:
        with self._lock:
            return sorted(self._daemons.values(), key=lambda s: s.url)

    def mark_ok(self, st: DaemonState, scraped: dict) -> None:
        with self._lock:
            st.health = scraped["health"]
            st.classes = scraped["classes"]
            st.metrics = scraped["metrics"]
            st.jobs = scraped["jobs"]
            st.healthy = bool(scraped["health"].get("ok", False))
            st.draining = bool(scraped["health"].get("draining", False))
            st.misses = 0
            st.next_probe = 0.0
            st.last_ok = time.monotonic()

    def mark_miss(self, st: DaemonState, backoff0_s: float,
                  max_backoff_s: float) -> int:
        """Count a failed probe; schedule the next one with exponential
        backoff. Returns the new consecutive-miss count."""
        with self._lock:
            st.misses += 1
            delay = min(max_backoff_s, backoff0_s * (2 ** (st.misses - 1)))
            st.next_probe = time.monotonic() + delay
            return st.misses

    def mark_dead(self, st: DaemonState) -> None:
        with self._lock:
            st.healthy = False


# -- the pure policy ---------------------------------------------------------


def class_stat(st: DaemonState, cls: str):
    for entry in st.classes:
        if entry.get("class") == cls:
            return entry
    return None


def has_free_slot(st: DaemonState, cls: str) -> bool:
    """A warm daemon admits this job without waiting: a same-class batch
    slot is open, or (batching off) the run queue is empty."""
    entry = class_stat(st, cls)
    if entry is None:
        return False
    if "batch_slots" in entry:
        return int(entry.get("slots_occupied", 0)) < int(entry["batch_slots"])
    return int(st.health.get("queue_depth", 0)) == 0


def queue_wait_mean_s(st: DaemonState) -> float:
    """Mean measured queue wait from the scraped histogram (all classes
    pooled): the daemon's own account of how long admission-to-start
    takes under its current load."""
    sums = st.metrics.get("tts_serve_queue_wait_seconds_sum", {})
    counts = st.metrics.get("tts_serve_queue_wait_seconds_count", {})
    total = sum(sums.values())
    n = sum(counts.values())
    return total / n if n else 0.0


def pool_bytes(st: DaemonState) -> int:
    return sum(int(e.get("pool_bytes", 0) or 0) for e in st.classes)


def load_score(st: DaemonState) -> float:
    """Weighted cold-placement load: lower is better."""
    return (W_QUEUE_DEPTH * int(st.health.get("queue_depth", 0))
            + W_QUEUE_WAIT_S * queue_wait_mean_s(st)
            + W_POOL_GIB * pool_bytes(st) / (1 << 30)
            + W_CLASSES * len(st.classes))


def placeable(st: DaemonState) -> bool:
    return st.healthy and not st.draining


def choose(states: list, cls: str):
    """Pick the daemon for a job of shape class ``cls``. Returns
    ``(DaemonState, reason)`` with reason ``"warm"`` or ``"cold"``, or
    ``(None, why)`` when no daemon is placeable. Deterministic: ties
    break on URL order."""
    candidates = [st for st in states if placeable(st)]
    if not candidates:
        return None, "no healthy daemon"
    warm = [st for st in candidates
            if (class_stat(st, cls) or {}).get("warm")]
    if warm:
        warm.sort(key=lambda st: (not has_free_slot(st, cls),
                                  load_score(st), st.url))
        return warm[0], "warm"
    candidates.sort(key=lambda st: (load_score(st), st.url))
    return candidates[0], "cold"


def pick_rebalance(states: list, min_depth: int = 2):
    """A conservative hot->idle move: when one daemon has ``min_depth``+
    jobs queued and another is completely idle (empty queue, nothing
    running), pick the hot daemon's longest-running checkpointed job to
    migrate. Returns ``(hot_state, job_record, idle_state)`` or ``None``
    — the caller executes the move over the migrate transport."""
    live = [st for st in states if placeable(st)]
    if len(live) < 2:
        return None
    live.sort(key=lambda st: (int(st.health.get("queue_depth", 0)), st.url))
    cold, hot = live[0], live[-1]
    if int(hot.health.get("queue_depth", 0)) < min_depth:
        return None
    if int(cold.health.get("queue_depth", 0)) != 0 or any(
            j.get("state") == "running" for j in cold.jobs):
        return None
    runners = [j for j in hot.jobs
               if j.get("state") == "running" and j.get("checkpoint")]
    if not runners:
        return None
    runners.sort(key=lambda j: (-int(j.get("steps", 0) or 0),
                                j.get("id", "")))
    return hot, runners[0], cold
