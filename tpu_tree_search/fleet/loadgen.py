"""Seeded synthetic fleet traffic + the saturation-curve driver.

``bench.py fleet_sat`` (and tests/test_fleet.py) drive a router with a
reproducible open-loop workload: **Poisson arrivals** (exponential
inter-arrival gaps at a fixed offered rate — an open system, so queueing
delay shows up as queue wait instead of throttling the generator),
**mixed shape classes** (round-robin-free random draws over a small
class mix, exercising warm placement and cold spills), and
**heavy-tailed job sizes** (Pareto-distributed ``max_steps``, capped —
most jobs are small, a few are long-runners, which is what makes
rebalancing and checkpointed recovery worth having).

Everything is driven by one ``random.Random(seed)``: ``make_plan`` is a
pure function of its arguments (pinned by a test), so a saturation curve
is re-runnable bit-for-bit at the plan level and comparable across
daemons/routers. The measured side reads each job's daemon record:
queue wait is ``started - submitted`` — the daemon's own clock, the same
quantity its ``tts_serve_queue_wait_seconds`` histogram observes.
"""

from __future__ import annotations

import random
import threading
import time

from ..serve.client import _get, _post

#: The default class mix: three nqueens shape classes small enough to
#: run under JAX_PLATFORMS=cpu in CI, distinct in class key (N and M
#: both feed serve/pool.class_key). Weights skew toward one "hot" class
#: so warm placement has something to be right about.
DEFAULT_CLASSES = [
    {"spec": {"problem": "nqueens", "N": 10, "M": 256}, "weight": 3},
    {"spec": {"problem": "nqueens", "N": 11, "M": 256}, "weight": 2},
    {"spec": {"problem": "nqueens", "N": 10, "M": 128}, "weight": 1},
]


def make_plan(seed: int, n_jobs: int, rate_per_s: float,
              classes: list | None = None, steps_scale: int = 24,
              steps_cap: int = 600, pareto_alpha: float = 1.5) -> list:
    """The deterministic workload: ``n_jobs`` arrivals as
    ``[{at_s, spec}, ...]`` sorted by offset. ``max_steps`` ~
    ``steps_scale * Pareto(alpha)`` capped at ``steps_cap`` (alpha 1.5:
    infinite variance, the classic heavy tail). Same arguments -> same
    plan, exactly."""
    rng = random.Random(seed)
    classes = classes or DEFAULT_CLASSES
    weights = [float(c.get("weight", 1)) for c in classes]
    t = 0.0
    plan = []
    for i in range(int(n_jobs)):
        t += rng.expovariate(rate_per_s)
        cls = rng.choices(classes, weights=weights, k=1)[0]
        steps = min(int(steps_cap),
                    max(8, int(steps_scale * rng.paretovariate(pareto_alpha))))
        spec = dict(cls["spec"])
        spec["max_steps"] = steps
        spec["label"] = f"loadgen-{seed}-{i:04d}"
        plan.append({"at_s": round(t, 6), "spec": spec})
    return plan


def _submit_worker(base: str, item: dict, t_zero: float, out: list,
                   lock: threading.Lock) -> None:
    delay = t_zero + item["at_s"] - time.monotonic()
    if delay > 0:
        time.sleep(delay)
    row = {"at_s": item["at_s"], "spec": item["spec"], "id": None,
           "error": None}
    try:
        code, resp = _post(base + "/submit", item["spec"], timeout=60.0,
                           retry_s=5.0)
        if code == 201:
            row["id"] = resp["id"]
            row["placement"] = resp.get("placement")
        else:
            row["error"] = f"{code}: {resp.get('error', resp)}"
    except (OSError, ValueError) as e:
        row["error"] = f"{type(e).__name__}: {e}"
    with lock:
        out.append(row)


def run_plan(router_url: str, plan: list, timeout_s: float = 600.0) -> dict:
    """Fire a plan at the router (open loop: one timer thread per
    arrival, so a slow admission never delays the next arrival), then
    poll every admitted job to a terminal state and measure.

    Returns ``{jobs: [...], summary: {...}, per_class: {...}}`` where
    each job row carries the daemon-clock ``queue_wait_ms``, final
    state, steps, and the placement decision the router made."""
    base = router_url.rstrip("/")
    if "://" not in base:
        base = "http://" + base
    rows: list = []
    lock = threading.Lock()
    t_zero = time.monotonic() + 0.05
    threads = [threading.Thread(target=_submit_worker,
                                args=(base, item, t_zero, rows, lock),
                                daemon=True)
               for item in plan]
    t_wall = time.time()
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=timeout_s)
    final = ("done", "failed", "cancelled")
    deadline = time.monotonic() + timeout_s
    for row in rows:
        if row["id"] is None:
            continue
        rec = None
        while time.monotonic() < deadline:
            try:
                code, rec = _get(f"{base}/job/{row['id']}", timeout=10.0,
                                 retry_s=5.0)
            except (OSError, ValueError):
                time.sleep(0.5)
                continue
            if code == 200 and rec.get("state") in final \
                    and not rec.get("stale"):
                break
            time.sleep(0.2)
        if rec is None or rec.get("state") not in final:
            row["state"] = "timeout"
            continue
        row["state"] = rec["state"]
        row["steps"] = rec.get("steps", 0)
        row["daemon"] = rec.get("daemon")
        row["resubmits"] = rec.get("resubmits", 0)
        started, submitted = rec.get("started"), rec.get("submitted")
        if started is not None and submitted is not None:
            row["queue_wait_ms"] = round(1000.0 * max(0.0,
                                                      started - submitted), 3)
    wall_s = max(1e-9, time.time() - t_wall)
    return {"jobs": rows, "summary": _summarize(rows, wall_s),
            "per_class": _per_class(rows)}


def _quantile(xs: list, q: float) -> float:
    """Nearest-rank quantile — 10-sample p99 must be the max, not an
    interpolated fiction."""
    if not xs:
        return 0.0
    xs = sorted(xs)
    k = min(len(xs) - 1, max(0, int(round(q * (len(xs) - 1)))))
    return xs[k]


def _class_of(spec: dict) -> str:
    """A human-stable class label for reporting (the router's real class
    key is opaque and long): problem + the shape fields that feed it."""
    keep = ("problem", "N", "M", "K", "tier", "lb")
    return ",".join(f"{k}={spec[k]}" for k in keep if spec.get(k)
                    is not None)


def _summarize(rows: list, wall_s: float) -> dict:
    done = [r for r in rows if r.get("state") == "done"]
    waits = [r["queue_wait_ms"] for r in done if "queue_wait_ms" in r]
    return {
        "offered": len(rows),
        "admitted": sum(1 for r in rows if r.get("id")),
        "done": len(done),
        "failed": sum(1 for r in rows
                      if r.get("state") in ("failed", "cancelled")),
        "timeout": sum(1 for r in rows if r.get("state") == "timeout"),
        "rejected": sum(1 for r in rows
                        if r.get("id") is None),
        "achieved_jobs_per_s": round(len(done) / wall_s, 4),
        "queue_wait_ms_p50": round(_quantile(waits, 0.50), 3),
        "queue_wait_ms_p99": round(_quantile(waits, 0.99), 3),
        "wall_s": round(wall_s, 3),
    }


def _per_class(rows: list) -> dict:
    out: dict = {}
    for r in rows:
        if r.get("state") != "done" or "queue_wait_ms" not in r:
            continue
        out.setdefault(_class_of(r["spec"]), []).append(r["queue_wait_ms"])
    return {cls: {"done": len(waits),
                  "queue_wait_ms_p50": round(_quantile(waits, 0.50), 3),
                  "queue_wait_ms_p99": round(_quantile(waits, 0.99), 3)}
            for cls, waits in sorted(out.items())}


def saturation_curve(router_url: str, rates: list, seed: int = 0,
                     jobs_per_rate: int = 12, classes: list | None = None,
                     steps_scale: int = 24, steps_cap: int = 600,
                     timeout_s: float = 600.0, on_point=None) -> list:
    """The ``fleet_sat`` ladder: one ``run_plan`` per offered rate,
    ascending, each from a derived seed (``seed*1000 + step``) so points
    are independent but the whole curve re-runs identically. Returns one
    row per rate: offered jobs/s, achieved jobs/s, p50/p99 queue wait
    (overall and per class). ``on_point(row)`` fires after each rate —
    bench.py banks partial curves through it, so a wall-clock cap still
    leaves a usable prefix."""
    curve = []
    for i, rate in enumerate(rates):
        plan = make_plan(seed * 1000 + i, jobs_per_rate, rate,
                         classes=classes, steps_scale=steps_scale,
                         steps_cap=steps_cap)
        res = run_plan(router_url, plan, timeout_s=timeout_s)
        row = {"offered_jobs_per_s": rate, **res["summary"],
               "per_class": res["per_class"]}
        curve.append(row)
        if on_point is not None:
            on_point(row)
    return curve
