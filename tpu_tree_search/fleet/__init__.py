"""``tts fleet`` — class-aware routing over N serve daemons.

The serve daemon (``serve/``) made one process a multi-tenant search
service: shape-class program pooling, checkpoint preemption, instance
batching, ``/metrics``. This package is the missing front-end for
ROADMAP item 2's fleet: one router process that owns *placement* across
many daemons, so a tenant talks to a single URL and jobs land where
their compiled program already lives.

Layout (each module owns one concern):

  * ``placement.py`` — the scraped per-daemon state (``/healthz`` +
    ``/classes`` + ``/metrics``) and the pure placement policy:
    warm-class-with-free-slot first (zero-compile admission, same
    ``serve/pool.class_key`` computation), weighted least-loaded
    otherwise (queue depth, measured queue-wait, pool bytes, class
    occupancy);
  * ``health.py``    — the background keeper thread: scrape loop with
    miss-counting + exponential backoff, daemon death/drain detection,
    periodic checkpoint pulls for in-flight jobs (the recovery fuel),
    and conservative hot->idle rebalancing of long-runners;
  * ``router.py``    — the stdlib HTTP router daemon (same zero-dep
    127.0.0.1 pattern as ``serve/server.py``): placement + lifecycle
    proxy (``/submit``, ``/job/<id>``, SSE pass-through, cancel) with a
    durable fleet-job -> daemon map under ``--state-dir``, and the
    failure-recovery path built on the ``tts migrate`` checkpoint
    transport (resubmit the last pulled cut + remaining budget
    elsewhere — bit-identical to an uninterrupted run);
  * ``loadgen.py``   — the seeded synthetic traffic generator (mixed
    shape classes, heavy-tailed job sizes, Poisson arrivals) and the
    saturation-curve driver behind ``bench.py fleet_sat``.

The router is **host-only**: it never imports jax, never constructs a
problem, and no knob it reads (``TTS_ROUTER``) may appear in any
compiled-program cache key — pinned by tests/test_fleet.py.
"""

from __future__ import annotations

#: One above the serve daemon's default (8643), itself one above the
#: obs/live watch port (8642).
DEFAULT_ROUTER_PORT = 8644

__all__ = ["DEFAULT_ROUTER_PORT"]
