"""The ``tts fleet`` router daemon: one URL in front of N serve daemons.

Zero-dependency by the same rule as ``serve/server.py`` (stdlib
``http.server`` only, bound to 127.0.0.1) and strictly **host-only**:
the router never imports jax and never builds a problem — its class-key
computation is the same host-side ``serve/pool.class_key`` the daemons
run at admission, which is the whole warm-placement contract.

API (all JSON; every job endpoint speaks *fleet* job ids, stable across
recoveries and rebalances — the daemon-local id of the moment rides
along as ``daemon_job``):

  * ``POST /submit``            — place + proxy. 201 -> the daemon's
    admission payload plus ``{id: <fleet id>, daemon, daemon_job,
    placement: warm|cold}``; 400 invalid spec; 503 when no registered
    daemon can take the job.
  * ``POST /register``          — body ``{url}``: add a daemon to the
    fleet (``tts serve --router`` self-registers at startup). Durable.
  * ``GET  /job/<id>``          — the owning daemon's record, identity
    rewritten to the fleet view; a cached copy (``stale: true``) while
    the owner is unreachable mid-recovery.
  * ``GET  /job/<id>/result``   — proxied result (409 until terminal).
  * ``POST /job/<id>/cancel``   — proxied cancel.
  * ``GET  /job/<id>/stream``   — SSE pass-through from the owning
    daemon, re-attached across recoveries/rebalances; the terminal
    ``done`` frame is rewritten to the fleet identity.
  * ``GET  /jobs``              — every fleet job (brief records).
  * ``GET  /daemons``           — per-daemon scraped snapshots.
  * ``GET  /fleet``             — the ``tts top --router`` aggregate:
    router health + daemon snapshots + brief job rows.
  * ``GET  /healthz``           — router liveness + fleet counts.
  * ``POST /shutdown``          — stop the router (daemons unaffected).

Recovery model: the keeper (health.py) pulls every in-flight job's
latest checkpoint cut — plus the record's exact ``steps`` at that cut —
into the router's ``--state-dir``. On daemon drain the router migrates
jobs live (cancel-with-cut -> fetch -> resubmit, the ``tts migrate``
flow); on daemon death it resubmits the last pulled cut with the
remaining ``max_steps`` budget elsewhere. Either way the engine's
checkpoint contract (cumulative counters) makes the final result
bit-identical to an uninterrupted run; a job that never reached a cut
simply restarts from scratch, which *is* an uninterrupted run.

Lock discipline (analysis/lockorder.py): ``FleetJobMap`` mirrors the
serve registry's ``_io_lock -> _lock`` persist nesting; no router
method holds a map lock while talking to a socket.
"""

from __future__ import annotations

import base64
import json
import os
import signal
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.error import HTTPError, URLError
from urllib.parse import urlparse
from urllib.request import urlopen

from ..obs.live import sse_begin, sse_event
from ..serve import VERSION
from ..serve.client import _get, _post, fetch_checkpoint
from ..serve.server import FINAL_STATES
from . import DEFAULT_ROUTER_PORT, placement
from .health import HealthChecker


def default_state_dir() -> str:
    return os.environ.get("TTS_FLEET_STATE") or os.path.join(
        os.path.expanduser("~"), ".cache", "tpu_tree_search", "fleet"
    )


class RouteError(RuntimeError):
    """No registered daemon could take the job (placement exhausted)."""

    def __init__(self, msg: str, tried: list):
        super().__init__(msg)
        self.tried = tried


class FleetJob:
    """One routed job: the durable fleet record. Mutated only through
    ``FleetJobMap`` methods (which persist atomically)."""

    def __init__(self, fid: str, spec: dict, cls: str):
        self.id = fid
        self.spec = spec  # the validated spec (re-routable as-is)
        self.cls = cls
        self.daemon = None  # current owner base URL
        self.daemon_job = None  # owner-local job id
        self.submitted = time.time()
        self.resubmits = 0  # recoveries + rebalances
        self.history: list = []  # every (daemon, daemon_job) placement
        self.ckpt = None  # last pulled checkpoint (router-local path)
        self.ckpt_steps = 0  # the record's exact steps at that cut
        self.last_record = None  # last owner record seen (pull cache)
        self.needs_recovery = False  # owner died; waiting for capacity
        self.migrating = False  # transient: a live migration is mid-flight
        self.error = None

    def record(self) -> dict:
        return {
            "id": self.id,
            "spec": self.spec,
            "class": self.cls,
            "daemon": self.daemon,
            "daemon_job": self.daemon_job,
            "submitted": self.submitted,
            "resubmits": self.resubmits,
            "history": self.history,
            "ckpt": self.ckpt,
            "ckpt_steps": self.ckpt_steps,
            "last_record": self.last_record,
            "needs_recovery": self.needs_recovery,
            "error": self.error,
        }

    def brief(self) -> dict:
        """The ``/jobs`` + ``/fleet`` row: mapping + cached progress."""
        rec = self.last_record or {}
        return {
            "id": self.id,
            "daemon": self.daemon,
            "daemon_job": self.daemon_job,
            "class": self.cls,
            "state": ("recovering" if self.needs_recovery
                      else rec.get("state") or "routed"),
            "steps": rec.get("steps", 0),
            "resubmits": self.resubmits,
            "submitted": self.submitted,
        }

    @classmethod
    def from_record(cls, rec: dict) -> "FleetJob":
        job = cls(rec["id"], rec["spec"], rec["class"])
        for k in ("daemon", "daemon_job", "submitted", "resubmits",
                  "history", "ckpt", "ckpt_steps", "last_record",
                  "needs_recovery", "error"):
            if k in rec:
                setattr(job, k, rec[k])
        return job


class FleetJobMap:
    """Durable fleet-id -> FleetJob map (``<state_dir>/jobs/``), the
    registry pattern from serve/jobs.py: every mutation persists the
    record atomically; a restarted router reloads the full map and the
    keeper resumes monitoring where it left off.

    Lock order: ``_io_lock`` may acquire ``_lock`` (``_persist``
    snapshots inside the write critical section), never the reverse."""

    def __init__(self, state_dir: str):
        self.jobs_dir = os.path.join(state_dir, "jobs")
        os.makedirs(self.jobs_dir, exist_ok=True)
        self._lock = threading.Lock()
        # Serializes _persist (same torn-write reasoning as the serve
        # registry: last rename to land must be the newest record).
        self._io_lock = threading.Lock()
        self._jobs = {}  # guarded-by: _lock
        self._seq = 0  # guarded-by: _lock

    def load(self) -> int:
        n = 0
        for name in sorted(os.listdir(self.jobs_dir)):
            if not name.endswith(".json"):
                continue
            try:
                with open(os.path.join(self.jobs_dir, name)) as f:
                    job = FleetJob.from_record(json.load(f))
            except (OSError, ValueError, KeyError):
                continue  # truncated/alien file: skip, don't crash startup
            with self._lock:
                self._jobs[job.id] = job
                try:
                    self._seq = max(self._seq, int(job.id.split("-")[-1]))
                except ValueError:
                    pass
            n += 1
        return n

    def create(self, spec: dict, cls: str) -> FleetJob:
        with self._lock:
            self._seq += 1
            job = FleetJob(f"fjob-{self._seq:06d}", spec, cls)
            self._jobs[job.id] = job
        self._persist(job)
        return job

    def get(self, fid: str):
        with self._lock:
            return self._jobs.get(fid)

    def all(self) -> list:
        with self._lock:
            return sorted(self._jobs.values(), key=lambda j: j.id)

    def by_daemon(self, url: str) -> list:
        url = url.rstrip("/")
        with self._lock:
            return sorted((j for j in self._jobs.values()
                           if j.daemon == url), key=lambda j: j.id)

    def find(self, url: str, daemon_job: str):
        url = url.rstrip("/")
        with self._lock:
            for j in self._jobs.values():
                if j.daemon == url and j.daemon_job == daemon_job:
                    return j
        return None

    def update(self, job: FleetJob, **fields) -> None:
        with self._lock:
            for k, v in fields.items():
                setattr(job, k, v)
        self._persist(job)

    def _persist(self, job: FleetJob) -> None:
        path = os.path.join(self.jobs_dir, f"{job.id}.json")
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        with self._io_lock:
            with self._lock:
                rec = job.record()
            with open(tmp, "w") as f:
                json.dump(rec, f)
            os.replace(tmp, path)


class FleetRouter:
    """The router's spine: fleet view + durable job map + keeper + HTTP."""

    def __init__(self, port: int = DEFAULT_ROUTER_PORT,
                 host: str = "127.0.0.1", state_dir: str | None = None,
                 daemons: list | None = None,
                 scrape_interval_s: float = 1.0, max_misses: int = 3,
                 pull_interval_s: float = 2.0, rebalance: bool = True,
                 rebalance_min_depth: int = 2,
                 proxy_timeout_s: float = 10.0):
        self.state_dir = state_dir or default_state_dir()
        os.makedirs(self.state_dir, exist_ok=True)
        self.ckpt_dir = os.path.join(self.state_dir, "ckpt")
        os.makedirs(self.ckpt_dir, exist_ok=True)
        self.view = placement.FleetView()
        self.jobs = FleetJobMap(self.state_dir)
        self.loaded = self.jobs.load()
        self.proxy_timeout_s = float(proxy_timeout_s)
        self.started = time.time()
        self.stop_event = threading.Event()
        for url in self._load_daemons():
            self.view.add(url)
        for url in daemons or []:
            self.register(url, persist=True, scrape=False)
        self.keeper = HealthChecker(
            self, interval_s=scrape_interval_s, max_misses=max_misses,
            pull_interval_s=pull_interval_s, rebalance=rebalance,
            rebalance_min_depth=rebalance_min_depth)
        self._httpd = ThreadingHTTPServer((host, port), _RouterHandler)
        self._httpd.daemon_threads = True
        self._httpd.router = self  # handler back-reference
        self.host = host
        self.port = self._httpd.server_address[1]
        self._http_thread = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> None:
        # One synchronous sweep first: static --daemon entries are
        # placeable before the first submit arrives.
        self.keeper.scrape_once()
        self.keeper.start()
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.2},
            name="tts-fleet-http", daemon=True)
        self._http_thread.start()

    def close(self) -> None:
        self.keeper.stop()
        self._httpd.shutdown()
        self._httpd.server_close()

    # -- daemon registration -------------------------------------------------

    def _daemons_path(self) -> str:
        return os.path.join(self.state_dir, "daemons.json")

    def _load_daemons(self) -> list:
        try:
            with open(self._daemons_path()) as f:
                return [str(u) for u in json.load(f)]
        except (OSError, ValueError):
            return []

    def register(self, url: str, persist: bool = True,
                 scrape: bool = True) -> dict:
        url = url.rstrip("/")
        if "://" not in url:
            url = "http://" + url
        st = self.view.add(url)
        if persist:
            urls = sorted(s.url for s in self.view.states())
            tmp = self._daemons_path() + ".tmp"
            with open(tmp, "w") as f:
                json.dump(urls, f)
            os.replace(tmp, self._daemons_path())
        if scrape:
            try:  # make it placeable now, not a keeper-tick later
                self.view.mark_ok(st, placement.scrape(url, timeout=3.0))
            except Exception:  # noqa: BLE001 — keeper will keep probing
                pass
        return {"url": url, "healthy": st.healthy,
                "daemons": len(self.view.states())}

    # -- health / aggregates -------------------------------------------------

    def health(self) -> dict:
        states = self.view.states()
        healthy = sum(1 for s in states if s.healthy)
        return {
            "ok": healthy > 0,
            "router": True,
            "daemons": len(states),
            "daemons_healthy": healthy,
            "jobs": len(self.jobs.all()),
            "uptime_s": round(max(0.0, time.time() - self.started), 3),
            "version": VERSION,
        }

    def fleet(self) -> dict:
        return {
            "router": self.health(),
            "daemons": [st.snapshot() for st in self.view.states()],
            "jobs": [j.brief() for j in self.jobs.all()],
        }

    # -- placement + submit --------------------------------------------------

    def _route(self, payload: dict, cls: str, exclude=(),
               only: str | None = None):
        """Place and POST one spec. Tries daemons in policy order until
        one admits (a 503 — queue full / draining — moves on to the
        next); returns ``(DaemonState, reason, response)``. ``only``
        pins the destination (rebalance)."""
        tried: list = []
        excluded = {u.rstrip("/") for u in exclude}
        while True:
            states = [st for st in self.view.states()
                      if st.url not in excluded and st.url not in tried
                      and (only is None or st.url == only.rstrip("/"))]
            st, reason = placement.choose(states, cls)
            if st is None:
                raise RouteError(
                    f"no daemon can take class {cls} ({reason})", tried)
            try:
                code, resp = _post(st.url + "/submit", payload,
                                   timeout=60.0, retry_s=2.0)
            except (URLError, OSError):
                tried.append(st.url)
                continue
            if code == 201:
                return st, reason, resp
            if code == 503:
                tried.append(st.url)
                continue
            # 400 etc.: the daemon's rejection is authoritative.
            raise RouteError(f"daemon {st.url} rejected the job "
                             f"({code}): {resp.get('error', resp)}", tried)

    def submit(self, spec) -> tuple[dict, int]:
        """Admission: validate host-side, classify with the daemons' own
        class-key computation, place, proxy. HTTP-thread safe: no jax,
        no problem builds, placement runs on the keeper's snapshots."""
        from ..serve.jobs import validate_spec
        from ..serve.pool import class_key

        ckpt_b64 = None
        if isinstance(spec, dict) and "resume_ckpt_b64" in spec:
            spec = dict(spec)
            ckpt_b64 = spec.pop("resume_ckpt_b64")
        try:
            validated = validate_spec(spec)
            cls = class_key(validated)
        except ValueError as e:
            return {"error": str(e)}, 400
        payload = dict(validated)
        if ckpt_b64 is not None:
            payload["resume_ckpt_b64"] = ckpt_b64
        try:
            st, reason, resp = self._route(payload, cls)
        except RouteError as e:
            return {"error": str(e), "tried": e.tried}, 503
        job = self.jobs.create(validated, cls)
        self.jobs.update(job, daemon=st.url, daemon_job=resp["id"],
                         history=[{"daemon": st.url,
                                   "daemon_job": resp["id"]}])
        return {**resp, "id": job.id, "daemon": st.url,
                "daemon_job": resp["id"], "placement": reason}, 201

    # -- job views -----------------------------------------------------------

    def fleet_record(self, job: FleetJob, rec: dict) -> dict:
        """A daemon job record rewritten to the fleet identity."""
        rec = dict(rec)
        rec["daemon_job"] = rec.get("id")
        rec["id"] = job.id
        rec["daemon"] = job.daemon
        rec["resubmits"] = job.resubmits
        return rec

    def job_record(self, job: FleetJob) -> dict:
        """The freshest record we can get: live proxy from the owner,
        else the pull cache (``stale: true``) — a job mid-recovery must
        keep answering polls as non-terminal, not 404."""
        try:
            code, rec = _get(f"{job.daemon}/job/{job.daemon_job}",
                             timeout=self.proxy_timeout_s)
            if code == 200:
                if rec.get("state") == "cancelled" and \
                        getattr(job, "migrating", False):
                    # A live migration cut this copy — its successor is
                    # about to be placed elsewhere. Report the
                    # transition, not a terminal state the fleet job
                    # never had (pollers must keep polling).
                    rec = dict(rec)
                    rec["state"] = "requeued"
                    return self.fleet_record(job, rec)
                self.jobs.update(job, last_record=rec)
                return self.fleet_record(job, rec)
        except (URLError, OSError):
            pass
        if job.last_record is not None:
            rec = self.fleet_record(job, job.last_record)
            if rec.get("state") not in FINAL_STATES:
                rec["stale"] = True
            return rec
        return {"id": job.id, "daemon": job.daemon, "state": "queued",
                "class": job.cls, "stale": True}

    # -- checkpoint pulls (keeper thread) ------------------------------------

    def _pull_one(self, job: FleetJob) -> None:
        base = job.daemon
        code, rec = _get(f"{base}/job/{job.daemon_job}", timeout=5.0)
        if code != 200:
            return
        self.jobs.update(job, last_record=rec)
        steps = int(rec.get("steps") or 0)
        if not rec.get("checkpoint") or \
                (job.ckpt is not None and steps == job.ckpt_steps):
            return  # nothing new to pull
        try:
            raw, _wire = fetch_checkpoint(base, job.daemon_job, timeout=30.0)
        except (HTTPError, URLError, OSError):
            return  # e.g. the cut was consumed (job finished); next round
        # Consistency guard: the checkpoint file and the record's steps
        # update together at a cut — re-read the record and keep the pull
        # only if no new cut landed between our two reads.
        code, rec2 = _get(f"{base}/job/{job.daemon_job}", timeout=5.0)
        if code != 200 or int(rec2.get("steps") or 0) != steps:
            return
        path = os.path.join(self.ckpt_dir, f"{job.id}.npz")
        tmp = f"{path}.tmp.{threading.get_ident()}"
        with open(tmp, "wb") as f:
            f.write(raw)
        os.replace(tmp, path)
        self.jobs.update(job, ckpt=path, ckpt_steps=steps,
                         last_record=rec2)

    def pull_checkpoints(self) -> None:
        """Keeper duty: refresh every in-flight job's record cache and
        copy new checkpoint cuts local; retry stranded recoveries once
        capacity is back."""
        for job in self.jobs.all():
            if job.needs_recovery:
                try:
                    self._recover_from_pull(job)
                except (RouteError, URLError, OSError):
                    pass  # still no capacity; keep the flag
                continue
            state = (job.last_record or {}).get("state")
            if state in FINAL_STATES:
                continue
            st = self.view.get(job.daemon) if job.daemon else None
            if st is None or not st.healthy:
                continue
            try:
                self._pull_one(job)
            except (URLError, OSError):
                continue

    # -- recovery ------------------------------------------------------------

    def _recovery_payload(self, job: FleetJob, steps_done: int,
                          raw_ckpt) -> dict:
        """The resubmission body: the job's own validated spec, with the
        checkpoint attached and a consumed ``max_steps`` budget reduced
        to the remainder — the exact ``tts migrate`` arithmetic, which
        is what makes the recovered run bit-identical to an
        uninterrupted one."""
        payload = dict(job.spec)
        if raw_ckpt is None:
            return payload  # never reached a cut: restart from scratch
        if payload.get("max_steps") is not None:
            remaining = int(payload["max_steps"]) - int(steps_done)
            if remaining <= 0:
                raise RouteError(
                    f"{job.id}: budget exhausted at the last cut", [])
            payload["max_steps"] = remaining
        payload["resume_ckpt_b64"] = base64.b64encode(raw_ckpt).decode()
        return payload

    def _place_recovered(self, job: FleetJob, payload: dict,
                         exclude=(), only: str | None = None) -> None:
        st, _reason, resp = self._route(payload, job.cls,
                                        exclude=exclude, only=only)
        self.jobs.update(
            job, daemon=st.url, daemon_job=resp["id"],
            resubmits=job.resubmits + 1,
            history=job.history + [{"daemon": st.url,
                                    "daemon_job": resp["id"]}],
            needs_recovery=False, error=None, last_record=None)

    def _recover_from_pull(self, job: FleetJob) -> None:
        """Dead-owner recovery: resubmit the last *pulled* cut (the
        owner cannot answer). ``ckpt_steps`` was recorded at pull time
        from the same record revision as the bytes, so the remaining
        budget is exact."""
        raw = None
        if job.ckpt and os.path.exists(job.ckpt):
            with open(job.ckpt, "rb") as f:
                raw = f.read()
        payload = self._recovery_payload(job, job.ckpt_steps, raw)
        self._place_recovered(job, payload,
                              exclude=(job.daemon,) if job.daemon else ())

    def _migrate_live(self, job: FleetJob, only: str | None = None) -> bool:
        """Live migration (drain/rebalance): the ``tts migrate`` flow
        against a still-answering owner — cancel (cutting a running
        slice at the next dispatch boundary), fetch the cut, resubmit
        the remainder elsewhere. Returns False when the job turned out
        terminal (nothing to move)."""
        src, djid = job.daemon, job.daemon_job
        code, rec = _get(f"{src}/job/{djid}", timeout=10.0, retry_s=2.0)
        if code != 200:
            raise RouteError(f"{job.id}: owner lost its record ({code})", [])
        if rec.get("state") in FINAL_STATES:
            self.jobs.update(job, last_record=rec)
            return False
        # The flag masks the source copy's transient 'cancelled' from
        # every proxy surface until the successor is placed (or the
        # migration fails and needs_recovery takes over).
        self.jobs.update(job, migrating=True)
        try:
            _post(f"{src}/job/{djid}/cancel", {}, retry_s=2.0)
            deadline = time.monotonic() + 120.0
            while time.monotonic() < deadline:
                code, rec = _get(f"{src}/job/{djid}", timeout=10.0,
                                 retry_s=2.0)
                if code == 200 and rec.get("state") in FINAL_STATES:
                    break
                time.sleep(0.2)
            if rec.get("state") == "done":
                self.jobs.update(job, last_record=rec)
                return False  # finished before the cut: result stands
            raw = None
            if rec.get("checkpoint"):
                raw, _wire = fetch_checkpoint(src, djid, timeout=30.0,
                                              retry_s=2.0)
            payload = self._recovery_payload(
                job, int(rec.get("steps") or 0), raw)
            self._place_recovered(job, payload, exclude=(src,), only=only)
            return True
        finally:
            self.jobs.update(job, migrating=False)

    def recover_daemon(self, url: str, live: bool) -> int:
        """Move every non-terminal job off a dead (``live=False``) or
        draining (``live=True``) daemon. Jobs that cannot be placed yet
        are flagged ``needs_recovery`` and retried by the keeper as
        capacity returns. Returns the number of jobs moved."""
        url = url.rstrip("/")
        moved = 0
        for job in self.jobs.by_daemon(url):
            state = (job.last_record or {}).get("state")
            if state in FINAL_STATES and not job.needs_recovery:
                continue
            try:
                if live:
                    moved += 1 if self._migrate_live(job) else 0
                else:
                    self._recover_from_pull(job)
                    moved += 1
            except (RouteError, HTTPError, URLError, OSError) as e:
                if live:
                    # The daemon died mid-drain: fall back to the pulls.
                    try:
                        self._recover_from_pull(job)
                        moved += 1
                        continue
                    except (RouteError, HTTPError, URLError, OSError):
                        pass
                self.jobs.update(job, needs_recovery=True,
                                 error=f"{type(e).__name__}: {e}")
        return moved

    # -- rebalance -----------------------------------------------------------

    def maybe_rebalance(self, min_depth: int = 2) -> bool:
        """One conservative hot->idle move per call (keeper cadence):
        the hot daemon's longest-running checkpointed job migrates to a
        fully idle daemon. Only jobs the router itself placed move."""
        picked = placement.pick_rebalance(self.view.states(), min_depth)
        if picked is None:
            return False
        hot, rec, cold = picked
        job = self.jobs.find(hot.url, rec.get("id"))
        if job is None:
            return False  # submitted around the router; not ours to move
        try:
            return self._migrate_live(job, only=cold.url)
        except (RouteError, HTTPError, URLError, OSError) as e:
            self.jobs.update(job, error=f"rebalance: {type(e).__name__}: {e}")
            return False


class _RouterHandler(BaseHTTPRequestHandler):
    server_version = "tts-fleet/1"

    def log_message(self, fmt, *args):  # silence per-request stderr noise
        pass

    @property
    def router(self) -> FleetRouter:
        return self.server.router

    def _json(self, payload, code: int = 200) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _body(self, limit: int = 64 << 20):
        n = int(self.headers.get("Content-Length") or 0)
        if n <= 0 or n > limit:
            return None
        try:
            return json.loads(self.rfile.read(n).decode())
        except (ValueError, UnicodeDecodeError):
            return None

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler's contract
        path = urlparse(self.path).path
        try:
            if path == "/healthz":
                self._json(self.router.health())
            elif path == "/fleet":
                self._json(self.router.fleet())
            elif path == "/daemons":
                self._json([st.snapshot()
                            for st in self.router.view.states()])
            elif path == "/jobs":
                self._json([j.brief() for j in self.router.jobs.all()])
            elif path.startswith("/job/"):
                parts = path.split("/")  # ['', 'job', '<id>', ...]
                job = (self.router.jobs.get(parts[2])
                       if len(parts) >= 3 else None)
                if job is None:
                    self._json({"error": "unknown job"}, code=404)
                elif len(parts) == 3:
                    self._json(self.router.job_record(job))
                elif parts[3] == "result":
                    self._proxy_result(job)
                elif parts[3] == "stream":
                    self._stream_proxy(job)
                else:
                    self._json({"error": "unknown path"}, code=404)
            else:
                self._json({"error": "unknown path"}, code=404)
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away; nothing to clean up

    def do_POST(self):  # noqa: N802
        path = urlparse(self.path).path
        try:
            if path == "/submit":
                body = self._body()
                if body is None:
                    self._json({"error": "invalid JSON body"}, code=400)
                    return
                payload, code = self.router.submit(body)
                self._json(payload, code=code)
            elif path == "/register":
                body = self._body(limit=1 << 16)
                if not isinstance(body, dict) or not body.get("url"):
                    self._json({"error": "body must be {url: ...}"},
                               code=400)
                    return
                self._json(self.router.register(str(body["url"])))
            elif path == "/shutdown":
                self._json({"ok": True})
                self.router.stop_event.set()
            elif path.startswith("/job/") and path.endswith("/cancel"):
                fid = path.split("/")[2]
                job = self.router.jobs.get(fid)
                if job is None:
                    self._json({"error": "unknown job"}, code=404)
                    return
                try:
                    code, resp = _post(
                        f"{job.daemon}/job/{job.daemon_job}/cancel", {},
                        timeout=self.router.proxy_timeout_s)
                except (URLError, OSError) as e:
                    self._json({"error": f"owner unreachable: {e}"},
                               code=503)
                    return
                if isinstance(resp, dict) and "id" in resp:
                    resp = {**resp, "id": fid,
                            "daemon_job": job.daemon_job}
                self._json(resp, code=code)
            else:
                self._json({"error": "unknown path"}, code=404)
        except (BrokenPipeError, ConnectionResetError):
            pass

    def _proxy_result(self, job: FleetJob) -> None:
        try:
            code, rec = _get(f"{job.daemon}/job/{job.daemon_job}/result",
                             timeout=self.router.proxy_timeout_s)
        except (URLError, OSError):
            cached = job.last_record
            if cached is not None and cached.get("state") in FINAL_STATES:
                self._json({"id": job.id, "state": cached["state"],
                            "result": cached.get("result"),
                            "error": cached.get("error"), "stale": True})
                return
            self._json({"error": "owner unreachable (recovering)",
                        "state": "queued"}, code=409)
            return
        if isinstance(rec, dict) and rec.get("state") == "cancelled" \
                and getattr(job, "migrating", False):
            # Mid-migration: the source copy's cancellation is not this
            # job's result — keep answering 409 until the successor ends.
            self._json({"error": "job is migrating", "state": "requeued"},
                       code=409)
            return
        if isinstance(rec, dict) and "id" in rec:
            rec = {**rec, "id": job.id, "daemon_job": job.daemon_job}
        self._json(rec, code=code)

    def _stream_proxy(self, job: FleetJob) -> None:
        """SSE pass-through, re-attached across recoveries: relay the
        owner's per-job stream byte-for-byte; when it drops (daemon
        death, migration cut) re-resolve the owner and reconnect. The
        terminal ``done`` frame is rewritten to the fleet identity; if
        the job finishes while no owner stream is attached (recovery
        landed the final cut elsewhere), a synthetic ``done`` frame is
        emitted from the proxied record. Clients dedupe replayed frames
        exactly as they already do for daemon restarts."""
        router = self.router
        sse_begin(self, comment=f"tts fleet job stream {job.id}")
        deadline = time.monotonic() + 3600.0
        while time.monotonic() < deadline and \
                not router.stop_event.is_set():
            job = router.jobs.get(job.id) or job  # refresh the mapping
            try:
                with urlopen(f"{job.daemon}/job/{job.daemon_job}/stream",
                             timeout=600.0) as resp:  # noqa: S310
                    in_done = False
                    for line in resp:
                        if line.startswith(b"event: done"):
                            # Held back until the payload is vetted: a
                            # live migration ends the SOURCE copy with
                            # 'cancelled', which is not this job's end.
                            in_done = True
                            continue
                        if in_done and line.startswith(b"data: "):
                            try:
                                rec = json.loads(line[6:].decode())
                            except ValueError:
                                rec = None
                            cur = router.jobs.get(job.id) or job
                            if rec is not None \
                                    and rec.get("state") == "cancelled" \
                                    and (cur.migrating or
                                         cur.daemon_job != rec.get("id")):
                                in_done = False
                                break  # reattach to the successor copy
                            if rec is not None:
                                rec = router.fleet_record(cur, rec)
                                line = (b"data: "
                                        + json.dumps(rec).encode() + b"\n")
                            self.wfile.write(b"event: done\n" + line
                                             + b"\n")
                            self.wfile.flush()
                            return  # the job's story is complete
                        self.wfile.write(line)
                        self.wfile.flush()
            except (URLError, OSError, ValueError):
                pass
            # Stream dropped: finished elsewhere, mid-recovery, or the
            # owner restarted. Poll the fleet view and either finish the
            # story or re-attach.
            rec = router.job_record(job)
            if rec.get("state") in FINAL_STATES and not rec.get("stale"):
                sse_event(self, rec, event="done")
                return
            time.sleep(0.3)


def router_main(port: int = DEFAULT_ROUTER_PORT, host: str = "127.0.0.1",
                state_dir: str | None = None, daemons: list | None = None,
                scrape_interval_s: float = 1.0, max_misses: int = 3,
                pull_interval_s: float = 2.0, rebalance: bool = True,
                rebalance_min_depth: int = 2) -> int:
    """The ``tts fleet`` entry point: start, print the banner, wait for
    SIGTERM/SIGINT (or POST /shutdown). The router carries no search
    state of its own beyond the durable job map — stopping it never
    touches the daemons' jobs, and a restart resumes monitoring from
    the map."""
    router = FleetRouter(
        port=port, host=host, state_dir=state_dir, daemons=daemons,
        scrape_interval_s=scrape_interval_s, max_misses=max_misses,
        pull_interval_s=pull_interval_s, rebalance=rebalance,
        rebalance_min_depth=rebalance_min_depth)

    def _on_signal(signum, frame):
        router.stop_event.set()

    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, _on_signal)
    router.start()
    n = len(router.view.states())
    print(f"Fleet router on {router.url} (v{VERSION}, "
          f"state: {router.state_dir}, {n} daemon(s) registered"
          + (f", reloaded {router.loaded} job record(s)" if router.loaded
             else "") + ")", flush=True)
    try:
        while not router.stop_event.wait(0.5):
            pass
    except KeyboardInterrupt:
        pass
    router.close()
    print("Fleet router stopped (daemons and their jobs are unaffected).",
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(router_main())
