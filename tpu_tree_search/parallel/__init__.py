"""Parallel runtimes: multi-device (threads + work stealing + termination),
mesh-SPMD chunk evaluation (jax.sharding + collectives), and the multi-host
distributed tier (jax.distributed).

Replaces the reference's L4 layer — the inlined partitioning / work-stealing /
termination scaffolding of the multi-GPU and distributed mains
(`nqueens_multigpu_chpl.chpl:199-320`, `pfsp_dist_multigpu_chpl.chpl:292-377`)
— with reusable components (SURVEY.md §2.4).
"""
