"""SPMD chunk evaluation over a TPU device mesh.

The reference parallelizes across GPUs with one host task per device and a
private pool each (`pfsp_multigpu_chpl.chpl:375-435`). On TPU there is a
second, more idiomatic axis: a single jitted step sharded over the whole
mesh, where XLA inserts the collectives (scaling-book recipe). This module
provides that step:

  * ``dp`` axis: the chunk's parent batch is sharded across devices — the
    direct analogue of the reference's one-GPU-per-chunk-slice, but with one
    dispatch for all chips and ICI (not host) moving the data.
  * ``mp`` axis (PFSP lb2 only): the Johnson machine-pair loop — the O(m²)
    table axis (`c_bound_johnson.c:48-92`) — is sharded, each device reducing
    its pair subset, combined with a ``jax.lax.pmax``. This is the
    model-parallel analogue the SIMT design has no equivalent of.
  * incumbent all-reduce: leaf makespans are min-reduced across the mesh
    inside the same step (``jax.lax.pmin``) — the mid-search UB broadcast the
    reference lacks entirely (SURVEY.md §2.4.4: reconciliation only at
    terminal reduction; BASELINE north star names this the planned
    improvement).

The step is shape-static and donates nothing host-side: the multi-device
engine calls it once per chunk with the batch padded to a bucket.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..obs import events as ev
from ..utils import jax_compat
from ..problems.base import INF_BOUND


def make_mesh(n_devices: int | None = None, mp: int = 1, devices=None) -> Mesh:
    """Build a (dp, mp) mesh over the first ``n_devices`` local devices.

    ``mp`` > 1 carves off a machine-pair axis for lb2; everything else uses
    pure data parallelism (mp=1).
    """
    if devices is None:
        devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    devices = np.asarray(devices[:n_devices])
    if n_devices % mp != 0:
        raise ValueError(f"n_devices={n_devices} not divisible by mp={mp}")
    return Mesh(devices.reshape(n_devices // mp, mp), ("dp", "mp"))


def _pad_len(n: int, k: int) -> int:
    return (n + k - 1) // k * k


class MeshEvaluator:
    """Sharded chunk evaluator for one problem over one mesh.

    ``__call__(parents, count, best) -> (results, new_best)`` where parents
    is a host-side dict batch (padded to a multiple of dp), results is a
    host-materializable array of per-child labels/bounds, and new_best folds
    the chunk's leaf improvements via an in-step mesh-wide min.
    """

    def __init__(self, problem, mesh: Mesh):
        self.problem = problem
        self.mesh = mesh
        self.dp = mesh.shape["dp"]
        self.mp = mesh.shape["mp"]
        t_build = ev.now_us()
        self._step = self._build(problem, mesh)
        ev.complete("build", t_build, cat="compile", args={
            "program": "mesh_chunk_step", "problem": problem.name,
            "dp": int(self.dp), "mp": int(self.mp),
        })

    # -- construction ------------------------------------------------------

    def _build(self, problem, mesh):
        if problem.name == "pfsp":
            return self._build_pfsp(problem, mesh)
        return self._build_nqueens(problem, mesh)

    def _build_nqueens(self, problem, mesh):
        from ..ops import nqueens_device

        core = nqueens_device.make_core(problem.N, problem.g)

        @partial(
            jax_compat.shard_map,
            mesh=mesh,
            in_specs=({"depth": P("dp"), "board": P("dp", None)},),
            out_specs=P("dp", None),
        )
        def step(parents):
            # mp axis unused for N-Queens: labels are replicated along it.
            # No incumbent exists (backtracking never prunes), so the step
            # returns labels only — no collective needed.
            return core(parents["board"], parents["depth"])

        jitted = jax.jit(step)

        def run(parents, count, best):
            del count, best
            return jitted(parents), INF_BOUND

        return jitted, run

    def _build_pfsp(self, problem, mesh):
        from ..ops import pfsp_device

        tables = problem.device_tables()
        jobs = problem.jobs
        lb = problem.lb
        if lb == "lb2":
            # Pair tables padded to a multiple of mp with copies of pair 0
            # (max over pairs is idempotent) — shared helper.
            pairs, lags, scheds = tables.mp_padded(self.mp)

        node_spec = {"depth": P("dp"), "limit1": P("dp"), "prmu": P("dp", None)}

        if lb == "lb2":
            in_specs = (
                node_spec,
                P(),  # best
                P(None, None),  # ptm_t
                P(None),  # min_heads
                P(None),  # min_tails
                P("mp", None),  # pairs
                P("mp", None),  # lags
                P("mp", None),  # johnson_schedules
            )

            @partial(jax_compat.shard_map, mesh=mesh, in_specs=(*in_specs, P()),
                     out_specs=(P("dp", None), P()))
            def step(parents, best, ptm_t, min_heads, min_tails, prs, lgs, sch, count):
                local = pfsp_device._lb2_chunk(
                    parents["prmu"], parents["limit1"], ptm_t,
                    min_heads, min_tails, prs, lgs, sch,
                    bf16=tables.exact_bf16,
                )
                bounds = jax.lax.pmax(local, "mp")  # combine pair subsets
                new_best = _fold_leaf_best(parents, bounds, best, jobs, count)
                return bounds, new_best

            args = (
                jnp.asarray(tables.ptm_t), jnp.asarray(tables.min_heads),
                jnp.asarray(tables.min_tails), jnp.asarray(pairs),
                jnp.asarray(lags), jnp.asarray(scheds),
            )
        else:
            chunk = (
                pfsp_device._lb1_chunk if lb == "lb1" else pfsp_device._lb1_d_chunk
            )
            in_specs = (node_spec, P(), P(None, None), P(None), P(None))

            @partial(jax_compat.shard_map, mesh=mesh, in_specs=(*in_specs, P()),
                     out_specs=(P("dp", None), P()))
            def step(parents, best, ptm_t, min_heads, min_tails, count):
                bounds = chunk(
                    parents["prmu"], parents["limit1"], ptm_t, min_heads,
                    min_tails, bf16=tables.exact_bf16,
                )
                new_best = _fold_leaf_best(parents, bounds, best, jobs, count)
                return bounds, new_best

            args = (
                jnp.asarray(tables.ptm_t), jnp.asarray(tables.min_heads),
                jnp.asarray(tables.min_tails),
            )

        jitted = jax.jit(step)

        def run(parents, count, best):
            bounds, new_best = jitted(
                parents, jnp.int32(best), *args, jnp.int32(count)
            )
            return bounds, int(new_best)

        return jitted, run

    # -- call --------------------------------------------------------------

    def pad_to_mesh(self, count: int) -> int:
        return _pad_len(count, self.dp)

    def __call__(self, parents, count, best):
        _, run = self._step
        t0 = ev.now_us()
        out = run(parents, count, best)
        ev.complete("chunk", t0, args={"count": int(count)})
        return out


def _fold_leaf_best(parents, bounds, best, jobs, count):
    """Mesh-wide incumbent fold: min over this shard's *valid* leaf-child
    makespans, then pmin across dp (the in-step UB all-reduce; mp shards
    share identical leaf values after pmax so pmin over dp suffices).

    Rows at global index >= count are padding (the engine pads chunks to the
    bucket/mesh size) and are masked out of the fold — their bounds must not
    corrupt the incumbent.
    """
    depth = parents["depth"]
    limit1 = parents["limit1"]
    local_b = bounds.shape[0]
    row = (
        jax.lax.axis_index("dp") * local_b
        + jnp.arange(local_b, dtype=jnp.int32)
    )
    valid_row = row < count  # (local_b,)
    j = jnp.arange(bounds.shape[1], dtype=jnp.int32)[None, :]
    open_slot = j >= (limit1[:, None] + 1)
    is_leaf = (depth[:, None] + 1 == jobs) & open_slot & valid_row[:, None]
    leaf_min = jnp.min(jnp.where(is_leaf, bounds, jnp.int32(INF_BOUND)))
    new_best = jnp.minimum(jnp.int32(best), leaf_min)
    return jax.lax.pmin(new_best, "dp")
