"""Distributed mesh-resident tier: per-host SPMD engines + DCN exchange.

The dist tier (`parallel/dist.py`) reproduces the reference's semantics with
per-device *offload* workers on every host — faithful, but each chunk pays a
host round trip. This tier is the pod-scale TPU-native composition instead:

  * **inside a host**: the mesh-resident engine (`parallel/resident_mesh.py`)
    owns all local chips with one `shard_map` program — HBM-resident pool
    shards, `lax.while_loop` chunk cycles, `pmin` incumbent folds and
    `ppermute` diffusion riding ICI;
  * **between hosts**: a bulk-synchronous exchange at step boundaries over
    the same `Collectives` interface the dist tier uses (threads for
    testing, `jax.distributed` / DCN on a real pod): incumbent all-reduce,
    deterministic donor->receiver matching with point-to-point node blocks
    through the KV channel, and two-round quiescence termination.

This is exactly SURVEY.md §2.5's prescription — "multi-chip = device mesh +
ICI collectives; multi-host pod = one process per host over DCN with
host-mediated work stealing" — with the reference's two-level hierarchy
(`pfsp_dist_multigpu_chpl.chpl:377-379`: locales over tasks) mapped to
hosts over mesh shards. Donations happen only when a receiver is starved
(its mesh cannot run a chunk), so the hot path stays pure ICI; a donation
costs the donor one frontier download + re-upload, amortized across the
many K-cycle blocks between exchanges.

Counting invariance: exchanges move nodes and tighten incumbents but never
create/destroy nodes, so with a fixed incumbent exploredTree/exploredSol
equal the sequential tier exactly (the same invariant every other tier
pins in tests).
"""

from __future__ import annotations

import threading
import time

import numpy as np

from ..engine.device import drain, warmup
from ..engine.resident import _emit_device_explored
from ..engine.results import Diagnostics, PhaseStats, SearchResult
from ..obs import counters as obs_counters
from ..obs import events as ev
from ..obs import flightrec as fr
from ..obs import phases as obs_phases
from ..obs import quality as obs_quality
from ..pool import SoAPool
from ..problems.base import INF_BOUND, Problem, batch_length, index_batch
from .dist import (
    JaxCollectives,
    LocalCollectives,
    ThreadCollectives,
    secondary_error,
)
from .resident_mesh import get_mesh_program, make_dp_mp_mesh


def _stride_shards(batch: dict, D: int) -> list[dict]:
    return [{k: v[w::D] for k, v in batch.items()} for w in range(D)]


def _host_loop(
    problem: Problem,
    m: int,
    M: int,
    K: int,
    rounds: int,
    mesh,
    coll,
    initial_best: int | None,
    seed_tag: int = 0,
    exchange_sleep_s: float = 0.0,
    partition_fn=None,
    max_steps: int | None = None,
    checkpoint_path: str | None = None,
    checkpoint_interval_s: float = 60.0,
    resume_from: str | None = None,
) -> dict:
    import jax

    from ..engine import checkpoint as ckpt_mod

    H = coll.num_hosts
    me = coll.host_id
    D = int(mesh.shape[mesh.axis_names[0]])
    best = (
        initial_best
        if initial_best is not None
        else getattr(problem, "initial_ub", INF_BOUND)
    )
    suffix = f".h{me}" if H > 1 else ""
    eff_ckpt = None if checkpoint_path is None else checkpoint_path + suffix
    eff_resume = None if resume_from is None else resume_from + suffix

    diagnostics = Diagnostics()
    t0 = time.perf_counter()

    # -- phase 1: replicate-and-slice warm-up (dist.py's scheme: identical
    # deterministic warm-up everywhere, zero communication; host 0 owns the
    # counters so the cross-host sum counts them once) — or restore --------
    pool = SoAPool(problem.node_fields())
    if eff_resume is not None:
        loaded = ckpt_mod.load(eff_resume, problem, expect_hosts=H)
        if H > 1:
            # Lockstep-cut coherence across the per-host files (same check
            # as the dist tier's resume, multidevice.py): mixed cuts would
            # lose or double-explore nodes donated between rounds.
            tags = coll.allgather_obj(loaded.cut_tag)
            if len(set(tags)) != 1:
                raise ValueError(
                    "incoherent multi-host resume: per-host checkpoint "
                    f"files come from different cuts ({tags}); restore a "
                    "matching set before resuming"
                )
        pool.push_back_bulk(loaded.batch)
        tree1, sol1 = loaded.tree, loaded.sol
        best = min(best, loaded.best)
    else:
        pool.push_back(index_batch(problem.root(), 0))
        tree1, sol1, best = warmup(problem, pool, best, H * D * m)
        if H > 1:
            warm = pool.as_batch()
            pool = SoAPool(problem.node_fields())
            if partition_fn is None:
                pool.push_back_bulk({k: v[me::H] for k, v in warm.items()})
            else:
                pool.push_back_bulk(partition_fn(warm, me, H))
            if me != 0:
                tree1 = sol1 = 0
    t1 = time.perf_counter()
    ev.counter("explored", host=me, tree=tree1, sol=sol1, phase=1)

    # -- phase 2: per-host SPMD loop + step-boundary exchanges --------------
    from ..engine.pipeline import (
        AdaptiveK,
        DispatchQueue,
        MESH_TARGET,
        resolve_k,
        resolve_pipeline_depth,
        resolve_target_band,
    )
    from ..engine.resident import resolve_capacity

    capacity, M = resolve_capacity(problem, M, None)
    T = max(2 * m, min(M, 8192))
    # Per-host adaptive K (TTS_K=auto): each host resizes its own program
    # along the shared geometric ladder — hosts already run different
    # cycle counts per exchange round, so differing K across hosts changes
    # nothing the exchange protocol depends on. The mesh target band keeps
    # K bounded by exchange responsiveness — it IS this tier's exchange
    # period (exchanges ride dispatch boundaries); with TTS_COSTMODEL it
    # resolves from the measured dispatch-latency fit, and the idle-host
    # exchange back-off from the measured exchange-round latency.
    k_auto, k_value = resolve_k(K, default_max=16)
    band, band_src = resolve_target_band(
        "dist_mesh", MESH_TARGET, problem, topology=f"dist_mesh-H{H}xD{D}"
    )
    if band_src is not None and exchange_sleep_s == 0.0:
        from ..obs import costmodel as cm

        prof = cm.load(cm.costmodel_path() or "")
        hit = cm.lookup(prof or {}, *band_src.split("|")) if prof else None
        measured_sleep = cm.exchange_sleep_s(hit[1]) if hit else None
        if measured_sleep is not None:
            exchange_sleep_s = measured_sleep
    # Steal policy (TTS_STEAL, parallel/topology.py): flat keeps this
    # tier's single-level donor->needy matching byte-identical; hier
    # layers the near/far schedule over the same lockstep rounds. The
    # exchange period here IS the dispatch cadence, so the far-period
    # resolution uses the adaptive-K target band's midpoint as the base
    # interval (or the measured idle back-off when one resolved).
    policy = None
    if H > 1:
        from .topology import Topology, resolve_policy

        dev0 = next(iter(mesh.devices.flat), None)
        slice_idx = getattr(dev0, "slice_index", None)
        topo = Topology.detect(
            H, slice_index=slice_idx,
            allgather=coll.allgather_obj if slice_idx is not None else None,
        )
        from ..ops import backend as BK

        policy = resolve_policy(
            problem, topo, m=m, cap=D * M,
            interval_s=exchange_sleep_s or (band[0] + band[1]) / 2.0,
            backend=BK.profile_backend(),
            topo_str=f"dist_mesh-H{H}xD{D}",
        )
    ctl = AdaptiveK(k_value, target=band) if k_auto else None
    depth = resolve_pipeline_depth()
    program = get_mesh_program(problem, mesh, m, M,
                               ctl.K if ctl else k_value, rounds, T, capacity)

    state = program.init_state(_stride_shards(pool.as_batch(), D), best)
    pool.clear()
    diagnostics.host_to_device += 1

    from ..analysis.guard import SteadyStateGuard, guard_enabled

    genabled = guard_enabled(None)
    guards: dict[int, SteadyStateGuard] = {}

    def guard_of(prog) -> SteadyStateGuard:
        g = guards.get(id(prog))
        if g is None:
            g = guards[id(prog)] = SteadyStateGuard(
                prog._step, "dist-mesh step", enabled=genabled
            )
        return g

    tree2 = 0
    sol2 = 0
    steps = 0
    completed = True  # flipped off on a max_steps cutoff
    quiescent_streak = 0
    blocks_sent = blocks_received = 0
    nodes_sent = nodes_received = 0
    exch_rounds = 0
    per_worker = np.zeros(D, dtype=np.int64)

    ctr_total: dict | None = None
    ph_total: dict | None = None  # per-phase ns totals (TTS_PHASEPROF=1)
    prev_best = best
    # Anytime quality (host-local trajectory, like the obs counters; an
    # exchange-adopted global incumbent lands at the next dispatch read).
    qt = obs_quality.tracker(problem)
    sizes = np.zeros(D, dtype=np.int32)
    n_disp = 0  # completed-dispatch sequence (flight-recorder registry)
    queue = DispatchQueue(depth)
    # Steady-state XLA capture: the jax profiler is process-global, so
    # only one virtual host's window arms (XlaTraceWindow's active guard).
    xwin = obs_phases.XlaTraceWindow("dist_mesh")
    last_ready = time.monotonic()

    def enqueue() -> None:
        nonlocal state
        t_enq = ev.now_us()
        with guard_of(program).step():
            out = program.step(state)
        state = program.carry(out)
        queue.push(out, t_enq)

    def consume(out, t_enq) -> tuple[int, int, int]:
        nonlocal tree2, sol2, sizes, best, ctr_total, ph_total, prev_best
        nonlocal per_worker, n_disp
        t_wait = ev.now_us()
        ti, si, cy, sizes, best, tree_vec, ctr = program.read_scalars(out)
        phb = program.read_phase_block(out)
        tree2 += ti
        sol2 += si
        n_disp += 1
        per_worker += tree_vec.astype(np.int64)
        diagnostics.kernel_launches += cy
        if ctr is not None:
            ctr_total = obs_counters.merge_host(ctr_total, ctr)
        if phb is not None:
            ph_total = obs_phases.merge_host(ph_total, phb)
        xwin.on_dispatch(n_disp)
        fr.heartbeat("dist_mesh", host=me, seq=n_disp, cycles=cy,
                     size=int(sizes.sum()), best=int(best), tree=tree2,
                     sol=sol2, depth=depth, K=program.K,
                     inflight=len(queue), phases=ph_total)
        if qt is not None:
            qt.observe(best, n_disp, tree1 + tree2)
        if ev.enabled():
            now = ev.now_us()
            ev.emit("dispatch", ph="X", ts=t_enq, host=me,
                    dur=max(0.0, now - t_enq), args={
                        "cycles": cy, "tree": ti, "sol": si,
                        "size": int(sizes.sum()), "best": int(best),
                        "shard_sizes": sizes.tolist(),
                        "enqueue_us": t_enq, "read_wait_us": now - t_wait,
                        "pipeline_depth": depth,
                    })
            if ctr is not None:
                ev.counter("device_counters", host=me,
                           **obs_counters.as_args(ctr))
            if phb is not None:
                ev.counter("device_phases", host=me,
                           **obs_phases.as_args(phb))
            if best < prev_best:
                ev.emit("incumbent", host=me, args={"best": int(best)})
        prev_best = best
        return ti, si, cy

    def drain_queue() -> None:
        # Coherence barrier: any action that downloads/snapshots the pool
        # (donations, lockstep cuts) must first fold every in-flight
        # speculative dispatch's counts — the frontier includes their work.
        for out, t_enq in queue.drain():
            consume(out, t_enq)

    def download() -> SoAPool:
        nonlocal best
        drain_queue()
        batch = program.full_batch(state)
        diagnostics.device_to_host += 1
        p = SoAPool(problem.node_fields())
        p.push_back_bulk(batch)
        return p

    def upload(p: SoAPool):
        nonlocal state, last_ready
        state = program.init_state(_stride_shards(p.as_batch(), D), best)
        diagnostics.host_to_device += 1
        # Donation-round re-uploads are sanctioned host round trips: the
        # next dispatch is a fresh warm one for the steady-state guard.
        guard_of(program).rearm()
        last_ready = time.monotonic()

    import pickle
    import uuid as _uuid

    # Checkpointing: lockstep cuts at exchange boundaries. The cut point —
    # right after a round's allgather, before its donations — is provably
    # donation-coherent: the allgather is a barrier, so every prior round's
    # blocks are integrated on both ends and none are in flight. Host 0
    # proposes "<uuid>:<round>" in the control tuple; every host stamps
    # that exact tag (resume verifies coherence collectively).
    run_uuid = _uuid.uuid4().hex[:12]
    ckpt_last = time.monotonic()

    def do_lockstep_cut(tag) -> None:
        drain_queue()  # counters must cover the snapshot's in-flight work
        staging = eff_ckpt + ".staging"
        ok = True
        t_cut = ev.now_us()
        try:
            batch = program.full_batch(state)
            diagnostics.device_to_host += 1
            ckpt_mod.save(staging, problem, batch, best,
                          tree1 + tree2, sol1 + sol2, hosts=H, cut_tag=tag)
        except Exception:  # noqa: BLE001 — a failed host must veto commit
            ok = False
        ckpt_mod.lockstep_commit(
            ok, staging, eff_ckpt,
            vote=coll.allgather_obj if H > 1 else None,
        )
        ev.complete("checkpoint", t_cut, wid=ev.COMM_TID, host=me,
                    args={"tag": str(tag), "ok": ok})

    fr.arm("dist_mesh")
    ev.emit("pipeline", host=me, args={
        "depth": depth, "K": program.K, "k_auto": k_auto, "tier": "dist_mesh",
    })
    if band_src is not None:
        ev.emit("costmodel", host=me, args={
            "source": band_src, "lo_ms": round(1e3 * band[0], 1),
            "hi_ms": round(1e3 * band[1], 1), "tier": "dist_mesh",
        })

    while True:
        while not queue.full:
            enqueue()
        out, t_enq = queue.pop()
        ti, si, cy = consume(out, t_enq)
        now = time.monotonic()
        period, last_ready = now - last_ready, now
        steps += 1
        total = int(sizes.sum())
        if ctl is not None and cy > 0 and ctl.observe(period, cy):
            drain_queue()
            program = get_mesh_program(problem, mesh, m, M, ctl.K, rounds,
                                       T, capacity)
            ev.emit("k_resize", host=me, args={"K": program.K})
            last_ready = time.monotonic()
            total = int(sizes.sum())
        # Idle = this host's mesh cannot run another chunk cycle anywhere.
        idle = int(sizes.max()) < m
        if max_steps is not None and steps >= max_steps:
            completed = False  # budget cutoff, not quiescence
            if eff_ckpt is not None:
                # Final lockstep cut so the budgeted run is resumable; all
                # hosts reach this point in the same iteration, and host
                # 0's tag rides a dedicated allgather.
                tag = f"{run_uuid}:cutoff{steps}"
                if H > 1:
                    tag = coll.allgather_obj(tag)[0]
                do_lockstep_cut(tag)
            break
        if H == 1:
            if (eff_ckpt is not None
                    and time.monotonic() - ckpt_last
                    >= checkpoint_interval_s):
                do_lockstep_cut(f"{run_uuid}:{steps}")
                ckpt_last = time.monotonic()
            if idle:
                break
            continue
        # Bulk-synchronous exchange (the dist tier's control-round shape).
        exch_rounds += 1
        want_ckpt = (
            eff_ckpt is not None and me == 0
            and time.monotonic() - ckpt_last >= checkpoint_interval_s
        )
        cut_id = f"{run_uuid}:{exch_rounds}" if want_ckpt else None
        # The exchange is a SPAN (not an instant): its duration is the
        # measured DCN/KV control-round latency — the "exchange" link
        # class of the cost model (obs/costmodel.py).
        t_x = ev.now_us()
        rows = coll.allgather_obj(
            (total, bool(idle), int(best), want_ckpt, cut_id)
        )
        gbest = min(r[2] for r in rows)
        ev.complete("exchange", t_x, wid=ev.COMM_TID, host=me, args={
            "round": exch_rounds, "size": total, "best": int(gbest),
            "idle": bool(idle),
        })
        if gbest < best:
            # Inject the global incumbent into the sharded state: the best
            # vector is a tiny (D,) array — replace it in place with the
            # same sharding, no pool touch.
            pv, pa, sz, bst = state
            bst = jax.device_put(
                np.minimum(np.asarray(bst), gbest).astype(np.int32),
                program._sh_vec,
            )
            state = (pv, pa, sz, bst)
            best = gbest
        if eff_ckpt is not None and rows[0][3]:
            # Cut point: after incumbent adoption (the snapshot carries the
            # tightened best), before this round's donations.
            do_lockstep_cut(rows[0][4])
            ckpt_last = time.monotonic()
        totals = [r[0] for r in rows]
        idles = [r[1] for r in rows]
        donors = sorted(
            (h for h in range(H) if totals[h] >= 4 * D * m),
            key=lambda h: (-totals[h], h),
        )
        needy = sorted(
            (h for h in range(H) if idles[h]),
            key=lambda h: (totals[h], h),
        )
        if policy is not None and policy.hier:
            # Two-level matching (topology.py): near pairs every round,
            # far pairs on far rounds for near-unmatched needy only. Same
            # allgathered inputs + same round counter on every host ->
            # identical pairs, exactly like the flat zip.
            pairs = [(d, r)
                     for d, r in policy.match(donors, needy, exch_rounds,
                                              sizes=totals)
                     if d != r]
        else:
            pairs = [(d, r) for d, r in zip(donors, needy) if d != r]
        if all(idles) and not pairs:
            quiescent_streak += 1
            if quiescent_streak >= 2:
                ev.emit("terminate", wid=ev.COMM_TID, host=me,
                        args={"round": exch_rounds})
                break
            continue
        quiescent_streak = 0
        send_to = next((r for d, r in pairs if d == me), None)
        recv_from = next((d for d, r in pairs if r == me), None)
        if send_to is not None:
            # Donor: download the frontier, split off the FRONT (oldest,
            # shallowest — `Pool_par.chpl:180-191`) capped at D*M nodes,
            # re-upload the rest. One transfer each way, only on donation
            # rounds.
            link = policy.link(me, send_to)
            p = download()
            # Steal-half-from-front policy, capped (the dist tier's bounded
            # donation: a huge frontier never ships unbounded over DCN).
            # Flat cap is the legacy D*M; hier caps per link class so far
            # links ship their resolved bulk quantum.
            block = p.pop_front_bulk_half(m, 0.5, cap=policy.cap_for(link))
            blob = pickle.dumps(block)
            # Donation SPAN over the KV put alone (bytes + duration — the
            # "donate" bandwidth sample of the cost model); the frontier
            # download/re-upload around it is charged to the donor's own
            # dispatch gap, not the link. The simulated link latency
            # (TTS_SIM_LAT_*) sleeps INSIDE the span so injected latency
            # lands in the measured donate:{link} fit.
            t_d = ev.now_us()
            policy.sim.sleep(link)
            coll.kv_set(
                f"tts/dmesh/{exch_rounds}/{me}->{send_to}", blob
            )
            if block is not None:
                blocks_sent += 1
                nodes_sent += batch_length(block)
                ev.complete("donate_send", t_d, wid=ev.COMM_TID, host=me,
                            args={"peer": send_to,
                                  "nodes": batch_length(block),
                                  "bytes": len(blob),
                                  "round": exch_rounds,
                                  "link": link,
                                  "level": policy.level_of(link)})
            upload(p)
        if recv_from is not None:
            link = policy.link(recv_from, me)
            t_d = ev.now_us()
            raw = coll.kv_get(
                f"tts/dmesh/{exch_rounds}/{recv_from}->{me}",
                timeout_s=120.0,
            )
            block = pickle.loads(raw)
            if block is not None:
                # Span covers the KV wait (donor prep + transfer): the
                # measured cost of receiving a donation block.
                ev.complete("donate_recv", t_d, wid=ev.COMM_TID, host=me,
                            args={"peer": recv_from,
                                  "nodes": batch_length(block),
                                  "bytes": len(raw),
                                  "round": exch_rounds,
                                  "link": link,
                                  "level": policy.level_of(link)})
                p = download()
                p.push_back_bulk(block)
                upload(p)
                blocks_received += 1
                nodes_received += batch_length(block)
                fr.note_steal(me, link, policy.level_of(link))
        if idle and recv_from is None and exchange_sleep_s:
            time.sleep(exchange_sleep_s)

    # -- phase 3: local residual drain --------------------------------------
    drain_queue()  # remaining speculative dispatches are no-ops by now
    xwin.close()
    batch = program.residual_batch(state)
    diagnostics.device_to_host += 1
    pool.reset_from(batch)
    t2 = time.perf_counter()
    _emit_device_explored(ctr_total, tree2, sol2, 0, 0, host=me)
    tree3, sol3, best = drain(problem, pool, best)
    t3 = time.perf_counter()
    ev.counter("explored", host=me, tree=tree3, sol=sol3, phase=3)
    if qt is not None:
        # The host drain can improve the incumbent one last time.
        qt.observe(best, n_disp, tree1 + tree2 + tree3)

    return {
        "tree": tree1 + tree2 + tree3,
        "sol": sol1 + sol2 + sol3,
        "best": best,
        "steals": blocks_received,
        "elapsed": t3 - t0,
        "phases": [
            PhaseStats(t1 - t0, tree1, sol1),
            PhaseStats(t2 - t1, tree2, sol2),
            PhaseStats(t3 - t2, tree3, sol3),
        ],
        "diag": diagnostics,
        "per_worker_tree": per_worker.tolist(),
        "comm": {
            "rounds": exch_rounds,
            "blocks_sent": blocks_sent,
            "blocks_received": blocks_received,
            "nodes_sent": nodes_sent,
            "nodes_received": nodes_received,
        },
        # Resolved steal policy (identical on every host — env + profile
        # resolution only); None below the exchange threshold (H == 1).
        "steal_policy": policy.describe() if policy is not None else None,
        "complete": completed,
        # Survivor-path mode the per-host SPMD step baked in (identical on
        # every host: same knob, same problem shape, same device platform).
        "compact": program.inner.compact,
        "compact_auto": program.inner.compact_auto,
        # Pipeline/K the host loop ran with (host-local: adaptive K may
        # land hosts on different ladder rungs).
        "pipeline_depth": depth,
        "k_resolved": program.K,
        "k_auto": k_auto,
        # Host-local counter totals (not reduced — per-host telemetry).
        "obs": (
            {
                **({"device_counters": ctr_total}
                   if ctr_total is not None else {}),
                **({"device_phases": ph_total}
                   if ph_total is not None else {}),
            }
            if (ctr_total is not None or ph_total is not None) else None
        ),
        # Host-local per-phase ns totals (TTS_PHASEPROF=1, obs/phases.py).
        "phase_profile": ph_total,
        # Host-local incumbent trajectory (obs/quality.py; not reduced).
        "quality": qt.result() if qt is not None else None,
    }


def _reduce(local: dict, coll) -> SearchResult:
    comm = {k: coll.allreduce_sum(v) for k, v in local["comm"].items()}
    return SearchResult(
        explored_tree=coll.allreduce_sum(local["tree"]),
        explored_sol=coll.allreduce_sum(local["sol"]),
        best=coll.allreduce_min(local["best"]),
        elapsed=coll.allreduce_max(local["elapsed"]),
        phases=local["phases"],
        diagnostics=local["diag"],
        per_worker_tree=local["per_worker_tree"],
        steals=coll.allreduce_sum(local["steals"]),
        comm=comm,
        steal_policy=local.get("steal_policy"),
        complete=bool(coll.allreduce_min(int(local["complete"]))),
        compact=local.get("compact"),
        compact_auto=local.get("compact_auto", False),
        pipeline_depth=local.get("pipeline_depth", 1),
        k_resolved=local.get("k_resolved"),
        k_auto=local.get("k_auto", False),
        obs=local.get("obs"),
        phase_profile=local.get("phase_profile"),
        quality=local.get("quality"),
    )




def dist_mesh_search(
    problem: Problem,
    m: int = 25,
    M: int = 16384,
    K: int | str = 16,
    rounds: int = 2,
    D: int | None = None,
    mp: int = 1,
    num_hosts: int | None = None,
    devices=None,
    initial_best: int | None = None,
    partition_fn=None,
    max_steps: int | None = None,
    checkpoint_path: str | None = None,
    checkpoint_interval_s: float = 60.0,
    resume_from: str | None = None,
) -> SearchResult:
    """Pod-scale search: per-host mesh-resident SPMD engines, DCN exchange.

    * Under ``jax.distributed`` (process_count > 1): this process builds a
      dp (or dp x mp) mesh over its local devices and exchanges with peers
      over the coordination service.
    * Single process with ``num_hosts=H > 1``: H virtual hosts in threads
      over disjoint local-device groups (testing mode).
    * ``num_hosts`` unset/1: degenerates to ``mesh_resident_search``
      semantics (no exchange).
    * ``mp > 1`` (PFSP lb2 only): each host's mesh gains the machine-pair
      model-parallel axis; the staged evaluator composes per shard
      (`pfsp_device.lb2_self_bounds_mp`).
    """
    import jax

    if jax.process_count() > 1:
        coll = JaxCollectives()
        local_devices = jax.local_devices() if devices is None else devices
        if D is None:
            D = max(1, len(local_devices) // mp)
        local = _host_loop(
            problem, m, M, K, rounds, make_dp_mp_mesh(local_devices, D, mp),
            coll, initial_best,
            partition_fn=partition_fn, max_steps=max_steps,
            checkpoint_path=checkpoint_path,
            checkpoint_interval_s=checkpoint_interval_s,
            resume_from=resume_from,
        )
        return _reduce(local, coll)

    all_devices = jax.devices() if devices is None else devices
    H = num_hosts or 1
    if H == 1:
        if D is None:
            D = max(1, len(all_devices) // mp)
        local = _host_loop(
            problem, m, M, K, rounds, make_dp_mp_mesh(all_devices, D, mp),
            LocalCollectives(), initial_best, max_steps=max_steps,
            checkpoint_path=checkpoint_path,
            checkpoint_interval_s=checkpoint_interval_s,
            resume_from=resume_from,
        )
        return _reduce(local, LocalCollectives())

    if H > len(all_devices):
        raise ValueError(
            f"num_hosts={H} exceeds available devices ({len(all_devices)})"
        )
    groups = [all_devices[h::H] for h in range(H)]
    if D is None:
        D = max(1, min(len(g) for g in groups) // mp)
    coll = ThreadCollectives(H)
    results: list = [None] * H
    errors: list = [None] * H

    def host_main(h: int):
        try:
            local = _host_loop(
                problem, m, M, K, rounds, make_dp_mp_mesh(groups[h], D, mp),
                coll.bind(h), initial_best,
                partition_fn=partition_fn, max_steps=max_steps,
                checkpoint_path=checkpoint_path,
                checkpoint_interval_s=checkpoint_interval_s,
                resume_from=resume_from,
            )
            results[h] = _reduce(local, coll)
        except BaseException as e:
            errors[h] = e
            try:
                coll._barrier.abort()
            except Exception:
                pass

    threads = [
        threading.Thread(target=host_main, args=(h,), name=f"tts-dmesh-{h}")
        for h in range(H)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    real = [e for e in errors if e is not None and not secondary_error(e)]
    for e in real or errors:
        if e is not None:
            raise e
    global_res = results[0]
    global_res.per_worker_tree = [
        t for r in results for t in r.per_worker_tree
    ]
    return global_res
