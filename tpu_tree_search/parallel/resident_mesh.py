"""Mesh-resident search: the device-resident engine sharded over a TPU mesh.

The reference's multi-GPU tier is host-orchestrated: one CPU task per GPU,
lock-based pools in host memory, work stealing by locked bulk copies
(`pfsp_multigpu_chpl.chpl:375-496`). The TPU-native formulation inverts it:
**one SPMD program owns the whole search**. Every device holds a private pool
shard in HBM and runs the resident chunk loop (engine/resident.py) locally;
the cross-device coordination is pure XLA collectives riding ICI:

  * **incumbent all-reduce** — after every K-cycle block the per-shard
    incumbents fold with ``lax.pmin``. This is the mid-search UB broadcast
    the reference lacks entirely (it reconciles incumbents only in the
    terminal reduction, SURVEY.md §2.4.4; BASELINE.json names this the
    planned improvement).
  * **diffusion load balancing** — instead of lock-based stealing (which
    needs shared memory TPUs don't have), each balance round every shard may
    donate up to T of its *front* (oldest, shallowest — the same
    steal-half-from-front policy as `Pool_par.chpl:180-191`) nodes to its
    ring neighbor via ``lax.ppermute``. The donation amounts are computed by
    every shard from an ``all_gather`` of pool sizes, so sender and receiver
    agree without any handshake; a round moves work only toward shards that
    are starving (< m nodes) from shards that can spare it (>= 2m — the
    reference's steal threshold, `Pool_par.chpl:154-158`).
  * **termination** — the host loop stops when the all-gathered sizes show
    every shard below the chunk threshold m; the residual (< D*m nodes)
    drains on host, exactly like the single-device tier's phase 3. This
    replaces the idle-flag allIdle scan (`util.chpl:16-30`): in a bulk-
    synchronous SPMD program the size vector *is* the idle state.

Counting invariance: balancing moves pool nodes between shards but never
creates/destroys them, and pruning is against the pmin-folded incumbent, so
with a fixed incumbent (N-Queens; PFSP ub=1) exploredTree/exploredSol equal
the sequential tier exactly — the same cross-tier determinism the reference
relies on for validation (SURVEY.md §4.2).
"""

from __future__ import annotations

import time

import numpy as np

from ..engine.device import drain, warmup
from ..engine.resident import _emit_device_explored, _make_program
from ..engine.results import Diagnostics, PhaseStats, SearchResult
from ..obs import counters as obs_counters
from ..obs import events as ev
from ..obs import flightrec as fr
from ..obs import phases as obs_phases
from ..obs import quality as obs_quality
from ..ops import pallas_kernels as PK
from ..pool import SoAPool
from ..problems.base import INF_BOUND, Problem, index_batch
from ..utils import jax_compat


def make_dp_mp_mesh(devices, D: int, mp: int):
    """The one dp / (dp, mp) mesh-construction policy (device order,
    reshape, feasibility check) — shared by the mesh-resident and
    dist_mesh tiers so their layouts can never drift."""
    from jax.sharding import Mesh

    if mp > 1:
        need = D * mp
        if len(devices) < need:
            raise ValueError(
                f"dp={D} x mp={mp} needs {need} devices, have "
                f"{len(devices)}"
            )
        return Mesh(np.asarray(devices[:need]).reshape(D, mp), ("dp", "mp"))
    return Mesh(np.asarray(devices[:D]), ("dp",))


class _MeshResidentProgram:
    """Compiled SPMD step for (problem, mesh, m, M, K, rounds, T, C)."""

    def __init__(
        self,
        problem: Problem,
        mesh,
        m: int,
        M: int,
        K: int,
        rounds: int,
        T: int,
        capacity: int,
    ):
        import jax

        axes = list(mesh.axis_names)
        if len(axes) == 2:
            if axes[1] != "mp":
                raise ValueError(
                    "mesh-resident tier: two-axis meshes must be (dp, mp)"
                )
            if getattr(problem, "lb", None) != "lb2":
                raise ValueError(
                    "mp-axis sharding splits the lb2 Johnson pair loop; "
                    "use a single-axis mesh for other problems/bounds"
                )
        elif len(axes) != 1:
            raise ValueError("mesh-resident tier needs a (dp[, mp]) mesh")
        self.problem = problem
        self.mesh = mesh
        self.D = int(mesh.shape[axes[0]])
        self.mp = int(mesh.shape["mp"]) if len(axes) == 2 else 1
        self.m = m
        self.M = M
        n = problem.child_slots
        self.K = max(1, min(K, (2**31 - 1) // max(1, M * n * max(1, rounds))))
        self.rounds = rounds
        self.T = T
        self.capacity = capacity
        # Single-device program supplies the pool schema, hooks, and the
        # K-cycle loop body; its own jitted step is unused here. Built for
        # the mesh's device platform so the kernel routing (Pallas on TPU,
        # XLA elsewhere) matches where the shards actually run.
        # mp > 1: every (dp, i) shard redundantly owns the same dp pool
        # block and splits the Johnson pair loop over mp; the pmax inside
        # the evaluator keeps all mp replicas' prune decisions identical,
        # so they stay in lockstep without any extra collective.
        self.inner = _make_program(
            problem, m, M, K, capacity, mesh.devices.flat[0],
            mp_axis="mp" if self.mp > 1 else None, mp_size=self.mp,
            # Staged lb2 runs per-shard in BOTH mesh modes (the compaction
            # is pure local ops, no collectives; Pallas-inside-shard_map is
            # already how the lb1/lb2 kernels execute in this tier). Under
            # mp > 1 the compacted self bound shards its pair loop over mp
            # and pmax-combines, so every replica prunes identically
            # (`pfsp_device.lb2_self_bounds_mp`).
            allow_staged=True,
        )
        self._build()

    # -- construction ------------------------------------------------------

    def _build(self):
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = self.mesh
        axis = mesh.axis_names[0]
        D, m, M, T, C = self.D, self.m, self.M, self.T, self.capacity
        K = self.K
        n = self.problem.child_slots
        Mn = M * n
        vals_dt = self.inner.pool_fields[0][1]
        aux_dt = self.inner.pool_fields[1][1]
        cond, body = self.inner.loop_fns(K)
        rounds = self.rounds
        obs = self.inner.obs
        phaseprof = self.inner.phaseprof
        perm = [(i, (i + 1) % D) for i in range(D)]  # ring, static

        def shard_step(pool_vals, pool_aux, size, best):
            # per-shard views: (C, n), (C,), (1,), (1,)
            sz = size[0]
            bst = best[0]
            # Zeros derived from a varying value: under shard_map the while
            # carry's varying-manual-axes types must match (scan-vma rule).
            tree = sz * 0
            sol = sz * 0
            cycles = sz * 0
            if obs:
                # Counter block accumulates across the dispatch's rounds
                # (carried back in each round); varying like the scalars.
                ctr = obs_counters.init_block() + (sz * 0)
            if phaseprof:
                # Phase-clock block (obs/phases.py): seeded once per
                # dispatch, accumulated across the rounds; varying like
                # the scalars (the callback clock runs per shard).
                ph = obs_phases.seed_block(
                    sz.astype(jnp.uint32)
                ) + (sz * 0).astype(jnp.uint32)
            for _ in range(rounds):
                init = (pool_vals, pool_aux, sz, bst, sz * 0, sz * 0, sz * 0)
                if obs:
                    init = init + (ctr,)
                if phaseprof:
                    init = init + (ph,)
                carry = lax.while_loop(cond, body, init)
                pool_vals, pool_aux, sz, bst, ti, si, cy = carry[:7]
                if obs:
                    ctr = carry[7]
                if phaseprof:
                    ph = carry[-1]
                tree += ti
                sol += si
                cycles += cy
                if phaseprof:
                    # Loop exit -> balance section: the gap (cond fails,
                    # carry unwinds) is `loop` time; the pmin fold + the
                    # diffusion round below are charged to `balance`.
                    ph, (pool_vals, pool_aux, sz, bst) = obs_phases.boundary(
                        ph, "loop", pool_vals, pool_aux, sz, bst,
                        tag="mesh_loop",
                    )
                # Incumbent all-reduce over ICI (north-star improvement).
                # pcast re-marks the reduced (axis-invariant) value as
                # varying so the next round's while-loop carry types match
                # (identity on pre-vma jax — jax_compat).
                bst = jax_compat.pcast_varying(lax.pmin(bst, axis), axis)
                if D > 1:
                    # -- diffusion balance round -------------------------------
                    sizes = lax.all_gather(sz, axis)  # (D,)
                    me = lax.axis_index(axis)
                    right = (me + 1) % D
                    # Donations computed identically on every shard from the
                    # gathered size vector: shard i gives to i+1 iff the
                    # receiver starves (< m) and the donor can spare (>= 2m,
                    # the reference's steal threshold), capped by the block
                    # size and the receiver's free space.
                    recv_sz = jnp.take(sizes, right)
                    recv_room = recv_sz + T + Mn <= C
                    my_give = jnp.where(
                        (recv_sz < m) & (sz >= 2 * m) & recv_room,
                        jnp.minimum(sz // 2, T),
                        0,
                    )
                    # The amount arriving from the left neighbor, recomputed
                    # from the same gathered vector (no handshake needed).
                    left = (me - 1) % D
                    left_sz = jnp.take(sizes, left)
                    my_room = sz + T + Mn <= C
                    incoming = jnp.where(
                        (sz < m) & (left_sz >= 2 * m) & my_room,
                        jnp.minimum(left_sz // 2, T),
                        0,
                    )
                    # Donate the pool *front* (oldest, shallowest subtrees —
                    # `Pool_par.chpl:180-191`): the first T rows are a static
                    # slice; rows beyond my_give are garbage the receiver
                    # never marks live.
                    blk_vals = lax.ppermute(pool_vals[:T], axis, perm)
                    blk_aux = lax.ppermute(pool_aux[:T], axis, perm)
                    # Remove donated front rows by rolling them to the dead
                    # tail region — gated: the dynamic-shift roll copies the
                    # whole pool, so skip it in the common no-donation case.
                    def _shed(pv, pa):
                        return (
                            jnp.roll(pv, -my_give, axis=0),
                            jnp.roll(pa, -my_give, axis=0),
                        )

                    pool_vals, pool_aux = lax.cond(
                        my_give > 0, _shed, lambda pv, pa: (pv, pa),
                        pool_vals, pool_aux,
                    )
                    sz = sz - my_give
                    # Append the incoming block only when this shard has T
                    # rows of dead space (my_room; incoming is gated on the
                    # same predicate) — an unconditional write could clobber
                    # live rows of a nearly-full pool.
                    def _append(pv, pa):
                        pv = lax.dynamic_update_slice(pv, blk_vals, (sz, 0))
                        pa = lax.dynamic_update_slice(pa, blk_aux, (sz,))
                        return pv, pa

                    pool_vals, pool_aux = lax.cond(
                        my_room, _append, lambda pv, pa: (pv, pa),
                        pool_vals, pool_aux,
                    )
                    sz = sz + incoming
                if phaseprof:
                    # Close the balance segment (incumbent fold + ppermute
                    # diffusion — the mesh tiers' steal/exchange phase).
                    ph, (pool_vals, pool_aux, sz, bst) = obs_phases.boundary(
                        ph, "balance", pool_vals, pool_aux, sz, bst,
                    )
            out = (
                pool_vals,
                pool_aux,
                sz[None],
                bst[None],
                tree[None],
                sol[None],
                cycles[None],
            )
            if obs:
                out = out + (ctr[None],)
            if phaseprof:
                out = out + (ph[None],)
            return out

        specs_pool = P(axis, None)
        specs_vec = P(axis)
        out_specs = (
            specs_pool, specs_vec, specs_vec, specs_vec,
            specs_vec, specs_vec, specs_vec,
        )
        if obs:
            out_specs = out_specs + (P(axis, None),)
        if phaseprof:
            out_specs = out_specs + (P(axis, None),)
        mapped = jax_compat.shard_map(
            shard_step,
            mesh=mesh,
            in_specs=(specs_pool, specs_vec, specs_vec, specs_vec),
            out_specs=out_specs,
            # pallas_call inside shard_map does not yet satisfy jax's vma
            # checker (out_shapes carry no vma; the kernel body mixes
            # varying batch blocks with replicated table blocks) — with the
            # default check_vma=True the TPU path dies at trace time the
            # moment the evaluator routes to a Pallas kernel (round-5
            # hardware session, test_mesh_staged_lb2_runs_on_tpu). jax's
            # own error message prescribes this flag. Disabled ONLY when
            # the evaluator actually routes to Pallas, so the checker keeps
            # guarding the ppermute/diffusion logic on the jnp path; the
            # Pallas composition is pinned by the interpret-mode regression
            # (test_mesh_pallas_inside_shard_map) + the CPU parity suite.
            # TRACKING (ADVICE r5): the disable covers the WHOLE body, so on
            # TPU the checker also stops guarding the ppermute/diffusion
            # logic — re-scope it to the pallas_call alone once jax lets
            # pallas_call declare vma on out_shapes (jax#21577 direction);
            # until then a collective-logic regression there is only caught
            # by the jnp-path CPU tests.
            check_vma=not PK.use_pallas(mesh.devices.flat[0]),
        )
        self._step = jax.jit(mapped, donate_argnums=(0, 1))

        sh_vec = NamedSharding(mesh, specs_vec)

        def init(fr_vals, fr_aux, counts, best0):
            # fr_*: (D, F, ...) stride-partitioned warm frontier, small.
            def shard_init(fr_v, fr_a, cnt, b0):
                pv = jnp.zeros((C, n), vals_dt)
                pa = jnp.zeros((C,), aux_dt)
                pv = lax.dynamic_update_slice(pv, fr_v[0].astype(vals_dt), (0, 0))
                pa = lax.dynamic_update_slice(pa, fr_a[0].astype(aux_dt), (0,))
                return pv, pa, cnt, b0

            return jax_compat.shard_map(
                shard_init,
                mesh=mesh,
                in_specs=(P(axis, None, None), P(axis, None), specs_vec, specs_vec),
                out_specs=(specs_pool, specs_vec, specs_vec, specs_vec),
            )(fr_vals, fr_aux, counts, best0)

        self._init = jax.jit(init)
        self._sh_vec = sh_vec

        def residual(pool_vals, pool_aux):
            # After termination every shard holds < m live rows; ship the
            # first 2m rows of each shard to host (static, tiny).
            R = min(2 * m, C)

            def shard_res(pv, pa):
                return pv[None, :R], pa[None, :R]

            return jax_compat.shard_map(
                shard_res,
                mesh=mesh,
                in_specs=(specs_pool, specs_vec),
                out_specs=(P(axis, None, None), P(axis, None)),
            )(pool_vals, pool_aux)

        self._residual = jax.jit(residual)

    # -- host API ----------------------------------------------------------

    def init_state(self, shard_batches: list[dict], best: int):
        import jax

        D = self.D
        name_v, _, shape_v = self.inner.pool_fields[0]
        name_a = self.inner.pool_fields[1][0]
        counts = np.array(
            [b[name_a].shape[0] for b in shard_batches], dtype=np.int32
        )
        F = max(1, int(counts.max()))
        if F > self.capacity:
            raise ValueError(
                f"warm frontier ({F} nodes/shard) exceeds pool capacity "
                f"{self.capacity}"
            )
        # Bucket the staging width to a power of two (capped at capacity):
        # ``_init`` is jitted per (D, F) shape, and callers that re-upload
        # repeatedly (the dist_mesh donation rounds) would otherwise pay a
        # fresh XLA compile for every distinct frontier size.
        F = min(1 << (F - 1).bit_length(), self.capacity)
        # Stage at the host storage dtypes (TTS_NARROW, problems/base.py):
        # `_init` widens to the device pool dtypes on-chip, so the H2D
        # upload ships narrow bytes.
        fields = self.inner.problem.node_fields()
        fr_v = np.zeros((D, F) + shape_v, dtype=fields[name_v][1])
        fr_a = np.zeros((D, F), dtype=fields[name_a][1])
        for w, b in enumerate(shard_batches):
            k = counts[w]
            if k:
                fr_v[w, :k] = b[name_v]
                fr_a[w, :k] = b[name_a]
        best0 = np.full((D,), best, dtype=np.int32)
        return self._init(fr_v, fr_a, jax.device_put(counts, self._sh_vec), best0)

    def step(self, state):
        return self._step(*state)

    def carry(self, out):
        """The dispatch's carried state ``(pool_vals, pool_aux, size,
        best)`` — the next dispatch's input. Nothing is forced, so a
        speculative dispatch can chain on it while still in flight."""
        return tuple(out[:4])

    def read_scalars(self, out):
        """Blocks on the small per-shard outputs only — returns
        ``(tree, sol, cycles, sizes, best, tree_vec, ctr)``. The donated
        pool leaves (``out[0:2]``) are never touched: under pipelined
        dispatch they were already donated into the next speculative
        dispatch. ``sizes``/``best`` are (D,) vectors carried outside the
        donation set."""
        tree, sol, cycles = out[4], out[5], out[6]
        ctr = np.asarray(out[7]) if self.inner.obs else None
        sizes = np.asarray(out[2])
        best = int(np.asarray(out[3]).min())
        return (
            int(np.asarray(tree).sum()),
            int(np.asarray(sol).sum()),
            int(np.asarray(cycles).sum()),
            sizes,
            best,
            np.asarray(tree),
            ctr,
        )

    def read_phase_block(self, out):
        """The dispatch's harvested (D, NSLOTS+1) phase-clock block (np
        array) when the profiler variant is armed, else None — the final,
        non-donated output leaf (same readback contract as the scalars)."""
        return np.asarray(out[-1]) if self.inner.phaseprof else None

    def read_stats(self, out):
        """(state, tree, sol, cycles, sizes, best, tree_vec, ctr) — the
        synchronous read (carry + scalars); ``ctr`` is the harvested
        (D, NSLOTS) counter block when device counters are on, else None
        (same dispatch-boundary readback as the scalars)."""
        return (self.carry(out),) + self.read_scalars(out)

    def residual_batch(self, state) -> dict:
        pool_vals, pool_aux, size, _ = state
        rv, ra = self._residual(pool_vals, pool_aux)
        return self._collect(np.asarray(rv), np.asarray(ra), np.asarray(size))

    def full_batch(self, state) -> dict:
        """Every live node of every shard (saturation-fallback download)."""
        pool_vals, pool_aux, size, _ = state
        sizes = np.asarray(size)
        rv = np.asarray(pool_vals).reshape(self.D, self.capacity, -1)
        ra = np.asarray(pool_aux).reshape(self.D, self.capacity)
        return self._collect(rv, ra, sizes)

    def _collect(self, rv, ra, sizes) -> dict:
        name_v = self.inner.pool_fields[0][0]
        name_a = self.inner.pool_fields[1][0]
        fields = self.problem.node_fields()
        parts_v = [rv[w, : sizes[w]] for w in range(self.D)]
        parts_a = [ra[w, : sizes[w]] for w in range(self.D)]
        batch = {
            name_v: np.concatenate(parts_v).astype(fields[name_v][1]),
            name_a: np.concatenate(parts_a).astype(fields[name_a][1]),
        }
        return self.inner.derive_fields(batch)


def get_mesh_program(problem, mesh, m: int, M: int, K: int, rounds: int,
                     T: int, capacity: int) -> _MeshResidentProgram:
    """The one per-problem cache of compiled SPMD mesh programs (a rebuild
    costs ~30s on TPU), shared by the mesh and dist_mesh tiers. Keys carry
    the env-dependent kernel-routing decisions (`routing_cache_token`) and
    the obs state, so a knob flip rebuilds instead of silently reusing a
    stale step — and the adaptive-K ladder (TTS_K=auto) resolves each rung
    through this cache, so re-selecting a rung is a hit, not a recompile."""
    cache = getattr(problem, "_mesh_programs", None)
    if cache is None:
        cache = problem._mesh_programs = {}
    from ..ops.pfsp_device import routing_cache_token

    key = (
        tuple(id(d) for d in mesh.devices.flat), mesh.devices.shape,
        m, M, K, rounds, T, capacity,
        routing_cache_token(problem, mesh.devices.flat[0]),
        obs_counters.device_counters_enabled(),
        obs_phases.phase_profiling_enabled(),
    )
    program = cache.get(key)
    if program is None:
        program = cache[key] = _MeshResidentProgram(
            problem, mesh, m, M, K, rounds, T, capacity
        )
    return program


def mesh_resident_search(
    problem: Problem,
    m: int = 25,
    M: int = 16384,
    K: int | str = 16,
    rounds: int = 2,
    T: int | None = None,
    capacity: int | None = None,
    mesh=None,
    devices=None,
    D: int | None = None,
    mp: int = 1,
    initial_best: int | None = None,
    warmup_target: int | None = None,
    max_steps: int | None = None,
    checkpoint_path: str | None = None,
    checkpoint_interval_s: float = 60.0,
    resume_from: str | None = None,
    guard: bool | None = None,
    yield_fn=None,
) -> SearchResult:
    """SPMD multi-device search: 3 phases like every tier, with phase 2 one
    sharded resident program (see module docstring). Checkpoint/resume as in
    ``resident_search`` (a mesh snapshot merges every shard's frontier, and a
    resumed frontier re-partitions stride-D, so D may change across runs);
    ``yield_fn`` is the same cooperative-preemption seam (a True return
    cuts the run at the next dispatch boundary like a ``max_steps``
    cutoff — the serve daemon's scheduler rides it).
    ``guard``/TTS_GUARD=1 asserts zero recompiles + zero implicit transfers
    per steady-state dispatch, exactly as in ``resident_search``. Dispatch
    is pipelined (TTS_PIPELINE) and ``K="auto"``/TTS_K=auto enables the
    adaptive ladder with the tighter mesh target band — see
    ``resident_search`` and engine/pipeline.py."""
    import jax
    from jax.sharding import Mesh

    if mesh is not None and mp != 1:
        raise ValueError(
            "pass either mesh= (with its own (dp, mp) axes) or mp=, not "
            "both — mp would be silently ignored"
        )
    if mesh is None:
        if devices is None:
            devices = jax.devices()
        if D is None:
            D = max(1, len(devices) // mp)
        mesh = make_dp_mp_mesh(devices, D, mp)
    D = int(mesh.shape[mesh.axis_names[0]])
    n = problem.child_slots
    from ..engine.resident import resolve_capacity

    capacity, M = resolve_capacity(problem, M, capacity)
    if T is None:
        T = max(2 * m, min(M, 8192))

    best = (
        initial_best
        if initial_best is not None
        else getattr(problem, "initial_ub", INF_BOUND)
    )
    from ..engine import checkpoint as ckpt

    pool = SoAPool(problem.node_fields())
    diagnostics = Diagnostics()
    phases: list[PhaseStats] = []
    t0 = time.perf_counter()

    # -- phase 1: host warm-up to D*m (`nqueens_multigpu_chpl.chpl:173`),
    # or checkpoint restore --------------------------------------------------
    if resume_from is not None:
        saved = ckpt.load(resume_from, problem)
        pool.push_back_bulk(saved.batch)
        tree1, sol1 = saved.tree, saved.sol
        # Keep the tighter incumbent (cf. resident_search resume).
        best = min(best, saved.best)
        # The resumed frontier re-partitions stride-D; grow the per-shard
        # capacity so the largest shard plus one fan-out fits even when D
        # shrank since the checkpoint.
        capacity = max(capacity, -(-pool.size // D) + 2 * M * n)
    else:
        pool.push_back(index_batch(problem.root(), 0))
        target = D * m if warmup_target is None else warmup_target
        tree1, sol1, best = warmup(problem, pool, best, target)
    t1 = time.perf_counter()
    phases.append(PhaseStats(t1 - t0, tree1, sol1))
    ev.counter("explored", tree=tree1, sol=sol1, phase=1)

    # -- phase 2: SPMD resident loop ---------------------------------------
    from ..engine.pipeline import (
        AdaptiveK,
        DispatchQueue,
        MESH_TARGET,
        resolve_k,
        resolve_pipeline_depth,
        resolve_target_band,
    )

    k_auto, k_value = resolve_k(K, default_max=16)
    # The mesh tier's K is bounded by balancing responsiveness: incumbent
    # pmin folds and diffusion rounds happen per dispatch, so the ladder
    # targets a tighter host period than the single-device tier — and that
    # band IS the tier's steal (diffusion) period. With TTS_COSTMODEL it
    # resolves from the measured dispatch-latency fit instead of the
    # fixed default (engine/pipeline.py resolve_target_band).
    band, band_src = resolve_target_band(
        "mesh", MESH_TARGET, problem, topology=f"mesh-D{D}"
    )
    ctl = AdaptiveK(k_value, target=band) if k_auto else None
    depth = resolve_pipeline_depth()
    program = get_mesh_program(problem, mesh, m, M,
                               ctl.K if ctl else k_value, rounds, T, capacity)

    def upload(warm_batch):
        # Static stride-D partition (`nqueens_multigpu_chpl.chpl:221-225`).
        shards = [{k: v[w::D] for k, v in warm_batch.items()} for w in range(D)]
        return program.init_state(shards, best)

    state = upload(pool.as_batch())
    pool.clear()
    diagnostics.host_to_device += 1

    tree2 = 0
    sol2 = 0
    per_worker = np.zeros(D, dtype=np.int64)
    sizes = np.zeros(D, dtype=np.int32)
    prev_sizes = None
    offloader = None

    from ..analysis.guard import SteadyStateGuard, guard_enabled

    genabled = guard_enabled(guard)
    guards: dict[int, SteadyStateGuard] = {}

    def guard_of(prog) -> SteadyStateGuard:
        g = guards.get(id(prog))
        if g is None:
            g = guards[id(prog)] = SteadyStateGuard(
                prog._step, "mesh-resident step", enabled=genabled
            )
        return g

    ctr_total: dict | None = None
    ph_total: dict | None = None  # per-phase ns totals (TTS_PHASEPROF=1)
    fb_tree = fb_sol = 0  # saturation-fallback host increments (obs parity)
    prev_best = best
    # Anytime quality: None on the off path; otherwise records the
    # incumbent trajectory from scalars consume() already reads.
    qt = obs_quality.tracker(problem)
    n_disp = 0  # completed-dispatch sequence (flight-recorder registry)
    queue = DispatchQueue(depth)
    xwin = obs_phases.XlaTraceWindow("mesh")

    def obs_result() -> dict | None:
        parts = {}
        if ctr_total is not None:
            parts["device_counters"] = ctr_total
        if ph_total is not None:
            parts["device_phases"] = ph_total
        return parts or None

    def enqueue() -> None:
        nonlocal state
        t_enq = ev.now_us()
        with guard_of(program).step():
            out = program.step(state)
        state = program.carry(out)
        queue.push(out, t_enq)

    def consume(out, t_enq) -> tuple[int, int, int]:
        nonlocal tree2, sol2, sizes, best, ctr_total, ph_total, prev_best
        nonlocal per_worker, n_disp
        t_wait = ev.now_us()
        ti, si, cy, sizes, best, tree_vec, ctr = program.read_scalars(out)
        phb = program.read_phase_block(out)
        tree2 += ti
        sol2 += si
        n_disp += 1
        per_worker += tree_vec.astype(np.int64)
        diagnostics.kernel_launches += cy
        if ctr is not None:
            ctr_total = obs_counters.merge_host(ctr_total, ctr)
        if phb is not None:
            ph_total = obs_phases.merge_host(ph_total, phb)
        xwin.on_dispatch(n_disp)
        fr.heartbeat("mesh", seq=n_disp, cycles=cy, size=int(sizes.sum()),
                     best=best, tree=tree2, sol=sol2, depth=depth,
                     K=program.K, inflight=len(queue),
                     phases=ph_total)
        if qt is not None:
            qt.observe(best, n_disp, tree1 + tree2)
        if ev.enabled():
            now = ev.now_us()
            ev.emit("dispatch", ph="X", ts=t_enq,
                    dur=max(0.0, now - t_enq), args={
                        "cycles": cy, "tree": ti, "sol": si,
                        "size": int(sizes.sum()), "best": best,
                        "shard_sizes": sizes.tolist(),
                        "enqueue_us": t_enq, "read_wait_us": now - t_wait,
                        "pipeline_depth": depth,
                    })
            if ctr is not None:
                ev.counter("device_counters", **obs_counters.as_args(ctr))
            if phb is not None:
                ev.counter("device_phases", **obs_phases.as_args(phb))
            if best < prev_best:
                ev.emit("incumbent", args={"best": best})
        prev_best = best
        return ti, si, cy

    def drain_queue() -> tuple[int, int]:
        dt = ds = 0
        for out, t_enq in queue.drain():
            ti, si, _ = consume(out, t_enq)
            dt += ti
            ds += si
        return dt, ds

    def snapshot_fn():
        batch = program.full_batch(state)
        diagnostics.device_to_host += 1
        return batch, best

    controller = ckpt.RunController(
        problem, checkpoint_path, checkpoint_interval_s, max_steps,
        snapshot_fn, drain_fn=drain_queue, yield_fn=yield_fn,
    )

    fr.arm("mesh")
    ev.emit("pipeline", args={
        "depth": depth, "K": program.K, "k_auto": k_auto, "tier": "mesh",
    })
    if band_src is not None:
        ev.emit("costmodel", args={
            "source": band_src, "lo_ms": round(1e3 * band[0], 1),
            "hi_ms": round(1e3 * band[1], 1), "tier": "mesh",
        })
    last_ready = time.monotonic()

    while True:
        while not queue.full:
            enqueue()
        out, t_enq = queue.pop()
        ti, si, cy = consume(out, t_enq)
        now = time.monotonic()
        period, last_ready = now - last_ready, now
        if int(sizes.max()) < m:
            drain_queue()  # speculative no-ops; state passes through
            break
        if controller.after_step(tree1 + tree2, sol1 + sol2):
            drain_queue()  # no-op if the cutoff save already drained
            xwin.close()
            t2 = time.perf_counter()
            phases.append(PhaseStats(t2 - t1, tree2, sol2))
            ev.emit("checkpoint", args={"cutoff": True})
            _emit_device_explored(ctr_total, tree2, sol2, fb_tree, fb_sol)
            return SearchResult(
                explored_tree=tree1 + tree2,
                explored_sol=sol1 + sol2,
                best=best,
                elapsed=t2 - t0,
                phases=phases,
                diagnostics=diagnostics,
                per_worker_tree=per_worker.tolist(),
                complete=False,
                steps=controller.steps,
                compact=program.inner.compact,
                compact_auto=program.inner.compact_auto,
                megakernel=program.inner.megakernel.state,
                megakernel_auto=program.inner.megakernel.auto,
                megakernel_reason=program.inner.megakernel.reason,
                pipeline_depth=depth,
                k_resolved=program.K,
                k_auto=k_auto,
                obs=obs_result(),
                phase_profile=ph_total,
                quality=qt.result() if qt is not None else None,
            )
        if ctl is not None and cy > 0 and ctl.observe(period, cy):
            drain_queue()
            program = get_mesh_program(problem, mesh, m, M, ctl.K, rounds,
                                       T, capacity)
            ev.emit("k_resize", args={"K": program.K})
            last_ready = time.monotonic()
            prev_sizes = None
            if int(sizes.max()) < m:
                break
            continue
        if cy == 0 and prev_sizes is not None and np.array_equal(sizes, prev_sizes):
            # Saturation: no shard ran a cycle and balancing moved nothing.
            # Fall back to host offload cycles (same guarantee as the
            # single-device tier) until the frontier fits again.
            from ..engine.device import DeviceOffloader, bucket_size

            drain_queue()  # saturated speculative dispatches are no-ops too
            t_fb = ev.now_us()
            fb_tree0, fb_sol0 = tree2, sol2
            pool.reset_from(program.full_batch(state))
            diagnostics.device_to_host += 1
            if offloader is None:
                offloader = DeviceOffloader(problem, program.mesh.devices.flat[0])
            chunk_buf = problem.empty_batch(M)
            fits = D * max(0, capacity - 2 * M * n)
            while pool.size >= m and pool.size > fits:
                count = pool.pop_back_bulk(m, M, chunk_buf)
                if count == 0:
                    break
                bucket = bucket_size(count, m, M)
                snapshot = {k: v[:count].copy() for k, v in chunk_buf.items()}
                dev = offloader.dispatch(snapshot, count, bucket, best)
                res = problem.generate_children(
                    snapshot, count, offloader.collect(dev), best
                )
                tree2 += res.tree_inc
                sol2 += res.sol_inc
                best = res.best
                pool.push_back_bulk(res.children)
            diagnostics.kernel_launches += offloader.diagnostics.kernel_launches
            diagnostics.host_to_device += offloader.diagnostics.host_to_device
            diagnostics.device_to_host += offloader.diagnostics.device_to_host
            offloader.diagnostics = Diagnostics()
            state = upload(pool.as_batch())
            pool.clear()
            diagnostics.host_to_device += 1
            # Sanctioned re-upload; next dispatch is a fresh warm one.
            guard_of(program).rearm()
            last_ready = time.monotonic()
            fb_tree += tree2 - fb_tree0
            fb_sol += sol2 - fb_sol0
            ev.complete("overflow_fallback", t_fb, args={
                "tree": tree2 - fb_tree0, "sol": sol2 - fb_sol0,
            })
            prev_sizes = None
            continue
        prev_sizes = sizes
    xwin.close()
    batch = program.residual_batch(state)
    diagnostics.device_to_host += 1
    pool.reset_from(batch)
    t2 = time.perf_counter()
    phases.append(PhaseStats(t2 - t1, tree2, sol2))
    _emit_device_explored(ctr_total, tree2, sol2, fb_tree, fb_sol)

    # -- phase 3: host drain ------------------------------------------------
    tree3, sol3, best = drain(problem, pool, best)
    t3 = time.perf_counter()
    phases.append(PhaseStats(t3 - t2, tree3, sol3))
    ev.counter("explored", tree=tree3, sol=sol3, phase=3)
    if qt is not None:
        # The host drain can improve the incumbent one last time.
        qt.observe(best, n_disp, tree1 + tree2 + tree3)

    return SearchResult(
        explored_tree=tree1 + tree2 + tree3,
        explored_sol=sol1 + sol2 + sol3,
        best=best,
        elapsed=t3 - t0,
        phases=phases,
        diagnostics=diagnostics,
        per_worker_tree=per_worker.tolist(),
        steps=controller.steps,
        compact=program.inner.compact,
        compact_auto=program.inner.compact_auto,
        megakernel=program.inner.megakernel.state,
        megakernel_auto=program.inner.megakernel.auto,
        megakernel_reason=program.inner.megakernel.reason,
        pipeline_depth=depth,
        k_resolved=program.K,
        k_auto=k_auto,
        obs=obs_result(),
        phase_profile=ph_total,
        quality=qt.result() if qt is not None else None,
    )
