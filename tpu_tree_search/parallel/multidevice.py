"""Multi-device search runtime: one host thread + private pool per device,
static initial partition, work stealing, idle-scan termination.

Reproduces the reference's multi-GPU tier semantics
(`pfsp_multigpu_chpl.chpl:312-535`, `nqueens_multigpu_chpl.chpl:152-346`):

  * warm-up on the main thread until the global pool holds ``D * m`` nodes
    (`nqueens_multigpu_chpl.chpl:173`);
  * static round-robin partition — worker w receives elements w, w+D, w+2D …
    of the warm pool, so adjacent (sibling) subtrees land on different
    devices (`nqueens_multigpu_chpl.chpl:221-225`);
  * each worker snapshots the incumbent (``best_l``) at partition time and
    prunes against it privately; incumbents reconcile at the terminal
    min-reduction — the reference's lazy-UB design (SURVEY.md §2.4.4). A
    ``share_bound`` flag adds the mid-search improvement the reference
    lacks: workers publish/adopt the global best between chunks;
  * work stealing when a worker's pool runs dry: victims in random order
    (`permute`, `nqueens_multigpu_chpl.chpl:441`), up to 10 lock attempts
    per victim, steal **half the victim's front** iff its size >= 2m
    (`Pool_par.chpl:180-191`);
  * termination: idle-state array + sticky-flag allIdle scan
    (`util.chpl:16-30`); workers flip BUSY again on new work;
  * leftovers drain back to the global pool, stats reduce at the join
    (`pfsp_multigpu_chpl.chpl:498-520`), final CPU drain on the main thread.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from ..engine.device import DeviceOffloader, bucket_size, drain, warmup
from ..engine.results import Diagnostics, PhaseStats, SearchResult
from ..obs import events as ev
from ..obs import flightrec as fr
from ..pool import ParallelSoAPool, SoAPool
from ..problems.base import INF_BOUND, Problem, batch_length, index_batch
from ..utils import TaskStates


class _SharedBest:
    """Optional mid-search incumbent exchange (improvement over the
    reference's terminal-only reconciliation, BASELINE.json north star)."""

    def __init__(self, value: int):
        self._value = value  # guarded-by: _lock
        self._lock = threading.Lock()

    def publish(self, value: int) -> int:
        with self._lock:
            if value < self._value:
                self._value = value
            return self._value

    def read(self) -> int:
        with self._lock:
            return self._value


class PauseGate:
    """Chunk-boundary rendezvous for checkpointing the threaded tiers.

    A worker at the top of its loop holds no in-flight nodes (the popped
    chunk's children were pushed before it came back around), so pausing
    every live worker there yields pools whose union is the exact frontier.
    Workers call ``poll()`` once per iteration (no-op unless a pause is
    wanted) and ``leave()`` on exit; the coordinator brackets the snapshot
    with ``pause()``/``resume()``. The reference has no checkpointing at
    all (SURVEY.md §5) — this is the thread-tier analogue of the resident
    engine's between-cycles snapshot."""

    def __init__(self, n_workers: int):
        self._cond = threading.Condition()
        self.active = n_workers  # guarded-by: _cond
        self.paused = 0  # guarded-by: _cond
        self.want = False  # guarded-by: _cond

    def poll(self, flush=None) -> None:
        """``flush``: called (outside the lock) before parking when a pause
        is wanted — the pipelined workers consume their in-flight chunk
        there, so the paused-pools union is still the exact frontier. A
        worker that misses a just-raised ``want`` here simply finishes its
        current iteration (children pushed, pending consumed next poll);
        ``pause()`` keeps waiting until every live worker parks."""
        with self._cond:
            if not self.want:
                return
        if flush is not None:
            flush()
        with self._cond:
            self.paused += 1
            self._cond.notify_all()
            while self.want:
                self._cond.wait()
            self.paused -= 1
            self._cond.notify_all()

    def leave(self) -> None:
        with self._cond:
            self.active -= 1
            self._cond.notify_all()

    def pause(self) -> None:
        with self._cond:
            self.want = True
            while self.paused < self.active:
                self._cond.wait()

    def resume(self) -> None:
        with self._cond:
            self.want = False
            self._cond.notify_all()

    def all_left(self) -> bool:
        """True once every worker has exited (the checkpoint timer's stop
        test).  Owns its lock — callers must not reach into ``_cond``
        (keeps the lock-order audit's acquisition sites inside the class,
        docs/ANALYSIS.md)."""
        with self._cond:
            return self.active == 0


class CheckpointManager:
    """Snapshot-and-save for the multi/dist tiers: pause workers at chunk
    boundaries, merge every local pool's frontier into one batch, and write
    the same tier-agnostic ``Checkpoint`` format the resident tiers use
    (a multi checkpoint resumes on the device tier and vice versa; the
    stride partition re-splits any frontier). ``base_tree``/``base_sol``
    carry counts from phases outside the workers (warm-up, a resumed run's
    history)."""

    def __init__(self, problem: Problem, path: str, gate: PauseGate,
                 pools: list[ParallelSoAPool], workers, shared,
                 base_tree: int, base_sol: int,
                 interval_s: float = 60.0, hosts: int = 1):
        self.problem = problem
        self.path = path
        self.gate = gate
        self.pools = pools
        self.workers = workers
        self.shared = shared
        self.base_tree = base_tree
        self.base_sol = base_sol
        self.interval_s = interval_s
        self.hosts = hosts
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def do_checkpoint(self, to_path: str | None = None,
                      cut_tag: int | str | None = None) -> bool:
        """Pause, snapshot, save; returns False (writing nothing) if a
        worker has died — its popped chunk is gone from the pools, so a cut
        would silently lose a subtree. ``to_path`` lets the dist tier stage
        to a temp file for its collective two-phase commit."""
        from ..engine import checkpoint as ckpt

        t_cut = ev.now_us()
        self.gate.pause()
        try:
            # Re-check AFTER the rendezvous: a worker that crashed while
            # pause() was gathering stragglers has left the gate (its error
            # set) without pushing its chunk's children.
            if any(w.error is not None for w in self.workers):
                return False
            merged = {k: [] for k in self.problem.empty_batch(0)}
            for p in self.pools:
                # tts-lint: waive guarded-by -- workers are quiesced at the PauseGate rendezvous; no thread can mutate pools until resume()
                b = p.as_batch()
                for k in merged:
                    merged[k].append(b[k])
            batch = {k: np.concatenate(v) for k, v in merged.items()}
            tree = self.base_tree + sum(w.tree for w in self.workers)
            sol = self.base_sol + sum(w.sol for w in self.workers)
            best = min(
                [self.shared.read() if self.shared is not None else INF_BOUND]
                + [w.best for w in self.workers]
            )
            ckpt.save(to_path or self.path, self.problem, batch, best, tree,
                      sol, hosts=self.hosts, cut_tag=cut_tag)
            ev.complete("checkpoint", t_cut, wid=ev.COMM_TID,
                        args={"nodes": int(batch_length(batch))})
            return True
        finally:
            self.gate.resume()

    # -- timer mode (multi tier; the dist tier drives do_checkpoint from
    # its communicator round instead, so all hosts cut in lockstep) --------
    def _timer_loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            if self.gate.all_left():
                return
            self.do_checkpoint()

    def start_timer(self) -> None:
        self._thread = threading.Thread(
            target=self._timer_loop, name="tts-ckpt", daemon=True
        )
        self._thread.start()

    def stop_timer(self) -> None:
        if self._thread is not None:
            self._stop.set()
            self._thread.join()


class _Worker:
    def __init__(self, wid: int, problem: Problem, pool: ParallelSoAPool, device):
        self.wid = wid
        self.problem = problem
        self.pool = pool
        self.device = device
        self.tree = 0
        self.sol = 0
        self.best = INF_BOUND
        self.steals = 0
        self.chunks = 0  # completed-chunk sequence (flight recorder)
        # Last successful steal's link class / hierarchy level (intra-host
        # steals are always local/0; carried on heartbeats so `tts watch`
        # can name the current steal level — parallel/topology.py).
        self.steal_link: str | None = None
        self.steal_level: int | None = None
        self.diagnostics = Diagnostics()
        self.error: BaseException | None = None


def _partition(problem: Problem, pool: SoAPool, D: int) -> list[ParallelSoAPool]:
    """Static stride-D split of the warm pool
    (`nqueens_multigpu_chpl.chpl:199-225`): worker w gets elements w::D."""
    batch = pool.as_batch()
    pools = []
    for w in range(D):
        p = ParallelSoAPool(problem.node_fields())
        # tts-lint: waive guarded-by -- pool is thread-local until run_workers hands it to a worker thread
        p.push_back_bulk({k: v[w::D] for k, v in batch.items()})
        pools.append(p)
    return pools


def _worker_loop(
    w: _Worker,
    pools: list[ParallelSoAPool],
    states: TaskStates,
    m: int,
    M: int,
    shared: _SharedBest | None,
    rng: np.random.Generator,
    perc: float = 0.5,
    stop_event: threading.Event | None = None,
    gate: PauseGate | None = None,
    host_id: int = 0,
):
    problem = w.problem
    idle_t0: float | None = None  # open idle span start (obs tracing)
    pending = None  # (staged, count, dev_result, t_chunk) in-flight chunk
    try:
        off = DeviceOffloader(problem, w.device)
        w.diagnostics = off.diagnostics
        D = len(pools)
        chunk_buf = problem.empty_batch(M)

        def consume_pending() -> None:
            # Collect + prune/branch + push of the in-flight chunk (the
            # async-overlap discipline of `device_search`, per worker:
            # while this chunk evaluated on device, the host popped and
            # staged the next one into the other staging buffer).
            nonlocal pending
            if pending is None:
                return
            staged, count, dev_result, t_chunk = pending
            pending = None
            results = off.collect(dev_result)
            res = problem.generate_children(staged, count, results, w.best)
            w.tree += res.tree_inc
            w.sol += res.sol_inc
            if res.best < w.best:
                w.best = res.best
                if shared is not None:
                    w.best = shared.publish(w.best)
                ev.emit("incumbent", wid=w.wid, host=host_id,
                        args={"best": w.best})
            w.pool.locked_push_back_bulk(res.children)
            w.chunks += 1
            ev.complete("chunk", t_chunk, wid=w.wid, host=host_id,
                        args={"count": count, "tree": res.tree_inc,
                              "sol": res.sol_inc})
            fr.heartbeat("multi", host=host_id, wid=w.wid, seq=w.chunks,
                         best=w.best, tree=w.tree, sol=w.sol,
                         steals=w.steals, steal_link=w.steal_link,
                         steal_level=w.steal_level)

        while True:
            if gate is not None:
                # Chunk boundary: the checkpoint rendezvous point — the
                # flush consumes any in-flight chunk first, so a paused
                # worker holds nothing outside its pool.
                gate.poll(flush=consume_pending)
            # Pre-mark BUSY: with an external idle sampler (the dist tier's
            # communicator thread) marking busy only *after* the pop would
            # open a window where a worker holds a chunk while looking idle.
            # For the self-evaluated allIdle scan this is equivalent to the
            # reference's after-pop transition (`pfsp_multigpu_chpl.chpl:416`).
            states.set_busy(w.wid)
            count = w.pool.locked_pop_back_bulk(m, M, chunk_buf)
            if count > 0:
                if idle_t0 is not None:
                    ev.complete("idle", idle_t0, wid=w.wid, host=host_id)
                    idle_t0 = None
                    fr.set_idle(host_id, w.wid, False)
                t_chunk = ev.now_us()
                if shared is not None:
                    w.best = min(w.best, shared.read())
                bucket = bucket_size(count, m, M)
                staged = off.stage(chunk_buf, count, bucket)
                dev_result = off.dispatch_staged(
                    staged, count, w.best, overlapped=pending is not None
                )
                nxt = (staged, count, dev_result, t_chunk)
                consume_pending()
                pending = nxt
                continue
            if pending is not None:
                # Pool dry but a chunk is in flight: its children may
                # refill the pool past m — never steal or go idle with
                # work outstanding.
                consume_pending()
                continue
            # -- work stealing (`pfsp_multigpu_chpl.chpl:438-479`) ---------
            # Timed as a SPAN (victim scan + locked pop + push): the cost
            # model's "steal" link — the local-class latency the steal
            # hierarchy compares against ici/dcn donation fits.
            stolen = False
            t_steal = ev.now_us()
            for victim_id in rng.permutation(D):
                if victim_id == w.wid:
                    continue
                victim = pools[victim_id]
                for _ in range(10):  # lock attempts cap, `Pool_par` call sites
                    if victim.try_lock():
                        try:
                            batch = victim.pop_front_bulk_half(m, perc)
                        finally:
                            victim.unlock()
                        if batch is not None:
                            w.pool.locked_push_back_bulk(batch)
                            w.steals += 1
                            w.steal_link, w.steal_level = "local", 0
                            stolen = True
                            ev.complete("steal", t_steal, wid=w.wid,
                                        host=host_id,
                                        args={"victim": int(victim_id),
                                              "nodes": batch_length(batch),
                                              "bytes": sum(
                                                  a.nbytes
                                                  for a in batch.values()),
                                              "link": "local", "level": 0})
                        break
                    time.sleep(0)  # yieldExecution backoff
                if stolen:
                    break
            if stolen:
                states.set_busy(w.wid)
                continue
            # -- termination (`pfsp_multigpu_chpl.chpl:481-495`) -----------
            states.set_idle(w.wid)
            if idle_t0 is None:
                # One miss per busy->idle transition, not per spin
                # iteration: the termination loop re-scans victims every
                # few microseconds and would flood the trace.
                ev.emit("steal_miss", wid=w.wid, host=host_id,
                        args={"link": "local", "level": 0})
                idle_t0 = ev.now_us()
                fr.set_idle(host_id, w.wid, True)
            if stop_event is not None:
                # Dist mode: local all-idle is NOT the end — the host may
                # still receive stolen work from another host. Poll until
                # the communicator declares global termination (the
                # two-level scheme, `pfsp_dist_multigpu_chpl.chpl:569-587`).
                if stop_event.is_set():
                    return
                time.sleep(0.0005)
                continue
            if states.all_idle():
                return
            time.sleep(0)
    except BaseException as e:  # surface into the main thread
        w.error = e
        states.set_idle(w.wid)
        states.flag.set()  # unblock everyone; search aborts
    finally:
        if idle_t0 is not None:
            ev.complete("idle", idle_t0, wid=w.wid, host=host_id)
        ev.counter("explored", wid=w.wid, host=host_id,
                   tree=w.tree, sol=w.sol, phase=2)
        if gate is not None:
            gate.leave()


def run_workers(
    problem: Problem,
    pool: SoAPool,
    D: int,
    assigned,
    m: int,
    M: int,
    best: int,
    share_bound: bool = True,
    seed: int = 0xB0B,
    perc: float = 0.5,
    comm=None,
    ckpt_path: str | None = None,
    ckpt_interval_s: float = 60.0,
    ckpt_base: tuple[int, int] = (0, 0),
    ckpt_hosts: int = 1,
    host_id: int = 0,
):
    """Step 2 of the multi-device tier: partition ``pool`` across D worker
    threads, run the offload/steal/terminate loops, join, and merge leftovers
    back into a fresh global pool. Returns
    ``(leftover_pool, tree2, sol2, best, workers)``. Shared by the
    single-host multi tier and the per-host phase of the distributed tier
    (the reference duplicates this scaffolding between its multi and dist
    mains, SURVEY.md §1 note).

    ``comm`` (dist tier): a host communicator with a
    ``run(pools, states, shared, stop_event)`` method, executed in its own
    thread alongside the workers. It owns global termination: workers then
    poll until ``stop_event`` is set instead of exiting on local all-idle.
    """
    fr.arm("multi")
    pools = _partition(problem, pool, D)
    leftover = SoAPool(problem.node_fields())
    states = TaskStates(D)
    shared = _SharedBest(best) if share_bound or comm is not None else None
    workers = [_Worker(w, problem, pools[w], assigned[w]) for w in range(D)]
    for w in workers:
        w.best = best
    stop_event = threading.Event() if comm is not None else None
    gate = mgr = None
    if ckpt_path is not None:
        gate = PauseGate(D)
        mgr = CheckpointManager(
            problem, ckpt_path, gate, pools, workers, shared,
            base_tree=ckpt_base[0], base_sol=ckpt_base[1],
            interval_s=ckpt_interval_s, hosts=ckpt_hosts,
        )
        if comm is not None:
            # Dist tier: the communicator drives checkpoints from its
            # exchange round so every host cuts in the same lockstep round
            # (no donation can straddle the snapshot).
            comm.ckpt_mgr = mgr
        else:
            mgr.start_timer()
    seeds = np.random.SeedSequence(seed)
    threads = [
        threading.Thread(
            target=_worker_loop,
            args=(w, pools, states, m, M, shared, np.random.default_rng(s),
                  perc, stop_event, gate, host_id),
            name=f"tts-worker-{w.wid}",
        )
        for w, s in zip(workers, seeds.spawn(D))
    ]
    comm_thread = None
    if comm is not None:
        comm_thread = threading.Thread(
            target=comm.run, args=(pools, states, shared, stop_event),
            name="tts-host-comm",
        )
        comm_thread.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if comm_thread is not None:
        comm_thread.join()
    if mgr is not None and comm is None:
        mgr.stop_timer()
    for w in workers:
        if w.error is not None:
            raise w.error
    if comm is not None and getattr(comm, "error", None) is not None:
        raise comm.error
    # leftovers back into the global pool (`pfsp_multigpu_chpl.chpl:498-503`)
    for p in pools:
        # tts-lint: waive guarded-by -- worker and communicator threads are joined; no concurrent access remains
        leftover.push_back_bulk(p.as_batch())
    tree2 = sum(w.tree for w in workers)
    sol2 = sum(w.sol for w in workers)
    best = min([best] + [w.best for w in workers])  # min-reduce (`:518-520`)
    return leftover, tree2, sol2, best, workers


def host_pipeline(
    problem: Problem,
    m: int,
    M: int,
    D: int,
    devices,
    initial_best: int | None = None,
    share_bound: bool = True,
    num_hosts: int = 1,
    host_id: int = 0,
    seed: int = 0xB0B,
    perc: float = 0.5,
    comm=None,
    partition_fn=None,
    checkpoint_path: str | None = None,
    checkpoint_interval_s: float = 60.0,
    resume_from: str | None = None,
) -> dict:
    """The full 3-phase pipeline one host runs: warm-up, partitioned
    parallel offload (work stealing + termination), drain.

    With ``num_hosts == 1`` this is the whole multi-GPU tier
    (`pfsp_multigpu_chpl.chpl:312-535`). With H hosts, every host runs the
    identical deterministic warm-up to ``H*D*m`` and takes its stride-H
    slice — the locale-level round-robin partition of the dist tier
    (`pfsp_dist_multigpu_chpl.chpl:339-374`) without communication; host 0
    owns the warm-up counters so the cross-host sum counts them once.
    Returns a dict of local stats for (cross-host) reduction.
    """
    # One thread per device; if D exceeds physical devices, oversubscribe
    # round-robin (the CPU-mesh testing mode, SURVEY.md §4.6).
    assigned = [devices[w % len(devices)] for w in range(D)]

    best = (
        initial_best
        if initial_best is not None
        else getattr(problem, "initial_ub", INF_BOUND)
    )
    # Per-host files for the multi-host tiers (each host snapshots its own
    # pools; resume needs the same host count).
    suffix = f".h{host_id}" if num_hosts > 1 else ""
    eff_ckpt = None if checkpoint_path is None else checkpoint_path + suffix
    eff_resume = None if resume_from is None else resume_from + suffix

    pool = SoAPool(problem.node_fields())
    t0 = time.perf_counter()
    if eff_resume is not None:
        # Resume replaces warm-up entirely: the loaded frontier IS this
        # host's share (same tier-agnostic format as the resident tiers).
        from ..engine import checkpoint as ckpt_mod

        loaded = ckpt_mod.load(eff_resume, problem, expect_hosts=num_hosts)
        if comm is not None:
            # Lockstep-cut coherence: every host's file must carry the SAME
            # cut id ("<run-uuid>:<round>", stamped by _HostComm). Per-host
            # files from different cuts — a host that crashed between the
            # two-phase-commit allgather and its os.replace, or stale files
            # from a prior run with the same host count — would pass the
            # hosts check yet describe an incoherent frontier union: nodes
            # donated between the two rounds get lost or double-explored.
            tags = comm.coll.allgather_obj(loaded.cut_tag)
            if len(set(tags)) != 1:
                raise ValueError(
                    "incoherent multi-host resume: per-host checkpoint "
                    f"files come from different cuts ({tags}); restore a "
                    "matching set (same run, same communicator round) "
                    "before resuming"
                )
        pool.push_back_bulk(loaded.batch)
        tree1, sol1 = 0, 0
        base_tree, base_sol = loaded.tree, loaded.sol
        best = min(best, loaded.best)
    else:
        base_tree = base_sol = 0
        pool.push_back(index_batch(problem.root(), 0))

        # -- step 1: warm-up to H*D*m (`nqueens_multigpu_chpl.chpl:173`,
        # dist target `pfsp_dist_multigpu_chpl.chpl:339-345`) --------------
        tree1, sol1, best = warmup(problem, pool, best, num_hosts * D * m)
        if num_hosts > 1:
            warm = pool.as_batch()
            pool = SoAPool(problem.node_fields())
            if partition_fn is None:
                pool.push_back_bulk(
                    {k: v[host_id::num_hosts] for k, v in warm.items()}
                )
            else:
                # Test/experiment hook: arbitrary (possibly skewed) host
                # partitions, e.g. to exercise inter-host stealing from a
                # host that starts empty.
                pool.push_back_bulk(partition_fn(warm, host_id, num_hosts))
            if host_id != 0:
                tree1 = sol1 = 0
    t1 = time.perf_counter()
    ev.counter("explored", host=host_id, tree=base_tree + tree1,
               sol=base_sol + sol1, phase=1)

    # -- step 2: partitioned parallel offload ------------------------------
    pool, tree2, sol2, best, workers = run_workers(
        problem, pool, D, assigned, m, M, best, share_bound, seed=seed,
        perc=perc, comm=comm,
        ckpt_path=eff_ckpt, ckpt_interval_s=checkpoint_interval_s,
        ckpt_base=(base_tree + tree1, base_sol + sol1),
        ckpt_hosts=num_hosts,
        host_id=host_id,
    )
    t2 = time.perf_counter()

    # -- step 3: drain (`pfsp_multigpu_chpl.chpl:529-535`) -----------------
    tree3, sol3, best = drain(problem, pool, best)
    t3 = time.perf_counter()
    ev.counter("explored", host=host_id, tree=tree3, sol=sol3, phase=3)

    diag = Diagnostics(
        kernel_launches=sum(w.diagnostics.kernel_launches for w in workers),
        host_to_device=sum(w.diagnostics.host_to_device for w in workers),
        device_to_host=sum(w.diagnostics.device_to_host for w in workers),
    )
    return {
        "tree": base_tree + tree1 + tree2 + tree3,
        "sol": base_sol + sol1 + sol2 + sol3,
        "best": best,
        "steals": sum(w.steals for w in workers),
        "phases": [
            PhaseStats(t1 - t0, tree1, sol1),
            PhaseStats(t2 - t1, tree2, sol2),
            PhaseStats(t3 - t2, tree3, sol3),
        ],
        "elapsed": t3 - t0,
        "per_worker_tree": [w.tree for w in workers],
        "diag": diag,
    }


def multidevice_search(
    problem: Problem,
    m: int = 25,
    M: int = 50000,
    D: int | None = None,
    devices=None,
    initial_best: int | None = None,
    share_bound: bool = True,
    perc: float = 0.5,
    checkpoint_path: str | None = None,
    checkpoint_interval_s: float = 60.0,
    resume_from: str | None = None,
) -> SearchResult:
    import jax

    if devices is None:
        devices = jax.devices()
    if D is None:
        D = len(devices)
    local = host_pipeline(
        problem, m, M, D, devices, initial_best, share_bound, perc=perc,
        checkpoint_path=checkpoint_path,
        checkpoint_interval_s=checkpoint_interval_s,
        resume_from=resume_from,
    )
    return SearchResult(
        explored_tree=local["tree"],
        explored_sol=local["sol"],
        best=local["best"],
        elapsed=local["elapsed"],
        phases=local["phases"],
        diagnostics=local["diag"],
        per_worker_tree=local["per_worker_tree"],
        steals=local["steals"],
    )
