"""Multi-device search runtime: one host thread + private pool per device,
static initial partition, work stealing, idle-scan termination.

Reproduces the reference's multi-GPU tier semantics
(`pfsp_multigpu_chpl.chpl:312-535`, `nqueens_multigpu_chpl.chpl:152-346`):

  * warm-up on the main thread until the global pool holds ``D * m`` nodes
    (`nqueens_multigpu_chpl.chpl:173`);
  * static round-robin partition — worker w receives elements w, w+D, w+2D …
    of the warm pool, so adjacent (sibling) subtrees land on different
    devices (`nqueens_multigpu_chpl.chpl:221-225`);
  * each worker snapshots the incumbent (``best_l``) at partition time and
    prunes against it privately; incumbents reconcile at the terminal
    min-reduction — the reference's lazy-UB design (SURVEY.md §2.4.4). A
    ``share_bound`` flag adds the mid-search improvement the reference
    lacks: workers publish/adopt the global best between chunks;
  * work stealing when a worker's pool runs dry: victims in random order
    (`permute`, `nqueens_multigpu_chpl.chpl:441`), up to 10 lock attempts
    per victim, steal **half the victim's front** iff its size >= 2m
    (`Pool_par.chpl:180-191`);
  * termination: idle-state array + sticky-flag allIdle scan
    (`util.chpl:16-30`); workers flip BUSY again on new work;
  * leftovers drain back to the global pool, stats reduce at the join
    (`pfsp_multigpu_chpl.chpl:498-520`), final CPU drain on the main thread.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from ..engine.device import DeviceOffloader, bucket_size, drain, warmup
from ..engine.results import Diagnostics, PhaseStats, SearchResult
from ..pool import ParallelSoAPool, SoAPool
from ..problems.base import INF_BOUND, Problem, batch_length, index_batch
from ..utils import TaskStates


class _SharedBest:
    """Optional mid-search incumbent exchange (improvement over the
    reference's terminal-only reconciliation, BASELINE.json north star)."""

    def __init__(self, value: int):
        self._value = value
        self._lock = threading.Lock()

    def publish(self, value: int) -> int:
        with self._lock:
            if value < self._value:
                self._value = value
            return self._value

    def read(self) -> int:
        return self._value


class _Worker:
    def __init__(self, wid: int, problem: Problem, pool: ParallelSoAPool, device):
        self.wid = wid
        self.problem = problem
        self.pool = pool
        self.device = device
        self.tree = 0
        self.sol = 0
        self.best = INF_BOUND
        self.steals = 0
        self.diagnostics = Diagnostics()
        self.error: BaseException | None = None


def _partition(problem: Problem, pool: SoAPool, D: int) -> list[ParallelSoAPool]:
    """Static stride-D split of the warm pool
    (`nqueens_multigpu_chpl.chpl:199-225`): worker w gets elements w::D."""
    batch = pool.as_batch()
    pools = []
    for w in range(D):
        p = ParallelSoAPool(problem.node_fields())
        p.push_back_bulk({k: v[w::D] for k, v in batch.items()})
        pools.append(p)
    return pools


def _worker_loop(
    w: _Worker,
    pools: list[ParallelSoAPool],
    states: TaskStates,
    m: int,
    M: int,
    shared: _SharedBest | None,
    rng: np.random.Generator,
    perc: float = 0.5,
    stop_event: threading.Event | None = None,
):
    problem = w.problem
    try:
        off = DeviceOffloader(problem, w.device)
        w.diagnostics = off.diagnostics
        D = len(pools)
        chunk_buf = problem.empty_batch(M)
        while True:
            # Pre-mark BUSY: with an external idle sampler (the dist tier's
            # communicator thread) marking busy only *after* the pop would
            # open a window where a worker holds a chunk while looking idle.
            # For the self-evaluated allIdle scan this is equivalent to the
            # reference's after-pop transition (`pfsp_multigpu_chpl.chpl:416`).
            states.set_busy(w.wid)
            count = w.pool.locked_pop_back_bulk(m, M, chunk_buf)
            if count > 0:
                if shared is not None:
                    w.best = min(w.best, shared.read())
                bucket = bucket_size(count, m, M)
                snapshot = {k: v[:count].copy() for k, v in chunk_buf.items()}
                dev_result = off.dispatch(snapshot, count, bucket, w.best)
                results = off.collect(dev_result)
                res = problem.generate_children(snapshot, count, results, w.best)
                w.tree += res.tree_inc
                w.sol += res.sol_inc
                if res.best < w.best:
                    w.best = res.best
                    if shared is not None:
                        w.best = shared.publish(w.best)
                w.pool.locked_push_back_bulk(res.children)
                continue
            # -- work stealing (`pfsp_multigpu_chpl.chpl:438-479`) ---------
            stolen = False
            for victim_id in rng.permutation(D):
                if victim_id == w.wid:
                    continue
                victim = pools[victim_id]
                for _ in range(10):  # lock attempts cap, `Pool_par` call sites
                    if victim.try_lock():
                        try:
                            batch = victim.pop_front_bulk_half(m, perc)
                        finally:
                            victim.unlock()
                        if batch is not None:
                            w.pool.locked_push_back_bulk(batch)
                            w.steals += 1
                            stolen = True
                        break
                    time.sleep(0)  # yieldExecution backoff
                if stolen:
                    break
            if stolen:
                states.set_busy(w.wid)
                continue
            # -- termination (`pfsp_multigpu_chpl.chpl:481-495`) -----------
            states.set_idle(w.wid)
            if stop_event is not None:
                # Dist mode: local all-idle is NOT the end — the host may
                # still receive stolen work from another host. Poll until
                # the communicator declares global termination (the
                # two-level scheme, `pfsp_dist_multigpu_chpl.chpl:569-587`).
                if stop_event.is_set():
                    return
                time.sleep(0.0005)
                continue
            if states.all_idle():
                return
            time.sleep(0)
    except BaseException as e:  # surface into the main thread
        w.error = e
        states.set_idle(w.wid)
        states.flag.set()  # unblock everyone; search aborts


def run_workers(
    problem: Problem,
    pool: SoAPool,
    D: int,
    assigned,
    m: int,
    M: int,
    best: int,
    share_bound: bool = True,
    seed: int = 0xB0B,
    perc: float = 0.5,
    comm=None,
):
    """Step 2 of the multi-device tier: partition ``pool`` across D worker
    threads, run the offload/steal/terminate loops, join, and merge leftovers
    back into a fresh global pool. Returns
    ``(leftover_pool, tree2, sol2, best, workers)``. Shared by the
    single-host multi tier and the per-host phase of the distributed tier
    (the reference duplicates this scaffolding between its multi and dist
    mains, SURVEY.md §1 note).

    ``comm`` (dist tier): a host communicator with a
    ``run(pools, states, shared, stop_event)`` method, executed in its own
    thread alongside the workers. It owns global termination: workers then
    poll until ``stop_event`` is set instead of exiting on local all-idle.
    """
    pools = _partition(problem, pool, D)
    leftover = SoAPool(problem.node_fields())
    states = TaskStates(D)
    shared = _SharedBest(best) if share_bound or comm is not None else None
    workers = [_Worker(w, problem, pools[w], assigned[w]) for w in range(D)]
    for w in workers:
        w.best = best
    stop_event = threading.Event() if comm is not None else None
    seeds = np.random.SeedSequence(seed)
    threads = [
        threading.Thread(
            target=_worker_loop,
            args=(w, pools, states, m, M, shared, np.random.default_rng(s),
                  perc, stop_event),
            name=f"tts-worker-{w.wid}",
        )
        for w, s in zip(workers, seeds.spawn(D))
    ]
    comm_thread = None
    if comm is not None:
        comm_thread = threading.Thread(
            target=comm.run, args=(pools, states, shared, stop_event),
            name="tts-host-comm",
        )
        comm_thread.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if comm_thread is not None:
        comm_thread.join()
    for w in workers:
        if w.error is not None:
            raise w.error
    if comm is not None and getattr(comm, "error", None) is not None:
        raise comm.error
    # leftovers back into the global pool (`pfsp_multigpu_chpl.chpl:498-503`)
    for p in pools:
        leftover.push_back_bulk(p.as_batch())
    tree2 = sum(w.tree for w in workers)
    sol2 = sum(w.sol for w in workers)
    best = min([best] + [w.best for w in workers])  # min-reduce (`:518-520`)
    return leftover, tree2, sol2, best, workers


def host_pipeline(
    problem: Problem,
    m: int,
    M: int,
    D: int,
    devices,
    initial_best: int | None = None,
    share_bound: bool = True,
    num_hosts: int = 1,
    host_id: int = 0,
    seed: int = 0xB0B,
    perc: float = 0.5,
    comm=None,
    partition_fn=None,
) -> dict:
    """The full 3-phase pipeline one host runs: warm-up, partitioned
    parallel offload (work stealing + termination), drain.

    With ``num_hosts == 1`` this is the whole multi-GPU tier
    (`pfsp_multigpu_chpl.chpl:312-535`). With H hosts, every host runs the
    identical deterministic warm-up to ``H*D*m`` and takes its stride-H
    slice — the locale-level round-robin partition of the dist tier
    (`pfsp_dist_multigpu_chpl.chpl:339-374`) without communication; host 0
    owns the warm-up counters so the cross-host sum counts them once.
    Returns a dict of local stats for (cross-host) reduction.
    """
    # One thread per device; if D exceeds physical devices, oversubscribe
    # round-robin (the CPU-mesh testing mode, SURVEY.md §4.6).
    assigned = [devices[w % len(devices)] for w in range(D)]

    best = (
        initial_best
        if initial_best is not None
        else getattr(problem, "initial_ub", INF_BOUND)
    )
    pool = SoAPool(problem.node_fields())
    pool.push_back(index_batch(problem.root(), 0))

    t0 = time.perf_counter()

    # -- step 1: warm-up to H*D*m (`nqueens_multigpu_chpl.chpl:173`,
    # dist target `pfsp_dist_multigpu_chpl.chpl:339-345`) ------------------
    tree1, sol1, best = warmup(problem, pool, best, num_hosts * D * m)
    if num_hosts > 1:
        warm = pool.as_batch()
        pool = SoAPool(problem.node_fields())
        if partition_fn is None:
            pool.push_back_bulk(
                {k: v[host_id::num_hosts] for k, v in warm.items()}
            )
        else:
            # Test/experiment hook: arbitrary (possibly skewed) host
            # partitions, e.g. to exercise inter-host stealing from a host
            # that starts empty.
            pool.push_back_bulk(partition_fn(warm, host_id, num_hosts))
        if host_id != 0:
            tree1 = sol1 = 0
    t1 = time.perf_counter()

    # -- step 2: partitioned parallel offload ------------------------------
    pool, tree2, sol2, best, workers = run_workers(
        problem, pool, D, assigned, m, M, best, share_bound, seed=seed,
        perc=perc, comm=comm,
    )
    t2 = time.perf_counter()

    # -- step 3: drain (`pfsp_multigpu_chpl.chpl:529-535`) -----------------
    tree3, sol3, best = drain(problem, pool, best)
    t3 = time.perf_counter()

    diag = Diagnostics(
        kernel_launches=sum(w.diagnostics.kernel_launches for w in workers),
        host_to_device=sum(w.diagnostics.host_to_device for w in workers),
        device_to_host=sum(w.diagnostics.device_to_host for w in workers),
    )
    return {
        "tree": tree1 + tree2 + tree3,
        "sol": sol1 + sol2 + sol3,
        "best": best,
        "steals": sum(w.steals for w in workers),
        "phases": [
            PhaseStats(t1 - t0, tree1, sol1),
            PhaseStats(t2 - t1, tree2, sol2),
            PhaseStats(t3 - t2, tree3, sol3),
        ],
        "elapsed": t3 - t0,
        "per_worker_tree": [w.tree for w in workers],
        "diag": diag,
    }


def multidevice_search(
    problem: Problem,
    m: int = 25,
    M: int = 50000,
    D: int | None = None,
    devices=None,
    initial_best: int | None = None,
    share_bound: bool = True,
    perc: float = 0.5,
) -> SearchResult:
    import jax

    if devices is None:
        devices = jax.devices()
    if D is None:
        D = len(devices)
    local = host_pipeline(
        problem, m, M, D, devices, initial_best, share_bound, perc=perc
    )
    return SearchResult(
        explored_tree=local["tree"],
        explored_sol=local["sol"],
        best=local["best"],
        elapsed=local["elapsed"],
        phases=local["phases"],
        diagnostics=local["diag"],
        per_worker_tree=local["per_worker_tree"],
        steals=local["steals"],
    )
