"""Topology descriptors, link classes, and the hierarchical steal policy.

The reference's distributed tier applies ONE flat steal policy to every
link — the same period and the same block cap whether the victim sits on
the same host, one ICI hop away, or across a DCN boundary. The paper's own
scaling story (and arXiv:0809.3285 / arXiv:1904.06825, PAPERS.md) says the
profitable steal period and steal *size* differ by an order of magnitude
between those links. This module makes work distribution topology-aware:

  * **Link classes.** Every worker pair classifies as ``local`` (same
    host, device<->device through host RAM), ``ici`` (different host,
    same pod/slice), or ``dcn`` (across pods). The pod map comes from
    ``TTS_PODS`` (virtual hosts / explicit deployments) or from jax's
    per-process slice index allgathered once at startup (real pods);
    with neither, every host shares pod 0 and all inter-host links are
    ``ici``.
  * **Two-level hierarchy** (``TTS_STEAL=hier``): the lockstep exchange
    round stays global (the matching must be identical on every host —
    no handshake), but near (ici) donor->needy pairs are matched **every**
    round with a small quantum, while far (dcn) pairs are matched only
    every ``far_every``-th round — and only for needy hosts the near level
    failed to feed — with a **bulk** quantum sized so the measured
    transfer cost (latency + bytes/bandwidth fit from COSTMODEL.json,
    obs/costmodel.py) amortizes below a target fraction of the evaluation
    time the block buys. ``TTS_STEAL=flat`` (the default) keeps today's
    single-level matching byte/behavior-identical.
  * **Simulated links.** ``TTS_SIM_LAT_ICI`` / ``TTS_SIM_LAT_DCN``
    (seconds) inject a one-way latency on the donation path of the
    matching link class — the virtual-host analogue of the simulated-
    latency harness in tests/test_pipeline.py. Unset means zero sleeps:
    production behavior is untouched.

The knob is host-side only — no compiled program ever sees it (pinned by
the ``steal-knob-inert`` contract below, ``tts check``).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field

from ..analysis.contracts import contract

#: Link classes, cheapest first (the victim-selection escalation order).
LINK_LOCAL = "local"
LINK_ICI = "ici"
LINK_DCN = "dcn"
LINK_CLASSES = (LINK_LOCAL, LINK_ICI, LINK_DCN)

#: Fixed fallbacks when no cost-model fit exists (documented in
#: docs/PARALLELISM.md): far (dcn) rounds fire every 4th near round, and
#: the far quantum is 8x the near cap — infrequent bulk donations vs the
#: near level's frequent small blocks.
FAR_EVERY_DEFAULT = 4
FAR_QUANTUM_MULT = 8
FAR_EVERY_MAX = 32


def steal_mode() -> str:
    """The ``TTS_STEAL`` knob: ``flat`` (default, today's single-level
    policy) or ``hier`` (two-level topology-aware matching). Unrecognized
    values fall back to flat — a typo must never change semantics."""
    raw = (os.environ.get("TTS_STEAL", "") or "").strip().lower()
    return "hier" if raw == "hier" else "flat"


def _parse_pods(raw: str, num_hosts: int) -> list[int] | None:
    """``TTS_PODS`` grammar: an integer K splits hosts into K contiguous
    equal pods (``TTS_PODS=2`` with H=4 -> [0,0,1,1]); a comma list gives
    the pod id per host (``TTS_PODS=0,0,1,1``). None on any mismatch."""
    raw = (raw or "").strip()
    if not raw:
        return None
    try:
        if "," in raw:
            pods = [int(x) for x in raw.split(",")]
            return pods if len(pods) == num_hosts else None
        k = int(raw)
        if k <= 0:
            return None
        per = max(1, (num_hosts + k - 1) // k)
        return [min(h // per, k - 1) for h in range(num_hosts)]
    except ValueError:
        return None


class Topology:
    """Host->pod map + pairwise link classification for H hosts."""

    def __init__(self, num_hosts: int, pod_of: list[int] | None = None):
        self.num_hosts = num_hosts
        self.pod_of = list(pod_of) if pod_of else [0] * num_hosts

    @classmethod
    def detect(cls, num_hosts: int, slice_index: int | None = None,
               allgather=None) -> "Topology":
        """Build the pod map: ``TTS_PODS`` wins (virtual hosts, explicit
        deployments); else, when the caller supplies its jax slice index
        and an allgather, the real multi-slice map is assembled once over
        the collectives; else one pod."""
        pods = _parse_pods(os.environ.get("TTS_PODS", ""), num_hosts)
        if pods is None and slice_index is not None and allgather is not None:
            gathered = allgather(int(slice_index))
            if len(gathered) == num_hosts:
                pods = [int(p) for p in gathered]
        return cls(num_hosts, pods)

    def link_class(self, a: int, b: int) -> str:
        """Link class between hosts ``a`` and ``b`` (ISSUE taxonomy:
        intra-host device<->device, intra-pod ICI, inter-pod DCN)."""
        if a == b:
            return LINK_LOCAL
        return LINK_ICI if self.pod_of[a] == self.pod_of[b] else LINK_DCN

    @property
    def num_pods(self) -> int:
        return len(set(self.pod_of))

    def describe(self) -> dict:
        return {"num_hosts": self.num_hosts, "pods": list(self.pod_of)}


class SimLinks:
    """Env-armed one-way link-latency injection for the simulated-latency
    harness (CPU A/B at virtual-host scale). A sleep fires on the donation
    path of the matching link class only when the knob is set — unset means
    ``armed`` is False and callers skip the call sites entirely."""

    def __init__(self):
        self.lat_s = {}
        for link, knob in ((LINK_ICI, "TTS_SIM_LAT_ICI"),
                           (LINK_DCN, "TTS_SIM_LAT_DCN")):
            try:
                v = float(os.environ.get(knob, "") or 0.0)
            except ValueError:
                v = 0.0
            if v > 0:
                self.lat_s[link] = v

    @property
    def armed(self) -> bool:
        return bool(self.lat_s)

    def sleep(self, link: str) -> None:
        lat = self.lat_s.get(link, 0.0)
        if lat > 0:
            time.sleep(lat)


@dataclass
class LevelSpec:
    """Resolved parameters for one hierarchy level."""

    link: str        # "ici" | "dcn"
    level: int       # 1 = near, 2 = far
    every: int       # match this link class every `every`-th exchange round
    quantum: int     # donation block cap (nodes)
    period_s: float  # resolved steal period (every * base interval)
    source: str      # "fixed" or the COSTMODEL.json profile key


@dataclass
class StealPolicy:
    """The resolved steal policy threaded through dist/dist_mesh/multi.

    ``flat`` mode carries only the legacy parameters (cap = M every round
    on every link) so the communicators' flat paths stay byte/behavior-
    identical; ``hier`` mode adds per-level periods and quanta plus the
    near-first/escalate-far matching below."""

    mode: str
    topology: Topology
    m: int
    cap: int                       # legacy flat cap (M / D*M)
    interval_s: float
    levels: dict = field(default_factory=dict)  # link -> LevelSpec
    sim: SimLinks = field(default_factory=SimLinks)

    @property
    def hier(self) -> bool:
        return self.mode == "hier"

    def link(self, a: int, b: int) -> str:
        return self.topology.link_class(a, b)

    def cap_for(self, link: str) -> int:
        if not self.hier:
            return self.cap
        spec = self.levels.get(link)
        return spec.quantum if spec is not None else self.cap

    def level_of(self, link: str) -> int:
        spec = self.levels.get(link)
        return spec.level if spec is not None else (0 if link == LINK_LOCAL
                                                    else 1)

    def match(self, donors: list[int], needy: list[int], round_no: int,
              sizes: list[int] | None = None) -> list[tuple[int, int]]:
        """Deterministic two-level matching (identical inputs on every
        host -> identical pairs, no handshake — the flat policy's key
        property, kept). Near (ici) pairs every round; far (dcn) pairs
        only on far rounds and only for needy hosts the near level left
        unmatched — victim selection prefers the cheapest link class and
        escalates outward only after local misses.

        ``sizes`` (the allgathered per-host donatable sizes, when the
        caller has them) arms the far **amortization floor**: a far
        donation pays the full link latency whatever it carries, so a
        donor qualifies for a far pair only when its pool can fill a
        meaningful fraction of the bulk quantum — shipping end-of-run
        scraps across the expensive link is exactly the waste the
        two-level policy exists to avoid."""
        far_spec = self.levels.get(LINK_DCN)
        far_round = far_spec is None or round_no % max(1, far_spec.every) == 0
        far_floor = 0
        if far_spec is not None and sizes is not None:
            far_floor = max(4 * self.m, far_spec.quantum // 2)
        pairs: list[tuple[int, int]] = []
        free = list(donors)
        unmatched = []
        for r in needy:
            near = next((d for d in free if self.link(d, r) == LINK_ICI), None)
            if near is not None:
                pairs.append((near, r))
                free.remove(near)
            else:
                unmatched.append(r)
        if far_round:
            for r in unmatched:
                far = next(
                    (d for d in free
                     if self.link(d, r) == LINK_DCN
                     and (sizes is None or sizes[d] >= far_floor)),
                    None,
                )
                if far is not None:
                    pairs.append((far, r))
                    free.remove(far)
        return pairs

    def describe(self) -> dict:
        """The surfaced policy (SearchResult.steal_policy, ``--json``,
        banner): mode + per-link-class resolved periods and quanta."""
        out = {"mode": self.mode, "pods": list(self.topology.pod_of)}
        if self.hier:
            out["levels"] = {
                link: {
                    "level": s.level,
                    "every": s.every,
                    "period_s": round(s.period_s, 4),
                    "quantum": s.quantum,
                    "source": s.source,
                }
                for link, s in sorted(self.levels.items())
            }
        else:
            out["levels"] = {
                "any": {"level": 1, "every": 1,
                        "period_s": round(self.interval_s, 4),
                        "quantum": self.cap, "source": "fixed"},
            }
        if self.sim.armed:
            out["sim_lat_s"] = dict(sorted(self.sim.lat_s.items()))
        return out


def bytes_per_node(problem) -> int | None:
    """Per-node payload size from the SoA schema — converts the cost
    model's per-byte donate slope into per-node terms for quantum sizing."""
    try:
        import numpy as np

        total = 0
        for _, (shape, dtype) in problem.node_fields().items():
            n = 1
            for d in shape:
                n *= int(d)
            total += n * np.dtype(dtype).itemsize
        return total or None
    except Exception:
        return None


def resolve_policy(problem, topology: Topology, *, m: int, cap: int,
                   interval_s: float, mode: str | None = None,
                   backend: str = "cpu", topo_str: str = "",
                   ) -> StealPolicy:
    """Build the policy for one search: flat unless ``TTS_STEAL=hier``.

    Hier levels resolve from the measured COSTMODEL.json fits when
    ``TTS_COSTMODEL`` is armed (obs/costmodel.py ``steal_quantum`` /
    ``steal_every``); the documented fixed fallbacks otherwise. Resolution
    uses only env + the profile file, so every host resolves the same
    policy without communication."""
    from ..obs import costmodel as cm

    mode = mode or steal_mode()
    policy = StealPolicy(mode=mode, topology=topology, m=m, cap=cap,
                         interval_s=interval_s)
    if mode != "hier":
        return policy
    entry, src = None, "fixed"
    path = cm.costmodel_path()
    if path:
        prof = cm.load(path)
        if prof:
            hit = cm.lookup(prof, backend, topo_str, cm.shape_class(problem))
            if hit is not None:
                src, entry = hit
    bpn = bytes_per_node(problem)
    near_q = cap
    far_q = min(cap * FAR_QUANTUM_MULT, max(cap, 2 ** 20))
    far_every = FAR_EVERY_DEFAULT
    near_src = far_src = "fixed"
    if entry is not None:
        q = cm.steal_quantum(entry, LINK_ICI, m=m, bytes_per_node=bpn,
                             cap=near_q * FAR_QUANTUM_MULT)
        if q is not None:
            near_q, near_src = q, src
        q = cm.steal_quantum(entry, LINK_DCN, m=m, bytes_per_node=bpn,
                             cap=far_q)
        if q is not None:
            far_q, far_src = max(q, near_q), src
        ev_ = cm.steal_every(entry, interval_s, cap=FAR_EVERY_MAX)
        if ev_ is not None:
            far_every = ev_
    policy.levels = {
        LINK_ICI: LevelSpec(LINK_ICI, 1, 1, near_q, interval_s, near_src),
        LINK_DCN: LevelSpec(LINK_DCN, 2, far_every, far_q,
                            interval_s * far_every, far_src),
    }
    return policy


# -- tts check contract -------------------------------------------------------
# TTS_STEAL is a pure host-side scheduling knob: the traced resident
# program must be byte-identical across off/flat/hier (the knob-inert
# family — engine/pipeline.py's TTS_PIPELINE precedent).


@contract(
    "steal-knob-inert",
    claim="TTS_STEAL never reaches compiled programs: flat and hier trace "
          "byte-identical jaxprs vs the unset baseline",
    artifact="variants",
)
def _contract_steal_inert(art, cell):
    if not art.has("off", "steal-flat", "steal-hier"):
        return []
    if art.text("off") == art.text("steal-flat") == art.text("steal-hier"):
        return []
    return [
        "TTS_STEAL leaked into the compiled step (host-side scheduling "
        "knob must be program-invisible)"
    ]
