"""Distributed multi-host tier.

Reproduces the reference's distributed tier semantics
(`pfsp_dist_multigpu_chpl.chpl:313-647`; MPI baseline:
`pfsp_dist_multigpu_cuda.c:330-816`) on jax's multi-process model:

  * **warm-up**: the reference warms up on locale 0 and scatters
    (`pfsp_dist_multigpu_chpl.chpl:339-374`). TPU hosts share no memory, so
    instead every host runs the *identical deterministic* warm-up to
    ``H * D * m`` nodes and takes its stride-H slice — zero communication,
    byte-identical partitions (replicate-and-slice; the warm-up is pure
    host compute, seconds at most).
  * **per-host step 2**: the multi-device worker runtime (partition, work
    stealing, idle-scan termination) over the host's local devices — exactly
    the inner ``coforall gpuID`` tier (`pfsp_dist_multigpu_chpl.chpl:406-470`).
  * **no inter-host stealing in v1** — the semantics of the reference's MPI
    baseline, which only reconciles at the end
    (`pfsp_dist_multigpu_cuda.c:570-623`, SURVEY.md §2.5). (The Chapel tier's
    PGAS remote steals have no ICI analogue; host-RPC stealing is the
    planned extension.)
  * **step 3**: each host drains its own leftovers (the MPI baseline gathers
    them to rank 0 and drains there, `pfsp_dist_multigpu_cuda.c:741-790`;
    local drains produce the same totals without the gather).
  * **final reductions**: tree/sol summed, best min-reduced, time max-reduced
    across hosts — `MPI_Reduce` equivalents (`pfsp_dist_multigpu_cuda.c:680-694`)
    over jax collectives.

Communication is abstracted behind a tiny ``Collectives`` interface so the
same driver runs: single-process (``LocalCollectives``), N virtual hosts in
threads for testing (``ThreadCollectives``, the oversubscribed-locale
smoke-test mode of SURVEY.md §4.6), and real multi-host pods
(``JaxCollectives`` over jax.distributed / DCN).
"""

from __future__ import annotations

import threading

import numpy as np

from ..engine.results import SearchResult
from ..problems.base import Problem
from .multidevice import host_pipeline


class LocalCollectives:
    """H=1 degenerate collectives."""

    num_hosts = 1
    host_id = 0

    def allreduce_sum(self, value: int) -> int:
        return value

    def allreduce_min(self, value: int) -> int:
        return value

    def allreduce_max(self, value) -> float:
        return value


class ThreadCollectives:
    """In-process collectives for H virtual hosts running in threads (the
    multi-host smoke-test mode; cf. the reference's oversubscribed UDP
    locales, `g5k_dist_multigpu_nvidia.sh:33`)."""

    def __init__(self, num_hosts: int):
        self.num_hosts = num_hosts
        self._barrier = threading.Barrier(num_hosts)
        self._lock = threading.Lock()
        self._values: list = [None] * num_hosts
        self._local = threading.local()

    def bind(self, host_id: int):
        """Each participating thread binds its host id once."""
        self._local.host_id = host_id
        return self

    @property
    def host_id(self) -> int:
        return self._local.host_id

    def _exchange(self, value):
        self._values[self.host_id] = value
        self._barrier.wait()
        vals = list(self._values)
        self._barrier.wait()
        return vals

    def allreduce_sum(self, value):
        return sum(self._exchange(value))

    def allreduce_min(self, value):
        return min(self._exchange(value))

    def allreduce_max(self, value):
        return max(self._exchange(value))


class JaxCollectives:
    """Real multi-host collectives over jax.distributed (DCN). The launcher
    must have called ``jax.distributed.initialize``; every host participates
    in every call (the reductions happen only at start/end, mirroring the
    MPI baseline's join-point-only communication, SURVEY.md §2.5)."""

    def __init__(self):
        import jax

        self.num_hosts = jax.process_count()
        self.host_id = jax.process_index()

    def _allgather(self, value):
        from jax.experimental import multihost_utils

        return np.asarray(
            multihost_utils.process_allgather(np.asarray([value]))
        ).reshape(-1)

    def allreduce_sum(self, value):
        return type(value)(self._allgather(value).sum())

    def allreduce_min(self, value):
        return type(value)(self._allgather(value).min())

    def allreduce_max(self, value):
        return type(value)(self._allgather(value).max())


def _host_search(
    problem: Problem,
    m: int,
    M: int,
    D: int,
    devices,
    collectives,
    initial_best: int | None,
    share_bound: bool,
    seed_base: int = 0xD157,
):
    """One host's full pipeline (warm-up + stride slice, local multi-device
    runtime, local drain); returns its local stats for reduction. Delegates
    to the shared ``host_pipeline`` (SURVEY.md §1: the reference duplicates
    this scaffolding between its multi and dist mains — we don't)."""
    return host_pipeline(
        problem, m, M, D, devices,
        initial_best=initial_best, share_bound=share_bound,
        num_hosts=collectives.num_hosts, host_id=collectives.host_id,
        seed=seed_base + collectives.host_id,
    )


def _reduce(local: dict, collectives) -> SearchResult:
    """`MPI_Reduce` equivalents: sum tree/sol, min best, max time
    (`pfsp_dist_multigpu_cuda.c:680-694`)."""
    tree = collectives.allreduce_sum(local["tree"])
    sol = collectives.allreduce_sum(local["sol"])
    best = collectives.allreduce_min(local["best"])
    elapsed = collectives.allreduce_max(local["elapsed"])
    return SearchResult(
        explored_tree=tree,
        explored_sol=sol,
        best=best,
        elapsed=elapsed,
        phases=local["phases"],
        diagnostics=local["diag"],
        per_worker_tree=local["per_worker_tree"],
    )


def dist_search(
    problem: Problem,
    m: int = 25,
    M: int = 50000,
    D: int | None = None,
    num_hosts: int | None = None,
    devices=None,
    initial_best: int | None = None,
    share_bound: bool = True,
) -> SearchResult:
    """Distributed search entry point.

    * Under ``jax.distributed`` (process_count > 1): this process runs its
      host's share; reductions go over DCN. Returns the global result.
    * Single process with ``num_hosts=H > 1``: runs H virtual hosts in
      threads over disjoint local-device groups (testing mode).
    * Single process, ``num_hosts`` unset/1: degenerates to one host.
    """
    import jax

    if jax.process_count() > 1:
        coll = JaxCollectives()
        local_devices = jax.local_devices() if devices is None else devices
        if D is None:
            D = len(local_devices)
        local = _host_search(
            problem, m, M, D, local_devices, coll, initial_best, share_bound
        )
        return _reduce(local, coll)

    all_devices = jax.devices() if devices is None else devices
    H = num_hosts or 1
    if H == 1:
        coll = LocalCollectives()
        if D is None:
            D = len(all_devices)
        local = _host_search(
            problem, m, M, D, all_devices, coll, initial_best, share_bound
        )
        return _reduce(local, coll)

    # Virtual hosts: split local devices into H disjoint groups.
    if H > len(all_devices):
        raise ValueError(
            f"num_hosts={H} exceeds available devices ({len(all_devices)}); "
            "virtual hosts need at least one device each"
        )
    groups = [all_devices[h::H] for h in range(H)]
    if D is None:
        D = max(1, min(len(g) for g in groups))
    coll = ThreadCollectives(H)
    results: list = [None] * H
    errors: list = [None] * H

    def host_main(h: int):
        try:
            results[h] = _reduce(
                _host_search(
                    problem, m, M, D, groups[h], coll.bind(h),
                    initial_best, share_bound,
                ),
                coll,
            )
        except BaseException as e:  # propagate after join
            errors[h] = e
            try:
                coll._barrier.abort()
            except Exception:
                pass

    threads = [
        threading.Thread(target=host_main, args=(h,), name=f"tts-host-{h}")
        for h in range(H)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for e in errors:
        if e is not None:
            raise e
    # All hosts computed identical global reductions; merge per-host extras.
    global_res = results[0]
    global_res.per_worker_tree = [
        t for r in results for t in r.per_worker_tree
    ]
    return global_res
