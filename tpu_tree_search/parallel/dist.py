"""Distributed multi-host tier.

Reproduces the reference's distributed tier semantics
(`pfsp_dist_multigpu_chpl.chpl:313-647`; MPI baseline:
`pfsp_dist_multigpu_cuda.c:330-816`) on jax's multi-process model:

  * **warm-up**: the reference warms up on locale 0 and scatters
    (`pfsp_dist_multigpu_chpl.chpl:339-374`). TPU hosts share no memory, so
    instead every host runs the *identical deterministic* warm-up to
    ``H * D * m`` nodes and takes its stride-H slice — zero communication,
    byte-identical partitions (replicate-and-slice; the warm-up is pure
    host compute, seconds at most).
  * **per-host step 2**: the multi-device worker runtime (partition, work
    stealing, idle-scan termination) over the host's local devices — exactly
    the inner ``coforall gpuID`` tier (`pfsp_dist_multigpu_chpl.chpl:406-470`).
  * **no inter-host stealing in v1** — the semantics of the reference's MPI
    baseline, which only reconciles at the end
    (`pfsp_dist_multigpu_cuda.c:570-623`, SURVEY.md §2.5). (The Chapel tier's
    PGAS remote steals have no ICI analogue; host-RPC stealing is the
    planned extension.)
  * **step 3**: each host drains its own leftovers (the MPI baseline gathers
    them to rank 0 and drains there, `pfsp_dist_multigpu_cuda.c:741-790`;
    local drains produce the same totals without the gather).
  * **final reductions**: tree/sol summed, best min-reduced, time max-reduced
    across hosts — `MPI_Reduce` equivalents (`pfsp_dist_multigpu_cuda.c:680-694`)
    over jax collectives.

Communication is abstracted behind a tiny ``Collectives`` interface so the
same driver runs: single-process (``LocalCollectives``), N virtual hosts in
threads for testing (``ThreadCollectives``, the oversubscribed-locale
smoke-test mode of SURVEY.md §4.6), and real multi-host pods
(``JaxCollectives`` over jax.distributed / DCN).
"""

from __future__ import annotations

import threading

import numpy as np

from ..engine.results import SearchResult
from ..obs import events as ev
from ..obs import flightrec as fr
from ..pool import ParallelSoAPool
from ..problems.base import Problem
from .multidevice import host_pipeline


def secondary_error(e: BaseException) -> bool:
    """True for errors a virtual host raises only BECAUSE a peer aborted
    the shared barrier (BrokenBarrierError inside a collective, or kv_get's
    TimeoutError("... (peer aborted)")) — never the root cause."""
    return isinstance(e, threading.BrokenBarrierError) or (
        isinstance(e, TimeoutError) and "peer aborted" in str(e)
    )


class LocalCollectives:
    """H=1 degenerate collectives."""

    num_hosts = 1
    host_id = 0

    def allreduce_sum(self, value: int) -> int:
        return value

    def allreduce_min(self, value: int) -> int:
        return value

    def allreduce_max(self, value) -> float:
        return value

    def allgather_obj(self, value) -> list:
        return [value]

    def kv_set(self, key: str, value: bytes) -> None:
        self._kv = getattr(self, "_kv", {})
        self._kv[key] = value

    def kv_get(self, key: str, timeout_s: float) -> bytes:
        try:
            return getattr(self, "_kv", {}).pop(key)
        except KeyError:
            raise TimeoutError(f"kv_get({key!r}): no such key") from None


class ThreadCollectives:
    """In-process collectives for H virtual hosts running in threads (the
    multi-host smoke-test mode; cf. the reference's oversubscribed UDP
    locales, `g5k_dist_multigpu_nvidia.sh:33`)."""

    def __init__(self, num_hosts: int):
        self.num_hosts = num_hosts
        self._barrier = threading.Barrier(num_hosts)
        self._lock = threading.Lock()
        self._values: list = [None] * num_hosts
        self._local = threading.local()
        self._kv: dict = {}  # guarded-by: _kv_cond
        self._kv_cond = threading.Condition()

    def bind(self, host_id: int):
        """Each participating thread binds its host id once."""
        self._local.host_id = host_id
        return self

    @property
    def host_id(self) -> int:
        return self._local.host_id

    def _exchange(self, value):
        self._values[self.host_id] = value
        self._barrier.wait()
        vals = list(self._values)
        self._barrier.wait()
        return vals

    def allreduce_sum(self, value):
        return sum(self._exchange(value))

    def allreduce_min(self, value):
        return min(self._exchange(value))

    def allreduce_max(self, value):
        return max(self._exchange(value))

    def allgather_obj(self, value) -> list:
        return self._exchange(value)

    def kv_set(self, key: str, value: bytes) -> None:
        with self._kv_cond:
            self._kv[key] = value
            self._kv_cond.notify_all()

    def kv_get(self, key: str, timeout_s: float) -> bytes:
        import time as _time

        deadline = _time.monotonic() + timeout_s
        with self._kv_cond:
            while key not in self._kv:
                # Short wait slices so an aborted peer (broken barrier)
                # is noticed promptly even though aborts don't notify us.
                if self._barrier.broken or _time.monotonic() > deadline:
                    raise TimeoutError(
                        f"kv_get({key!r}) timed out"
                        + (" (peer aborted)" if self._barrier.broken else "")
                    )
                self._kv_cond.wait(timeout=0.05)
            return self._kv.pop(key)


class JaxCollectives:
    """Real multi-host collectives over jax.distributed (DCN). The launcher
    must have called ``jax.distributed.initialize``; every host participates
    in every call (the reductions happen only at start/end, mirroring the
    MPI baseline's join-point-only communication, SURVEY.md §2.5).

    The whole control plane rides the coordination service (the same
    DCN-backed KV store jax.distributed itself uses for barriers) rather
    than XLA array collectives: control tuples are a few hundred bytes at
    exchange boundaries, where a device dispatch per round would cost more
    than it moves — and a dead peer surfaces as a bounded-timeout error
    here (fail-stop with a root cause) instead of a hung collective."""

    #: Bounded wait for any single control-plane step (a peer's round blob,
    #: the cleanup barrier): seconds here mean a dead or wedged peer, so
    #: the exchange raises — fail-stop — instead of hanging the search.
    AG_TIMEOUT_S = 120.0

    def __init__(self):
        import jax

        self.num_hosts = jax.process_count()
        self.host_id = jax.process_index()
        self._round = 0  # per-call key uniqueness (all hosts count together)

    def allreduce_sum(self, value):
        return type(value)(sum(self.allgather_obj(value)))

    def allreduce_min(self, value):
        return type(value)(min(self.allgather_obj(value)))

    def allreduce_max(self, value):
        return type(value)(max(self.allgather_obj(value)))

    def allgather_obj(self, value) -> list:
        """RAGGED arbitrary-object allgather: each host posts its pickled
        blob once at a round-unique key and every peer reads exactly the
        bytes each sender wrote — the exchange payload scales with the
        actual sizes (sum of the blobs per receiver), not H x max-length
        as the old padded array-allgather did. Only small control tuples
        travel this way — node payloads go point-to-point via ``kv_set`` /
        ``kv_get``, never all-to-all. Cleanup: a blob has H-1 readers, so
        the sender may only delete its key after the round's barrier
        proves every peer has read it (kv_get's delete-after-first-read
        would lose it for the rest)."""
        import pickle

        if self.num_hosts == 1:
            return [value]
        client = self._client()
        r = self._round
        self._round += 1
        me = self.host_id
        tmo_ms = int(self.AG_TIMEOUT_S * 1000)
        client.key_value_set_bytes(f"tts/agobj/{r}/{me}", pickle.dumps(value))
        out = []
        for h in range(self.num_hosts):
            if h == me:
                out.append(value)
            else:
                out.append(pickle.loads(
                    client.blocking_key_value_get_bytes(
                        f"tts/agobj/{r}/{h}", tmo_ms
                    )
                ))
        client.wait_at_barrier(f"tts/agobj/{r}/done", tmo_ms)
        client.key_value_delete(f"tts/agobj/{r}/{me}")
        return out

    @staticmethod
    def _client():
        from jax._src import distributed

        client = distributed.global_state.client
        if client is None:
            raise RuntimeError(
                "jax.distributed KV store unavailable (initialize() not "
                "called?)"
            )
        return client

    def kv_set(self, key: str, value: bytes) -> None:
        """Point-to-point donation delivery over the jax.distributed
        coordination service's KV store — the DCN analogue of the CUDA
        baseline's point-to-point steal (`Pool_ext.c:138-151`); non-receivers
        never see the payload (vs. the broadcast allgather)."""
        self._client().key_value_set_bytes(key, value, allow_overwrite=True)

    def kv_get(self, key: str, timeout_s: float) -> bytes:
        client = self._client()
        data = client.blocking_key_value_get_bytes(
            key, int(timeout_s * 1000)
        )
        try:
            client.key_value_delete(key)
        except Exception:
            pass  # cleanup is best-effort; keys are round-unique
        return data


class _HostComm:
    """Per-host communicator: periodic cross-host incumbent exchange,
    host-mediated work stealing, and two-level termination.

    The Chapel reference steals across locales with remote CAS on the
    victim's pool lock (`pfsp_dist_multigpu_chpl.chpl:520-567`) — TPU hosts
    share no memory, so the steal is host-mediated (SURVEY.md §2.5): each
    host runs this loop in a thread next to its workers, and every
    ``interval_s`` all hosts meet in a bulk-synchronous exchange round:

      1. allgather ``(pool_size, best, all_workers_idle)``;
      2. every host adopts the global min incumbent (the periodic UB
         all-reduce the reference lacks, BASELINE.json north star);
      3. rich hosts (size >= 2m) are deterministically matched to starving
         idle hosts (same gathered data on every host -> same matching, no
         handshake); each donor locks its fullest local pool and pops half
         its *front* (`Pool_par.chpl:180-191` policy) **capped at M nodes**
         (the mesh tier's bounded-donation policy,
         `resident_mesh.py` diffusion cap), and delivers the block
         *point-to-point* through the collectives' KV channel — only the
         matched receiver ever sees the payload (the CUDA baseline's
         point-to-point steal semantics, `Pool_ext.c:138-151`, vs. a
         broadcast);
      4. two consecutive rounds with all hosts idle, no donations, and only
         drain-sized leftovers end the loop everywhere at once (two-level
         termination, `pfsp_dist_multigpu_chpl.chpl:569-587`; the second
         round re-samples pool sizes so a momentarily-between-polls worker
         can't divert poppable work to the serial host drain); local
         workers then exit via ``stop_event`` and the per-host drain picks
         up any sub-chunk remainder, so no work is ever lost.

    When every host is busy and none is needy, the exchange cadence backs
    off geometrically (up to 16x ``interval_s``) and resets the moment any
    host reports need — a balanced run pays almost no collective overhead.

    Under ``TTS_STEAL=hier`` (parallel/topology.py) the matching in step 3
    becomes two-level: near (intra-pod ICI) donor->needy pairs every
    round with the near quantum, far (inter-pod DCN) pairs only every
    ``far_every``-th round — and only for needy hosts the near level
    could not feed — with the bulk far quantum. The round counter
    advances in lockstep, so the level schedule is identical on every
    host and the flat policy's no-handshake property is preserved.
    ``TTS_STEAL=flat`` (default) keeps the single-level matching above
    byte/behavior-identical.
    """

    #: kv_get wait for a matched donation (donor is alive and popping from
    #: a local pool; seconds would indicate a dead peer -> fail-stop).
    KV_TIMEOUT_S = 120.0
    BACKOFF_MAX = 16  # cadence back-off cap (x interval_s)

    def __init__(self, collectives, m: int, perc: float = 0.5,
                 interval_s: float = 0.02, M: int = 50000,
                 ckpt_interval_s: float = 60.0, policy=None):
        from .topology import StealPolicy, Topology

        self.coll = collectives
        # Captured here (construction happens on the bound host thread):
        # ThreadCollectives.host_id is thread-local and the communicator
        # runs in its own thread, which re-binds with this value.
        self.me = collectives.host_id
        self.m = m
        self.M = M
        self.perc = perc
        self.interval_s = interval_s
        self.policy = policy or StealPolicy(
            mode="flat", topology=Topology(collectives.num_hosts), m=m,
            cap=M, interval_s=interval_s,
        )
        self.rounds = 0
        self.blocks_sent = 0
        self.blocks_received = 0
        self.nodes_sent = 0
        self.nodes_received = 0
        self.error: BaseException | None = None
        self._inflight = None  # popped-but-undelivered donation block
        # Checkpointing (set by run_workers when --checkpoint is active):
        # host 0's clock decides WHEN; the decision rides the round's
        # control tuple so every host snapshots in the same lockstep round
        # — donations complete within a round, so no node can straddle the
        # cut and the union of the per-host files is the exact frontier.
        self.ckpt_mgr = None
        self.ckpt_interval_s = ckpt_interval_s
        self._ckpt_last = None
        # Cut identity: host 0 proposes "<uuid>:<round>" in the round's
        # control tuple and every host stamps that exact string into its
        # per-host file, so resume can prove all files belong to the same
        # lockstep cut of the same run (stale files from a prior run with
        # the same host count, or files from two different cuts after a
        # mid-commit crash, must be refused — they describe incoherent
        # frontiers).
        import uuid as _uuid

        self._run_uuid = _uuid.uuid4().hex[:12]

    def _donate_from(self, pools: list[ParallelSoAPool], cap: int | None = None):
        """Locked front-steal from the fullest local pool (on behalf of a
        remote host); None when no pool can spare a block. Blocks are capped
        (M nodes flat; the link-class quantum under hier) so a huge pool
        never ships an unbounded payload over DCN (the reference steals
        perc-of-pool uncapped, `Pool_ext.c:138-151`; the mesh tier here
        caps donations — same policy)."""
        # (No waiver needed: guarded-by does not descend into lambda
        # bodies, so the advisory racy read in the key fn is out of its
        # scope — the pop below re-checks size under try_lock anyway.)
        victim = max(pools, key=lambda p: p.size)
        # tts-lint: waive guarded-by -- advisory racy size read; pop_front_bulk_half re-checks the 2m threshold under the lock
        if victim.size < 2 * self.m:
            return None
        if victim.try_lock():
            try:
                return victim.pop_front_bulk_half(
                    self.m, self.perc, cap=self.M if cap is None else cap
                )
            finally:
                victim.unlock()
        return None

    def run(self, pools: list[ParallelSoAPool], states, shared, stop_event):
        bind = getattr(self.coll, "bind", None)
        if bind is not None:
            bind(self.me)
        try:
            self._loop(pools, states, shared, stop_event)
        except BaseException as e:  # never leave workers polling forever
            self.error = e
            stop_event.set()
            # A block popped for donation but not delivered must not be
            # lost — requeue it locally (counts stay exact; the search
            # just keeps the work).
            if self._inflight is not None:
                pools[0].locked_push_back_bulk(self._inflight)
                self._inflight = None
            # ThreadCollectives: wake peers blocked in the barrier. Real
            # multi-host (JaxCollectives) has no abort — a dead host stalls
            # the collective, jax's fail-stop model (the reference behaves
            # identically: a crashed locale hangs allIdle, SURVEY.md §5).
            barrier = getattr(self.coll, "_barrier", None)
            if barrier is not None:
                try:
                    barrier.abort()
                except Exception:
                    pass

    def _loop(self, pools: list[ParallelSoAPool], states, shared, stop_event):
        import pickle
        import time as _time

        coll = self.coll
        H = coll.num_hosts
        me = self.me
        rrobin = 0
        backoff = 1  # cadence multiplier (adaptive back-off)
        quiescent_streak = 0
        from ..problems.base import batch_length

        while True:
            _time.sleep(self.interval_s * backoff)
            if states.flag.is_set():  # a worker died: abort everywhere
                stop_event.set()
                abort = getattr(coll, "_barrier", None)
                if abort is not None:
                    abort.abort()
                return
            self.rounds += 1
            # tts-lint: waive guarded-by -- advisory racy size sample for the control tuple; quiescence needs two consecutive all-idle rounds
            size = sum(p.size for p in pools)
            # Donations come from a single pool, so donor eligibility and
            # the quiescence test must use the *largest pool*, not the host
            # sum: D pools can each hold m-1 drain-leftover nodes — a host
            # sum >= 2m that no pool can ever donate would loop forever.
            # tts-lint: waive guarded-by -- advisory racy size sample; donor eligibility is re-checked under try_lock in _donate_from
            max_pool = max(p.size for p in pools)
            idle = states._all_idle()
            best = shared.read()
            # Host 0's wall clock decides checkpoint rounds (host clocks
            # need not agree; the flag in the control tuple synchronizes
            # the cut).
            want_ckpt = False
            if self.ckpt_mgr is not None and me == 0:
                if self._ckpt_last is None:
                    self._ckpt_last = _time.monotonic()
                elif (_time.monotonic() - self._ckpt_last
                      >= self.ckpt_interval_s):
                    want_ckpt = True
            cut_id = (
                f"{self._run_uuid}:{self.rounds}" if want_ckpt else None
            )
            # Timed SPAN: the allgather wall is the measured control-round
            # latency — the cost model's "exchange" link (obs/costmodel.py).
            t_x = ev.now_us()
            rows = coll.allgather_obj(
                (size, max_pool, best, bool(idle), want_ckpt, cut_id)
            )
            gbest = min(r[2] for r in rows)
            shared.publish(gbest)
            ev.complete("exchange", t_x, wid=ev.COMM_TID, host=me, args={
                "round": self.rounds, "size": size, "best": int(gbest),
                "idle": bool(idle), "backoff": backoff,
            })
            if gbest < best:
                ev.emit("incumbent", wid=ev.COMM_TID, host=me,
                        args={"best": int(gbest)})
            sizes = [r[0] for r in rows]
            maxes = [r[1] for r in rows]
            idles = [r[3] for r in rows]
            do_ckpt = self.ckpt_mgr is not None and rows[0][4]
            # Deterministic donor->receiver matching (identical on every
            # host): richest donors paired with hungriest idle receivers.
            donors = sorted(
                (h for h in range(H) if maxes[h] >= 2 * self.m),
                key=lambda h: (-maxes[h], h),
            )
            needy = sorted(
                (h for h in range(H) if idles[h] and sizes[h] < self.m),
                key=lambda h: (sizes[h], h),
            )
            if self.policy.hier:
                # Two-level topology-aware matching: near (ici) pairs
                # every round, far (dcn) pairs only on far rounds and
                # only for needy the near level missed — deterministic on
                # the lockstep round counter (parallel/topology.py).
                pairs = self.policy.match(donors, needy, self.rounds,
                                          sizes=maxes)
            else:
                pairs = list(zip(donors, needy))
            if not pairs:
                if all(idles) and max(maxes) < 2 * self.m:
                    # Global quiescence candidate: every host idle, no pool
                    # can donate. Confirm with a second consecutive round
                    # (sizes re-sampled after observing all-idle) so a
                    # worker that was momentarily between polls can't have
                    # its poppable work diverted to the serial host drain.
                    quiescent_streak += 1
                    if quiescent_streak >= 2:
                        ev.emit("terminate", wid=ev.COMM_TID, host=me,
                                args={"round": self.rounds})
                        stop_event.set()
                        return
                    backoff = 1  # confirm promptly
                else:
                    quiescent_streak = 0
                    if not needy:
                        # Everyone is busy and rich: back off geometrically
                        # so a balanced run pays ~no collective overhead;
                        # any needy report resets the cadence.
                        backoff = min(backoff * 2, self.BACKOFF_MAX)
                    else:
                        backoff = 1
            else:
                quiescent_streak = 0
                backoff = 1
                # Point-to-point delivery through the KV channel: only
                # matched hosts touch payloads; keys are round-unique (the
                # round counter advances in lockstep — one metadata
                # allgather per round).
                send_to = next((r for d, r in pairs if d == me), None)
                recv_from = next((d for d, r in pairs if r == me), None)
                if send_to is not None:
                    link = self.policy.link(me, send_to)
                    payload = self._donate_from(
                        pools, cap=self.policy.cap_for(link)
                    )
                    self._inflight = payload
                    blob = pickle.dumps(payload)
                    # Donation SPAN over the KV put (bytes + duration: the
                    # "donate"/"donate:<link>" bandwidth samples of the
                    # cost model). The simulated-latency harness sleeps
                    # INSIDE the span so injected link latencies land in
                    # the measured fit (zero sleeps unless TTS_SIM_LAT_*
                    # is armed).
                    t_d = ev.now_us()
                    self.policy.sim.sleep(link)
                    coll.kv_set(
                        f"tts/steal/{self.rounds}/{me}->{send_to}", blob
                    )
                    self._inflight = None
                    if payload is not None:
                        self.blocks_sent += 1
                        self.nodes_sent += batch_length(payload)
                        ev.complete("donate_send", t_d, wid=ev.COMM_TID,
                                    host=me,
                                    args={"peer": send_to,
                                          "nodes": batch_length(payload),
                                          "bytes": len(blob),
                                          "link": link,
                                          "level": self.policy.level_of(link),
                                          "round": self.rounds})
                if recv_from is not None:
                    link = self.policy.link(recv_from, me)
                    t_d = ev.now_us()
                    raw = coll.kv_get(
                        f"tts/steal/{self.rounds}/{recv_from}->{me}",
                        self.KV_TIMEOUT_S,
                    )
                    batch = pickle.loads(raw)
                    if batch is not None:
                        # Span covers the KV wait (donor prep + transfer).
                        ev.complete("donate_recv", t_d, wid=ev.COMM_TID,
                                    host=me,
                                    args={"peer": recv_from,
                                          "nodes": batch_length(batch),
                                          "bytes": len(raw),
                                          "link": link,
                                          "level": self.policy.level_of(link),
                                          "round": self.rounds})
                        # Whole block into one local pool (keeps it >= m so
                        # the receiving worker can pop; intra-host stealing
                        # spreads it from there).
                        pools[rrobin].locked_push_back_bulk(batch)
                        rrobin = (rrobin + 1) % len(pools)
                        self.blocks_received += 1
                        self.nodes_received += batch_length(batch)
                        fr.note_steal(me, link, self.policy.level_of(link))
            if do_ckpt:
                # Same round on every host (rows[0][4]): donations above
                # completed, workers pause at chunk boundaries, each host
                # stages its own share, and the set commits atomically only
                # if EVERY host staged successfully — a host whose worker
                # died keeps the whole set on the previous coherent cut
                # (donated nodes must never appear in files from different
                # rounds: they would be double-explored or lost on resume).
                from ..engine.checkpoint import lockstep_commit

                staging = self.ckpt_mgr.path + ".staging"
                ok = self.ckpt_mgr.do_checkpoint(
                    to_path=staging, cut_tag=rows[0][5]
                )
                lockstep_commit(ok, staging, self.ckpt_mgr.path,
                                vote=coll.allgather_obj)
                self._ckpt_last = _time.monotonic()


def _host_search(
    problem: Problem,
    m: int,
    M: int,
    D: int,
    devices,
    collectives,
    initial_best: int | None,
    share_bound: bool,
    seed_base: int = 0xD157,
    steal: bool = True,
    steal_interval_s: float = 0.02,
    perc: float = 0.5,
    partition_fn=None,
    checkpoint_path: str | None = None,
    checkpoint_interval_s: float = 60.0,
    resume_from: str | None = None,
    topology=None,
):
    """One host's full pipeline (warm-up + stride slice, local multi-device
    runtime with an inter-host communicator, local drain); returns its local
    stats for reduction. Delegates to the shared ``host_pipeline``
    (SURVEY.md §1: the reference duplicates this scaffolding between its
    multi and dist mains — we don't). Checkpoints are per-host files
    (``path.h<rank>``), cut in the same communicator round on every host —
    or on independent timers when ``steal=False`` (no inter-host traffic
    exists to straddle an unsynchronized cut)."""
    comm = None
    policy = None
    if steal and collectives.num_hosts > 1:
        import jax

        from .topology import Topology, resolve_policy

        topo = topology or Topology.detect(collectives.num_hosts)
        # Resolved from env + the (shared) profile file only — every host
        # lands on the identical policy without communication.
        from ..ops import backend as BK

        policy = resolve_policy(
            problem, topo, m=m, cap=M, interval_s=steal_interval_s,
            backend=BK.profile_backend(),
            topo_str=f"dist-H{collectives.num_hosts}xD{D}",
        )
        comm = _HostComm(
            collectives, m, perc=perc, interval_s=steal_interval_s, M=M,
            ckpt_interval_s=checkpoint_interval_s, policy=policy,
        )
    local = host_pipeline(
        problem, m, M, D, devices,
        initial_best=initial_best, share_bound=share_bound,
        num_hosts=collectives.num_hosts, host_id=collectives.host_id,
        seed=seed_base + collectives.host_id, perc=perc, comm=comm,
        partition_fn=partition_fn,
        checkpoint_path=checkpoint_path,
        checkpoint_interval_s=checkpoint_interval_s,
        resume_from=resume_from,
    )
    if comm is not None:
        local["comm"] = {
            "rounds": comm.rounds,
            "blocks_sent": comm.blocks_sent,
            "blocks_received": comm.blocks_received,
            "nodes_sent": comm.nodes_sent,
            "nodes_received": comm.nodes_received,
        }
    if policy is not None:
        local["steal_policy"] = policy.describe()
    return local


def _reduce(local: dict, collectives) -> SearchResult:
    """`MPI_Reduce` equivalents: sum tree/sol, min best, max time
    (`pfsp_dist_multigpu_cuda.c:680-694`); communicator counters sum too."""
    tree = collectives.allreduce_sum(local["tree"])
    sol = collectives.allreduce_sum(local["sol"])
    best = collectives.allreduce_min(local["best"])
    elapsed = collectives.allreduce_max(local["elapsed"])
    steals = collectives.allreduce_sum(local["steals"])
    comm = None
    if "comm" in local:
        comm = {
            k: collectives.allreduce_sum(v) for k, v in local["comm"].items()
        }
    return SearchResult(
        explored_tree=tree,
        explored_sol=sol,
        best=best,
        elapsed=elapsed,
        phases=local["phases"],
        diagnostics=local["diag"],
        per_worker_tree=local["per_worker_tree"],
        steals=steals,
        comm=comm,
        steal_policy=local.get("steal_policy"),
    )


def dist_search(
    problem: Problem,
    m: int = 25,
    M: int = 50000,
    D: int | None = None,
    num_hosts: int | None = None,
    devices=None,
    initial_best: int | None = None,
    share_bound: bool = True,
    steal: bool = True,
    steal_interval_s: float = 0.02,
    perc: float = 0.5,
    partition_fn=None,
    checkpoint_path: str | None = None,
    checkpoint_interval_s: float = 60.0,
    resume_from: str | None = None,
) -> SearchResult:
    """Distributed search entry point.

    * Under ``jax.distributed`` (process_count > 1): this process runs its
      host's share; reductions go over DCN. Returns the global result.
    * Single process with ``num_hosts=H > 1``: runs H virtual hosts in
      threads over disjoint local-device groups (testing mode).
    * Single process, ``num_hosts`` unset/1: degenerates to one host.

    ``steal=True`` (default) runs the inter-host communicator: periodic
    incumbent all-reduce + host-mediated work stealing + two-level
    termination (see ``_HostComm``); ``steal=False`` keeps the MPI
    baseline's join-point-only semantics (`pfsp_dist_multigpu_cuda.c`).
    """
    import jax

    if jax.process_count() > 1:
        coll = JaxCollectives()
        local_devices = jax.local_devices() if devices is None else devices
        if D is None:
            D = len(local_devices)
        # Real pods: the pod map comes from each process's slice index,
        # allgathered once (multi-slice deployments put ICI inside a slice
        # and DCN between slices); TTS_PODS still wins inside detect().
        from .topology import Topology

        slice_idx = getattr(local_devices[0], "slice_index", None) \
            if (steal and local_devices) else None
        topo = Topology.detect(
            coll.num_hosts, slice_index=slice_idx,
            allgather=coll.allgather_obj if slice_idx is not None else None,
        )
        local = _host_search(
            problem, m, M, D, local_devices, coll, initial_best, share_bound,
            steal=steal, steal_interval_s=steal_interval_s, perc=perc,
            partition_fn=partition_fn,
            checkpoint_path=checkpoint_path,
            checkpoint_interval_s=checkpoint_interval_s,
            resume_from=resume_from,
            topology=topo,
        )
        return _reduce(local, coll)

    all_devices = jax.devices() if devices is None else devices
    H = num_hosts or 1
    if H == 1:
        coll = LocalCollectives()
        if D is None:
            D = len(all_devices)
        local = _host_search(
            problem, m, M, D, all_devices, coll, initial_best, share_bound,
            steal=False,
            checkpoint_path=checkpoint_path,
            checkpoint_interval_s=checkpoint_interval_s,
            resume_from=resume_from,
        )
        return _reduce(local, coll)

    # Virtual hosts: split local devices into H disjoint groups.
    if H > len(all_devices):
        raise ValueError(
            f"num_hosts={H} exceeds available devices ({len(all_devices)}); "
            "virtual hosts need at least one device each"
        )
    groups = [all_devices[h::H] for h in range(H)]
    if D is None:
        D = max(1, min(len(g) for g in groups))
    coll = ThreadCollectives(H)
    results: list = [None] * H
    errors: list = [None] * H

    locals_: list = [None] * H

    def host_main(h: int):
        try:
            locals_[h] = _host_search(
                problem, m, M, D, groups[h], coll.bind(h),
                initial_best, share_bound,
                steal=steal, steal_interval_s=steal_interval_s, perc=perc,
                partition_fn=partition_fn,
                checkpoint_path=checkpoint_path,
                checkpoint_interval_s=checkpoint_interval_s,
                resume_from=resume_from,
            )
            results[h] = _reduce(locals_[h], coll)
        except BaseException as e:  # propagate after join
            errors[h] = e
            try:
                coll._barrier.abort()
            except Exception:
                pass

    threads = [
        threading.Thread(target=host_main, args=(h,), name=f"tts-host-{h}")
        for h in range(H)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # An erroring host aborts the shared barrier, so its PEERS — possibly
    # including host 0 — die with secondary errors. Surface the root cause,
    # not whichever error sits at the lowest index.
    real = [e for e in errors if e is not None and not secondary_error(e)]
    for e in real or errors:
        if e is not None:
            raise e
    # All hosts computed identical global reductions; merge per-host extras.
    global_res = results[0]
    global_res.per_worker_tree = [
        t for r in results for t in r.per_worker_tree
    ]
    return global_res
