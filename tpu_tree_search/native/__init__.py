"""Native (C++) host runtime bindings.

The reference's host path is C (`baselines/*/lib/*.c`); ours is
`csrc/tts_native.cpp`, compiled lazily into a shared library and bound via
ctypes (no pybind11 in the image). The Python implementations in
`problems/` and `engine/` remain the semantic oracles and the portable
fallback; everything here is property-tested against them.

Set ``TTS_NATIVE=0`` to force the pure-Python path.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from pathlib import Path

import numpy as np

_SRC = Path(__file__).resolve().parents[2] / "csrc" / "tts_native.cpp"
_BUILD_DIR = _SRC.parent / "_build"

_lock = threading.Lock()
_lib = None
_lib_error: str | None = None


def _compile() -> Path:
    flags = ["-O3", "-std=c++17", "-shared", "-fPIC"]
    src_text = _SRC.read_text()
    tag = hashlib.sha256((src_text + " ".join(flags)).encode()).hexdigest()[:16]
    out = _BUILD_DIR / f"libtts_native_{tag}.so"
    if out.exists():
        return out
    _BUILD_DIR.mkdir(parents=True, exist_ok=True)
    # Per-process tmp name: concurrent builders (pytest workers, parallel CLI
    # runs) must not write through the same inode before the atomic rename.
    tmp = out.with_suffix(f".so.tmp.{os.getpid()}")
    cmd = [os.environ.get("CXX", "g++"), *flags, "-o", str(tmp), str(_SRC)]
    # -march=native when the toolchain supports it (it may not in a sandbox).
    probe = subprocess.run(
        cmd[:1] + ["-march=native", "-E", "-x", "c++", "-", "-o", os.devnull],
        input=b"",
        capture_output=True,
    )
    if probe.returncode == 0:
        cmd.insert(1, "-march=native")
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(tmp, out)
    return out


def _declare(lib: ctypes.CDLL) -> None:
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.tts_nq_sequential.argtypes = [ctypes.c_int32, ctypes.c_int32, i64p, i64p]
    lib.tts_nq_sequential.restype = None
    lib.tts_nq_warmup.argtypes = [
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int64,
        i32p, u8p, ctypes.c_int64, i64p, i64p,
    ]
    lib.tts_nq_warmup.restype = ctypes.c_int64
    lib.tts_nq_drain.argtypes = [
        ctypes.c_int32, ctypes.c_int32, i32p, u8p, ctypes.c_int64, i64p, i64p,
    ]
    lib.tts_nq_drain.restype = None
    lib.tts_nq_generate.argtypes = [
        ctypes.c_int32, i32p, u8p, ctypes.c_int64, u8p, i32p, u8p, i64p,
    ]
    lib.tts_nq_generate.restype = ctypes.c_int64
    lib.tts_pfsp_new.argtypes = [
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
        i32p, i32p, i32p, ctypes.c_int32, i32p, i32p, i32p,
    ]
    lib.tts_pfsp_new.restype = ctypes.c_void_p
    lib.tts_pfsp_free.argtypes = [ctypes.c_void_p]
    lib.tts_pfsp_free.restype = None
    lib.tts_pfsp_sequential.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, i64p, i64p, i32p,
    ]
    lib.tts_pfsp_sequential.restype = None
    lib.tts_pfsp_warmup.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, i32p, i32p, i32p,
        ctypes.c_int64, i64p, i64p, i32p,
    ]
    lib.tts_pfsp_warmup.restype = ctypes.c_int64
    lib.tts_pfsp_drain.argtypes = [
        ctypes.c_void_p, i32p, i32p, i32p, ctypes.c_int64, i64p, i64p, i32p,
    ]
    lib.tts_pfsp_drain.restype = None
    lib.tts_pfsp_generate.argtypes = [
        ctypes.c_void_p, i32p, i32p, i32p, ctypes.c_int64, i32p,
        i32p, i32p, i32p, i64p, i32p,
    ]
    lib.tts_pfsp_generate.restype = ctypes.c_int64


def load():
    """Compile-on-demand loader; returns the CDLL or None (with the failure
    reason kept in ``load_error()``)."""
    global _lib, _lib_error
    if os.environ.get("TTS_NATIVE", "1") == "0":
        return None
    with _lock:
        if _lib is not None or _lib_error is not None:
            return _lib
        try:
            path = _compile()
            lib = ctypes.CDLL(str(path))
            _declare(lib)
            _lib = lib
        except subprocess.CalledProcessError as e:
            stderr = (e.stderr or b"").decode(errors="replace").strip()
            _lib_error = f"native build failed: {stderr or e}"
        except Exception as e:  # missing toolchain, sandbox, ...
            _lib_error = f"{type(e).__name__}: {e}"
        return _lib


def load_error() -> str | None:
    return _lib_error


def _ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def _i32(arr):
    return _ptr(arr, ctypes.c_int32)


def _u8(arr):
    return _ptr(arr, ctypes.c_uint8)


class NativeNQueens:
    """Native host primitives for one N-Queens configuration."""

    def __init__(self, lib: ctypes.CDLL, N: int, g: int):
        self._lib = lib
        self.N = N
        self.g = g

    def sequential(self) -> tuple[int, int]:
        tree = ctypes.c_int64()
        sol = ctypes.c_int64()
        self._lib.tts_nq_sequential(
            self.N, self.g, ctypes.byref(tree), ctypes.byref(sol)
        )
        return tree.value, sol.value

    def warmup(self, batch: dict, target: int) -> tuple[dict, int, int]:
        size_in = batch["depth"].shape[0]
        cap = max(size_in, target + self.N)
        depth = np.zeros(cap, dtype=np.int32)
        board = np.zeros((cap, self.N), dtype=np.uint8)
        depth[:size_in] = batch["depth"]
        board[:size_in] = batch["board"]
        tree = ctypes.c_int64()
        sol = ctypes.c_int64()
        out = self._lib.tts_nq_warmup(
            self.N, self.g, target, _i32(depth), _u8(board), size_in,
            ctypes.byref(tree), ctypes.byref(sol),
        )
        frontier = {"depth": depth[:out].copy(), "board": board[:out].copy()}
        return frontier, tree.value, sol.value

    def drain(self, batch: dict) -> tuple[int, int]:
        size = batch["depth"].shape[0]
        depth = np.ascontiguousarray(batch["depth"], dtype=np.int32)
        board = np.ascontiguousarray(batch["board"], dtype=np.uint8)
        tree = ctypes.c_int64()
        sol = ctypes.c_int64()
        self._lib.tts_nq_drain(
            self.N, self.g, _i32(depth), _u8(board), size,
            ctypes.byref(tree), ctypes.byref(sol),
        )
        return tree.value, sol.value

    def generate_children(
        self, parents: dict, count: int, labels: np.ndarray
    ) -> tuple[dict, int, int]:
        pdepth = np.ascontiguousarray(parents["depth"][:count], dtype=np.int32)
        pboard = np.ascontiguousarray(parents["board"][:count], dtype=np.uint8)
        lab = np.ascontiguousarray(labels[:count], dtype=np.uint8)
        cap = count * self.N
        cdepth = np.zeros(cap, dtype=np.int32)
        cboard = np.zeros((cap, self.N), dtype=np.uint8)
        sol_inc = ctypes.c_int64()
        k = self._lib.tts_nq_generate(
            self.N, _i32(pdepth), _u8(pboard), count, _u8(lab),
            _i32(cdepth), _u8(cboard), ctypes.byref(sol_inc),
        )
        children = {"depth": cdepth[:k].copy(), "board": cboard[:k].copy()}
        return children, int(k), sol_inc.value


class NativePFSP:
    """Native host primitives for one PFSP (instance, lb) configuration.

    Owns an opaque context holding the instance tables built by the Python
    oracle (`bounds.py`), so every tier shares bit-identical tables.
    """

    _LB_KINDS = {"lb1": 0, "lb1_d": 1, "lb2": 2}

    def __init__(self, lib: ctypes.CDLL, lb1_data, lb2_data, lb: str):
        self._lib = lib
        self.jobs = int(lb1_data.jobs)
        self.machines = int(lb1_data.machines)
        # Keep the table arrays alive for the context's lifetime.
        self._tables = (
            np.ascontiguousarray(lb1_data.p_times, dtype=np.int32),
            np.ascontiguousarray(lb1_data.min_heads, dtype=np.int32),
            np.ascontiguousarray(lb1_data.min_tails, dtype=np.int32),
            np.ascontiguousarray(lb2_data.pairs, dtype=np.int32),
            np.ascontiguousarray(lb2_data.lags, dtype=np.int32),
            np.ascontiguousarray(lb2_data.johnson_schedules, dtype=np.int32),
        )
        ptm, mh, mt, pairs, lags, jsched = self._tables
        self._ctx = lib.tts_pfsp_new(
            self.jobs, self.machines, self._LB_KINDS[lb],
            _i32(ptm), _i32(mh), _i32(mt),
            pairs.shape[0], _i32(pairs), _i32(lags), _i32(jsched),
        )

    def __del__(self):
        ctx = getattr(self, "_ctx", None)
        if ctx:
            self._lib.tts_pfsp_free(ctx)
            self._ctx = None

    def sequential(self, best: int) -> tuple[int, int, int]:
        tree = ctypes.c_int64()
        sol = ctypes.c_int64()
        best_out = ctypes.c_int32()
        self._lib.tts_pfsp_sequential(
            self._ctx, best, ctypes.byref(tree), ctypes.byref(sol),
            ctypes.byref(best_out),
        )
        return tree.value, sol.value, best_out.value

    def warmup(self, batch: dict, best: int, target: int):
        size_in = batch["depth"].shape[0]
        cap = max(size_in, target + self.jobs)
        depth = np.zeros(cap, dtype=np.int32)
        limit1 = np.zeros(cap, dtype=np.int32)
        prmu = np.zeros((cap, self.jobs), dtype=np.int32)
        depth[:size_in] = batch["depth"]
        limit1[:size_in] = batch["limit1"]
        prmu[:size_in] = batch["prmu"]
        tree = ctypes.c_int64()
        sol = ctypes.c_int64()
        best_io = ctypes.c_int32(best)
        out = self._lib.tts_pfsp_warmup(
            self._ctx, target, _i32(depth), _i32(limit1), _i32(prmu), size_in,
            ctypes.byref(tree), ctypes.byref(sol), ctypes.byref(best_io),
        )
        frontier = {
            "depth": depth[:out].copy(),
            "limit1": limit1[:out].copy(),
            "prmu": prmu[:out].copy(),
        }
        return frontier, tree.value, sol.value, best_io.value

    def drain(self, batch: dict, best: int) -> tuple[int, int, int]:
        size = batch["depth"].shape[0]
        depth = np.ascontiguousarray(batch["depth"], dtype=np.int32)
        limit1 = np.ascontiguousarray(batch["limit1"], dtype=np.int32)
        prmu = np.ascontiguousarray(batch["prmu"], dtype=np.int32)
        tree = ctypes.c_int64()
        sol = ctypes.c_int64()
        best_io = ctypes.c_int32(best)
        self._lib.tts_pfsp_drain(
            self._ctx, _i32(depth), _i32(limit1), _i32(prmu), size,
            ctypes.byref(tree), ctypes.byref(sol), ctypes.byref(best_io),
        )
        return tree.value, sol.value, best_io.value

    def generate_children(
        self, parents: dict, count: int, bounds: np.ndarray, best: int
    ):
        n = self.jobs
        pdepth = np.ascontiguousarray(parents["depth"][:count], dtype=np.int32)
        plimit1 = np.ascontiguousarray(parents["limit1"][:count], dtype=np.int32)
        pprmu = np.ascontiguousarray(parents["prmu"][:count], dtype=np.int32)
        bnds = np.ascontiguousarray(bounds[:count], dtype=np.int32)
        cap = count * n
        cdepth = np.zeros(cap, dtype=np.int32)
        climit1 = np.zeros(cap, dtype=np.int32)
        cprmu = np.zeros((cap, n), dtype=np.int32)
        sol_inc = ctypes.c_int64()
        best_io = ctypes.c_int32(best)
        k = self._lib.tts_pfsp_generate(
            self._ctx, _i32(pdepth), _i32(plimit1), _i32(pprmu), count,
            _i32(bnds), _i32(cdepth), _i32(climit1), _i32(cprmu),
            ctypes.byref(sol_inc), ctypes.byref(best_io),
        )
        children = {
            "depth": cdepth[:k].copy(),
            "limit1": climit1[:k].copy(),
            "prmu": cprmu[:k].copy(),
        }
        return children, int(k), sol_inc.value, best_io.value
