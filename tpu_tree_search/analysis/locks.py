"""``guarded-by``: a lightweight static race detector for the host-thread
runtime (the lock-based work-stealing tiers in ``pool/``, ``parallel/``).

Annotations (comments — zero runtime cost, greppable):

* Field, trailing form::

      self._value = value  # guarded-by: _lock

* Field, class-body form (covers inherited fields)::

      class ParallelSoAPool(SoAPool):
          # guarded-by: lock -- front, size, capacity, data

* Method contract, class-body form — the method touches guarded state and
  documents "caller must hold the lock"; its *body* is exempt, its *call
  sites* are checked::

      # requires-lock: lock -- push_back_bulk, pop_back_bulk

Enforcement: every attribute access ``B.field`` / call ``B.method(...)``
whose base ``B`` is *inferred* to be an instance of an annotated class must
sit lexically inside ``with B.<lock>:`` or the taken branch of
``if B.try_lock():``. Inference is deliberately shallow and conservative —
parameter/return annotations, direct constructions, ``self`` in methods of
the annotated class, instance attributes typed in ``__init__``, and element
types of ``list[C]`` through indexing / iteration / ``min``/``max``.
Anything unresolvable is silently exempt: the rule under-approximates, so a
finding is always worth reading. Accesses in ``__init__`` of the declaring
class are exempt (the instance is not yet shared), as are accesses from a
method of the declaring class that the class itself documents with
``requires-lock`` (the contract moves the check to the call sites).
"""

from __future__ import annotations

import ast
import re

from .core import Finding, Module, Project, rule

_FIELD_TRAIL = re.compile(r"#\s*guarded-by:\s*(?P<lock>\w+)\s*(?:--.*)?$")
_FIELD_CLASS = re.compile(
    r"#\s*guarded-by:\s*(?P<lock>\w+)\s*--\s*(?P<fields>[\w, ]+)$"
)
_METHOD_CLASS = re.compile(
    r"#\s*requires-lock:\s*(?P<lock>\w+)\s*--\s*(?P<methods>[\w, ]+)$"
)

FunctionNode = (ast.FunctionDef, ast.AsyncFunctionDef)


class _ClassInfo:
    def __init__(self, name: str):
        self.name = name
        self.fields: dict[str, str] = {}  # field -> lock attr
        self.methods: dict[str, str] = {}  # method -> lock attr
        self.attr_types: dict[str, str] = {}  # instance attr -> class name


# -- annotation collection (project-wide) ----------------------------------


def _collect(project: Project) -> dict[str, _ClassInfo]:
    """Guarded classes by name. Class names are matched globally across the
    analyzed tree (unique-per-package assumption, see docs/ANALYSIS.md)."""

    def build(_):
        classes: dict[str, _ClassInfo] = {}

        def info(name: str) -> _ClassInfo:
            return classes.setdefault(name, _ClassInfo(name))

        for mod in project.modules:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                for line in range(
                    node.lineno, (node.end_lineno or node.lineno) + 1
                ):
                    comment = mod.comments.get(line)
                    if not comment or _innermost_class_at(mod, line) is not node:
                        continue
                    m = _FIELD_CLASS.search(comment)
                    if m:
                        for f in m.group("fields").split(","):
                            if f.strip():
                                info(node.name).fields[f.strip()] = m.group("lock")
                    m = _METHOD_CLASS.search(comment)
                    if m:
                        for meth in m.group("methods").split(","):
                            if meth.strip():
                                info(node.name).methods[meth.strip()] = m.group("lock")
                for sub in ast.walk(node):
                    if not isinstance(sub, (ast.Assign, ast.AnnAssign)):
                        continue
                    comment = mod.comments.get(sub.lineno, "")
                    m = _FIELD_TRAIL.search(comment)
                    if not m or _FIELD_CLASS.search(comment):
                        continue
                    targets = (
                        sub.targets if isinstance(sub, ast.Assign) else [sub.target]
                    )
                    for t in targets:
                        if (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                            and _owning_class(mod, sub) is node
                        ):
                            info(node.name).fields[t.attr] = m.group("lock")
        # instance-attribute types from __init__, for every class (so bases
        # like ``self.pools`` / ``self.gate`` resolve in method bodies)
        for mod in project.modules:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                init = next(
                    (s for s in node.body
                     if isinstance(s, ast.FunctionDef) and s.name == "__init__"),
                    None,
                )
                if init is None:
                    continue
                env = _param_types(init)
                for sub in ast.walk(init):
                    if not isinstance(sub, ast.Assign):
                        continue
                    for t in sub.targets:
                        if not (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                        ):
                            continue
                        ty = _expr_type(mod, sub.value, env, classes)
                        if ty is not None:
                            info(node.name).attr_types[t.attr] = ty
        return classes

    return project.fact("guarded-by:classes", build)


def _innermost_class_at(mod: Module, line: int) -> ast.ClassDef | None:
    best: ast.ClassDef | None = None
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ClassDef) and node.lineno <= line <= (
            node.end_lineno or node.lineno
        ):
            if best is None or node.lineno > best.lineno:
                best = node
    return best


def _owning_class(mod: Module, node: ast.AST) -> ast.ClassDef | None:
    """The innermost class lexically containing ``node`` (methods and
    closures nested in methods both resolve to their class)."""
    cur = mod.parent.get(node)
    while cur is not None:
        if isinstance(cur, ast.ClassDef):
            return cur
        cur = mod.parent.get(cur)
    return None


# -- shallow type inference ------------------------------------------------


def _ann_type(ann: ast.AST | None) -> str | None:
    """``C`` / ``"C"`` / ``C | None`` / ``Optional[C]`` -> ``C``;
    ``list[C]`` / ``Sequence[C]`` / ``tuple[C, ...]`` -> ``list:C``."""
    if ann is None:
        return None
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        try:
            ann = ast.parse(ann.value, mode="eval").body
        except SyntaxError:
            return None
    if isinstance(ann, ast.Name):
        return ann.id
    if isinstance(ann, ast.BinOp) and isinstance(ann.op, ast.BitOr):
        for side in (ann.left, ann.right):
            ty = _ann_type(side)
            if ty is not None and ty != "None":
                return ty
        return None
    if isinstance(ann, ast.Subscript):
        base = ann.value
        base_name = base.attr if isinstance(base, ast.Attribute) else (
            base.id if isinstance(base, ast.Name) else None
        )
        if base_name in ("list", "List", "Sequence", "Iterable", "tuple", "Tuple"):
            elt = ann.slice
            if isinstance(elt, ast.Tuple) and elt.elts:
                elt = elt.elts[0]
            inner = _ann_type(elt)
            return f"list:{inner}" if inner else None
        if base_name == "Optional":
            return _ann_type(ann.slice)
    return None


def _param_types(fn: ast.AST) -> dict[str, str]:
    if isinstance(fn, ast.Lambda):
        return {}
    out: dict[str, str] = {}
    for a in fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs:
        ty = _ann_type(a.annotation)
        if ty is not None:
            out[a.arg] = ty
    return out


def _expr_type(
    mod: Module, expr: ast.AST, env: dict[str, str],
    classes: dict[str, _ClassInfo],
) -> str | None:
    if isinstance(expr, ast.Name):
        return env.get(expr.id)
    if isinstance(expr, ast.Subscript):
        base = _expr_type(mod, expr.value, env, classes)
        if base and base.startswith("list:"):
            return base.split(":", 1)[1]
        return None
    if isinstance(expr, ast.Attribute):
        base = _expr_type(mod, expr.value, env, classes)
        if base in classes:
            return classes[base].attr_types.get(expr.attr)
        return None
    if isinstance(expr, ast.Call):
        fname = expr.func.id if isinstance(expr.func, ast.Name) else None
        if fname is None:
            return None
        if fname in classes:
            return fname  # direct construction
        if fname in ("min", "max", "next") and expr.args:
            base = _expr_type(mod, expr.args[0], env, classes)
            if base and base.startswith("list:"):
                return base.split(":", 1)[1]
            return None
        for node in ast.walk(mod.tree):  # local fn with return annotation
            if (
                isinstance(node, ast.FunctionDef)
                and node.name == fname
                and mod.enclosing_function(node) is None
            ):
                return _ann_type(node.returns)
    return None


def _function_env(
    mod: Module, fn: ast.AST, classes: dict[str, _ClassInfo],
    memo: dict[ast.AST, dict[str, str]],
) -> dict[str, str]:
    """Flow-insensitive name->type environment for ``fn``, including its
    lexical ancestors' bindings (closures see outer locals)."""
    if fn in memo:
        return memo[fn]
    outer = mod.enclosing_function(fn)
    env = dict(
        _function_env(mod, outer, classes, memo)
    ) if outer is not None else {}
    env.update(_param_types(fn))
    owner = _owning_class(mod, fn)
    if owner is not None and not isinstance(fn, ast.Lambda):
        env.setdefault("self", owner.name)
    if not isinstance(fn, ast.Lambda):
        for _ in range(4):  # small fixpoint for chained assignments
            changed = False
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign):
                    ty = _expr_type(mod, node.value, env, classes)
                    if ty is None:
                        continue
                    for t in node.targets:
                        if isinstance(t, ast.Name) and env.get(t.id) != ty:
                            env[t.id] = ty
                            changed = True
                elif isinstance(node, ast.AnnAssign):
                    ty = _ann_type(node.annotation)
                    if ty and isinstance(node.target, ast.Name):
                        if env.get(node.target.id) != ty:
                            env[node.target.id] = ty
                            changed = True
                elif isinstance(node, (ast.For, ast.AsyncFor)):
                    ity = _expr_type(mod, node.iter, env, classes)
                    if (
                        ity and ity.startswith("list:")
                        and isinstance(node.target, ast.Name)
                    ):
                        elt = ity.split(":", 1)[1]
                        if env.get(node.target.id) != elt:
                            env[node.target.id] = elt
                            changed = True
                elif isinstance(node, ast.comprehension):
                    ity = _expr_type(mod, node.iter, env, classes)
                    if (
                        ity and ity.startswith("list:")
                        and isinstance(node.target, ast.Name)
                    ):
                        elt = ity.split(":", 1)[1]
                        if env.get(node.target.id) != elt:
                            env[node.target.id] = elt
                            changed = True
            if not changed:
                break
    memo[fn] = env
    return env


# -- lock-scope tracking ---------------------------------------------------


def _held_locks(mod: Module, node: ast.AST) -> set[str]:
    """Lock expressions (unparse strings) held at ``node``: enclosing
    ``with B.lock:`` items and the taken branch of ``if B.try_lock():``.
    Lock scopes do not cross function boundaries."""
    held: set[str] = set()
    cur: ast.AST | None = node
    while cur is not None:
        parent = mod.parent.get(cur)
        if parent is None:
            break
        if isinstance(parent, (ast.With, ast.AsyncWith)) and cur in parent.body:
            for item in parent.items:
                try:
                    held.add(ast.unparse(item.context_expr))
                except Exception:
                    pass
        if isinstance(parent, ast.If) and cur in parent.body:
            test = parent.test
            if (
                isinstance(test, ast.Call)
                and isinstance(test.func, ast.Attribute)
                and test.func.attr == "try_lock"
            ):
                try:
                    held.add(f"{ast.unparse(test.func.value)}.__try_lock__")
                except Exception:
                    pass
        if isinstance(parent, (*FunctionNode, ast.Lambda)):
            break
        cur = parent
    return held


def _lock_satisfied(base_txt: str, lock: str, held: set[str]) -> bool:
    return f"{base_txt}.{lock}" in held or f"{base_txt}.__try_lock__" in held


def _own_nodes(fn: ast.AST):
    """Walk a function body without descending into nested functions (each
    function is checked exactly once, under its own environment)."""
    stack = list(fn.body)
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (*FunctionNode, ast.Lambda)):
                continue
            stack.append(child)


@rule("guarded-by")
def guarded_by(module: Module, project: Project) -> list[Finding]:
    classes = _collect(project)
    guarded = {c for c, info in classes.items() if info.fields or info.methods}
    if not guarded:
        return []
    findings: list[Finding] = []
    env_memo: dict[ast.AST, dict[str, str]] = {}
    for fn in ast.walk(module.tree):
        if not isinstance(fn, FunctionNode):
            continue
        owner = _owning_class(module, fn)
        if owner is not None and owner.name in guarded:
            if fn.name == "__init__":
                continue  # instance not yet shared
            if fn.name in classes[owner.name].methods:
                continue  # requires-lock contract: checked at call sites
        env = _function_env(module, fn, classes, env_memo)
        for node in _own_nodes(fn):
            if not isinstance(node, ast.Attribute):
                continue
            base_ty = _expr_type(module, node.value, env, classes)
            if base_ty not in guarded:
                continue
            info = classes[base_ty]
            parent = module.parent.get(node)
            is_call = isinstance(parent, ast.Call) and parent.func is node
            try:
                base_txt = ast.unparse(node.value)
            except Exception:
                continue
            if is_call and node.attr in info.methods:
                lock = info.methods[node.attr]
                if not _lock_satisfied(base_txt, lock, _held_locks(module, node)):
                    findings.append(Finding(
                        "guarded-by", module.path, node.lineno, node.col_offset,
                        f"call to {base_ty}.{node.attr}() (requires-lock: "
                        f"{lock}) outside `with {base_txt}.{lock}:` / "
                        f"`if {base_txt}.try_lock():`",
                    ))
            elif not is_call and node.attr in info.fields:
                lock = info.fields[node.attr]
                if not _lock_satisfied(base_txt, lock, _held_locks(module, node)):
                    kind = "write to" if isinstance(
                        node.ctx, (ast.Store, ast.Del)
                    ) else "read of"
                    findings.append(Finding(
                        "guarded-by", module.path, node.lineno, node.col_offset,
                        f"unlocked {kind} {base_ty}.{node.attr} (guarded-by: "
                        f"{lock}); hold `{base_txt}.{lock}` or waive with a "
                        "justification",
                    ))
    return findings
