"""Inline waivers + the baseline ratchet for ``tts lint``.

Two suppression mechanisms, with different jobs:

* **Inline waiver** — a trailing comment on the flagged line (or the line
  above it): ``# tts-lint: waive <rule> -- <one-line justification>``.
  The justification is mandatory; a waiver without one is itself a finding
  (rule ``waiver-format``). Use waivers for accesses that are *individually*
  safe (e.g. an advisory racy ``pool.size`` read re-checked under the lock).

* **Baseline file** — a committed JSON ratchet keyed per ``rule:file`` with
  the accepted finding *count*. Pre-existing debt lints green; any edit that
  *adds* a finding to a cell fails; fixing findings lets ``--update-baseline``
  shrink the cell. Counts (not line numbers) keep the ratchet stable under
  unrelated edits. Use the baseline for legacy debt you intend to burn down,
  not for new code.
"""

from __future__ import annotations

import json
import re

from .core import PRAGMA, Finding, Module

_WAIVE_RE = re.compile(
    r"#\s*" + re.escape(PRAGMA) + r"\s*waive\s+(?P<rules>[\w\-, ]+?)"
    r"(?:\s*--\s*(?P<reason>.+))?\s*$"
)


def waivers_for(module: Module) -> tuple[dict[int, set[str]], list[Finding]]:
    """Map line -> waived rule names. A waiver on its own line applies to the
    next source line; a trailing waiver applies to its own line. Returns
    (waivers, format_findings) — reasonless waivers are flagged, not honored.
    """
    waived: dict[int, set[str]] = {}
    bad: list[Finding] = []
    for line, comment in module.comments.items():
        m = _WAIVE_RE.search(comment)
        if m is None:
            continue
        if not m.group("reason"):
            bad.append(
                Finding(
                    "waiver-format", module.path, line, 0,
                    "waiver missing justification: use "
                    f"'# {PRAGMA} waive <rule> -- <why this is safe>'",
                )
            )
            continue
        rules = {r.strip() for r in m.group("rules").split(",") if r.strip()}
        # Trailing comment waives its own line; a standalone comment line
        # waives the following line.
        target = line if module.text.splitlines()[line - 1].split("#")[0].strip() else line + 1
        waived.setdefault(target, set()).update(rules)
    return waived, bad


def apply_waivers(
    modules: list[Module], findings: list[Finding],
    selected_rules: set[str] | None = None,
) -> tuple[list[Finding], list[Finding]]:
    """Split findings into (active, waived).

    Stale-waiver detection (ISSUE 8): a waiver whose rule *did run* but no
    longer fires on its line is dead weight that silently disarms the rule
    for any future edit of that line — it becomes a ``waiver-stale``
    finding.  ``selected_rules`` is the set of rules this run executed
    (``None`` = all): a waiver for a rule that was not run cannot be judged
    and is left alone, and a waiver naming a rule that does not exist is
    always stale."""
    from .core import RULES

    by_path: dict[str, dict[int, set[str]]] = {}
    extra: list[Finding] = []
    for mod in modules:
        w, bad = waivers_for(mod)
        by_path[mod.path] = w
        extra.extend(bad)
    active: list[Finding] = list(extra)
    waived: list[Finding] = []
    used: set[tuple[str, int, str]] = set()
    for f in findings:
        rules = by_path.get(f.path, {}).get(f.line, set())
        if f.rule in rules:
            waived.append(f)
            used.add((f.path, f.line, f.rule))
        else:
            active.append(f)
    ran = set(RULES) if selected_rules is None else set(selected_rules)
    for mod in modules:
        for line, rules in sorted(by_path.get(mod.path, {}).items()):
            for r in sorted(rules):
                if r in RULES and r not in ran:
                    continue  # not judged this run
                if (mod.path, line, r) in used:
                    continue
                reason = (
                    "names unknown rule" if r not in RULES
                    else "its rule no longer fires on this line"
                )
                active.append(Finding(
                    "waiver-stale", mod.path, line, 0,
                    f"stale waiver for '{r}': {reason} — remove it (a dead "
                    "waiver silently disarms the rule for future edits)",
                ))
    return active, waived


# -- baseline ratchet -----------------------------------------------------


def load_baseline(path: str | None) -> dict[str, int]:
    if path is None:
        return {}
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except FileNotFoundError:
        return {}
    counts = data.get("counts", {})
    return {str(k): int(v) for k, v in counts.items()}


def save_baseline(path: str, findings: list[Finding]) -> None:
    counts: dict[str, int] = {}
    for f in findings:
        counts[f.cell] = counts.get(f.cell, 0) + 1
    with open(path, "w", encoding="utf-8") as f:
        json.dump(
            {
                "comment": "tts lint ratchet: accepted finding count per "
                "rule:file cell; regenerate with `tts lint "
                "--update-baseline` (counts may only shrink in review)",
                "counts": dict(sorted(counts.items())),
            },
            f,
            indent=2,
        )
        f.write("\n")


def ratchet(
    findings: list[Finding], baseline: dict[str, int]
) -> tuple[list[Finding], list[Finding]]:
    """Split active findings into (new, baselined). A cell at-or-under its
    baseline count is wholly baselined; a cell over it surfaces *all* its
    findings (a count ratchet cannot know which ones are the new ones)."""
    cells: dict[str, list[Finding]] = {}
    for f in findings:
        cells.setdefault(f.cell, []).append(f)
    new: list[Finding] = []
    old: list[Finding] = []
    for cell, fs in cells.items():
        if len(fs) <= baseline.get(cell, 0):
            old.extend(fs)
        else:
            new.extend(fs)
    new.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    old.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return new, old
