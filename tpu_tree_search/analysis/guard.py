"""Runtime trace/transfer guards for the resident engines (``TTS_GUARD=1``).

The static rules prove properties of the *source*; this module asserts the
complementary *runtime* invariant: once a resident engine reaches steady
state, every host dispatch of the compiled step must reuse the cached XLA
executable (zero recompilations) and move zero bytes implicitly between
host and device — the search advances purely on-device, and the host reads
back only the sanctioned counter scalars between K-cycle blocks.

Usage: the engine wraps each dispatch in ``SteadyStateGuard.step()``. The
first dispatch is the warm one (compilation + constant upload are expected
and excluded); every later dispatch runs under
``jax.transfer_guard("disallow")`` and is followed by a jit-cache-size
check. A violation raises ``GuardViolation`` naming the step — failing
loudly at the moment a perf regression re-introduces a per-cycle host
round trip (~360 ms each, docs/HW_VALIDATION.md) instead of silently
running 700x slower.

Backend note: the transfer guard catches implicit host->device transfers on
every backend; implicit device->host reads are reliably caught on
accelerator backends (on CPU the "device" buffer aliases host memory and
jax does not count the read as a transfer). The compilation-count assertion
is backend-independent.
"""

from __future__ import annotations

import os
from contextlib import contextmanager


class GuardViolation(RuntimeError):
    """A steady-state resident dispatch recompiled or transferred."""


def guard_enabled(flag: bool | None = None) -> bool:
    """Explicit flag wins; else the TTS_GUARD env knob (``--guard`` in the
    CLI pins it for the run)."""
    if flag is not None:
        return flag
    return os.environ.get("TTS_GUARD", "0") not in ("", "0")


def _cache_size(jitted) -> int | None:
    fn = getattr(jitted, "_cache_size", None)
    if fn is None:
        return None
    try:
        return int(fn())
    except Exception:
        return None


class SteadyStateGuard:
    """Wraps a jitted step's dispatches; asserts steady-state purity.

    ``enabled=False`` collapses to a no-op so engines can install it
    unconditionally and keep one code path.
    """

    def __init__(self, jitted, label: str = "resident step",
                 enabled: bool = True):
        self.jitted = jitted
        self.label = label
        self.enabled = enabled
        self.steps = 0  # dispatches seen (first one is the warm dispatch)
        self._warm_cache: int | None = None

    @contextmanager
    def step(self):
        if not self.enabled:
            yield
            return
        if self.steps == 0:
            # Warm dispatch: compilation + table/constant upload expected.
            yield
            self.steps += 1
            self._warm_cache = _cache_size(self.jitted)
            return
        import jax

        try:
            with jax.transfer_guard("disallow"):
                yield
        except Exception as e:
            if "isallowed" in str(e):  # jaxlib "Disallowed ... transfer"
                raise GuardViolation(
                    f"{self.label}: implicit transfer in steady-state "
                    f"dispatch {self.steps + 1}: {e}"
                ) from e
            raise
        self.steps += 1
        size = _cache_size(self.jitted)
        if (
            self._warm_cache is not None
            and size is not None
            and size > self._warm_cache
        ):
            raise GuardViolation(
                f"{self.label}: steady-state dispatch {self.steps} "
                f"recompiled (jit cache grew {self._warm_cache} -> {size}); "
                "a shape/dtype/static-arg is varying between dispatches"
            )

    def rearm(self) -> None:
        """Accept the next dispatch as a new warm one (engines call this
        after a sanctioned re-initialization, e.g. the capacity-stall
        offload fallback re-uploading a rebuilt pool)."""
        self.steps = 0
        self._warm_cache = None


# -- compiled-program contracts (`tts check`, analysis/contracts.py) --------

from .contracts import contract


@contract(
    "guard-knob-inert",
    claim="TTS_GUARD=1 never changes the compiled program — the guard "
          "observes dispatches; an instrument that perturbs what it "
          "measures would make every guarded run unrepresentative",
    artifact="variants",
)
def _contract_guard_inert(art, cell):
    if not art.has("off", "guard1"):
        return []
    if art.text("off") == art.text("guard1"):
        return []
    return ["TTS_GUARD leaked into the compiled step"]
