"""Compiled-program contract registry (``tts check``).

The repo's performance claims are claims about *compiled-program
structure*: "the dense survivor path lowers free of sort/scatter", "one
child-value gather per cycle in every mode", "telemetry off is
byte-identical, compiled out not branched", "the pipeline knob never leaks
into the device program".  Until ISSUE 8 each claim was pinned by a one-off
jaxpr assertion in the test file that introduced it — each guarding only
the single knob combination its author traced.  This module is the single
registry those pins migrated into: a :class:`Contract` is a named,
documented claim plus a check over a traced program artifact, **declared
next to the code it pins** (``ops/compaction.py`` declares the dense
contracts, ``engine/resident.py`` the fused-push and donation contracts,
``obs/counters.py``/``obs/phases.py`` the off-identity contracts, …) and
evaluated by ``analysis/program_audit.py`` over every cell of the knob
matrix — tracing only, no execution, CPU is enough.

Registration happens at import time of the declaring module;
``program_audit.load_contracts()`` imports them all.  The registry is
append-only within a process: redefining a name raises (two modules
claiming one contract is a bug, except under module reload, where the
declaring module re-registering its own contract is idempotent).

Artifact families (what a check receives):

* ``resident-step`` — a :class:`StepArtifact` of one matrix cell's
  resident program: the built program object, its closed jaxpr, the
  recursive primitive list, and the lowered StableHLO text (lazy).
* ``compact-ids``   — jaxpr of the bare ``ops.compaction.compact_ids``
  rank inversion for one mode.
* ``lb2-eval``      — jaxprs of the lb2 child/self chunk evaluators at one
  pair-block size.
* ``variants``      — a :class:`VariantArtifact`: jaxpr texts of one base
  configuration traced under several knob settings, for the byte-identity
  and knob-inertness contracts.
* ``cache-key``     — a :class:`CacheKeyArtifact`: the observed program
  cache behavior under knob flips on one problem instance.
* ``lock-graph``    — the static lock-acquisition graph
  (``analysis/lockorder.py``).

The helpers below (``prim_eqns``, ``prim_counts``, ``loop_op_count``,
``child_value_gathers``) are the one implementation of jaxpr-walking the
five migrated test files each used to re-implement.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

__all__ = [
    "CONTRACTS",
    "Contract",
    "CacheKeyArtifact",
    "StepArtifact",
    "VariantArtifact",
    "child_value_gathers",
    "contract",
    "loop_op_count",
    "prim_counts",
    "prim_eqns",
    "subjaxprs",
]


# -- jaxpr walking (shared by contracts, tests, and the fingerprints) ------


def subjaxprs(value):
    """Sub-jaxprs reachable from one eqn param value (while/cond/scan/pjit
    bodies come through params as Jaxpr/ClosedJaxpr or lists of them)."""
    from jax.core import ClosedJaxpr, Jaxpr

    if isinstance(value, Jaxpr):
        return [value]
    if isinstance(value, ClosedJaxpr):
        return [value.jaxpr]
    if isinstance(value, (list, tuple)):
        return [j for v in value for j in subjaxprs(v)]
    return []


def prim_eqns(jaxpr, out=None):
    """Every ``(primitive_name, eqn)`` in a jaxpr, recursing into
    sub-jaxprs.  Accepts an open or closed jaxpr."""
    if hasattr(jaxpr, "jaxpr"):  # ClosedJaxpr
        jaxpr = jaxpr.jaxpr
    if out is None:
        out = []
    for eqn in jaxpr.eqns:
        out.append((eqn.primitive.name, eqn))
        for v in eqn.params.values():
            for sub in subjaxprs(v):
                prim_eqns(sub, out)
    return out


def prim_counts(jaxpr) -> dict[str, int]:
    """Recursive primitive histogram — the op fingerprint unit."""
    counts: dict[str, int] = {}
    for name, _ in prim_eqns(jaxpr):
        counts[name] = counts.get(name, 0) + 1
    return dict(sorted(counts.items()))


def loop_op_count(jaxpr) -> int:
    """Serial device loops: ``fori_loop`` lowers to ``scan`` when the trip
    count is static and ``while`` otherwise — count both, recursively."""
    return sum(1 for name, _ in prim_eqns(jaxpr) if name in ("while", "scan"))


def child_value_gathers(prims, rows: int, lanes: int, vals_dtype) -> list:
    """The gather eqns big enough to be moving child values: any output of
    >= ``rows * lanes`` elements in the pool value dtype.  (Mask gathers —
    bool/int32 keep/lane planes — move no node data and are exempt by the
    fused-push contract's definition.)"""
    out = []
    for name, eqn in prims:
        if name != "gather":
            continue
        if any(
            v.aval.size >= rows * lanes and v.aval.dtype == vals_dtype
            for v in eqn.outvars
        ):
            out.append(eqn)
    return out


# -- the registry ----------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Contract:
    """One named compiled-program claim.

    ``check(artifact, cell)`` returns a list of violation messages (empty =
    the claim holds for that cell).  ``applies(cell)`` filters which matrix
    cells the contract runs on; None = every cell carrying its artifact
    family.  ``declared_in`` records the module that owns the claim (the
    catalogue in docs/ANALYSIS.md is generated from these fields).
    """

    name: str
    claim: str
    artifact: str
    check: Callable
    applies: Callable | None = None
    declared_in: str = ""

    def run(self, artifact, cell) -> list[str]:
        if self.applies is not None and not self.applies(cell):
            return []
        return list(self.check(artifact, cell))


#: name -> Contract.  Populated at import time by the declaring modules
#: (``program_audit.load_contracts()`` imports them all).
CONTRACTS: dict[str, Contract] = {}


def contract(name: str, claim: str, artifact: str,
             applies: Callable | None = None):
    """Decorator: register the decorated check function as a contract.

    Declared next to the code it pins — the decorated function stays
    importable and individually callable (the migrated tests call it
    through :func:`run_one`)."""

    def deco(fn):
        mod = getattr(fn, "__module__", "") or ""
        prev = CONTRACTS.get(name)
        if prev is not None and prev.declared_in != mod:
            raise ValueError(
                f"contract {name!r} already declared in {prev.declared_in}"
            )
        CONTRACTS[name] = Contract(
            name=name, claim=claim, artifact=artifact, check=fn,
            applies=applies, declared_in=mod,
        )
        return fn

    return deco


def get(name: str) -> Contract:
    if name not in CONTRACTS:
        raise KeyError(
            f"unknown contract {name!r} (loaded: {sorted(CONTRACTS)}) — "
            "did program_audit.load_contracts() run?"
        )
    return CONTRACTS[name]


def run_one(name: str, artifact, cell=None) -> list[str]:
    """Evaluate one contract directly (the migrated tests' entry point:
    a test builds its artifact and asserts ``run_one(...) == []``, so the
    registry stays the single owner of the check logic)."""
    c = get(name)
    return list(c.check(artifact, cell))


# -- artifacts -------------------------------------------------------------


class StepArtifact:
    """One matrix cell's resident-step program, traced but never executed.

    ``prog`` is the built ``_ResidentProgram`` (carries the resolved
    compaction mode, S budget, obs/phaseprof flags); ``jaxpr`` its closed
    jaxpr; ``prims`` the recursive primitive list.  ``lowered_text`` lowers
    to StableHLO on first use (donation/aliasing is a lowering-level fact —
    it does not appear in the jaxpr)."""

    def __init__(self, prog, jaxpr, lower_fn=None, eval_counts=None):
        self.prog = prog
        self.jaxpr = jaxpr
        self.prims = prim_eqns(jaxpr)
        self.prim_names = {n for n, _ in self.prims}
        #: Primitive histogram of the BARE bound evaluator (traced alone):
        #: the survivor-path contracts budget against it — the step may
        #: contain the evaluator's own sort/scatter ops, and nothing more.
        self.eval_counts: dict[str, int] = eval_counts or {}
        self._lower_fn = lower_fn
        self._lowered_text: str | None = None

    @property
    def text(self) -> str:
        return str(self.jaxpr)

    @property
    def counts(self) -> dict[str, int]:
        return prim_counts(self.jaxpr)

    @property
    def lowered_text(self) -> str:
        if self._lowered_text is None:
            if self._lower_fn is None:
                raise RuntimeError("artifact built without a lower_fn")
            self._lowered_text = self._lower_fn()
        return self._lowered_text


@dataclasses.dataclass
class VariantArtifact:
    """Jaxpr texts (+ outvar counts) of one base configuration traced under
    several knob settings: ``variants[label] = (text, n_outvars)``.  The
    identity/inertness contracts compare labels; which labels exist is part
    of each contract's own applicability check."""

    variants: dict[str, tuple[str, int]]

    def text(self, label: str) -> str:
        return self.variants[label][0]

    def outvars(self, label: str) -> int:
        return self.variants[label][1]

    def has(self, *labels: str) -> bool:
        return all(lb in self.variants for lb in labels)


@dataclasses.dataclass
class CacheKeyArtifact:
    """Observed program-cache behavior on ONE problem instance:
    ``distinct[knob]`` — programs built under a flip of ``knob`` (must be
    different cache entries); ``shared[knob]`` — programs built under a
    flip of an inert knob (must be the *same* cache entry)."""

    distinct: dict[str, tuple[object, object]]
    shared: dict[str, tuple[object, object]]
