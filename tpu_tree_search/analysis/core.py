"""Pass framework for ``tts lint``: parsed-module model + rule registry.

The repo's whole performance story is "keep the search loop on-device"
(docs/HW_VALIDATION.md: ~360 ms per host dispatch vs ~0.5 ms per on-device
cycle), and its host-thread runtime is lock-based.  Neither invariant is
visible to generic linters, so this package carries a small JAX-aware
static-analysis framework: each rule is a function over a parsed ``Module``
(AST + comments + import aliases) registered under a stable name; the driver
parses every file once, runs all rules, then filters findings through inline
waivers (baseline ratcheting lives in ``baseline.py``).

Rules see a ``Project`` so cross-file facts (e.g. ``guarded-by`` annotations
declared in ``pool/pool.py`` but enforced in ``parallel/dist.py``) are
collected once and shared.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import tokenize
from typing import Callable, Iterable

#: Inline-waiver / marker comment prefix (see docs/ANALYSIS.md).
PRAGMA = "tts-lint:"


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation, pinned to ``file:line:col``."""

    rule: str
    path: str
    line: int
    col: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}"

    @property
    def cell(self) -> str:
        """Baseline-ratchet key: findings are counted per (rule, file) so the
        committed baseline survives line drift from unrelated edits."""
        return f"{self.rule}:{self.path}"


class Module:
    """One parsed source file: AST with parent links, comments by line,
    and resolved import aliases — shared by every rule."""

    def __init__(self, path: str, text: str):
        self.path = path
        self.text = text
        self.tree = ast.parse(text, filename=path)
        self.comments: dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(io.StringIO(text).readline):
                if tok.type == tokenize.COMMENT:
                    self.comments[tok.start[0]] = tok.string
        except tokenize.TokenError:
            pass  # partial comment map is still useful
        # Parent links let rules walk lexically outward (lock scopes,
        # enclosing-function lookup) without re-walking the tree.
        self.parent: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parent[child] = node
        # Import aliases: local name -> dotted module/object path, so rules
        # can resolve ``np.asarray`` -> ``numpy.asarray`` and ``lax.cond``
        # -> ``jax.lax.cond`` regardless of the import spelling.
        self.aliases: dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.aliases[a.asname or a.name] = f"{node.module}.{a.name}"

    # -- helpers shared by rules ------------------------------------------

    def qualname(self, node: ast.AST) -> str | None:
        """Dotted name of a Name/Attribute chain with the import alias
        expanded (``np.asarray`` -> ``numpy.asarray``); None for anything
        that is not a plain dotted chain."""
        parts: list[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        root = self.aliases.get(node.id, node.id)
        parts.append(root)
        return ".".join(reversed(parts))

    def enclosing_function(self, node: ast.AST) -> ast.AST | None:
        cur = self.parent.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                return cur
            cur = self.parent.get(cur)
        return None

    def enclosing_class(self, node: ast.AST) -> ast.ClassDef | None:
        cur = self.parent.get(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # a class defined inside a function still owns its methods,
                # but a function boundary between node and class means node
                # is in a method body — keep climbing to find the class.
                pass
            cur = self.parent.get(cur)
        return None


class Project:
    """All modules of one lint run (cross-file annotation visibility)."""

    def __init__(self, modules: list[Module]):
        self.modules = modules
        self._facts: dict[str, object] = {}

    def fact(self, key: str, build: Callable[["Project"], object]):
        """Memoised project-wide analysis product (e.g. the guarded-by
        annotation table) so N rules x M files don't recompute it."""
        if key not in self._facts:
            self._facts[key] = build(self)
        return self._facts[key]


#: name -> rule function ``(Module, Project) -> list[Finding]``.
RULES: dict[str, Callable[[Module, Project], list[Finding]]] = {}


def rule(name: str):
    def deco(fn):
        RULES[name] = fn
        return fn

    return deco


def _normalize(path: str) -> str:
    """Repo-relative path when under the cwd (stable baseline keys whether
    the caller passed absolute or relative targets); absolute otherwise."""
    ap = os.path.abspath(path)
    cwd = os.getcwd()
    if ap == cwd or ap.startswith(cwd + os.sep):
        return os.path.relpath(ap, cwd)
    return ap


def iter_py_files(paths: Iterable[str]) -> list[str]:
    out: list[str] = []
    paths = [_normalize(p) for p in paths]
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = [
                    d for d in dirs
                    if not d.startswith(".") and d != "__pycache__"
                ]
                out.extend(
                    os.path.join(root, f) for f in files if f.endswith(".py")
                )
        elif p.endswith(".py"):
            out.append(p)
    return sorted(set(out))


def parse_modules(paths: Iterable[str]) -> tuple[list[Module], list[Finding]]:
    """Parse every file; syntax errors become findings (rule ``parse``)
    instead of crashing the whole run."""
    modules: list[Module] = []
    errors: list[Finding] = []
    for path in iter_py_files(paths):
        try:
            with open(path, encoding="utf-8") as f:
                text = f.read()
            modules.append(Module(path, text))
        except SyntaxError as e:
            errors.append(
                Finding("parse", path, e.lineno or 1, e.offset or 0,
                        f"syntax error: {e.msg}")
            )
    return modules, errors


def run_rules(modules: list[Module],
              only: Iterable[str] | None = None) -> list[Finding]:
    # Import for registration side effects (kept out of module import time
    # so `tpu_tree_search.analysis.guard` stays importable alone).
    from . import jax_rules, lockorder, locks  # noqa: F401

    project = Project(modules)
    selected = set(only) if only is not None else set(RULES)
    findings: list[Finding] = []
    for mod in modules:
        for name, fn in sorted(RULES.items()):
            if name in selected:
                findings.extend(fn(mod, project))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings
