"""``tts check`` — the compiled-program contract auditor.

Enumerates the **knob matrix** (problem family x bound x ``TTS_COMPACT`` x
``TTS_LB2_PAIRBLOCK`` x ``TTS_OBS`` x ``TTS_PHASEPROF``, with
``TTS_PIPELINE``/``TTS_GUARD`` covered by inertness variants), traces every
cell's resident program with ``jax.make_jaxpr`` / lowered StableHLO on
whatever backend is present (CPU is enough — **no execution happens**),
and evaluates every registered :class:`~.contracts.Contract` against the
artifacts.  Three kinds of output:

* **Contract violations** — a named claim (see ``docs/ANALYSIS.md``
  catalogue) failing on a named cell.  Always fatal: contracts carry no
  accepted-debt baseline.
* **Fingerprint drift** — each cell's recursive primitive histogram is
  compared against the committed ``.tts-contracts.json``
  (``tts check --update`` regenerates it).  Drift fails with the named
  cell and a per-op diff; this is the same commit-the-expected-state
  ratchet discipline as ``tts lint``'s baseline, at program granularity.
  The baseline records the jax version it was traced under — under a
  different jax the op-level comparison is skipped with a warning (XLA's
  lowering is not stable across releases; the structural contracts above
  still run and still gate).
* **Lock-order audit** — the static lock-acquisition graph
  (``analysis/lockorder.py``) evaluated as a contract over the package.

The knob pins are process-local and restored: the audit clears every
knob it does not set, so ``tts check`` is deterministic under CI's
``TTS_OBS=1`` / ``TTS_COMPACT=<mode>`` matrix jobs too.
"""

from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import json
import os

from .contracts import (
    CONTRACTS,
    CacheKeyArtifact,
    StepArtifact,
    VariantArtifact,
    contract,
    prim_counts,
)
from .core import Finding, Project, parse_modules

DEFAULT_BASELINE = ".tts-contracts.json"

#: Every knob a cell may pin; ``_pin`` clears the rest so the audit is
#: deterministic under CI's env-matrix jobs.
KNOBS = (
    "TTS_COMPACT", "TTS_OBS", "TTS_PHASEPROF", "TTS_LB2_PAIRBLOCK",
    "TTS_PIPELINE", "TTS_K", "TTS_GUARD", "TTS_PALLAS", "TTS_PALLAS_LB2",
    "TTS_LB2_STAGED", "TTS_XLA_TRACE", "TTS_FLIGHTREC", "TTS_COSTMODEL",
    "TTS_QUALITY", "TTS_MEGAKERNEL", "TTS_MEGAKERNEL_MT", "TTS_STEAL",
    "TTS_PODS", "TTS_SIM_LAT_ICI", "TTS_SIM_LAT_DCN", "TTS_NARROW",
    "TTS_HBM_GBPS", "TTS_KERNEL_BACKEND", "TTS_PALLAS_GPU_MB",
)

#: Matrix axes (the lb2 families add the pair-block axis).
COMPACT_AXIS = ("auto", "scatter", "sort", "search", "dense")
OBS_AXIS = ("0", "1")
PHASEPROF_AXIS = ("0", "1")
PAIRBLOCK_AXIS = ("1", "4", "auto")

FAMILIES = ("nqueens", "pfsp-lb1", "pfsp-lb1d", "pfsp-lb2")


def load_contracts() -> dict:
    """Import every contract-declaring module (registration side effects)
    and return the registry."""
    from ..engine import batched, pipeline, resident  # noqa: F401
    from ..obs import counters, phases, quality  # noqa: F401
    from ..ops import backend, compaction, megakernel, pfsp_device  # noqa: F401
    from ..parallel import topology  # noqa: F401
    from . import guard, lockorder  # noqa: F401

    return CONTRACTS


@contextlib.contextmanager
def _pin(env: dict[str, str]):
    """Pin exactly ``env`` over the audit knobs (everything else unset);
    restore on exit."""
    prev = {k: os.environ.get(k) for k in KNOBS}
    for k in KNOBS:
        os.environ.pop(k, None)
    os.environ.update({k: v for k, v in env.items() if v is not None})
    try:
        yield
    finally:
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


# -- the matrix ------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Cell:
    """One knob-matrix cell of one problem family."""

    family: str
    compact: str = "auto"
    obs: str = "0"
    phaseprof: str = "0"
    pairblock: str | None = None
    # None = knob unset (the historical matrix — keys stay byte-stable);
    # "force" pins the one-kernel cycle (ops/megakernel.py) armed, or the
    # refusal fallback where the family cannot arm (pfsp-lb1d).
    megakernel: str | None = None
    # None = TTS_MEGAKERNEL_MT unset (keys stay byte-stable); a width
    # pins the streamed pool-tile axis so the grid form (grid = M/Mt > 1)
    # gets its own audited cell.
    mt: str | None = None

    @property
    def key(self) -> str:
        s = f"{self.family}|compact={self.compact}|obs={self.obs}|ph={self.phaseprof}"
        if self.pairblock is not None:
            s += f"|pb={self.pairblock}"
        if self.megakernel is not None:
            s += f"|mk={self.megakernel}"
        if self.mt is not None:
            s += f"|mt={self.mt}"
        return s

    def env(self) -> dict[str, str]:
        e = {
            "TTS_COMPACT": self.compact,
            "TTS_OBS": self.obs,
            "TTS_PHASEPROF": self.phaseprof,
        }
        if self.pairblock is not None:
            e["TTS_LB2_PAIRBLOCK"] = self.pairblock
        if self.megakernel is not None:
            e["TTS_MEGAKERNEL"] = self.megakernel
        if self.mt is not None:
            e["TTS_MEGAKERNEL_MT"] = self.mt
        return e


def _family_factory(family: str):
    """(problem factory, build params) for one family.  Shapes are the
    smallest ones that still exercise every structural path (tracing cost,
    not runtime, is what matters — nothing here executes)."""
    from ..problems import NQueensProblem, PFSPProblem
    from ..problems.pfsp import taillard

    if family == "nqueens":
        return (lambda: NQueensProblem(N=8)), dict(m=5, M=64, K=4)
    if family == "pfsp-lb1":
        return (lambda: PFSPProblem(
            lb="lb1", ub=0, p_times=taillard.reduced_instance(14, 10, 5)
        )), dict(m=5, M=128, K=4)
    if family == "pfsp-lb1d":
        return (lambda: PFSPProblem(
            lb="lb1_d", ub=0, p_times=taillard.reduced_instance(14, 10, 5)
        )), dict(m=5, M=128, K=4)
    if family == "pfsp-lb2":
        return (lambda: PFSPProblem(
            lb="lb2", ub=0, p_times=taillard.reduced_instance(14, 8, 5)
        )), dict(m=5, M=64, K=4)
    raise ValueError(f"unknown family {family!r} (know {FAMILIES})")


def matrix_cells(families=None, compact=None, obs=None, phaseprof=None,
                 pairblock=None) -> list[Cell]:
    """The full (or axis-filtered) knob matrix."""
    out: list[Cell] = []
    for fam in families or FAMILIES:
        pbs = (pairblock or PAIRBLOCK_AXIS) if fam == "pfsp-lb2" else (None,)
        for c in compact or COMPACT_AXIS:
            for o in obs or OBS_AXIS:
                for ph in phaseprof or PHASEPROF_AXIS:
                    for pb in pbs:
                        out.append(Cell(fam, c, o, ph, pb))
        # One-kernel cycle axis (TTS_MEGAKERNEL=force): compact stays
        # auto (the fused cycle subsumes the survivor path), pairblock
        # stays auto on lb2; pfsp-lb1d pins the REFUSAL fallback — the
        # megakernel-single-call contract asserts a recorded reason and
        # zero pallas_calls there.
        pb = "auto" if fam == "pfsp-lb2" else None
        for o in obs or OBS_AXIS:
            for ph in phaseprof or PHASEPROF_AXIS:
                out.append(Cell(fam, "auto", o, ph, pb, megakernel="force"))
        # Streamed-grid axis (TTS_MEGAKERNEL_MT, ops/megakernel.py): one
        # tiled force cell per armable family — Mt=16 divides every matrix
        # M (64/128) so the pool axis genuinely tiles (grid > 1). pfsp-lb1d
        # is the refusal family; the tile width is inert there and the
        # force cells above already audit the fallback.
        if fam != "pfsp-lb1d":
            out.append(Cell(fam, "auto", (obs or OBS_AXIS)[0],
                            (phaseprof or PHASEPROF_AXIS)[0], pb,
                            megakernel="force", mt="16"))
    return out


def trace_cell(cell: Cell, problem=None, params=None) -> StepArtifact:
    """Build + trace one cell's resident program (no execution).  A shared
    ``problem`` instance exercises the program cache across cells; None
    builds a fresh one."""
    import jax

    factory, p = _family_factory(cell.family)
    if problem is None:
        problem = factory()
    if params is None:
        params = p
    from ..engine.resident import _make_program, resolve_capacity

    with _pin(cell.env()):
        capacity, M = resolve_capacity(problem, params["M"], None)
        prog = _make_program(problem, params["m"], M, params["K"], capacity,
                             jax.devices()[0])
        state = prog.init_state({}, getattr(problem, "initial_ub", 0))
        jaxpr = jax.make_jaxpr(prog._step)(*state)
        eval_counts = _eval_counts(prog, M)
    return StepArtifact(
        prog, jaxpr, lower_fn=lambda: prog._step.lower(*state).as_text(),
        eval_counts=eval_counts,
    )


def _eval_counts(prog, M: int) -> dict[str, int]:
    """Primitive histogram of the cell's BARE bound evaluator — the
    op budget the survivor-path contracts charge against (an lb2
    evaluator's one-hot free-flag scatter is the evaluator's business;
    the dense survivor path may add nothing on top)."""
    import jax
    import jax.numpy as jnp

    ev = prog._make_eval()
    n = prog.problem.child_slots
    args = (
        jnp.zeros((M, n), jnp.int32),
        jnp.zeros((M,), jnp.int32),
        jnp.zeros((M,), bool),
        jnp.int32(0),
    )
    return prim_counts(jax.make_jaxpr(ev)(*args))


def _contracts_for(artifact_kind: str):
    return [c for c in CONTRACTS.values() if c.artifact == artifact_kind]


def _violations(name: str, cell_key: str, msgs) -> list[Finding]:
    return [
        Finding(f"contract:{name}", cell_key, 0, 0, m) for m in msgs
    ]


def audit_matrix(cells, fingerprints: dict | None = None) -> list[Finding]:
    """Trace every cell and run the resident-step contracts.  When
    ``fingerprints`` is given, each cell's op histogram + outvar count is
    recorded into it under the cell key."""
    findings: list[Finding] = []
    by_family: dict[str, list[Cell]] = {}
    for c in cells:
        by_family.setdefault(c.family, []).append(c)
    step_contracts = _contracts_for("resident-step")
    for fam, fam_cells in by_family.items():
        factory, params = _family_factory(fam)
        problem = factory()  # shared per family: exercises the cache keys
        for cell in fam_cells:
            art = trace_cell(cell, problem=problem, params=params)
            for c in step_contracts:
                findings.extend(_violations(c.name, cell.key, c.run(art, cell)))
            if fingerprints is not None:
                fingerprints[cell.key] = {
                    "ops": art.counts,
                    "outvars": len(art.jaxpr.jaxpr.outvars),
                }
    return findings


def audit_compact_ids(fingerprints: dict | None = None) -> list[Finding]:
    """The bare rank-inversion contracts (`ops/compaction.compact_ids`),
    traced per mode on the (64, 20)-grid shape the tests pinned."""
    import jax
    import numpy as np

    from ..ops.compaction import MODES, compact_ids

    findings: list[Finding] = []
    ids_contracts = _contracts_for("compact-ids")
    with _pin({}):
        for mode in MODES:
            jaxpr = jax.make_jaxpr(
                lambda k, m=mode: compact_ids(k, 640, m)
            )(np.zeros((64, 20), bool))
            art = {"mode": mode, "jaxpr": jaxpr}
            key = f"compact-ids|mode={mode}"
            for c in ids_contracts:
                findings.extend(_violations(c.name, key, c.run(art, None)))
            if fingerprints is not None:
                fingerprints[key] = {"ops": prim_counts(jaxpr)}
    return findings


def audit_lb2_eval(fingerprints: dict | None = None,
                   pairblocks=(1, 8, None)) -> list[Finding]:
    """The lb2 pair-axis contracts on the published blocked shape (ta021:
    P=190 pairs — where the auto policy genuinely blocks, so the loop-free
    pin is not vacuous).  ``None`` in ``pairblocks`` = the auto
    resolution."""
    import jax
    import jax.numpy as jnp

    from ..ops import pfsp_device as P
    from ..problems import PFSPProblem

    findings: list[Finding] = []
    eval_contracts = _contracts_for("lb2-eval")
    with _pin({}):
        prob = PFSPProblem(inst=21, lb="lb2", ub=1)
        t = prob.device_tables()
        n = prob.jobs
        args = (jnp.zeros((8, n), jnp.int32), jnp.zeros((8,), jnp.int32),
                t.ptm_t, t.min_heads, t.min_tails, t.pairs, t.lags,
                t.johnson_schedules)
        for pb in pairblocks:
            pb_resolved = P.lb2_pairblock(t.pairs.shape[0], n) if pb is None \
                else pb
            child = jax.make_jaxpr(
                lambda *a: P._lb2_chunk(*a, pairblock=pb_resolved))(*args)
            self_ = jax.make_jaxpr(
                lambda *a: P._lb2_self_chunk(*a, pairblock=pb_resolved))(*args)
            art = {"pairblock": pb_resolved, "auto": pb is None,
                   "child": child, "self": self_}
            key = f"lb2-eval|pb={'auto:' if pb is None else ''}{pb_resolved}"
            for c in eval_contracts:
                findings.extend(_violations(c.name, key, c.run(art, None)))
            if fingerprints is not None:
                fingerprints[key] = {
                    "ops": prim_counts(child),
                    "ops_self": prim_counts(self_),
                }
    return findings


def audit_batched(fingerprints: dict | None = None,
                  widths=(1, 2)) -> list[Finding]:
    """The instance-batch contracts (``engine/batched.py``): B=1 jaxpr
    byte-identity against the solo resident step, and splice-aval
    equality (``make_slot`` leaves == the compiled step's per-slot input
    avals) for each audited width.  Tracing only — nothing executes."""
    import jax

    from ..engine.batched import make_batched_program
    from ..engine.resident import _make_program, resolve_capacity

    factory, params = _family_factory("nqueens")
    findings: list[Finding] = []
    step_contracts = _contracts_for("batched-step")
    with _pin({}):
        problem = factory()
        capacity, M = resolve_capacity(problem, params["M"], None)
        dev = jax.devices()[0]
        inner = _make_program(problem, params["m"], M, params["K"],
                              capacity, dev)
        state = inner.init_state({}, getattr(problem, "initial_ub", 0))
        resident_text = str(jax.make_jaxpr(inner._step)(*state))
        for B in widths:
            prog = make_batched_program(problem, B, params["m"], M,
                                        params["K"], capacity, dev)
            args = [leaf for _ in range(B) for leaf in state]
            jaxpr = jax.make_jaxpr(prog._step)(*args)
            art = {
                "B": B,
                "b1_text": str(jaxpr) if B == 1 else None,
                "resident_text": resident_text,
                "slot_avals": [(tuple(s.shape), str(s.dtype))
                               for s in prog.slot_avals()],
                "carry_avals": [(tuple(a.shape), str(a.dtype))
                                for a in jaxpr.in_avals],
            }
            key = f"batched|nqueens|B{B}"
            for c in step_contracts:
                findings.extend(_violations(c.name, key, c.run(art, None)))
            if fingerprints is not None:
                fingerprints[key] = {
                    "ops": prim_counts(jaxpr),
                    "outvars": len(jaxpr.jaxpr.outvars),
                }
    return findings


# -- variant (byte-identity / knob-inertness) artifacts --------------------

#: label -> env pins.  "off" is the all-unset baseline every identity
#: contract compares against.
VARIANT_ENVS = {
    "off": {},
    "obs0": {"TTS_OBS": "0"},
    "obs-host": {"TTS_OBS": "host"},
    "obs1": {"TTS_OBS": "1"},
    "phase0": {"TTS_PHASEPROF": "0"},
    "phase1": {"TTS_PHASEPROF": "1"},
    "phase1-obs1": {"TTS_PHASEPROF": "1", "TTS_OBS": "1"},
    "pipe0": {"TTS_PIPELINE": "0"},
    "pipe2": {"TTS_PIPELINE": "2"},
    "guard1": {"TTS_GUARD": "1"},
    "quality1": {"TTS_QUALITY": "1"},
    "mk0": {"TTS_MEGAKERNEL": "0"},
    # Streamed-grid axis: off must stay byte-identical under a pinned tile
    # width (the knob only matters once the kernel arms), and the tiled
    # force build must keep the off step's outvar signature
    # (megakernel-tiled-identity, ops/megakernel.py).
    "mk0-mt": {"TTS_MEGAKERNEL": "0", "TTS_MEGAKERNEL_MT": "16"},
    "mk-tiled": {"TTS_MEGAKERNEL": "force", "TTS_MEGAKERNEL_MT": "16"},
    "steal-flat": {"TTS_STEAL": "flat"},
    "steal-hier": {"TTS_STEAL": "hier", "TTS_PODS": "2"},
    "narrow0": {"TTS_NARROW": "0"},
    # Kernel-backend seam (ops/backend.py): auto/jnp/tpu must stay
    # byte-identical to "off" on this non-GPU audit host; gpu may change
    # the program body but never the step's carry signature
    # (kernel-backend-inert).
    "kb-auto": {"TTS_KERNEL_BACKEND": "auto"},
    "kb-jnp": {"TTS_KERNEL_BACKEND": "jnp"},
    "kb-tpu": {"TTS_KERNEL_BACKEND": "tpu"},
    "kb-gpu": {"TTS_KERNEL_BACKEND": "gpu"},
}


def variant_artifact(family: str, labels=None) -> VariantArtifact:
    """Trace one family's step under each variant env — every label on a
    FRESH problem instance, so identity is a fact about the build, never a
    cache hit."""
    import jax

    from ..engine.resident import _make_program, resolve_capacity
    from ..ops.compaction import resolve_compact_mode

    factory, params = _family_factory(family)
    variants: dict[str, tuple[str, int]] = {}

    def trace(env) -> tuple[str, int]:
        problem = factory()
        with _pin(env):
            capacity, M = resolve_capacity(problem, params["M"], None)
            prog = _make_program(problem, params["m"], M, params["K"],
                                 capacity, jax.devices()[0])
            state = prog.init_state({}, getattr(problem, "initial_ub", 0))
            jaxpr = jax.make_jaxpr(prog._step)(*state)
        return str(jaxpr), len(jaxpr.jaxpr.outvars)

    for label, env in VARIANT_ENVS.items():
        if labels is not None and label not in labels:
            continue
        variants[label] = trace(env)
    if labels is None or any(lb.startswith("compact-") for lb in labels):
        # auto-vs-explicit identity: trace auto and the mode it resolves to.
        with _pin({"TTS_COMPACT": "auto"}):
            _, M0 = resolve_capacity(factory(), params["M"], None)
            resolved = resolve_compact_mode(
                factory(), M0, factory().child_slots
            )
        variants["compact-auto"] = trace({"TTS_COMPACT": "auto"})
        variants[f"compact-{resolved}"] = trace({"TTS_COMPACT": resolved})
    return VariantArtifact(variants)


def audit_variants(families=None) -> list[Finding]:
    findings: list[Finding] = []
    var_contracts = _contracts_for("variants")
    for fam in families or FAMILIES:
        art = variant_artifact(fam)
        for c in var_contracts:
            findings.extend(_violations(c.name, f"{fam}|variants",
                                        c.run(art, None)))
    return findings


def cache_key_artifact(family: str) -> CacheKeyArtifact:
    """Observed ``_make_program`` cache behavior on one instance: knobs
    that are baked into the compiled program must rebuild on a flip; the
    host-only knobs must hit the same cached program."""
    import jax

    from ..engine.resident import _make_program, resolve_capacity

    factory, params = _family_factory(family)
    problem = factory()

    def build(env):
        with _pin(env):
            capacity, M = resolve_capacity(problem, params["M"], None)
            return _make_program(problem, params["m"], M, params["K"],
                                 capacity, jax.devices()[0])

    base = {"TTS_COMPACT": "sort"}
    p0 = build(base)
    distinct = {
        "TTS_COMPACT": (p0, build({**base, "TTS_COMPACT": "search"})),
        "TTS_OBS": (p0, build({**base, "TTS_OBS": "1"})),
        "TTS_PHASEPROF": (p0, build({**base, "TTS_PHASEPROF": "1"})),
        # The one-kernel cycle is baked into the step (and into the
        # routing token even when the resolver refuses), so a knob flip
        # must rebuild — a stale cached off-program under force (or vice
        # versa) would silently run the wrong cycle body.
        "TTS_MEGAKERNEL": (
            build({**base, "TTS_MEGAKERNEL": "0"}),
            build({**base, "TTS_MEGAKERNEL": "force"}),
        ),
        # The streamed pool-tile width changes the armed cycle's grid
        # (single-tile resident vs tiled streaming), so a pinned Mt under
        # force must build a distinct program from plain force.
        "TTS_MEGAKERNEL_MT": (
            build({**base, "TTS_MEGAKERNEL": "force"}),
            build({**base, "TTS_MEGAKERNEL": "force",
                   "TTS_MEGAKERNEL_MT": "16"}),
        ),
        # Narrow host storage: the device step jaxpr is knob-inert
        # (`narrow-knob-inert`), but the HOST staging avals the program
        # was built against are not — a flip must rebuild so a stale
        # program never receives the other layout's arrays.
        "TTS_NARROW": (
            build({**base, "TTS_NARROW": "auto"}),
            build({**base, "TTS_NARROW": "0"}),
        ),
        # The kernel-backend flavor rides the routing token (raw knob +
        # resolved kind), so a flip to the gpu flavor must rebuild — a
        # stale auto program under =gpu would run the wrong kernel body.
        "TTS_KERNEL_BACKEND": (
            p0,
            build({**base, "TTS_KERNEL_BACKEND": "gpu"}),
        ),
    }
    if family == "pfsp-lb2":
        distinct["TTS_LB2_PAIRBLOCK"] = (
            build({**base, "TTS_LB2_PAIRBLOCK": "1"}),
            build({**base, "TTS_LB2_PAIRBLOCK": "4"}),
        )
    shared = {
        "TTS_PIPELINE": (p0, build({**base, "TTS_PIPELINE": "2"})),
        "TTS_GUARD": (p0, build({**base, "TTS_GUARD": "1"})),
        "TTS_STEAL": (p0, build({**base, "TTS_STEAL": "hier"})),
        "rebuild": (p0, build(base)),
    }
    return CacheKeyArtifact(distinct=distinct, shared=shared)


def audit_cache_keys(families=None) -> list[Finding]:
    findings: list[Finding] = []
    key_contracts = _contracts_for("cache-key")
    for fam in families or FAMILIES:
        art = cache_key_artifact(fam)
        for c in key_contracts:
            findings.extend(_violations(c.name, f"{fam}|cache-key",
                                        c.run(art, None)))
    return findings


def audit_locks(paths=None) -> list[Finding]:
    """The lock-order contract over the package sources (or ``paths``)."""
    from . import lockorder

    if paths is None:
        pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        paths = ["tpu_tree_search" if os.path.isdir("tpu_tree_search") else pkg]
    modules, parse_errors = parse_modules(paths)
    findings = list(parse_errors)
    graph = lockorder.build_graph(Project(modules))
    for c in _contracts_for("lock-graph"):
        findings.extend(_violations(c.name, "lock-graph", c.run(graph, None)))
    return findings


# -- the op-fingerprint baseline -------------------------------------------


def _hash_cells(cells: dict) -> str:
    blob = json.dumps(cells, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def load_baseline(path: str) -> dict | None:
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except (FileNotFoundError, ValueError):
        return None


def save_baseline(path: str, cells: dict) -> dict:
    import jax

    doc = {
        "comment": "tts check op-fingerprint baseline: per-cell primitive "
                   "histogram of every compiled program in the knob "
                   "matrix; regenerate with `tts check --update` (drift "
                   "must be intentional and reviewed)",
        "jax": jax.__version__,
        "fingerprint": _hash_cells(cells),
        "cells": cells,
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    return doc


def committed_fingerprint(path: str | None = None) -> str | None:
    """The committed baseline's overall fingerprint hash — bench rows
    record it so a banked perf number is tied to the exact program
    structure it measured (ISSUE 8 satellite)."""
    if path is None:
        path = DEFAULT_BASELINE if os.path.exists(DEFAULT_BASELINE) else \
            os.path.join(
                os.path.dirname(os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__)))),
                DEFAULT_BASELINE,
            )
    doc = load_baseline(path)
    return doc.get("fingerprint") if doc else None


def _diff_ops(old: dict, new: dict) -> str:
    """Readable per-op delta: the jaxpr-level diff a drift report needs."""
    deltas = []
    for op in sorted(set(old) | set(new)):
        a, b = old.get(op, 0), new.get(op, 0)
        if a != b:
            deltas.append(f"{op}: {a} -> {b}")
    return "; ".join(deltas) or "(identical op counts)"


@contract(
    "op-fingerprint",
    claim="every matrix cell's recursive primitive histogram matches the "
          "committed .tts-contracts.json baseline — compiled-program "
          "structure cannot drift silently (`tts check --update` accepts "
          "reviewed drift; a baseline traced under a different jax "
          "version is reported as a warning, not compared op-by-op)",
    artifact="fingerprint",
)
def _check_fingerprint(art, cell=None):
    current, doc = art["current"], art["baseline"]
    out = []
    if doc is None:
        return [f"no committed baseline at {art['path']} — run "
                "`tts check --update` and commit it"]
    base_cells = doc.get("cells", {})
    for key in sorted(current):
        if key not in base_cells:
            out.append(f"{key}: cell missing from baseline (new matrix "
                       "cell? run --update)")
            continue
        old, new = base_cells[key], current[key]
        if old.get("ops") != new.get("ops"):
            out.append(f"{key}: op drift — {_diff_ops(old.get('ops', {}), new.get('ops', {}))}")
        elif old.get("outvars") != new.get("outvars"):
            out.append(f"{key}: outvar count {old.get('outvars')} -> "
                       f"{new.get('outvars')}")
    for key in sorted(set(base_cells) - set(current)):
        out.append(f"{key}: baseline cell no longer produced (stale "
                   "baseline? run --update)")
    return out


# -- orchestration ---------------------------------------------------------


@dataclasses.dataclass
class CheckResult:
    findings: list[Finding]
    fingerprints: dict
    cells: int
    contracts: int
    warnings: list[str]
    updated: str | None = None

    @property
    def fingerprint(self) -> str:
        return _hash_cells(self.fingerprints)


def run_check(families=None, update: bool = False,
              baseline_path: str | None = None,
              lock_paths=None, with_locks: bool = True,
              with_fingerprint: bool = True) -> CheckResult:
    """The full audit (the ``tts check`` entry point)."""
    load_contracts()
    baseline_path = baseline_path or DEFAULT_BASELINE
    findings: list[Finding] = []
    fingerprints: dict = {}
    warnings: list[str] = []
    cells = matrix_cells(families=families)
    findings += audit_matrix(cells, fingerprints)
    findings += audit_variants(families)
    findings += audit_cache_keys(families)
    if families is None:
        findings += audit_compact_ids(fingerprints)
        findings += audit_lb2_eval(fingerprints)
        findings += audit_batched(fingerprints)
    if with_locks:
        findings += audit_locks(lock_paths)
    updated = None
    if update:
        save_baseline(baseline_path, fingerprints)
        updated = baseline_path
    elif with_fingerprint and families is None:
        doc = load_baseline(baseline_path)
        if doc is not None:
            import jax

            if doc.get("jax") != jax.__version__:
                warnings.append(
                    f"baseline {baseline_path} traced under jax "
                    f"{doc.get('jax')}, running {jax.__version__}: op-level "
                    "comparison skipped (re-run --update under this jax to "
                    "re-arm the fingerprint gate)"
                )
                doc = False  # sentinel: skip comparison, not "missing"
        if doc is not False:
            art = {"current": fingerprints, "baseline": doc,
                   "path": baseline_path}
            findings += _violations(
                "op-fingerprint", "fingerprint",
                CONTRACTS["op-fingerprint"].run(art, None),
            )
    n_contracts = len(load_contracts())
    findings.sort(key=lambda f: (f.path, f.rule, f.message))
    return CheckResult(findings, fingerprints, len(cells), n_contracts,
                       warnings, updated)


# -- CLI -------------------------------------------------------------------


def add_check_args(p) -> None:
    p.add_argument("--update", action="store_true",
                   help="regenerate the op-fingerprint baseline "
                        f"(./{DEFAULT_BASELINE}) from the current programs")
    p.add_argument("--baseline", default=None,
                   help=f"fingerprint baseline path (default ./{DEFAULT_BASELINE})")
    p.add_argument("--family", action="append", default=None, dest="families",
                   metavar="NAME", choices=FAMILIES,
                   help="audit only this problem family (repeatable; "
                        "skips the fingerprint gate, which is whole-matrix)")
    p.add_argument("--no-locks", action="store_true",
                   help="skip the lock-order audit")
    p.add_argument("--list", action="store_true", dest="list_contracts",
                   help="print the contract catalogue and exit")
    p.add_argument("--json", action="store_true", dest="check_json",
                   help="emit one JSON object instead of text")


def run_check_cli(args) -> int:
    if args.list_contracts:
        for name, c in sorted(load_contracts().items()):
            print(f"{name}  [{c.artifact}]  ({c.declared_in})")
            print(f"    {c.claim}")
        return 0
    if args.update and args.families:
        print("tts check: --update regenerates the WHOLE-matrix baseline; "
              "it cannot be combined with --family")
        return 2
    res = run_check(
        families=args.families, update=args.update,
        baseline_path=args.baseline,
        with_locks=not args.no_locks,
    )
    if args.check_json:
        print(json.dumps({
            "findings": [vars(f) for f in res.findings],
            "cells": res.cells,
            "contracts": res.contracts,
            "fingerprint": res.fingerprint,
            "warnings": res.warnings,
            "updated": res.updated,
        }))
        return 1 if res.findings else 0
    for w in res.warnings:
        print(f"warning: {w}")
    for f in res.findings:
        print(f.render())
    if res.updated:
        print(f"fingerprint baseline written: {res.updated} "
              f"({len(res.fingerprints)} cells, hash {res.fingerprint})")
    print(
        f"tts check: {len(res.findings)} finding(s) over {res.cells} matrix "
        f"cells, {res.contracts} contracts (fingerprint {res.fingerprint})"
    )
    return 1 if res.findings else 0
