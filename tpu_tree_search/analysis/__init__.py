"""``tpu_tree_search.analysis`` — JAX-aware static analysis (``tts lint``)
and runtime guards for the search engines.

Static side: a pluggable AST-pass framework (``core``), four rules —
``host-sync-in-jit``, ``tracer-branch``, ``guarded-by``,
``static-arg-hygiene`` (``jax_rules`` / ``locks``) — inline waivers and a
committed count-ratchet baseline (``baseline``). Runtime side: the
``TTS_GUARD=1`` steady-state transfer/recompile guard (``guard``). See
docs/ANALYSIS.md for the rule catalogue and annotation grammar.
"""

from __future__ import annotations

import argparse
import json
import os

from .baseline import (
    apply_waivers,
    load_baseline,
    ratchet,
    save_baseline,
)
from .core import RULES, Finding, parse_modules, run_rules
from .guard import GuardViolation, SteadyStateGuard, guard_enabled

# Rule modules register themselves into RULES at import time.
from . import jax_rules as _jax_rules  # noqa: E402,F401  (registration)
from . import lockorder as _lockorder  # noqa: E402,F401  (registration)
from . import locks as _locks  # noqa: E402,F401  (registration)

__all__ = [
    "Finding",
    "GuardViolation",
    "RULES",
    "SteadyStateGuard",
    "add_lint_args",
    "guard_enabled",
    "lint",
    "lint_main",
    "run_lint_cli",
]

DEFAULT_BASELINE = ".tts-lint-baseline.json"


def lint(paths, baseline: dict[str, int] | None = None,
         rules=None) -> dict[str, list[Finding]]:
    """Run the analysis; returns findings split into ``new`` (fail the
    build), ``baselined`` (accepted debt) and ``waived`` (inline-justified).
    """
    modules, parse_errors = parse_modules(paths)
    findings = run_rules(modules, only=rules)
    active, waived = apply_waivers(
        modules, findings,
        selected_rules=set(rules) if rules is not None else None,
    )
    active = parse_errors + active
    new, old = ratchet(active, baseline or {})
    return {"new": new, "baselined": old, "waived": waived}


def _default_paths() -> list[str]:
    # Repo checkout first (package + the bench/scripts harnesses — ISSUE 8
    # widened the default scan scope to everything the CI gate covers);
    # fall back to the installed package so `tts lint` works from anywhere.
    if os.path.isdir("tpu_tree_search"):
        paths = ["tpu_tree_search"]
        if os.path.isfile("bench.py"):
            paths.append("bench.py")
        if os.path.isdir("scripts"):
            paths.append("scripts")
        return paths
    return [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]


def add_lint_args(p: argparse.ArgumentParser) -> None:
    p.add_argument("paths", nargs="*", default=None,
                   help="files/dirs to lint (default: the package)")
    p.add_argument("--baseline", default=None,
                   help=f"ratchet file (default: ./{DEFAULT_BASELINE} "
                        "when present)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline: report ALL findings")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline to the current finding set")
    p.add_argument("--rule", action="append", default=None, dest="rules",
                   metavar="NAME", help="run only this rule (repeatable)")
    p.add_argument("--json", action="store_true", dest="lint_json",
                   help="emit one JSON object instead of text")
    p.add_argument("--show-waived", action="store_true",
                   help="also list waived findings")


def run_lint_cli(args) -> int:
    paths = args.paths or _default_paths()
    baseline_path = args.baseline
    if baseline_path is None and os.path.exists(DEFAULT_BASELINE):
        baseline_path = DEFAULT_BASELINE
    baseline = {} if args.no_baseline else load_baseline(baseline_path)
    res = lint(paths, baseline, rules=args.rules)
    if args.update_baseline:
        target = args.baseline or DEFAULT_BASELINE
        save_baseline(target, res["new"] + res["baselined"])
        print(f"baseline written: {target} "
              f"({len(res['new']) + len(res['baselined'])} finding(s))")
        return 0
    if args.lint_json:
        print(json.dumps({
            k: [vars(f) for f in v] for k, v in res.items()
        }))
        return 1 if res["new"] else 0
    for f in res["new"]:
        print(f.render())
    if args.show_waived:
        for f in res["waived"]:
            print(f"{f.render()}  (waived)")
    n_new, n_old, n_waived = (
        len(res["new"]), len(res["baselined"]), len(res["waived"])
    )
    print(
        f"tts lint: {n_new} new finding(s), {n_old} baselined, "
        f"{n_waived} waived"
    )
    return 1 if res["new"] else 0


def lint_main(argv=None) -> int:
    """`python -m tpu_tree_search.analysis` entry point."""
    p = argparse.ArgumentParser(
        prog="python -m tpu_tree_search.analysis",
        description="JAX-aware static analysis for tpu_tree_search "
                    "(see docs/ANALYSIS.md)",
    )
    add_lint_args(p)
    return run_lint_cli(p.parse_args(argv))
