"""``python -m tpu_tree_search.analysis`` — standalone lint entry point
(the ``tts lint`` subcommand without the rest of the CLI; usable in CI
before the package's heavy deps are importable)."""

import sys

from . import lint_main

if __name__ == "__main__":
    sys.exit(lint_main())
