"""``lock-order``: a static lock-*acquisition-order* audit for the
host-thread runtime (the deepening of ``locks.py``'s per-access checking,
ISSUE 8).

``guarded-by`` proves each shared access holds *its* lock; it says nothing
about holding two locks at once.  The work-stealing tiers routinely touch
two pools (thief + victim), the dist tier nests pool locks under the KV
condition, and the checkpoint gate parks every worker — so the deadlock
question is about the *graph*: which lock can be **blocking-acquired while
another is held**.  This module builds that graph statically:

* **Nodes** are class-level locks ``ClassName.attr`` — every
  ``self.attr = threading.Lock()/RLock()/Condition()`` assignment, plus
  every lock named by a ``guarded-by``/``requires-lock`` annotation.
* **Edges** ``H -> A`` mean "somewhere, lock ``A`` is acquired while
  ``H`` is held": lexical nesting of ``with B.lock:`` /
  ``if B.try_lock():`` scopes (the same scope tracking as ``guarded-by``,
  with the base expression resolved to a class by the shared shallow type
  inference), direct ``B.lock.acquire()`` calls, and one level of call
  propagation — calling a method whose body blocking-acquires its own
  class's locks (``locked_*`` wrappers, ``kv_set``/``kv_get``…) while a
  lock is held adds the corresponding edges.
* Each edge records whether the *acquisition* blocks: ``try_lock()`` and
  ``acquire(blocking=False)`` edges are non-blocking — they can fail but
  never wait, so they cannot close a deadlock cycle.

Findings:

* ``lock-order`` — a cycle among **blocking** edges: two threads taking
  the cycle's locks in different orders can deadlock.  Reported once per
  cycle, at the edge that closes it.
* ``lock-order-same-class`` — a *blocking* acquisition of a lock of class
  ``C`` while a ``C`` lock is already held.  The class-level graph cannot
  order two instances of the same lock, so the only statically safe
  discipline is the one the steal paths follow: the second same-class
  lock must be ``try_lock`` (this is exactly what the repo's "advisory
  racy read" waivers implicitly assume — victim pools are probed with
  ``try_lock`` and released before the thief's own pool is locked).

Like ``guarded-by``, the analysis under-approximates: unresolvable bases
add no nodes and no edges, so a finding is always worth reading, and a
clean report means "no cycle among the locks the analysis can see" —
``threading.Barrier``/``Condition.wait`` rendezvous are out of scope
(documented in docs/ANALYSIS.md).
"""

from __future__ import annotations

import ast
import dataclasses

from .core import Finding, Module, Project, rule
from .locks import (
    FunctionNode,
    _collect,
    _expr_type,
    _function_env,
    _own_nodes,
    _owning_class,
)

_LOCK_CTORS = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Semaphore", "threading.BoundedSemaphore",
}


@dataclasses.dataclass(frozen=True)
class Edge:
    """``A`` acquired at (path, line) while ``held`` was held."""

    held: str
    acquired: str
    path: str
    line: int
    blocking: bool


@dataclasses.dataclass
class LockGraph:
    nodes: set[str]
    edges: list[Edge]

    def blocking_edges(self) -> list[Edge]:
        return [e for e in self.edges if e.blocking]

    def cycles(self) -> list[list[Edge]]:
        """Elementary cycles among blocking edges (DFS over the small class
        graph; deduplicated by node set)."""
        adj: dict[str, list[Edge]] = {}
        for e in self.blocking_edges():
            adj.setdefault(e.held, []).append(e)
        seen_sets: set[frozenset] = set()
        out: list[list[Edge]] = []

        def walk(node: str, path_edges: list[Edge], on_path: list[str]):
            for e in adj.get(node, ()):
                if e.acquired in on_path:
                    cyc = path_edges[on_path.index(e.acquired):] + [e]
                    key = frozenset(x.acquired for x in cyc)
                    if key not in seen_sets:
                        seen_sets.add(key)
                        out.append(cyc)
                    continue
                walk(e.acquired, path_edges + [e], on_path + [e.acquired])

        for start in sorted(adj):
            walk(start, [], [start])
        return out


# -- lock-node discovery ---------------------------------------------------


def _lock_nodes(project: Project) -> dict[str, set[str]]:
    """class name -> its lock attribute names.  Sources: ``threading.*``
    constructor assignments to ``self.<attr>`` anywhere in the class, plus
    the lock names referenced by guarded-by/requires-lock annotations."""

    def build(_):
        classes = _collect(project)
        locks: dict[str, set[str]] = {}
        for cname, info in classes.items():
            names = set(info.fields.values()) | set(info.methods.values())
            if names:
                locks.setdefault(cname, set()).update(names)
        for mod in project.modules:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                for sub in ast.walk(node):
                    if not (isinstance(sub, ast.Assign)
                            and isinstance(sub.value, ast.Call)):
                        continue
                    q = mod.qualname(sub.value.func)
                    if q not in _LOCK_CTORS:
                        continue
                    for t in sub.targets:
                        if (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                            and _owning_class(mod, sub) is node
                        ):
                            locks.setdefault(node.name, set()).add(t.attr)
        return locks

    return project.fact("lock-order:nodes", build)


# -- acquisition extraction ------------------------------------------------


@dataclasses.dataclass(frozen=True)
class _Acq:
    node: str       # "ClassName.attr"
    line: int
    col: int
    blocking: bool


def _resolve_lock(mod: Module, expr: ast.AST, env, classes, locks
                  ) -> str | None:
    """``B.attr`` -> "T.attr" when B's inferred type T declares lock attr
    ``attr``; None otherwise."""
    if not isinstance(expr, ast.Attribute):
        return None
    base_ty = _expr_type(mod, expr.value, env, classes)
    if base_ty is None or expr.attr not in locks.get(base_ty, ()):
        return None
    return f"{base_ty}.{expr.attr}"


def _try_lock_node(mod: Module, call: ast.Call, env, classes, locks
                   ) -> str | None:
    """``B.try_lock()`` -> B's class lock node (the conventional ``lock``
    attribute, else the class's single declared lock)."""
    f = call.func
    if not (isinstance(f, ast.Attribute) and f.attr == "try_lock"):
        return None
    base_ty = _expr_type(mod, f.value, env, classes)
    if base_ty is None:
        return None
    names = locks.get(base_ty, set())
    if "lock" in names:
        return f"{base_ty}.lock"
    if len(names) == 1:
        return f"{base_ty}.{next(iter(names))}"
    return None


def _direct_acquisitions(mod: Module, fn, env, classes, locks) -> list[_Acq]:
    """Blocking/non-blocking lock acquisitions lexically inside ``fn``
    (not descending into nested defs)."""
    out: list[_Acq] = []
    for node in _own_nodes(fn):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                lk = _resolve_lock(mod, item.context_expr, env, classes, locks)
                if lk is not None:
                    out.append(_Acq(lk, node.lineno, node.col_offset, True))
        elif isinstance(node, ast.Call):
            lk = _try_lock_node(mod, node, env, classes, locks)
            if lk is not None:
                out.append(_Acq(lk, node.lineno, node.col_offset, False))
                continue
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "acquire":
                lk = _resolve_lock(mod, f.value, env, classes, locks)
                if lk is not None:
                    blocking = True
                    for kw in node.keywords:
                        if kw.arg == "blocking" and isinstance(
                            kw.value, ast.Constant
                        ) and kw.value.value is False:
                            blocking = False
                    if node.args and isinstance(node.args[0], ast.Constant) \
                            and node.args[0].value is False:
                        blocking = False
                    out.append(_Acq(lk, node.lineno, node.col_offset, blocking))
    return out


def _method_summaries(project: Project) -> dict[tuple[str, str], set[str]]:
    """(class, method) -> lock nodes the method body blocking-acquires
    directly (one level of call propagation for ``locked_*``-style
    wrappers)."""

    def build(_):
        classes = _collect(project)
        locks = _lock_nodes(project)
        summaries: dict[tuple[str, str], set[str]] = {}
        for mod in project.modules:
            env_memo: dict = {}
            for fn in ast.walk(mod.tree):
                if not isinstance(fn, FunctionNode):
                    continue
                owner = _owning_class(mod, fn)
                if owner is None:
                    continue
                env = _function_env(mod, fn, classes, env_memo)
                acqs = _direct_acquisitions(mod, fn, env, classes, locks)
                blocking = {a.node for a in acqs if a.blocking}
                if blocking:
                    summaries[(owner.name, fn.name)] = blocking
        return summaries

    return project.fact("lock-order:summaries", build)


def _released_before(if_node: ast.If, base: ast.AST, line: int) -> bool:
    """True when the ``if B.try_lock():`` body explicitly releases ``B``
    (``B.unlock()`` / ``B.lock.release()``) at a line before ``line`` —
    the try/finally release-then-continue idiom of the steal paths.  A
    lexical under-approximation: a release the walk can't match keeps the
    lock conservatively held."""
    try:
        base_txt = ast.unparse(base)
    except Exception:
        return False
    for sub in ast.walk(if_node):
        if not (isinstance(sub, ast.Call) and isinstance(sub.func, ast.Attribute)):
            continue
        if sub.lineno >= line:
            continue
        f = sub.func
        try:
            if f.attr == "unlock" and ast.unparse(f.value) == base_txt:
                return True
            if (f.attr == "release" and isinstance(f.value, ast.Attribute)
                    and ast.unparse(f.value.value) == base_txt):
                return True
        except Exception:
            continue
    return False


def _held_lock_nodes(mod: Module, node: ast.AST, env, classes, locks
                     ) -> set[str]:
    """Typed version of ``locks._held_locks``: the set of lock *nodes*
    (``T.attr``) held at ``node`` via enclosing ``with``/``try_lock``
    scopes (held is held, however it was acquired — but an explicit
    ``unlock()`` earlier in a try_lock body ends the hold)."""
    held: set[str] = set()
    at_line = getattr(node, "lineno", 0)
    cur: ast.AST | None = node
    while cur is not None:
        parent = mod.parent.get(cur)
        if parent is None:
            break
        if isinstance(parent, (ast.With, ast.AsyncWith)) and cur in parent.body:
            for item in parent.items:
                lk = _resolve_lock(mod, item.context_expr, env, classes, locks)
                if lk is not None:
                    held.add(lk)
        if isinstance(parent, ast.If) and cur in parent.body:
            test = parent.test
            if isinstance(test, ast.Call):
                lk = _try_lock_node(mod, test, env, classes, locks)
                if lk is not None and not _released_before(
                    parent, test.func.value, at_line
                ):
                    held.add(lk)
        if isinstance(parent, FunctionNode) or isinstance(parent, ast.Lambda):
            break
        cur = parent
    return held


def build_graph(project: Project) -> LockGraph:
    """The project-wide lock-acquisition graph (memoised project fact)."""

    def build(_):
        classes = _collect(project)
        locks = _lock_nodes(project)
        summaries = _method_summaries(project)
        nodes = {f"{c}.{a}" for c, attrs in locks.items() for a in attrs}
        edges: list[Edge] = []
        for mod in project.modules:
            env_memo: dict = {}
            for fn in ast.walk(mod.tree):
                if not isinstance(fn, FunctionNode):
                    continue
                env = _function_env(mod, fn, classes, env_memo)
                for node in _own_nodes(fn):
                    acqs: list[_Acq] = []
                    if isinstance(node, (ast.With, ast.AsyncWith)):
                        for item in node.items:
                            lk = _resolve_lock(
                                mod, item.context_expr, env, classes, locks
                            )
                            if lk is not None:
                                acqs.append(_Acq(
                                    lk, node.lineno, node.col_offset, True
                                ))
                    elif isinstance(node, ast.Call):
                        lk = _try_lock_node(mod, node, env, classes, locks)
                        if lk is not None:
                            acqs.append(_Acq(
                                lk, node.lineno, node.col_offset, False
                            ))
                        else:
                            f = node.func
                            if isinstance(f, ast.Attribute):
                                if f.attr == "acquire":
                                    lk = _resolve_lock(
                                        mod, f.value, env, classes, locks
                                    )
                                    if lk is not None:
                                        acqs.append(_Acq(
                                            lk, node.lineno,
                                            node.col_offset, True
                                        ))
                                else:
                                    # one-level call propagation
                                    base_ty = _expr_type(
                                        mod, f.value, env, classes
                                    )
                                    for target in summaries.get(
                                        (base_ty, f.attr), ()
                                    ) if base_ty else ():
                                        acqs.append(_Acq(
                                            target, node.lineno,
                                            node.col_offset, True
                                        ))
                    if not acqs:
                        continue
                    held = _held_lock_nodes(mod, node, env, classes, locks)
                    for acq in acqs:
                        for h in held:
                            if h == acq.node and not acq.blocking:
                                # try_lock of a same-class sibling while
                                # holding one: the sanctioned discipline.
                                continue
                            edges.append(Edge(
                                h, acq.node, mod.path, acq.line, acq.blocking
                            ))
        return LockGraph(nodes=nodes, edges=edges)

    return project.fact("lock-order:graph", build)


# -- the rule --------------------------------------------------------------


@rule("lock-order")
def lock_order(module: Module, project: Project) -> list[Finding]:
    graph = build_graph(project)
    findings: list[Finding] = []
    # Same-class blocking re-acquisition: instance order is invisible to a
    # class-level graph, so the second one must be try_lock.
    for e in graph.edges:
        if e.path != module.path or not e.blocking:
            continue
        if e.held == e.acquired:
            findings.append(Finding(
                "lock-order", module.path, e.line, 0,
                f"blocking acquisition of {e.acquired} while an instance "
                f"of {e.held} is already held — two instances of one lock "
                "class have no static order; probe the second with "
                "try_lock() (the steal-path discipline) or release first",
            ))
    # Cycles among blocking edges: report at the closing edge, in the
    # module that contains it (once per cycle).
    for cyc in graph.cycles():
        closing = cyc[-1]
        if closing.path != module.path or closing.held == closing.acquired:
            continue
        chain = " -> ".join([e.held for e in cyc] + [cyc[-1].acquired])
        findings.append(Finding(
            "lock-order", module.path, closing.line, 0,
            f"lock-acquisition cycle (deadlock potential): {chain}; "
            "break the cycle by ordering the acquisitions or probing "
            "with try_lock()",
        ))
    return findings


# -- contract surface (tts check) ------------------------------------------

from .contracts import contract  # noqa: E402  (registry import is stdlib-only)


@contract(
    "lock-order-acyclic",
    claim="the static lock-acquisition graph across pool/, parallel/, and "
          "the KV store has no cycle among blocking edges and no blocking "
          "same-class re-acquisition (deadlock freedom of the steal/"
          "exchange/checkpoint paths, up to the analysis's visibility)",
    artifact="lock-graph",
)
def check_lock_order(graph: LockGraph, cell=None) -> list[str]:
    out = []
    for e in graph.edges:
        if e.blocking and e.held == e.acquired:
            out.append(
                f"{e.path}:{e.line}: blocking same-class re-acquisition "
                f"of {e.acquired}"
            )
    for cyc in graph.cycles():
        if cyc[-1].held == cyc[-1].acquired:
            continue
        chain = " -> ".join([e.held for e in cyc] + [cyc[-1].acquired])
        where = ", ".join(f"{e.path}:{e.line}" for e in cyc)
        out.append(f"cycle {chain} (edges at {where})")
    return out
