"""JAX-aware rules: host-sync-in-jit, tracer-branch, static-arg-hygiene.

All three share one per-module analysis: the set of *traced functions* —
functions whose bodies execute under a JAX trace. A function is a traced
root when it is

  * decorated with a jit-like transform (``@jax.jit``,
    ``@partial(jax.jit, ...)``),
  * passed by name (or as a lambda) to a trace entry point
    (``jax.jit(step, ...)``, ``lax.while_loop(cond, body, init)``,
    ``lax.cond(p, a, b, ...)``, ``jax.shard_map(f, ...)``,
    ``pl.pallas_call(kernel, ...)`` …), or
  * explicitly marked ``# tts-lint: traced`` — the escape hatch for closures
    returned through an indirection the resolver cannot follow (e.g. the
    resident engine's ``loop_fns`` returning ``(cond, body)``).

Tracedness then closes over *statically resolvable local calls*: a local
function called from a traced body is traced too. The resolver is lexical
(same module, innermost scope outward) — cross-module calls are out of
scope by design; annotate the callee's module instead.
"""

from __future__ import annotations

import ast

from .core import PRAGMA, Finding, Module, Project, rule

#: Final attribute names of jax entry points that trace function arguments.
TRACE_ENTRIES = {
    "jit", "pmap", "vmap", "grad", "value_and_grad", "jacfwd", "jacrev",
    "hessian", "shard_map", "while_loop", "fori_loop", "scan", "cond",
    "switch", "associative_scan", "pallas_call", "checkpoint", "remat",
    "custom_jvp", "custom_vjp",
}

#: Method calls that synchronize with / copy to the host.
HOST_SYNC_METHODS = {"item", "tolist", "block_until_ready", "to_py"}

#: Qualified calls that materialize device values on host.
HOST_SYNC_CALLS = {
    "numpy.asarray", "numpy.array", "numpy.ascontiguousarray", "numpy.copy",
    "jax.device_get",
}


def _is_trace_entry(module: Module, call: ast.Call) -> bool:
    qual = module.qualname(call.func)
    if qual is None:
        return False
    parts = qual.split(".")
    return parts[-1] in TRACE_ENTRIES and parts[0] == "jax"


def _partial_trace_entry(module: Module, call: ast.Call) -> bool:
    """``partial(jax.jit, ...)`` used as a decorator/factory."""
    qual = module.qualname(call.func)
    if qual not in ("functools.partial", "partial"):
        return False
    return bool(call.args) and _is_entry_ref(module, call.args[0])


def _is_entry_ref(module: Module, node: ast.AST) -> bool:
    if not isinstance(node, (ast.Name, ast.Attribute)):
        return False
    qual = module.qualname(node)
    if qual is None:
        return False
    parts = qual.split(".")
    return parts[-1] in TRACE_ENTRIES and parts[0] == "jax"


FunctionNode = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


class _Scopes:
    """Lexical function-name resolution for one module."""

    def __init__(self, module: Module):
        self.module = module
        # nearest enclosing function of every def (None = module level)
        self.owner: dict[ast.AST, ast.AST | None] = {}
        # scope -> {name: def_node}
        self.defs: dict[ast.AST | None, dict[str, ast.AST]] = {None: {}}
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                owner = module.enclosing_function(node)
                self.owner[node] = owner
                self.defs.setdefault(owner, {})[node.name] = node

    def resolve(self, at: ast.AST, name: str) -> ast.AST | None:
        """Innermost-scope-outward lookup of a function name."""
        scope = self.module.enclosing_function(at)
        while True:
            found = self.defs.get(scope, {}).get(name)
            if found is not None:
                return found
            if scope is None:
                return None
            scope = self.module.enclosing_function(scope)


def _own_nodes(fn: ast.AST):
    """Walk a function's body without descending into nested functions
    (nested defs get their own walk once proven traced)."""
    body = fn.body if not isinstance(fn, ast.Lambda) else [fn.body]
    stack = list(body)
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, FunctionNode):
                yield child  # the def itself (for call-closure), not its body
                continue
            stack.append(child)


def _has_marker(module: Module, fn: ast.AST) -> bool:
    if isinstance(fn, ast.Lambda):
        return False
    for line in (fn.lineno, fn.lineno - 1):
        comment = module.comments.get(line, "")
        if PRAGMA in comment and "traced" in comment.split(PRAGMA, 1)[-1]:
            return True
    return False


def traced_functions(module: Module, project: Project) -> set[ast.AST]:
    """The per-module set of function nodes whose bodies run under trace."""

    def build(_):
        scopes = _Scopes(module)
        roots: set[ast.AST] = set()
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if _is_entry_ref(module, dec):
                        roots.add(node)
                    elif isinstance(dec, ast.Call) and (
                        _is_trace_entry(module, dec)
                        or _partial_trace_entry(module, dec)
                    ):
                        roots.add(node)
                if _has_marker(module, node):
                    roots.add(node)
            elif isinstance(node, ast.Call) and _is_trace_entry(module, node):
                for arg in list(node.args) + [kw.value for kw in node.keywords]:
                    if isinstance(arg, ast.Lambda):
                        roots.add(arg)
                    elif isinstance(arg, ast.Name):
                        target = scopes.resolve(node, arg.id)
                        if target is not None:
                            roots.add(target)
        # Close over statically resolvable local calls.
        traced = set()
        work = list(roots)
        while work:
            fn = work.pop()
            if fn in traced:
                continue
            traced.add(fn)
            for node in _own_nodes(fn):
                if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                    callee = scopes.resolve(node, node.func.id)
                    if callee is not None and callee not in traced:
                        work.append(callee)
        return traced

    return project.fact(f"traced:{module.path}", build)


# -- taint: which local names may hold traced values ----------------------


#: Attribute reads that yield static (Python-level) metadata even on a
#: tracer — values derived through them are NOT traced.
STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "itemsize", "sharding"}


def _names_in(node: ast.AST) -> set[str]:
    """Loaded names that can carry a *traced value* out of ``node``:
    skips subtrees under static-metadata attributes (``x.shape[0]`` is a
    Python int at trace time, not a tracer)."""
    out: set[str] = set()
    stack = [node]
    while stack:
        n = stack.pop()
        if isinstance(n, ast.Attribute) and n.attr in STATIC_ATTRS:
            continue
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
            out.add(n.id)
        stack.extend(ast.iter_child_nodes(n))
    return out


def _target_names(target: ast.AST) -> set[str]:
    return {
        n.id for n in ast.walk(target)
        if isinstance(n, ast.Name) and isinstance(n.ctx, (ast.Store,))
    }


def jit_static_params(module: Module, fn: ast.AST) -> set[str]:
    """Parameter names the function's own jit decorator declares static
    (``static_argnames``, plus ``static_argnums`` mapped through the
    positional list): Python values at trace time, never tracers, so they
    must not seed the taint set — branching on them is how a static knob
    (e.g. ``pairblock``) legitimately specializes the compiled program."""
    if isinstance(fn, ast.Lambda):
        return set()
    nums: set[int] = set()
    names: set[str] = set()
    for dec in fn.decorator_list:
        if isinstance(dec, ast.Call) and (
            _is_trace_entry(module, dec) or _partial_trace_entry(module, dec)
        ):
            n2, s2 = _jit_static_sets(dec)
            nums |= n2
            names |= s2
    args = fn.args.posonlyargs + fn.args.args
    for i in nums:
        if 0 <= i < len(args):
            names.add(args[i].arg)
    return names


def tainted_names(fn: ast.AST, static: set[str] = frozenset()) -> set[str]:
    """Forward may-analysis: parameters are traced values; anything assigned
    from an expression mentioning a traced name may be traced too.
    ``static`` names (a jit decorator's static params) are excluded up
    front — they are Python values under the trace — though an in-body
    rebind from a tainted expression re-taints them."""
    if isinstance(fn, ast.Lambda):
        args = fn.args
    else:
        args = fn.args
    taint: set[str] = {
        a.arg for a in args.posonlyargs + args.args + args.kwonlyargs
    }
    if args.vararg:
        taint.add(args.vararg.arg)
    if args.kwarg:
        taint.add(args.kwarg.arg)
    taint -= set(static)
    if isinstance(fn, ast.Lambda):
        return taint
    for _ in range(10):  # fixpoint (bounded; assignments chains are short)
        changed = False
        for node in _own_nodes(fn):
            value = None
            targets: set[str] = set()
            if isinstance(node, ast.Assign):
                value = node.value
                for t in node.targets:
                    targets |= _target_names(t)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                value = node.value
                if node.value is not None:
                    targets |= _target_names(node.target)
            elif isinstance(node, ast.NamedExpr):
                value = node.value
                targets |= _target_names(node.target)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                value = node.iter
                targets |= _target_names(node.target)
            if value is not None and targets and (_names_in(value) & taint):
                if not targets <= taint:
                    taint |= targets
                    changed = True
        if not changed:
            break
    return taint


def _excluded_use(module: Module, name_node: ast.Name, test: ast.AST) -> bool:
    """Uses of a traced name inside a branch test that are static at trace
    time or unknowable: ``x is None`` identity checks, ``isinstance``,
    static-metadata attributes, and names that only feed *arguments of a
    call* (``if use_pallas(device):`` — the callee may be a pure config
    predicate; flagging every such call would drown the signal)."""
    cur: ast.AST | None = name_node
    while cur is not None and cur is not test:
        parent = module.parent.get(cur)
        if isinstance(parent, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in parent.ops
        ):
            return True
        if isinstance(parent, ast.Attribute) and parent.attr in STATIC_ATTRS:
            return True
        if isinstance(parent, ast.Call) and cur is not parent.func:
            return True
        cur = parent
    return False


# -- rules -----------------------------------------------------------------


@rule("host-sync-in-jit")
def host_sync_in_jit(module: Module, project: Project) -> list[Finding]:
    """Host-synchronizing calls reachable inside a traced (jit / shard_map /
    lax-control-flow) body. Each one either fails at trace time or — worse —
    silently moves the resident hot loop back onto the host round-trip path
    the engine exists to avoid (docs/HW_VALIDATION.md: ~360 ms per dispatch
    vs ~0.5 ms per on-device cycle)."""
    findings: list[Finding] = []
    for fn in traced_functions(module, project):
        taint = tainted_names(fn, jit_static_params(module, fn))
        for node in _own_nodes(fn):
            if not isinstance(node, ast.Call):
                continue
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in HOST_SYNC_METHODS
            ):
                findings.append(Finding(
                    "host-sync-in-jit", module.path, node.lineno,
                    node.col_offset,
                    f".{node.func.attr}() inside a traced function forces a "
                    "device->host sync; keep reductions on device and read "
                    "results outside the jitted step",
                ))
                continue
            qual = module.qualname(node.func)
            if qual in HOST_SYNC_CALLS:
                findings.append(Finding(
                    "host-sync-in-jit", module.path, node.lineno,
                    node.col_offset,
                    f"{qual}() inside a traced function materializes device "
                    "values on host (implicit transfer); use jnp ops or "
                    "move the conversion outside the traced region",
                ))
                continue
            if (
                isinstance(node.func, ast.Name)
                and node.func.id in ("int", "float", "bool")
                and node.args
                and not isinstance(node.args[0], ast.Constant)
                and (_names_in(node.args[0]) & taint)
            ):
                findings.append(Finding(
                    "host-sync-in-jit", module.path, node.lineno,
                    node.col_offset,
                    f"{node.func.id}() on a traced value concretizes it "
                    "(ConcretizationTypeError at trace time, or a silent "
                    "host sync); use .astype()/jnp casts instead",
                ))
    return findings


@rule("tracer-branch")
def tracer_branch(module: Module, project: Project) -> list[Finding]:
    """Python ``if``/``while`` on a possibly-traced value inside a traced
    function — fails at trace time (ConcretizationTypeError) or, with
    concrete sizes, silently bakes one branch into the compiled program."""
    findings: list[Finding] = []
    for fn in traced_functions(module, project):
        taint = tainted_names(fn, jit_static_params(module, fn))
        if isinstance(fn, ast.Lambda):
            continue
        for node in _own_nodes(fn):
            if not isinstance(node, (ast.If, ast.While)):
                continue
            uses = [
                n for n in ast.walk(node.test)
                if isinstance(n, ast.Name)
                and isinstance(n.ctx, ast.Load)
                and n.id in taint
            ]
            live = [n for n in uses if not _excluded_use(module, n, node.test)]
            if live:
                kind = "if" if isinstance(node, ast.If) else "while"
                names = ", ".join(sorted({n.id for n in live}))
                findings.append(Finding(
                    "tracer-branch", module.path, node.lineno, node.col_offset,
                    f"Python `{kind}` on possibly-traced value(s) {names} "
                    "inside a traced function; use lax.cond/lax.while_loop/"
                    "jnp.where",
                ))
    return findings


# -- static-arg-hygiene ----------------------------------------------------

_SCALAR_ANN = {"int", "bool", "str"}


def _scalar_like(arg: ast.arg) -> bool:
    if arg.annotation is not None:
        names = {
            n.id for n in ast.walk(arg.annotation) if isinstance(n, ast.Name)
        }
        # `int | jax.Array`-style unions that admit an array are fine.
        if names & {"Array", "ArrayLike", "ndarray"}:
            return False
        return bool(names & _SCALAR_ANN)
    return False


def _jit_static_sets(call: ast.Call) -> tuple[set[int], set[str]]:
    nums: set[int] = set()
    names: set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, int):
                    nums.add(n.value)
        elif kw.arg == "static_argnames":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, str):
                    names.add(n.value)
    return nums, names


@rule("static-arg-hygiene")
def static_arg_hygiene(module: Module, project: Project) -> list[Finding]:
    """Jitted entry points whose Python-scalar parameters (per annotation or
    scalar default) are not declared static. Passing them dynamic traces
    them to weak-typed 0-d arrays — shape-controlling uses fail, and every
    call site converting via int() re-syncs; declaring them static makes the
    recompile-per-value cost explicit and intentional."""
    scopes = _Scopes(module)
    findings: list[Finding] = []
    # (def, static nums, static names) bindings from decorators + jit calls
    bindings: list[tuple[ast.AST, set[int], set[str], int, int]] = []
    for node in ast.walk(module.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _is_entry_ref(module, dec) and _final(module, dec) == "jit":
                    bindings.append((node, set(), set(), dec.lineno, dec.col_offset))
                elif isinstance(dec, ast.Call):
                    if _is_trace_entry(module, dec) and _final(module, dec.func) == "jit":
                        nums, names = _jit_static_sets(dec)
                        bindings.append((node, nums, names, dec.lineno, dec.col_offset))
                    elif _partial_trace_entry(module, dec) and _final(module, dec.args[0]) == "jit":
                        nums, names = _jit_static_sets(dec)
                        bindings.append((node, nums, names, dec.lineno, dec.col_offset))
        elif isinstance(node, ast.Call) and _is_trace_entry(module, node):
            if _final(module, node.func) != "jit" or not node.args:
                continue
            fn_ref = node.args[0]
            if isinstance(fn_ref, ast.Name):
                target = scopes.resolve(node, fn_ref.id)
                if target is not None and not isinstance(target, ast.Lambda):
                    nums, names = _jit_static_sets(node)
                    bindings.append(
                        (target, nums, names, node.lineno, node.col_offset)
                    )
    for fn, nums, names, line, col in bindings:
        args = fn.args.posonlyargs + fn.args.args
        for i, a in enumerate(args):
            if a.arg == "self" and i == 0:
                continue
            if i in nums or a.arg in names:
                continue
            if _scalar_like(a):
                findings.append(Finding(
                    "static-arg-hygiene", module.path, line, col,
                    f"jitted '{getattr(fn, 'name', '<lambda>')}' takes "
                    f"Python-scalar param '{a.arg}' dynamically; add it to "
                    "static_argnames (explicit recompile-per-value) or pass "
                    "a jnp array",
                ))
        for a in fn.args.kwonlyargs:
            if a.arg not in names and _scalar_like(a):
                findings.append(Finding(
                    "static-arg-hygiene", module.path, line, col,
                    f"jitted '{getattr(fn, 'name', '<lambda>')}' takes "
                    f"Python-scalar keyword param '{a.arg}' dynamically; "
                    "add it to static_argnames or pass a jnp array",
                ))
    return findings


def _final(module: Module, node: ast.AST) -> str | None:
    qual = module.qualname(node)
    return qual.split(".")[-1] if qual else None
