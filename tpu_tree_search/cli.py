"""Command-line entry point — one program instead of the reference's eight.

``tts <problem> --tier seq|device|multi|dist [flags]`` replaces the
per-(problem, tier) Chapel mains (`README.md:47-88` of the reference). Flags
and defaults match the reference's config consts: ``--N --g`` for N-Queens
(`nqueens_chpl.chpl:15-16`), ``--inst --lb --ub`` for PFSP
(`pfsp_chpl.chpl:20-22`), ``--m --M`` chunk thresholds and ``--D`` device
count for the offload tiers (`nqueens_gpu_chpl.chpl:12-21`,
`README.md:47-58`). The banner/report format mirrors `print_settings` /
`print_results` (`pfsp_chpl.chpl:54-77`) plus the per-phase breakdown and
offload diagnostics of the device tiers (`nqueens_gpu_chpl.chpl:178-283`).
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tts", description="TPU-native accelerated tree search"
    )
    sub = p.add_subparsers(dest="problem", required=True)

    common = argparse.ArgumentParser(add_help=False)
    common.add_argument(
        "--tier",
        choices=["seq", "device", "mesh", "multi", "dist", "dist_mesh"],
        default="seq",
        help=(
            "scaling tier: sequential / single-device / SPMD device mesh / "
            "multi-device host threads / multi-host (offload workers) / "
            "multi-host with per-host SPMD mesh engines (pod-scale)"
        ),
    )
    common.add_argument(
        "--engine",
        choices=["resident", "offload"],
        default="resident",
        help=(
            "device tier engine: resident = pool in HBM, chunk cycles inside "
            "one jitted loop (fast); offload = per-chunk host round trip "
            "(the reference's structure)"
        ),
    )
    common.add_argument("--m", type=int, default=25, help="minimum chunk size")
    common.add_argument(
        "--M", type=int, default=None,
        help="maximum chunk size (default: measured per problem/backend — "
        "1024 for PFSP device tiers on TPU, else the reference's 50000; "
        "see docs/HW_VALIDATION.md chunk-size tuning)",
    )
    common.add_argument("--K", type=str, default=None,
                        help="resident tiers: device chunk cycles per host "
                        "dispatch (default 4096 device / 16 mesh), or "
                        "'auto' — resize K along a geometric ladder toward "
                        "a target host period (also TTS_K=auto; "
                        "engine/pipeline.py)")
    common.add_argument(
        "--D", type=int, default=None,
        help="number of devices/shards (mesh, multi, dist tiers); "
        "default: all local devices. With --mp N this is the dp-axis "
        "size and the run consumes D*mp devices",
    )
    common.add_argument(
        "--mp", type=int, default=1,
        help="mesh tier, PFSP lb2 only: shard the Johnson machine-pair "
        "loop over a second mesh axis of this size (dp x mp devices)",
    )
    common.add_argument(
        "--compact",
        choices=["auto", "scatter", "sort", "search", "dense"],
        default=None,
        help="survivor-path compaction for the device tiers "
        "(default: TTS_COMPACT env or 'auto' — picks per problem shape "
        "from the measured table in ops/compaction.py; the explicit "
        "modes are bit-identical — pick by measurement, see bench.py's "
        "per-run A/B; 'dense' is the shift-based fast path, free of "
        "sort/scatter/searchsorted)",
    )
    common.add_argument("--stats-file", type=str, default=None,
                        help="append one result line to this .dat file")
    common.add_argument("--json", action="store_true", help="emit one JSON result line")
    common.add_argument("--checkpoint", type=str, default=None,
                        help="save the search frontier to this file periodically "
                        "(device/mesh tiers)")
    common.add_argument("--checkpoint-interval", type=float, default=60.0,
                        help="seconds between checkpoint snapshots")
    common.add_argument("--resume", type=str, default=None,
                        help="resume a search from a checkpoint file")
    common.add_argument("--max-steps", type=int, default=None,
                        help="stop after this many device dispatches "
                        "(checkpointing cutoff; result is marked incomplete)")
    common.add_argument("--perc", type=float, default=0.5,
                        help="multi/dist tiers: fraction of a victim's pool "
                        "front taken per steal (the CUDA baseline's --perc; "
                        "0.5 = the steal-half policy)")
    common.add_argument("--hosts", type=int, default=None,
                        help="dist tier: number of virtual hosts to run in "
                        "one process (testing mode; real pods use "
                        "jax.distributed and ignore this)")
    common.add_argument("--no-steal", action="store_true",
                        help="dist tier: disable inter-host stealing + "
                        "incumbent exchange (MPI-baseline join-point-only "
                        "semantics)")
    common.add_argument("--distributed", action="store_true",
                        help="dist tier, real pods: call "
                        "jax.distributed.initialize() before searching "
                        "(coordinator/process env supplied by the launcher, "
                        "e.g. GKE/TPU-VM metadata — the -nl/MPI launcher "
                        "analogue)")
    common.add_argument("--coordinator", type=str, default=None,
                        help="with --distributed: host:port of the rank-0 "
                        "coordination service (defaults to launcher env)")
    common.add_argument("--num-hosts", type=int, default=None,
                        help="with --distributed: process count in the "
                        "slice (defaults to launcher env)")
    common.add_argument("--host-id", type=int, default=None,
                        help="with --distributed: this process's rank "
                        "(defaults to launcher env, e.g. TPU_WORKER_ID)")
    common.add_argument("--steal-interval", type=float, default=None,
                        help="dist tier: communicator cadence floor in "
                        "seconds (default 0.02; backs off geometrically "
                        "while all hosts are busy)")
    common.add_argument("--profile", type=str, default=None,
                        help="write a jax profiler trace of the search to "
                        "this directory (view with TensorBoard/XProf)")
    common.add_argument("--trace", type=str, default=None,
                        help="write a Chrome-trace-event JSON of the run's "
                        "telemetry to this file (open in Perfetto; "
                        "summarize with `tts report`); implies TTS_OBS=1 "
                        "unless TTS_OBS is already set "
                        "(docs/OBSERVABILITY.md)")
    common.add_argument("--metrics-file", type=str, default=None,
                        help="append one JSON line per telemetry counter "
                        "sample to this file (scrape-ready); implies "
                        "TTS_OBS=1 unless TTS_OBS is already set")
    common.add_argument("--obs-serve", type=int, default=None,
                        metavar="PORT",
                        help="serve live run snapshots (nodes/s, incumbent, "
                        "pool occupancy, pipeline depth/K) on "
                        "127.0.0.1:PORT over HTTP/SSE; watch with "
                        "`tts watch --port PORT`; implies TTS_OBS=1 "
                        "unless TTS_OBS is already set "
                        "(docs/OBSERVABILITY.md)")
    common.add_argument("--costmodel", type=str, default=None,
                        metavar="PATH",
                        help="after the run, fit per-link-class "
                        "latency+bandwidth profiles from the recorded "
                        "spans and merge them into this COSTMODEL.json "
                        "(keyed by backend/topology/problem shape); "
                        "TTS_COSTMODEL=PATH makes later runs resolve "
                        "their K bands from it; implies TTS_OBS=1 unless "
                        "TTS_OBS is already set")
    common.add_argument("--phase-profile", action="store_true",
                        help="resident tiers: arm the on-device per-phase "
                        "cycle clocks (pop/eval/compact/push/overflow + "
                        "mesh balance — obs/phases.py). Builds a separate "
                        "cache-keyed program variant (equivalent to "
                        "TTS_PHASEPROF=1); search results stay "
                        "bit-identical, the decomposition table prints "
                        "with the results. Never use for headline "
                        "measurements — see `tts profile` and "
                        "docs/OBSERVABILITY.md leg 7")
    common.add_argument("--xla-trace", type=str, default=None,
                        metavar="DIR",
                        help="capture an XLA profiler trace of the "
                        "steady-state dispatch window into DIR (opens "
                        "after the first dispatch — warmup and while-loop "
                        "compile excluded; view with TensorBoard/XProf). "
                        "Equivalent to TTS_XLA_TRACE=DIR; --profile "
                        "traces the whole session instead")
    common.add_argument("--guard", action="store_true",
                        help="resident tiers: assert every steady-state "
                        "device dispatch performs zero recompilations and "
                        "zero implicit host transfers (fail loudly instead "
                        "of silently paying ~360ms/cycle round trips; "
                        "equivalent to TTS_GUARD=1 — docs/ANALYSIS.md)")

    nq = sub.add_parser("nqueens", parents=[common], help="N-Queens backtracking")
    nq.add_argument("--N", type=int, default=14, help="number of queens")
    nq.add_argument("--g", type=int, default=1, help="safety checks per evaluation")

    pf = sub.add_parser("pfsp", parents=[common], help="PFSP Branch-and-Bound")
    pf.add_argument("--inst", type=int, default=14, help="Taillard instance (1..120)")
    pf.add_argument("--lb", type=str, default="lb1", choices=["lb1", "lb1_d", "lb2"])
    pf.add_argument("--ub", type=int, default=1, choices=[0, 1],
                    help="initial upper bound: 1=known optimum, 0=inf")
    pf.add_argument("--lb2-variant", type=str, default="full",
                    choices=["full", "nabeshima", "lageweg"],
                    help="lb2 Johnson machine-pair subset (the reference's "
                    "enum lb2_variant, Bound_johnson.chpl:6): full = all "
                    "m(m-1)/2 pairs (reference default); nabeshima = "
                    "adjacent pairs (i, i+1); lageweg = every machine "
                    "paired with the last — both m-1 pairs, weaker bounds "
                    "but ~m/2x fewer pair evaluations")
    pf.add_argument("--lb2-pairblock", type=str, default=None,
                    help="lb2 machine-pair block size: evaluate this many "
                    "Johnson pairs at once as an extra tensor axis "
                    "(default: TTS_LB2_PAIRBLOCK env or 'auto'; 1 = the "
                    "serial per-pair loop; clamped to the pair count)")

    lint = sub.add_parser(
        "lint",
        help="JAX-aware static analysis: host-sync-in-jit, tracer-branch, "
        "guarded-by, static-arg-hygiene (docs/ANALYSIS.md)",
    )
    from .analysis import add_lint_args

    add_lint_args(lint)

    chk = sub.add_parser(
        "check",
        help="compiled-program contract audit: trace every knob-matrix "
        "cell's resident program (no execution, CPU is enough) and "
        "verify the registered structural contracts + the op-fingerprint "
        "baseline + the lock-order audit (docs/ANALYSIS.md)",
    )
    from .analysis.program_audit import add_check_args

    add_check_args(chk)

    rep = sub.add_parser(
        "report",
        help="summarize a --trace file: steal efficiency, idle fraction "
        "per worker, cycle-rate timeline (docs/OBSERVABILITY.md)",
    )
    rep.add_argument("trace", nargs="+",
                     help="trace/metrics/flight-recorder files (merged "
                     "into one report; truncated or empty files are "
                     "summarized as far as they parse)")
    rep.add_argument("--json", action="store_true", dest="report_json",
                     help="emit the summary as one JSON object")
    rep.add_argument("--roofline", action="store_true",
                     dest="report_roofline",
                     help="require the memory-roofline section (per-phase "
                     "%%-of-peak, obs/roofline.py): exit 2 when the trace "
                     "was not phase-profiled (TTS_PHASEPROF=1)")
    rep.add_argument("--costmodel", type=str, default=None,
                     dest="report_costmodel", metavar="PATH",
                     help="COSTMODEL.json whose measured `hbm` link fit "
                     "supplies the roofline peak-bandwidth denominator "
                     "(else TTS_HBM_GBPS / the nominal backend table)")

    prof = sub.add_parser(
        "profile",
        help="run a search with the per-phase cycle clocks armed and "
        "print the decomposition table (plus an optional --xla-trace "
        "capture): `tts profile pfsp --inst 14 --tier device "
        "[--xla-trace DIR]` — sugar for the same run command with "
        "--phase-profile forced on (docs/OBSERVABILITY.md leg 7)",
    )
    prof.add_argument("rest", nargs=argparse.REMAINDER,
                      help="a full run command (problem + flags)")

    watch = sub.add_parser(
        "watch",
        help="live view of a run started with --obs-serve PORT (or, with "
        "--job, of one serve-daemon job): one status line per snapshot "
        "(nodes/s, incumbent, pool occupancy, pipeline depth/K)",
    )
    watch.add_argument("--port", type=int, default=None,
                       help="the --obs-serve port (default 8642), or with "
                       "--job the serve daemon's port (default 8643)")
    watch.add_argument("--host", type=str, default="127.0.0.1")
    watch.add_argument("--interval", type=float, default=1.0,
                       help="polling fallback interval in seconds")
    watch.add_argument("--once", action="store_true",
                       help="print the current snapshot and exit")
    watch.add_argument("--json", action="store_true", dest="watch_json",
                       help="emit raw snapshot JSON lines")
    watch.add_argument("--job", type=str, default=None, metavar="ID",
                       help="follow one serve-daemon job's stream instead "
                       "of a --obs-serve run (docs/SERVING.md)")

    from .serve import DEFAULT_PORT as _SERVE_PORT

    srv = sub.add_parser(
        "serve",
        help="persistent multi-tenant search daemon: admit jobs over a "
        "localhost HTTP/JSON API, pool compiled programs per shape "
        "class (second same-class job admits with zero recompiles), "
        "preempt via bit-identical checkpoint cuts (docs/SERVING.md)",
    )
    srv.add_argument("--port", type=int, default=_SERVE_PORT,
                     help=f"listen port on 127.0.0.1 (default {_SERVE_PORT}; "
                     "0 = OS-assigned, printed at startup)")
    srv.add_argument("--host", type=str, default="127.0.0.1")
    srv.add_argument("--state-dir", type=str, default=None,
                     help="durable job records + checkpoints (default "
                     "TTS_SERVE_STATE or ~/.cache/tpu_tree_search/serve)")
    srv.add_argument("--workers", type=int, default=1,
                     help="concurrent job slices (default 1: one resident "
                     "loop owns the accelerator at a time)")
    srv.add_argument("--quantum", type=float, default=5.0,
                     help="seconds a job runs before it must yield to "
                     "waiting work (checkpoint cut + requeue; the cut "
                     "lands at the next dispatch boundary)")
    srv.add_argument("--max-queue", type=int, default=64,
                     help="admission control: reject submits (503) beyond "
                     "this queue depth")
    srv.add_argument("--warm", type=str, nargs="?", const="serve",
                     default=None, metavar="NAMES",
                     help="pre-warm the program pool at startup: 'serve' "
                     "(every serve-able config), 'all', or a "
                     "comma-separated config list (`tts warmup` names)")
    srv.add_argument("--batch-slots", type=int, default=None, metavar="B",
                     help="instance-axis batch slots per compiled program: "
                     "when >=2 same-shape-class jobs are queued, one "
                     "program advances up to B of them per dispatch, "
                     "splicing/retiring jobs at dispatch boundaries with "
                     "zero recompiles (default TTS_BATCH_SLOTS or 1 = "
                     "today's serial path; docs/SERVING.md)")
    srv.add_argument("--ckpt-every", type=float, default=None, metavar="S",
                     help="cut a recoverable checkpoint every S seconds "
                     "even with nothing waiting (default TTS_CKPT_EVERY "
                     "or off) — the fleet router pulls these to survive "
                     "a killed daemon (docs/SERVING.md)")
    srv.add_argument("--router", type=str, default=None, metavar="URL",
                     help="self-register with a `tts fleet` router at "
                     "startup (default TTS_ROUTER; failure is non-fatal)")

    from .fleet import DEFAULT_ROUTER_PORT as _ROUTER_PORT

    flt = sub.add_parser(
        "fleet",
        help="class-aware router over N serve daemons: one URL places "
        "each job where its compiled program is already warm, proxies "
        "the job lifecycle, and recovers in-flight jobs off dead or "
        "draining daemons via checkpoint resubmission (docs/SERVING.md)",
    )
    flt.add_argument("--port", type=int, default=_ROUTER_PORT,
                     help=f"router port on 127.0.0.1 (default "
                     f"{_ROUTER_PORT}; 0 = OS-assigned, printed at "
                     "startup)")
    flt.add_argument("--host", type=str, default="127.0.0.1")
    flt.add_argument("--state-dir", type=str, default=None,
                     help="durable fleet job map + pulled checkpoints "
                     "(default TTS_FLEET_STATE or "
                     "~/.cache/tpu_tree_search/fleet)")
    flt.add_argument("--daemon", action="append", default=None,
                     metavar="URL", dest="daemons",
                     help="register a serve daemon (repeatable; daemons "
                     "can also self-register via `tts serve --router` "
                     "or POST /register)")
    flt.add_argument("--scrape-interval", type=float, default=1.0,
                     help="seconds between keeper scrapes of each "
                     "daemon's /healthz,/classes,/metrics,/jobs")
    flt.add_argument("--health-misses", type=int, default=3,
                     help="consecutive failed probes before a daemon is "
                     "declared dead and its jobs recovered (default 3)")
    flt.add_argument("--pull-interval", type=float, default=2.0,
                     help="seconds between checkpoint pulls of in-flight "
                     "jobs (the SIGKILL-recovery fuel; default 2)")
    flt.add_argument("--no-rebalance", action="store_true",
                     help="disable hot->idle migration of long-runners")
    flt.add_argument("--rebalance-depth", type=int, default=2,
                     help="queue depth on the hot daemon before a "
                     "rebalance move is considered (default 2)")

    smt = sub.add_parser(
        "submit",
        help="submit a run to a serve daemon: `tts submit [--wait] -- "
        "pfsp --inst 14 --tier device` (the run args are the normal "
        "`tts` run command; --wait streams to completion)",
    )
    smt.add_argument("--port", type=int, default=_SERVE_PORT,
                     help=f"serve daemon port (default {_SERVE_PORT})")
    smt.add_argument("--host", type=str, default="127.0.0.1")
    smt.add_argument("--router", type=str, default=None, metavar="URL",
                     help="submit through a `tts fleet` router instead "
                     "of one daemon (default TTS_ROUTER): the job lands "
                     "on the daemon whose compiled programs are already "
                     "warm for its shape class")
    smt.add_argument("--wait", action="store_true",
                     help="follow the job's stream and print the final "
                     "result (exit 1 unless it completes)")
    smt.add_argument("--json", action="store_true", dest="submit_json",
                     help="emit the submit response (or with --wait the "
                     "final job record) as one JSON line")
    smt.add_argument("rest", nargs=argparse.REMAINDER,
                     help="a full run command (problem + flags)")

    top = sub.add_parser(
        "top",
        help="live per-job / per-class table for a serve daemon "
        "(assembled from /healthz + /jobs + /classes; the fleet "
        "operator console — docs/SERVING.md)",
    )
    top.add_argument("--port", type=int, default=_SERVE_PORT,
                     help=f"serve daemon port (default {_SERVE_PORT})")
    top.add_argument("--host", type=str, default="127.0.0.1")
    top.add_argument("--router", type=str, default=None, metavar="URL",
                     help="aggregate a whole fleet instead of one daemon "
                     "(default TTS_ROUTER): per-daemon rows + fleet "
                     "totals from the router's /fleet endpoint")
    top.add_argument("--interval", type=float, default=2.0,
                     help="refresh period in seconds (default 2)")
    top.add_argument("--once", action="store_true",
                     help="print one frame and exit (scripts, CI smoke)")
    top.add_argument("--json", action="store_true", dest="top_json",
                     help="emit the composed health/jobs/classes payload "
                     "as one JSON line per refresh")

    mig = sub.add_parser(
        "migrate",
        help="move a job between serve daemons over its portable "
        "checkpoint: cancel-with-cut on the source, fetch "
        "/job/<id>/checkpoint, resubmit spec+checkpoint to --to "
        "(counters stay cumulative, so the result is bit-identical "
        "to never having moved — docs/SERVING.md)",
    )
    mig.add_argument("job", type=str, help="job id on the source daemon")
    mig.add_argument("--to", type=str, required=True, metavar="URL",
                     help="destination daemon base URL "
                     "(host:port or http://host:port)")
    mig.add_argument("--port", type=int, default=_SERVE_PORT,
                     help=f"source daemon port (default {_SERVE_PORT})")
    mig.add_argument("--host", type=str, default="127.0.0.1",
                     help="source daemon host (default 127.0.0.1)")
    mig.add_argument("--json", action="store_true", dest="migrate_json",
                     help="emit the old->new id mapping as one JSON line")

    wrm = sub.add_parser(
        "warmup",
        help="AOT-compile the validation matrix into the persistent "
        "compile cache with per-config hit/miss reporting "
        "(scripts/warm_cache.py's engine; docs/SERVING.md)",
    )
    wrm.add_argument("--configs", type=str, default=None, metavar="NAMES",
                     help="'all' (default), 'serve', or a comma-separated "
                     "config name list")
    wrm.add_argument("--timeout", type=float, default=None,
                     help="per-config subprocess timeout in seconds "
                     "(default TTS_WARM_TIMEOUT or 420)")
    return p


def validate_args(parser: argparse.ArgumentParser, args) -> None:
    """Reject flag combinations that would otherwise be silently ignored."""
    if args.K is not None and args.K != "auto":
        try:
            args.K = int(args.K)
        except ValueError:
            parser.error("--K must be 'auto' or a positive integer")
        if args.K < 1:
            parser.error("--K must be >= 1 (or 'auto')")
    if args.guard and not (
        args.tier in ("mesh", "dist_mesh")
        or (args.tier == "device" and args.engine == "resident")
    ):
        parser.error(
            "--guard asserts steady-state purity of the resident device "
            "loops (--tier device with the resident engine, mesh, "
            "dist_mesh); the offload/multi/dist workers round-trip every "
            "chunk by design"
        )
    if args.tier in ("mesh", "dist_mesh") and args.engine == "offload":
        parser.error(
            "--engine offload is not available for this tier "
            "(mesh/dist_mesh are resident-only; use --tier multi for "
            "host-orchestrated offload across devices)"
        )
    if args.phase_profile and not uses_compaction(args):
        parser.error(
            "--phase-profile arms the resident loops' on-device phase "
            "clocks (--tier device with the resident engine, mesh, "
            "dist_mesh); the offload/multi/dist workers have no device "
            "cycle to decompose"
        )
    if args.xla_trace is not None and args.profile is not None:
        parser.error(
            "--xla-trace (steady-state dispatch window) and --profile "
            "(whole session) are both XLA profiler captures — pick one"
        )
    if args.compact is not None and not uses_compaction(args):
        parser.error(
            "--compact only applies to runs with device-side compaction "
            "(--tier device with the resident engine, mesh, dist_mesh); "
            "the offload/multi/dist workers prune on host"
        )
    if args.perc != 0.5 and args.tier not in ("multi", "dist"):
        parser.error(
            "--perc only applies to the work-stealing tiers (multi, dist)"
        )
    if not 0.0 < args.perc <= 1.0:
        # Semantics of the steal fraction: reference `Pool_ext.c:138-151`.
        parser.error(
            "--perc must be in (0, 1]: the fraction of the victim's front "
            "taken per steal"
        )
    if (
        args.hosts is not None or args.distributed
    ) and args.tier not in ("dist", "dist_mesh"):
        parser.error(
            "--hosts/--distributed only apply to --tier dist/dist_mesh"
        )
    if args.no_steal and args.tier != "dist":
        parser.error("--no-steal only applies to --tier dist")
    if args.distributed and args.hosts is not None:
        parser.error("--distributed (real pods) and --hosts (virtual "
                     "hosts) are mutually exclusive")
    if (
        args.coordinator is not None
        or args.num_hosts is not None
        or args.host_id is not None
    ) and not args.distributed:
        parser.error("--coordinator/--num-hosts/--host-id require "
                     "--distributed")
    if args.steal_interval is not None:
        if args.tier != "dist":
            parser.error("--steal-interval only applies to --tier dist")
        if args.steal_interval <= 0:
            parser.error("--steal-interval must be > 0")
    if args.hosts is not None and args.hosts < 1:
        parser.error("--hosts must be >= 1")
    if args.mp != 1:
        if args.tier not in ("mesh", "dist_mesh"):
            parser.error("--mp only applies to --tier mesh/dist_mesh")
        if args.mp < 1:
            parser.error("--mp must be >= 1")
        if args.problem != "pfsp" or args.lb != "lb2":
            parser.error("--mp shards the lb2 Johnson pair loop "
                         "(pfsp --lb lb2 only)")
    if args.problem == "pfsp":
        if args.lb2_variant != "full" and args.lb != "lb2":
            parser.error("--lb2-variant selects the lb2 Johnson pair "
                         "subset (--lb lb2 only)")
        if args.lb2_pairblock is not None:
            if args.lb != "lb2":
                parser.error("--lb2-pairblock batches the lb2 Johnson "
                             "pair axis (--lb lb2 only)")
            if args.lb2_pairblock != "auto":
                try:
                    v = int(args.lb2_pairblock)
                except ValueError:
                    parser.error("--lb2-pairblock must be 'auto' or a "
                                 "positive integer")
                else:
                    if v < 1:
                        parser.error("--lb2-pairblock must be >= 1 "
                                     "(1 = the serial per-pair loop)")


def make_problem(args):
    if args.problem == "nqueens":
        from .problems import NQueensProblem

        return NQueensProblem(N=args.N, g=args.g)
    from .problems import PFSPProblem

    return PFSPProblem(inst=args.inst, lb=args.lb, ub=args.ub,
                       lb2_variant=args.lb2_variant)


def resolve_chunk_size(M, problem_name: str, tier: str, engine: str,
                       backend: str | None = None) -> int:
    """Measured default for ``--M`` when the user does not pass one.

    On-chip tuning (scripts/headline_tune.py / lb2_tune.py, round 5 —
    docs/HW_VALIDATION.md) showed the RESIDENT loop's per-cycle cost is
    ~linear in M while PFSP frontiers rarely fill large chunks, so
    small-but-full chunks run ~1.3x (lb1) to ~3x (staged lb2) faster:
    PFSP device tier + resident engine on TPU defaults to 1024.
    The gpu backend gets its own explicit row, 49152: the reference's GPU
    offload sizing is the same ``M = 50000`` pool chunk (the published
    PFSP-on-GPU runs saturate the device with a single ~50k-node offload
    per cycle — arXiv 2012.09511 §IV), rounded DOWN to a multiple of 8 so
    the resident pool keeps the sublane-quantum alignment the megakernel
    and tiled compaction gates require (50000 % 8 == 2 would refuse them).
    Everything else — explicit ``--M``, the offload engine (each chunk
    pays a ~360ms host round trip; small chunks would multiply them),
    remaining non-TPU backends (unmeasured), N-Queens (wide frontiers fill
    big chunks), and the sharded tiers (M is per shard) — keeps the
    reference's 50000 (the per-program ``config const M = 50000`` of each
    GPU main, `pfsp_gpu_chpl.chpl:24` / `nqueens_gpu_chpl.chpl:21`; it is
    not defined in `util.chpl`). The candidate combination is
    checked BEFORE the backend so non-candidates (e.g. ``--tier seq``)
    never touch jax."""
    if M is not None:
        return M
    if not (problem_name == "pfsp" and tier == "device"
            and engine == "resident"):
        return 50000
    if backend is None:
        try:
            from .ops import backend as BK

            backend = BK.policy_backend()
        except Exception:
            backend = "cpu"
    if backend == "tpu":
        return 1024
    if backend == "gpu":
        return 49152
    return 50000


def uses_compaction(args) -> bool:
    """True for runs whose engine performs device-side stream compaction
    (`engine/resident.py _compact_ids`): the resident device engine and
    the mesh-resident tiers. The offload/multi/dist workers prune and
    branch on host and never consult TTS_COMPACT."""
    return (args.tier in ("mesh", "dist_mesh")
            or (args.tier == "device" and args.engine == "resident"))


def run_tier(problem, args):
    args.M = resolve_chunk_size(args.M, getattr(problem, "name", ""),
                                args.tier, args.engine)
    # Flag > env for THIS run only: restore on exit so a caller invoking
    # main() twice in one process does not inherit the pins (compaction
    # programs cache per mode via the routing token; the guard is read at
    # engine start).
    import os

    pins = {}
    if args.compact is not None:
        pins["TTS_COMPACT"] = args.compact
    if getattr(args, "lb2_pairblock", None) is not None:
        pins["TTS_LB2_PAIRBLOCK"] = args.lb2_pairblock
    if args.guard:
        pins["TTS_GUARD"] = "1"
    if args.phase_profile:
        pins["TTS_PHASEPROF"] = "1"
    if args.xla_trace is not None:
        pins["TTS_XLA_TRACE"] = args.xla_trace
    if (
        (args.trace is not None or args.metrics_file is not None
         or args.obs_serve is not None or args.costmodel is not None)
        and "TTS_OBS" not in os.environ
    ):
        # --trace/--metrics-file/--obs-serve/--costmodel turn telemetry on
        # for the run; an explicit TTS_OBS (e.g. =host to keep device
        # programs untouched) wins.
        pins["TTS_OBS"] = "1"
    if not pins:
        return _dispatch_tier(problem, args)

    prev = {k: os.environ.get(k) for k in pins}
    os.environ.update(pins)
    try:
        return _dispatch_tier(problem, args)
    finally:
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _dispatch_tier(problem, args):
    ckpt_kw = dict(
        max_steps=args.max_steps,
        checkpoint_path=args.checkpoint,
        checkpoint_interval_s=args.checkpoint_interval,
        resume_from=args.resume,
    )
    wants_resident = (
        args.checkpoint is not None
        or args.resume is not None
        or args.max_steps is not None
        or args.K is not None
    )
    if args.tier == "seq" and wants_resident:
        raise NotImplementedError(
            "--checkpoint/--resume/--max-steps/--K need a device tier"
        )
    if args.tier in ("multi", "dist") and (
        args.max_steps is not None or args.K is not None
    ):
        raise NotImplementedError(
            "--max-steps/--K need the device, mesh, or dist_mesh tier"
        )
    if args.tier == "dist_mesh":
        from .parallel.dist_mesh import dist_mesh_search

        kw = dict(m=args.m, M=args.M, D=args.D, mp=args.mp,
                  num_hosts=args.hosts, **ckpt_kw)
        if args.K is not None:
            kw["K"] = args.K
        return dist_mesh_search(problem, **kw)
    if args.tier == "seq":
        from .engine import sequential_search

        return sequential_search(problem)
    if args.tier == "device":
        if args.engine == "resident":
            from .engine.resident import resident_search

            if args.K is not None:
                ckpt_kw["K"] = args.K
            return resident_search(problem, m=args.m, M=args.M, **ckpt_kw)
        if wants_resident:
            raise NotImplementedError(
                "--checkpoint/--resume/--max-steps/--K need the resident engine"
            )
        from .engine.device import device_search

        return device_search(problem, m=args.m, M=args.M)
    if args.tier == "mesh":
        from .parallel.resident_mesh import mesh_resident_search

        if args.K is not None:
            ckpt_kw["K"] = args.K
        return mesh_resident_search(
            problem, m=args.m, M=args.M, D=args.D, mp=args.mp, **ckpt_kw
        )
    ckpt_pass = dict(
        checkpoint_path=args.checkpoint,
        checkpoint_interval_s=args.checkpoint_interval,
        resume_from=args.resume,
    )
    if args.tier == "multi":
        from .parallel.multidevice import multidevice_search

        return multidevice_search(
            problem, m=args.m, M=args.M, D=args.D, perc=args.perc,
            **ckpt_pass,
        )
    from .parallel.dist import dist_search

    if args.steal_interval is not None:
        # Only forward when explicitly set — dist_search owns the default.
        ckpt_pass["steal_interval_s"] = args.steal_interval
    return dist_search(
        problem, m=args.m, M=args.M, D=args.D, perc=args.perc,
        num_hosts=args.hosts, steal=not args.no_steal,
        **ckpt_pass,
    )


def print_settings(args) -> None:
    print("\n=================================================")
    tier_names = {
        "seq": "Sequential",
        "device": "Single-device",
        "mesh": "SPMD device-mesh",
        "multi": "Multi-device",
        "dist": "Distributed multi-device",
        "dist_mesh": "Distributed mesh-resident",
    }
    print(f"{tier_names[args.tier]} TPU tree search\n")
    if args.problem == "nqueens":
        print(f"Resolution of the {args.N}-Queens instance")
        print(f"  with {args.g} safety check(s) per evaluation")
    else:
        from .problems.pfsp import taillard

        print(
            f"Resolution of PFSP Taillard's instance: ta{args.inst:03d} "
            f"(m = {taillard.nb_machines(args.inst)}, n = {taillard.nb_jobs(args.inst)})"
        )
        print("Initial upper bound: " + ("opt" if args.ub == 1 else "inf"))
        print(f"Lower bound function: {args.lb}")
        if args.lb == "lb2" and args.lb2_variant != "full":
            print(f"lb2 machine-pair subset: {args.lb2_variant}")
        print("Branching rule: fwd")
    if uses_compaction(args):
        # The raw knob; the RESOLVED path (auto picks per problem shape)
        # is printed with the results and recorded in the stats line.
        import os

        knob = args.compact or os.environ.get("TTS_COMPACT", "auto")
        print(f"Survivor path (TTS_COMPACT): {knob}")
        # Raw one-kernel-cycle knob; the RESOLVED state (auto arms per
        # device/shape/VMEM fit, refusals record why) is printed with the
        # results and recorded in the stats line.
        mknob = os.environ.get("TTS_MEGAKERNEL", "auto") or "auto"
        print(f"One-kernel cycle (TTS_MEGAKERNEL): {mknob}")
        # Raw dispatch-pipeline knobs; the RESOLVED depth/K are printed
        # with the results (auto may resize K along the ladder mid-run).
        pknob = os.environ.get("TTS_PIPELINE", "auto") or "auto"
        kknob = os.environ.get("TTS_K") or (
            args.K if args.K is not None else "default"
        )
        print(f"Dispatch pipeline (TTS_PIPELINE): {pknob}; "
              f"K schedule (TTS_K): {kknob}")
        if args.phase_profile:
            print("Phase profiler (TTS_PHASEPROF): armed — separate "
                  "program variant, NOT a headline measurement")
        if args.xla_trace is not None:
            print(f"XLA trace capture (TTS_XLA_TRACE): {args.xla_trace} "
                  "(steady-state dispatch window)")
    if args.tier in ("dist", "dist_mesh"):
        # Raw steal-hierarchy knob; the RESOLVED per-link-class periods
        # and quanta are printed with the results and recorded in the
        # stats line (parallel/topology.py).
        import os

        from .parallel.topology import steal_mode

        pods = os.environ.get("TTS_PODS")
        print(f"Inter-host stealing (TTS_STEAL): {steal_mode()}"
              + (f"; pod map (TTS_PODS): {pods}" if pods else ""))
    print("=================================================")


def print_results(args, problem, res) -> None:
    for i, ph in enumerate(res.phases[:3], 1):
        label = {1: "Initial search on CPU", 2: "Search on device", 3: "Final search on CPU"}.get(
            i, f"Phase {i}"
        )
        if len(res.phases) > 1:
            print(f"\n{label} completed")
            print(f"Size of the explored tree: {ph.tree}")
            print(f"Number of explored solutions: {ph.sol}")
            print(f"Elapsed time: {ph.seconds:.6f} [s]")
    if res.complete:
        print("\nExploration terminated.")
    elif args.checkpoint is not None:
        print("\nExploration interrupted (checkpointed; resume with --resume).")
    else:
        # max_steps cutoff without --checkpoint: no file exists — don't
        # claim one does.
        print("\nExploration interrupted (no checkpoint written).")
    print("\n=================================================")
    print(f"Size of the explored tree: {res.explored_tree}")
    print(f"Number of explored solutions: {res.explored_sol}")
    if args.problem == "pfsp":
        tag = " (improved)" if res.best < problem.initial_ub else " (not improved)"
        print(f"Optimal makespan: {res.best}{tag}")
    print(f"Elapsed time: {res.elapsed:.6f} [s]")
    if res.per_worker_tree:
        shares = ", ".join(f"{s:.2f}" for s in res.workload_shares())
        print(f"Workload per device (%): [{shares}]")
    if res.compact:
        tag = " (auto)" if res.compact_auto else ""
        print(f"Survivor path: {res.compact}{tag}")
    if res.kernel_backend:
        # The resolved kernel flavor (TTS_KERNEL_BACKEND, ops/backend.py),
        # with the raw knob when it forced a non-default resolution.
        from .ops import backend as _BK

        mode = _BK.kernel_backend_mode()
        tag = "" if mode == "auto" else f" (forced: {mode})"
        print(f"Kernel backend: {res.kernel_backend}{tag}")
    if res.megakernel:
        tag = " (auto)" if res.megakernel_auto else ""
        why = f" — {res.megakernel_reason}" if res.megakernel_reason else ""
        # Armed builds name the streamed pool-tile width and whether the
        # pool axis actually tiled (ops/megakernel.py Decision): "tiled
        # Mt=16 x4" is the double-buffered HBM->VMEM streaming form,
        # "resident Mt=M" the single-tile pool-resident form.
        tile = ""
        if res.megakernel == "on" and res.megakernel_mt:
            form = "tiled" if res.megakernel_tiled else "resident"
            tile = f", {form} Mt={res.megakernel_mt}"
        print(f"One-kernel cycle: {res.megakernel}{tag}{tile}{why}")
    if res.k_resolved is not None:
        tag = " (auto)" if res.k_auto else ""
        print(f"Dispatch pipeline: depth={res.pipeline_depth}, "
              f"K={res.k_resolved}{tag}")
    if res.phase_profile:
        # The `tts profile` deliverable: the measured on-device cycle
        # decomposition, closed by the dominant-phase call-out.
        from .obs import phases as obs_phases
        from .obs.report import phase_table

        for line in phase_table(obs_phases.decomp(res.phase_profile)):
            print(line)
    d = res.diagnostics
    if d.kernel_launches:
        dbuf = (
            f" double_buffered={d.double_buffered}"
            if d.double_buffered else ""
        )
        print(
            f"Device diagnostics: kernel_launch={d.kernel_launches} "
            f"host_to_device={d.host_to_device} "
            f"device_to_host={d.device_to_host}{dbuf}"
        )
    if res.quality and res.quality.get("points"):
        # TTS_QUALITY=1: the anytime curve (obs/quality.py) — one line per
        # incumbent improvement, closed with the primal gap when the
        # instance has a committed reference optimum.
        from .obs import quality as obs_quality

        q = res.quality
        opt = q.get("optimum")
        print(f"Quality trajectory ({len(q['points'])} incumbent(s)"
              + (f", optimum {opt}" if opt is not None else "") + "):")
        for p in q["points"]:
            g = obs_quality.primal_gap(p.get("best"), opt)
            print(f"  t={p['t_s']:.3f}s  step={p['step']}  "
                  f"best={p['best']}  nodes={p['nodes']}"
                  + (f"  gap={100.0 * g:.2f}%" if g is not None else ""))
        pi = obs_quality.primal_integral(q["points"], opt,
                                         max(res.elapsed, 1e-9))
        if pi is not None:
            print(f"  primal integral: {pi:.4f}")
    if res.steals:
        print(f"Work steals (intra-host): {res.steals}")
    if res.comm:
        c = res.comm
        print(
            f"Inter-host comm: exchange_rounds={c['rounds']} "
            f"stolen_blocks={c['blocks_received']} "
            f"stolen_nodes={c['nodes_received']}"
        )
    if res.steal_policy:
        # The RESOLVED steal hierarchy (parallel/topology.py): one line
        # per link class — level, match period, and donation quantum,
        # with the COSTMODEL.json key each resolved from (or "fixed").
        sp = res.steal_policy
        print(f"Steal policy: {sp['mode']} pods={sp['pods']}")
        for link, s in sp.get("levels", {}).items():
            print(f"  {link}: level={s['level']} every={s['every']} "
                  f"period={s['period_s']}s quantum={s['quantum']} "
                  f"({s['source']})")
    print("=================================================\n")


def result_record(args, res) -> dict:
    rec = {
        "problem": args.problem,
        "tier": args.tier,
        "explored_tree": res.explored_tree,
        "explored_sol": res.explored_sol,
        "elapsed_s": round(res.elapsed, 6),
    }
    if not res.complete:
        rec["complete"] = False
    if res.steals:
        rec["steals"] = res.steals
    if res.comm:
        rec["comm"] = res.comm
    if res.steal_policy:
        # The resolved steal hierarchy (TTS_STEAL, parallel/topology.py):
        # per-link-class periods/quanta + the profile key each came from.
        rec["steal_policy"] = res.steal_policy
    if res.obs:
        # On-device counter totals (TTS_OBS=1): the stats line carries the
        # run's telemetry snapshot like the reference's diagnostics counters
        # ride its .dat lines.
        rec["obs"] = res.obs
    if res.quality and res.quality.get("points"):
        # TTS_QUALITY=1: the incumbent trajectory (obs/quality.py).
        rec["quality"] = res.quality
    if args.problem == "pfsp":
        rec.update(inst=args.inst, lb=args.lb, ub=args.ub, optimum=res.best)
    else:
        rec.update(N=args.N, g=args.g)
    if args.tier != "seq":
        # Which evaluation path the run's configuration selects — lets a
        # stats line prove the hot path was active (the reference's runs
        # are implicitly kernel-or-nothing; here the jnp fallback is silent
        # by design). Re-derived from the same inputs the evaluator
        # builders use (default backend + job count); a run that pins
        # chunks to a non-default device would need the decision captured
        # in diagnostics instead.
        from .ops import pallas_kernels as PK

        rec["pallas"] = PK.use_pallas()
        if uses_compaction(args):
            # The RESOLVED survivor path the compiled step baked in (the
            # engine surfaces it on the result — under auto the knob alone
            # no longer names the mode). Runs whose engine never compacts
            # carry no "compact" key at all — a stats line must not claim
            # a mode the run did not use.  Fallback for engines that do
            # compact but predate the surfacing: args.compact first
            # (run_tier restores the env pin before this record is built).
            from .ops.pfsp_device import compact_mode

            rec["compact"] = (
                res.compact or args.compact or compact_mode()
            )
            if res.compact_auto:
                rec["compact_auto"] = True
            # Pipeline depth + the K the run ended on (auto may have
            # resized along the ladder) — the stats line must prove which
            # dispatch regime produced the number.
            rec["pipeline_depth"] = res.pipeline_depth
            if res.k_resolved is not None:
                rec["k"] = res.k_resolved
            if res.k_auto:
                rec["k_auto"] = True
            # The RESOLVED one-kernel-cycle state (engine-surfaced, like
            # "compact") — a stats line must prove whether the fused cycle
            # or the op-chain produced the number, and a refusal must say
            # why it fell back.
            # The resolved kernel flavor (TTS_KERNEL_BACKEND seam) — a
            # stats line must prove which lowering produced the number,
            # and the raw knob when it forced the resolution.
            if res.kernel_backend is not None:
                rec["kernel_backend"] = res.kernel_backend
                from .ops import backend as _BK

                if _BK.kernel_backend_mode() != "auto":
                    rec["kernel_backend_mode"] = _BK.kernel_backend_mode()
            if res.megakernel is not None:
                rec["megakernel"] = res.megakernel
                if res.megakernel_auto:
                    rec["megakernel_auto"] = True
                if res.megakernel_reason:
                    rec["megakernel_reason"] = res.megakernel_reason
                # Armed builds record the streamed pool-tile width and
                # whether the pool axis tiled — the stats line must prove
                # WHICH megakernel form (single-tile resident vs streamed
                # grid) produced the number.
                if res.megakernel_mt:
                    rec["megakernel_mt"] = res.megakernel_mt
                    rec["megakernel_tiled"] = res.megakernel_tiled
            if res.roofline is not None:
                # Phase-profiled runs bank the memory-roofline audit
                # (obs/roofline.py) — per-phase %-of-memory-bound-peak.
                rec["roofline_mem"] = res.roofline
        if args.problem == "pfsp" and args.lb == "lb2":
            # Staging applies at every mp: under mp > 1 the compacted self
            # bound shards its pair loop with a pmax combine. The job count
            # matters: auto mode only stages at n <= 100.
            from .ops import pfsp_device as P
            from .problems.pfsp import bounds as PB
            from .problems.pfsp import taillard

            n_ = taillard.nb_jobs(args.inst)
            rec["lb2_staged"] = P.lb2_staged_enabled(None, n_)
            # Resolved pair-block size (the run's baked-in value): flag
            # first — run_tier restores the env pin before this record is
            # built (same convention as "compact" above).
            Pn = len(PB.machine_pairs(
                taillard.nb_machines(args.inst), args.lb2_variant
            ))
            knob = args.lb2_pairblock
            if knob is None or knob == "auto":
                rec["lb2_pairblock"] = (
                    P._auto_pairblock(Pn, n_) if knob == "auto"
                    else P.lb2_pairblock(Pn, n_)
                )
            else:
                rec["lb2_pairblock"] = min(int(knob), Pn)
            if args.lb2_variant != "full":
                rec["lb2_variant"] = args.lb2_variant
    return rec


def run_topology(args) -> str:
    """The profile-key topology string of this run (obs/costmodel.py):
    mirrors what the engines pass to ``resolve_target_band`` so a capture
    from tier X exactly matches a later run of tier X."""
    if args.tier in ("seq", "device"):
        return "device-D1"
    D = args.D if args.D is not None else 0  # 0 = "all local devices"
    if args.tier == "mesh":
        return f"mesh-D{D}" if D else "mesh-Dall"
    if args.tier == "dist_mesh":
        H = args.hosts or 1
        return f"dist_mesh-H{H}xD{D}" if D else f"dist_mesh-H{H}xDall"
    H = args.hosts or 1
    return f"{args.tier}-H{H}xD{D}" if D else f"{args.tier}-H{H}xDall"


def write_costmodel(args, problem, evts, path, cm) -> tuple[str, dict]:
    """Fit + merge this run's profile entry into ``path`` (the
    ``--costmodel`` capture). Returns the entry's (key, value)."""
    try:
        import jax

        backend = jax.default_backend()
    except Exception:  # noqa: BLE001 — capture must not fail the run
        backend = "cpu"
    profile = cm.build_profile(
        evts, backend, run_topology(args), cm.shape_class(problem)
    )
    cm.save(path, profile)
    key = next(iter(profile))
    return key, profile[key]


def enable_compile_cache() -> None:
    """Persist XLA/Mosaic executables across processes (the resident tiers
    compile ~30s while-loop programs, and large-instance Mosaic compiles
    exceed 240s — ta056/ta111 class, docs/HW_VALIDATION.md).

    ``TTS_COMPILE_CACHE=<dir>`` points the cache at a shared directory (the
    warm-cache recipe: run ``scripts/warm_cache.py`` once during any green
    window with the same value, and every later CLI/bench/sweep process —
    they all call this at startup — reuses the banked executables);
    ``TTS_COMPILE_CACHE=0`` opts out; unset defaults to a per-build
    ``~/.cache/tpu_tree_search/xla/<key>`` directory."""
    import os

    want = os.environ.get("TTS_COMPILE_CACHE", "")
    if want == "0":
        return
    try:
        import platform
        import socket

        import jax
        import jaxlib

        # Key the cache by build + host: an AOT executable produced by a
        # different libtpu/jaxlib build or another machine's CPU features
        # must never be loaded (observed failure modes: libtpu
        # FAILED_PRECONDITION version mismatch, XLA:CPU SIGILL warnings).
        key = "-".join([
            jax.__version__, jaxlib.__version__,
            platform.machine(), socket.gethostname(),
        ])
        path = want or os.path.join(
            os.path.expanduser("~"), ".cache", "tpu_tree_search", "xla", key
        )
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass  # cache is an optimization; never fail a run over it


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.problem == "profile":
        # `tts profile <run-args>`: the same run command with the phase
        # clocks forced on; the decomposition table prints with the
        # results (add --xla-trace DIR inside <run-args> to also bank a
        # steady-state XLA capture).
        rest = [a for a in args.rest if a != "--"]
        if not rest:
            parser.error(
                "profile: pass a full run command, e.g. "
                "`tts profile pfsp --inst 14 --tier device`"
            )
        args = parser.parse_args(rest)
        if args.problem in ("lint", "check", "report", "watch", "profile",
                            "serve", "submit", "warmup", "top", "migrate",
                            "fleet"):
            parser.error("profile wraps a search run, not another "
                         "subcommand")
        args.phase_profile = True
    if args.problem == "lint":
        # Pure static analysis: no jax import, no backend init.
        from .analysis import run_lint_cli

        return run_lint_cli(args)
    if args.problem == "check":
        # Tracing-only program audit (jax traces, nothing executes).
        from .analysis.program_audit import run_check_cli

        return run_check_cli(args)
    if args.problem == "report":
        # Pure trace summarization: no jax import, no backend init.
        from .obs.report import report_main

        return report_main(args.trace, as_json=args.report_json,
                           roofline=args.report_roofline,
                           costmodel=args.report_costmodel)
    if args.problem == "watch":
        if args.job is not None:
            # Pure HTTP client of a serve daemon: no jax import.
            from .serve import DEFAULT_PORT
            from .serve.client import watch_job_main

            return watch_job_main(
                args.job, port=args.port or DEFAULT_PORT, host=args.host,
                once=args.once, as_json=args.watch_json,
            )
        # Pure HTTP client of a --obs-serve run: no jax import.
        from .obs.live import watch_main

        return watch_main(args.port or 8642, host=args.host,
                          interval=args.interval, once=args.once,
                          as_json=args.watch_json)
    if args.problem == "top":
        # Pure HTTP client of a serve daemon (or fleet router): no jax.
        router = args.router or os.environ.get("TTS_ROUTER")
        if router:
            from .serve.client import fleet_top_main

            return fleet_top_main(router, interval=args.interval,
                                  once=args.once, as_json=args.top_json)
        from .serve.client import top_main

        return top_main(port=args.port, host=args.host,
                        interval=args.interval, once=args.once,
                        as_json=args.top_json)
    if args.problem == "migrate":
        # Pure HTTP client of two serve daemons: no jax import.
        from .serve.client import migrate_main

        return migrate_main(args.job, args.to, port=args.port,
                            host=args.host, as_json=args.migrate_json)
    if args.problem == "serve":
        # The daemon: jax stays out of the HTTP threads (scheduler
        # workers import the engines lazily on the first slice).
        from .serve.server import serve_main

        enable_compile_cache()
        return serve_main(port=args.port, host=args.host,
                          state_dir=args.state_dir, workers=args.workers,
                          quantum_s=args.quantum, max_queue=args.max_queue,
                          warm=args.warm, batch_slots=args.batch_slots,
                          ckpt_every_s=args.ckpt_every,
                          router=args.router or os.environ.get("TTS_ROUTER"))
    if args.problem == "fleet":
        # The router: host-only by construction (no jax anywhere in
        # fleet/ — placement reuses the daemons' own host-side class-key
        # computation), so no compile cache and no backend init.
        from .fleet.router import router_main

        return router_main(port=args.port, host=args.host,
                           state_dir=args.state_dir, daemons=args.daemons,
                           scrape_interval_s=args.scrape_interval,
                           max_misses=args.health_misses,
                           pull_interval_s=args.pull_interval,
                           rebalance=not args.no_rebalance,
                           rebalance_min_depth=args.rebalance_depth)
    if args.problem == "submit":
        # Thin client: re-parse the run command through THIS parser so
        # every CLI-side validation runs before the spec leaves the
        # process (same REMAINDER trick as `tts profile`); no jax import.
        rest = [a for a in args.rest if a != "--"]
        if not rest:
            parser.error(
                "submit: pass a full run command, e.g. "
                "`tts submit -- pfsp --inst 14 --tier device`"
            )
        run_args = parser.parse_args(rest)
        if run_args.problem not in ("nqueens", "pfsp"):
            parser.error("submit wraps a search run, not another "
                         "subcommand")
        validate_args(parser, run_args)
        from .serve.client import spec_from_args, submit_main

        return submit_main(spec_from_args(run_args), port=args.port,
                           host=args.host, wait=args.wait,
                           as_json=args.submit_json,
                           router=args.router or os.environ.get("TTS_ROUTER"))
    if args.problem == "warmup":
        # Subprocess orchestration: each config compiles in its own
        # process against the persistent cache; no jax import here.
        from .serve.warmup import warmup_main

        return warmup_main(args.configs, timeout_s=args.timeout)
    validate_args(parser, args)
    primary = True
    if args.distributed:
        # Must run before ANY jax call that initializes backends (including
        # the profiler's trace session). Coordinator/process ids come from
        # the launcher's environment (the -nl / mpirun analogue).
        import jax

        # Explicit flags override the launcher env (useful for manual
        # launches and the docs/POD_LAUNCH.md two-shell smoke test); None
        # falls back to GKE/TPU-VM metadata discovery.
        init_kw = {}
        if args.coordinator is not None:
            init_kw["coordinator_address"] = args.coordinator
        if args.num_hosts is not None:
            init_kw["num_processes"] = args.num_hosts
        if args.host_id is not None:
            init_kw["process_id"] = args.host_id
        try:
            jax.distributed.initialize(**init_kw)
        except Exception as e:
            print(
                f"Error: jax.distributed.initialize() failed: {e}\n"
                "(--distributed needs the launcher to supply coordinator/"
                "process environment, or pass --coordinator/--num-hosts/"
                "--host-id explicitly)",
                file=sys.stderr,
            )
            return 2
        primary = jax.process_index() == 0
    enable_compile_cache()
    try:
        problem = make_problem(args)
    except ValueError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 2
    if primary:
        print_settings(args)
    from .obs import events as obs_events

    wants_obs = (args.trace or args.metrics_file or args.costmodel
                 or args.obs_serve is not None)
    if wants_obs or obs_events.enabled():
        # Run-scoped telemetry: a prior run's events in this process must
        # not leak into this run's trace.
        obs_events.reset()
        # Arm the flight recorder from the MAIN thread (signal handlers
        # only attach here; engines re-arm the watchdog per run). With
        # TTS_OBS off and TTS_FLIGHTREC unset this is a no-op.
        from .obs import flightrec

        flightrec.reset()
        flightrec.recorder().install()
    live_server = None
    if args.obs_serve is not None and primary:
        from .obs import live as obs_live

        live_server = obs_live.serve(args.obs_serve)
        print(f"Live monitor: {live_server.url} "
              f"(tts watch --port {live_server.port})")
    try:
        if args.profile:
            # Trace the whole search (phase timers remain the first-class
            # report, SURVEY.md §5 tracing; this adds the XLA-level view).
            import jax

            with jax.profiler.trace(args.profile):
                res = run_tier(problem, args)
        else:
            res = run_tier(problem, args)
    except (ModuleNotFoundError, NotImplementedError) as e:
        print(f"Error: tier '{args.tier}' unavailable: {e}", file=sys.stderr)
        return 2
    finally:
        if live_server is not None:
            live_server.close()
    # Multi-process pods: every host computed the same reduced result;
    # report from process 0 only (the MPI baseline's rank-0 stats line,
    # `pfsp_dist_multigpu_cuda.c:179-187`).
    if primary:
        print_results(args, problem, res)
        rec = result_record(args, res)
        if args.trace or args.metrics_file or args.costmodel:
            from .obs import export as obs_export

            evts = obs_events.drain()
            if args.trace:
                n = obs_export.write_chrome_trace(evts, args.trace)
                print(f"Trace written: {args.trace} ({n} events; "
                      "open in Perfetto or `tts report`)")
            if args.metrics_file:
                obs_export.write_metrics_jsonl(evts, args.metrics_file)
            if args.costmodel:
                from .obs import costmodel as obs_costmodel

                key, entry = write_costmodel(
                    args, problem, evts, args.costmodel, obs_costmodel
                )
                links = ", ".join(sorted(entry["links"])) or "none"
                print(f"Cost model written: {args.costmodel} [{key}] "
                      f"(links: {links}; arm with TTS_COSTMODEL="
                      f"{args.costmodel})")
        if args.json:
            print(json.dumps(rec))
        if args.stats_file:
            # Append-only stats line, like `stats_pfsp_gpu_cuda.dat`
            # (`pfsp_gpu_cuda.c:140-148`).
            with open(args.stats_file, "a") as f:
                f.write(json.dumps(rec) + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
