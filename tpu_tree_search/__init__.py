"""tpu-tree-search: a TPU-native framework for accelerated tree search.

Re-implements, TPU-first, the capabilities of the Chapel/CUDA reference
`Guillaume-Helbecque/GPU-accelerated-tree-search-Chapel`: multi-pool
depth-first backtracking / Branch-and-Bound whose batched node evaluations
(N-Queens safety checks, PFSP lb1/lb1_d/lb2 lower bounds) run as XLA/Pallas
kernels on TPU chips, with four scaling tiers (sequential, single-device,
multi-device, multi-host) instead of the reference's eight copy-pasted
programs (see SURVEY.md §1).

Layout:
  problems/  problem plugins (N-Queens, PFSP) + numpy oracle bounds
  ops/       device kernels (vectorized jnp + Pallas)
  pool/      host-side work pools (SoA deque, lock-based parallel variant,
             optional C++ native backend)
  engine/    search drivers: sequential, chunked-offload device, fused
             on-device (lax.while_loop)
  parallel/  multi-device runtime (work stealing, termination) and
             mesh/multi-host tier (jax.sharding + collectives)
  utils/     termination detection, diagnostics counters, config
"""

__version__ = "0.1.0"
