"""Problem protocol — the plugin interface that factors the reference's eight
copy-pasted programs into one engine (SURVEY.md §1 note, §7.1.1).

A problem supplies:
  * an SoA node schema (fixed-size fields, device-friendly dtypes),
  * the root node,
  * host-side ``decompose`` (evaluate + branch one node) for the sequential
    tier and the warm-up / drain phases of the offload tiers
    (`nqueens_chpl.chpl:70-89`, `pfsp_chpl.chpl:88-172`),
  * a batched device evaluator (children labels/bounds for a chunk of
    parents) for the offload tiers (`nqueens_gpu_chpl.chpl:97-123`,
    `pfsp_gpu_chpl.chpl:192-270`),
  * vectorized host ``generate_children`` consuming device results
    (`nqueens_gpu_chpl.chpl:126-149`, `pfsp_gpu_chpl.chpl:273-303`).

Node batches are plain dicts ``{field: np.ndarray[batch, ...]}`` (SoA). A
single node is the same dict with unbatched arrays.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Mapping

import numpy as np

# A node batch: field name -> array whose leading axis is the batch.
NodeBatch = dict[str, np.ndarray]

# Sentinel "no incumbent" upper bound (C uses INT_MAX, `pfsp_c.c`; Chapel
# max(int)). Kept within int32 so device kernels can carry it.
INF_BOUND = 2**31 - 1


@dataclass
class DecomposeResult:
    children: NodeBatch  # surviving children, batch-first SoA
    tree_inc: int  # nodes pushed (exploredTree increment)
    sol_inc: int  # leaves visited (exploredSol increment)
    best: int  # possibly-improved incumbent


class Problem:
    """Interface; see NQueensProblem / PFSPProblem for the two instantiations."""

    name: str = "problem"
    # Children slots per parent (== branching-factor upper bound): N for
    # N-Queens, jobs for PFSP. Device result slot [i*width + j] is child j of
    # parent i (SURVEY.md Appendix A "chunk cycle invariant").
    child_slots: int

    def node_fields(self) -> Mapping[str, tuple[tuple[int, ...], np.dtype]]:
        """Field name -> (per-node shape, dtype)."""
        raise NotImplementedError

    def root(self) -> NodeBatch:
        """Batch of one: the root node."""
        raise NotImplementedError

    def decompose(self, node: dict[str, Any], best: int) -> DecomposeResult:
        """Evaluate + branch one node on host (sequential-tier semantics)."""
        raise NotImplementedError

    # -- offload tier ------------------------------------------------------

    def make_device_evaluator(self, device=None):
        """Returns a jit-compiled ``fn(parents: dict[str, jnp], count, best)
        -> results`` evaluating all children of a padded chunk. ``results``
        has shape (capacity, child_slots). ``device`` (optional) is the
        target device, used to route hand-written kernels per platform.
        """
        raise NotImplementedError

    def generate_children(
        self, parents: NodeBatch, count: int, results: np.ndarray, best: int
    ) -> DecomposeResult:
        """Vectorized host-side prune/branch from device results."""
        raise NotImplementedError

    # -- native host runtime (csrc/tts_native.cpp) -------------------------
    #
    # Problems may provide C++ fast paths for the host-side phases by
    # overriding ``_make_native``; every ``native_*`` hook returns None when
    # the native library is unavailable (TTS_NATIVE=0 or no toolchain) and
    # the caller falls back to the Python path. The Python implementations
    # stay the semantic oracles.

    def _make_native(self, lib):
        """Build this problem's native runtime from the loaded library."""
        return None

    def _native(self):
        if not hasattr(self, "_native_rt"):
            from .. import native

            lib = native.load()
            self._native_rt = self._make_native(lib) if lib else None
        return self._native_rt

    def native_sequential(self, best: int):
        """Full sequential search -> (tree, sol, best) or None."""
        return None

    def native_warmup(self, batch: NodeBatch, best: int, target: int):
        """BFS warm-up -> (frontier_batch, tree, sol, best) or None."""
        return None

    def native_drain(self, batch: NodeBatch, best: int):
        """DFS a frontier to completion -> (tree, sol, best) or None."""
        return None

    # -- helpers -----------------------------------------------------------

    def empty_batch(self, capacity: int) -> NodeBatch:
        return {
            name: np.zeros((capacity,) + shape, dtype=dtype)
            for name, (shape, dtype) in self.node_fields().items()
        }


def batch_length(batch: NodeBatch) -> int:
    for v in batch.values():
        return v.shape[0]
    return 0


def concat_batches(batches: list[NodeBatch]) -> NodeBatch:
    keys = batches[0].keys()
    return {k: np.concatenate([b[k] for b in batches], axis=0) for k in keys}


def slice_batch(batch: NodeBatch, lo: int, hi: int) -> NodeBatch:
    return {k: v[lo:hi] for k, v in batch.items()}


def index_batch(batch: NodeBatch, idx) -> NodeBatch:
    return {k: v[idx] for k, v in batch.items()}
