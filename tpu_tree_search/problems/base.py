"""Problem protocol — the plugin interface that factors the reference's eight
copy-pasted programs into one engine (SURVEY.md §1 note, §7.1.1).

A problem supplies:
  * an SoA node schema (fixed-size fields, device-friendly dtypes),
  * the root node,
  * host-side ``decompose`` (evaluate + branch one node) for the sequential
    tier and the warm-up / drain phases of the offload tiers
    (`nqueens_chpl.chpl:70-89`, `pfsp_chpl.chpl:88-172`),
  * a batched device evaluator (children labels/bounds for a chunk of
    parents) for the offload tiers (`nqueens_gpu_chpl.chpl:97-123`,
    `pfsp_gpu_chpl.chpl:192-270`),
  * vectorized host ``generate_children`` consuming device results
    (`nqueens_gpu_chpl.chpl:126-149`, `pfsp_gpu_chpl.chpl:273-303`).

Node batches are plain dicts ``{field: np.ndarray[batch, ...]}`` (SoA). A
single node is the same dict with unbatched arrays.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Mapping

import numpy as np

# A node batch: field name -> array whose leading axis is the batch.
NodeBatch = dict[str, np.ndarray]


def narrow_mode() -> str:
    """``TTS_NARROW`` — narrow node storage dtypes (int8/int16 instead of
    int32) through the host pools, staging, donate payloads, and
    checkpoints. ``auto`` (default) narrows every field whose value range
    provably fits; ``0`` pins the historical int32 layout (byte-identical
    programs — the `narrow-knob-inert` contract). The device-resident
    pools were already narrow (`engine/resident._pool_int_dtype`); this
    knob closes the host side of the stack."""
    mode = os.environ.get("TTS_NARROW", "auto")
    if mode not in ("auto", "0"):
        raise ValueError(
            f"TTS_NARROW must be 'auto' or '0', got {mode!r}"
        )
    return mode


def narrow_enabled() -> bool:
    return narrow_mode() != "0"

# Sentinel "no incumbent" upper bound (C uses INT_MAX, `pfsp_c.c`; Chapel
# max(int)). Kept within int32 so device kernels can carry it.
INF_BOUND = 2**31 - 1


@dataclass
class DecomposeResult:
    children: NodeBatch  # surviving children, batch-first SoA
    tree_inc: int  # nodes pushed (exploredTree increment)
    sol_inc: int  # leaves visited (exploredSol increment)
    best: int  # possibly-improved incumbent


class Problem:
    """Interface; see NQueensProblem / PFSPProblem for the two instantiations."""

    name: str = "problem"
    # Children slots per parent (== branching-factor upper bound): N for
    # N-Queens, jobs for PFSP. Device result slot [i*width + j] is child j of
    # parent i (SURVEY.md Appendix A "chunk cycle invariant").
    child_slots: int

    def field_specs(
        self,
    ) -> Mapping[str, tuple[tuple[int, ...], np.dtype, np.dtype]]:
        """Field name -> (per-node shape, wide dtype, narrow storage dtype).

        The narrow dtype is a problem-declared property: the problem knows
        its fields' value ranges (a permutation of ``n`` jobs fits int8 for
        n <= 127, int16 through the ta111-class n=500; depth/limit1 are
        bounded by n). ``node_fields`` resolves the pair against the
        ``TTS_NARROW`` knob — everything downstream (host pools, staging,
        donate pickles, checkpoints) allocates from ``node_fields`` and
        narrows automatically.
        """
        raise NotImplementedError

    def node_fields(self) -> Mapping[str, tuple[tuple[int, ...], np.dtype]]:
        """Field name -> (per-node shape, storage dtype), with the
        ``TTS_NARROW`` knob resolved. Single source of truth for every
        host-side node buffer."""
        narrow = narrow_enabled()
        return {
            name: (shape, np.dtype(nd if narrow else wd))
            for name, (shape, wd, nd) in self.field_specs().items()
        }

    def root(self) -> NodeBatch:
        """Batch of one: the root node."""
        raise NotImplementedError

    def decompose(self, node: dict[str, Any], best: int) -> DecomposeResult:
        """Evaluate + branch one node on host (sequential-tier semantics)."""
        raise NotImplementedError

    # -- offload tier ------------------------------------------------------

    def make_device_evaluator(self, device=None):
        """Returns a jit-compiled ``fn(parents: dict[str, jnp], count, best)
        -> results`` evaluating all children of a padded chunk. ``results``
        has shape (capacity, child_slots). ``device`` (optional) is the
        target device, used to route hand-written kernels per platform.
        """
        raise NotImplementedError

    def generate_children(
        self, parents: NodeBatch, count: int, results: np.ndarray, best: int
    ) -> DecomposeResult:
        """Vectorized host-side prune/branch from device results."""
        raise NotImplementedError

    # -- native host runtime (csrc/tts_native.cpp) -------------------------
    #
    # Problems may provide C++ fast paths for the host-side phases by
    # overriding ``_make_native``; every ``native_*`` hook returns None when
    # the native library is unavailable (TTS_NATIVE=0 or no toolchain) and
    # the caller falls back to the Python path. The Python implementations
    # stay the semantic oracles.

    def _make_native(self, lib):
        """Build this problem's native runtime from the loaded library."""
        return None

    def _native(self):
        if not hasattr(self, "_native_rt"):
            from .. import native

            lib = native.load()
            self._native_rt = self._make_native(lib) if lib else None
        return self._native_rt

    def native_sequential(self, best: int):
        """Full sequential search -> (tree, sol, best) or None."""
        return None

    def native_warmup(self, batch: NodeBatch, best: int, target: int):
        """BFS warm-up -> (frontier_batch, tree, sol, best) or None."""
        return None

    def native_drain(self, batch: NodeBatch, best: int):
        """DFS a frontier to completion -> (tree, sol, best) or None."""
        return None

    # -- helpers -----------------------------------------------------------

    def empty_batch(self, capacity: int) -> NodeBatch:
        return {
            name: np.zeros((capacity,) + shape, dtype=dtype)
            for name, (shape, dtype) in self.node_fields().items()
        }


def batch_length(batch: NodeBatch) -> int:
    for v in batch.values():
        return v.shape[0]
    return 0


def concat_batches(batches: list[NodeBatch]) -> NodeBatch:
    keys = batches[0].keys()
    return {k: np.concatenate([b[k] for b in batches], axis=0) for k in keys}


def slice_batch(batch: NodeBatch, lo: int, hi: int) -> NodeBatch:
    return {k: v[lo:hi] for k, v in batch.items()}


def index_batch(batch: NodeBatch, idx) -> NodeBatch:
    return {k: v[idx] for k, v in batch.items()}
