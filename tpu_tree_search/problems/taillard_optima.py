"""Committed best-known makespans for the bundled Taillard PFSP instances.

Primal-gap computation (``obs/quality.py``, ``tts report``) needs a
*reference* value per instance: the anytime-search literature reports
quality as the gap to the best known solution, not as raw makespans
(Berthold's primal-integral framing, arXiv:2012.09511 §5 uses the same
convention for B&B@Grid). This table commits that reference separately
from ``problems/pfsp/taillard.py`` so a drive-by edit of the engine's
initial-UB table cannot silently move the goalposts of every historical
quality curve — ``tests/test_quality.py`` cross-checks the two.

Provenance: E. Taillard, "Benchmarks for basic scheduling problems"
(EJOR 64, 1993), per the summary table shipped with the reference kit's
``c_taillard.c:31-43`` (the same values the engine uses for ``ub=1``
warm starts). For the 20- and 50-job classes these are proven optima;
for the largest classes (100x20 upward) they are best-known upper
bounds — either way they are the fixed reference a gap is quoted
against. Instances built from an ad-hoc ``p_times`` matrix have no
entry, and every helper here degrades to ``None`` (gap unknown) rather
than guessing.
"""

from __future__ import annotations

#: Best-known makespan per 1-based Taillard instance id. Grouped by
#: instance class (jobs x machines), ten instances per class.
BEST_KNOWN: dict[int, int] = {
    # ta001-ta010 (20x5)
    1: 1278, 2: 1359, 3: 1081, 4: 1293, 5: 1235,
    6: 1195, 7: 1234, 8: 1206, 9: 1230, 10: 1108,
    # ta011-ta020 (20x10)
    11: 1582, 12: 1659, 13: 1496, 14: 1377, 15: 1419,
    16: 1397, 17: 1484, 18: 1538, 19: 1593, 20: 1591,
    # ta021-ta030 (20x20)
    21: 2297, 22: 2099, 23: 2326, 24: 2223, 25: 2291,
    26: 2226, 27: 2273, 28: 2200, 29: 2237, 30: 2178,
    # ta031-ta040 (50x5)
    31: 2724, 32: 2834, 33: 2621, 34: 2751, 35: 2863,
    36: 2829, 37: 2725, 38: 2683, 39: 2552, 40: 2782,
    # ta041-ta050 (50x10)
    41: 2991, 42: 2867, 43: 2839, 44: 3063, 45: 2976,
    46: 3006, 47: 3093, 48: 3037, 49: 2897, 50: 3065,
    # ta051-ta060 (50x20)
    51: 3846, 52: 3699, 53: 3640, 54: 3719, 55: 3610,
    56: 3679, 57: 3704, 58: 3691, 59: 3741, 60: 3755,
    # ta061-ta070 (100x5)
    61: 5493, 62: 5268, 63: 5175, 64: 5014, 65: 5250,
    66: 5135, 67: 5246, 68: 5094, 69: 5448, 70: 5322,
    # ta071-ta080 (100x10)
    71: 5770, 72: 5349, 73: 5676, 74: 5781, 75: 5467,
    76: 5303, 77: 5595, 78: 5617, 79: 5871, 80: 5845,
    # ta081-ta090 (100x20)
    81: 6173, 82: 6183, 83: 6252, 84: 6254, 85: 6285,
    86: 6331, 87: 6223, 88: 6372, 89: 6247, 90: 6404,
    # ta091-ta100 (200x10)
    91: 10862, 92: 10480, 93: 10922, 94: 10889, 95: 10524,
    96: 10329, 97: 10854, 98: 10730, 99: 10438, 100: 10675,
    # ta101-ta110 (200x20)
    101: 11158, 102: 11160, 103: 11281, 104: 11275, 105: 11259,
    106: 11176, 107: 11337, 108: 11301, 109: 11146, 110: 11284,
    # ta111-ta120 (500x20)
    111: 26040, 112: 26500, 113: 26371, 114: 26456, 115: 26334,
    116: 26469, 117: 26389, 118: 26560, 119: 26005, 120: 26457,
}


def known_optimum(inst) -> int | None:
    """Best-known makespan for a 1-based instance id; ``None`` when the
    instance is unknown (ad-hoc matrices, non-integer ids)."""
    if not isinstance(inst, int):
        return None
    return BEST_KNOWN.get(inst)


def optimum_for(problem) -> int | None:
    """Reference value for a problem object: PFSP instances resolve
    through their ``inst`` id; everything else (N-Queens — a counting
    problem with no objective — ad-hoc matrices) has no reference."""
    if getattr(problem, "name", None) != "pfsp":
        return None
    return known_optimum(getattr(problem, "inst", None))


def gap(best, optimum) -> float | None:
    """Relative primal gap ``(best - optimum) / optimum``; ``None`` when
    either side is unknown/unusable (no incumbent yet, unknown instance,
    non-positive reference)."""
    if best is None or optimum is None or optimum <= 0:
        return None
    from .base import INF_BOUND

    if best >= INF_BOUND:
        return None
    return (float(best) - float(optimum)) / float(optimum)
