"""N-Queens as a backtracking Problem plugin.

Semantics mirror the reference exactly (counting parity is a golden-test
invariant, SURVEY.md §4.2):
  * node = (depth, board) where board is a permutation of rows; columns
    0..depth-1 are placed, the rest are candidates
    (`lib/nqueens/NQueens_node.chpl:9-31`);
  * branching swaps board[depth] <=> board[j] for each safe j >= depth
    (`nqueens_chpl.chpl:70-89`);
  * a node popped at depth == N counts one solution; children are counted
    into exploredTree when pushed — including depth-N leaves
    (`nqueens_chpl.chpl:74-86`);
  * the safety check runs ``g`` redundant rounds as an artificial workload
    knob (`nqueens_chpl.chpl:51-67`, `README.md:67-68`).
"""

from __future__ import annotations

import numpy as np

from .base import DecomposeResult, NodeBatch, Problem


class NQueensProblem(Problem):
    name = "nqueens"

    def __init__(self, N: int = 14, g: int = 1):
        if N <= 0 or g <= 0:
            raise ValueError("All parameters must be positive integers.")
        self.N = int(N)
        self.g = int(g)
        self.child_slots = self.N

    def field_specs(self):
        # board was already 1-byte; depth is bounded by N, so int16
        # always fits (the device pool further narrows it to int8 when
        # N <= 127 — `engine/resident._NQueensResident`).
        return {
            "depth": ((), np.dtype(np.int32), np.dtype(np.int16)),
            "board": ((self.N,), np.dtype(np.uint8), np.dtype(np.uint8)),
        }

    def root(self) -> NodeBatch:
        depth_dt = self.node_fields()["depth"][1]
        return {
            "depth": np.zeros((1,), dtype=depth_dt),
            "board": np.arange(self.N, dtype=np.uint8)[None, :],
        }

    # -- host path ---------------------------------------------------------

    def is_safe(self, board: np.ndarray, queen_num: int, row_pos: int) -> bool:
        """Diagonal-safety check (`nqueens_chpl.chpl:51-67`). The ``g`` loop
        only repeats the same comparisons (workload knob), so one round
        decides the label.
        """
        if queen_num == 0:
            return True
        i = np.arange(queen_num)
        other = board[:queen_num].astype(np.int64)
        d = queen_num - i
        return bool(np.all((other != row_pos - d) & (other != row_pos + d)))

    def decompose(self, node: dict, best: int) -> DecomposeResult:
        depth = int(node["depth"])
        board = node["board"]
        N = self.N
        if depth == N:
            return DecomposeResult(self.empty_batch(0), 0, 1, best)
        kept = []
        for j in range(depth, N):
            if self.is_safe(board, depth, int(board[j])):
                child = board.copy()
                child[depth], child[j] = child[j], child[depth]
                kept.append(child)
        children = {
            "depth": np.full(len(kept), depth + 1,
                             dtype=self.node_fields()["depth"][1]),
            "board": (
                np.stack(kept) if kept else np.zeros((0, N), dtype=np.uint8)
            ),
        }
        return DecomposeResult(children, len(kept), 0, best)

    # -- native host runtime -----------------------------------------------

    def _make_native(self, lib):
        from .. import native

        return native.NativeNQueens(lib, self.N, self.g)

    def native_sequential(self, best: int):
        nat = self._native()
        if nat is None:
            return None
        tree, sol = nat.sequential()
        return tree, sol, best

    def native_warmup(self, batch: NodeBatch, best: int, target: int):
        nat = self._native()
        if nat is None:
            return None
        frontier, tree, sol = nat.warmup(batch, target)
        return frontier, tree, sol, best

    def native_drain(self, batch: NodeBatch, best: int):
        nat = self._native()
        if nat is None:
            return None
        tree, sol = nat.drain(batch)
        return tree, sol, best

    # -- device path -------------------------------------------------------

    def make_device_evaluator(self, device=None):
        from ..ops import nqueens_device

        core = nqueens_device.make_jitted_core(self.N, self.g, device)

        def evaluate(parents, count, best):
            """Batched safety labels, one slot per (parent, candidate column)
            (`nqueens_gpu_chpl.chpl:97-123`). Storage may stage depth
            narrow (TTS_NARROW); the label math runs at int32 — a no-op
            cast when storage is already wide."""
            del count, best
            import jax.numpy as jnp

            depth = jnp.asarray(parents["depth"]).astype(jnp.int32)
            return core(parents["board"], depth)

        return evaluate

    def generate_children(
        self, parents: NodeBatch, count: int, results: np.ndarray, best: int
    ) -> DecomposeResult:
        """Vectorized equivalent of `nqueens_gpu_chpl.chpl:126-149`."""
        nat = self._native()
        if nat is not None:
            children, tree_inc, sol_inc = nat.generate_children(
                parents, count, np.asarray(results)
            )
            return DecomposeResult(children, tree_inc, sol_inc, best)
        N = self.N
        depth = parents["depth"][:count].astype(np.int64)
        board = parents["board"][:count]
        labels = np.asarray(results[:count]).astype(bool)  # (count, N)
        k = np.arange(N)[None, :]
        is_parent_leaf = depth == N
        sol_inc = int(is_parent_leaf.sum())
        mask = labels & (k >= depth[:, None]) & ~is_parent_leaf[:, None]
        pi, kj = np.nonzero(mask)
        children_board = board[pi].copy()
        rows = np.arange(pi.size)
        di = depth[pi].astype(np.int64)
        tmp = children_board[rows, di]
        children_board[rows, di] = children_board[rows, kj]
        children_board[rows, kj] = tmp
        children = {
            "depth": (depth[pi] + 1).astype(self.node_fields()["depth"][1]),
            "board": children_board,
        }
        return DecomposeResult(children, int(pi.size), sol_inc, best)
