"""Problem plugins: N-Queens (backtracking) and PFSP (Branch-and-Bound)."""

from .base import INF_BOUND, DecomposeResult, NodeBatch, Problem
from .nqueens import NQueensProblem
from .pfsp.problem import PFSPProblem

__all__ = [
    "INF_BOUND",
    "DecomposeResult",
    "NodeBatch",
    "Problem",
    "NQueensProblem",
    "PFSPProblem",
]
