"""PFSP (Permutation Flowshop Scheduling) as a Branch-and-Bound Problem plugin.

Node and branching semantics mirror the reference exactly (golden-count
parity, SURVEY.md §4):
  * node = (depth, limit1, prmu); jobs prmu[0..limit1] are the fixed prefix;
    forward branching swaps prmu[depth] <=> prmu[i] for i in limit1+1..jobs-1
    (`lib/pfsp/PFSP_node.chpl:9-36`, `pfsp_chpl.chpl:88-113`);
  * a child with depth == jobs is a leaf: counted into exploredSol at
    generation, never pushed; it updates the incumbent if its bound (== its
    makespan) beats it (`pfsp_chpl.chpl:100-111`);
  * a non-leaf child is pushed (and counted into exploredTree) iff
    ``lowerbound < best`` strictly (`pfsp_chpl.chpl:106-111`);
  * initial incumbent = known optimum (ub=1) or +inf (ub=0)
    (`pfsp_chpl.chpl:40`).
"""

from __future__ import annotations

import numpy as np

from ..base import INF_BOUND, DecomposeResult, NodeBatch, Problem
from . import bounds as B
from . import taillard

ALLOWED_LOWER_BOUNDS = ("lb1", "lb1_d", "lb2")


class PFSPProblem(Problem):
    name = "pfsp"

    def __init__(
        self,
        inst: int = 14,
        lb: str = "lb1",
        ub: int = 1,
        p_times: np.ndarray | None = None,
        lb2_variant: str = "full",
    ):
        """``p_times`` overrides the Taillard instance (for reduced test
        instances); then ``ub`` must be 0 (no table optimum exists).
        ``lb2_variant`` selects the Johnson machine-pair subset
        (`bounds.LB2_VARIANTS`; the reference's `enum lb2_variant`,
        `Bound_johnson.chpl:6`).
        """
        if lb not in ALLOWED_LOWER_BOUNDS:
            raise ValueError("Error - Unsupported lower bound")
        if ub not in (0, 1):
            raise ValueError("Error: unsupported upper bound initialization")
        if lb2_variant not in B.LB2_VARIANTS:
            raise ValueError(
                f"Error - Unsupported lb2 variant: {lb2_variant!r} "
                f"(choose from {B.LB2_VARIANTS})"
            )
        if p_times is None:
            if not (1 <= inst <= 120):
                raise ValueError("Error: unsupported Taillard's instance")
            p_times = taillard.processing_times(inst)
            self.initial_ub = taillard.best_ub(inst) if ub == 1 else INF_BOUND
            self.inst = inst
        else:
            if ub != 0:
                raise ValueError("custom instances have no table optimum; use ub=0")
            self.initial_ub = INF_BOUND
            # Ad-hoc matrix: no named identity (a checkpoint meta carrying
            # the constructor-default inst would let two different ad-hoc
            # instances of the same shape impersonate each other).
            self.inst = None
        self.lb = lb
        self.ub = ub
        self.lb2_variant = lb2_variant
        self.jobs = int(p_times.shape[1])
        self.machines = int(p_times.shape[0])
        self.child_slots = self.jobs
        self.lb1_data = B.make_lb1(p_times)
        self.lb2_data = B.make_lb2(self.lb1_data, lb2_variant)

    def field_specs(self):
        # prmu holds job indices < jobs (int8 through 127 jobs, int16
        # through the ta111-class n=500); depth/limit1 are bounded by
        # jobs (limit1 >= -1), so int16 always fits.
        prmu_narrow = np.int8 if self.jobs <= 127 else np.int16
        return {
            "depth": ((), np.dtype(np.int32), np.dtype(np.int16)),
            "limit1": ((), np.dtype(np.int32), np.dtype(np.int16)),
            "prmu": ((self.jobs,), np.dtype(np.int32), np.dtype(prmu_narrow)),
        }

    def root(self) -> NodeBatch:
        fields = self.node_fields()
        return {
            "depth": np.zeros((1,), dtype=fields["depth"][1]),
            "limit1": np.full((1,), -1, dtype=fields["limit1"][1]),
            "prmu": np.arange(self.jobs, dtype=fields["prmu"][1])[None, :],
        }

    # -- host path ---------------------------------------------------------

    def _child_bound(self, child_prmu, child_limit1: int, best: int) -> int:
        if self.lb == "lb2":
            return B.lb2_bound(
                self.lb1_data, self.lb2_data, child_prmu, child_limit1, self.jobs, best
            )
        return B.lb1_bound(self.lb1_data, child_prmu, child_limit1, self.jobs)

    def decompose(self, node: dict, best: int) -> DecomposeResult:
        """One-node evaluate + branch (`pfsp_chpl.chpl:88-188`)."""
        if self.lb == "lb1_d":
            return self._decompose_lb1_d(node, best)
        depth = int(node["depth"])
        limit1 = int(node["limit1"])
        prmu = node["prmu"]
        jobs = self.jobs
        kept_prmu: list[np.ndarray] = []
        sol_inc = 0
        tree_inc = 0
        for i in range(limit1 + 1, jobs):
            child = prmu.copy()
            child[depth], child[i] = child[i], child[depth]
            lowerbound = self._child_bound(child, limit1 + 1, best)
            if depth + 1 == jobs:  # leaf
                sol_inc += 1
                if lowerbound < best:
                    best = lowerbound
            elif lowerbound < best:
                kept_prmu.append(child)
                tree_inc += 1
        return DecomposeResult(self._children(kept_prmu, depth, limit1), tree_inc, sol_inc, best)

    def _decompose_lb1_d(self, node: dict, best: int) -> DecomposeResult:
        """One `lb1_children_bounds` pass for all children
        (`pfsp_chpl.chpl:115-145`).
        """
        depth = int(node["depth"])
        limit1 = int(node["limit1"])
        prmu = node["prmu"]
        jobs = self.jobs
        lb_begin = B.lb1_children_bounds(self.lb1_data, prmu, limit1, jobs)
        kept_prmu: list[np.ndarray] = []
        sol_inc = 0
        tree_inc = 0
        for i in range(limit1 + 1, jobs):
            job = int(prmu[i])
            lowerbound = int(lb_begin[job])
            if depth + 1 == jobs:  # leaf
                sol_inc += 1
                if lowerbound < best:
                    best = lowerbound
            elif lowerbound < best:
                child = prmu.copy()
                child[depth], child[i] = child[i], child[depth]
                kept_prmu.append(child)
                tree_inc += 1
        return DecomposeResult(self._children(kept_prmu, depth, limit1), tree_inc, sol_inc, best)

    def _children(self, kept_prmu: list, depth: int, limit1: int) -> NodeBatch:
        k = len(kept_prmu)
        fields = self.node_fields()
        prmu_dt = fields["prmu"][1]
        return {
            "depth": np.full(k, depth + 1, dtype=fields["depth"][1]),
            "limit1": np.full(k, limit1 + 1, dtype=fields["limit1"][1]),
            "prmu": (
                np.stack(kept_prmu).astype(prmu_dt)
                if kept_prmu
                else np.zeros((0, self.jobs), dtype=prmu_dt)
            ),
        }

    # -- native host runtime -----------------------------------------------

    def _make_native(self, lib):
        from ... import native

        return native.NativePFSP(lib, self.lb1_data, self.lb2_data, self.lb)

    def native_sequential(self, best: int):
        nat = self._native()
        if nat is None:
            return None
        return nat.sequential(best)

    def native_warmup(self, batch: NodeBatch, best: int, target: int):
        nat = self._native()
        if nat is None:
            return None
        return nat.warmup(batch, best, target)

    def native_drain(self, batch: NodeBatch, best: int):
        nat = self._native()
        if nat is None:
            return None
        return nat.drain(batch, best)

    # -- device path -------------------------------------------------------

    def device_tables(self):
        """Per-instance device tables, built once and shared by all
        offloaders/workers/benchmarks (the chunk kernels themselves are
        module-level jits, so the compile cache is shared too)."""
        from ...ops import pfsp_device

        if not hasattr(self, "_device_tables"):
            self._device_tables = pfsp_device.PFSPDeviceTables(
                self.lb1_data, self.lb2_data
            )
        return self._device_tables

    def make_device_evaluator(self, device=None):
        from ...ops import pfsp_device

        return pfsp_device.make_evaluator(self.device_tables(), self.lb, device)

    def generate_children(
        self, parents: NodeBatch, count: int, results: np.ndarray, best: int
    ) -> DecomposeResult:
        """Vectorized prune/branch from device bounds
        (`pfsp_gpu_chpl.chpl:273-303`). Children are emitted in the
        reference's (parent, slot) ascending order. Within a chunk the
        incumbent used for pruning is the chunk-entry one; leaf improvements
        are folded with a min — identical to the reference's sequential
        in-chunk updates whenever ub=1 (the incumbent never improves), and a
        valid B&B relaxation otherwise (SURVEY.md §2.4.4 lazy UB).
        """
        nat = self._native()
        if nat is not None:
            children, tree_inc, sol_inc, best = nat.generate_children(
                parents, count, np.asarray(results), best
            )
            return DecomposeResult(children, tree_inc, sol_inc, best)
        jobs = self.jobs
        depth = parents["depth"][:count].astype(np.int64)
        limit1 = parents["limit1"][:count].astype(np.int64)
        prmu = parents["prmu"][:count]
        bnds = np.asarray(results[:count]).astype(np.int64)  # (count, jobs)
        j = np.arange(jobs)[None, :]
        open_slot = j >= (limit1[:, None] + 1)
        is_leaf_child = (depth[:, None] + 1 == jobs) & open_slot
        sol_inc = int(is_leaf_child.sum())
        leaf_bounds = bnds[is_leaf_child]
        if leaf_bounds.size:
            best = min(best, int(leaf_bounds.min()))
        keep = open_slot & ~is_leaf_child & (bnds < best)
        pi, kj = np.nonzero(keep)
        child_prmu = prmu[pi].copy()
        rows = np.arange(pi.size)
        di = depth[pi]
        tmp = child_prmu[rows, di].copy()
        child_prmu[rows, di] = child_prmu[rows, kj]
        child_prmu[rows, kj] = tmp
        fields = self.node_fields()
        children = {
            "depth": (depth[pi] + 1).astype(fields["depth"][1]),
            "limit1": (limit1[pi] + 1).astype(fields["limit1"][1]),
            "prmu": child_prmu.astype(fields["prmu"][1]),
        }
        return DecomposeResult(children, int(pi.size), sol_inc, best)
