"""PFSP lower bounds — numpy oracle implementations.

These are the *semantic anchors* for the framework: straightforward integer
re-implementations of the canonical C bound library
(`/root/reference/baselines/pfsp/lib/c_bound_simple.c`,
`/root/reference/baselines/pfsp/lib/c_bound_johnson.c`). The TPU kernels in
`tpu_tree_search.ops` are property-tested against these on random
permutations/prefixes (SURVEY.md §4c).

Where the reference's Chapel port diverges from the C library, we follow the
C semantics (SURVEY.md §7.3 "parity traps": the Chapel `fill_min_heads_tails`
min-heads accumulation bug at `Bound_simple.chpl:271` is NOT reproduced; cf.
correct C at `c_bound_simple.c:278-322`).

Conventions (match the C library):
  * ``p_times`` is ``(machines, jobs)`` int — ``p_times[machine, job]``.
  * ``prmu`` is a permutation of ``0..jobs-1``; jobs ``prmu[0..limit1]`` form
    the fixed prefix ("scheduled at the front"); jobs ``prmu[limit2..]`` the
    fixed suffix. Forward branching only, so ``limit2 == jobs`` everywhere in
    the search (`pfsp_chpl.chpl:23-26`).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


# ---------------------------------------------------------------------------
# lb1 — one-machine bound (c_bound_simple.c)
# ---------------------------------------------------------------------------


@dataclass
class LB1Data:
    """Per-instance tables for lb1 (`c_bound_simple.h:14-21`)."""

    p_times: np.ndarray  # (machines, jobs) int32
    min_heads: np.ndarray  # (machines,) int32 — min start times per machine
    min_tails: np.ndarray  # (machines,) int32 — min run-out times per machine

    @property
    def jobs(self) -> int:
        return self.p_times.shape[1]

    @property
    def machines(self) -> int:
        return self.p_times.shape[0]


def make_lb1(p_times: np.ndarray) -> LB1Data:
    """Build lb1 tables: `fill_min_heads_tails`, `c_bound_simple.c:277-322`.

    min_heads[k] = min over jobs of the earliest time machine k could start
    (head of the job on machines 0..k-1); 0 on machine 0. min_tails[k] = min
    over jobs of the run-out after machine k; 0 on the last machine.
    """
    p = np.asarray(p_times, dtype=np.int64)
    m, n = p.shape
    heads = np.cumsum(p, axis=0)  # heads[k, j] = sum of p[0..k, j]
    min_heads = np.empty(m, dtype=np.int64)
    min_heads[0] = 0
    if m > 1:
        # tmp[k-1] after the forward pass == cumulative head up to machine k-1
        min_heads[1:] = heads[:-1, :].min(axis=1)
    tails = np.cumsum(p[::-1, :], axis=0)[::-1, :]  # tails[k, j] = sum p[k.., j]
    min_tails = np.empty(m, dtype=np.int64)
    min_tails[m - 1] = 0
    if m > 1:
        min_tails[:-1] = tails[1:, :].min(axis=1)
    return LB1Data(
        p_times=np.asarray(p_times, dtype=np.int32),
        min_heads=min_heads.astype(np.int32),
        min_tails=min_tails.astype(np.int32),
    )


def add_forward(job: int, p: np.ndarray, front: np.ndarray) -> None:
    """Extend the head schedule by one job (`c_bound_simple.c:31-38`)."""
    m = p.shape[0]
    front[0] += p[0, job]
    for j in range(1, m):
        front[j] = max(front[j - 1], front[j]) + p[j, job]


def add_backward(job: int, p: np.ndarray, back: np.ndarray) -> None:
    """Extend the tail schedule by one job (`c_bound_simple.c:40-49`)."""
    m = p.shape[0]
    back[m - 1] += p[m - 1, job]
    for j in range(m - 2, -1, -1):
        back[j] = max(back[j], back[j + 1]) + p[j, job]


def schedule_front(d: LB1Data, prmu, limit1: int) -> np.ndarray:
    """Completion times of the fixed prefix per machine (`c_bound_simple.c:51-69`)."""
    if limit1 == -1:
        return d.min_heads.astype(np.int64)
    front = np.zeros(d.machines, dtype=np.int64)
    p = d.p_times
    for i in range(limit1 + 1):
        add_forward(int(prmu[i]), p, front)
    return front


def schedule_back(d: LB1Data, prmu, limit2: int) -> np.ndarray:
    """Tail times of the fixed suffix per machine (`c_bound_simple.c:71-90`)."""
    if limit2 == d.jobs:
        return d.min_tails.astype(np.int64)
    back = np.zeros(d.machines, dtype=np.int64)
    p = d.p_times
    for k in range(d.jobs - 1, limit2 - 1, -1):
        add_backward(int(prmu[k]), p, back)
    return back


def eval_solution(d: LB1Data, prmu) -> int:
    """Makespan of a complete permutation (`c_bound_simple.c:92-106`)."""
    tmp = np.zeros(d.machines, dtype=np.int64)
    for i in range(d.jobs):
        add_forward(int(prmu[i]), d.p_times, tmp)
    return int(tmp[d.machines - 1])


def sum_unscheduled(d: LB1Data, prmu, limit1: int, limit2: int) -> np.ndarray:
    """Total remaining work per machine (`c_bound_simple.c:108-124`)."""
    mid = np.asarray(prmu[limit1 + 1 : limit2], dtype=np.int64)
    if mid.size == 0:
        return np.zeros(d.machines, dtype=np.int64)
    return d.p_times[:, mid].astype(np.int64).sum(axis=1)


def machine_bound_from_parts(front, back, remain) -> int:
    """Chain the per-machine head+remain+tail bound (`c_bound_simple.c:126-141`)."""
    m = len(front)
    tmp0 = int(front[0]) + int(remain[0])
    lb = tmp0 + int(back[0])
    for i in range(1, m):
        tmp1 = max(tmp0, int(front[i]) + int(remain[i]))
        lb = max(lb, tmp1 + int(back[i]))
        tmp0 = tmp1
    return lb


def lb1_bound(d: LB1Data, prmu, limit1: int, limit2: int) -> int:
    """The full one-machine bound (`c_bound_simple.c:143-158`)."""
    front = schedule_front(d, prmu, limit1)
    back = schedule_back(d, prmu, limit2)
    remain = sum_unscheduled(d, prmu, limit1, limit2)
    return machine_bound_from_parts(front, back, remain)


def add_front_and_bound(d: LB1Data, job: int, front, back, remain) -> int:
    """O(m) bound after placing ``job`` at the prefix end (`c_bound_simple.c:213-244`)."""
    m = d.machines
    p = d.p_times
    lb = int(front[0]) + int(remain[0]) + int(back[0])
    tmp0 = int(front[0]) + int(p[0, job])
    for i in range(1, m):
        tmp1 = max(tmp0, int(front[i]))
        lb = max(lb, tmp1 + int(remain[i]) + int(back[i]))
        tmp0 = tmp1 + int(p[i, job])
    return lb


def lb1_children_bounds(d: LB1Data, prmu, limit1: int, limit2: int) -> np.ndarray:
    """Bounds for *all* children in one pass, indexed by job id
    (`c_bound_simple.c:160-211`). Entries for already-fixed jobs are 0.
    """
    front = schedule_front(d, prmu, limit1)
    back = schedule_back(d, prmu, limit2)
    remain = sum_unscheduled(d, prmu, limit1, limit2)
    lb_begin = np.zeros(d.jobs, dtype=np.int64)
    for i in range(limit1 + 1, limit2):
        job = int(prmu[i])
        lb_begin[job] = add_front_and_bound(d, job, front, back, remain)
    return lb_begin


# ---------------------------------------------------------------------------
# lb2 — two-machine / Johnson bound (c_bound_johnson.c)
# ---------------------------------------------------------------------------


#: The reference's ``enum lb2_variant`` pair subsets (`Bound_johnson.chpl:6`,
#: `fill_machine_pairs` `:50-88`): ``full`` takes every (i, j) with i < j
#: (P = m(m-1)/2, the default of every reference tier); ``nabeshima`` the
#: adjacent pairs (i, i+1) [Nabeshima'67]; ``lageweg`` every machine paired
#: with the last, (i, m-1) [Lageweg'78] — both P = m-1. (LB2_LEARN reuses
#: the full pair set with a learned visit order; visit order only matters
#: for the early exit, which the TPU formulation drops, so it is not a
#: distinct table shape here.)
LB2_VARIANTS = ("full", "nabeshima", "lageweg")


def machine_pairs(m: int, variant: str = "full") -> list[tuple[int, int]]:
    """The `fill_machine_pairs` pair subsets, one list per variant."""
    if variant == "full":
        return [(i, j) for i in range(m - 1) for j in range(i + 1, m)]
    if variant == "nabeshima":
        return [(i, i + 1) for i in range(m - 1)]
    if variant == "lageweg":
        return [(i, m - 1) for i in range(m - 1)]
    raise ValueError(
        f"lb2_variant must be one of {LB2_VARIANTS}, got {variant!r}"
    )


@dataclass
class LB2Data:
    """Per-instance tables for lb2 (`c_bound_johnson.h:16-27`)."""

    pairs: np.ndarray  # (P, 2) int32 machine pairs (m1 < m2)
    lags: np.ndarray  # (P, jobs) int32 — q_iuv term [Lageweg'78]
    johnson_schedules: np.ndarray  # (P, jobs) int32 — job ids in Johnson order

    @property
    def nb_machine_pairs(self) -> int:
        return self.pairs.shape[0]


def make_lb2(d: LB1Data, variant: str = "full") -> LB2Data:
    """Build lb2 tables: machine pairs (`c_bound_johnson.c:48-91`, subset per
    ``variant`` — see `LB2_VARIANTS`), lags (`:94-109`), and per-pair
    Johnson-optimal schedules (`:147-178`).

    The Johnson sort uses a *stable* argsort on key (partition, ptm1 | -ptm2):
    partition 0 (ptm1 < ptm2) first by ascending ptm1, then partition 1 by
    descending ptm2 (`johnson_comp`, `c_bound_johnson.c:120-141`). The C
    qsort's tie order is unspecified; any fixed tie-break yields a valid
    Johnson schedule, and all tiers of this framework share this one.
    """
    p = d.p_times.astype(np.int64)
    m, n = p.shape
    pair_list = machine_pairs(m, variant)
    pairs = np.array(pair_list, dtype=np.int32).reshape(-1, 2)
    P = pairs.shape[0]

    heads = np.cumsum(p, axis=0)
    lags = np.empty((P, n), dtype=np.int64)
    for k, (m1, m2) in enumerate(pair_list):
        # sum of p[m1+1 .. m2-1, j]
        lags[k] = heads[m2 - 1] - heads[m1]

    schedules = np.empty((P, n), dtype=np.int32)
    for k, (m1, m2) in enumerate(pair_list):
        ptm1 = p[m1] + lags[k]
        ptm2 = p[m2] + lags[k]
        partition = (ptm1 >= ptm2).astype(np.int64)  # 0: ptm1 < ptm2
        key = np.where(partition == 0, ptm1, -ptm2)
        order = np.lexsort((key, partition))  # stable: partition major, key minor
        schedules[k] = order.astype(np.int32)

    return LB2Data(pairs=pairs, lags=lags.astype(np.int32), johnson_schedules=schedules)


def set_flags(prmu, limit1: int, limit2: int, n: int) -> np.ndarray:
    """1 for jobs fixed in prefix/suffix, 0 for free (`c_bound_johnson.c:180-188`)."""
    flags = np.zeros(n, dtype=np.int64)
    for j in range(limit1 + 1):
        flags[int(prmu[j])] = 1
    for j in range(limit2, n):
        flags[int(prmu[j])] = 1
    return flags


def _compute_cmax_johnson(
    p: np.ndarray, d2: LB2Data, flags, tmp0: int, tmp1: int, ma0: int, ma1: int, ind: int
) -> tuple[int, int]:
    """Johnson two-machine cmax of the free jobs with lags
    (`c_bound_johnson.c:190-209`). Returns (tmp0, tmp1).
    """
    n = p.shape[1]
    for j in range(n):
        job = int(d2.johnson_schedules[ind, j])
        if flags[job] == 0:
            lag = int(d2.lags[ind, job])
            tmp0 += int(p[ma0, job])
            tmp1 = max(tmp1, tmp0 + lag)
            tmp1 += int(p[ma1, job])
    return tmp0, tmp1


def lb_makespan(
    p: np.ndarray, d2: LB2Data, flags, front, back, min_cmax: int
) -> int:
    """Max over machine pairs, with early exit once the bound already prunes
    (`c_bound_johnson.c:211-237`). Pair visit order is index order
    (machine_pair_order is identity for LB2_FULL, `c_bound_johnson.c:61-69`).
    """
    lb = 0
    for i in range(d2.nb_machine_pairs):
        ma0 = int(d2.pairs[i, 0])
        ma1 = int(d2.pairs[i, 1])
        tmp0 = int(front[ma0])
        tmp1 = int(front[ma1])
        tmp0, tmp1 = _compute_cmax_johnson(p, d2, flags, tmp0, tmp1, ma0, ma1, i)
        tmp1 = max(tmp1 + int(back[ma1]), tmp0 + int(back[ma0]))
        lb = max(lb, tmp1)
        if lb > min_cmax:
            break
    return lb


def lb2_bound(
    d1: LB1Data, d2: LB2Data, prmu, limit1: int, limit2: int, best_cmax: int
) -> int:
    """The full two-machine bound (`c_bound_johnson.c:239-254`)."""
    front = schedule_front(d1, prmu, limit1)
    back = schedule_back(d1, prmu, limit2)
    flags = set_flags(prmu, limit1, limit2, d1.jobs)
    return lb_makespan(d1.p_times, d2, flags, front, back, best_cmax)
