"""PFSP problem family: Taillard instances, numpy oracle bounds, plugin."""

from . import bounds, taillard
from .problem import PFSPProblem

__all__ = ["bounds", "taillard", "PFSPProblem"]
