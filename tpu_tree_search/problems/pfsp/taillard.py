"""Taillard PFSP benchmark instances, generated deterministically.

Re-implementation of the instance generator used by the reference
(`/root/reference/baselines/pfsp/lib/c_taillard.c:5-112`,
`/root/reference/lib/pfsp/Taillard.chpl:3-98`): a Lehmer LCG seeded from the
published per-instance seed table yields integer processing times in [1, 99].
The LCG's uniform step divides in *single precision* (C: ``(float)seed /
(float)m``, `c_taillard.c:84`), which we replicate bit-exactly with
``np.float32`` so the generated instances match the reference byte for byte.

Processing-time layout is row-major by machine: ``ptm[machine, job]``
(`c_taillard.c:99-103`).
"""

from __future__ import annotations

import numpy as np

# Per-instance LCG seeds (ta001..ta120), `c_taillard.c:5-29` / `Taillard.chpl:3-27`.
TIME_SEEDS = (
    873654221, 379008056, 1866992158, 216771124, 495070989,
    402959317, 1369363414, 2021925980, 573109518, 88325120,
    587595453, 1401007982, 873136276, 268827376, 1634173168,
    691823909, 73807235, 1273398721, 2065119309, 1672900551,
    479340445, 268827376, 1958948863, 918272953, 555010963,
    2010851491, 1519833303, 1748670931, 1923497586, 1829909967,
    1328042058, 200382020, 496319842, 1203030903, 1730708564,
    450926852, 1303135678, 1273398721, 587288402, 248421594,
    1958948863, 575633267, 655816003, 1977864101, 93805469,
    1803345551, 49612559, 1899802599, 2013025619, 578962478,
    1539989115, 691823909, 655816003, 1315102446, 1949668355,
    1923497586, 1805594913, 1861070898, 715643788, 464843328,
    896678084, 1179439976, 1122278347, 416756875, 267829958,
    1835213917, 1328833962, 1418570761, 161033112, 304212574,
    1539989115, 655816003, 960914243, 1915696806, 2013025619,
    1168140026, 1923497586, 167698528, 1528387973, 993794175,
    450926852, 1462772409, 1021685265, 83696007, 508154254,
    1861070898, 26482542, 444956424, 2115448041, 118254244,
    471503978, 1215892992, 135346136, 1602504050, 160037322,
    551454346, 519485142, 383947510, 1968171878, 540872513,
    2013025619, 475051709, 914834335, 810642687, 1019331795,
    2056065863, 1342855162, 1325809384, 1988803007, 765656702,
    1368624604, 450181436, 1927888393, 1759567256, 606425239,
    19268348, 1298201670, 2041736264, 379756761, 28837162,
)

# Known optimal makespans (initial UB when ub=1), `c_taillard.c:31-43`.
OPTIMAL_MAKESPANS = (
    1278, 1359, 1081, 1293, 1235, 1195, 1234, 1206, 1230, 1108,            # 20x5
    1582, 1659, 1496, 1377, 1419, 1397, 1484, 1538, 1593, 1591,            # 20x10
    2297, 2099, 2326, 2223, 2291, 2226, 2273, 2200, 2237, 2178,            # 20x20
    2724, 2834, 2621, 2751, 2863, 2829, 2725, 2683, 2552, 2782,            # 50x5
    2991, 2867, 2839, 3063, 2976, 3006, 3093, 3037, 2897, 3065,            # 50x10
    3846, 3699, 3640, 3719, 3610, 3679, 3704, 3691, 3741, 3755,            # 50x20
    5493, 5268, 5175, 5014, 5250, 5135, 5246, 5094, 5448, 5322,            # 100x5
    5770, 5349, 5676, 5781, 5467, 5303, 5595, 5617, 5871, 5845,            # 100x10
    6173, 6183, 6252, 6254, 6285, 6331, 6223, 6372, 6247, 6404,            # 100x20
    10862, 10480, 10922, 10889, 10524, 10329, 10854, 10730, 10438, 10675,  # 200x10
    11158, 11160, 11281, 11275, 11259, 11176, 11337, 11301, 11146, 11284,  # 200x20
    26040, 26500, 26371, 26456, 26334, 26469, 26389, 26560, 26005, 26457,  # 500x20
)


def nb_jobs(inst: int) -> int:
    """Job count for instance id (1..120), `c_taillard.c:45-52`."""
    if inst > 110:
        return 500
    if inst > 90:
        return 200
    if inst > 60:
        return 100
    if inst > 30:
        return 50
    return 20


def nb_machines(inst: int) -> int:
    """Machine count for instance id (1..120), `c_taillard.c:54-68`."""
    if inst > 110:
        return 20
    if inst > 100:
        return 20
    if inst > 90:
        return 10
    if inst > 80:
        return 20
    if inst > 70:
        return 10
    if inst > 60:
        return 5
    if inst > 50:
        return 20
    if inst > 40:
        return 10
    if inst > 30:
        return 5
    if inst > 20:
        return 20
    if inst > 10:
        return 10
    return 5


def best_ub(inst: int) -> int:
    """Known optimal makespan (1-based instance id), `c_taillard.c:70-73`."""
    return OPTIMAL_MAKESPANS[inst - 1]


def _unif_step(seed: int) -> tuple[int, int]:
    """One LCG draw in [1, 99]; returns (new_seed, value). `c_taillard.c:75-87`.

    The 0..1 projection divides in float32 (then widens to float64 for the
    range scaling) — this ordering is load-bearing for bit parity.
    """
    m, a, b, c = 2147483647, 16807, 127773, 2836
    k = seed // b
    seed = a * (seed % b) - k * c
    if seed < 0:
        seed += m
    value_0_1 = np.float32(seed) / np.float32(m)
    return seed, 1 + int(float(value_0_1) * 99.0)


def processing_times(inst: int) -> np.ndarray:
    """Processing-time matrix ``(machines, jobs)`` int32 for ta<inst>.

    Row-major by machine, filled machine-major (`c_taillard.c:89-104`).
    """
    n = nb_jobs(inst)
    m = nb_machines(inst)
    seed = TIME_SEEDS[inst - 1]
    ptm = np.empty((m, n), dtype=np.int32)
    for i in range(m):
        for j in range(n):
            seed, v = _unif_step(seed)
            ptm[i, j] = v
    return ptm


def reduced_instance(inst: int, jobs: int, machines: int | None = None) -> np.ndarray:
    """A small synthetic instance: the top-left ``(machines, jobs)`` corner of
    ta<inst>'s processing-time matrix. Used by tests to keep B&B trees tiny
    while exercising the full bound machinery (SURVEY.md §4: 'reduced-job
    variants'). Not a reference instance — golden counts are self-anchored.
    """
    ptm = processing_times(inst)
    m = machines if machines is not None else ptm.shape[0]
    return np.ascontiguousarray(ptm[:m, :jobs])
