"""Job specs + the durable job registry.

A job spec is the JSON body of ``POST /submit`` — the serve-side mirror of
the CLI's run arguments (``cli.build_parser``), restricted to the tiers a
resident daemon can preempt (device/mesh: both ride
``RunController.yield_fn``). ``validate_spec`` normalizes and defaults it
without touching jax, so admission control runs entirely in the HTTP
thread; ``build_problem`` is the jax-side constructor the scheduler calls.

Job records are durable: every state transition rewrites the job's JSON
file atomically under ``<state_dir>/jobs/``, and a restarted daemon
reloads them — finished jobs keep serving their results, interrupted ones
come back as ``requeued`` (their checkpoint makes the resume exact).
"""

from __future__ import annotations

import json
import os
import threading
import time

#: Job lifecycle. queued -> running -> done | failed | cancelled, with two
#: detours: running -> queued (preempted, checkpoint cut) and
#: queued/running -> requeued (daemon drained; a restart re-admits).
STATES = ("queued", "running", "done", "failed", "cancelled", "requeued")

_TIERS = ("device", "mesh")
_LBS = ("lb1", "lb1_d", "lb2")
_LB2_VARIANTS = ("full", "nabeshima", "lageweg")
_COMPACTS = ("auto", "scatter", "sort", "search", "dense")


def _as_int(spec: dict, key: str, lo: int, hi: int, default=None):
    v = spec.get(key, default)
    if v is None:
        return None
    if isinstance(v, bool) or not isinstance(v, int):
        raise ValueError(f"spec.{key} must be an integer")
    if not lo <= v <= hi:
        raise ValueError(f"spec.{key} must be in [{lo}, {hi}], got {v}")
    return v


def validate_spec(spec) -> dict:
    """Normalize a submitted spec: fill defaults, reject junk. Returns a
    fresh dict (the admission record); raises ``ValueError`` on invalid
    input. Pure host code — no jax import, safe in the HTTP thread."""
    if not isinstance(spec, dict):
        raise ValueError("spec must be a JSON object")
    known = {
        "problem", "tier", "N", "g", "inst", "lb", "ub", "lb2_variant",
        "lb2_pairblock", "m", "M", "K", "D", "mp", "compact", "max_steps",
        "label",
    }
    unknown = sorted(set(spec) - known)
    if unknown:
        raise ValueError(f"unknown spec field(s): {', '.join(unknown)}")
    problem = spec.get("problem")
    if problem not in ("nqueens", "pfsp"):
        raise ValueError("spec.problem must be 'nqueens' or 'pfsp'")
    tier = spec.get("tier", "device")
    if tier not in _TIERS:
        raise ValueError(
            f"spec.tier must be one of {_TIERS} (the preemptible resident "
            "tiers); use the CLI directly for seq/multi/dist runs"
        )
    out = {"problem": problem, "tier": tier}
    if problem == "nqueens":
        out["N"] = _as_int(spec, "N", 4, 32, default=14)
        out["g"] = _as_int(spec, "g", 1, 64, default=1)
    else:
        out["inst"] = _as_int(spec, "inst", 1, 120, default=14)
        out["lb"] = spec.get("lb", "lb1")
        if out["lb"] not in _LBS:
            raise ValueError(f"spec.lb must be one of {_LBS}")
        out["ub"] = _as_int(spec, "ub", 0, 1, default=1)
        out["lb2_variant"] = spec.get("lb2_variant", "full")
        if out["lb2_variant"] not in _LB2_VARIANTS:
            raise ValueError(f"spec.lb2_variant must be one of {_LB2_VARIANTS}")
        if out["lb2_variant"] != "full" and out["lb"] != "lb2":
            raise ValueError("spec.lb2_variant requires lb='lb2'")
        pb = spec.get("lb2_pairblock")
        if pb is not None:
            if out["lb"] != "lb2":
                raise ValueError("spec.lb2_pairblock requires lb='lb2'")
            if pb != "auto" and not (
                isinstance(pb, int) and not isinstance(pb, bool) and pb >= 1
            ):
                raise ValueError("spec.lb2_pairblock must be 'auto' or an "
                                 "integer >= 1")
            out["lb2_pairblock"] = pb
    out["m"] = _as_int(spec, "m", 1, 1 << 20, default=25)
    M = _as_int(spec, "M", 1, 1 << 24)
    if M is None:
        # The CLI's measured default (cli.resolve_chunk_size) needs the
        # backend; serve resolves it once at admission so the shape class
        # is fully determined by the normalized spec.
        from ..cli import resolve_chunk_size

        M = resolve_chunk_size(None, problem, tier, "resident")
    out["M"] = M
    K = spec.get("K")
    if K is not None:
        if K != "auto" and not (
            isinstance(K, int) and not isinstance(K, bool) and K >= 1
        ):
            raise ValueError("spec.K must be 'auto' or an integer >= 1")
        out["K"] = K
    if tier == "mesh":
        D = _as_int(spec, "D", 1, 4096)
        if D is not None:
            out["D"] = D
        mp = _as_int(spec, "mp", 1, 4096, default=1)
        if mp != 1:
            if problem != "pfsp" or out.get("lb") != "lb2":
                raise ValueError("spec.mp shards the lb2 Johnson pair loop "
                                 "(pfsp lb='lb2' only)")
            out["mp"] = mp
    elif spec.get("D") is not None or spec.get("mp", 1) != 1:
        raise ValueError("spec.D/spec.mp only apply to tier='mesh'")
    compact = spec.get("compact")
    if compact is not None:
        if compact not in _COMPACTS:
            raise ValueError(f"spec.compact must be one of {_COMPACTS}")
        out["compact"] = compact
    ms = _as_int(spec, "max_steps", 1, 1 << 31)
    if ms is not None:
        out["max_steps"] = ms
    label = spec.get("label")
    if label is not None:
        if not isinstance(label, str) or len(label) > 200:
            raise ValueError("spec.label must be a string (<= 200 chars)")
        out["label"] = label
    return out


def build_problem(spec: dict):
    """Construct the problem instance for a validated spec (jax side —
    scheduler/pool only)."""
    if spec["problem"] == "nqueens":
        from ..problems import NQueensProblem

        return NQueensProblem(N=spec["N"], g=spec["g"])
    from ..problems import PFSPProblem

    return PFSPProblem(inst=spec["inst"], lb=spec["lb"], ub=spec["ub"],
                       lb2_variant=spec.get("lb2_variant", "full"))


def job_pins(spec: dict) -> dict:
    """The process-env knobs a job's trace-time routing reads
    (``routing_cache_token``): applied under the scheduler's ``EnvLease``
    for the duration of the job's slice. Only per-job knobs live here —
    server-wide routing (TTS_PALLAS, TTS_GUARD, ...) is fixed at daemon
    start and part of the pool's server token instead."""
    pins = {}
    if spec.get("compact") is not None:
        pins["TTS_COMPACT"] = spec["compact"]
    if spec.get("lb2_pairblock") is not None:
        pins["TTS_LB2_PAIRBLOCK"] = str(spec["lb2_pairblock"])
    return pins


def result_record(res) -> dict:
    """The serve-side result payload for a finished SearchResult — the
    counters are full-run totals even across preempted slices (the
    checkpoint seeds them), which is what makes the daemon's answer
    bit-comparable to a standalone ``tts run``."""
    rec = {
        "explored_tree": res.explored_tree,
        "explored_sol": res.explored_sol,
        "best": res.best,
        "elapsed_s": round(res.elapsed, 6),
        "complete": bool(res.complete),
    }
    if res.compact:
        rec["compact"] = res.compact
        if res.compact_auto:
            rec["compact_auto"] = True
    if res.pipeline_depth:
        rec["pipeline_depth"] = res.pipeline_depth
    if res.k_resolved is not None:
        rec["k"] = res.k_resolved
        if res.k_auto:
            rec["k_auto"] = True
    if res.obs:
        rec["obs"] = res.obs
    if res.quality and res.quality.get("points"):
        rec["quality"] = res.quality
    return rec


class Job:
    """One admitted job: the durable record plus runtime-only handles.

    Fields are mutated ONLY through ``JobRegistry`` methods (which hold
    the registry lock and persist the record); the single exception is
    ``cancel_requested``, an advisory flag the HTTP thread sets and the
    scheduler's ``yield_fn`` reads — one-writer/one-reader, staleness of
    one dispatch boundary is the designed cancellation latency."""

    def __init__(self, jid: str, spec: dict, class_key: str, pins: dict):
        self.id = jid
        self.spec = spec
        self.class_key = class_key
        self.pins = pins
        self.state = "queued"
        self.submitted = time.time()
        self.started = None
        self.finished = None
        self.slices = 0
        self.preemptions = 0
        # Cumulative RunController dispatch steps across every slice: the
        # consumed share of the spec's max_steps budget — each slice runs
        # with the remainder, so a preempted/drained/restarted max_steps
        # job finishes only when the budget is actually exhausted.
        self.steps = 0
        self.checkpoint = None  # path; set on first preemption cut
        self.result = None
        self.error = None
        self.warm_hit = False  # admitted into an already-warm class
        self.new_programs = 0  # program-cache entries this job compiled
        self.new_step_compiles = 0  # jit step-cache entries this job compiled
        # Runtime-only (not persisted):
        self.cancel_requested = False
        self.recorder = None  # per-job FlightRecorder, bound during slices
        # Per-job QualityRecorder (obs/quality.py), bound during slices;
        # spans preemptions so the trajectory covers the whole job. The
        # stream handler polls .points() for SSE `incumbent` frames.
        self.quality = None

    def record(self) -> dict:
        """The persisted/public JSON view."""
        return {
            "id": self.id,
            "spec": self.spec,
            "class": self.class_key,
            "pins": self.pins,
            "state": self.state,
            "submitted": self.submitted,
            "started": self.started,
            "finished": self.finished,
            "slices": self.slices,
            "preemptions": self.preemptions,
            "steps": self.steps,
            "checkpoint": self.checkpoint,
            "result": self.result,
            "error": self.error,
            "warm_hit": self.warm_hit,
            "new_programs": self.new_programs,
            "new_step_compiles": self.new_step_compiles,
        }

    @classmethod
    def from_record(cls, rec: dict) -> "Job":
        job = cls(rec["id"], rec["spec"], rec["class"], rec.get("pins", {}))
        for k in ("state", "submitted", "started", "finished", "slices",
                  "preemptions", "steps", "checkpoint", "result", "error",
                  "warm_hit", "new_programs", "new_step_compiles"):
            if k in rec:
                setattr(job, k, rec[k])
        return job


class JobRegistry:
    """Durable id -> Job map. Every mutation goes through a method that
    holds the lock and rewrites the job's file atomically (tmp + rename,
    the checkpoint module's convention) — a crashed daemon loses at most
    the transition in flight, never a whole record.

    Lock order (audited by analysis/lockorder.py): ``_io_lock`` may
    acquire ``_lock`` (``_persist`` snapshots the record inside its write
    critical section), never the reverse — every mutator releases
    ``_lock`` before calling ``_persist``."""

    def __init__(self, state_dir: str):
        self.state_dir = state_dir
        self.jobs_dir = os.path.join(state_dir, "jobs")
        os.makedirs(self.jobs_dir, exist_ok=True)
        self._lock = threading.Lock()
        # Serializes _persist's snapshot+write+rename: concurrent
        # transitions of one job (HTTP cancel vs worker) must neither
        # interleave bytes in a shared tmp file nor let an older snapshot's
        # rename land after a newer one.
        self._io_lock = threading.Lock()
        self._jobs = {}  # guarded-by: _lock
        self._seq = 0  # guarded-by: _lock

    def load(self) -> int:
        """Reload persisted records (daemon restart). Jobs that were
        queued/running when the previous daemon died come back as
        ``requeued`` — their checkpoint (if any) makes re-admission exact.
        Returns the number of records loaded."""
        n = 0
        for name in sorted(os.listdir(self.jobs_dir)):
            if not name.endswith(".json"):
                continue
            path = os.path.join(self.jobs_dir, name)
            try:
                with open(path) as f:
                    rec = json.load(f)
                job = Job.from_record(rec)
            except (OSError, ValueError, KeyError):
                continue  # truncated/alien file: skip, don't crash startup
            if job.state in ("queued", "running"):
                job.state = "requeued"
            with self._lock:
                self._jobs[job.id] = job
                # Keep new ids monotonic past every loaded one.
                try:
                    self._seq = max(self._seq, int(job.id.split("-")[-1]))
                except ValueError:
                    pass
            self._persist(job)
            n += 1
        return n

    def create(self, spec: dict, class_key: str, pins: dict,
               warm_hit: bool = False) -> Job:
        with self._lock:
            self._seq += 1
            jid = f"job-{self._seq:06d}"
            job = Job(jid, spec, class_key, pins)
            job.warm_hit = warm_hit
            self._jobs[jid] = job
        self._persist(job)
        return job

    def get(self, jid: str):
        with self._lock:
            return self._jobs.get(jid)

    def all(self) -> list:
        with self._lock:
            return sorted(self._jobs.values(), key=lambda j: j.id)

    def update(self, job: Job, **fields) -> None:
        """Apply field updates under the lock, then persist."""
        with self._lock:
            for k, v in fields.items():
                setattr(job, k, v)
        self._persist(job)

    def transition(self, job: Job, state: str, **fields) -> None:
        assert state in STATES, state
        self._stamp(job, state, fields)
        self.update(job, state=state, **fields)

    def transition_if(self, job: Job, from_states, state: str,
                      **fields) -> bool:
        """Compare-and-swap transition: applies (and persists) only while
        the job is still in one of ``from_states``. This is what keeps a
        racing cancel and a worker's queue pop coherent — whichever CAS
        wins, the loser no-ops instead of resurrecting a terminal state."""
        assert state in STATES, state
        self._stamp(job, state, fields)
        with self._lock:
            if job.state not in from_states:
                return False
            job.state = state
            for k, v in fields.items():
                setattr(job, k, v)
        self._persist(job)
        return True

    @staticmethod
    def _stamp(job: Job, state: str, fields: dict) -> None:
        now = time.time()
        if state == "running" and job.started is None:
            fields.setdefault("started", now)
        if state in ("done", "failed", "cancelled"):
            fields.setdefault("finished", now)

    def _persist(self, job: Job) -> None:
        path = os.path.join(self.jobs_dir, f"{job.id}.json")
        # Thread-unique tmp name AND one writer at a time: snapshotting
        # under the registry lock inside the io critical section means the
        # last rename to land is always the newest record — a restart never
        # loads a torn or stale-ordered file.
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        with self._io_lock:
            with self._lock:
                rec = job.record()
            with open(tmp, "w") as f:
                json.dump(rec, f)
            os.replace(tmp, path)
