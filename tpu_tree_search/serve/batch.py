"""Batch executor: instance-axis batched execution for the serve daemon.

One `BatchExecutor` exists per (shape class, pins) pair, owned by the
scheduler and run on whatever worker thread pops a batchable job.  It
drives a B-slot `engine/batched.py` program: every queued same-class job
becomes a slot, one K-cycle dispatch advances all live slots, and the
per-job slice semantics of `Scheduler._run_slice` are reproduced at the
slot level —

  * admission (fresh warm-up, or checkpoint restore into the slot) and
    retirement (residual drain on finish, snapshot-to-``.ckpt.npz`` on
    any cut) happen only at dispatch boundaries;
  * each slot keeps its own quantum clock (started at admission, the
    lease is already held), cumulative ``max_steps`` budget, cancel
    flag, flight recorder, quality recorder and event job-context, so a
    tenant observes exactly the artifacts a solo run would produce;
  * a quantum/cancel/drain cut removes ONE slot — the batch keeps
    running for the others — and free slots are refilled from the
    front-contiguous same-class run of the queue (a different-class
    waiter progressively empties the batch instead of starving).

Bit-identity with solo execution holds per slot because the batched
program masks frozen slots (engine/batched.py): a slot executes exactly
the cycle sequence its solo program would.  Two deliberate divergences:
a capacity-stalled slot is requeued with a solo-only flag (the solo
engine's host-offload fallback needs a growable pool), and a resumed
job whose saved frontier no longer fits a fixed slot falls back to solo
the same way.

Threading: the executor runs entirely on one worker thread and takes NO
locks of its own — `occupied` is a plain int published for metrics, and
all queue/registry access goes through the scheduler's existing methods.
The executor object itself persists across batch sessions so the
steady-state guard stays armed once warm.
"""

from __future__ import annotations

import os
import time

from ..engine import checkpoint as ckpt_mod
from ..engine.results import SearchResult
from ..obs import counters as obs_counters
from ..obs import events as ev
from ..obs import flightrec
from ..obs import quality as obs_quality
from ..pool import SoAPool
from ..problems.base import INF_BOUND, index_batch
from . import pool as pool_mod
from .jobs import result_record


class _Slot:
    """Host-side bookkeeping for one occupied batch slot."""

    __slots__ = ("job", "budget", "tree", "sol", "slice_steps", "n_disp",
                 "ctr", "prev_best", "t_start", "t0")

    def __init__(self, job):
        self.job = job
        self.budget = job.spec.get("max_steps")
        self.tree = 0
        self.sol = 0
        self.slice_steps = 0  # counted dispatches this batch session
        self.n_disp = 0  # dispatch seq (heartbeat/quality x-axis)
        self.ctr = None  # harvested device-counter totals
        self.prev_best = INF_BOUND
        self.t_start = time.monotonic()  # run_seconds clock
        self.t0 = time.monotonic()  # quantum clock


class BatchExecutor:
    """B-slot batched runner for one (class_key, pins) shape class."""

    def __init__(self, scheduler, class_key: str, pins: dict, B: int):
        self.sched = scheduler
        self.class_key = class_key
        self.pins = dict(pins)
        self.B = int(B)
        self.occupied = 0  # published for batch_stats; GIL-atomic int
        self._guards = {}  # id(prog) -> SteadyStateGuard (persists warm)

    # -- metrics shorthands -------------------------------------------

    def _inc(self, name, labels=None, v=1):
        self.sched._inc(name, labels, v)

    def _observe(self, name, value, labels=None):
        self.sched._observe(name, value, labels)

    # -- session ------------------------------------------------------

    def run(self, job0, wid: int) -> None:
        """Run one batch session starting from `job0` (already popped off
        the queue by the worker). Returns when every slot has retired."""
        sched = self.sched
        if job0.cancel_requested:
            sched.registry.transition_if(job0, ("queued", "requeued"),
                                         "cancelled")
            return
        entry = sched.pool.admit(job0.spec)
        problem = entry.problem
        self._mark = pool_mod.compile_stats(problem)
        spec = job0.spec

        from ..engine.pipeline import resolve_k
        from ..engine.resident import resolve_capacity

        _auto, k_value = resolve_k(spec.get("K") or 4096, default_max=4096)
        sched.lease.acquire(self.pins)
        try:
            self._session(job0, entry, problem, spec, k_value,
                          resolve_capacity)
        finally:
            self.occupied = 0
            sched.lease.release()

    def _fail_slots(self, slots, e) -> None:
        """An unexpected executor error must not leak spliced jobs in
        'running' — the worker's own wrap only knows the popped job."""
        for sl in slots:
            if sl is not None:
                self.sched.registry.transition_if(
                    sl.job, ("running",), "failed",
                    error=f"{type(e).__name__}: {e}")

    def _session(self, job0, entry, problem, spec, k_value,
                 resolve_capacity) -> None:
        import jax

        from ..analysis.guard import SteadyStateGuard, guard_enabled
        from ..engine.batched import make_batched_program

        sched = self.sched
        B = self.B
        capacity, M = resolve_capacity(problem, spec["M"], None)
        prog = make_batched_program(problem, B, spec["m"], M, k_value,
                                    capacity, jax.devices()[0])
        guard = self._guards.get(id(prog))
        if guard is None:
            guard = self._guards[id(prog)] = SteadyStateGuard(
                prog._step, f"batched[{self.class_key}]",
                enabled=guard_enabled())
        slots: list[_Slot | None] = [None] * B
        states = [prog.empty_slot() for _ in range(B)]
        ctx = dict(entry=entry, problem=problem, prog=prog, states=states,
                   slots=slots, capacity=capacity, M=M, guard=guard)

        # job0 may fall back (cancel race / solo-only resume) — the
        # session still picks up any already-queued peers below.
        self._admit(0, job0, ctx)
        first_job = slots[0].job if slots[0] is not None else None
        try:
            self._drive(ctx, first_job)
        except Exception as e:  # noqa: BLE001 — see _fail_slots
            self._fail_slots(slots, e)
            raise

    def _drive(self, ctx, first_job) -> None:
        sched = self.sched
        B = self.B
        prog, slots, states = ctx["prog"], ctx["slots"], ctx["states"]
        problem, guard = ctx["problem"], ctx["guard"]
        first = True
        while True:
            if not sched._stop_requested():
                free = [i for i in range(B) if slots[i] is None]
                if free:
                    for job in sched.take_same_class_front(
                            self.class_key, self.pins, len(free)):
                        i = free.pop(0)
                        if not self._admit(i, job, ctx):
                            free.insert(0, i)
                        elif first_job is None:
                            first_job = job
            occupied = [i for i in range(B) if slots[i] is not None]
            self.occupied = len(occupied)
            if not occupied:
                return
            self._observe("tts_serve_batch_efficiency",
                          len(occupied) / B, {"cls": self.class_key})
            t_enq = ev.now_us()
            with guard.step():
                out = prog.step(states)
            carry = prog.carry(out)
            for i in range(B):
                states[i] = carry[i]
            if first:
                # First dispatch compiles the batched program (cold
                # pool): that cost belongs to the job that triggered the
                # session, mirroring the solo path's per-slice delta.
                first = False
                if first_job is not None:
                    self._credit_compiles(first_job, problem)
            for i in occupied:
                self._boundary(i, ctx, out, t_enq)

    # -- admission ----------------------------------------------------

    def _admit(self, i: int, job, ctx) -> bool:
        """Splice `job` into slot `i`. Returns False when a racing cancel
        won or the job must run solo (saved frontier exceeds the fixed
        slot capacity); the slot stays free either way."""
        sched = self.sched
        problem, prog = ctx["problem"], ctx["prog"]
        if job.cancel_requested:
            sched.registry.transition_if(job, ("queued", "requeued"),
                                         "cancelled")
            return False
        saved = None
        if job.checkpoint:
            try:
                saved = ckpt_mod.load(job.checkpoint, problem)
            except Exception as e:  # noqa: BLE001 — a bad ckpt fails the
                sched.registry.transition_if(  # job, not the batch
                    job, ("queued", "requeued"), "failed",
                    error=f"{type(e).__name__}: {e}")
                return False
            n = problem.child_slots
            rows = int(saved.batch[prog.inner.size_field].shape[0])
            if rows + 2 * prog.M * n > ctx["capacity"]:
                # Fixed slot capacity can't hold the saved frontier; the
                # solo engine grows its pool on resume — send it there.
                job._solo_only = True
                self._requeue_back(job)
                return False
        if not sched.registry.transition_if(job, ("queued", "requeued"),
                                            "running",
                                            slices=job.slices + 1):
            return False
        if job.slices == 1:
            self._observe("tts_serve_queue_wait_seconds",
                          max(0.0, (job.started or time.time())
                              - job.submitted),
                          {"cls": job.class_key})
        if job.recorder is None:
            job.recorder = flightrec.FlightRecorder(
                always_on=True, snapshot_period_us=50_000.0)
            with job.recorder._lock:
                job.recorder._meta.update(job=job.id, cls=job.class_key)
        if job.quality is None:
            job.quality = obs_quality.QualityRecorder()
        job.quality.step_offset = job.steps
        sl = _Slot(job)
        if saved is not None:
            best = min(getattr(problem, "initial_ub", INF_BOUND),
                       int(saved.best))
            sl.tree, sl.sol = int(saved.tree), int(saved.sol)
            ctx["states"][i] = prog.make_slot(saved.batch, best)
        else:
            best = getattr(problem, "initial_ub", INF_BOUND)
            pool = SoAPool(problem.node_fields())
            pool.push_back(index_batch(problem.root(), 0))
            with flightrec.bound(job.recorder), \
                    ev.job_context(job.id):
                from ..engine.device import warmup

                sl.tree, sl.sol, best = warmup(problem, pool, best,
                                               job.spec["m"])
                ev.counter("explored", tree=sl.tree, sol=sl.sol, phase=1)
            ctx["states"][i] = prog.make_slot(pool.as_batch(), best)
        sl.prev_best = best
        ctx["slots"][i] = sl
        self._inc("tts_serve_slots_spliced_total", {"cls": self.class_key})
        return True

    def _requeue_back(self, job) -> None:
        """Return a popped job to the back of the queue (state preserved);
        under drain the queue is closed, so park it as requeued."""
        try:
            self.sched.submit(job)
        except RuntimeError:
            self._inc("tts_serve_requeues_total")
            self.sched.registry.transition_if(
                job, ("queued", "requeued", "running"), "requeued")

    # -- harvest + boundary actions -----------------------------------

    def _boundary(self, i: int, ctx, out, t_enq: float) -> None:
        """Per-slot post-dispatch bookkeeping and lifecycle decision, in
        the solo slice's order: finished -> budget -> cancel -> drain ->
        quantum -> capacity stall."""
        sched = self.sched
        prog, slots = ctx["prog"], ctx["slots"]
        sl = slots[i]
        job = sl.job
        tree_inc, sol_inc, cycles, size, best, ctr = \
            prog.read_slot_scalars(out, i)
        sl.tree += tree_inc
        sl.sol += sol_inc
        sl.n_disp += 1
        if ctr is not None:
            sl.ctr = obs_counters.merge_host(sl.ctr, ctr)
        with flightrec.bound(job.recorder), ev.job_context(job.id):
            from ..obs import flightrec as fr

            fr.heartbeat("batched", seq=sl.n_disp, cycles=cycles,
                         size=size, best=best, tree=sl.tree, sol=sl.sol,
                         K=prog.K)
            if ev.enabled():
                now = ev.now_us()
                ev.emit("dispatch", ph="X", ts=t_enq,
                        dur=max(0.0, now - t_enq), args={
                            "cycles": cycles, "tree": tree_inc,
                            "sol": sol_inc, "size": size, "best": best,
                            "slot": i, "B": self.B,
                        })
                if ctr is not None:
                    ev.counter("device_counters",
                               **obs_counters.as_args(ctr))
                if best < sl.prev_best:
                    ev.emit("incumbent", args={"best": best})
        job.quality.observe(best, sl.n_disp, sl.tree)
        sl.prev_best = best

        if size < job.spec["m"]:
            self._retire_done(i, ctx, best)
            return
        # The dispatch ran with frontier work left: it counts against the
        # cumulative budget, exactly like the solo RunController (which
        # skips after_step only on the terminal dispatch).
        sl.slice_steps += 1
        if sl.budget is not None and job.steps + sl.slice_steps >= sl.budget:
            self._retire_budget(i, ctx, best)
            return
        if job.cancel_requested:
            self._cut(i, ctx, best, "cancelled")
        elif sched._stop_requested():
            self._cut(i, ctx, best, "requeued")
        elif (sched.ckpt_every_s is not None
              and time.monotonic() - sl.t0 >= sched.ckpt_every_s):
            # Periodic recoverability cut (--ckpt-every): same preemption
            # path as a quantum cut, so the slot's checkpoint + exact step
            # count land on disk for the fleet router to pull.
            self._cut(i, ctx, best, "preempted")
        elif (time.monotonic() - sl.t0 >= sched.quantum_s
              and sched._waiters()):
            self._cut(i, ctx, best, "preempted")
        elif cycles == 0:
            # Capacity stall: the slot's pool is too full for another
            # fan-out and a fixed slot can't grow — hand the job to the
            # solo path (host-offload fallback / bigger pool on resume).
            self._cut(i, ctx, best, "stall")

    # -- retirement ---------------------------------------------------

    def _credit_compiles(self, job, problem) -> None:
        """Attribute compile-counter deltas since the watermark to `job`
        and advance the watermark (steady state: delta is zero)."""
        mark = pool_mod.compile_stats(problem)
        d_prog, d_step = mark[0] - self._mark[0], mark[1] - self._mark[1]
        self._mark = mark
        if d_prog or d_step:
            self.sched.registry.update(
                job,
                new_programs=job.new_programs + d_prog,
                new_step_compiles=job.new_step_compiles + d_step)

    def _result(self, sl, best: int, complete: bool, prog) -> SearchResult:
        job = sl.job
        return SearchResult(
            explored_tree=sl.tree,
            explored_sol=sl.sol,
            best=best,
            elapsed=time.monotonic() - sl.t_start,
            complete=complete,
            steps=sl.slice_steps,
            compact=prog.inner.compact,
            compact_auto=prog.inner.compact_auto,
            pipeline_depth=1,
            k_resolved=prog.K,
            k_auto=False,
            obs={"device_counters": sl.ctr} if sl.ctr is not None else None,
            quality=(job.quality.result()
                     if job.quality is not None and job.quality.points()
                     else None),
        )

    def _release_slot(self, i: int, ctx, job, problem) -> None:
        sched = self.sched
        sl = ctx["slots"][i]
        sched.registry.update(job, steps=job.steps + sl.slice_steps)
        self._credit_compiles(job, problem)
        sched.pool.mark_warm(ctx["entry"])
        self._observe("tts_serve_run_seconds",
                      time.monotonic() - sl.t_start,
                      {"cls": job.class_key})
        self._inc("tts_serve_slices_total", {"cls": job.class_key})
        self._inc("tts_serve_slots_retired_total", {"cls": self.class_key})
        ctx["slots"][i] = None

    def _retire_done(self, i: int, ctx, best: int) -> None:
        """Slot finished (frontier below m): residual download + host
        drain (solo phase 3), then the solo done path."""
        sched = self.sched
        prog, problem = ctx["prog"], ctx["problem"]
        sl = ctx["slots"][i]
        job = sl.job
        batch, size, best = prog.residual_slot(ctx["states"], i)
        pool = SoAPool(problem.node_fields())
        if size:
            pool.reset_from(batch)
        with flightrec.bound(job.recorder), ev.job_context(job.id):
            from ..engine.device import drain

            tree3, sol3, best = drain(problem, pool, best)
            ev.counter("explored", tree=tree3, sol=sol3, phase=3)
        sl.tree += tree3
        sl.sol += sol3
        if best < sl.prev_best:
            job.quality.observe(best, sl.n_disp, sl.tree)
        res = self._result(sl, best, True, prog)
        self._release_slot(i, ctx, job, problem)
        sched.registry.transition(job, "done", result=result_record(res))
        ckpt = sched._checkpoint_path(job)
        for p in (ckpt, job.checkpoint):
            if p and os.path.exists(p):
                os.remove(p)
        sched.registry.update(job, checkpoint=None)
        # The retired carry stays in states[i] as frozen ballast
        # (size < m fails its cond) until the next splice replaces it.

    def _retire_budget(self, i: int, ctx, best: int) -> None:
        """Cumulative max_steps exhausted: the job 'completes' at its
        cutoff by design (solo done-at-budget path, checkpoints
        removed)."""
        sched = self.sched
        prog, problem = ctx["prog"], ctx["problem"]
        sl = ctx["slots"][i]
        job = sl.job
        res = self._result(sl, best, False, prog)
        self._release_slot(i, ctx, job, problem)
        sched.registry.transition(job, "done", result=result_record(res))
        ckpt = sched._checkpoint_path(job)
        for p in (ckpt, job.checkpoint):
            if p and os.path.exists(p):
                os.remove(p)
        sched.registry.update(job, checkpoint=None)
        ctx["states"][i] = prog.empty_slot()  # still live: must freeze

    def _cut(self, i: int, ctx, best: int, kind: str) -> None:
        """Cut a live slot out as a checkpoint: cancel keeps it resumable,
        drain requeues it for the next daemon, quantum preemption sends it
        to the back of the queue, a capacity stall requeues it solo-only."""
        sched = self.sched
        prog, problem = ctx["prog"], ctx["problem"]
        sl = ctx["slots"][i]
        job = sl.job
        batch, _size, best = prog.snapshot_slot(ctx["states"], i)
        path = sched._checkpoint_path(job)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        ckpt_mod.save(path, problem, batch, best, sl.tree, sl.sol)
        with flightrec.bound(job.recorder), ev.job_context(job.id):
            ev.emit("checkpoint", args={"cut": kind, "slot": i})
        res = self._result(sl, best, False, prog)
        self._release_slot(i, ctx, job, problem)
        ctx["states"][i] = prog.empty_slot()  # cut slot is live: freeze it
        if kind == "cancelled":
            sched.registry.transition(job, "cancelled", checkpoint=path,
                                      result=result_record(res))
            return
        if kind == "requeued":
            self._inc("tts_serve_requeues_total")
            sched.registry.transition(job, "requeued", checkpoint=path)
            return
        if kind == "stall":
            job._solo_only = True
            self._inc("tts_serve_requeues_total")
            sched.registry.update(job, checkpoint=path)
            sched.registry.transition(job, "queued")
            self._requeue_back(job)
            return
        # Quantum preemption: back of the queue, resume from the cut.
        self._inc("tts_serve_preemptions_total")
        sched.registry.update(job, preemptions=job.preemptions + 1,
                              checkpoint=path)
        sched.registry.transition(job, "queued")
        self._requeue_back(job)

