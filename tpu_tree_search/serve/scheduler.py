"""The serve scheduler: worker threads + checkpoint-based preemption.

One job runs as a sequence of **slices**. Each slice is one
``resident_search``/``mesh_resident_search`` call whose ``yield_fn``
(checked by ``RunController`` at every dispatch boundary) cuts the run
when the job is cancelled, the daemon is draining, or the job's time
quantum expired while other work waits. A cut drains the dispatch queue,
snapshots the frontier, and writes the job's checkpoint — the next slice
resumes from it and the final counters are full-run totals, bit-identical
to an uninterrupted run (engine/checkpoint.py's contract, proved by
tests/test_checkpoint.py and re-proved for serve in tests/test_serve.py).

Env pins: trace-time routing reads process env (``routing_cache_token``),
so two jobs pinning DIFFERENT knob values must not trace concurrently.
``EnvLease`` is a refcounted knob lease — jobs with identical pin dicts
share it (full concurrency), a job with different pins waits for the
current holders to finish their slices. With the default single worker
the lease never blocks; it exists so ``--workers N`` stays correct.

Lock order (analysis/lockorder.py audits this): no scheduler method holds
two of {Scheduler._cv, Scheduler._batch_lock, EnvLease._cv,
JobRegistry._lock, JobRegistry._io_lock} at once — every cross-class call
happens outside the local ``with`` block. ``_batch_lock`` is a leaf that
guards only the ``_batch_execs`` dict (executor lookup/create). The
registry's own ``_io_lock -> _lock`` nesting (``JobRegistry._persist``)
is the graph's only two-lock hold.

Instance batching (``--batch-slots B`` / ``TTS_BATCH_SLOTS``, serve/
batch.py): when B > 1 and the popped job's immediate queue neighbour
shares its shape class, the worker runs a ``BatchExecutor`` session
instead of a solo slice — same quantum/cancel/drain/budget semantics,
one K-cycle dispatch advancing up to B same-class jobs at once.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

from . import pool as pool_mod
from .jobs import result_record


class EnvLease:
    """Refcounted process-env pin lease. ``acquire(pins)`` blocks until
    the current pin set is empty or equal, then applies the pins (saving
    prior values); the last ``release`` restores them. Methods never hold
    any other lock while waiting."""

    def __init__(self):
        self._cv = threading.Condition()
        self._pins = None  # guarded-by: _cv
        self._count = 0  # guarded-by: _cv
        self._saved = {}  # guarded-by: _cv

    def acquire(self, pins: dict) -> None:
        pins = dict(pins)
        with self._cv:
            while self._count and self._pins != pins:
                self._cv.wait(0.2)
            if self._count == 0:
                self._pins = pins
                self._saved = {k: os.environ.get(k) for k in pins}
                os.environ.update(pins)
            self._count += 1

    def release(self) -> None:
        with self._cv:
            self._count -= 1
            if self._count == 0:
                for k, v in self._saved.items():
                    if v is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = v
                self._pins = None
                self._saved = {}
                self._cv.notify_all()


class Scheduler:
    """FIFO queue + N worker threads (default 1: one accelerator, one
    resident loop at a time — more workers only help when jobs share pins
    and the backend multiplexes)."""

    def __init__(self, registry, pool, workers: int = 1,
                 quantum_s: float = 5.0, state_dir: str = ".",
                 metrics=None, batch_slots: int | None = None,
                 ckpt_every_s: float | None = None):
        self.registry = registry
        self.pool = pool
        self.workers = max(1, int(workers))
        self.quantum_s = float(quantum_s)
        if ckpt_every_s is None:
            ckpt_every_s = float(os.environ.get("TTS_CKPT_EVERY", "0") or 0)
        # Periodic recoverability cuts (``--ckpt-every`` / TTS_CKPT_EVERY,
        # 0 = off): the slice yield_fn fires every ckpt_every_s even with
        # nothing waiting, so the job's checkpoint + exact step count hit
        # disk together at each cut — the fleet router pulls those to
        # survive a SIGKILLed daemon. Host-side policy only: the engine
        # call itself is unchanged (checkpoint_interval_s stays cut-only).
        self.ckpt_every_s = float(ckpt_every_s) or None
        self.state_dir = state_dir
        if batch_slots is None:
            batch_slots = int(os.environ.get("TTS_BATCH_SLOTS", "1") or 1)
        # B=1 IS the solo path: _batchable never fires and no executor is
        # ever built (contract batch-b1-identity pins that equivalence at
        # the jaxpr level too).
        self.batch_slots = max(1, int(batch_slots))
        self._batch_lock = threading.Lock()  # leaf: guards _batch_execs
        self._batch_execs = {}  # guarded-by: _batch_lock
        # serve/metrics.ServeMetrics (or None when embedded without a
        # daemon). Its lock is a leaf: inc/observe never call out, so
        # recording from any point here cannot invert the lock order.
        self.metrics = metrics
        self.lease = EnvLease()
        self._cv = threading.Condition()
        self._queue = deque()  # guarded-by: _cv  (job ids)
        self._stopping = False  # guarded-by: _cv
        self._active = 0  # guarded-by: _cv  (jobs inside a slice)
        self._threads = []
        self.started = False

    # -- queue side (HTTP thread + workers) --------------------------------

    def start(self) -> None:
        self.started = True
        for i in range(self.workers):
            t = threading.Thread(target=self._worker, args=(i,),
                                 name=f"tts-serve-worker-{i}", daemon=True)
            t.start()
            self._threads.append(t)

    def workers_alive(self) -> int:
        """Worker threads still running (``/healthz`` ``workers_alive``).
        ``_threads`` is append-only from ``start``; no lock needed."""
        return sum(1 for t in self._threads if t.is_alive())

    def _inc(self, name: str, labels=None, v: int = 1) -> None:
        if self.metrics is not None:
            self.metrics.inc(name, labels, v)

    def _observe(self, name: str, value: float, labels=None) -> None:
        if self.metrics is not None:
            self.metrics.observe(name, value, labels)

    def submit(self, job) -> int:
        """Enqueue an admitted job; returns its queue position."""
        with self._cv:
            if self._stopping:
                raise RuntimeError("scheduler is draining")
            self._queue.append(job.id)
            pos = len(self._queue)
            self._cv.notify()
        return pos

    def cancel(self, job) -> bool:
        """Cancel: drop a queued job immediately; flag a running one (its
        yield_fn cuts at the next dispatch boundary). Returns False when
        the job already finished."""
        # The flag goes first: whatever state the job races into after our
        # checks, the slice's yield_fn sees it and the post-slice check
        # records 'cancelled' — an acknowledged cancel can never end 'done'.
        job.cancel_requested = True
        with self._cv:
            if job.id in self._queue:
                self._queue.remove(job.id)
        if self.registry.transition_if(job, ("queued", "requeued"),
                                       "cancelled"):
            return True
        # Not queued/requeued: either running (the slice will cut and mark
        # it cancelled) or already terminal.
        return job.state == "running"

    def queue_depth(self) -> int:
        with self._cv:
            return len(self._queue)

    def _waiters(self) -> bool:
        with self._cv:
            return self._stopping or len(self._queue) > 0

    def _stop_requested(self) -> bool:
        with self._cv:
            return self._stopping

    def drain(self, timeout_s: float = 120.0) -> None:
        """Graceful stop: reject new work, cut running slices at the next
        dispatch boundary (checkpointed), mark everything still pending as
        ``requeued`` (a restarted daemon re-admits it), wait for workers
        to go idle."""
        with self._cv:
            self._stopping = True
            pending = list(self._queue)
            self._queue.clear()
            self._cv.notify_all()
        for jid in pending:
            job = self.registry.get(jid)
            if job is not None and job.state == "queued":
                self.registry.transition(job, "requeued")
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._cv:
                if self._active == 0:
                    return
            time.sleep(0.05)

    # -- worker side -------------------------------------------------------

    def _worker(self, wid: int) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._stopping:
                    self._cv.wait(0.5)
                if self._stopping and not self._queue:
                    return
                jid = self._queue.popleft()
                self._active += 1
            job = None
            try:
                job = self.registry.get(jid)
                if job is not None and job.state in ("queued", "requeued"):
                    if self._batchable(job):
                        self._run_batch(job, wid)
                    else:
                        self._run_slice(job, wid)
            except Exception as e:  # noqa: BLE001 — a worker must outlive
                # ANY per-job failure (admission, knob resolution, registry
                # persistence, recorder setup — not just the search call):
                # with the default --workers 1 a dead worker leaves a
                # daemon that accepts submits but never runs another job.
                try:
                    if job is not None:
                        self.registry.transition_if(
                            job, ("queued", "requeued", "running"), "failed",
                            error=f"{type(e).__name__}: {e}",
                        )
                except Exception:  # noqa: BLE001 — even the failed
                    pass  # transition failing (disk full) must not kill us
            finally:
                with self._cv:
                    self._active -= 1
                    self._cv.notify_all()

    def _checkpoint_path(self, job) -> str:
        return os.path.join(self.state_dir, "jobs", f"{job.id}.ckpt.npz")

    # -- instance batching (serve/batch.py) --------------------------------

    def _batchable(self, job) -> bool:
        """Route a popped job to the batch executor only when batching is
        on, the job can occupy a fixed slot (device tier, fixed K, not
        flagged solo-only), and the NEXT queued job shares its class —
        batch formation follows the same front-contiguity rule as slot
        refills, so a lone job never pays the batched program's compile."""
        if self.batch_slots <= 1 or job.spec["tier"] != "device":
            return False
        if job.spec.get("K") == "auto" or \
                (os.environ.get("TTS_K") or "").strip().lower() == "auto":
            # AdaptiveK rebuilds the program mid-run; a fixed-B batch
            # cannot (zero-recompile guarantee).
            return False
        if getattr(job, "_solo_only", False):
            return False
        with self._cv:
            head = self._queue[0] if self._queue else None
        if head is None:
            return False
        peer = self.registry.get(head)
        return (peer is not None and peer.class_key == job.class_key
                and peer.pins == job.pins)

    def take_same_class_front(self, class_key: str, pins: dict,
                              limit: int) -> list:
        """Pop up to `limit` FRONT-CONTIGUOUS queued jobs of one shape
        class for slot refills. Stops at the first different-class (or
        solo-only) job: a waiter at the head must see the batch shrink,
        not watch later same-class arrivals leapfrog it.

        Lock discipline: snapshot ids under _cv, resolve via the registry
        OUTSIDE it (no _cv -> JobRegistry._lock nesting), then remove
        under _cv re-checking membership (a racing cancel may have
        removed an id in between)."""
        if limit <= 0:
            return []
        with self._cv:
            if self._stopping:
                return []
            prefix = list(self._queue)[:limit + 8]
        chosen = []
        for jid in prefix:
            job = self.registry.get(jid)
            if job is None or job.class_key != class_key \
                    or job.pins != pins or getattr(job, "_solo_only", False):
                break
            chosen.append(job)
            if len(chosen) >= limit:
                break
        taken = []
        with self._cv:
            for job in chosen:
                if job.id in self._queue:
                    self._queue.remove(job.id)
                    taken.append(job)
        return taken

    def _run_batch(self, job, wid: int) -> None:
        key = (job.class_key, tuple(sorted(job.pins.items())))
        with self._batch_lock:
            ex = self._batch_execs.get(key)
            if ex is None:
                from .batch import BatchExecutor

                ex = BatchExecutor(self, job.class_key, job.pins,
                                   self.batch_slots)
                self._batch_execs[key] = ex
        ex.run(job, wid)

    def batch_stats(self) -> list[dict]:
        """Per-class batch occupancy for /metrics and `tts top`."""
        with self._batch_lock:
            execs = list(self._batch_execs.values())
        return [{"class": ex.class_key, "slots": ex.B,
                 "occupied": ex.occupied} for ex in execs]

    def _run_slice(self, job, wid: int) -> None:
        from ..obs import events as obs_events
        from ..obs import flightrec
        from ..obs import quality as obs_quality

        if job.cancel_requested:
            # Cancel raced the job off the queue: honour it before spending
            # any admission work.
            self.registry.transition_if(job, ("queued", "requeued"),
                                        "cancelled")
            return
        entry = self.pool.admit(job.spec)
        problem = entry.problem
        prog0, step0 = pool_mod.compile_stats(problem)
        if not self.registry.transition_if(job, ("queued", "requeued"),
                                           "running", slices=job.slices + 1):
            return  # a racing cancel won; never flip a terminal state back
        if job.slices == 1:
            # First slice: submit-to-start is the job's queue wait.
            self._observe("tts_serve_queue_wait_seconds",
                          max(0.0, (job.started or time.time())
                              - job.submitted),
                          {"cls": job.class_key})
        if job.recorder is None:
            # Private ring per job: never installs process-wide handlers;
            # always_on makes it record without TTS_OBS.
            # Finer snapshot cadence than the global ring: a tenant
            # watching one short job wants more than one frame.
            job.recorder = flightrec.FlightRecorder(
                always_on=True, snapshot_period_us=50_000.0
            )
            with job.recorder._lock:
                job.recorder._meta.update(job=job.id, cls=job.class_key)
        if job.quality is None:
            # Per-job incumbent trajectory (obs/quality.py): always on for
            # serve jobs, bound per slice; spans preemptions.
            job.quality = obs_quality.QualityRecorder()
        job.quality.step_offset = job.steps
        ckpt = self._checkpoint_path(job)
        quantum = self.quantum_s
        every = self.ckpt_every_s
        t0 = time.monotonic()  # restarted below, once the env lease is held

        def yield_fn() -> bool:
            if job.cancel_requested or self._stop_requested():
                return True
            elapsed = time.monotonic() - t0
            if every is not None and elapsed >= every:
                return True  # periodic cut: a recoverable checkpoint lands
            return elapsed >= quantum and self._waiters()

        budget = job.spec.get("max_steps")
        kw = dict(
            m=job.spec["m"], M=job.spec["M"],
            # The spec's max_steps is a CUMULATIVE budget: each slice runs
            # with whatever the previous slices left over, so a preempted
            # or drained job resumes mid-budget instead of restarting it.
            max_steps=None if budget is None else budget - job.steps,
            checkpoint_path=ckpt,
            checkpoint_interval_s=1e9,  # cut-only: no periodic snapshots
            resume_from=job.checkpoint,
            yield_fn=yield_fn,
        )
        if job.spec.get("K") is not None:
            kw["K"] = job.spec["K"]
        t_lease = time.monotonic()
        self.lease.acquire(job.pins)
        # Quantum clock starts AFTER the lease: time blocked waiting for a
        # conflicting env pin is queueing, not run time — charging it would
        # preempt a contended pinned job at its first dispatch boundary
        # every slice.
        t0 = time.monotonic()
        self._observe("tts_serve_lease_wait_seconds", t0 - t_lease)
        try:
            with flightrec.bound(job.recorder), \
                    obs_quality.bound(job.quality), \
                    obs_events.job_context(job.id):
                if job.spec["tier"] == "mesh":
                    from ..parallel.resident_mesh import mesh_resident_search

                    res = mesh_resident_search(
                        problem, D=job.spec.get("D"),
                        mp=job.spec.get("mp", 1), **kw,
                    )
                else:
                    from ..engine.resident import resident_search

                    res = resident_search(problem, **kw)
        except Exception as e:  # noqa: BLE001 — a job must not kill its worker
            self.registry.transition(job, "failed", error=f"{type(e).__name__}: {e}")
            return
        finally:
            self.lease.release()
            # Counted in `finally` so failed slices land in the series too.
            self._observe("tts_serve_run_seconds", time.monotonic() - t0,
                          {"cls": job.class_key})
            self._inc("tts_serve_slices_total", {"cls": job.class_key})
        prog1, step1 = pool_mod.compile_stats(problem)
        self.registry.update(
            job,
            steps=job.steps + res.steps,
            new_programs=job.new_programs + (prog1 - prog0),
            new_step_compiles=job.new_step_compiles + (step1 - step0),
        )
        self.pool.mark_warm(entry)
        if res.complete or (budget is not None and job.steps >= budget):
            # Done: the search finished, or the cumulative max_steps budget
            # is exhausted (a max_steps job "completes" at its cutoff by
            # design). A yield cut — cancel, drain, quantum preemption —
            # always leaves the budget unexhausted (the max_steps cutoff
            # wins the same dispatch boundary), so it can never be
            # mistaken for the cutoff and silently truncate a result.
            self.registry.transition(job, "done", result=result_record(res))
            for p in (ckpt, job.checkpoint):
                if p and os.path.exists(p):
                    os.remove(p)
            self.registry.update(job, checkpoint=None)
            return
        has_ckpt = os.path.exists(ckpt)
        if job.cancel_requested:
            self.registry.transition(
                job, "cancelled",
                checkpoint=ckpt if has_ckpt else job.checkpoint,
                result=result_record(res),
            )
            return
        if self._stop_requested():
            # Daemon drain: preserve the cut for the next daemon.
            self._inc("tts_serve_requeues_total")
            self.registry.transition(
                job, "requeued",
                checkpoint=ckpt if has_ckpt else job.checkpoint,
            )
            return
        # Quantum preemption: back of the queue, resume from the cut.
        self._inc("tts_serve_preemptions_total")
        self.registry.update(
            job, preemptions=job.preemptions + 1,
            checkpoint=ckpt if has_ckpt else job.checkpoint,
        )
        self.registry.transition(job, "queued")
        try:
            self.submit(job)
        except RuntimeError:
            self._inc("tts_serve_requeues_total")
            self.registry.transition(job, "requeued")
