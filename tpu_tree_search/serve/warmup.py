"""AOT warm matrix — ``scripts/warm_cache.py`` promoted to a module.

Two consumers share the one config table:

  * ``tts warmup`` (and the legacy script, now a shim) runs each config
    in a subprocess against the persistent XLA compile cache
    (``cli.enable_compile_cache``), reporting per-config **hit/miss**: a
    miss banks new cache files, a hit compiles nothing — the count of new
    files in the cache directory is the measurement, so a second run of
    the same matrix must report all hits.
  * ``tts serve --warm`` admits the serve-able configs as internal
    ``max_steps=1`` jobs, warming the daemon's OWN program pool in
    process — after it, the first tenant job of a warmed class admits
    with zero recompiles.

Cache keys include the full program shape, so warming MUST run the exact
entry points with the exact shapes the consumers use: each config is one
``resident_search(..., max_steps=1)`` — the full while-loop program plus
its kernels, compiled and executed for a single step. Staged and unstaged
lb2 are distinct programs; both warm. Each subprocess has its own timeout
(a compile hang must only cost its slot — bench.py's probe lesson).
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

_ITEM = r"""
import os, time, sys
t0 = time.time()
import jax
from tpu_tree_search.cli import enable_compile_cache
from tpu_tree_search.engine.resident import resident_search
from tpu_tree_search.problems import NQueensProblem, PFSPProblem

enable_compile_cache()
mc = os.environ.get("TTS_WARM_MIN_COMPILE_S")
if mc:
    # Testability: CPU test compiles are sub-second; lowering the floor
    # makes them land in the cache so hit/miss accounting is observable.
    try:
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", float(mc))
    except Exception:
        pass
kind = sys.argv[1]
if kind == "kernel":
    # Kernel-level warm at the smoke-gate shapes: large-instance resident
    # programs explore tens of millions of nodes in ONE K=4096 dispatch
    # (max_steps can't cut inside a dispatch), blowing the slot timeout on
    # execution the cache doesn't need — the session's reusable artifacts
    # for these classes are the Mosaic KERNEL compiles.
    import jax.numpy as jnp
    from tpu_tree_search.ops import pallas_kernels as PK
    inst, lb, B = int(sys.argv[2]), sys.argv[3], int(sys.argv[4])
    prob = PFSPProblem(inst=inst, lb=lb, ub=1)
    t = prob.device_tables()
    n = prob.jobs
    prmu = jnp.tile(jnp.arange(n, dtype=jnp.int32), (B, 1))
    limit1 = jnp.zeros((B,), dtype=jnp.int32)
    if lb == "lb1":
        out = PK.pfsp_lb1_bounds(prmu, limit1, t.ptm_t, t.min_heads,
                                 t.min_tails, bf16=t.exact_bf16)
    else:
        out = PK.pfsp_lb2_bounds(prmu, limit1, t)
    out.block_until_ready()
    print(f"WARM_OK shape={tuple(out.shape)} wall={time.time() - t0:.1f}s")
    sys.exit(0)
if kind == "nqueens":
    prob = NQueensProblem(N=int(sys.argv[2]))
else:
    prob = PFSPProblem(inst=int(sys.argv[2]), lb=sys.argv[3], ub=1)
M = int(sys.argv[3] if kind == "nqueens" else sys.argv[5])
res = resident_search(prob, m=25, M=M, max_steps=1)
print(f"WARM_OK tree={res.explored_tree} wall={time.time() - t0:.1f}s")
"""


class WarmConfig:
    """One warm slot: a name (CLI-selectable), the subprocess argv tail,
    env overrides, and — when the config is a full resident run the serve
    daemon can replay — the equivalent job spec."""

    def __init__(self, name: str, label: str, argv: list[str],
                 env: dict | None = None):
        self.name = name
        self.label = label
        self.argv = argv
        self.env = env or {}

    @property
    def servable(self) -> bool:
        return self.argv[0] != "kernel"

    def spec(self) -> dict | None:
        """The serve-side job spec for this config (``max_steps=1``), or
        None for kernel-only rows. Env-only knobs (TTS_K, TTS_COMPACT,
        TTS_LB2_PAIRBLOCK) map to spec fields; staging env rows have no
        spec knob and warm under the daemon's own TTS_LB2_STAGED."""
        if not self.servable:
            return None
        kind = self.argv[0]
        spec: dict = {"tier": "device", "max_steps": 1,
                      "label": f"warm:{self.name}"}
        if kind == "nqueens":
            spec.update(problem="nqueens", N=int(self.argv[1]),
                        M=int(self.argv[2]))
        else:
            spec.update(problem="pfsp", inst=int(self.argv[1]),
                        lb=self.argv[2], ub=1, M=int(self.argv[4]))
        if "TTS_K" in self.env:
            spec["K"] = int(self.env["TTS_K"])
        if "TTS_COMPACT" in self.env:
            spec["compact"] = self.env["TTS_COMPACT"]
        if "TTS_LB2_PAIRBLOCK" in self.env:
            pb = self.env["TTS_LB2_PAIRBLOCK"]
            spec["lb2_pairblock"] = pb if pb == "auto" else int(pb)
        return spec


# The bench + smoke-gate matrix, most valuable first so a closing tunnel
# window still banks the flagship programs. M values match the bench's
# measured defaults (HEADLINE_M / lb2_M — scripts/headline_tune.py,
# scripts/lb2_tune.py): warming MUST compile the exact programs the bench
# dispatches. See scripts/warm_cache.py history for the per-row rationale
# (staged/unstaged lb2 pairs, the TTS_K=auto ladder rungs, compaction-mode
# A/B variants, large-instance kernel-only rows).
CONFIGS: list[WarmConfig] = [
    WarmConfig("ta014-lb2-staged", "ta014 lb2 staged M=1024",
               ["pfsp", "14", "lb2", "-", "1024"], {"TTS_LB2_STAGED": "1"}),
    WarmConfig("ta014-lb2-unstaged", "ta014 lb2 unstaged M=1024",
               ["pfsp", "14", "lb2", "-", "1024"], {"TTS_LB2_STAGED": "0"}),
    WarmConfig("ta014-lb2-staged-pb1", "ta014 lb2 staged M=1024 pairblock=1",
               ["pfsp", "14", "lb2", "-", "1024"],
               {"TTS_LB2_STAGED": "1", "TTS_LB2_PAIRBLOCK": "1"}),
    WarmConfig("ta021-lb2-staged", "ta021 lb2 staged M=1024",
               ["pfsp", "21", "lb2", "-", "1024"], {"TTS_LB2_STAGED": "1"}),
    WarmConfig("ta021-lb2-unstaged", "ta021 lb2 unstaged M=1024",
               ["pfsp", "21", "lb2", "-", "1024"], {"TTS_LB2_STAGED": "0"}),
    WarmConfig("ta014-lb1-jnp", "ta014 lb1 M=1024 jnp",
               ["pfsp", "14", "lb1", "-", "1024"], {"TTS_PALLAS": "0"}),
    WarmConfig("ta014-lb1-K1", "ta014 lb1 M=1024 K=1",
               ["pfsp", "14", "lb1", "-", "1024"], {"TTS_K": "1"}),
    WarmConfig("ta014-lb1-K4", "ta014 lb1 M=1024 K=4",
               ["pfsp", "14", "lb1", "-", "1024"], {"TTS_K": "4"}),
    WarmConfig("ta014-lb1-K16", "ta014 lb1 M=1024 K=16",
               ["pfsp", "14", "lb1", "-", "1024"], {"TTS_K": "16"}),
    WarmConfig("ta014-lb1-K64", "ta014 lb1 M=1024 K=64",
               ["pfsp", "14", "lb1", "-", "1024"], {"TTS_K": "64"}),
    WarmConfig("ta014-lb1-K256", "ta014 lb1 M=1024 K=256",
               ["pfsp", "14", "lb1", "-", "1024"], {"TTS_K": "256"}),
    WarmConfig("ta014-lb1-K1024", "ta014 lb1 M=1024 K=1024",
               ["pfsp", "14", "lb1", "-", "1024"], {"TTS_K": "1024"}),
    WarmConfig("ta014-lb1", "ta014 lb1 M=1024",
               ["pfsp", "14", "lb1", "-", "1024"]),
    WarmConfig("ta014-lb1d", "ta014 lb1_d M=1024",
               ["pfsp", "14", "lb1_d", "-", "1024"]),
    WarmConfig("nqueens-15", "nqueens N=15 M=65536",
               ["nqueens", "15", "65536"]),
    WarmConfig("nqueens-16", "nqueens N=16 M=65536",
               ["nqueens", "16", "65536"]),
    WarmConfig("nqueens-17", "nqueens N=17 M=65536",
               ["nqueens", "17", "65536"]),
    WarmConfig("nqueens-15-M8k", "nqueens N=15 M=8192",
               ["nqueens", "15", "8192"]),
    WarmConfig("nqueens-15-M256k", "nqueens N=15 M=262144",
               ["nqueens", "15", "262144"]),
    WarmConfig("nqueens-16-M256k", "nqueens N=16 M=262144",
               ["nqueens", "16", "262144"]),
    WarmConfig("nqueens-17-M128k", "nqueens N=17 M=131072",
               ["nqueens", "17", "131072"]),
    WarmConfig("ta014-lb1-scatter", "ta014 lb1 M=1024 compact=scatter",
               ["pfsp", "14", "lb1", "-", "1024"],
               {"TTS_COMPACT": "scatter"}),
    WarmConfig("ta014-lb1-sort", "ta014 lb1 M=1024 compact=sort",
               ["pfsp", "14", "lb1", "-", "1024"], {"TTS_COMPACT": "sort"}),
    WarmConfig("ta014-lb1-search", "ta014 lb1 M=1024 compact=search",
               ["pfsp", "14", "lb1", "-", "1024"],
               {"TTS_COMPACT": "search"}),
    WarmConfig("ta014-lb2-scatter", "ta014 lb2 M=1024 compact=scatter",
               ["pfsp", "14", "lb2", "-", "1024"],
               {"TTS_COMPACT": "scatter"}),
    WarmConfig("ta014-lb2-sort", "ta014 lb2 M=1024 compact=sort",
               ["pfsp", "14", "lb2", "-", "1024"], {"TTS_COMPACT": "sort"}),
    WarmConfig("ta014-lb2-search", "ta014 lb2 M=1024 compact=search",
               ["pfsp", "14", "lb2", "-", "1024"],
               {"TTS_COMPACT": "search"}),
    WarmConfig("nqueens-15-scatter", "nqueens N=15 M=65536 compact=scatter",
               ["nqueens", "15", "65536"], {"TTS_COMPACT": "scatter"}),
    WarmConfig("ta031-lb1-kernel", "ta031 lb1 kernel B=64",
               ["kernel", "31", "lb1", "64"]),
    WarmConfig("ta056-lb1-kernel", "ta056 lb1 kernel B=32",
               ["kernel", "56", "lb1", "32"]),
    WarmConfig("ta056-lb2-kernel", "ta056 lb2 kernel B=16",
               ["kernel", "56", "lb2", "16"]),
    WarmConfig("ta111-lb1-kernel", "ta111 lb1 kernel B=16",
               ["kernel", "111", "lb1", "16"]),
]


def select_configs(names: str | None) -> list[WarmConfig]:
    """``names``: None/"all" for the whole matrix, "serve" for the
    serve-able subset, else a comma-separated name list (unknown names
    raise ValueError — a typo must not silently warm nothing)."""
    if names in (None, "", "all"):
        return list(CONFIGS)
    if names == "serve":
        return [c for c in CONFIGS if c.servable]
    by_name = {c.name: c for c in CONFIGS}
    out = []
    unknown = []
    for name in names.split(","):
        name = name.strip()
        if name in by_name:
            out.append(by_name[name])
        elif name:
            unknown.append(name)
    if unknown:
        raise ValueError(
            f"unknown warm config(s): {', '.join(unknown)} "
            f"(known: {', '.join(sorted(by_name))})"
        )
    return out


def cache_dir() -> str | None:
    """The directory ``cli.enable_compile_cache`` will use in a child of
    this process — the hit/miss accounting target. None when the cache is
    opted out (TTS_COMPILE_CACHE=0) or jax is unimportable."""
    want = os.environ.get("TTS_COMPILE_CACHE", "")
    if want == "0":
        return None
    if want:
        return want
    try:
        import platform
        import socket

        import jax
        import jaxlib

        key = "-".join([
            jax.__version__, jaxlib.__version__,
            platform.machine(), socket.gethostname(),
        ])
        return os.path.join(
            os.path.expanduser("~"), ".cache", "tpu_tree_search", "xla", key
        )
    except Exception:
        return None


def _cache_files(path: str | None) -> set[str]:
    if path is None or not os.path.isdir(path):
        return set()
    out = set()
    for root, _dirs, files in os.walk(path):
        for f in files:
            out.add(os.path.join(root, f))
    return out


def run_configs(configs: list[WarmConfig], timeout_s: float | None = None,
                emit=print) -> int:
    """The subprocess warm loop (``tts warmup`` / the legacy script):
    returns the failure count. Per config, reports ok/FAIL, wall seconds,
    and the compile-cache delta — ``miss(+N files)`` banked N new
    executables, ``hit`` compiled nothing new (the warm goal)."""
    if timeout_s is None:
        timeout_s = float(os.environ.get("TTS_WARM_TIMEOUT", "420"))
    cdir = cache_dir()
    failures = 0
    for cfg in configs:
        before = _cache_files(cdir)
        t0 = time.time()
        try:
            res = subprocess.run(
                [sys.executable, "-c", _ITEM, *cfg.argv],
                timeout=timeout_s, capture_output=True, text=True,
                env={**os.environ, **cfg.env},
            )
            ok = res.returncode == 0 and "WARM_OK" in res.stdout
            detail = (res.stdout.strip().splitlines() or [""])[-1] if ok else \
                (res.stderr or res.stdout).strip().splitlines()[-1:]
        except subprocess.TimeoutExpired:
            ok, detail = False, f"timeout {timeout_s:.0f}s"
        failures += not ok
        new = len(_cache_files(cdir) - before) if cdir else 0
        cache = ("cache=off" if cdir is None
                 else f"miss(+{new} files)" if new else "hit")
        # flush: the session log must stream per-config progress (a
        # redirect block-buffers prints, hiding everything until exit —
        # observed when the tunnel died mid-run and the log stayed empty).
        emit(f"{'ok ' if ok else 'FAIL'} {time.time() - t0:7.1f}s  "
             f"[{cache}]  {cfg.name}  {detail}")
    return failures


def warmup_main(names: str | None = None,
                timeout_s: float | None = None) -> int:
    """``tts warmup`` entry point."""
    try:
        configs = select_configs(names)
    except ValueError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 2
    failures = run_configs(configs,
                           timeout_s=timeout_s,
                           emit=lambda line: print(line, flush=True))
    return 1 if failures else 0


def warm_pool(daemon, names: str | None = "serve"):
    """``tts serve --warm``: admit each serve-able config as an internal
    max_steps=1 job and wait, warming the daemon's program pool so the
    first real job of each class is a zero-recompile admission. Yields one
    progress line per config (the daemon prints them)."""
    configs = [c for c in select_configs(names or "serve") if c.servable]
    for cfg in configs:
        spec = cfg.spec()
        payload, code = daemon.submit(spec)
        if code != 201:
            yield (f"warm FAIL {cfg.name}: {payload.get('error')}")
            continue
        job = daemon.registry.get(payload["id"])
        t0 = time.time()
        while job.state not in ("done", "failed", "cancelled"):
            time.sleep(0.1)
        state = "ok " if job.state == "done" else "FAIL"
        yield (f"warm {state} {time.time() - t0:6.1f}s  {cfg.name}  "
               f"class={job.class_key}")
