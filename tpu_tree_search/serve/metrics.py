"""Daemon operational metrics — Prometheus text exposition on ``/metrics``.

The serve daemon (``server.py``) already *has* every number an operator
needs — queue depth in the scheduler, per-class program counts in the
pool, job states in the registry, compile deltas on each job record —
but scattered across three components behind three locks, visible only
by scripting the JSON endpoints. This module aggregates them into the
one surface fleet tooling actually scrapes: ``GET /metrics`` in
Prometheus text exposition format (version 0.0.4), hand-rendered so the
serving path stays stdlib-only (no ``prometheus_client`` dependency).

Two kinds of series:

  * **Live gauges** read from the components at scrape time (queue
    depth, jobs by state, pool per-class stats, uptime, workers alive).
    Nothing is double-counted: the components stay the source of truth.
  * **Event counters / histograms** accumulated by ``ServeMetrics`` as
    the daemon runs (admission outcomes, 409 conflicts, preemptions,
    requeues, per-class slice counts; queue-wait / run-time / lease-wait
    histograms). These capture *flow* that no point-in-time component
    read can reconstruct.

Lock discipline (enforced by ``analysis/lockorder.py``): ``ServeMetrics``
has exactly one lock guarding only its own dicts. ``inc``/``observe``
never call out while holding it, so call sites inside scheduler/registry
critical sections cannot deadlock (metrics lock is always a leaf).
``render`` snapshots the metrics state under the metrics lock *first*,
then reads each live component under that component's own lock — never
two locks at once.
"""

from __future__ import annotations

import re
import threading
import time

#: Histogram bucket bounds, seconds. Spans sub-10ms warm-cache slices to
#: multi-minute searches; queue/lease waits land in the low buckets on a
#: healthy daemon, so growth in the tail is the saturation signal.
BUCKETS = (0.005, 0.02, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
           10.0, 30.0, 60.0, 120.0, 300.0)

_COUNTER_HELP = {
    "tts_serve_admissions_total":
        "POST /submit outcomes (admitted / invalid / queue_full / draining).",
    "tts_serve_conflicts_total":
        "HTTP 409 conflict responses, by endpoint.",
    "tts_serve_preemptions_total":
        "Quantum preemptions (slice cut at a checkpoint, job requeued).",
    "tts_serve_requeues_total":
        "Jobs pushed back to queued without preemption (drain / re-submit).",
    "tts_serve_slices_total":
        "Engine slices run, by shape class.",
    "tts_serve_slots_spliced_total":
        "Jobs spliced into a batch slot, by shape class.",
    "tts_serve_slots_retired_total":
        "Batch slots retired (finished or cut), by shape class.",
}

_HIST_HELP = {
    "tts_serve_queue_wait_seconds":
        "Submit-to-first-slice wait, by shape class.",
    "tts_serve_run_seconds":
        "Per-slice engine wall time, by shape class.",
    "tts_serve_lease_wait_seconds":
        "Env-pin lease acquisition wait before a slice.",
    "tts_serve_batch_efficiency":
        "Live-slot fraction per batched dispatch (1.0 = full batch), "
        "by shape class.",
}


def _esc(v) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_labels(labels) -> str:
    """``(("cls","pfsp-20x20"),)`` -> ``{cls="pfsp-20x20"}``."""
    if not labels:
        return ""
    return "{" + ",".join(f'{k}="{_esc(v)}"' for k, v in labels) + "}"


def _key(labels: dict | None) -> tuple:
    return tuple(sorted((labels or {}).items()))


class ServeMetrics:
    """Monotonic counters + fixed-bucket histograms behind one leaf lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict = {}  # guarded-by: _lock -- (name, labels) -> n
        # guarded-by: _lock -- (name, labels) -> [per-bucket counts, sum, n]
        self._hists: dict = {}

    def inc(self, name: str, labels: dict | None = None, v: int = 1) -> None:
        key = (name, _key(labels))
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + v

    def observe(self, name: str, value: float,
                labels: dict | None = None) -> None:
        key = (name, _key(labels))
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = [[0] * (len(BUCKETS) + 1), 0.0, 0]
            i = 0
            while i < len(BUCKETS) and value > BUCKETS[i]:
                i += 1
            h[0][i] += 1
            h[1] += float(value)
            h[2] += 1

    def snapshot(self) -> tuple[dict, dict]:
        """Consistent copy of (counters, histograms) for rendering."""
        with self._lock:
            return (dict(self._counters),
                    {k: [list(h[0]), h[1], h[2]]
                     for k, h in self._hists.items()})


def _header(lines: list, name: str, typ: str, help_: str) -> None:
    lines.append(f"# HELP {name} {help_}")
    lines.append(f"# TYPE {name} {typ}")


def _gauge(lines: list, name: str, help_: str, samples: list) -> None:
    """``samples``: list of (labels-tuple, value)."""
    _header(lines, name, "gauge", help_)
    for labels, v in samples:
        lines.append(f"{name}{_fmt_labels(labels)} {v}")


def render(daemon) -> str:
    """The full ``/metrics`` payload for a :class:`~.server.ServeDaemon`.

    Component reads (registry / scheduler / pool) each take that
    component's own lock internally; nothing here holds two at once.
    """
    from . import VERSION
    from .jobs import STATES

    counters, hists = daemon.metrics.snapshot()  # metrics lock, released
    jobs = daemon.registry.all()          # registry lock, released
    depth = daemon.scheduler.queue_depth()  # scheduler cv, released
    pool_stats = daemon.pool.stats()      # pool lock, released

    lines: list[str] = []
    _gauge(lines, "tts_serve_build_info",
           "Daemon build/version (value is always 1).",
           [(((("version", VERSION),)), 1)])
    _gauge(lines, "tts_serve_uptime_seconds",
           "Seconds since the daemon started.",
           [((), round(max(0.0, time.time() - daemon.started), 3))])
    _gauge(lines, "tts_serve_queue_depth",
           "Jobs waiting in the scheduler run queue.", [((), depth)])
    _gauge(lines, "tts_serve_workers_alive",
           "Scheduler worker threads currently alive.",
           [((), daemon.scheduler.workers_alive())])
    _gauge(lines, "tts_serve_batch_slots",
           "Configured instance-batch slots per compiled program "
           "(--batch-slots; 1 = batching off).",
           [((), daemon.scheduler.batch_slots)])
    batch = daemon.scheduler.batch_stats()  # batch lock, released
    if batch:
        _gauge(lines, "tts_serve_batch_slots_occupied",
               "Batch slots currently holding a live job, by shape class.",
               sorted(((("cls", b["class"]),), int(b["occupied"]))
                      for b in batch))

    by_state: dict = {s: 0 for s in STATES}
    by_class_state: dict = {}
    new_prog: dict = {}
    new_steps: dict = {}
    for j in jobs:
        by_state[j.state] = by_state.get(j.state, 0) + 1
        ck = (("cls", j.class_key), ("state", j.state))
        by_class_state[ck] = by_class_state.get(ck, 0) + 1
        cls = (("cls", j.class_key),)
        new_prog[cls] = new_prog.get(cls, 0) + int(j.new_programs or 0)
        new_steps[cls] = (new_steps.get(cls, 0)
                         + int(j.new_step_compiles or 0))
    _gauge(lines, "tts_serve_jobs", "Jobs in the registry, by state.",
           [((("state", s),), n) for s, n in sorted(by_state.items())])
    _gauge(lines, "tts_serve_class_jobs",
           "Jobs in the registry, by shape class and state.",
           sorted(by_class_state.items()))

    # Compile deltas are per-job monotonic facts summed over an
    # append-only registry, so exposing them as counters is sound.
    _header(lines, "tts_serve_new_programs_total", "counter",
            "Fresh program-cache compiles attributed to jobs, by class.")
    for cls, n in sorted(new_prog.items()):
        lines.append(f"tts_serve_new_programs_total{_fmt_labels(cls)} {n}")
    _header(lines, "tts_serve_new_step_compiles_total", "counter",
            "Fresh step-fn compiles attributed to jobs, by class.")
    for cls, n in sorted(new_steps.items()):
        lines.append(
            f"tts_serve_new_step_compiles_total{_fmt_labels(cls)} {n}")

    _gauge(lines, "tts_serve_pool_classes",
           "Shape classes resident in the program pool.",
           [((), len(pool_stats))])
    by_class = sorted(pool_stats, key=lambda st: st.get("class", ""))
    for metric, field, help_ in (
        ("tts_serve_class_programs", "programs",
         "Compiled programs resident, by shape class."),
        ("tts_serve_class_step_cache_entries", "step_cache_entries",
         "Step-fn cache entries, by shape class."),
        ("tts_serve_class_warm", "warm",
         "1 if the class program is warm (compiled), by shape class."),
        ("tts_serve_class_jobs_admitted", "jobs_admitted",
         "Jobs ever admitted, by shape class."),
        ("tts_serve_pool_bytes", "pool_bytes",
         "Device-resident pool bytes across the class's cached programs "
         "(capacity x per-node pool bytes x slots/shards), read at "
         "scrape time."),
    ):
        _gauge(lines, metric, help_,
               [((("cls", st.get("class", "?")),), int(st.get(field, 0)))
                for st in by_class])

    by_name: dict = {}
    for (name, labels), v in counters.items():
        by_name.setdefault(name, []).append((labels, v))
    for name in sorted(by_name):
        _header(lines, name, "counter",
                _COUNTER_HELP.get(name, "Daemon event counter."))
        for labels, v in sorted(by_name[name]):
            lines.append(f"{name}{_fmt_labels(labels)} {v}")

    hist_by_name: dict = {}
    for (name, labels), h in hists.items():
        hist_by_name.setdefault(name, []).append((labels, h))
    for name in sorted(hist_by_name):
        _header(lines, name, "histogram",
                _HIST_HELP.get(name, "Daemon latency histogram."))
        for labels, (bucket_counts, total, count) in sorted(
                hist_by_name[name]):
            cum = 0
            for bound, n in zip(BUCKETS, bucket_counts):
                cum += n
                lab = labels + (("le", f"{bound}"),)
                lines.append(f"{name}_bucket{_fmt_labels(lab)} {cum}")
            cum += bucket_counts[-1]
            lab = labels + (("le", "+Inf"),)
            lines.append(f"{name}_bucket{_fmt_labels(lab)} {cum}")
            lines.append(
                f"{name}_sum{_fmt_labels(labels)} {round(total, 6)}")
            lines.append(f"{name}_count{_fmt_labels(labels)} {count}")
    return "\n".join(lines) + "\n"


#: Content-Type for the exposition format.
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)'        # metric name
    r'(?:\{(.*)\})?'                      # optional label body
    r'\s+(-?(?:[0-9.eE+-]+|\+?Inf|NaN))$')  # value
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_text(text: str) -> dict:
    """Lenient exposition-format parser (for tests and ``tts top``):
    ``{name: {labels-tuple: value}}``. Raises ``ValueError`` on a
    malformed sample line, so tests double as a format check."""
    out: dict = {}
    for ln in text.splitlines():
        if not ln.strip() or ln.startswith("#"):
            continue
        m = _SAMPLE_RE.match(ln)
        if m is None:
            raise ValueError(f"unparseable metrics line: {ln!r}")
        name, body, val = m.groups()
        labels = []
        if body:
            labels = [(k, v.replace('\\"', '"').replace("\\n", "\n")
                       .replace("\\\\", "\\"))
                      for k, v in _LABEL_RE.findall(body)]
        out.setdefault(name, {})[tuple(labels)] = float(val)
    return out
